// Incremental deposit Merkle accumulator — native runtime component.
//
// The reference's only non-Python executable is the deposit contract's EVM
// bytecode (/root/reference deposit_contract/contracts/
// validator_registration.v.py:69-140 compiled by Vyper); this is the same
// O(log n) accumulator as compiled native code, exposed through a C ABI for
// ctypes (no pybind11 in the image). Semantics are differentially tested
// against the Python model (deposit_contract/contract.py) which is itself
// pinned to the framework's generic SSZ Merkleizer.
//
// Build: g++ -O3 -shared -fPIC deposit_tree.cpp -o libdeposit_tree.so
// (done lazily by deposit_contract/native.py).

#include <cstdint>
#include <cstring>

namespace {

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), self-contained
// ---------------------------------------------------------------------------

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
};

inline uint32_t rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

struct Sha256 {
    uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                     0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    uint8_t buf[64];
    uint64_t total = 0;
    size_t fill = 0;

    void compress(const uint8_t *p) {
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
                   (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
            uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
        uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = hh + S1 + ch + K[i] + w[i];
            uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
            uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
            uint32_t t2 = S0 + maj;
            hh = g; g = f; f = e; e = d + t1;
            d = c; c = b; b = a; a = t1 + t2;
        }
        h[0] += a; h[1] += b; h[2] += c; h[3] += d;
        h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
    }

    void update(const uint8_t *p, size_t n) {
        total += n;
        while (n) {
            size_t take = 64 - fill < n ? 64 - fill : n;
            std::memcpy(buf + fill, p, take);
            fill += take; p += take; n -= take;
            if (fill == 64) { compress(buf); fill = 0; }
        }
    }

    void final(uint8_t out[32]) {
        uint64_t bits = total * 8;
        uint8_t pad = 0x80;
        update(&pad, 1);
        uint8_t z = 0;
        while (fill != 56) update(&z, 1);
        uint8_t len[8];
        for (int i = 0; i < 8; i++) len[i] = uint8_t(bits >> (56 - 8 * i));
        update(len, 8);
        for (int i = 0; i < 8; i++) {
            out[4 * i] = uint8_t(h[i] >> 24);
            out[4 * i + 1] = uint8_t(h[i] >> 16);
            out[4 * i + 2] = uint8_t(h[i] >> 8);
            out[4 * i + 3] = uint8_t(h[i]);
        }
    }
};

void sha256_2(const uint8_t a[32], const uint8_t b[32], uint8_t out[32]) {
    Sha256 s;
    s.update(a, 32);
    s.update(b, 32);
    s.final(out);
}

void sha256_buf(const uint8_t *p, size_t n, uint8_t out[32]) {
    Sha256 s;
    s.update(p, n);
    s.final(out);
}

// ---------------------------------------------------------------------------
// Accumulator (mirrors deposit_contract/contract.py / the Vyper deposit())
// ---------------------------------------------------------------------------

constexpr int TREE_DEPTH = 32;
constexpr uint64_t MAX_DEPOSIT_COUNT = (uint64_t(1) << TREE_DEPTH) - 1;
constexpr uint64_t MIN_DEPOSIT_GWEI = 1000000000ULL;

struct DepositTree {
    uint8_t branch[TREE_DEPTH][32] = {};
    uint8_t zerohashes[TREE_DEPTH][32] = {};
    uint64_t count = 0;

    DepositTree() {
        for (int i = 1; i < TREE_DEPTH; i++)
            sha256_2(zerohashes[i - 1], zerohashes[i - 1], zerohashes[i]);
    }
};

void le64(uint64_t v, uint8_t out[8]) {
    for (int i = 0; i < 8; i++) out[i] = uint8_t(v >> (8 * i));
}

// hash_tree_root(DepositData) with the contract's hand-rolled chunk tree
// (contract.py:32-44; the EVM code computes the identical shape)
void deposit_data_root(const uint8_t pk[48], const uint8_t wc[32],
                       uint64_t amount_gwei, const uint8_t sig[96],
                       uint8_t out[32]) {
    uint8_t pk_padded[64] = {};
    std::memcpy(pk_padded, pk, 48);
    uint8_t pk_root[32];
    sha256_buf(pk_padded, 64, pk_root);

    uint8_t sig_lo[32], sig_hi_in[64] = {}, sig_hi[32], sig_root[32];
    sha256_buf(sig, 64, sig_lo);
    std::memcpy(sig_hi_in, sig + 64, 32);
    sha256_buf(sig_hi_in, 64, sig_hi);
    sha256_2(sig_lo, sig_hi, sig_root);

    uint8_t left[32], right_in[64] = {}, right[32];
    sha256_2(pk_root, wc, left);
    le64(amount_gwei, right_in);
    std::memcpy(right_in + 32, sig_root, 32);
    sha256_buf(right_in, 64, right);
    sha256_2(left, right, out);
}

}  // namespace

extern "C" {

void *dt_new() { return new DepositTree(); }
void dt_free(void *h) { delete static_cast<DepositTree *>(h); }
uint64_t dt_count(void *h) { return static_cast<DepositTree *>(h)->count; }

// 0 ok; 1 tree full; 2 deposit below minimum
int dt_deposit(void *h, const uint8_t pk[48], const uint8_t wc[32],
               const uint8_t sig[96], uint64_t value_gwei) {
    auto *t = static_cast<DepositTree *>(h);
    if (t->count >= MAX_DEPOSIT_COUNT) return 1;
    if (value_gwei < MIN_DEPOSIT_GWEI) return 2;

    uint8_t node[32];
    deposit_data_root(pk, wc, value_gwei, sig, node);

    uint64_t size = t->count + 1;
    int level = 0;
    while ((size & 1) == 0) {
        sha256_2(t->branch[level], node, node);
        size >>= 1;
        level++;
    }
    std::memcpy(t->branch[level], node, 32);
    t->count++;
    return 0;
}

// contiguous column batches: pks [n*48], wcs [n*32], sigs [n*96], values [n]
int dt_deposit_batch(void *h, uint64_t n, const uint8_t *pks,
                     const uint8_t *wcs, const uint8_t *sigs,
                     const uint64_t *values) {
    for (uint64_t i = 0; i < n; i++) {
        int rc = dt_deposit(h, pks + 48 * i, wcs + 32 * i, sigs + 96 * i,
                            values[i]);
        if (rc) return rc;
    }
    return 0;
}

void dt_root(void *h, uint8_t out[32]) {
    auto *t = static_cast<DepositTree *>(h);
    uint8_t node[32] = {};
    uint64_t size = t->count;
    for (int level = 0; level < TREE_DEPTH; level++) {
        uint8_t next[32];
        if (size & 1)
            sha256_2(t->branch[level], node, next);
        else
            sha256_2(node, t->zerohashes[level], next);
        std::memcpy(node, next, 32);
        size >>= 1;
    }
    std::memcpy(out, node, 32);
}

}  // extern "C"
