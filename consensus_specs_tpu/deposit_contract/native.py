"""ctypes bridge to the native deposit-tree accumulator.

Loads (building on first use) native/deposit_tree.cpp — the C++
counterpart of the reference's EVM deposit contract
(/root/reference deposit_contract/contracts/validator_registration.v.py:
69-140). The Python model (contract.py) remains the behavioral oracle;
`NativeDepositTree` must agree with it byte-for-byte
(tests/test_deposit_contract.py::test_native_*), giving the same
python <-> native differential the reference runs python <-> EVM
(deposit_contract/tests/contracts/test_deposit.py).

Build is lazy via g++ (`-O3 -shared -fPIC`) into the repo .cache dir; on a
machine without a toolchain `available()` is False and callers skip.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "native", "deposit_tree.cpp")
_LIB_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "..", "..", ".cache", "native")
_LIB = os.path.join(_LIB_DIR, "libdeposit_tree.so")

_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    try:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            os.makedirs(_LIB_DIR, exist_ok=True)
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", _LIB],
                check=True, capture_output=True, timeout=120)
        lib = ctypes.CDLL(_LIB)
    except Exception:
        _build_failed = True
        return None
    lib.dt_new.restype = ctypes.c_void_p
    lib.dt_free.argtypes = [ctypes.c_void_p]
    lib.dt_count.restype = ctypes.c_uint64
    lib.dt_count.argtypes = [ctypes.c_void_p]
    lib.dt_deposit.restype = ctypes.c_int
    lib.dt_deposit.argtypes = [ctypes.c_void_p] + [ctypes.c_char_p] * 3 + [ctypes.c_uint64]
    lib.dt_deposit_batch.restype = ctypes.c_int
    lib.dt_deposit_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint64)]
    lib.dt_root.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    _lib = lib
    return lib


def available() -> bool:
    return _load() is not None


class NativeDepositTree:
    """Same surface as contract.DepositContract's accumulator core."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native deposit tree unavailable (no g++?)")
        self._lib = lib
        self._h = lib.dt_new()

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.dt_free(self._h)
            self._h = None

    @property
    def deposit_count(self) -> int:
        return int(self._lib.dt_count(self._h))

    def deposit(self, pubkey: bytes, withdrawal_credentials: bytes,
                signature: bytes, value_gwei: int) -> None:
        assert len(pubkey) == 48 and len(withdrawal_credentials) == 32 \
            and len(signature) == 96
        rc = self._lib.dt_deposit(self._h, pubkey, withdrawal_credentials,
                                  signature, value_gwei)
        assert rc == 0, f"native deposit rejected (rc={rc})"

    def deposit_batch(self, pubkeys: np.ndarray, wcs: np.ndarray,
                      sigs: np.ndarray, values: np.ndarray) -> None:
        """Column batches: [n,48]/[n,32]/[n,96] uint8 + [n] uint64."""
        n = pubkeys.shape[0]
        values = np.ascontiguousarray(values, dtype=np.uint64)
        rc = self._lib.dt_deposit_batch(
            self._h, n,
            np.ascontiguousarray(pubkeys, np.uint8).tobytes(),
            np.ascontiguousarray(wcs, np.uint8).tobytes(),
            np.ascontiguousarray(sigs, np.uint8).tobytes(),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
        assert rc == 0, f"native batch deposit rejected (rc={rc})"

    def get_deposit_root(self) -> bytes:
        out = ctypes.create_string_buffer(32)
        self._lib.dt_root(self._h, out)
        return out.raw
