"""Incremental deposit Merkle accumulator (the on-chain algorithm).

The contract keeps O(log n) state: one `branch` node per tree level plus a
counter. Each deposit leaf is the SSZ hash_tree_root of its DepositData —
computed here exactly the way the EVM code hand-rolls it (pubkey padded to
two chunks, signature as a three-chunk subtree, amount as a little-endian
64-bit chunk) so the differential test against the framework's generic SSZ
Merkleizer proves both sides agree byte-for-byte
(/root/reference deposit_contract/tests/contracts/test_deposit.py does the
same cross-check against pyspec).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..utils.hash import sha256

TREE_DEPTH = 32
MIN_DEPOSIT_GWEI = 1_000_000_000
FULL_DEPOSIT_GWEI = 32_000_000_000
CHAIN_START_FULL_DEPOSIT_THRESHOLD = 2 ** 16
SECONDS_PER_DAY = 86_400
MAX_DEPOSIT_COUNT = 2 ** TREE_DEPTH - 1


def _le64(value: int) -> bytes:
    assert 0 <= value < 2 ** 64
    return value.to_bytes(8, "little")


def deposit_data_root(pubkey: bytes, withdrawal_credentials: bytes,
                      amount_gwei: int, signature: bytes) -> bytes:
    """hash_tree_root(DepositData) the way the contract computes it:
    fixed-shape chunk tree, no generic SSZ machinery on chain."""
    pubkey_root = sha256(pubkey + b"\x00" * 16)
    signature_root = sha256(
        sha256(signature[:64])
        + sha256(signature[64:96] + b"\x00" * 32)
    )
    return sha256(
        sha256(pubkey_root + withdrawal_credentials)
        + sha256(_le64(amount_gwei) + b"\x00" * 24 + signature_root)
    )


@dataclass
class DepositEvent:
    pubkey: bytes
    withdrawal_credentials: bytes
    amount: bytes            # little-endian 8 bytes, as logged on chain
    signature: bytes
    merkle_tree_index: bytes


@dataclass
class Eth2GenesisEvent:
    deposit_root: bytes
    deposit_count: bytes
    time: bytes


class DepositContract:
    """The registration contract's state machine."""

    def __init__(self):
        self._zerohashes: List[bytes] = [b"\x00" * 32]
        for _ in range(TREE_DEPTH - 1):
            self._zerohashes.append(
                sha256(self._zerohashes[-1] + self._zerohashes[-1]))
        self._branch: List[bytes] = [b"\x00" * 32] * TREE_DEPTH
        self.deposit_count = 0
        self.full_deposit_count = 0
        self.chain_started = False
        self.logs: List[object] = []

    # -- views --------------------------------------------------------------

    def get_deposit_root(self) -> bytes:
        node = b"\x00" * 32
        size = self.deposit_count
        for level in range(TREE_DEPTH):
            if size & 1:
                node = sha256(self._branch[level] + node)
            else:
                node = sha256(node + self._zerohashes[level])
            size >>= 1
        return node

    def get_deposit_count(self) -> bytes:
        return _le64(self.deposit_count)

    # -- transactions -------------------------------------------------------

    def deposit(self, pubkey: bytes, withdrawal_credentials: bytes,
                signature: bytes, value_gwei: int,
                timestamp: int = 0) -> Optional[Eth2GenesisEvent]:
        assert self.deposit_count < MAX_DEPOSIT_COUNT
        assert len(pubkey) == 48
        assert len(withdrawal_credentials) == 32
        assert len(signature) == 96
        assert value_gwei >= MIN_DEPOSIT_GWEI

        index = self.deposit_count
        leaf = deposit_data_root(pubkey, withdrawal_credentials, value_gwei,
                                 signature)

        # fold the new leaf into the branch: climb while the subtree at
        # each level is complete (trailing-one positions of index+1)
        node = leaf
        size = index + 1
        level = 0
        while size & 1 == 0:
            node = sha256(self._branch[level] + node)
            size >>= 1
            level += 1
        self._branch[level] = node

        self.deposit_count += 1
        self.logs.append(DepositEvent(
            pubkey=pubkey,
            withdrawal_credentials=withdrawal_credentials,
            amount=_le64(value_gwei),
            signature=signature,
            merkle_tree_index=_le64(index),
        ))

        if value_gwei >= FULL_DEPOSIT_GWEI:
            self.full_deposit_count += 1
            if self.full_deposit_count == CHAIN_START_FULL_DEPOSIT_THRESHOLD:
                boundary = (timestamp - timestamp % SECONDS_PER_DAY
                            + 2 * SECONDS_PER_DAY)
                event = Eth2GenesisEvent(
                    deposit_root=self.get_deposit_root(),
                    deposit_count=_le64(self.deposit_count),
                    time=_le64(boundary),
                )
                self.logs.append(event)
                self.chain_started = True
                return event
        return None
