"""Deposit contract model: the eth1-side incremental Merkle accumulator.

Port of /root/reference deposit_contract/contracts/
validator_registration.v.py (Vyper/EVM there; a host-side Python model
here — the EVM is outside this framework's scope, but the accumulator
algorithm and its differential contract against the consensus-side SSZ
hash_tree_root(DepositData) are capability we must carry:
deposit() :69-140, get_deposit_root :51-62, Eth2Genesis trigger :128-140).
"""
from .contract import DepositContract, DepositEvent, Eth2GenesisEvent  # noqa: F401
