"""RPC-over-stream protocol: wrappers, methods, dispatch, loopback transport.

Contract: /root/reference specs/networking/rpc-interface.md — protocol id
`/eth/serenity/beacon/rpc/1` (:36), request wrapper (id, method_id, body)
and response wrapper (id, response_code, result) (:40-56), JSON-RPC-2.0-
style id semantics with out-of-order responses allowed (:58-68), reserved
response codes (:76-85), and the method set: hello 0 (:92-117), goodbye 1
(:140-156), get_status 2 (:160-182), beacon_block_roots 10 (:186-208),
beacon_block_headers 11 (:210-240), beacon_block_bodies 12 (:244-264),
beacon_chain_state 13 (:268-285, wire format TBD upstream — reserved here).

Bodies are SSZ containers from the framework's own type system; the
request's union-typed `body` (:56) is modeled as method-id-tagged SSZ
bytes, which is exactly how a union discriminates on the wire. Transports
are injected; `loopback_pair` wires two nodes memory-to-memory for tests.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from ..utils.ssz.impl import deserialize, serialize
from ..utils.ssz.typing import (
    Bytes32, Container, List as SSZList, uint8, uint16, uint64)
from .messaging import decode_message, encode_message

RPC_PROTOCOL_ID = "/eth/serenity/beacon/rpc/1"

# Reserved response codes (:76-85)
OK = 0
PARSE_ERROR = 10
INVALID_REQUEST = 20
METHOD_NOT_FOUND = 30
SERVER_ERROR = 40

GOODBYE_SHUTDOWN = 1
GOODBYE_IRRELEVANT_NETWORK = 2
GOODBYE_FAULT = 3


# ---------------------------------------------------------------------------
# Wire wrappers (:40-56)
# ---------------------------------------------------------------------------

class Request(Container):
    id: uint64
    method_id: uint16
    body: bytes            # SSZ of the method's request container


class Response(Container):
    id: uint64
    response_code: uint16
    result: bytes          # SSZ of the method's response container (may be empty)


# ---------------------------------------------------------------------------
# Method bodies
# ---------------------------------------------------------------------------

class Hello(Container):                      # method 0 (:92-117)
    network_id: uint8
    chain_id: uint64
    latest_finalized_root: Bytes32
    latest_finalized_epoch: uint64
    best_root: Bytes32
    best_slot: uint64


class Goodbye(Container):                    # method 1 (:140-156)
    reason: uint64


class Status(Container):                     # method 2 (:160-182)
    sha: Bytes32
    user_agent: bytes
    timestamp: uint64


class BlockRootsRequest(Container):          # method 10 (:186-208)
    start_slot: uint64
    count: uint64


class BlockRootSlot(Container):
    block_root: Bytes32
    slot: uint64


class BlockRootsResponse(Container):
    roots: SSZList[BlockRootSlot]


class BlockHeadersRequest(Container):        # method 11 (:210-240)
    start_root: Bytes32
    start_slot: uint64
    max_headers: uint64
    skip_slots: uint64


class BlockHeadersResponse(Container):
    headers: bytes         # SSZ of List[BeaconBlockHeader] (preset-shaped spec type)


class BlockBodiesRequest(Container):         # method 12 (:244-264)
    block_roots: SSZList[Bytes32]


class BlockBodiesResponse(Container):
    block_bodies: bytes    # SSZ of List[BeaconBlockBody] (preset-shaped spec type)


MAX_BLOCK_ROOTS_COUNT = 32768   # (:208)

HELLO, GOODBYE, GET_STATUS = 0, 1, 2
BEACON_BLOCK_ROOTS, BEACON_BLOCK_HEADERS, BEACON_BLOCK_BODIES = 10, 11, 12
BEACON_CHAIN_STATE = 13         # wire format TBD upstream; id reserved

METHOD_TYPES: Dict[int, Tuple[type, Optional[type]]] = {
    HELLO: (Hello, Hello),
    GOODBYE: (Goodbye, None),
    GET_STATUS: (Status, Status),
    BEACON_BLOCK_ROOTS: (BlockRootsRequest, BlockRootsResponse),
    BEACON_BLOCK_HEADERS: (BlockHeadersRequest, BlockHeadersResponse),
    BEACON_BLOCK_BODIES: (BlockBodiesRequest, BlockBodiesResponse),
}


class RpcError(Exception):
    def __init__(self, code: int, message: str = ""):
        super().__init__(message or f"rpc error {code}")
        self.code = code


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------

class RpcNode:
    """One endpoint of the RPC protocol.

    Handlers are `fn(request_container) -> response_container | None`;
    `call` sends a request through the attached transport and returns the
    decoded response container (or raises RpcError with the peer's code).
    Ids are per-connection monotonic (:62-64); responses match on id, so a
    transport MAY deliver them out of order (:66-68)."""

    def __init__(self, name: str = "node"):
        self.name = name
        self._handlers: Dict[int, Callable[[Any], Any]] = {}
        self._types: Dict[int, Tuple[Optional[type], Optional[type]]] = \
            dict(METHOD_TYPES)
        self._send: Optional[Callable[[bytes], bytes]] = None
        self._next_id = 0
        self.said_goodbye: Optional[int] = None

        # built-in: goodbye just records the reason (:150-156)
        def _on_goodbye(body: Goodbye):
            self.said_goodbye = int(body.reason)
            return None
        self._handlers[GOODBYE] = _on_goodbye

    def register(self, method_id: int, handler: Callable[[Any], Any],
                 req_type: Optional[type] = None,
                 resp_type: Optional[type] = None) -> None:
        """Attach a handler; for method ids outside METHOD_TYPES (custom or
        reserved ones like BEACON_CHAIN_STATE) pass the body/result
        container types here — without them the handler receives raw bytes
        and must return raw bytes (the union stays untyped on this node)."""
        self._handlers[method_id] = handler
        if req_type is not None or resp_type is not None:
            self._types[method_id] = (req_type, resp_type)
        else:
            # registering with no types marks the method as known-but-
            # untyped on this node: bodies/results travel as raw bytes
            self._types.setdefault(method_id, (None, None))

    def attach(self, send: Callable[[bytes], bytes]) -> None:
        """send(wire_request_bytes) -> wire_response_bytes."""
        self._send = send

    # -- client side --------------------------------------------------------

    def call(self, method_id: int, body: Any) -> Any:
        assert self._send is not None, "no transport attached"
        if method_id not in self._types:
            raise RpcError(METHOD_NOT_FOUND,
                           f"no body types known for method {method_id}; "
                           "register(..., req_type=, resp_type=) first")
        req_type, resp_type = self._types[method_id]
        if req_type is None:
            body_bytes = bytes(body)
        else:
            assert isinstance(body, req_type), f"body must be {req_type.__name__}"
            body_bytes = serialize(body, req_type)
        req_id = self._next_id
        self._next_id += 1
        wire = encode_message(serialize(
            Request(id=req_id, method_id=method_id, body=body_bytes), Request))
        _, _, resp_bytes = decode_message(self._send(wire))
        resp = deserialize(resp_bytes, Response)
        if int(resp.id) != req_id:
            raise RpcError(INVALID_REQUEST, "response id mismatch")
        if int(resp.response_code) != OK:
            raise RpcError(int(resp.response_code))
        if resp_type is None:
            return bytes(resp.result) or None
        return deserialize(bytes(resp.result), resp_type)

    # -- server side --------------------------------------------------------

    def handle_wire(self, data: bytes) -> bytes:
        """Decode request -> dispatch -> encoded response. Error paths map
        to the reserved response codes; malformed ids echo 0."""
        req_id = 0
        try:
            _, _, payload = decode_message(data)
            req = deserialize(payload, Request)
            req_id = int(req.id)
        except Exception:
            return self._respond(req_id, PARSE_ERROR, b"")
        method = int(req.method_id)
        if method not in self._handlers:
            return self._respond(req_id, METHOD_NOT_FOUND, b"")
        req_type, resp_type = self._types.get(method, (None, None))
        try:
            body = (deserialize(bytes(req.body), req_type)
                    if req_type is not None else bytes(req.body))
        except Exception:
            return self._respond(req_id, INVALID_REQUEST, b"")
        try:
            result = self._handlers[method](body)
            if result is None:
                out = b""
            elif resp_type is None:
                out = bytes(result)   # untyped method: handler returns bytes
            else:
                out = serialize(result, resp_type)
        except RpcError as err:
            return self._respond(req_id, err.code, b"")
        except Exception:
            return self._respond(req_id, SERVER_ERROR, b"")
        return self._respond(req_id, OK, out)

    @staticmethod
    def _respond(req_id: int, code: int, result: bytes) -> bytes:
        return encode_message(serialize(
            Response(id=req_id, response_code=code, result=result), Response))


def loopback_pair(a_name: str = "a", b_name: str = "b") -> Tuple[RpcNode, RpcNode]:
    """Two nodes wired memory-to-memory: a.call() dispatches on b and vice
    versa — the in-process transport the test corpus drives."""
    a, b = RpcNode(a_name), RpcNode(b_name)
    a.attach(b.handle_wire)
    b.attach(a.handle_wire)
    return a, b


# ---------------------------------------------------------------------------
# Handshake policy (:119-138)
# ---------------------------------------------------------------------------

def should_disconnect(mine: Hello, theirs: Hello,
                      my_root_at_epoch: Callable[[int], Optional[bytes]]) -> bool:
    """The two SHOULD-disconnect conditions after the hello exchange:
    different network, or the peer's finalized root not being our chain's
    root at that epoch (my_root_at_epoch -> None when unknown)."""
    if int(theirs.network_id) != int(mine.network_id):
        return True
    known = my_root_at_epoch(int(theirs.latest_finalized_epoch))
    if known is not None and bytes(known) != bytes(theirs.latest_finalized_root):
        return True
    return False
