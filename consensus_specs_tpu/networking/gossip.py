"""Gossipsub model: parameters, topics, and an in-process router.

Contract: /root/reference specs/networking/libp2p-standardization.md:72-158:
the standardized mesh parameters (:86-105), the `beacon_block` /
`beacon_attestation` topics plus per-shard-subnet attestation topics
(:109-127), SHA2-256 topic hashes (:107-108), SSZ message payloads with a
512 KB cap (:131-139).

The router is deliberately transport-free: nodes subscribe handlers and
publish SSZ bytes; propagation is synchronous, deduplicated by message
digest (gossipsub's seen-cache), and capped at the spec's message size.
It is the multi-node test backend — the same role the minimal preset plays
for state-transition tests (SURVEY.md §4 "the minimal preset is the fake
backend").
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Set, Tuple

from ..utils.hash import sha256

GOSSIPSUB_PROTOCOL_ID = "/eth/serenity/gossipsub/1.0.0"

TOPIC_BEACON_BLOCK = "beacon_block"
TOPIC_BEACON_ATTESTATION = "beacon_attestation"

MAX_GOSSIP_MESSAGE_BYTES = 512 * 1024


@dataclass(frozen=True)
class GossipParams:
    """Standardized mesh parameters (libp2p-standardization.md:86-105)."""
    mesh_size: int = 6        # D
    mesh_lo: int = 4          # D_lo
    mesh_high: int = 12       # D_high
    gossip_lazy: int = 6      # D_lazy
    fanout_ttl: int = 60      # seconds
    gossip_history: int = 3   # heartbeats
    heartbeat_interval: int = 1  # seconds


def shard_attestation_topic(shard: int, shard_subnet_count: int) -> str:
    """`shard{shard % SHARD_SUBNET_COUNT}_attestation` (:123-127)."""
    return f"shard{shard % shard_subnet_count}_attestation"


def topic_hash(topic: str) -> bytes:
    """Topics travel as SHA2-256 hashes of the topic string (:107-108)."""
    return sha256(topic.encode())


class GossipRouter:
    """In-process pubsub fabric shared by a set of model nodes.

    subscribe() registers (node, handler) on a topic; publish() delivers the
    payload to every OTHER subscriber exactly once per unique message
    (seen-cache dedup — re-publishing an already-seen message, as a
    forwarding node would, is a no-op)."""

    def __init__(self, params: GossipParams = GossipParams()):
        self.params = params
        self._subs: Dict[bytes, List[Tuple[str, Callable[[str, bytes], None]]]] = {}
        self._seen: Set[bytes] = set()
        self.delivered = 0   # observability: total handler invocations
        self.dropped_oversize = 0
        self.handler_failures = 0

    def subscribe(self, node_id: str, topic: str,
                  handler: Callable[[str, bytes], None]) -> None:
        self._subs.setdefault(topic_hash(topic), []).append((node_id, handler))

    def publish(self, node_id: str, topic: str, payload: bytes) -> int:
        """-> number of peers the message reached (0 if duplicate/oversize —
        oversize messages are dropped, as a gossipsub router would drop
        them, and counted in dropped_oversize)."""
        if len(payload) > MAX_GOSSIP_MESSAGE_BYTES:
            self.dropped_oversize += 1
            return 0
        digest = sha256(topic_hash(topic) + payload)
        if digest in self._seen:
            return 0
        # mark seen BEFORE the delivery sweep: a handler that synchronously
        # republishes the same message (the forwarding pattern) must hit the
        # duplicate check, not re-enter a nested sweep. If the sweep itself
        # escapes (impossible above, but future-proof), un-mark so a
        # half-delivered message is not permanently blacklisted.
        self._seen.add(digest)
        reached = 0
        try:
            for sub_id, handler in self._subs.get(topic_hash(topic), []):
                if sub_id == node_id:
                    continue
                try:
                    handler(topic, payload)
                    reached += 1
                except Exception:
                    # a peer's handler failing is that peer's problem:
                    # delivery to the others proceeds, observably counted
                    self.handler_failures += 1
        except BaseException:
            self._seen.discard(digest)
            raise
        self.delivered += reached
        return reached
