"""Node identification: records, peer ids, multiaddrs.

Contract: /root/reference specs/networking/node-identification.md:11-27 —
nodes advertise ENR-style records carrying at least (ip, tcp port, public
key); receivers MUST verify record signatures and the peer id is the
SHA2-256 multihash of the public key. Port defaults to 9000.

Adaptation notes: EIP-778 signs records with secp256k1; this framework's
crypto stack is BLS12-381 (the only curve the protocol itself needs), so
records sign with the standard bls backend boundary (crypto/bls) over the
record's content digest — same verify-or-disconnect contract, no second
curve implementation hauled in for a transport detail.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..crypto import bls
from ..utils.hash import sha256

DEFAULT_TCP_PORT = 9000
ENR_SIGNING_DOMAIN = 0x454E52   # "ENR"

_MULTIHASH_SHA256 = bytes([0x12, 0x20])   # sha2-256, 32 bytes


@dataclass
class NodeRecord:
    """The addressable identity a node gossips about itself."""
    ip: str
    pubkey: bytes                      # BLS public key (48 bytes)
    tcp_port: int = DEFAULT_TCP_PORT
    udp_port: Optional[int] = None     # discv5 side-channel
    seq: int = 0                       # record sequence number (EIP-778 semantics)
    signature: bytes = field(default=b"", repr=False)

    def content_digest(self) -> bytes:
        parts = [
            self.ip.encode(),
            int(self.tcp_port).to_bytes(2, "little"),
            int(self.udp_port or 0).to_bytes(2, "little"),
            int(self.seq).to_bytes(8, "little"),
            bytes(self.pubkey),
        ]
        return sha256(b"\x00".join(parts))

    def sign(self, privkey: int) -> "NodeRecord":
        self.signature = bls.bls_sign(
            self.content_digest(), privkey, ENR_SIGNING_DOMAIN)
        return self

    def verify(self) -> bool:
        """MUST-verify gate: a False here means disconnect the peer."""
        if not self.signature:
            return False
        try:
            return bls.bls_verify(bytes(self.pubkey), self.content_digest(),
                                  bytes(self.signature), ENR_SIGNING_DOMAIN)
        except Exception:
            return False


def peer_id(pubkey: bytes) -> bytes:
    """SHA2-256 multihash of the public key (node-identification.md:23-25)."""
    return _MULTIHASH_SHA256 + sha256(bytes(pubkey))


def multiaddr(record: NodeRecord) -> str:
    """The libp2p dial address derivable from a record's keys."""
    return f"/ip4/{record.ip}/tcp/{record.tcp_port}/p2p/{peer_id(record.pubkey).hex()}"
