"""Wire message envelope.

Contract: /root/reference specs/networking/messaging.md:21-45 — a message
is (compression nibble, encoding nibble, uint64 body length, body). The two
nibbles pack into one byte (compression high, encoding low); the length is
little-endian per SSZ numeric convention. "Clients MUST ignore messages
with malformed bodies" — decode therefore reports malformation via a typed
error the caller can drop, never by crashing.

Also provides the raw-TCP `ETH` prefix for non-libp2p transports
(/root/reference specs/networking/rpc-interface.md:87-89).
"""
from __future__ import annotations

from typing import Tuple

COMPRESSION_NONE = 0x0
ENCODING_SSZ = 0x1

TCP_PREFIX = b"ETH"          # 0x455448, raw-TCP disambiguation prefix

_HEADER_LEN = 1 + 8          # packed nibbles + uint64 length


class MessageEnvelopeError(ValueError):
    """Malformed envelope — the spec says to ignore such messages."""


def encode_message(body: bytes, compression: int = COMPRESSION_NONE,
                   encoding: int = ENCODING_SSZ) -> bytes:
    if not 0 <= compression <= 0xF or not 0 <= encoding <= 0xF:
        raise ValueError("nibble out of range")
    header = bytes([(compression << 4) | encoding])
    return header + len(body).to_bytes(8, "little") + bytes(body)


def decode_message(data: bytes) -> Tuple[int, int, bytes]:
    """-> (compression, encoding, body). Raises MessageEnvelopeError on any
    malformation (short header, unknown nibble, length mismatch)."""
    if len(data) < _HEADER_LEN:
        raise MessageEnvelopeError("short envelope")
    compression = data[0] >> 4
    encoding = data[0] & 0xF
    if compression != COMPRESSION_NONE:
        raise MessageEnvelopeError(f"unknown compression nibble {compression}")
    if encoding != ENCODING_SSZ:
        raise MessageEnvelopeError(f"unknown encoding nibble {encoding}")
    length = int.from_bytes(data[1:9], "little")
    body = data[_HEADER_LEN:]
    if len(body) != length:
        raise MessageEnvelopeError(
            f"length field {length} != body length {len(body)}")
    return compression, encoding, body


def frame_tcp(message: bytes) -> bytes:
    """Prefix for raw-TCP transports (pre-libp2p interop)."""
    return TCP_PREFIX + message


def unframe_tcp(data: bytes) -> bytes:
    if not data.startswith(TCP_PREFIX):
        raise MessageEnvelopeError("missing ETH prefix")
    return data[len(TCP_PREFIX):]
