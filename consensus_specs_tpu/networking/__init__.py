"""Executable model of the Eth 2.0 networking specs.

The reference's networking layer is paper-only (SURVEY.md §2a row
"Networking") — four markdown documents and no code. Here each document is
an executable module so the wire behavior is testable and the test
framework can drive multi-node flows in-process:

- messaging.py   — message envelope codec
  (/root/reference specs/networking/messaging.md:21-45)
- rpc.py         — RPC-over-stream request/response protocol + methods
  (/root/reference specs/networking/rpc-interface.md:36-285)
- gossip.py      — gossipsub parameters, topics, in-process router
  (/root/reference specs/networking/libp2p-standardization.md:72-158)
- identity.py    — node records, peer ids, multiaddrs
  (/root/reference specs/networking/node-identification.md:11-27)

No sockets: transport is an injectable byte-pipe abstraction (the
in-process loopback used in tests mirrors how the rest of the framework
treats multi-node work — offline, deterministic, vector-friendly).
"""
from .messaging import (  # noqa: F401
    COMPRESSION_NONE, ENCODING_SSZ, MessageEnvelopeError, decode_message,
    encode_message)
from .identity import NodeRecord, multiaddr, peer_id  # noqa: F401
from .gossip import (  # noqa: F401
    GOSSIPSUB_PROTOCOL_ID, GossipParams, GossipRouter, TOPIC_BEACON_ATTESTATION,
    TOPIC_BEACON_BLOCK, shard_attestation_topic, topic_hash)
from .rpc import (  # noqa: F401
    RPC_PROTOCOL_ID, Goodbye, Hello, RpcError, RpcNode, loopback_pair)
