"""Cross-slot batching queue for staged pairing work (ISSUE 15).

A `VerificationQueue` holds STAGED groups — each one aggregate-verify's
pairing inputs, already host-staged to limb arrays by
`JaxBackend.stage_indexed_batch` — bucketed by pair count (the static
shape axis of the grouped pairing program). Groups accumulate ACROSS
slots until a bucket reaches the target occupancy (>= 128 groups per
launch by default: the shape where the shared-squaring Miller loop and
the batched final exponentiation actually fill a device batch, vs the
handful of groups one block contributes), at which point
`take_batches()` hands full batches to the pipeline. `partial=True`
drains the remainder — the fork-choice-deadline flush.

Depth is mirrored into the `firehose.queue_depth` gauge on every
mutation so /metrics and /healthz read the live backlog.
"""
from __future__ import annotations

import collections
from typing import Deque, Dict, List, Tuple

import numpy as np

from ._metrics import counter as _counter
from ._metrics import gauge as _gauge


class VerificationQueue:
    """Staged pairing groups, bucketed by pair count, accumulated across
    slots toward `target_groups` per device launch."""

    def __init__(self, target_groups: int = 128):
        assert target_groups >= 1
        self.target_groups = int(target_groups)
        # pair count -> deque of (key, g1 [count,2,L], g2 [count,2,2,L])
        self._buckets: Dict[int, Deque[tuple]] = {}
        self._depth = 0
        _gauge("firehose.queue_depth").set(0)   # registered from birth:
        # /metrics must show the backlog row before the first aggregate

    # -- state ----------------------------------------------------------

    @property
    def depth(self) -> int:
        """Total groups queued (the /healthz backlog)."""
        return self._depth

    def bucket_depths(self) -> Dict[int, int]:
        return {c: len(dq) for c, dq in self._buckets.items()}

    # -- mutation -------------------------------------------------------

    def push(self, key, pairs) -> None:
        """Enqueue one group: `pairs` = [(g1 [2,L], g2 [2,2,L])...] limb
        arrays (the stage_indexed_batch group shape). `key` is the
        caller's verdict handle (the verifier's content digest)."""
        count = len(pairs)
        assert count >= 1, "empty groups are decided at staging, not queued"
        g1 = np.stack([a for a, _ in pairs])
        g2 = np.stack([b for _, b in pairs])
        self._buckets.setdefault(count, collections.deque()).append(
            (key, g1, g2))
        self._depth += 1
        _counter("firehose.enqueued").inc()
        _gauge("firehose.queue_depth").set(self._depth)

    def take_batches(self, partial: bool = False
                     ) -> List[Tuple[int, list]]:
        """Pop dispatchable batches: every full `target_groups` run per
        bucket, plus — with `partial=True` (the deadline flush) — each
        bucket's remainder. Returns [(pair_count, members)] with members
        = [(key, g1, g2)] in FIFO order."""
        out: List[Tuple[int, list]] = []
        for count in sorted(self._buckets):
            dq = self._buckets[count]
            while len(dq) >= self.target_groups:
                out.append((count, [dq.popleft()
                                    for _ in range(self.target_groups)]))
            if partial and dq:
                out.append((count, [dq.popleft() for _ in range(len(dq))]))
        for count in [c for c, dq in self._buckets.items() if not dq]:
            del self._buckets[count]
        taken = sum(len(m) for _, m in out)
        if taken:
            self._depth -= taken
            _gauge("firehose.queue_depth").set(self._depth)
        return out
