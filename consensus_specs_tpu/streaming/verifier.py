"""`StreamingVerifier`: the attestation-firehose facade (ISSUE 15).

Ingests attestations/aggregates — SSZ gossip payloads through the
networking decode path, pre-staged pairing groups, or the block path's
deferred-verification items — dedups them by content digest (the
gossipsub seen-cache idiom, but over verification WORK rather than
wire bytes), stages them through the SAME host pipeline as the
synchronous path (`JaxBackend.stage_indexed_batch`: grouped G1
decompress+aggregate, batched G2 decompress, batched hash-to-curve),
accumulates the staged groups across slots in a `VerificationQueue`,
and drives the double-buffered `FirehosePipeline`. Verdicts come back
per attestation, BIT-IDENTICAL to `verify_indexed_batch` — the
differential suite in tests/test_streaming.py is the acceptance gate.

The serving rhythm:

    v = StreamingVerifier(target_groups=128, deadline_ms=...)
    v.ingest_gossip(spec, state, payload)     # per gossip message
    v.pump()                                  # per slot tick: stage +
                                              #   dispatch full batches
    v.flush()                                 # fork-choice deadline:
                                              #   partial batches + ONE
                                              #   guarded materialization
    v.verdict(digest)                         # -> bool | None

`state_transition` consumes the queued verdicts through
`spec._streaming_verifier` (models/phase0/block.py): items the firehose
already verified are served from the cache (`firehose.cache_hits`);
misses verify through the same queue with an immediate flush.
"""
from __future__ import annotations

import collections
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.hash import sha256
from ._metrics import counter as _counter
from ._metrics import span as _span
from .pipeline import FirehosePipeline
from .queue import VerificationQueue

# exception classes the SSZ decoder / spec validity checks raise for
# garbage a gossip peer could actually send (the beacon_node _INVALID set)
_UNDECODABLE = (AssertionError, IndexError, ValueError)


def item_digest(pubkey_sets, message_hashes, signature, domain) -> bytes:
    """Content digest of one verification item — the dedup key AND the
    verdict-cache key shared by gossip pre-verification and the block
    path (identical staging inputs => identical digest => one device
    verification total)."""
    parts = [int(domain).to_bytes(8, "little"), bytes(signature)]
    for pk_set, mh in zip(pubkey_sets, message_hashes):
        parts.append(b"\x01")
        parts.append(bytes(mh))
        for pk in pk_set:
            parts.append(bytes(pk))
    return sha256(b"".join(parts))


def indexed_verify_item(spec, state, indexed) -> tuple:
    """The (pubkey_sets, message_hashes, signature, domain) tuple
    `validate_indexed_attestation` sinks for an indexed attestation —
    built here for gossip ingest so the firehose pre-verifies EXACTLY
    the item the block path will look up later."""
    bit0 = indexed.custody_bit_0_indices
    bit1 = indexed.custody_bit_1_indices
    pubkey_sets = [
        [bytes(state.validator_registry[i].pubkey) for i in bit0],
        [bytes(state.validator_registry[i].pubkey) for i in bit1],
    ]
    message_hashes = [
        spec.hash_tree_root(spec.AttestationDataAndCustodyBit(
            data=indexed.data, custody_bit=False)),
        spec.hash_tree_root(spec.AttestationDataAndCustodyBit(
            data=indexed.data, custody_bit=True)),
    ]
    domain = spec.get_domain(state, spec.DOMAIN_ATTESTATION,
                             indexed.data.target_epoch)
    return (pubkey_sets, message_hashes, bytes(indexed.signature),
            int(domain))


class StreamingVerifier:
    """Queue + pipeline + verdict cache behind one facade."""

    def __init__(self, *, backend=None, target_groups: int = 128,
                 deadline_ms: Optional[float] = None,
                 ring_capacity: Optional[int] = None,
                 retain: int = 65536,
                 clock: Callable[[], float] = time.perf_counter,
                 sleep: Callable[[float], None] = time.sleep,
                 register: bool = True):
        if backend is None:
            from ..ops.bls_jax import JaxBackend
            backend = JaxBackend()
        self.backend = backend
        self.deadline_ms = deadline_ms
        self.queue = VerificationQueue(target_groups)
        padded = 1
        while padded < target_groups:
            padded *= 2
        if ring_capacity is None:
            ring_capacity = max(1024, 8 * padded)
        assert ring_capacity >= padded, \
            f"ring_capacity {ring_capacity} < padded target {padded}"
        self.pipeline = FirehosePipeline(
            deadline_ms=deadline_ms, ring_capacity=ring_capacity,
            clock=clock, sleep=sleep)
        # Dedup/verdict retention is BOUNDED — the gossipsub seen-cache
        # idiom: a sustained firehose must not grow host state per
        # aggregate forever. Resolved digests evict FIFO past `retain`
        # (floored well above any flush window, so a block's sink can
        # never lose a verdict mid-lookup); an evicted item that
        # re-arrives simply re-verifies.
        self.retain = max(int(retain), 4096)
        self._verdicts: Dict[bytes, bool] = {}
        self._resolved: collections.deque = collections.deque()
        self._seen: set = set()            # digests submitted or decided
        self._pending: List[Tuple[bytes, tuple]] = []   # awaiting staging
        if register:
            from . import activate
            activate(self)

    # -- ingest ----------------------------------------------------------

    def submit_indexed(self, pubkey_sets, message_hashes, signature,
                       domain) -> bytes:
        """Enqueue one indexed-attestation verification item; returns its
        digest (the verdict handle). Duplicates — same committees, same
        message, same aggregate — collapse onto one verification."""
        item = (
            [ [bytes(p) for p in s] for s in pubkey_sets ],
            [bytes(m) for m in message_hashes],
            bytes(signature), int(domain))
        digest = item_digest(*item)
        if digest in self._verdicts:
            _counter("firehose.cache_hits").inc()
            return digest
        if digest in self._seen:
            _counter("firehose.duplicates").inc()
            return digest
        self._seen.add(digest)
        self._pending.append((digest, item))
        _counter("firehose.ingested").inc()
        return digest

    def submit_staged(self, key, pairs) -> None:
        """Enqueue an ALREADY-STAGED pairing group: pairs = [(g1 [2,L],
        g2 [2,2,L])] limb arrays. The ingestion point for synthetic
        gossip load (bench/smoke) and internal re-verification; keys are
        the caller's verdict handles, deduplicated like digests."""
        if key in self._seen or key in self._verdicts:
            _counter("firehose.duplicates").inc()
            return
        self._seen.add(key)
        _counter("firehose.ingested").inc()
        self.queue.push(key, pairs)

    def ingest_gossip(self, spec, state, payload) -> Optional[bytes]:
        """One `beacon_attestation` gossip payload (SSZ bytes, the
        networking/gossip.py wire format) -> submitted digest, or None
        when the payload is undecodable / names unknown committees
        (counted; a bad gossip message must never crash the firehose)."""
        from ..utils.ssz.impl import deserialize
        try:
            att = deserialize(bytes(payload), spec.Attestation)
            indexed = spec.convert_to_indexed(state, att)
            item = indexed_verify_item(spec, state, indexed)
        except _UNDECODABLE:
            _counter("firehose.undecodable").inc()
            return None
        return self.submit_indexed(*item)

    # -- the pipeline rhythm ---------------------------------------------

    def _remember(self, key, verdict: bool) -> None:
        """Record a resolved verdict, evicting the oldest resolved
        entries (and their dedup digests) past the retention bound."""
        if key not in self._verdicts:
            self._resolved.append(key)
        self._verdicts[key] = bool(verdict)
        while len(self._resolved) > self.retain:
            old = self._resolved.popleft()
            self._verdicts.pop(old, None)
            self._seen.discard(old)

    def _stage_pending(self) -> None:
        """Host-stage every pending item through the synchronous path's
        staging (batched across items: one grouped G1 program, one
        hash-to-curve batch) and queue the resulting pairing groups.
        Items decided at staging (malformed -> False, empty product ->
        True) resolve immediately."""
        if not self._pending:
            return
        pending, self._pending = self._pending, []
        results, groups = self.backend.stage_indexed_batch(
            [item for _, item in pending])
        for idx, (digest, _) in enumerate(pending):
            if results[idx] is not None:
                self._remember(digest, results[idx])
        for idx, pairs in groups:
            self.queue.push(pending[idx][0], pairs)

    def pump(self) -> None:
        """One pipeline turn (call per slot tick / ingest wave): stage
        pending items — host work that overlaps whatever the device is
        pairing — then launch every FULL batch asynchronously. Never
        blocks."""
        with _span("firehose.stage", pending=len(self._pending)):
            self._stage_pending()
        for count, members in self.queue.take_batches():
            self.pipeline.dispatch(count, members)

    def flush(self, deadline_ms: Optional[float] = None
              ) -> Dict[object, bool]:
        """The fork-choice deadline: stage + dispatch everything still
        queued (PARTIAL batches included — counted), then block once on
        the pipeline's guarded ring materialization. Returns the newly
        resolved {key: verdict}; the cache keeps them for `verdict`."""
        with _span("firehose.stage", pending=len(self._pending)):
            self._stage_pending()
        for count, members in self.queue.take_batches(partial=True):
            if len(members) < self.queue.target_groups:
                _counter("firehose.partial_flushes").inc()
            self.pipeline.dispatch(count, members)
        got = self.pipeline.flush(
            deadline_ms if deadline_ms is not None else self.deadline_ms)
        for key, verdict in got.items():
            self._remember(key, verdict)
        return got

    # -- verdicts ---------------------------------------------------------

    def verdict(self, key) -> Optional[bool]:
        """Resolved verdict for a digest/key, None while still queued or
        in flight."""
        return self._verdicts.get(key)

    def verdicts_for(self, items: Sequence[tuple]) -> List[bool]:
        """The block path's entry (models/phase0/block.py): items are
        the `_att_verify_sink` tuples (pubkey_sets, message_hashes,
        signature, domain). Already-verified items (gossip
        pre-verification) are served from the cache; misses stage,
        queue, and flush through the same pipeline. Verdicts are
        bit-identical to `verify_indexed_batch(items)` — same staging,
        same device programs, batch shape proven inert by the
        differential suite."""
        digests = [self.submit_indexed(*item) for item in items]
        if any(d not in self._verdicts for d in digests):
            self.pump()
            self.flush()
        return [bool(self._verdicts[d]) for d in digests]
