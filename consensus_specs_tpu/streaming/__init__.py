"""Streaming verification: the attestation-firehose subsystem (ISSUE 15).

Decouples BLS signature verification from `state_transition`. Mainnet
traffic is a gossip firehose — thousands of aggregates per slot from
~1M attesting validators — and the grouped-Miller kernel amortizes its
fq12 squarings across GROUPS (PAPERS.md [2]): it only pays off when fed
full device batches, which one block's worth of attestations never is.
This package accumulates verification work ACROSS slots into full
batches and overlaps the host staging of batch N+1 with the device
pairing of batch N:

  * queue.py    — `VerificationQueue`: staged pairing groups bucketed by
                  pair count, accumulated across slots toward a target
                  batch occupancy (>= 128 groups per launch).
  * pipeline.py — `FirehosePipeline`: async dispatch of full batches
                  through `resilience.guarded_dispatch`, per-batch
                  verdicts scattered into a device-resident ring buffer
                  (donated in-place on accelerators), ONE host transfer
                  at the fork-choice deadline — `jax.block_until_ready`
                  only there; a deadline miss flushes the partial batch
                  late (salvaged) instead of stalling.
  * verifier.py — `StreamingVerifier`: the facade. Ingests aggregates
                  (SSZ gossip payloads via the networking decode path,
                  or pre-staged items), dedups by content digest, stages
                  through the SAME host pipeline as the synchronous path
                  (`JaxBackend.stage_indexed_batch`), and hands
                  per-attestation verdicts back to `state_transition` /
                  fork-choice — bit-identical to the synchronous path.

Telemetry control surface (the PR 7 registry; all counters always=True
so /healthz stays truthful under CSTPU_TELEMETRY=0): spans
`firehose.{stage,dispatch,flush,harvest}` with exit-only fences, gauge
`firehose.queue_depth`, pow2 histogram `firehose.batch_occupancy`,
counters `firehose.deadline_miss` (+ ingested/duplicates/cache_hits/
launches/groups_verified). `BeaconNodeAPI.get_healthz()` serves
`firehose_health()`; `/metrics` exposes the instruments.
"""
from __future__ import annotations

import time
from typing import Optional

from .pipeline import FirehosePipeline
from .queue import VerificationQueue
from .verifier import StreamingVerifier

__all__ = [
    "FirehosePipeline", "StreamingVerifier", "VerificationQueue",
    "activate", "active", "firehose_health",
]

# the process-global verifier /healthz reports (the DegradationLadder
# idiom: last activated wins; None = no firehose running)
_ACTIVE: Optional[StreamingVerifier] = None


def activate(verifier: Optional[StreamingVerifier]):
    """Install `verifier` as the process-global firehose (what /healthz
    and `firehose_health` report). Returns the previous one so tests and
    drills can restore it."""
    global _ACTIVE
    prev = _ACTIVE
    _ACTIVE = verifier
    return prev


def active() -> Optional[StreamingVerifier]:
    return _ACTIVE


def firehose_health() -> dict:
    """The /healthz firehose section: queue backlog, in-flight batches,
    seconds since the last flush, and the always-on counters — a plain
    JSON-ready dict, meaningful (all-zero backlog, None flush age) even
    when no verifier is active."""
    from .. import telemetry

    v = _ACTIVE
    last_flush = v.pipeline.last_flush_at if v is not None else None
    return {
        "backlog": v.queue.depth if v is not None else 0,
        "in_flight_batches": v.pipeline.in_flight if v is not None else 0,
        "last_flush_age_s": (round(time.monotonic() - last_flush, 3)
                             if last_flush is not None else None),
        "target_groups": v.queue.target_groups if v is not None else None,
        "counters": {
            name: int(telemetry.counter(f"firehose.{name}",
                                        always=True).value)
            for name in ("ingested", "duplicates", "cache_hits",
                         "launches", "groups_verified", "deadline_miss",
                         "partial_flushes")
        },
    }
