"""Shared lazy-import telemetry handles for the streaming package.

One definition for the three modules (queue/pipeline/verifier): every
firehose instrument is registered `always=True` — /healthz reads them
most urgently exactly when observability might be switched off — and
the telemetry import stays inside the call so the package is importable
without dragging the registry in at module load.
"""
from __future__ import annotations


def counter(name: str):
    from .. import telemetry
    return telemetry.counter(name, always=True)


def gauge(name: str):
    from .. import telemetry
    return telemetry.gauge(name, always=True)


def histogram(name: str):
    from .. import telemetry
    return telemetry.histogram(name, always=True)


def span(name: str, **args):
    from .. import telemetry
    return telemetry.span(name, **args)
