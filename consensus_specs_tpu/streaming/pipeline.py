"""Double-buffered device pipeline for the attestation firehose
(ISSUE 15 tentpole).

`FirehosePipeline` owns the device side of the streaming verifier:

  * **async dispatch** — each full batch launches the SAME two grouped
    pairing programs the synchronous path uses
    (`ops/bls_jax.grouped_pairing_check`, so the jit + persistent
    compile caches are shared), through `resilience.guarded_dispatch`
    UNARMED: no deadline, no fence — the launch returns immediately and
    the host goes back to staging the next batch (decompression +
    hash-to-curve of batch N+1 overlaps the pairing of batch N).
  * **verdict ring** — every batch's [G] verdict vector is scattered
    into a device-resident ring buffer by a one-equation
    `dynamic_update_slice` program whose ring argument is DONATED on
    accelerator backends (in-place update, byte-exact aliasing;
    XLA:CPU runs the undonated twin — persistent-cache-deserialized
    donated CPU executables have violated input/output aliasing, the
    PR 3 caveat). Verdicts therefore accumulate ON DEVICE; nothing is
    transferred per batch.
  * **deadline-bounded flush** — `flush(deadline_ms)` is the ONLY point
    that blocks: one guarded, wall-clock-budgeted materialization of the
    ring (`jax.block_until_ready` semantics at the fork-choice deadline,
    ROADMAP item 1). The guard runs with retries=0, so a late result is
    SALVAGED — the partial batch still lands, the miss is counted
    (`firehose.deadline_miss`, `resilience.deadline_misses`) and stays
    visible on /healthz — instead of a retry loop stalling fork choice.
  * **watchdogs** — the retrace watchdog wraps the ring-scatter program
    (shape-pinned key) and the re-layout watchdog fingerprints the
    chained ring buffer each scatter: a steady-state firehose must
    launch with ZERO events of either kind (the bench/smoke acceptance).

Degradation wiring: the pairing programs read the committed oracle
knobs at dispatch time (`_redc_mode_jit` keys one program per
CSTPU_FQ_REDC backend), so the PR 13 ladder's `redc_leaf` /
`scalar_double_add` rungs degrade the firehose the same bit-identical
way they degrade the block path — no extra plumbing here.
"""
from __future__ import annotations

import collections
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..telemetry import watchdog as _watchdog
from ..utils.donation import platform_donated_jit
from ._metrics import counter as _counter
from ._metrics import histogram as _histogram
from ._metrics import span as _span


# ---------------------------------------------------------------------------
# Verdict-ring scatter program
# ---------------------------------------------------------------------------

def _ring_scatter(ring, verdicts, start):
    """ring [R] bool, verdicts [G] bool, start scalar -> updated ring.
    The ring argument is donated on accelerators (same shape/dtype in and
    out: the aliasing survives lowering — pinned by the trace contract
    below), so steady-state batches update one resident buffer with no
    allocation and no transfer."""
    import jax
    return jax.lax.dynamic_update_slice(ring, verdicts, (start,))


# Twin jitted scatters resolved from the live platform (donate on
# accelerators, pinned undonated on XLA:CPU) — the shared
# platform_donated_jit helper builds lazily, so declaring it here keeps
# this module's no-jax-at-import property.
_ring_scatter_pd = platform_donated_jit(_ring_scatter, donate_argnums=(0,))


def _ring_scatter_jit():
    """The backend-selected jitted scatter (a plain jax.jit object, so
    the retrace watchdog sees its compile cache)."""
    return _ring_scatter_pd.resolve()


class FirehosePipeline:
    """Async grouped-pairing dispatch + device verdict ring + deadline
    flush. `clock`/`sleep` are forwarded to `guarded_dispatch`, so the
    deadline tests run on a fake clock with zero real sleeps."""

    def __init__(self, *, deadline_ms: Optional[float] = None,
                 ring_capacity: int = 1024,
                 clock: Callable[[], float] = time.perf_counter,
                 sleep: Callable[[float], None] = time.sleep):
        assert ring_capacity >= 1
        self.deadline_ms = deadline_ms
        self.ring_capacity = int(ring_capacity)
        self._clock = clock
        self._sleep = sleep
        self._ring = None               # device [R] bool, lazily allocated
        self._offset = 0                # next free ring slot
        self._pending: List[tuple] = []  # (keys, start, n) awaiting harvest
        self._harvested: Dict[object, bool] = {}   # ring drained early
        self.last_flush_at: Optional[float] = None
        self.launches = 0
        # real groups of the most recent launches (bounded: a sustained
        # firehose must not grow host state per launch — cumulative
        # totals live in the always-on counters)
        self.occupancies: collections.deque = collections.deque(
            maxlen=4096)

    # -- state ----------------------------------------------------------

    @property
    def in_flight(self) -> int:
        """Batches dispatched and not yet flushed."""
        return len(self._pending)

    # -- dispatch (async) ------------------------------------------------

    def dispatch(self, count: int, members) -> None:
        """Launch one batch: members = [(key, g1 [count,2,L],
        g2 [count,2,2,L])]. Returns immediately — the pairing programs
        and the ring scatter are all async; nothing is fetched until
        `flush`."""
        import jax.numpy as jnp
        from ..ops import bls_jax as BJ
        from ..resilience import guarded_dispatch

        keys = [m[0] for m in members]
        g1, g2 = BJ.stage_group_arrays([(m[1], m[2]) for m in members],
                                       count)
        g = g1.shape[0]
        if g > self.ring_capacity:
            # a clear configuration error, not a trace-time XLA shape
            # failure from dynamic_update_slice(update > operand)
            raise ValueError(
                f"firehose batch pads to {g} groups but the verdict "
                f"ring holds {self.ring_capacity}; size ring_capacity "
                f">= the padded target occupancy")
        if self._offset + g > self.ring_capacity:
            # ring full before the deadline: drain early (counted — at
            # the nominal load point the capacity covers a whole window)
            _counter("firehose.ring_wraps").inc()
            self._harvested.update(self._drain())
        with _span("firehose.dispatch", groups=len(members), pairs=count,
                   padded=g):
            # unarmed guard: async launch in a try-frame — taxonomy and
            # transient retry apply (host-staged inputs are re-usable),
            # the deadline only ever arms the flush
            out = guarded_dispatch(
                ("firehose.batch", count, g), BJ.grouped_pairing_check,
                jnp.asarray(g1), jnp.asarray(g2),
                deadline_ms=0.0, clock=self._clock, sleep=self._sleep)
            ring = self._ring
            if ring is None:
                ring = jnp.zeros((self.ring_capacity,), bool)
            self._ring = _watchdog.dispatch(
                ("firehose.ring", self.ring_capacity, g),
                _ring_scatter_jit(), ring, out, np.int32(self._offset))
        # the chained ring value: any placement change between scatters
        # is a re-layout event (ONE key covers every step)
        _watchdog.layout_check(("firehose.ring.layout",
                                self.ring_capacity), self._ring)
        self._pending.append((keys, self._offset, len(members)))
        self._offset += g
        self.launches += 1
        self.occupancies.append(len(members))
        _counter("firehose.launches").inc()
        _counter("firehose.groups_launched").inc(len(members))
        _histogram("firehose.batch_occupancy").observe(len(members))

    # -- flush (the only blocking point) ---------------------------------

    def _drain(self) -> Dict[object, bool]:
        """Materialize the ring and map every pending batch's verdicts.
        The ONE device->host transfer; callers decide whether it runs
        under a deadline guard."""
        verdicts: Dict[object, bool] = {}
        if not self._pending:
            return verdicts
        ok = np.asarray(self._ring)
        for keys, start, n in self._pending:
            for k, key in enumerate(keys):
                verdicts[key] = bool(ok[start + k])
        self._pending = []
        self._offset = 0
        return verdicts

    def flush(self, deadline_ms: Optional[float] = None
              ) -> Dict[object, bool]:
        """Block on everything in flight and return {key: verdict}.

        With a wall-clock budget armed (`deadline_ms` or the pipeline
        default), the materialization runs through `guarded_dispatch`
        with retries=0: a late ring is SALVAGED (the verdicts still
        land — discarding correct work would only convert lateness into
        unavailability) and the miss is counted on /healthz."""
        from .. import telemetry
        from ..resilience import guarded_dispatch

        if deadline_ms is None:
            deadline_ms = self.deadline_ms
        verdicts = dict(self._harvested)
        self._harvested = {}
        with _span("firehose.flush", batches=len(self._pending),
                   deadline_ms=deadline_ms or 0):
            if self._pending:
                misses0 = telemetry.counter(
                    "resilience.deadline_misses", always=True).value
                verdicts.update(guarded_dispatch(
                    ("firehose.flush", self.ring_capacity), self._drain,
                    deadline_ms=deadline_ms or 0.0, retries=0,
                    clock=self._clock, sleep=self._sleep))
                missed = telemetry.counter(
                    "resilience.deadline_misses", always=True).value - misses0
                if missed:
                    _counter("firehose.deadline_miss").inc(missed)
        _counter("firehose.groups_verified").inc(len(verdicts))
        self.last_flush_at = time.monotonic()
        return verdicts


# ---------------------------------------------------------------------------
# Trace-tier kernel contracts (tools/analysis/trace/, `make contracts`)
# ---------------------------------------------------------------------------
# The steady-state firehose verification program at the COMMITTED batch
# shape — G = 128 groups x P = 3 pairs, the >= 128-group occupancy the
# bench/smoke acceptance asserts — plus the verdict-ring scatter. The
# grouped-Miller / batched-verdict REDC-lane pins are EXACTLY 128x the
# per-group budgets the ops.bls_jax contracts pin at G = 1 (396/672
# Miller, 967 verdict): the lane cost is linear in the batch axis, so
# any super-linear drift — a per-group recombination escaping the
# shared-squaring structure at the wide shape — breaks the pin. Zero
# device_put end to end, and the ring's in-place donation must survive
# lowering.

_FIREHOSE_G = 128     # committed steady-state batch occupancy
_FIREHOSE_P = 3       # spec aggregate-verify pair count


def _firehose_miller_build(mode):
    import jax.numpy as jnp
    from ..ops import bls_jax as BJ
    from ..ops import fq as F
    return dict(
        fn=BJ.miller_loop_grouped,
        args=(jnp.zeros((_FIREHOSE_G, _FIREHOSE_P, 2, F.L), jnp.int64),
              jnp.zeros((_FIREHOSE_G, _FIREHOSE_P, 2, 2, F.L), jnp.int64)),
        context=lambda: F.pinned_fq_redc_backend(mode))


def _firehose_verdict_build():
    import jax.numpy as jnp
    from ..ops import bls_jax as BJ
    from ..ops import fq as F
    return dict(
        fn=BJ._grouped_verdict,
        args=(jnp.zeros((_FIREHOSE_G, 2, 3, 2, F.L), jnp.int64),),
        context=lambda: F.pinned_fq_redc_backend("coeff"))


def _ring_scatter_build():
    import jax.numpy as jnp
    return dict(
        fn=_ring_scatter,
        args=(jnp.zeros((1024,), bool),
              jnp.zeros((_FIREHOSE_G,), bool), np.int32(0)),
        jit_kwargs={"donate_argnums": (0,)})


# ---------------------------------------------------------------------------
# Memory contract (tools/analysis/memory/, `make memory`)
# ---------------------------------------------------------------------------
# The steady-state firehose working set as ONE modeled program: the
# verdict ring (donated — it aliases its output and counts once, the
# in-place update the class dispatches through platform_donated_jit)
# plus TWO in-flight batches at the committed G = 128 x P = 3 shape —
# batch A resident through pairing -> verdict -> ring scatter while
# batch B's staged arrays and Miller accumulators overlap it, exactly
# the double-buffer overlap dispatch() sustains. The budget is the
# figure the firehose bench's sustained-load acceptance rests on: the
# ring never grows, the per-batch buffers turn over, and a second
# resident copy of a batch (a defensive clone of the staged arrays
# creeping into dispatch) blows the modeled peak past it.

def _firehose_steady_mem_build(g: int = _FIREHOSE_G):
    import jax as _jax
    import jax.numpy as jnp
    from ..ops import bls_jax as BJ
    from ..ops import fq as F
    S = _jax.ShapeDtypeStruct
    g1 = S((g, _FIREHOSE_P, 2, F.L), jnp.int64)
    g2 = S((g, _FIREHOSE_P, 2, 2, F.L), jnp.int64)

    def steady(ring, start, g1a, g2a, g1b, g2b):
        fa = BJ.miller_loop_grouped(g1a, g2a)     # batch A: pairing
        va = BJ._grouped_verdict(fa)              # batch A: verdict
        ring = _ring_scatter(ring, va, start)     # A lands in the ring
        fb = BJ.miller_loop_grouped(g1b, g2b)     # batch B overlaps
        return ring, fb

    return dict(fn=steady,
                args=(S((1024,), jnp.bool_), S((), jnp.int32),
                      g1, g2, g1, g2),
                donate_argnums=(0,),
                context=lambda: F.pinned_fq_redc_backend("coeff"))


# No standing `compiled` probe: the steady-state program embeds two
# unrolled Miller loops, which XLA:CPU compiles in ~4 minutes apiece
# even at tiny g (see the matching note on ops/bls_jax.MEM_CONTRACTS,
# whose g=4 probe agreed with the model out-of-band); the trace-based
# budget check below is the standing gate.
MEM_CONTRACTS = [
    dict(
        name="streaming.pipeline.firehose_steady_state",
        build=_firehose_steady_mem_build,
        # modeled steady-state peak ~7.6 MiB (ring + verdict fold of
        # batch A live across batch B's Miller accumulator): 16 MiB is
        # a real ceiling — a second resident batch copy trips it
        budget_bytes=16 << 20,
    ),
]


TRACE_CONTRACTS = [
    dict(
        name=f"streaming.pipeline.firehose_miller[{mode}]",
        build=(lambda m=mode: _firehose_miller_build(m)),
        budgets={"redc_lanes": lanes},
        exact=("redc_lanes",),
        forbid=("f64", "callback", "device_put"),
    )
    for mode, lanes in (("coeff", 396 * _FIREHOSE_G),
                        ("leaf", 672 * _FIREHOSE_G))
] + [
    dict(
        name="streaming.pipeline.firehose_verdict[coeff]",
        build=_firehose_verdict_build,
        budgets={"redc_lanes": 967 * _FIREHOSE_G},
        exact=("redc_lanes",),
        forbid=("f64", "callback", "device_put"),
    ),
    dict(
        name="streaming.pipeline.verdict_ring_scatter",
        build=_ring_scatter_build,
        budgets={"jaxpr_eqns": 4},
        donate_min=1,
        forbid=("f64", "callback", "device_put"),
    ),
]
