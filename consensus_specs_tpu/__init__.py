"""consensus_specs_tpu — a TPU-native executable beacon-chain specification.

A ground-up re-design of the capabilities of ethereum/consensus-specs (2019
snapshot): SSZ typing/serialization/Merkleization, the phase-0 state
transition, phase-1 custody game and shard chains, fork choice, presets, and a
dual-use test/vector-generation framework — with the numerically heavy kernels
(SHA-256 Merkleization, swap-or-not shuffling, BLS12-381 aggregate
verification, epoch reward loops) implemented as jit/vmap'd JAX array programs
for TPU.
"""

__version__ = "0.1.0"
