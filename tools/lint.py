#!/usr/bin/env python
"""Self-contained lint gate (no third-party linters in the image).

Checks, per Python file: parses (SyntaxError = fail), no tabs in
indentation, no trailing whitespace, lines <= 120 columns (the reference
lints at 120, Makefile:60-62), and module-level imports that are never
referenced (AST-based, conservative: skips __init__.py re-exports and
imports marked `# noqa`).
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_COLS = 120


def iter_py_files(targets):
    for target in targets:
        path = Path(target)
        if path.is_dir():
            yield from sorted(path.rglob("*.py"))
        elif path.suffix == ".py":
            yield path


def unused_imports(tree: ast.AST, source: str, is_init: bool):
    if is_init:
        return []
    lines = source.splitlines()
    imported = {}   # name -> (lineno, shown_as)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                imported[name] = (node.lineno, alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                name = alias.asname or alias.name
                imported[name] = (node.lineno, alias.name)
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            root = node
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name):
                used.add(root.id)
    out = []
    for name, (lineno, shown) in imported.items():
        if name in used:
            continue
        line = lines[lineno - 1] if lineno <= len(lines) else ""
        if "noqa" in line:
            continue
        # names can appear in docstring doctests or __all__ strings
        if f'"{name}"' in source or f"'{name}'" in source:
            continue
        out.append((lineno, f"unused import: {shown}"))
    return out


def lint_file(path: Path):
    problems = []
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as exc:
        return [(exc.lineno or 0, f"syntax error: {exc.msg}")]
    for i, line in enumerate(source.splitlines(), 1):
        stripped = line.rstrip("\n")
        indent = stripped[:len(stripped) - len(stripped.lstrip("\t \x0c"))]
        if "\t" in indent:
            problems.append((i, "tab in indentation"))
        if stripped != stripped.rstrip():
            problems.append((i, "trailing whitespace"))
        if len(stripped) > MAX_COLS:
            problems.append((i, f"line too long ({len(stripped)} > {MAX_COLS})"))
    problems.extend(unused_imports(tree, source, path.name == "__init__.py"))
    return problems


def main(argv):
    failed = False
    count = 0
    for path in iter_py_files(argv or ["."]):
        count += 1
        for lineno, message in lint_file(path):
            print(f"{path}:{lineno}: {message}")
            failed = True
    print(f"lint: {count} files checked", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
