"""One-shot TPU validation + profiling pass (run when the relay is up).

Drives, on the real chip, everything added since the last on-TPU check:
batched G1/G2 decompression, the fused decompress+aggregate paths, the
batched hash_to_g2 cofactor multiply — each against the bignum oracle —
then profiles the epoch-transition sub-stages with honest fences so the
next optimization targets the real bottleneck.

Usage: python tools/tpu_followup.py  (from the repo root)
"""
import sys
import time

import numpy as np


def sync(x):
    import jax
    leaf = jax.tree_util.tree_leaves(x)[0]
    return np.asarray(leaf.ravel()[0:1])


def main():
    import jax
    print("devices:", jax.devices(), flush=True)

    from consensus_specs_tpu.crypto import bls12_381 as gt
    from consensus_specs_tpu.ops import decompress as D
    from consensus_specs_tpu.ops.bls_jax import JaxBackend, hash_to_g2_batch

    # 1) batched G1 decompress: 256 pubkeys, oracle spot-check
    enc = [gt.privtopub(k) for k in range(1, 17)] * 16
    data = np.stack([np.frombuffer(e, np.uint8) for e in enc])
    t0 = time.time()
    x, y, valid, inf = D.g1_decompress_batch(data)
    print(f"g1 decompress 256 first: {time.time()-t0:.1f}s "
          f"valid={bool(valid.all())}", flush=True)
    t0 = time.time()
    D.g1_decompress_batch(data)
    print(f"g1 decompress 256 steady: {time.time()-t0:.2f}s", flush=True)
    from consensus_specs_tpu.ops import fq as F
    ox, oy = gt.decompress_g1(enc[3])
    assert (F.from_mont(np.asarray(x)[3]), F.from_mont(np.asarray(y)[3])) \
        == (ox, oy), "G1 decompress oracle mismatch on TPU"

    # 2) fused aggregate (decompress + addition tree) parity
    jx, py = JaxBackend(), gt.PythonBackend()
    t0 = time.time()
    agg = jx.aggregate_pubkeys(enc)
    print(f"fused aggregate 256 first: {time.time()-t0:.1f}s", flush=True)
    assert agg == py.aggregate_pubkeys(enc), "aggregate parity fail on TPU"
    t0 = time.time()
    jx.aggregate_pubkeys(enc)
    print(f"fused aggregate 256 steady: {time.time()-t0:.2f}s", flush=True)

    # 3) batched hash_to_g2 parity on chip
    reqs = [(bytes([m]) * 32, 1) for m in range(8)]
    t0 = time.time()
    got = hash_to_g2_batch(reqs)
    print(f"hash_to_g2 batch8 first: {time.time()-t0:.1f}s", flush=True)
    assert got == [gt.hash_to_g2(mh, d) for mh, d in reqs], \
        "hash_to_g2 batch parity fail on TPU"
    t0 = time.time()
    hash_to_g2_batch([(bytes([m]) * 32, 2) for m in range(8)])
    print(f"hash_to_g2 batch8 steady: {time.time()-t0:.2f}s", flush=True)

    # 4) unrolled == fori sha256 on chip
    import jax.numpy as jnp
    from consensus_specs_tpu.ops.sha256 import sha256_pairs
    rng = np.random.default_rng(5)
    words = jnp.asarray(rng.integers(0, 2 ** 32, (8192, 16), dtype=np.uint32))
    a = np.asarray(sha256_pairs(words, unroll=True))
    b = np.asarray(sha256_pairs(words, unroll=False))
    assert (a == b).all(), "unrolled != fori on TPU"
    print("sha256 unrolled == fori on chip", flush=True)

    # 5) epoch sub-stage profile (which term dominates the ~400 ms?)
    from consensus_specs_tpu.models import phase0
    from consensus_specs_tpu.models.phase0.epoch_soa import (
        EpochConfig, epoch_transition_device, synthetic_epoch_state)
    spec = phase0.get_spec("mainnet")
    cfg = EpochConfig.from_spec(spec)
    V = 1_000_000
    cols, scal, inp = synthetic_epoch_state(cfg, V, np.random.default_rng(42),
                                            slashed_p=0.001, incl_delay_max=32,
                                            random_slashed_balances=True)
    sync(epoch_transition_device(cfg, cols, scal, inp))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        sync(epoch_transition_device(cfg, cols, scal, inp))
        ts.append(time.perf_counter() - t0)
    print(f"epoch full: {min(ts)*1e3:.0f} ms", flush=True)

    import jax
    # isolate the activation-queue sort (suspected dominant term)
    elig = np.asarray(cols.activation_eligibility_epoch, dtype=np.uint64) \
        if hasattr(cols, "activation_eligibility_epoch") else None
    if elig is not None:
        key = jnp.asarray(elig)
        f_sort = jax.jit(lambda k: jnp.argsort(k, stable=True))
        sync(f_sort(key))
        t0 = time.perf_counter()
        sync(f_sort(key))
        print(f"stable argsort alone: {(time.perf_counter()-t0)*1e3:.0f} ms",
              flush=True)

    print("ALL TPU FOLLOW-UP CHECKS PASSED", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
