"""One-shot TPU validation + profiling pass (run when the relay is up).

Drives, on the real chip, everything added since the last on-TPU check:
batched G1/G2 decompression, the fused decompress+aggregate paths, the
batched hash_to_g2 cofactor multiply — each against the bignum oracle —
then profiles the epoch-transition sub-stages with honest fences so the
next optimization targets the real bottleneck.

Usage: python tools/tpu_followup.py  (from the repo root)
"""
import os
import sys
import time

import numpy as np

# `python tools/tpu_followup.py` puts tools/ (not the repo root) on
# sys.path; the package and bench live at the root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sync(x):
    import jax
    leaf = jax.tree_util.tree_leaves(x)[0]
    return np.asarray(leaf.ravel()[0:1])


def _modeled_traffic_gb(label, fn, *args):
    """(lo, hi) GB of HBM traffic for `fn(*args)` from the memory
    tier's cost model (tools/analysis/memory/liveness.py) over the real
    jaxpr — the roofline's byte denominators, deduped onto the same
    accounting `make memory` budgets — cross-checked against the bytes
    the compiled HLO actually allocates. A >2x peak divergence between
    model and compiled aborts the run: a roofline over an untrusted
    byte model is noise, not a denominator."""
    import jax
    from tools.analysis.memory import liveness as ML
    closed = jax.make_jaxpr(fn)(*args)
    lo, hi = ML.traffic_bounds(closed)
    model = ML.analyze(closed)
    stats = jax.jit(fn).lower(*args).compile().memory_analysis()
    if stats is not None:
        compiled_peak = (int(stats.argument_size_in_bytes)
                         + int(stats.output_size_in_bytes)
                         - int(getattr(stats, "alias_size_in_bytes", 0))
                         + int(stats.temp_size_in_bytes))
        ratio = (max(model.peak_bytes, compiled_peak)
                 / max(1, min(model.peak_bytes, compiled_peak)))
        print(f"[roofline] {label}: modeled peak "
              f"{model.peak_bytes/1e6:.1f} MB vs compiled HLO "
              f"{compiled_peak/1e6:.1f} MB (x{ratio:.2f})", flush=True)
        assert ratio <= 2.0, (
            f"{label}: liveness model and compiled memory_analysis "
            f"diverge x{ratio:.2f} (> 2x) — fix the model before "
            f"trusting this roofline")
    return lo / 1e9, hi / 1e9


class _Stages:
    """Linear stage marker: `stages.next("followup.x")` closes the
    previous stage's telemetry span (printing its wall time + the
    watchdog counters so far) and opens the next — the per-stage
    snapshot embedding without restructuring the linear script."""

    def __init__(self, telemetry):
        self._t = telemetry
        self._cur = None

    def next(self, name=None):
        if self._cur is not None:
            self._cur.__exit__(None, None, None)
            agg = self._t.snapshot()["spans"].get(self._cur.name)
            if agg is not None:
                print(f"[telemetry] {self._cur.name}: "
                      f"{agg['last_ms']:.0f} ms | watchdog retrace="
                      f"{self._t.counter('watchdog.retrace_events').value} "
                      f"relayout="
                      f"{self._t.counter('watchdog.relayout_events').value}",
                      flush=True)
            self._cur = None
        if name is not None:
            self._cur = self._t.span(name)
            self._cur.__enter__()

    def finish(self):
        import json
        self.next(None)
        print("[telemetry] snapshot: "
              + json.dumps(self._t.snapshot()), flush=True)


def main():
    import os

    import jax
    # CPU smoke mode for the harness itself (the config API is the only
    # reliable pin once the site hook pre-imported jax — see bench.py)
    if os.environ.get("CSTPU_FOLLOWUP_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    # share bench.py's persistent compile cache: the pairing/Merkle programs
    # take minutes to compile fresh on the chip; a timed-out attempt's
    # compiles still carry over to the next retry through the disk cache
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "..", ".cache", "xla")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    print("devices:", jax.devices(), flush=True)

    from consensus_specs_tpu import telemetry
    from consensus_specs_tpu.crypto import bls12_381 as gt
    from consensus_specs_tpu.ops import decompress as D
    from consensus_specs_tpu.ops.bls_jax import JaxBackend, hash_to_g2_batch

    telemetry.watchdog.install_compile_listener()
    stages = _Stages(telemetry)
    stages.next("followup.decompress_aggregate")

    # 1) batched G1 decompress: 256 pubkeys, oracle spot-check
    enc = [gt.privtopub(k) for k in range(1, 17)] * 16
    data = np.stack([np.frombuffer(e, np.uint8) for e in enc])
    t0 = time.time()
    x, y, valid, inf = D.g1_decompress_batch(data)
    print(f"g1 decompress 256 first: {time.time()-t0:.1f}s "
          f"valid={bool(valid.all())}", flush=True)
    t0 = time.time()
    D.g1_decompress_batch(data)
    print(f"g1 decompress 256 steady: {time.time()-t0:.2f}s", flush=True)
    from consensus_specs_tpu.ops import fq as F
    ox, oy = gt.decompress_g1(enc[3])
    assert (F.from_mont(np.asarray(x)[3]), F.from_mont(np.asarray(y)[3])) \
        == (ox, oy), "G1 decompress oracle mismatch on TPU"

    # 2) fused aggregate (decompress + addition tree) parity
    jx, py = JaxBackend(), gt.PythonBackend()
    t0 = time.time()
    agg = jx.aggregate_pubkeys(enc)
    print(f"fused aggregate 256 first: {time.time()-t0:.1f}s", flush=True)
    assert agg == py.aggregate_pubkeys(enc), "aggregate parity fail on TPU"
    t0 = time.time()
    jx.aggregate_pubkeys(enc)
    print(f"fused aggregate 256 steady: {time.time()-t0:.2f}s", flush=True)

    # 3) batched hash_to_g2 parity on chip
    reqs = [(bytes([m]) * 32, 1) for m in range(8)]
    t0 = time.time()
    got = hash_to_g2_batch(reqs)
    print(f"hash_to_g2 batch8 first: {time.time()-t0:.1f}s", flush=True)
    assert got == [gt.hash_to_g2(mh, d) for mh, d in reqs], \
        "hash_to_g2 batch parity fail on TPU"
    t0 = time.time()
    hash_to_g2_batch([(bytes([m]) * 32, 2) for m in range(8)])
    print(f"hash_to_g2 batch8 steady: {time.time()-t0:.2f}s", flush=True)

    stages.next("followup.sha_pallas_ab")
    # Sections 4/4b need the real Mosaic pipeline: the unrolled SHA form
    # trips XLA:CPU's algebraic-simplifier rewrite loop (ops/sha256.py) and
    # the compiled Pallas lowering exists only for TPU. Gating them on the
    # device platform lets the REST of this pass smoke-test on CPU, so a
    # Python-level bug here can't waste a rare relay window.
    on_tpu = jax.devices()[0].platform == "tpu"
    import jax.numpy as jnp
    from consensus_specs_tpu.ops.sha256 import sha256_pairs
    rng = np.random.default_rng(5)
    words = jnp.asarray(rng.integers(0, 2 ** 32, (8192, 16), dtype=np.uint32))
    if on_tpu:
        # 4) unrolled == fori sha256 on chip
        a = np.asarray(sha256_pairs(words, unroll=True))
        b = np.asarray(sha256_pairs(words, unroll=False))
        assert (a == b).all(), "unrolled != fori on TPU"
        print("sha256 unrolled == fori on chip", flush=True)

        # 4b) Pallas (Mosaic) pair-hash vs XLA kernel on chip + A/B timing
        from consensus_specs_tpu.ops.sha256_pallas import sha256_pairs_pallas
        t0 = time.time()
        p = np.asarray(sha256_pairs_pallas(words, interpret=False))
        print(f"pallas pair-hash first: {time.time()-t0:.1f}s", flush=True)
        assert (p == a).all(), "pallas != XLA pair-hash on TPU"
        for label, fn in (("pallas", lambda: sha256_pairs_pallas(words, interpret=False)),
                          ("xla", lambda: sha256_pairs(words, unroll=True))):
            t0 = time.time()
            for _ in range(3):
                np.asarray(fn())
            print(f"sha256 pair-hash {label} steady: {(time.time()-t0)/3*1e3:.1f} ms",
                  flush=True)
    else:
        print("[skip] unrolled-SHA + Pallas A/B (TPU-only lowering; "
              "CPU smoke mode)", flush=True)

    stages.next("followup.roofline")
    # 4c) roofline accounting (VERDICT r4 #4): per kernel, the modeled
    #     bytes/ops, the measured wall-clock, and the implied fraction of
    #     chip peak — so "is this actually fast?" has a denominator.
    #     Peaks assumed (TPU v5e, documented upper bounds): HBM 819 GB/s;
    #     VPU int32 ~4 Tops/s (4 ALUs x 8x128 lanes x ~0.94 GHz x 4-wide).
    #     The fence floor (one tiny-transfer round trip through the relay)
    #     is measured and subtracted: through the tunnel it can dominate
    #     ms-scale kernels.
    import jax.numpy as jnp
    HBM_PEAK = 819e9
    VPU_PEAK = 4e12

    tiny = jnp.zeros(8, jnp.uint32)
    jax.block_until_ready(tiny)
    rtts = []
    for _ in range(5):
        t0 = time.perf_counter()
        np.asarray(tiny[0:1])
        rtts.append(time.perf_counter() - t0)
    rtt = min(rtts)
    print(f"[roofline] fence floor (tiny-transfer round trip): {rtt*1e3:.1f} ms",
          flush=True)

    from consensus_specs_tpu.ops.shuffle import shuffle_permutation_on_device
    Vr = 1_000_000
    R = 90
    perm = shuffle_permutation_on_device(bytes(range(32)), Vr, R)
    np.asarray(perm.ravel()[0:1])
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        p2 = shuffle_permutation_on_device(bytes(range(32)), Vr, R)
        np.asarray(p2.ravel()[0:1])
        ts.append(time.perf_counter() - t0)
    t_shuf = max(min(ts) - rtt, 1e-9)
    # traffic bounds from the memory tier's cost model over the REAL
    # round kernel's jaxpr (tools/analysis/memory/liveness.py — the
    # same per-eqn byte accounting the MEM_CONTRACTS budgets use),
    # replacing the hand-maintained B/elem/round table this block used
    # to carry: `hi` streams every eqn's operands/results (no fusion),
    # `lo` is the perfectly-fused floor. The model is cross-checked
    # against what the compiled HLO actually allocates and FAILS on
    # >2x divergence instead of silently trusting itself.
    from consensus_specs_tpu.ops.sha256 import bytes_to_words as _b2w
    from consensus_specs_tpu.ops.shuffle import (_shuffle_rounds_stacked,
                                                 host_pivots)
    _sd = bytes(range(32))
    _sw = jnp.asarray(_b2w(np.frombuffer(_sd, dtype=np.uint8)))
    _pv = jnp.asarray(host_pivots(_sd, Vr, R))
    lo_gb, hi_gb = _modeled_traffic_gb(
        "shuffle rounds", lambda s, p: _shuffle_rounds_stacked(s, p, Vr, R),
        _sw, _pv)
    hbm_gbs = HBM_PEAK / 1e9   # peak in GB/s (traffic model is in GB)
    print(f"[roofline] shuffle 1M x {R} rounds: {t_shuf*1e3:.1f} ms "
          f"(fence-corrected) | traffic model {lo_gb:.1f}-{hi_gb:.1f} GB -> "
          f"{lo_gb/t_shuf:.0f}-{hi_gb/t_shuf:.0f} GB/s = "
          f"{100*lo_gb/t_shuf/hbm_gbs:.1f}-{100*hi_gb/t_shuf/hbm_gbs:.1f}% "
          f"of HBM peak; bandwidth-bound floor {hi_gb/hbm_gbs*1e3:.1f} ms",
          flush=True)

    # A/B: the stacked-movement variant (one [2, n] reverse+roll per round
    # instead of two; bit-equality pinned in tests/test_shuffle_kernel.py)
    sw, pv = _sw, _pv
    ps = _shuffle_rounds_stacked(sw, pv, Vr, R)
    assert np.array_equal(np.asarray(ps), np.asarray(perm)), \
        "stacked shuffle != reference kernel on TPU"
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(_shuffle_rounds_stacked(sw, pv, Vr, R).ravel()[0:1])
        ts.append(time.perf_counter() - t0)
    t_stk = max(min(ts) - rtt, 1e-9)
    print(f"[roofline] shuffle stacked variant: {t_stk*1e3:.1f} ms "
          f"({t_shuf/t_stk:.2f}x vs reference kernel) — adopt via "
          f"install_device_shuffler if it wins", flush=True)

    from consensus_specs_tpu.utils.ssz import bulk as _bulk
    rng_r = np.random.default_rng(3)
    cols_r = [
        jnp.asarray(rng_r.integers(0, 256, (Vr, 48), dtype=np.uint8)),
        jnp.asarray(rng_r.integers(0, 256, (Vr, 32), dtype=np.uint8)),
        jnp.asarray(np.zeros(Vr, np.uint64)), jnp.asarray(np.zeros(Vr, np.uint64)),
        jnp.asarray(np.zeros(Vr, np.uint64)), jnp.asarray(np.zeros(Vr, np.uint64)),
        jnp.asarray(np.zeros(Vr, bool)),
        jnp.asarray(np.full(Vr, 32_000_000_000, np.uint64)),
        jnp.asarray(rng_r.integers(31e9, 33e9, Vr).astype(np.uint64)),
    ]
    jax.block_until_ready(cols_r)
    _bulk.registry_and_balances_roots_device(*cols_r)
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        _bulk.registry_and_balances_roots_device(*cols_r)  # host-materializing
        ts.append(time.perf_counter() - t0)
    t_root = max(min(ts) - rtt, 1e-9)
    # compressions: 8 subtree hashes/validator + ~V top-tree + V/4 balances
    n_comp = 8 * Vr + Vr + Vr // 4
    # one SHA-256 compression ~= 64 rounds x ~25 int ops + schedule ~48 x 15
    ops = n_comp * (64 * 25 + 48 * 15)
    print(f"[roofline] registry+balances root 1M: {t_root*1e3:.1f} ms "
          f"(fence-corrected) | ~{n_comp/1e6:.1f}M compressions, "
          f"~{ops/1e9:.0f} Gop -> {ops/t_root/1e12:.2f} Tops/s = "
          f"{100*ops/t_root/VPU_PEAK:.0f}% of VPU int peak; "
          f"compute-bound floor {ops/VPU_PEAK*1e3:.0f} ms", flush=True)

    # grouped pairing throughput model (if the cache is warm this is fast)
    from consensus_specs_tpu.ops.bls_jax import (grouped_pairing_check,
                                                 stage_example_groups)
    g1s, g2s = stage_example_groups(8)
    dg1s, dg2s = jnp.asarray(g1s), jnp.asarray(g2s)
    ok8 = np.asarray(grouped_pairing_check(dg1s, dg2s))
    assert bool(ok8.all())
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(grouped_pairing_check(dg1s, dg2s))
        ts.append(time.perf_counter() - t0)
    t_pair = max(min(ts) - rtt, 1e-9)
    print(f"[roofline] grouped pairing G=8 (24 Miller loops): "
          f"{t_pair*1e3:.0f} ms fence-corrected = {8/t_pair:.1f} aggverify/s "
          f"(per-group cost amortizes further at G=128)", flush=True)

    stages.next("followup.epoch_profile")
    # 5) epoch sub-stage profile (which term dominates the ~400 ms?)
    from consensus_specs_tpu.models import phase0
    from consensus_specs_tpu.models.phase0.epoch_soa import (
        EpochConfig, epoch_transition_device, synthetic_epoch_state)
    spec = phase0.get_spec("mainnet")
    cfg = EpochConfig.from_spec(spec)
    V = 1_000_000
    cols, scal, inp = synthetic_epoch_state(cfg, V, np.random.default_rng(42),
                                            slashed_p=0.001, incl_delay_max=32,
                                            random_slashed_balances=True)
    # epoch_transition_device donates the columns on TPU: hold the host copy
    # needed below, then chain each call's output columns into the next
    elig_host = np.asarray(cols.activation_eligibility_epoch, dtype=np.uint64)
    out = epoch_transition_device(cfg, cols, scal, inp)
    sync(out)
    cols = out[0]
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = epoch_transition_device(cfg, cols, scal, inp)
        sync(out)
        cols = out[0]
        ts.append(time.perf_counter() - t0)
    print(f"epoch full: {min(ts)*1e3:.0f} ms", flush=True)

    import jax
    # isolate the activation-queue sort (suspected dominant term)
    elig = elig_host
    if elig is not None:
        key = jnp.asarray(elig)
        f_sort = jax.jit(lambda k: jnp.argsort(k, stable=True))
        sync(f_sort(key))
        t0 = time.perf_counter()
        sync(f_sort(key))
        print(f"stable argsort alone: {(time.perf_counter()-t0)*1e3:.0f} ms",
              flush=True)

    stages.next("followup.config3_block")
    # 6) the config-3 batched block pipeline on chip: a minimal-preset block
    #    of real attestations through process_attestations_batched ->
    #    verify_indexed_batch (grouped G1 agg, batched G2 decompress,
    #    hash_to_G2, grouped pairing), plus a tampered-signature rejection
    import bench
    from copy import deepcopy
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.utils.ssz.impl import hash_tree_root
    spec_min = phase0.get_spec("minimal")
    old_active = bls.bls_active
    bls.bls_active = True
    bls.set_backend("python")
    try:
        state, block = bench.build_config3_state_and_block(
            spec_min, 8 * spec_min.SLOTS_PER_EPOCH, 4, n_keys=8)
        bls.set_backend("jax")
        good = deepcopy(state)
        t0 = time.time()
        spec_min.state_transition(good, block)
        print(f"config-3 batched block (4 atts) first: {time.time()-t0:.1f}s",
              flush=True)
        good2 = deepcopy(state)
        t0 = time.time()
        spec_min.state_transition(good2, block)
        print(f"config-3 batched block steady: {time.time()-t0:.2f}s", flush=True)
        assert hash_tree_root(good) == hash_tree_root(good2)
        bad = deepcopy(block)
        sig = bytearray(bad.body.attestations[1].signature)
        sig[-1] ^= 1
        bad.body.attestations[1].signature = bytes(sig)
        try:
            spec_min.state_transition(deepcopy(state), bad)
            raise SystemExit("tampered attestation accepted on TPU!")
        except AssertionError:
            pass
        print("config-3 batched block verified + tampered sig rejected on chip",
              flush=True)
    finally:
        bls.bls_active = old_active
        bls.set_backend("python")

    stages.finish()
    print("ALL TPU FOLLOW-UP CHECKS PASSED", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
