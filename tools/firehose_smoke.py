"""`make firehose`: drive the streaming verifier under sustained
synthetic gossip load on the 8-device virtual mesh and dump the
acceptance artifact:

    out/firehose.json     load shape, throughput, occupancy, verdict
                          diff, watchdog + deadline counters

Each wave mixes VALID aggregates with a deterministic-FALSE one
(group 0's G1 points against group 1's G2 points), so the verdict
diff against the synchronous `_grouped_pairing_dispatch` exercises
both polarities every round. Exits non-zero on ANY of: a streamed
verdict differing from the synchronous path, a retrace or re-layout
watchdog event, or a deadline miss at the nominal load point.

Usage: python tools/firehose_smoke.py  (from the repo root)
Env:   CSTPU_FIREHOSE_GROUPS (target batch occupancy, default 8 — the
       smoke shape; bench.py runs the committed 128),
       CSTPU_FIREHOSE_ROUNDS (waves, default 4),
       CSTPU_FIREHOSE_DEADLINE_MS (flush budget, default 600000).
"""
import json
import os
import sys
import time

# `python tools/firehose_smoke.py` puts tools/ (not the repo root) on
# sys.path; the package lives at the root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    # CPU pin + virtual mesh BEFORE backend init (the conftest recipe:
    # the ambient environment may point jax at a TPU relay)
    if os.environ.get("CSTPU_TEST_TPU") != "1":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if os.environ.get("CSTPU_TEST_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8")
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "..", ".cache", "xla")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from consensus_specs_tpu import streaming, telemetry
    from consensus_specs_tpu.ops import bls_jax as BJ

    telemetry.set_enabled(True)
    telemetry.watchdog.install_compile_listener()
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "out")
    os.makedirs(out_dir, exist_ok=True)

    target = int(os.environ.get("CSTPU_FIREHOSE_GROUPS", 8))
    rounds = int(os.environ.get("CSTPU_FIREHOSE_ROUNDS", 4))
    deadline_ms = float(os.environ.get("CSTPU_FIREHOSE_DEADLINE_MS",
                                       600_000.0))
    print(f"devices: {len(jax.devices())} ({jax.devices()[0].platform}); "
          f"firehose target {target} groups x {rounds} waves, "
          f"deadline {deadline_ms:.0f} ms", flush=True)

    g1, g2 = BJ.stage_example_groups(min(8, max(2, target)))
    n_distinct, P = g1.shape[0], g1.shape[1]

    def pairs_for(k):
        if k % target == target - 1:
            # the wave's deterministic-FALSE group: mismatched points
            return [(g1[0, p], g2[1, p]) for p in range(P)]
        i = k % n_distinct
        return [(g1[i, p], g2[i, p]) for p in range(P)]

    v = streaming.StreamingVerifier(target_groups=target,
                                    deadline_ms=deadline_ms)
    t0 = time.perf_counter()
    for k in range(target):                 # one full wave: compiles the
        v.submit_staged(("warm", k), pairs_for(k))   # steady batch shape
    v.pump()
    v.flush()
    print(f"warm-up flush: {time.perf_counter() - t0:.2f}s", flush=True)

    retrace0 = telemetry.counter("watchdog.retrace_events").value
    relayout0 = telemetry.counter("watchdog.relayout_events").value
    miss0 = telemetry.counter("firehose.deadline_miss", always=True).value
    keys = []
    t0 = time.perf_counter()
    for w in range(rounds):
        for k in range(target):
            key = (w, k)
            keys.append(key)
            v.submit_staged(key, pairs_for(k))
        v.pump()
    streamed = {}
    streamed.update(v.flush())
    wall = time.perf_counter() - t0

    sync = BJ._grouped_pairing_dispatch(
        [(key, pairs_for(key[1])) for key in keys])
    mismatches = [key for key in keys if streamed[key] != sync[key]]
    retrace = telemetry.counter("watchdog.retrace_events").value - retrace0
    relayout = (telemetry.counter("watchdog.relayout_events").value
                - relayout0)
    misses = (telemetry.counter("firehose.deadline_miss",
                                always=True).value - miss0)
    n_false = sum(1 for key in keys if not streamed[key])

    row = {
        "target_groups": target,
        "rounds": rounds,
        "groups": len(keys),
        "false_verdicts": n_false,
        "wall_s": round(wall, 3),
        "aggverify_per_s": round(len(keys) / wall, 2),
        "pairings_per_s": round(len(keys) * P / wall, 2),
        "verdict_mismatches": len(mismatches),
        "deadline_misses": int(misses),
        "watchdog": {"retrace_events": int(retrace),
                     "relayout_events": int(relayout)},
        "health": streaming.firehose_health(),
    }
    streaming.activate(None)
    path = os.path.join(out_dir, "firehose.json")
    with open(path, "w") as fh:
        json.dump(row, fh, indent=2)
    print(f"artifact: out/firehose.json — {row['aggverify_per_s']} "
          f"aggverify/s ({row['pairings_per_s']} pairings/s), "
          f"{n_false}/{len(keys)} false verdicts (expected {rounds}), "
          f"{misses} deadline misses, watchdogs {retrace} retrace / "
          f"{relayout} re-layout", flush=True)
    if mismatches:
        print(f"FAIL: {len(mismatches)} streamed verdict(s) differ from "
              f"the synchronous path: {mismatches[:5]}", flush=True)
        return 1
    if n_false != rounds:
        print(f"FAIL: expected exactly {rounds} false verdicts (one per "
              f"wave), saw {n_false}", flush=True)
        return 1
    if retrace or relayout:
        print("FAIL: the steady-state firehose tripped a watchdog",
              flush=True)
        return 1
    if misses:
        print("FAIL: deadline miss at the nominal load point", flush=True)
        return 1
    print("FIREHOSE SMOKE OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
