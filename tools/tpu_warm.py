"""Incremental TPU compile-cache warming for the grouped BLS pairing.

The axon relay wedges for hours and has died mid-compile in every round so
far; the grouped pairing (the framework's defining kernel) has therefore
never executed on real silicon. This tool makes every relay window bank
durable progress:

  * smallest shape FIRST: G=1 proves Mosaic compile-feasibility AND
    on-chip correctness of the pairing in the first minutes of a window;
  * then the ladder climbs to the bench shape (G=128), each rung landing
    in the persistent compile cache (.cache/xla) independently — a window
    that dies between rungs still leaves every finished compile on disk
    for the next attempt (and for bench.py, which shares the cache);
  * a heartbeat thread prints elapsed time every 60 s so a dead window is
    diagnosable from the log (silent 35-minute hangs killed round 4's
    only window).

Each rung verifies the staged signatures actually pass on chip (a [G]
all-true verdict), so the first successful rung is the first hardware
evidence for specs/bls_signature.md:139-146 semantics.

Usage: python tools/tpu_warm.py [G ...]   (default ladder: 1 8 128)
"""
import os
import sys
import threading
import time

import numpy as np

# `python tools/tpu_warm.py` puts tools/ (not the repo root) on sys.path;
# the package and bench live at the root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_T0 = time.time()


def _say(msg):
    print(f"[warm +{time.time() - _T0:.0f}s] {msg}", flush=True)


def _heartbeat():
    while True:
        time.sleep(60)
        _say("heartbeat (still alive; compile in progress?)")


def main(ladder):
    threading.Thread(target=_heartbeat, daemon=True).start()

    import jax
    # CSTPU_WARM_CPU=1 pins the host backend for harness smoke tests; the
    # config API is the only pin that works once the site hook pre-imported
    # jax (env-var JAX_PLATFORMS is read at import time — same as bench.py).
    if os.environ.get("CSTPU_WARM_CPU") == "1":
        jax.config.update("jax_platforms", "cpu")
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "..", ".cache", "xla")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    _say(f"devices: {jax.devices()}")

    import jax.numpy as jnp
    from consensus_specs_tpu.ops.bls_jax import (
        grouped_pairing_check, stage_example_groups)

    # Stage the largest rung once on the host (pure-bignum signing is slow)
    # and slice the smaller rungs out of it: all rungs share group values,
    # so a verdict mismatch between rungs would be a real device bug.
    g_max = max(ladder)
    _say(f"staging {g_max} signature groups on host")
    g1_all, g2_all = stage_example_groups(g_max)
    _say("staging done")

    for G in ladder:
        dg1 = jnp.asarray(g1_all[:G])
        dg2 = jnp.asarray(g2_all[:G])
        jax.block_until_ready((dg1, dg2))
        _say(f"G={G}: compiling + running grouped pairing "
             f"({G} shared-squaring 3-pair products + batched final exp)")
        t0 = time.time()
        ok = np.asarray(grouped_pairing_check(dg1, dg2))
        t_first = time.time() - t0
        if not bool(ok.all()):
            _say(f"G={G}: VERDICT FAILED on chip: {ok}")
            return 1
        t0 = time.time()
        np.asarray(grouped_pairing_check(dg1, dg2))
        t_steady = time.time() - t0
        _say(f"G={G}: OK on chip — first {t_first:.1f}s (incl. compile), "
             f"steady {t_steady * 1e3:.0f} ms "
             f"({G / t_steady:.1f} aggverify/s)")

    _say("ALL RUNGS PASSED — pairing cache warm for bench.py")
    return 0


if __name__ == "__main__":
    ladder = [int(a) for a in sys.argv[1:]] or [1, 8, 128]
    sys.exit(main(ladder))
