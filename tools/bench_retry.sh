#!/bin/bash
# Detached TPU-bench retry loop (VERDICT r3 directive #1).
#
# The axon TPU relay wedges for hours at a time (import jax hangs in
# uninterruptible native code). This loop probes the backend in a
# subprocess with a timeout, and whenever the relay is up it runs the
# full benchmark (bench.py) plus the on-chip validation pass
# (tools/tpu_followup.py — including the unrolled-SHA-256 check that
# XLA:CPU cannot run), writes raw timestamped logs under bench_logs/,
# and commits them. It exits once both passes succeed; until then it
# keeps retrying forever, surviving the interactive session via setsid.
#
# Launch:  setsid nohup bash tools/bench_retry.sh >> bench_logs/retry_loop.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
mkdir -p bench_logs

commit_logs() {
    local msg="$1"
    for _ in 1 2 3 4 5; do
        if git add bench_logs && git commit -q -m "$msg" -- bench_logs; then
            return 0
        fi
        sleep 7   # index.lock contention with the interactive session
    done
    echo "WARN: could not commit bench_logs ($msg); left in working tree"
    return 1
}

while true; do
    ts=$(date -u +%Y%m%dT%H%M%SZ)
    if timeout 180 python -c "import jax; print(jax.devices())" \
            > bench_logs/probe_last.log 2>&1; then
        echo "$ts probe OK: $(tail -1 bench_logs/probe_last.log)" \
            >> bench_logs/probe_history.log
        # Phase 1 — incremental pairing compile warming, smallest shape
        # first (G=1 proves Mosaic feasibility + on-chip correctness in
        # minutes; each rung banks into .cache/xla). Logs commit BEFORE the
        # long bench so a relay death mid-bench cannot lose this evidence.
        wlog="bench_logs/warm_${ts}.log"
        PYTHONUNBUFFERED=1 timeout 4500 python tools/tpu_warm.py \
            > "$wlog" 2>&1
        wrc=$?
        echo "warm rc=$wrc" >> "$wlog"
        commit_logs "bench_logs: TPU warm pass $ts (rc=$wrc)"
        blog="bench_logs/bench_${ts}.log"
        bjson="bench_logs/bench_${ts}.json"
        # 5400s: with the warm pass banking the pairing compiles, a bench
        # attempt needs epoch + root (cached from earlier windows) + the
        # block pipeline; the persistent cache still carries partial
        # progress into the next attempt if this one times out
        PYTHONUNBUFFERED=1 timeout 5400 python bench.py > "$bjson" 2> "$blog"
        rc=$?
        echo "bench rc=$rc" >> "$blog"
        commit_logs "bench_logs: TPU bench $ts (rc=$rc)"
        flog="bench_logs/followup_${ts}.log"
        PYTHONUNBUFFERED=1 timeout 3600 python tools/tpu_followup.py \
            > "$flog" 2>&1
        frc=$?
        echo "followup rc=$frc" >> "$flog"
        commit_logs "bench_logs: TPU followup $ts (rc=$frc)"
        # an incomplete capture (relay died mid-run; bench.py still exits 0
        # and flags the JSON's unit string) must not stop the loop
        if [ "$rc" -eq 0 ] && [ "$frc" -eq 0 ] \
                && ! grep -q 'lost mid-run' "$bjson"; then
            echo "$ts" > bench_logs/SUCCESS
            commit_logs "bench_logs: verified TPU bench + followup pass $ts"
            exit 0
        fi
    else
        echo "$ts probe FAILED (wedged relay?)" >> bench_logs/probe_history.log
    fi
    # Persist the probe history hourly so the wedge evidence survives
    # even if this loop is killed between successes.
    now=$(date +%s)
    if [ "$((now - ${last_hb:-0}))" -ge 3600 ]; then
        last_hb=$now
        commit_logs "bench_logs: probe heartbeat

No-Verification-Needed: operational log churn only" || true
    fi
    sleep 120
done
