"""Multi-pass JAX/TPU trace-safety & spec-conformance static analyzer.

Stdlib-only (like tools/lint.py and tools/cov.py): every pass is pure
`ast` walking — no third-party linters, no imports of the analyzed code.
The bug classes it gates are the ones that break the pyspec->TPU lift
(PAPER.md §1) yet pass both the test suite (which runs with x64 enabled
and small states) and tools/lint.py (syntax/style only):

  CSA1xx  trace-safety    Python control flow / host casts on traced values
  CSA2xx  dtype-width     uint64 Gwei/slot math through 32-bit defaults
  CSA3xx  purity          host side effects baked into traced programs
  CSA4xx  state-aliasing  `state` parameters a body never consults
  CSA5xx  jit-cache       retrace storms and unhashable static arguments
  CSA6xx  sharding        collective/PartitionSpec axes vs declared meshes
  CSA7xx  pallas          BlockSpec/grid/Ref contracts of pallas_call
  CSA8xx  spec-drift      constants + signatures vs the reference pyspec

A second, trace tier (tools/analysis/trace/) operates on the REAL
jaxprs/StableHLO of the hot kernels via declarative TRACE_CONTRACTS
exported next to the kernels:

  CSA11xx jaxpr op-budget ratchet (REDC lanes, dependent add chains)
  CSA12xx lowered-program hygiene (f64, callbacks, transfers, donation)
  CSA13xx collective/layout inventory drift (chained shardings)

A third, value-range tier (tools/analysis/ranges/) walks the same
jaxprs with an interval abstract interpreter, proving the declared
limb/column magnitude budgets and wrap semantics of the kernels'
RANGE_CONTRACTS:

  CSA1401 proved-overflow violation (wrap / output bound / invariant)
  CSA1402 unprovable-op notice (value widened to the dtype range)
  CSA1403 missing loop invariant
  CSA1404 range-snapshot drift vs ranges_baseline.json

A fourth, buffer-lifetime tier (tools/analysis/lifetime/) is an
interprocedural abstract interpreter of device-buffer OWNERSHIP over
the call-graph IR, cross-checked against the real lowering facts the
trace tier extracts (tf.aliasing_output donation survival):

  CSA1501 use-after-donate (read/dispatch of a donated value)
  CSA1502 donated-value escape (attribute store / return of a stale
          handle)
  CSA1503 double-in-flight donation (the firehose overlap shape)
  CSA1504 missing CPU-undonated twin (the PR 3 caveat codified;
          utils/donation.platform_donated_jit is the blessed pattern)
  CSA1505 redundant defensive copy before a donation-free program

A fifth, memory tier (tools/analysis/memory/) is an abstract
interpreter of peak BUFFER LIVENESS over the real jaxprs at ceiling
shapes (10M-validator epoch, the 2^20-leaf forest, the G=128 grouped
pairing), cross-checked against compiled.memory_analysis() and the
8-device per-shard bound, with a bytes ratchet and a Pallas VMEM
budget:

  CSA1601 declared-budget violation (peak/shard bound/compiled check)
  CSA1602 memory-snapshot drift vs memory_baseline.json (bytes ratchet)
  CSA1603 superlinear memory scaling vs the declared order
  CSA1604 Pallas VMEM overflow (BlockSpec x dtype x buffering)
  CSA1605 host round-trip widening live buffer ranges (notice)

The jax-touching tiers register only their rule catalogs at import
(stdlib, for --list-rules on the no-jax lint lane); the tracing and
interpretation machinery loads lazily behind --trace / --ranges /
--lifetime / --memory.

The per-module passes run over each file's jit context; trace context
propagates across module boundaries through the call-graph IR
(callgraph.py), and program-level passes (CSA6xx, CSA8xx) run once over
the whole-program view.

Entry points:
  python -m tools.analysis <targets> [--json out.json] [--baseline b.json]
                                     [--reference-root DIR]
  make analyze

See tools/analysis/README.md for the rule catalog and suppression syntax
(`# csa: ignore[CSA101]` on the flagged line or the line above).
"""
from .core import (Finding, Rule, RULES, PASSES, register_pass,  # noqa: F401
                   register_rule, analyze_paths, load_baseline)
from . import passes  # noqa: F401  (importing registers the passes)
from . import trace   # noqa: F401  (registers the trace-tier rule catalog;
#                       stdlib-only — tracing itself lives in trace/engine.py,
#                       loaded lazily by the CLI's --trace path)
from . import ranges  # noqa: F401  (registers the range-tier rule catalog;
#                       the interval interpreter lives in ranges/interp.py +
#                       ranges/engine.py, loaded lazily by --ranges)
from . import lifetime  # noqa: F401  (registers the lifetime-tier rule
#                       catalog; the ownership prover lives in
#                       lifetime/engine.py, loaded lazily by --lifetime)
from . import memory  # noqa: F401  (registers the memory-tier rule
#                       catalog; the liveness interpreter lives in
#                       memory/liveness.py + memory/engine.py, loaded
#                       lazily by --memory)
