"""Peak-buffer-liveness abstract interpreter over jaxprs.

The model (documented, and cross-checked against
`compiled.memory_analysis()` by the engine wherever the backend
reports it):

  * program inputs are CALLER-OWNED: a non-donated invar is resident
    for the whole call (XLA cannot free the caller's buffer), so it
    contributes its bytes from eqn 0 to the end;
  * a DONATED invar whose shape/dtype matches an output is ALIASED to
    that output (greedy congruent matching, the same pairing XLA's
    donation performs): the pair shares ONE buffer, live for the whole
    program, and the output's defining eqn adds no bytes. A donated
    invar nothing matches is freed after its last use;
  * an intermediate value is live from its defining eqn to its last
    use; a program output stays live to the end;
  * jaxpr constants are baked into the executable and counted resident
    for the whole program;
  * an eqn with sub-jaxprs (scan / while / cond / pjit / custom_*)
    contributes its body's TRANSIENT peak (body peak beyond the body's
    own inputs and outputs, which the outer walk already tracks as the
    eqn's operands and results) atop the live set carried across the
    eqn;
  * the modeled peak is the max, over eqns, of live bytes at that eqn
    plus the eqn's transient contribution.

Per-shard footprints reuse the same walk with a different byte
function: a leaf whose element count reaches the contract's sharding
threshold divides by the mesh size (the repo's placement policy — [V]
columns shard over "v", scalars and SHARD_COUNT-sized tables
replicate; see parallel/sharding.py), everything else replicates.

CSA1605 events: a callback primitive staged BETWEEN device eqns, while
buffers defined earlier and used later are live, widens every spanning
buffer's live range by a host round-trip. The walk records
(primitive, spanning bytes) for each such eqn.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

# primitives that bounce through the host mid-program (the trace tier
# forbids them on committed kernels; here they are a liveness event)
_HOST_PRIMS = ("pure_callback", "io_callback", "debug_callback",
               "host_callback")


def aval_bytes(aval) -> int:
    """Bytes of one buffer with the given abstract value. Non-array
    avals (tokens, abstract refs without a shape) cost nothing."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(dtype.itemsize)


def sharded_bytes_fn(devices: int, min_elems: int) -> Callable:
    """Byte function for the per-shard walk: a leaf with >= min_elems
    elements shards over `devices` (ceil division — XLA pads the last
    shard), smaller leaves replicate on every device."""
    def fn(aval) -> int:
        full = aval_bytes(aval)
        shape = getattr(aval, "shape", None)
        if not shape:
            return full
        elems = 1
        for d in shape:
            elems *= int(d)
        if elems >= min_elems:
            return -(-full // devices)
        return full
    return fn


@dataclass
class HostEvent:
    primitive: str
    eqn_index: int
    spanning_bytes: int


@dataclass
class Liveness:
    peak_bytes: int = 0
    arg_bytes: int = 0
    out_bytes: int = 0
    alias_bytes: int = 0      # donated-input bytes aliased onto outputs
    const_bytes: int = 0
    temp_bytes: int = 0       # peak beyond args + outs - alias
    n_eqns: int = 0
    host_events: List[HostEvent] = field(default_factory=list)
    # (eqn_index, primitive, live bytes at that eqn) of the peak eqn
    peak_site: Optional[Tuple[int, str, int]] = None


def _is_literal(atom) -> bool:
    return hasattr(atom, "val")


def _sub_jaxprs(eqn):
    """Every sub-jaxpr closed over by an eqn's params (pjit/scan keep a
    ClosedJaxpr under "jaxpr", custom_* under "call_jaxpr"/"fun_jaxpr",
    cond a tuple under "branches", while_loop cond/body pairs)."""
    subs = []
    for val in eqn.params.values():
        for item in (val if isinstance(val, (tuple, list)) else (val,)):
            if hasattr(item, "jaxpr") and hasattr(item, "consts"):
                subs.append(item)          # ClosedJaxpr
            elif hasattr(item, "eqns") and hasattr(item, "invars"):
                subs.append(item)          # raw Jaxpr (rare)
    return subs


def _match_donations(invars, outvars, donated: set,
                     bytes_fn: Callable) -> Tuple[set, set, int]:
    """Greedy congruent pairing of donated invars with outputs — the
    matching XLA's donation performs. Returns (aliased invar ids,
    aliased outvar ids, aliased bytes under bytes_fn)."""
    aliased_in, aliased_out = set(), set()
    alias_bytes = 0
    taken = set()
    for i in sorted(donated):
        if i >= len(invars):
            continue
        iv = invars[i]
        sig = (tuple(iv.aval.shape), str(iv.aval.dtype))
        for ov in outvars:
            if _is_literal(ov) or id(ov) in taken or id(ov) in aliased_out:
                continue
            aval = getattr(ov, "aval", None)
            if aval is None or not hasattr(aval, "shape"):
                continue
            if (tuple(aval.shape), str(aval.dtype)) == sig:
                aliased_in.add(id(iv))
                aliased_out.add(id(ov))
                alias_bytes += bytes_fn(iv.aval)
                break
    return aliased_in, aliased_out, alias_bytes


def analyze(closed, donated: Optional[set] = None,
            bytes_fn: Callable = aval_bytes) -> Liveness:
    """Walk a ClosedJaxpr and return the modeled peak liveness.

    `donated` holds FLAT invar indices (the engine expands jit-level
    donate_argnums over each argument's leaves)."""
    jaxpr = getattr(closed, "jaxpr", closed)
    donated = donated or set()
    res = Liveness(n_eqns=len(jaxpr.eqns))

    invars = list(jaxpr.invars)
    outvars = [v for v in jaxpr.outvars if not _is_literal(v)]
    outvar_ids = {id(v) for v in outvars}
    res.arg_bytes = sum(bytes_fn(v.aval) for v in invars)
    res.out_bytes = sum(bytes_fn(v.aval) for v in jaxpr.outvars
                        if getattr(v, "aval", None) is not None)
    res.const_bytes = sum(bytes_fn(v.aval) for v in jaxpr.constvars)

    aliased_in, aliased_out, res.alias_bytes = _match_donations(
        invars, jaxpr.outvars, donated, bytes_fn)

    # last program-order use of every var (program outputs: the end)
    last_use: Dict[int, int] = {}
    end = len(jaxpr.eqns)
    for i, eqn in enumerate(jaxpr.eqns):
        for atom in eqn.invars:
            if not _is_literal(atom):
                last_use[id(atom)] = i
    for v in outvars:
        last_use[id(v)] = end

    # resident for the whole program: non-donated inputs (caller-owned),
    # donated-and-aliased inputs (the shared in/out buffer), constants
    live: Dict[int, int] = {}
    never_free = set()
    for i, v in enumerate(invars):
        live[id(v)] = bytes_fn(v.aval)
        if i not in donated or id(v) in aliased_in:
            never_free.add(id(v))
    for v in jaxpr.constvars:
        live[id(v)] = bytes_fn(v.aval)
        never_free.add(id(v))

    live_total = sum(live.values())
    peak = live_total
    res.peak_site = (-1, "<args>", peak)

    for i, eqn in enumerate(jaxpr.eqns):
        # transient contribution of sub-jaxpr bodies beyond their own
        # I/O (already tracked as this eqn's operands and results)
        extra = 0
        for sub in _sub_jaxprs(eqn):
            inner = analyze(sub, bytes_fn=bytes_fn)
            extra = max(extra, inner.temp_bytes)
        prim = getattr(eqn.primitive, "name", str(eqn.primitive))
        if any(h in prim for h in _HOST_PRIMS):
            spanning = sum(b for vid, b in live.items()
                           if last_use.get(vid, -1) > i)
            if spanning:
                res.host_events.append(HostEvent(prim, i, spanning))
        for ov in eqn.outvars:
            if type(ov).__name__ == "DropVar":
                continue
            if id(ov) in aliased_out:
                continue          # donation: the input's buffer is reused
            if id(ov) in last_use and id(ov) not in live:
                b = bytes_fn(ov.aval)     # dead results allocate nothing
                live[id(ov)] = b
                live_total += b
        here = live_total + extra
        if here > peak:
            peak = here
            res.peak_site = (i, prim, here)
        for atom in eqn.invars:
            vid = id(atom) if not _is_literal(atom) else None
            if (vid is not None and vid not in never_free
                    and vid not in outvar_ids
                    and last_use.get(vid) == i):
                b = live.pop(vid, None)
                if b is not None:
                    live_total -= b

    res.peak_bytes = peak
    res.temp_bytes = max(
        0, peak - (res.arg_bytes + res.out_bytes - res.alias_bytes
                   + res.const_bytes))
    return res


def traffic_bounds(closed, bytes_fn: Callable = aval_bytes
                   ) -> Tuple[int, int]:
    """(lo, hi) HBM-traffic bounds from the same cost model the
    contracts use: `lo` assumes perfect fusion (each program input read
    once, each output written once); `hi` assumes NO fusion (every eqn
    streams its operands in and its results out). The real machine
    lands between them — tools/tpu_followup.py's roofline stage prints
    both instead of a hand-maintained bytes-per-element table."""
    jaxpr = getattr(closed, "jaxpr", closed)
    lo = (sum(aval_bytes(v.aval) for v in jaxpr.invars)
          + sum(bytes_fn(getattr(v, "aval", None))
                if hasattr(getattr(v, "aval", None), "shape") else 0
                for v in jaxpr.outvars))
    hi = 0

    def walk(jx):
        nonlocal hi
        for eqn in jx.eqns:
            for atom in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(atom, "aval", None)
                if aval is not None and hasattr(aval, "shape"):
                    hi += bytes_fn(aval)
            for sub in _sub_jaxprs(eqn):
                walk(getattr(sub, "jaxpr", sub))
    walk(jaxpr)
    return lo, max(lo, hi)


def fit_order(ns, ys) -> float:
    """Least-squares slope of log y over log n — the scaling exponent a
    contract's probe shapes exhibit. Degenerate inputs (a constant
    metric, probes of one size) fit 0.0."""
    pts = [(math.log(n), math.log(y)) for n, y in zip(ns, ys)
           if n > 0 and y > 0]
    if len(pts) < 2:
        return 0.0
    mx = sum(x for x, _ in pts) / len(pts)
    my = sum(y for _, y in pts) / len(pts)
    den = sum((x - mx) ** 2 for x, _ in pts)
    if den == 0:
        return 0.0
    return sum((x - mx) * (y - my) for x, y in pts) / den
