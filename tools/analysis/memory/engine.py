"""Memory-contract engine: discover MEM_CONTRACTS, run the peak-
liveness interpreter over the real jaxprs, cross-check the model
against what XLA allocates, ratchet the modeled bytes against the
committed baseline.

A **memory contract** is a plain dict a kernel module exports in its
`MEM_CONTRACTS` list (plain data, the TRACE_CONTRACTS idiom — the
engine imports the kernel modules, never the reverse):

    name           unique id, e.g. "models.phase0.epoch_soa.epoch_10m_hbm"
    build          () -> {"fn": traceable, "args": tuple of arrays or
                   jax.ShapeDtypeStruct pytrees (ceiling shapes cost
                   nothing to trace), "context": () -> contextmanager
                   (optional), "donate_argnums": top-level arg positions
                   whose buffers the production dispatch donates
                   (optional — expanded over each argument's leaves, so
                   the liveness model aliases them onto congruent
                   outputs and counts the pair ONCE)}
    budget_bytes   declared peak-HBM ceiling the modeled peak must stay
                   under (CSA1601); absent = ratchet only
    sharded        {"devices": N, "min_elems": int, "replicated_cap_bytes":
                   int} — rerun the walk with the per-shard byte
                   function (a leaf with >= min_elems elements shards
                   over N, everything else replicates: the repo's
                   placement policy) and PROVE
                   shard_peak <= ceil(single_peak / N) + replicated_cap
                   (CSA1601)
    scaling        {"ns": [2-3 probe sizes], "build": n -> build-spec,
                   "metric": "peak_bytes" | "temp_bytes", "max_order":
                   float, "tol": slope slack (default 0.15)} — fit the
                   log-log slope of the metric over the probes and
                   assert it <= max_order + tol (CSA1603)
    compiled       {"build": () -> build-spec at a documented probe
                   shape (default: the contract's own build), "tol":
                   ratio (default 1.25), "slack_bytes": abs slack
                   (default 4096)} or True — lower + compile the probe
                   and check the model against compiled.
                   memory_analysis(): argument/output/alias bytes
                   always (exact on every backend), peak vs
                   arg+out-alias+temp only when the backend reports a
                   nonzero temp (XLA:CPU reports 0 — the working set is
                   only visible on accelerator backends). Divergence
                   beyond tolerance is CSA1601: the model is wrong, fix
                   the model, never trust it quietly.
    vmem           {"blocks": [((rows, cols), "dtype"), ...] or a
                   callable returning that list, "buffering": pipeline
                   copies (default 2, the Pallas double-buffered
                   pipeline), "budget_bytes": default 16 MiB/core} —
                   bound the BlockSpec footprint (CSA1604). A contract
                   may be vmem-only (no "build").

The ratchet (memory_baseline.json maps contract -> {metric: value},
metrics "peak_bytes"/"temp_bytes" + "shard_peak_bytes"/"vmem_bytes"
when the contract declares those checks): modeled bytes that GREW vs
the committed snapshot are CSA1602 — as is a contract with no
snapshot. Shrunk bytes are a notice (refresh the baseline). Host
round-trips the walk detects while buffers span them are CSA1605
notices.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from ..core import Finding, _parse_suppressions
from . import liveness as L

REPO_ROOT = Path(__file__).resolve().parents[3]
DEFAULT_BASELINE = Path(__file__).resolve().parents[1] / \
    "memory_baseline.json"

VMEM_BUDGET_BYTES = 16 * 1024 * 1024      # per-core VMEM (v4/v5 class)

# ratchet direction per metric: bytes only grow by a reviewed edit
METRIC_SIGN = {"peak_bytes": 1, "temp_bytes": 1,
               "shard_peak_bytes": 1, "vmem_bytes": 1}


# ---------------------------------------------------------------------------
# Discovery (mirrors ranges/engine.discover)
# ---------------------------------------------------------------------------

def discover(package_root: Optional[Path] = None) -> List[dict]:
    import importlib
    root = Path(package_root or REPO_ROOT / "consensus_specs_tpu")
    contracts: List[dict] = []
    seen = set()
    for path in sorted(root.rglob("*.py")):
        source = path.read_text()
        if "MEM_CONTRACTS" not in source:
            continue
        rel = path.relative_to(root.parent).with_suffix("")
        module = importlib.import_module(".".join(rel.parts))
        for contract in getattr(module, "MEM_CONTRACTS", []):
            c = dict(contract)
            name = c["name"]
            assert name not in seen, f"duplicate memory contract {name}"
            seen.add(name)
            c.setdefault("path", str(path))
            c.setdefault("line", _name_line(source, name))
            contracts.append(c)
    return contracts


def _name_line(source: str, name: str) -> int:
    lines = source.splitlines()
    # quoted match first — a bare substring scan would anchor a name at
    # a longer name containing it, mis-placing inline suppressions
    for i, line in enumerate(lines, 1):
        if f'"{name}"' in line or f"'{name}'" in line:
            return i
    for i, line in enumerate(lines, 1):
        if name in line:
            return i
    for i, line in enumerate(lines, 1):
        if "MEM_CONTRACTS" in line:
            return i
    return 1


def declared_snapshot(contracts: Optional[Iterable[dict]] = None) -> dict:
    """{contract: declared peak budget} without tracing anything — the
    cheap declaration read bench.py embeds next to the trace/range/
    lifetime snapshot rows."""
    if contracts is None:
        contracts = discover()
    return {c["name"]: c.get("budget_bytes") for c in contracts}


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def load_memory_baseline(path=None) -> Dict[str, Dict[str, int]]:
    p = Path(path or DEFAULT_BASELINE)
    if not p.exists():
        return {}
    return {k: dict(v) for k, v in
            json.loads(p.read_text()).get("contracts", {}).items()}


def write_memory_baseline(path, snapshot: Dict[str, Dict[str, int]]) -> None:
    ordered = {k: {m: snapshot[k][m] for m in sorted(snapshot[k])}
               for k in sorted(snapshot)}
    Path(path).write_text(json.dumps(
        {"version": 1,
         "comment": "Modeled peak-liveness snapshot (the CSA1602 bytes "
                    "ratchet). peak_bytes/temp_bytes are what the "
                    "liveness model derived over the contract's ceiling "
                    "shapes; shard_peak_bytes the per-shard walk, "
                    "vmem_bytes the Pallas block footprint. Loosening "
                    "an entry is a reviewed edit; "
                    "--update-memory-baseline refreshes after wins.",
         "contracts": ordered}, indent=2) + "\n")


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------

@dataclass
class MemResult:
    name: str
    path: str
    line: int
    measured: Dict[str, int] = field(default_factory=dict)
    detail: Dict[str, object] = field(default_factory=dict)
    skipped: str = ""


@dataclass
class MemReport:
    findings: List[Finding]
    suppressed: List[Finding]
    results: List[MemResult]
    notices: List[str]
    stale_baseline: List[str]

    @property
    def snapshot(self) -> Dict[str, Dict[str, int]]:
        return {r.name: dict(r.measured) for r in self.results
                if not r.skipped and r.measured}


def _rel(path: str) -> str:
    try:
        return str(Path(path).resolve().relative_to(REPO_ROOT))
    except ValueError:
        return path


def _flat_donated(args, donate_argnums) -> set:
    """Expand jit-level donate_argnums (top-level positions) to FLAT
    invar indices over the argument pytree's leaves."""
    import jax
    donated = set()
    offset = 0
    donate = set(donate_argnums or ())
    for i, arg in enumerate(args):
        n = len(jax.tree_util.tree_leaves(arg))
        if i in donate:
            donated.update(range(offset, offset + n))
        offset += n
    return donated


def _trace(spec):
    """Trace one build spec to (ClosedJaxpr, flat donated indices)."""
    import contextlib
    import jax
    fn, args = spec["fn"], tuple(spec["args"])
    with contextlib.ExitStack() as stack:
        ctx_factory = spec.get("context")
        if ctx_factory:
            stack.enter_context(ctx_factory())
        closed = jax.make_jaxpr(fn)(*args)
    return closed, _flat_donated(args, spec.get("donate_argnums"))


def _analyze_spec(spec, bytes_fn=L.aval_bytes) -> L.Liveness:
    closed, donated = _trace(spec)
    return L.analyze(closed, donated=donated, bytes_fn=bytes_fn)


def _vmem_bytes(vmem: dict) -> int:
    blocks = vmem["blocks"]
    if callable(blocks):
        blocks = blocks()
    import numpy as np
    total = 0
    for shape, dtype in blocks:
        n = 1
        for d in shape:
            n *= int(d)
        total += n * np.dtype(dtype).itemsize
    return total * int(vmem.get("buffering", 2))


def _compiled_check(spec, model_small: L.Liveness, tol: float,
                    slack: int) -> Dict[str, object]:
    """Lower + compile the probe spec and compare the liveness model's
    bytes against compiled.memory_analysis(). Returns {"checked":
    {metric: [model, compiled, ok]}, "failures": [msg, ...]}."""
    import contextlib
    import jax
    fn, args = spec["fn"], tuple(spec["args"])
    jit_kwargs = {}
    if spec.get("donate_argnums"):
        jit_kwargs["donate_argnums"] = tuple(spec["donate_argnums"])
    with contextlib.ExitStack() as stack:
        ctx_factory = spec.get("context")
        if ctx_factory:
            stack.enter_context(ctx_factory())
        # Compile FRESH, never through the persistent compilation cache:
        # an XLA:CPU executable deserialized from the cache drops its
        # donated-aliasing metadata (the PR 3 caveat CSA1504 codifies),
        # so memory_analysis() on a cache hit reports alias 0 and a
        # different temp — the cross-check would flag the model for the
        # cache's dishonesty. conftest.py points the cache at .cache/xla
        # for the test lanes; unset it for the probe compile only.
        cache_dir = jax.config.jax_compilation_cache_dir
        if cache_dir is not None:
            jax.config.update("jax_compilation_cache_dir", None)
        try:
            compiled = jax.jit(fn, **jit_kwargs).lower(*args).compile()
        finally:
            if cache_dir is not None:
                jax.config.update("jax_compilation_cache_dir", cache_dir)
    stats = compiled.memory_analysis()
    if stats is None:
        return {"checked": {}, "failures": [],
                "note": "backend reports no memory_analysis"}

    def close(model, actual):
        if abs(model - actual) <= slack:
            return True
        lo, hi = sorted((model, actual))
        return lo > 0 and hi / lo <= tol

    checked, failures = {}, []
    pairs = [
        ("argument_bytes", model_small.arg_bytes,
         int(getattr(stats, "argument_size_in_bytes", 0))),
        ("output_bytes", model_small.out_bytes,
         int(getattr(stats, "output_size_in_bytes", 0))),
        ("alias_bytes", model_small.alias_bytes,
         int(getattr(stats, "alias_size_in_bytes", 0))),
    ]
    temp = int(getattr(stats, "temp_size_in_bytes", 0))
    if temp > 0:
        # the backend reports a real working set: check the PEAK, the
        # quantity the budgets are about (XLA:CPU reports temp 0 — the
        # peak is then invisible and only the exact arg/out/alias
        # components are checkable)
        compiled_peak = (int(stats.argument_size_in_bytes)
                         + int(stats.output_size_in_bytes)
                         - int(getattr(stats, "alias_size_in_bytes", 0))
                         + temp)
        pairs.append(("peak_bytes", model_small.peak_bytes, compiled_peak))
    for metric, model, actual in pairs:
        ok = close(model, actual)
        checked[metric] = [int(model), int(actual), ok]
        if not ok:
            failures.append(
                f"model `{metric}` = {model} diverges from "
                f"compiled.memory_analysis() = {actual} beyond the "
                f"documented tolerance (x{tol}, slack {slack} B)")
    return {"checked": checked, "failures": failures}


def _measure(contract: dict):
    """Evaluate one contract. Returns (MemResult, findings) where
    findings is a list of (rule, message)."""
    res = MemResult(name=contract["name"], path=contract["path"],
                    line=contract["line"])
    found: List[tuple] = []

    model = None
    if "build" in contract:
        spec = contract["build"]()
        closed, donated = _trace(spec)
        model = L.analyze(closed, donated=donated)
        res.measured["peak_bytes"] = model.peak_bytes
        res.measured["temp_bytes"] = model.temp_bytes
        res.detail["arg_bytes"] = model.arg_bytes
        res.detail["out_bytes"] = model.out_bytes
        res.detail["alias_bytes"] = model.alias_bytes
        res.detail["const_bytes"] = model.const_bytes
        res.detail["n_eqns"] = model.n_eqns
        if model.peak_site:
            i, prim, bytes_at = model.peak_site
            res.detail["peak_site"] = {"eqn": i, "primitive": prim,
                                       "live_bytes": bytes_at}
        for ev in model.host_events:
            found.append((
                "CSA1605",
                f"host round-trip (`{ev.primitive}` at eqn "
                f"{ev.eqn_index}) while {ev.spanning_bytes} bytes of "
                f"device buffers span it — their live ranges widen by "
                f"host latency"))

        budget = contract.get("budget_bytes")
        if budget is not None and model.peak_bytes > int(budget):
            found.append((
                "CSA1601",
                f"modeled peak {model.peak_bytes} B exceeds the "
                f"declared budget {int(budget)} B"))

        sharded = contract.get("sharded")
        if sharded:
            n = int(sharded["devices"])
            shard_model = L.analyze(
                closed, donated=donated,
                bytes_fn=L.sharded_bytes_fn(n, int(sharded["min_elems"])))
            cap = int(sharded["replicated_cap_bytes"])
            bound = -(-model.peak_bytes // n) + cap
            res.measured["shard_peak_bytes"] = shard_model.peak_bytes
            res.detail["shard_bound"] = {"devices": n, "cap_bytes": cap,
                                         "bound_bytes": bound}
            if shard_model.peak_bytes > bound:
                found.append((
                    "CSA1601",
                    f"per-shard modeled peak {shard_model.peak_bytes} B "
                    f"escapes single/N + replicated cap = "
                    f"{model.peak_bytes}/{n} + {cap} = {bound} B"))

        comp = contract.get("compiled")
        if comp:
            comp = comp if isinstance(comp, dict) else {}
            probe_spec = (comp["build"]() if "build" in comp else spec)
            probe_model = (model if probe_spec is spec
                           else _analyze_spec(probe_spec))
            cc = _compiled_check(probe_spec, probe_model,
                                 float(comp.get("tol", 1.25)),
                                 int(comp.get("slack_bytes", 4096)))
            res.detail["compiled"] = cc["checked"]
            for msg in cc["failures"]:
                found.append(("CSA1601", msg))

    scaling = contract.get("scaling")
    if scaling:
        metric = scaling.get("metric", "peak_bytes")
        ns = list(scaling["ns"])
        values = [getattr(_analyze_spec(scaling["build"](n)), metric)
                  for n in ns]
        order = L.fit_order(ns, values)
        max_order = float(scaling["max_order"])
        tol = float(scaling.get("tol", 0.15))
        res.detail["scaling"] = {"ns": ns, metric: values,
                                 "fitted_order": round(order, 4),
                                 "max_order": max_order}
        if order > max_order + tol:
            found.append((
                "CSA1603",
                f"`{metric}` scales as n^{order:.2f} over probes {ns}, "
                f"above the declared O(n^{max_order}) (+{tol} slack)"))

    vmem = contract.get("vmem")
    if vmem:
        total = _vmem_bytes(vmem)
        budget = int(vmem.get("budget_bytes", VMEM_BUDGET_BYTES))
        res.measured["vmem_bytes"] = total
        res.detail["vmem_budget_bytes"] = budget
        if total > budget:
            found.append((
                "CSA1604",
                f"BlockSpec footprint {total} B (blocks x dtype x "
                f"buffering {vmem.get('buffering', 2)}) exceeds the "
                f"{budget} B per-core VMEM budget"))

    return res, found


def run_contracts(contracts: Optional[List[dict]] = None,
                  baseline: Optional[Dict[str, Dict[str, int]]] = None,
                  baseline_path=None) -> MemReport:
    if contracts is None:
        contracts = discover()
    if baseline is None:
        baseline = load_memory_baseline(baseline_path)
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    results: List[MemResult] = []
    notices: List[str] = []
    matched = set()
    suppression_cache: Dict[str, Dict[int, set]] = {}

    def emit(res, rule, message):
        path = _rel(res.path)
        line = res.line
        f = Finding(rule, path, line, message, context=res.name)
        sup = suppression_cache.get(path)
        if sup is None:
            try:
                sup = _parse_suppressions(
                    (REPO_ROOT / path).read_text()
                    if not Path(path).is_absolute()
                    else Path(path).read_text())
            except OSError:
                sup = {}
            suppression_cache[path] = sup
        for ln in (line, line - 1):
            rules = sup.get(ln)
            if rules and ("*" in rules or rule in rules):
                suppressed.append(f)
                return
        findings.append(f)

    for contract in contracts:
        try:
            res, found = _measure(contract)
        except Exception as exc:   # a broken contract is a finding, not a crash
            res = MemResult(name=contract["name"], path=contract["path"],
                            line=contract["line"],
                            skipped=f"{type(exc).__name__}: {exc}")
            results.append(res)
            emit(res, "CSA1601",
                 f"contract failed to trace/model: {res.skipped}")
            matched.add(res.name)     # unverifiable, not stale: the
            continue                  # baseline entry must survive
        results.append(res)
        for rule, message in found:
            emit(res, rule, message)

        base = baseline.get(res.name, {})
        if res.name in baseline:
            matched.add(res.name)
        for metric, got in res.measured.items():
            sign = METRIC_SIGN.get(metric, 1)
            prior = base.get(metric)
            if prior is None:
                emit(res, "CSA1602",
                     f"`{metric}` = {got} has no memory-baseline entry "
                     f"(run --update-memory-baseline and commit)")
            elif sign * (got - prior) > 0:
                emit(res, "CSA1602",
                     f"modeled `{metric}` = {got} regressed vs the "
                     f"committed baseline {prior}")
            elif got != prior:
                notices.append(
                    f"memory: {res.name} `{metric}` shrank "
                    f"{prior} -> {got}; refresh via "
                    f"--update-memory-baseline")

    stale = sorted(set(baseline) - matched)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return MemReport(findings=findings, suppressed=suppressed,
                     results=results, notices=notices,
                     stale_baseline=stale)


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------

def render_human(report: MemReport) -> str:
    from ..core import RULES
    out = []
    for f in report.findings:
        out.append(f"{f.path}:{f.line}: [{f.rule}] {RULES[f.rule].severity}:"
                   f" {f.context}: {f.message}")
        if RULES[f.rule].hint:
            out.append(f"    hint: {RULES[f.rule].hint}")
    for name in report.stale_baseline:
        out.append(f"memory-baseline: stale contract (removed? delete it): "
                   f"{name}")
    for note in report.notices:
        out.append(f"notice: {note}")
    ran = sum(1 for r in report.results if not r.skipped)
    out.append(f"memory: {len(report.results)} contract(s), {ran} modeled, "
               f"{len(report.findings)} finding(s), "
               f"{len(report.suppressed)} suppressed")
    return "\n".join(out)


def render_json(report: MemReport) -> str:
    from ..core import RULES

    def row(f: Finding):
        return {"rule": f.rule, "path": f.path, "line": f.line,
                "contract": f.context, "message": f.message,
                "severity": RULES[f.rule].severity,
                "fingerprint": f.fingerprint()}

    return json.dumps({
        "findings": [row(f) for f in report.findings],
        "suppressed": [row(f) for f in report.suppressed],
        "contracts": [
            {"name": r.name, "path": _rel(r.path), "line": r.line,
             "skipped": r.skipped, "measured": r.measured,
             "detail": r.detail}
            for r in report.results],
        "notices": report.notices,
        "stale_baseline": report.stale_baseline,
    }, indent=2)
