"""Memory tier: a peak-buffer-liveness abstract interpreter over the
REAL jaxprs that machine-checks HBM/VMEM byte budgets.

The trace tier counts ops, the range tier bounds values, the lifetime
tier proves ownership; this tier bounds BYTES. ROADMAP items 3 and 4
both block on memory facts nobody proved before it: the Pallas kernels
need a machine-checked VMEM block budget (the range hulls give widths,
nothing bounds bytes-on-chip), and the 10M-validator epoch needs a
per-shard HBM capacity argument that is not hand arithmetic.

Kernel modules export `MEM_CONTRACTS` lists (the TRACE_CONTRACTS /
RANGE_CONTRACTS idiom — plain data, the engine imports the kernel
modules, never the reverse). Each contract names a traceable program at
its CEILING shape (V = 10^7 validators, the 2^20-leaf forest, the
G = 128 x P = 3 grouped pairing, the firehose ring plus two in-flight
batches — ShapeDtypeStructs, so nothing allocates) and the liveness
interpreter (memory/liveness.py) walks the jaxpr in program order: a
buffer is live from its defining eqn to its last use, a DONATED input
aliases its congruent output and is counted once, and scan/while/cond
sub-jaxprs contribute their body's transient peak atop the carried
live set. The modeled peak is cross-checked against what XLA itself
allocates (`compiled.memory_analysis()` — argument/output/alias/temp
bytes) wherever the backend reports it, the per-shard footprint of the
sharded epoch is proven == single/N + the declared replicated cap on
the 8-device virtual mesh, a scaling exponent fitted from 2-3 probe
shapes asserts the declared order (epoch O(V), forest update
O(dirty * log V) bytes), and Pallas BlockSpec footprints are bounded
against the 16 MiB/core VMEM budget.

  CSA1601  declared-budget violation   (modeled peak over the declared
                                        HBM budget, the per-shard bound
                                        single/N + replicated cap fails,
                                        or the model diverges from
                                        compiled.memory_analysis()
                                        beyond the documented tolerance)
  CSA1602  memory-baseline regression  (modeled bytes grew vs the
                                        committed memory_baseline.json,
                                        or a contract with no snapshot —
                                        the bytes ratchet, like the
                                        trace tier's lane ratchet)
  CSA1603  superlinear scaling         (the exponent fitted from the
                                        contract's probe shapes exceeds
                                        the declared order)
  CSA1604  Pallas VMEM overflow        (BlockSpec blocks x dtype x
                                        pipeline buffering exceed the
                                        16 MiB/core VMEM budget)
  CSA1605  host round-trip             (notice: a callback between
                                        device eqns widens every
                                        spanning buffer's live range to
                                        host latency)

Entry points:

  python -m tools.analysis --memory [--memory-baseline b.json]
                                    [--update-memory-baseline]
                                    [--json out/memory.json]
  make memory

This module registers the rule catalog only (stdlib, importable by the
no-jax lint lane for `--list-rules`); liveness.py and engine.py are
loaded lazily by the CLI's --memory path, by tests, by bench.py's
memory-snapshot row, and by tools/tpu_followup.py's roofline stage.
"""
from ..core import register_rule

register_rule(
    "CSA1601",
    "memory budget violation: modeled peak bytes escape the declared "
    "budget, the per-shard bound, or the compiled cross-check",
    "error",
    "the liveness model derived a peak the contract's declared budget "
    "(or the single/N + replicated-cap shard bound, or the compiled "
    "memory_analysis within the documented tolerance) cannot cover — "
    "shrink the kernel's live set or raise the budget in the same "
    "reviewable diff",
)
register_rule(
    "CSA1602",
    "memory-baseline regression: modeled bytes grew vs the committed "
    "snapshot",
    "error",
    "modeled peak/temp bytes only grow by a reviewed edit: run "
    "`python -m tools.analysis --memory --update-memory-baseline` and "
    "commit tools/analysis/memory_baseline.json in the diff that "
    "explains the new bytes",
)
register_rule(
    "CSA1603",
    "superlinear memory scaling vs the contract's declared order",
    "error",
    "the exponent fitted from the contract's probe shapes exceeds the "
    "declared order (epoch O(V), forest update O(dirty*log V)) — a "
    "full-width rebuild or quadratic temp crept onto the scaled path",
)
register_rule(
    "CSA1604",
    "Pallas VMEM overflow: BlockSpec blocks x dtype x buffering exceed "
    "the per-core budget",
    "error",
    "the kernel's block shapes, times the pipeline's buffering factor, "
    "do not fit the 16 MiB/core VMEM — shrink the block_lanes tile or "
    "the declared buffering",
)
register_rule(
    "CSA1605",
    "host round-trip between device eqns widens live buffer ranges",
    "notice",
    "a callback primitive executes while device buffers are live: every "
    "spanning buffer stays resident across host latency — hoist the "
    "callback out of the program or move it before the buffers' "
    "defining eqns",
)

MEMORY_RULE_IDS = ("CSA1601", "CSA1602", "CSA1603", "CSA1604", "CSA1605")
