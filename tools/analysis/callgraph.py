"""Whole-program IR: module graph + call graph + cross-module jit taint.

PR 1's jitmap discovers jit context per file; its transitive-callee step
stops at the module edge, so a helper imported from another module and
called inside a jitted body was analyzed as host code. This module lifts
the same taint model to the program level:

  1. every target file gets a dotted module name. Package roots are
     detected by ``__init__.py`` — a directory target that is itself a
     package keeps its name as the prefix (``consensus_specs_tpu.ops.
     sha256``), a plain directory of fixtures roots names at the
     directory (``pkg.a`` for ``<tmpdir>/pkg/a.py``), a single-file
     target is just its stem (``bench``);
  2. imports are resolved to program modules: ``import a.b [as c]``,
     ``from a.b import f [as g]``, ``from pkg import mod``, and
     relative ``from ..models.phase0.epoch_soa import X`` forms;
  3. jit context propagates along resolved call edges until fixpoint: a
     def in module B called (by from-imported name, or as an attribute
     of an imported module object) from any jit-context function in
     module A becomes jit context in B's JitMap, with the same
     annotation-driven parameter classification jitmap applies to
     same-module transitive callees — so every existing per-module pass
     sees it with no changes of its own;
  4. jitted *names* propagate too: ``from ops.x import f_jit`` makes
     call sites of ``f_jit`` in the importing module visible to the
     CSA5xx cache-hygiene pass.

The Program object also carries the analysis options (the spec-drift
reference root) and the notices program-level passes emit (e.g. the
CSA8xx skip notice when the reference tree is absent).
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from . import jitmap


@dataclass
class ModuleNode:
    name: str                       # dotted module name
    info: object                    # core.ModuleInfo
    is_init: bool = False
    # local name -> dotted module ("import a.b as c", "import a.b")
    module_aliases: Dict[str, str] = field(default_factory=dict)
    # local name -> (dotted source module, remote name)
    from_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    # module-level function defs by name
    defs: Dict[str, ast.FunctionDef] = field(default_factory=dict)

    @property
    def package(self) -> List[str]:
        parts = self.name.split(".")
        return parts if self.is_init else parts[:-1]


@dataclass
class Program:
    modules: Dict[str, ModuleNode] = field(default_factory=dict)
    by_path: Dict[str, ModuleNode] = field(default_factory=dict)
    # (caller module, caller def) -> {(callee module, callee def)}
    edges: Dict[Tuple[str, str], Set[Tuple[str, str]]] = \
        field(default_factory=dict)
    options: Dict[str, object] = field(default_factory=dict)
    notices: List[str] = field(default_factory=list)
    # rule ids whose pass did not run this invocation (e.g. CSA8xx when
    # the reference tree is absent): their baseline entries are exempt
    # from staleness, or a deliberate-divergence entry recorded where
    # the reference exists would fail the ratchet on machines without it
    skipped_rules: Set[str] = field(default_factory=set)

    def module_named(self, suffix: str) -> Optional[ModuleNode]:
        """The first module whose dotted name equals or ends with
        `suffix` (used by passes to anchor program-level findings)."""
        for name, node in sorted(self.modules.items()):
            if name == suffix or name.endswith("." + suffix):
                return node
        return None


def module_name_for(path: Path, root: Path) -> str:
    rel = path.resolve().relative_to(root.resolve())
    parts = list(rel.with_suffix("").parts)
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def root_for_target(target: Path) -> Path:
    """The import root a target's module names are computed against."""
    if target.is_dir():
        # a dir that IS a package keeps its own name as the prefix
        return target.parent if (target / "__init__.py").exists() else target
    return target.parent


def _parse_imports(node: ModuleNode) -> None:
    pkg = node.package
    for stmt in ast.walk(node.info.tree):
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                local = alias.asname or alias.name
                node.module_aliases[local] = alias.name
        elif isinstance(stmt, ast.ImportFrom):
            if stmt.level == 0:
                base = (stmt.module or "").split(".")
            else:
                # `from .` = the module's package; each extra dot climbs
                keep = len(pkg) - (stmt.level - 1)
                if keep < 0:
                    continue
                base = pkg[:keep] if stmt.level > 1 else list(pkg)
                if stmt.module:
                    base = base + stmt.module.split(".")
            src = ".".join(p for p in base if p)
            for alias in stmt.names:
                local = alias.asname or alias.name
                node.from_imports[local] = (src, alias.name)


def resolve_module(node: ModuleNode, dotted: str,
                   program: Program) -> Optional[ModuleNode]:
    """The program module a dotted *value* expression refers to, if any:
    an import alias, a from-imported submodule, or a full module path."""
    if not dotted:
        return None
    if dotted in node.module_aliases:
        return program.modules.get(node.module_aliases[dotted])
    fi = node.from_imports.get(dotted)
    if fi is not None:
        src, remote = fi
        return program.modules.get(f"{src}.{remote}" if src else remote)
    return program.modules.get(dotted)


def resolve_call(node: ModuleNode, call: ast.Call, program: Program
                 ) -> Optional[Tuple[ModuleNode, Optional[ast.FunctionDef]]]:
    """(defining module, FunctionDef|None) for a call that resolves to a
    program module's module-level def; None for anything else (methods,
    builtins, third-party calls)."""
    func = call.func
    if isinstance(func, ast.Name):
        fi = node.from_imports.get(func.id)
        if fi is not None:
            src_mod = program.modules.get(fi[0])
            if src_mod is not None and fi[1] in src_mod.defs:
                return src_mod, src_mod.defs[fi[1]]
            return None
        if func.id in node.defs:
            return node, node.defs[func.id]
        return None
    if isinstance(func, ast.Attribute):
        base = jitmap._dotted(func.value)
        target = resolve_module(node, base, program)
        if target is not None:
            return target, target.defs.get(func.attr)
    return None


def _propagate_jit(program: Program) -> None:
    """Extend each module's JitMap with cross-module transitive callees
    (and imported jitted names) until fixpoint."""
    work: List[Tuple[ModuleNode, ast.AST]] = []
    for node in program.modules.values():
        jmap = node.info.jit_map          # forces the per-module build
        work.extend((node, jf.node) for jf in list(jmap.funcs.values()))
    seen = {id(fn) for _, fn in work}
    while work:
        node, fn = work.pop()
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            resolved = resolve_call(node, sub, program)
            if resolved is None:
                continue
            t_node, t_def = resolved
            if t_def is None:
                continue
            t_jmap = t_node.info.jit_map
            if t_def not in t_jmap.funcs:
                static, traced = jitmap._callee_params(t_def)
                t_jmap.funcs[t_def] = jitmap.JitFunc(
                    t_def, t_def.name, direct=False,
                    traced_params=traced, static_params=static)
            if id(t_def) not in seen:
                seen.add(id(t_def))
                work.append((t_node, t_def))

    # imported jitted names: make `from m import f_jit` call sites
    # visible to the importing module's CSA5xx checks. To fixpoint —
    # re-export chains (a defines, b re-exports, c calls) must resolve
    # regardless of module iteration order.
    changed = True
    while changed:
        changed = False
        for node in program.modules.values():
            for local, (src, remote) in node.from_imports.items():
                src_mod = program.modules.get(src)
                if src_mod is None:
                    continue
                jitted = src_mod.info.jit_map.jitted_names
                if remote in jitted and \
                        local not in node.info.jit_map.jitted_names:
                    node.info.jit_map.jitted_names[local] = jitted[remote]
                    changed = True


def _build_edges(program: Program) -> None:
    for node in program.modules.values():
        for name, fn in node.defs.items():
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                resolved = resolve_call(node, sub, program)
                if resolved is None or resolved[1] is None:
                    continue
                t_node, t_def = resolved
                program.edges.setdefault((node.name, name), set()).add(
                    (t_node.name, t_def.name))


def build(rooted_modules: List[Tuple[Path, object]],
          options: Optional[Dict[str, object]] = None) -> Program:
    """`rooted_modules`: (import root, core.ModuleInfo) pairs."""
    program = Program(options=dict(options or {}))
    for root, info in rooted_modules:
        name = module_name_for(Path(info.path), root)
        is_init = Path(info.path).name == "__init__.py"
        node = ModuleNode(name=name, info=info, is_init=is_init)
        for stmt in info.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                node.defs[stmt.name] = stmt
        if name in program.modules:
            # two targets map to one dotted name (same-stem files from
            # different roots). Imports resolve to the first; the later
            # module still gets a distinct key so every program pass
            # scans it (a silent drop would be order-dependent).
            program.notices.append(
                f"callgraph: module name '{name}' is ambiguous "
                f"({program.modules[name].info.path} vs {info.path}); "
                f"imports resolve to the first")
            suffix = 2
            while f"{name}#{suffix}" in program.modules:
                suffix += 1
            name = f"{name}#{suffix}"
            node.name = name
        program.modules[name] = node
        program.by_path[info.path] = node
    for node in program.modules.values():
        _parse_imports(node)
    _build_edges(program)
    _propagate_jit(program)
    return program


# -- shared helpers for the program-level passes ----------------------------

def enclosing_qualnames(info) -> Dict[int, ast.AST]:
    """id(node) -> nearest enclosing FunctionDef/ClassDef node, for
    passes that anchor findings with a scope-qualified context."""
    out: Dict[int, ast.AST] = {}

    def visit(parent: ast.AST, scope: Optional[ast.AST]):
        for child in ast.iter_child_nodes(parent):
            nxt = scope
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                nxt = child
            if scope is not None:
                out[id(child)] = scope
            visit(child, nxt)
    visit(info.tree, None)
    return out


def context_of(info, enclosing: Dict[int, ast.AST], node: ast.AST) -> str:
    scope = enclosing.get(id(node))
    return info.qualname(scope) if scope is not None else ""
