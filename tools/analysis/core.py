"""Framework: Finding, rule registry, suppressions, baseline, reporters.

A *rule* is an identifier + severity + documentation. A *pass* is a
function `(module: ModuleInfo) -> Iterable[Finding]`; passes register
themselves at import time (tools/analysis/passes/__init__.py imports each
pass module). The driver parses every target file once, hands the shared
`ModuleInfo` (source, AST, suppression map, lazily-built jit-context map)
to each pass, then filters the findings through inline suppressions and
the committed baseline.

Suppression syntax (checked on the finding's line and the line above):

    x = int(flag)  # csa: ignore[CSA102] -- host cast is deliberate here
    # csa: ignore[CSA401]
    def handler(state, msg): ...

Baseline (tools/analysis/baseline.json): a list of fingerprint entries,
each with a mandatory human reason. A baselined finding is reported as
suppressed, not failed — the ratchet: new code cannot add findings, and
deleting fixed entries shrinks the file monotonically.
"""
from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field, asdict
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional

# "notice" findings are still actionable (the ratchet applies — suppress
# with a justification when a flagged site is genuinely in budget); the
# tier only signals that the rule is a heuristic, not a proof.
SEVERITIES = ("error", "warning", "notice")

_SUPPRESS_RE = re.compile(r"#\s*csa:\s*ignore\[([A-Za-z0-9_*,\s]+)\]")


@dataclass(frozen=True)
class Rule:
    id: str
    summary: str
    severity: str
    hint: str = ""


RULES: Dict[str, Rule] = {}
PASSES: List[Callable] = []
PROGRAM_PASSES: List[Callable] = []


def register_rule(rule_id: str, summary: str, severity: str,
                  hint: str = "") -> Rule:
    assert severity in SEVERITIES, severity
    assert rule_id not in RULES, f"duplicate rule {rule_id}"
    rule = Rule(rule_id, summary, severity, hint)
    RULES[rule_id] = rule
    return rule


def register_pass(fn: Callable) -> Callable:
    """A per-module pass: `(ModuleInfo) -> Iterable[Finding]`."""
    PASSES.append(fn)
    return fn


def register_program_pass(fn: Callable) -> Callable:
    """A whole-program pass: `(callgraph.Program) -> Iterable[Finding]`.
    Runs once after every target module is parsed and the call-graph IR
    (cross-module jit context) is built."""
    PROGRAM_PASSES.append(fn)
    return fn


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str
    context: str = ""   # enclosing function qualname — line-stable identity

    @property
    def severity(self) -> str:
        return RULES[self.rule].severity

    @property
    def hint(self) -> str:
        return RULES[self.rule].hint

    def fingerprint(self) -> str:
        """Line-number-independent identity used by the baseline: findings
        survive unrelated edits above them but change when the enclosing
        function or the message (which names the offending code) does."""
        return f"{self.path}::{self.rule}::{self.context}::{self.message}"


@dataclass
class ModuleInfo:
    """Everything the passes need about one parsed file."""
    path: str
    source: str
    tree: ast.Module
    lines: List[str]
    # line -> set of suppressed rule ids ("*" = all)
    suppressions: Dict[int, set] = field(default_factory=dict)
    _jit_map: Optional[object] = None  # lazily-built passes_jitmap.JitMap
    _qualnames: Optional[Dict[int, str]] = None  # id(node) -> dotted name

    @property
    def jit_map(self):
        if self._jit_map is None:
            from . import jitmap
            self._jit_map = jitmap.build(self.tree)
        return self._jit_map

    def qualname(self, node: ast.AST) -> str:
        """Scope-qualified name (`Outer._install.get_total_balance`) so
        fingerprints of same-named functions in one file don't collide."""
        if self._qualnames is None:
            names: Dict[int, str] = {}

            def visit(parent: ast.AST, prefix: str):
                for child in ast.iter_child_nodes(parent):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef)):
                        q = f"{prefix}.{child.name}" if prefix else child.name
                        names[id(child)] = q
                        visit(child, q)
                    else:
                        visit(child, prefix)
            visit(self.tree, "")
            self._qualnames = names
        return self._qualnames.get(id(node), getattr(node, "name", ""))

    def suppressed(self, finding: Finding) -> bool:
        for line in (finding.line, finding.line - 1):
            rules = self.suppressions.get(line)
            if rules and ("*" in rules or finding.rule in rules):
                return True
        return False


def _parse_suppressions(source: str) -> Dict[int, set]:
    out: Dict[int, set] = {}
    for i, line in enumerate(source.splitlines(), 1):
        m = _SUPPRESS_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def load_module(path: Path) -> Optional[ModuleInfo]:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError:
        return None  # tools/lint.py owns the syntax gate
    return ModuleInfo(path=str(path), source=source, tree=tree,
                      lines=source.splitlines(),
                      suppressions=_parse_suppressions(source))


def iter_py_files_rooted(targets: Iterable[str]):
    """(import root, file) pairs — the root is what callgraph computes
    dotted module names against (see callgraph.root_for_target)."""
    from .callgraph import root_for_target
    for target in targets:
        path = Path(target)
        if path.is_dir():
            root = root_for_target(path)
            for sub in sorted(path.rglob("*.py")):
                yield root, sub
        elif path.suffix == ".py":
            yield path.parent, path


# -- baseline ---------------------------------------------------------------

def load_baseline(path: Optional[str]) -> Dict[str, str]:
    """fingerprint -> reason. Missing file = empty baseline."""
    if not path or not Path(path).exists():
        return {}
    data = json.loads(Path(path).read_text())
    out = {}
    for entry in data.get("entries", []):
        out[entry["fingerprint"]] = entry.get("reason", "")
    return out


def write_baseline(path: str, findings: List[Finding],
                   prior: Optional[Dict[str, str]] = None) -> None:
    """Write the baseline for `findings`. `prior` (fingerprint -> reason)
    preserves hand-written reasons for entries that are still live —
    pass every finding that should stay accepted (actionable AND already-
    baselined), or refreshing the file would silently drop live entries."""
    prior = prior or {}
    seen = set()
    entries = []
    for f in findings:
        fp = f.fingerprint()
        if fp in seen:
            continue
        seen.add(fp)
        entries.append({"fingerprint": fp, "rule": f.rule,
                        "reason": prior.get(fp) or "TODO: justify or fix"})
    Path(path).write_text(json.dumps(
        {"version": 1,
         "comment": "Accepted findings; every entry needs a reason. "
                    "Delete entries as the code they cover is fixed.",
         "entries": entries}, indent=2) + "\n")


# -- driver -----------------------------------------------------------------

@dataclass
class Report:
    findings: List[Finding]            # actionable (not suppressed/baselined)
    suppressed: List[Finding]          # inline-suppressed
    baselined: List[Finding]           # matched a baseline entry
    stale_baseline: List[str]          # baseline fingerprints nothing matched
    files_checked: int = 0
    notices: List[str] = field(default_factory=list)  # program-pass notes


def analyze_paths(targets: Iterable[str],
                  baseline: Optional[Dict[str, str]] = None,
                  options: Optional[Dict[str, object]] = None) -> Report:
    from . import callgraph
    baseline = baseline or {}
    actionable: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    matched = set()
    rooted = []
    for root, path in iter_py_files_rooted(targets):
        mod = load_module(path)
        if mod is not None:
            rooted.append((root, mod))
    program = callgraph.build(rooted, options)

    def classify(finding: Finding, mod: Optional[ModuleInfo]):
        if mod is not None and mod.suppressed(finding):
            suppressed.append(finding)
        elif finding.fingerprint() in baseline:
            matched.add(finding.fingerprint())
            baselined.append(finding)
        else:
            actionable.append(finding)

    for root, mod in rooted:
        for pass_fn in PASSES:
            for finding in pass_fn(mod):
                classify(finding, mod)
    by_path = {mod.path: mod for _, mod in rooted}
    for pass_fn in PROGRAM_PASSES:
        for finding in pass_fn(program):
            classify(finding, by_path.get(finding.path))
    stale = sorted(
        fp for fp in set(baseline) - matched
        # entries for rules whose pass was skipped this run (CSA8xx
        # without a reference tree) are unverifiable, not stale
        if fp.split("::")[1] not in program.skipped_rules)
    actionable.sort(key=lambda f: (f.path, f.line, f.rule))
    return Report(findings=actionable, suppressed=suppressed,
                  baselined=baselined, stale_baseline=stale,
                  files_checked=len(rooted), notices=list(program.notices))


# -- reporters --------------------------------------------------------------

def render_human(report: Report) -> str:
    out = []
    for f in report.findings:
        out.append(f"{f.path}:{f.line}: [{f.rule}] {f.severity}: {f.message}")
        if f.hint:
            out.append(f"    hint: {f.hint}")
    for fp in report.stale_baseline:
        out.append(f"baseline: stale entry (fixed? delete it): {fp}")
    for note in report.notices:
        out.append(f"notice: {note}")
    out.append(f"analysis: {report.files_checked} files, "
               f"{len(report.findings)} finding(s), "
               f"{len(report.suppressed)} suppressed, "
               f"{len(report.baselined)} baselined")
    return "\n".join(out)


def render_json(report: Report) -> str:
    def row(f: Finding):
        d = asdict(f)
        d.pop("context")
        d.update(severity=f.severity, hint=f.hint,
                 fingerprint=f.fingerprint())
        return d
    return json.dumps({
        "findings": [row(f) for f in report.findings],
        "suppressed": [row(f) for f in report.suppressed],
        "baselined": [row(f) for f in report.baselined],
        "stale_baseline": report.stale_baseline,
        "notices": report.notices,
        "files_checked": report.files_checked,
    }, indent=2)
