"""Buffer-lifetime tier: an interprocedural abstract interpreter of
device-buffer OWNERSHIP over the call-graph IR (tools/analysis/
callgraph.py), cross-checked against the real lowering facts the trace
tier extracts (`tf.aliasing_output` donation survival,
tools/analysis/trace/tracer.donated_count).

Three hazards in this repo's history were the same bug class — host
code touching a device buffer whose ownership had been given away: the
PR 3 donated-epoch callers that reused `cols` after the donating call,
the XLA:CPU deserialized-donated-executable aliasing violation (worked
around with the pinned undonated twin), and the PR 15 verdict ring
whose donated `dynamic_update_slice` must never leave a stale host
reference outstanding. The trace tier counts ops, the range tier
bounds values; this tier proves LIFETIME.

Each array-typed value carries an abstract ownership state:

  LIVE            the host handle is valid
  DONATED         passed through a donated argument position of an
                  unconditionally-donating jit — dead on every backend
  MAYBE-DONATED   same, but the donation is platform-conditional (the
                  utils/donation.platform_donated_jit idiom) — dead on
                  accelerators, alive on XLA:CPU; both worlds model as
                  "must not be read again"

states flow through calls (interprocedural summaries over module-level
defs and uniquely-named methods), returns, attribute stores/loads
(`self._ring`), tuple/pytree destructuring, and loops to fixpoint.
Donation facts come from `donate_argnums`/`donate_argnames` at jit
sites (decorator / wrapper-assign / partial forms, resolved through
the same machinery as CSA5xx), and the trace tier's donate_min
contracts distinguish "declared but dead after lowering" (inert — no
findings) from "really consumed".

  CSA1501  use-after-donate          (a read or dispatch of a value in
                                      DONATED / MAYBE-DONATED state)
  CSA1502  donated-value escape      (a donated value stored to an
                                      attribute or returned while the
                                      stale host alias remains)
  CSA1503  double-in-flight donation (one buffer passed to two async
                                      dispatches before any
                                      materialization point — the
                                      firehose overlap shape)
  CSA1504  missing CPU-undonated twin (a donate_argnums jit with no
                                      platform guard — the PR 3 caveat
                                      codified; platform_donated_jit is
                                      the blessed pattern)
  CSA1505  redundant defensive copy  (notice: a .copy()/copy=True
                                      re-upload feeding a callable the
                                      prover shows never donates)

Entry points:

  python -m tools.analysis --lifetime [--lifetime-baseline b.json]
                                      [--update-lifetime-baseline]
                                      [--no-lower] [--json out]
  make lifetime

This module registers the rule catalog only (stdlib, importable by the
no-jax lint lane for `--list-rules`); engine.py is loaded lazily by
the CLI's --lifetime path, tests, and bench.py's lifetime snapshot.
The lowering cross-check is the only part that imports jax, and it
degrades to a notice when jax is absent or `--no-lower` is passed.
"""
from ..core import register_rule

register_rule(
    "CSA1501",
    "use-after-donate: a value is read after being passed through a "
    "donated jit argument",
    "error",
    "donation kills the host handle at dispatch — rebind the name to "
    "the call's output (the `cols = out[0]` chaining idiom), read host "
    "copies BEFORE the donating call, or route through the undonated "
    "twin (utils/donation.platform_donated_jit `.undonated`)",
)
register_rule(
    "CSA1502",
    "donated-value escape: a donated buffer is stored to an attribute "
    "or returned while the stale host alias remains",
    "error",
    "an escaping stale handle outlives the function and fails at an "
    "arbitrarily distant use — rebind the attribute to the donating "
    "call's output in the same statement (the `self._ring = "
    "dispatch(..., ring, ...)` idiom) or drop the escape",
)
register_rule(
    "CSA1503",
    "double-in-flight donation: one buffer reaches two dispatches with "
    "no materialization point between",
    "error",
    "the second dispatch consumes a buffer the first may still own "
    "(the firehose overlap shape) — materialize between launches "
    "(block_until_ready / np.asarray) or give each launch its own "
    "buffer (the double-buffer rotation)",
)
register_rule(
    "CSA1504",
    "donating jit with no platform guard (missing CPU-undonated twin)",
    "warning",
    "XLA:CPU executables deserialized from the persistent compilation "
    "cache have violated donated input/output aliasing (PR 3) — "
    "construct the program through utils/donation.platform_donated_jit "
    "(the blessed guard) or gate donation on jax.default_backend()",
)
register_rule(
    "CSA1505",
    "redundant defensive copy feeding a donation-free program",
    "notice",
    "the copied buffer feeds a callable the prover shows never donates "
    "its inputs — the defensive copy is pure overhead; drop it (or "
    "suppress with the reason the copy exists)",
)

LIFETIME_RULE_IDS = ("CSA1501", "CSA1502", "CSA1503", "CSA1504",
                     "CSA1505")
