"""Buffer-lifetime engine: interprocedural donation/aliasing prover.

Pipeline (see the package docstring for the rule catalog):

  1. parse the default target set (the runtime package + the
     donation-bearing entry points) into the call-graph IR
     (callgraph.build — same modules, dotted names and import
     resolution the CSA5xx jit-taint pass uses);
  2. discover DONORS — callables that consume (donate) some of their
     arguments: decorated jits, wrapper-assign jits, partial forms,
     `platform_donated_jit` helper instances and their `.donated` /
     `.undonated` / `.resolve()` projections, all resolved across
     module boundaries through from-imports and module aliases;
  3. fixpoint two interprocedural summary maps over every module-level
     def and class method: CALL summaries ("calling f donates its arg
     k") and RETURN summaries ("f() returns a donor with signature
     s"), so `guarded_dispatch(key, _epoch_transition_jit(), cfg,
     cols, ...)` resolves through both the wrapper shift and the
     factory return;
  4. cross-check against REAL lowerings: the trace tier's donate_min
     contracts are lowered and `tf.aliasing_output` annotations
     counted (trace/tracer.donated_count) — a donor whose donation
     was dropped by lowering is INERT (declared but dead: a notice,
     never a finding);
  5. run a path-based abstract interpreter over every function body:
     paths ("cols", "cols.balance", "self._ring", "levels[0]",
     non-constant subscripts widened to "[*]") carry LIVE / DONATED /
     MAYBE-DONATED states through assignments (may-alias edges),
     branches (joined), loops (re-executed to a second pass over the
     joined state, so cross-iteration hazards surface), donor calls,
     dispatch wrappers (`watchdog.dispatch` / `guarded_dispatch`
     shift donated positions by their two leading host args), tuple
     destructuring and attribute stores.

The dispatch-wrapper convention and the rebind idioms this engine
exonerates are exactly the house style: `cols = out[0]` chaining,
`self._ring = dispatch(..., ring, ...)` same-statement rebind, and
handing ownership to the caller via `return dispatch(...)`.
"""
from __future__ import annotations

import ast
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .. import callgraph
from ..core import (Finding, RULES, iter_py_files_rooted, load_baseline,
                    load_module)
from ..jitmap import _const_ints, _const_strs, _dotted, _jit_call_of

REPO_ROOT = Path(__file__).resolve().parents[3]
DEFAULT_BASELINE = Path(__file__).resolve().parents[1] / \
    "lifetime_baseline.json"

# The donation-bearing surface: the runtime package plus every entry
# point PR 3 hand-audited for donated-call reuse.
DEFAULT_TARGETS = ("consensus_specs_tpu", "bench.py", "__graft_entry__.py",
                   "tools/tpu_followup.py", "tests/test_multichip.py")

# Dispatch wrappers that forward `fn(*args)` after two host-side
# leading arguments (key, fn): telemetry.watchdog.dispatch and
# resilience.guarded_dispatch.
_WRAPPER_NAMES = {"dispatch", "guarded_dispatch"}
_WRAPPER_SHIFT = 2

_HELPER_NAMES = {"platform_donated_jit", "PlatformDonatedJit"}


# ---------------------------------------------------------------------------
# Donation signatures
# ---------------------------------------------------------------------------

@dataclass
class DSig:
    """What calling a value donates: arg position / kwarg name ->
    flavor ("always" | "cond"). `src`/`line` anchor messages at the
    donating program's declaration."""
    pos: Dict[int, str] = field(default_factory=dict)
    names: Dict[str, str] = field(default_factory=dict)
    src: str = ""
    line: int = 0
    fn_name: str = ""     # wrapped traced fn, for the lowering match
    module: str = ""
    inert: bool = False   # lowering dropped the donation

    def live(self) -> bool:
        return (not self.inert) and bool(self.pos or self.names)


def _donate_kwargs(call: ast.Call) -> Tuple[Tuple[int, ...],
                                            Tuple[str, ...], bool]:
    """(argnums, argnames, conditional) declared on a jit-ish call.
    An IfExp donate value (`(0,) if donate else ()`) is a platform
    guard: the donation is conditional."""
    argnums: List[int] = []
    argnames: List[str] = []
    conditional = False
    for kw in call.keywords:
        if kw.arg not in ("donate_argnums", "donate_argnames"):
            continue
        value = kw.value
        if isinstance(value, ast.IfExp):
            conditional = True
            parts = [value.body, value.orelse]
        else:
            parts = [value]
        for part in parts:
            if kw.arg == "donate_argnums":
                argnums.extend(_const_ints(part))
            else:
                argnames.extend(_const_strs(part))
    return tuple(dict.fromkeys(argnums)), tuple(dict.fromkeys(argnames)), \
        conditional


def _wrapped_fn_name(expr: ast.AST) -> str:
    """The traced fn a jit/helper application wraps, by name:
    `f`, `partial(f, cfg)` -> "f"."""
    name = _dotted(expr)
    if name:
        return name.split(".")[-1]
    if isinstance(expr, ast.Call) and \
            _dotted(expr.func).split(".")[-1] == "partial" and expr.args:
        return _wrapped_fn_name(expr.args[0])
    return ""


def _sig_of_jit_application(call: ast.Call, module: str) -> Optional[DSig]:
    """DSig for `jax.jit(f, donate_argnums=...)` /
    `partial(jax.jit, donate_argnums=...)(f)` /
    `platform_donated_jit(f, donate_argnums=...)` value expressions.
    None when the application donates nothing."""
    callee = _dotted(call.func).split(".")[-1]
    carrier: Optional[ast.Call] = None
    wrapped = ""
    helper = False
    if callee in _HELPER_NAMES:
        carrier = call
        wrapped = _wrapped_fn_name(call.args[0]) if call.args else ""
        helper = True
    else:
        jc = _jit_call_of(call)
        if jc is call:           # jax.jit(f, ...) directly
            carrier = call
            wrapped = _wrapped_fn_name(call.args[0]) if call.args else ""
        elif isinstance(call.func, ast.Call):
            inner = _jit_call_of(call.func)
            if inner is not None:   # partial(jax.jit, ...)(f)
                carrier = call.func
                wrapped = _wrapped_fn_name(call.args[0]) if call.args else ""
    if carrier is None:
        return None
    argnums, argnames, conditional = _donate_kwargs(carrier)
    if not argnums and not argnames:
        return None
    flavor = "cond" if (helper or conditional) else "always"
    return DSig(pos={i: flavor for i in argnums},
                names={n: flavor for n in argnames},
                src=wrapped or "jit", line=call.lineno,
                fn_name=wrapped, module=module)


def _resig(sig: DSig, flavor: str) -> DSig:
    return DSig(pos={k: flavor for k in sig.pos},
                names={k: flavor for k in sig.names},
                src=sig.src, line=sig.line, fn_name=sig.fn_name,
                module=sig.module, inert=sig.inert)


def _ordered_stmts(fn: ast.FunctionDef) -> List[ast.stmt]:
    """Every statement of a function body in SOURCE order, descending
    into compound statements but not into nested defs/classes."""
    out: List[ast.stmt] = []

    def rec(stmts):
        for s in stmts:
            out.append(s)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(s, attr, None)
                if isinstance(sub, list):
                    rec(sub)
            for handler in getattr(s, "handlers", []):
                rec(handler.body)
    rec(fn.body)
    return out


# ---------------------------------------------------------------------------
# Whole-program donation context
# ---------------------------------------------------------------------------

class DonationContext:
    """Donor tables + interprocedural summaries over a callgraph
    Program, with the lowering facts applied."""

    def __init__(self, program: callgraph.Program,
                 facts: Optional[dict] = None):
        self.program = program
        self.facts = facts
        # module name -> local name -> DSig (calling that name donates)
        self.donors: Dict[str, Dict[str, DSig]] = {}
        # module name -> local name of a helper INSTANCE (projections
        # .donated/.undonated/.resolve() apply) -> DSig
        self.helpers: Dict[str, Dict[str, DSig]] = {}
        # raw unconditional jit applications, for CSA1504
        self.unguarded: List[Tuple[str, int, str, DSig]] = []
        # def summaries: id(FunctionDef) -> DSig (call donates args)
        self.call_summaries: Dict[int, DSig] = {}
        # def summaries: id(FunctionDef) -> DSig (return value IS a donor)
        self.return_summaries: Dict[int, DSig] = {}
        # method name -> DSig | None(ambiguous); positions exclude self
        self.method_summaries: Dict[str, Optional[DSig]] = {}
        self._discover_donors()
        self._apply_facts()
        self._fix_summaries()

    # -- donor discovery ----------------------------------------------------

    def _discover_donors(self) -> None:
        for node in self.program.modules.values():
            donors: Dict[str, DSig] = {}
            helpers: Dict[str, DSig] = {}
            # decorated defs (module-level and methods)
            for sub in ast.walk(node.info.tree):
                if not isinstance(sub, ast.FunctionDef):
                    continue
                for deco in sub.decorator_list:
                    jc = _jit_call_of(deco)
                    if jc is None or not isinstance(deco, ast.Call):
                        continue
                    argnums, argnames, conditional = _donate_kwargs(jc)
                    if not argnums and not argnames:
                        continue
                    flavor = "cond" if conditional else "always"
                    sig = DSig(pos={i: flavor for i in argnums},
                               names={n: flavor for n in argnames},
                               src=sub.name, line=sub.lineno,
                               fn_name=sub.name, module=node.name)
                    donors[sub.name] = sig
                    if not conditional:
                        self.unguarded.append(
                            (node.info.path, sub.lineno, sub.name, sig))
            # wrapper assignments anywhere in the module
            for sub in ast.walk(node.info.tree):
                if not isinstance(sub, ast.Assign) or \
                        not isinstance(sub.value, ast.Call):
                    continue
                sig = _sig_of_jit_application(sub.value, node.name)
                if sig is None:
                    continue
                callee = _dotted(sub.value.func).split(".")[-1]
                is_helper = callee in _HELPER_NAMES
                targets = [t.id for t in sub.targets
                           if isinstance(t, ast.Name)]
                for tname in targets:
                    sig2 = DSig(pos=dict(sig.pos), names=dict(sig.names),
                                src=tname, line=sub.lineno,
                                fn_name=sig.fn_name, module=node.name)
                    if is_helper:
                        helpers[tname] = sig2
                        donors[tname] = sig2   # calling the instance
                    else:
                        donors[tname] = sig2
                        if all(f == "always" for f in
                               list(sig.pos.values())
                               + list(sig.names.values())):
                            self.unguarded.append(
                                (node.info.path, sub.lineno, tname, sig2))
            # projections of helper instances: name = helper.donated
            for sub in ast.walk(node.info.tree):
                if not isinstance(sub, ast.Assign) or \
                        not isinstance(sub.value, ast.Attribute):
                    continue
                base = _dotted(sub.value.value)
                if base in helpers and sub.value.attr == "donated":
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            donors[t.id] = _resig(helpers[base], "always")
            self.donors[node.name] = donors
            self.helpers[node.name] = helpers

        # bare unconditional donate jits used as plain expressions
        # (not assigned, not decorating) still need the CSA1504 sweep
        for node in self.program.modules.values():
            covered = set()
            for s in ast.walk(node.info.tree):
                if isinstance(s, ast.Assign):
                    covered.add(id(s.value))
                    if isinstance(s.value, ast.Call):
                        # partial(jax.jit, ...)(f): the inner carrier
                        # was already attributed to the assignment
                        covered.add(id(s.value.func))
                elif isinstance(s, ast.FunctionDef):
                    for deco in s.decorator_list:
                        covered.add(id(deco))
            for sub in ast.walk(node.info.tree):
                if not isinstance(sub, ast.Call) or id(sub) in covered:
                    continue
                callee = _dotted(sub.func).split(".")[-1]
                if callee in _HELPER_NAMES:
                    continue
                sig = _sig_of_jit_application(sub, node.name)
                if sig is None:
                    continue
                if all(f == "always" for f in
                       list(sig.pos.values()) + list(sig.names.values())):
                    self.unguarded.append(
                        (node.info.path, sub.lineno,
                         sig.fn_name or "jit", sig))

    def _apply_facts(self) -> None:
        """Mark donors whose donation the REAL lowering dropped as
        inert: declared but dead (notice-only, never a finding)."""
        if not self.facts:
            return
        by_name = {k[1]: v for k, v in self.facts.items()}
        for donors in self.donors.values():
            for sig in donors.values():
                fact = self.facts.get((sig.module, sig.fn_name)) \
                    or by_name.get(sig.fn_name)
                if fact is not None and fact.get("survived") == 0:
                    sig.inert = True

    # -- value-level donor resolution ---------------------------------------

    def _module_donor(self, node: callgraph.ModuleNode,
                      name: str) -> Optional[DSig]:
        """DSig for a bare name in `node`: a local donor, a
        from-imported donor, or a def with a call summary."""
        sig = self.donors.get(node.name, {}).get(name)
        if sig is not None:
            return sig
        fi = node.from_imports.get(name)
        if fi is not None:
            src, remote = fi
            sig = self.donors.get(src, {}).get(remote)
            if sig is not None:
                return sig
            src_mod = self.program.modules.get(src)
            if src_mod is not None and remote in src_mod.defs:
                return self.call_summaries.get(
                    id(src_mod.defs[remote]))
        if name in node.defs:
            return self.call_summaries.get(id(node.defs[name]))
        return None

    def _helper_of(self, node: callgraph.ModuleNode,
                   name: str) -> Optional[DSig]:
        sig = self.helpers.get(node.name, {}).get(name)
        if sig is not None:
            return sig
        fi = node.from_imports.get(name)
        if fi is not None:
            return self.helpers.get(fi[0], {}).get(fi[1])
        return None

    def callable_sig(self, node: callgraph.ModuleNode, expr: ast.AST,
                     env: Optional[Dict[str, DSig]] = None
                     ) -> Optional[DSig]:
        """The donation signature of a VALUE used as a callable:
        donor names (local/imported), helper projections, jit
        applications, factory-call returns, defs with call summaries,
        uniquely-named methods."""
        env = env or {}
        if isinstance(expr, ast.Name):
            if expr.id in env:
                return env[expr.id]
            return self._module_donor(node, expr.id)
        if isinstance(expr, ast.Attribute):
            base = _dotted(expr.value)
            # helper projection: pd.donated / pd.undonated
            helper = env.get(base) if base in env else \
                self._helper_of(node, base)
            if helper is not None:
                if expr.attr == "donated":
                    return _resig(helper, "always")
                if expr.attr == "undonated":
                    return None
            target = callgraph.resolve_module(node, base, self.program) \
                if base else None
            if target is not None:
                sig = self.donors.get(target.name, {}).get(expr.attr)
                if sig is not None:
                    return sig
                if expr.attr in target.defs:
                    return self.call_summaries.get(
                        id(target.defs[expr.attr]))
                return None
            # method by unique name (self.m / obj.m)
            return self.method_summaries.get(expr.attr) or None
        if isinstance(expr, ast.Call):
            # jit application used inline
            sig = _sig_of_jit_application(expr, node.name)
            if sig is not None:
                return sig
            # pd.resolve() — the backend-selected twin (conditional)
            if isinstance(expr.func, ast.Attribute) and \
                    expr.func.attr == "resolve":
                base = _dotted(expr.func.value)
                helper = env.get(base) if base in env else \
                    self._helper_of(node, base)
                if helper is not None:
                    return helper
            # factory call: f() returns a donor
            return self.returned_sig(node, expr, env)
        return None

    def returned_sig(self, node: callgraph.ModuleNode, call: ast.Call,
                     env: Optional[Dict[str, DSig]] = None
                     ) -> Optional[DSig]:
        """DSig of a CALL's return value, when the callee is a factory
        whose return summary says it hands back a donor
        (`_epoch_transition_jit()`, `_ring_scatter_jit()`)."""
        resolved = callgraph.resolve_call(node, call, self.program)
        if resolved is None or resolved[1] is None:
            return None
        return self.return_summaries.get(id(resolved[1]))

    def call_donations(self, node: callgraph.ModuleNode, call: ast.Call,
                       env: Optional[Dict[str, DSig]] = None
                       ) -> Tuple[Optional[DSig], Dict[int, str],
                                  Dict[str, str], bool]:
        """(sig, donated arg positions -> flavor, donated kwarg names
        -> flavor, via_dispatch_wrapper) for one call site. Positions
        index `call.args` (wrapper shift applied)."""
        env = env or {}
        func = call.func
        last = _dotted(func).split(".")[-1]
        if last in _WRAPPER_NAMES and len(call.args) >= 2:
            inner = self.callable_sig(node, call.args[1], env)
            if inner is None or not inner.live():
                return inner, {}, {}, True
            pos = {p + _WRAPPER_SHIFT: f for p, f in inner.pos.items()}
            return inner, pos, dict(inner.names), True
        sig = self.callable_sig(node, func, env)
        if sig is None or not sig.live():
            return sig, {}, {}, False
        return sig, dict(sig.pos), dict(sig.names), False

    # -- interprocedural summaries ------------------------------------------

    def _scan_def(self, node: callgraph.ModuleNode, fn: ast.FunctionDef,
                  is_method: bool) -> Tuple[Optional[DSig],
                                            Optional[DSig]]:
        """(call summary, return summary) for one def: a SOURCE-ORDER
        statement walk maintaining a local donor env — enough to see
        through `pd = platform_donated_jit(...); fn = pd.resolve();
        guarded_dispatch(key, fn, cols, ...)`."""
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        if is_method and params and params[0] == "self":
            params = params[1:]
        env: Dict[str, DSig] = {}
        call_sig: Optional[DSig] = None
        ret_sig: Optional[DSig] = None
        for stmt in _ordered_stmts(fn):
            for call in ast.walk(stmt):
                if not isinstance(call, ast.Call):
                    continue
                sig, pos, names, _ = \
                    self.call_donations(node, call, env)
                if not pos and not names:
                    continue
                for p, flavor in pos.items():
                    if p < len(call.args) and \
                            isinstance(call.args[p], ast.Name):
                        pname = call.args[p].id
                        if pname in params:
                            if call_sig is None:
                                call_sig = DSig(src=sig.src,
                                                line=sig.line,
                                                fn_name=sig.fn_name,
                                                module=node.name)
                            call_sig.pos[params.index(pname)] = flavor
                for kwname, flavor in names.items():
                    for kw in call.keywords:
                        if kw.arg == kwname and \
                                isinstance(kw.value, ast.Name) and \
                                kw.value.id in params:
                            if call_sig is None:
                                call_sig = DSig(src=sig.src,
                                                line=sig.line,
                                                fn_name=sig.fn_name,
                                                module=node.name)
                            call_sig.pos[
                                params.index(kw.value.id)] = flavor
            if isinstance(stmt, ast.Assign):
                value_sig = self.callable_sig(node, stmt.value, env) \
                    if isinstance(stmt.value,
                                  (ast.Call, ast.Attribute, ast.Name)) \
                    else None
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        if value_sig is not None and value_sig.live():
                            env[t.id] = value_sig
                        else:
                            env.pop(t.id, None)
            elif isinstance(stmt, ast.Return) and stmt.value is not None:
                rs = None
                if isinstance(stmt.value,
                              (ast.Name, ast.Attribute, ast.Call)):
                    rs = self.callable_sig(node, stmt.value, env)
                if rs is not None and rs.live():
                    ret_sig = rs
        return call_sig, ret_sig

    def _fix_summaries(self) -> None:
        # (node, fn, is_method) worklist covering module-level defs and
        # class methods of every target module
        items: List[Tuple[callgraph.ModuleNode, ast.FunctionDef, bool]] = []
        for node in self.program.modules.values():
            for fn in node.defs.values():
                items.append((node, fn, False))
            for stmt in node.info.tree.body:
                if isinstance(stmt, ast.ClassDef):
                    for sub in stmt.body:
                        if isinstance(sub, ast.FunctionDef):
                            items.append((node, sub, True))
        for _ in range(4):       # summaries stabilize in a few rounds
            changed = False
            method_sigs: Dict[str, List[Optional[DSig]]] = {}
            for node, fn, is_method in items:
                call_sig, ret_sig = self._scan_def(node, fn, is_method)
                if call_sig is not None:
                    prev = self.call_summaries.get(id(fn))
                    if prev is None or prev.pos != call_sig.pos:
                        self.call_summaries[id(fn)] = call_sig
                        changed = True
                if ret_sig is not None and \
                        self.return_summaries.get(id(fn)) is not ret_sig:
                    if id(fn) not in self.return_summaries:
                        changed = True
                    self.return_summaries[id(fn)] = ret_sig
                if is_method:
                    method_sigs.setdefault(fn.name, []).append(
                        self.call_summaries.get(id(fn)))
            # a method summary applies only when every same-named
            # method agrees (otherwise attribute dispatch is ambiguous)
            self.method_summaries = {}
            for name, sigs in method_sigs.items():
                live = [s for s in sigs if s is not None]
                if len(live) == len(sigs) and live and \
                        all(s.pos == live[0].pos for s in live):
                    self.method_summaries[name] = live[0]
            if not changed:
                break


# ---------------------------------------------------------------------------
# Lowering cross-check
# ---------------------------------------------------------------------------

def lowering_facts() -> Tuple[Optional[dict], List[str]]:
    """Lower every trace contract that pins donate_min and count the
    `tf.aliasing_output` annotations that actually survived; keyed by
    (traced fn's module, fn name). Returns (facts | None, notices) —
    None when jax is unavailable (the prover then trusts declarations,
    which is the conservative direction)."""
    notices: List[str] = []
    try:
        from ..trace.engine import ensure_cpu_devices
        ensure_cpu_devices(8)
        import jax
    except ImportError:
        return None, ["lifetime: jax unavailable — lowering cross-check "
                      "skipped, declared donations trusted"]
    from ..trace import engine as tengine
    from ..trace import tracer
    facts: dict = {}
    for contract in tengine.discover():
        if not contract.get("donate_min"):
            continue
        try:
            spec = contract["build"]()
            fn = spec["fn"]
            text = jax.jit(fn, **dict(spec.get("jit_kwargs", {}))) \
                .lower(*spec["args"]).as_text()
        except Exception as exc:
            notices.append(f"lifetime: contract {contract['name']} failed "
                           f"to lower ({type(exc).__name__}: {exc}); "
                           f"its donor stays effective")
            continue
        survived = tracer.donated_count(text)
        facts[(fn.__module__, fn.__name__)] = {
            "contract": contract["name"],
            "declared": int(contract["donate_min"]),
            "survived": survived,
        }
        if survived == 0:
            notices.append(
                f"lifetime: {contract['name']} declares donation but "
                f"lowering dropped every tf.aliasing_output — donor "
                f"treated as inert")
    return facts, notices


# ---------------------------------------------------------------------------
# Abstract interpreter
# ---------------------------------------------------------------------------

def _segments(path: str) -> List[str]:
    """"self.levels[0]" -> ["self", ".levels", "[0]"]."""
    segs: List[str] = []
    cur = ""
    for ch in path:
        if ch in ".[":
            if cur:
                segs.append(cur)
            cur = ch
        elif ch == "]":
            segs.append(cur + "]")
            cur = ""
        else:
            cur += ch
    if cur:
        segs.append(cur)
    return segs


def _seg_match(a: str, b: str) -> bool:
    if a == b:
        return True
    wild = a.endswith("[*]") or b.endswith("[*]")
    return wild and a.startswith("[") and b.startswith("[")


def _covers(donated: str, read: str) -> bool:
    """True when `donated` being dead makes reading `read` unsafe:
    equal paths, or `donated` is a (wildcard-compatible) prefix of
    `read` (donating `cols` kills `cols.balance`; donating
    `levels[*]` kills `levels[0]`)."""
    d, r = _segments(donated), _segments(read)
    if len(d) > len(r):
        return False
    return all(_seg_match(x, y) for x, y in zip(d, r))


@dataclass
class Donation:
    flavor: str          # "always" | "cond"
    src: str             # donating program display name
    line: int            # donation site line
    via_dispatch: bool   # launched through an async dispatch wrapper
    token: int           # unique id, ties aliases of one donation


class AbsState:
    def __init__(self):
        self.donated: Dict[str, Donation] = {}
        self.edges: Dict[str, Set[str]] = {}
        # attribute-rooted donations awaiting a rebind (escape check):
        # token -> (path, Donation)
        self.pending: Dict[int, Tuple[str, Donation]] = {}
        # roots whose attribute paths outlive the frame (self + params);
        # set once by FunctionProver.run, shared by copies
        self.escape_roots: Set[str] = {"self"}

    def copy(self) -> "AbsState":
        s = AbsState()
        s.donated = dict(self.donated)
        s.edges = {k: set(v) for k, v in self.edges.items()}
        s.pending = dict(self.pending)
        s.escape_roots = self.escape_roots
        return s

    def replace(self, other: "AbsState") -> None:
        """Adopt `other`'s facts wholesale (a branch superseded us)."""
        self.donated = dict(other.donated)
        self.edges = {k: set(v) for k, v in other.edges.items()}
        self.pending = dict(other.pending)

    def drop_conditional(self) -> None:
        """A terminating platform-guarded branch absolved this path:
        platform-conditional (MAYBE-DONATED) buffers are alive here —
        the donating world raised/returned out."""
        for p in [p for p, d in self.donated.items()
                  if d.flavor == "cond"]:
            del self.donated[p]
        for tok in [t for t, (_, d) in self.pending.items()
                    if d.flavor == "cond"]:
            del self.pending[tok]

    def join(self, other: "AbsState") -> None:
        self.donated.update(
            {k: v for k, v in other.donated.items()
             if k not in self.donated})
        for k, v in other.edges.items():
            self.edges.setdefault(k, set()).update(v)
        self.pending.update(other.pending)

    def alias(self, a: str, b: str) -> None:
        if a == b:
            return
        self.edges.setdefault(a, set()).add(b)
        self.edges.setdefault(b, set()).add(a)

    def closure(self, path: str) -> Set[str]:
        out = {path}
        work = [path]
        while work:
            p = work.pop()
            for q in self.edges.get(p, ()):
                if q not in out:
                    out.add(q)
                    work.append(q)
        return out

    def dead(self, path: str) -> Optional[Donation]:
        for p in self.closure(path):
            for d, don in self.donated.items():
                if _covers(d, p):
                    return don
        return None

    def donate(self, path: str, don: Donation) -> None:
        closure = self.closure(path)
        for p in closure:
            self.donated[p] = don
        # attribute paths rooted at self/a parameter outlive the frame
        # (the stale handle is caller-visible): track them until a
        # rebind (or a return handoff) exonerates. Subscripts of LOCAL
        # names (`single[0]`) die with the frame — donating one as its
        # final use is the normal contract, not an escape.
        for p in sorted(closure):
            segs = _segments(p)
            if len(segs) > 1 and "." in p and \
                    segs[0] in self.escape_roots:
                self.pending[don.token] = (p, don)
                break

    def rebind(self, path: str) -> None:
        """Assignment to `path` kills its donated/alias facts (and any
        extension facts: rebinding `cols` clears `cols.balance`)."""
        for d in [d for d in self.donated if _covers(path, d)]:
            del self.donated[d]
        for tok in [t for t, (p, _) in self.pending.items()
                    if _covers(path, p)]:
            del self.pending[tok]
        for p in [p for p in self.edges if _covers(path, p)]:
            for q in self.edges.pop(p):
                self.edges.get(q, set()).discard(p)


def _path_of(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _path_of(node.value)
        return f"{base}.{node.attr}" if base else None
    if isinstance(node, ast.Subscript):
        base = _path_of(node.value)
        if base is None:
            return None
        idx = node.slice
        if isinstance(idx, ast.Constant) and \
                isinstance(idx.value, (int, str)):
            return f"{base}[{idx.value}]"
        return f"{base}[*]"
    return None


_COPY_ATTRS = {"copy"}
_COPY_CALLS = {"jnp.copy", "np.copy", "numpy.copy"}
_MATERIALIZE = {"block_until_ready"}

# aval metadata survives donation (jax keeps the abstract value on the
# deleted array) — reading it is always legal
_METADATA = {".shape", ".dtype", ".ndim", ".size", ".nbytes",
             ".sharding", ".aval", ".weak_type", ".itemsize"}

# attributes whose presence in a branch test marks it as a PLATFORM
# guard (the donate-on-accel / alive-on-CPU split the house idiom
# builds on): jax.default_backend(), pd.donate_now(), device.platform
_PLATFORM_ATTRS = {"default_backend", "donate_now", "platform"}


def _is_platform_test(test: ast.AST) -> bool:
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) and sub.attr in _PLATFORM_ATTRS:
            return True
    return False


def _is_copy_expr(node: ast.AST) -> Optional[ast.AST]:
    """The copied source expression when `node` is a defensive copy:
    x.copy(), jnp.copy(x), jnp.array(x, copy=True), np.array(x,
    copy=True)."""
    if not isinstance(node, ast.Call):
        return None
    dotted = _dotted(node.func)
    if isinstance(node.func, ast.Attribute) and \
            node.func.attr in _COPY_ATTRS and not node.args:
        return node.func.value
    if dotted in _COPY_CALLS and node.args:
        return node.args[0]
    if dotted.split(".")[-1] in ("array", "asarray") and node.args:
        for kw in node.keywords:
            if kw.arg == "copy" and \
                    isinstance(kw.value, ast.Constant) and \
                    kw.value.value is True:
                return node.args[0]
    return None


class FunctionProver:
    """Path-based abstract interpretation of one function body."""

    def __init__(self, ctx: DonationContext, node: callgraph.ModuleNode,
                 fn: ast.FunctionDef, qualname: str, emit):
        self.ctx = ctx
        self.node = node
        self.fn = fn
        self.qualname = qualname
        self.emit = emit            # (rule, line, message) -> None
        self.env: Dict[str, DSig] = {}   # local donor-valued names
        self._token = iter(range(1, 1 << 30))

    def run(self) -> None:
        state = AbsState()
        args = self.fn.args
        state.escape_roots = {"self"} | {
            a.arg for a in args.posonlyargs + args.args + args.kwonlyargs}
        self._block(self.fn.body, state)
        for path, don in state.pending.values():
            self.emit("CSA1502", don.line,
                      f"donated `{path}` (to `{don.src}`) is never "
                      f"rebound in `{self.qualname}` — the stale "
                      f"handle escapes through the attribute")

    # -- statements ---------------------------------------------------------

    def _block(self, stmts: Iterable[ast.stmt],
               state: AbsState) -> bool:
        """Interpret a statement list; True when the block TERMINATES
        (return/raise/break/continue) — its state never falls through,
        so loop second passes and branch joins must not absorb it."""
        for stmt in stmts:
            if self._stmt(stmt, state):
                return True
        return False

    def _stmt(self, stmt: ast.stmt, state: AbsState) -> bool:
        if isinstance(stmt, ast.Assign):
            self._assign(stmt.targets, stmt.value, state)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._assign([stmt.target], stmt.value, state)
        elif isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, state)
            path = _path_of(stmt.target)
            if path is not None:
                self._check_read(path, stmt.target.lineno, state)
                state.rebind(path)
        elif isinstance(stmt, ast.Expr):
            self._expr(stmt.value, state)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr(stmt.value, state, returning=True)
            return True
        elif isinstance(stmt, (ast.Break, ast.Continue)):
            return True
        elif isinstance(stmt, ast.If):
            self._expr(stmt.test, state)
            s_else = state.copy()
            t_body = self._block(stmt.body, state)
            t_else = self._block(stmt.orelse, s_else)
            if t_body and t_else:
                return True
            guard = _is_platform_test(stmt.test)
            if t_body:
                # only the else path survives; if the terminated branch
                # was a platform guard (`if backend != "cpu": raise`),
                # the survivors are the world where conditional
                # donations never happened — the PR 3 recovery idiom
                state.replace(s_else)
                if guard:
                    state.drop_conditional()
            elif t_else:
                if guard:
                    state.drop_conditional()
            else:
                state.join(s_else)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, state)
            tpath = _path_of(stmt.target)
            before = state.copy()
            if tpath is not None:
                state.rebind(tpath)
            t1 = self._block(stmt.body, state)
            state.join(before)
            # second pass over the joined state surfaces
            # cross-iteration hazards (findings dedup upstream);
            # a terminated first pass never reaches iteration two
            if not t1:
                if tpath is not None:
                    state.rebind(tpath)
                self._block(stmt.body, state)
            self._block(stmt.orelse, state)
            state.join(before)
        elif isinstance(stmt, ast.While):
            self._expr(stmt.test, state)
            before = state.copy()
            t1 = self._block(stmt.body, state)
            state.join(before)
            if not t1:
                self._expr(stmt.test, state)
                self._block(stmt.body, state)
            self._block(stmt.orelse, state)
            state.join(before)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr, state)
                if item.optional_vars is not None:
                    p = _path_of(item.optional_vars)
                    if p is not None:
                        state.rebind(p)
            return self._block(stmt.body, state)
        elif isinstance(stmt, ast.Try):
            t_body = self._block(stmt.body, state)
            # handlers see the post-body state: an exception raised
            # DURING a donating dispatch consumed the buffers just as
            # surely as success did (resident.py's recovery comment)
            h_terms = [self._block(h.body, state)
                       for h in stmt.handlers]
            if not t_body:
                t_body = self._block(stmt.orelse, state)
            if self._block(stmt.finalbody, state):
                return True
            return t_body and bool(h_terms) and all(h_terms) or \
                (t_body and not stmt.handlers)
        elif isinstance(stmt, ast.Raise):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child, state)
            return True
        elif isinstance(stmt, ast.Assert):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._expr(child, state)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                p = _path_of(t)
                if p is not None:
                    state.rebind(p)
        # nested defs / classes / imports: out of scope (documented)
        return False

    def _assign(self, targets: List[ast.AST], value: ast.AST,
                state: AbsState) -> None:
        # donor-valued locals: fn = _epoch_transition_jit() / pd.resolve()
        vsig = None
        if isinstance(value, (ast.Call, ast.Attribute, ast.Name)):
            vsig = self.ctx.callable_sig(self.node, value, self.env)
        self._expr(value, state)
        vpath = _path_of(value)
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                for i, elt in enumerate(t.elts):
                    p = _path_of(elt)
                    if p is None:
                        continue
                    state.rebind(p)
                    if vpath is not None:
                        state.alias(p, f"{vpath}[{i}]")
                continue
            p = _path_of(t)
            if p is None:
                continue
            state.rebind(p)
            if isinstance(t, ast.Name):
                if vsig is not None and vsig.live():
                    self.env[t.id] = vsig
                else:
                    self.env.pop(t.id, None)
            if vpath is not None:
                state.alias(p, vpath)

    # -- expressions --------------------------------------------------------

    def _check_read(self, path: str, line: int, state: AbsState,
                    returning: bool = False,
                    dispatching: bool = False) -> None:
        if any(seg in _METADATA for seg in _segments(path)):
            return   # .shape/.dtype/... stay readable on a dead array
        don = state.dead(path)
        if don is None:
            return
        flavor = "dead on every backend" if don.flavor == "always" else \
            "dead on accelerator backends (platform-conditional donation)"
        if returning:
            self.emit("CSA1502", line,
                      f"`{path}` escapes `{self.qualname}` after being "
                      f"donated to `{don.src}` (line {don.line}) — "
                      f"the caller receives a {flavor} handle")
        elif dispatching and don.via_dispatch:
            self.emit("CSA1503", line,
                      f"`{path}` is already in flight (donated to "
                      f"`{don.src}` at line {don.line}) and reaches a "
                      f"second dispatch with no materialization point "
                      f"between")
        else:
            self.emit("CSA1501", line,
                      f"`{path}` used after donation to `{don.src}` "
                      f"(line {don.line}) — the buffer is {flavor}")

    def _expr(self, node: ast.AST, state: AbsState,
              returning: bool = False) -> None:
        if isinstance(node, ast.Call):
            self._call(node, state, returning)
            return
        path = _path_of(node)
        if path is not None:
            self._check_read(path, node.lineno, state,
                             returning=returning)
            return
        if isinstance(node, (ast.Tuple, ast.List)):
            for elt in node.elts:
                self._expr(elt, state, returning=returning)
            return
        if isinstance(node, ast.IfExp):
            self._expr(node.test, state)
            self._expr(node.body, state, returning=returning)
            self._expr(node.orelse, state, returning=returning)
            return
        if isinstance(node, ast.Lambda):
            # a separate scope whose body runs at CALL time (usually
            # under trace) — its params must not shadow-donate ours
            return
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            for gen in node.generators:
                self._expr(gen.iter, state)
            tmp = state.copy()   # comp targets live in their own scope
            for gen in node.generators:
                p = _path_of(gen.target)
                if p is not None:
                    tmp.rebind(p)
                for cond in gen.ifs:
                    self._expr(cond, tmp)
            parts = (node.key, node.value) \
                if isinstance(node, ast.DictComp) else (node.elt,)
            for part in parts:
                self._expr(part, tmp)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, state)

    def _call(self, call: ast.Call, state: AbsState,
              returning: bool = False) -> None:
        sig, pos, names, via_wrapper = \
            self.ctx.call_donations(self.node, call, self.env)
        dotted = _dotted(call.func)
        attr = dotted.split(".")[-1]
        # the callee expression itself may read state (self.f(...)):
        # attribute bases are reads only when themselves donated
        fpath = _path_of(call.func.value) \
            if isinstance(call.func, ast.Attribute) else None
        if fpath is not None:
            self._check_read(fpath, call.lineno, state)
        donated_args: List[Tuple[str, str]] = []
        for i, arg in enumerate(call.args):
            apath = _path_of(arg)
            flavor = pos.get(i)
            if flavor is not None and sig is not None:
                if apath is not None:
                    self._check_read(apath, arg.lineno, state,
                                     dispatching=True)
                    donated_args.append((apath, flavor))
                else:
                    self._expr(arg, state)
            elif apath is not None:
                self._check_read(apath, arg.lineno, state,
                                 dispatching=via_wrapper)
                self._copy_check(arg, sig, state)
            else:
                self._expr(arg, state)
                self._copy_check(arg, sig, state)
        for kw in call.keywords:
            kpath = _path_of(kw.value)
            flavor = names.get(kw.arg) if kw.arg else None
            if flavor is not None and sig is not None and \
                    kpath is not None:
                self._check_read(kpath, kw.value.lineno, state,
                                 dispatching=True)
                donated_args.append((kpath, flavor))
            elif kpath is not None:
                self._check_read(kpath, kw.value.lineno, state)
            else:
                self._expr(kw.value, state)
        # materialization fences clear the in-flight marker
        if attr in _MATERIALIZE:
            for don in state.donated.values():
                don.via_dispatch = False
        # apply the donations AFTER every argument was read live
        for apath, flavor in donated_args:
            don = Donation(flavor=flavor, src=sig.src or attr,
                           line=call.lineno, via_dispatch=via_wrapper,
                           token=next(self._token))
            if returning and ("." in apath or "[" in apath):
                # `return dispatch(..., self.cols, ...)`: ownership is
                # handed to the caller (who rebinds) — the documented
                # chaining convention, not an escape
                state.donate(apath, don)
                state.pending.pop(don.token, None)
            else:
                state.donate(apath, don)

    def _copy_check(self, arg: ast.AST, sig: Optional[DSig],
                    state: AbsState) -> None:
        """CSA1505: a defensive copy feeding a NON-donated position of
        a resolved program whose donation signature we know."""
        src = _is_copy_expr(arg)
        if src is None or sig is None:
            return
        spath = _path_of(src)
        if spath is None:
            return
        self.emit("CSA1505", arg.lineno,
                  f"defensive copy of `{spath}` feeds `{sig.src}`, "
                  f"which never consumes this argument — the copy is "
                  f"pure overhead")


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

@dataclass
class LifetimeReport:
    findings: List[Finding]
    suppressed: List[Finding]
    baselined: List[Finding]
    stale_baseline: List[str]
    notices: List[str]
    files_checked: int = 0
    donors: int = 0
    facts: Optional[dict] = None


def _rel(path: str) -> str:
    try:
        return str(Path(path).resolve().relative_to(REPO_ROOT))
    except ValueError:
        return path


def run_lifetime(targets: Optional[Iterable[str]] = None,
                 baseline: Optional[Dict[str, str]] = None,
                 baseline_path=None, lower: bool = True
                 ) -> LifetimeReport:
    if targets is None:
        targets = [str(REPO_ROOT / t) for t in DEFAULT_TARGETS
                   if (REPO_ROOT / t).exists()]
    if baseline is None:
        baseline = load_baseline(
            str(baseline_path or DEFAULT_BASELINE))
    rooted = []
    for root, path in iter_py_files_rooted([str(t) for t in targets]):
        mod = load_module(path)
        if mod is not None:
            rooted.append((root, mod))
    program = callgraph.build(rooted, {})

    notices: List[str] = []
    facts: Optional[dict] = None
    if lower:
        facts, fact_notices = lowering_facts()
        notices.extend(fact_notices)
    else:
        notices.append("lifetime: lowering cross-check disabled "
                       "(--no-lower) — declared donations trusted")
    ctx = DonationContext(program, facts)

    raw: List[Finding] = []
    seen_keys: Set[Tuple[str, str, int, str]] = set()

    for node in program.modules.values():
        def emit_for(qualname: str):
            def emit(rule: str, line: int, message: str) -> None:
                key = (node.info.path, rule, line, message)
                if key in seen_keys:
                    return
                seen_keys.add(key)
                raw.append(Finding(rule, _rel(node.info.path), line,
                                   message, context=qualname))
            return emit

        fns: List[Tuple[ast.FunctionDef, str]] = []
        for fn in node.defs.values():
            fns.append((fn, node.info.qualname(fn)))
        for stmt in node.info.tree.body:
            if isinstance(stmt, ast.ClassDef):
                for sub in stmt.body:
                    if isinstance(sub, ast.FunctionDef):
                        fns.append((sub, node.info.qualname(sub)))
        for fn, qualname in fns:
            FunctionProver(ctx, node, fn, qualname,
                           emit_for(qualname)).run()

    # CSA1504: unconditional donate jits outside the blessed helper
    by_path = {mod.path: mod for _, mod in rooted}
    for path, line, name, sig in ctx.unguarded:
        nums = sorted(sig.pos)
        argnames = sorted(sig.names)
        detail = f"donate_argnums={tuple(nums)}" if nums else \
            f"donate_argnames={tuple(argnames)}"
        raw.append(Finding("CSA1504", _rel(path), line,
                           f"`{name}` donates ({detail}) with no "
                           f"platform guard — XLA:CPU needs the "
                           f"undonated twin "
                           f"(utils.donation.platform_donated_jit)",
                           context=name))

    # donation declared but dead after lowering — visibility only
    if facts:
        for (mod_name, fn_name), fact in sorted(facts.items()):
            if fact["survived"] == 0:
                notices.append(
                    f"lifetime: {mod_name}.{fn_name} — donation "
                    f"declared but dropped by lowering (contract "
                    f"{fact['contract']})")

    # classify through inline suppressions and the baseline ratchet
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    matched: Set[str] = set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        mod = by_path.get(str(REPO_ROOT / f.path)) or by_path.get(f.path)
        if mod is not None and mod.suppressed(f):
            suppressed.append(f)
        elif f.fingerprint() in baseline:
            matched.add(f.fingerprint())
            baselined.append(f)
        else:
            findings.append(f)
    stale = sorted(set(baseline) - matched)
    donors = sum(len(d) for d in ctx.donors.values())
    return LifetimeReport(findings=findings, suppressed=suppressed,
                          baselined=baselined, stale_baseline=stale,
                          notices=notices, files_checked=len(rooted),
                          donors=donors, facts=facts)


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------

def render_human(report: LifetimeReport) -> str:
    out = []
    for f in report.findings:
        out.append(f"{f.path}:{f.line}: [{f.rule}] "
                   f"{RULES[f.rule].severity}: {f.message}")
        if RULES[f.rule].hint:
            out.append(f"    hint: {RULES[f.rule].hint}")
    for fp in report.stale_baseline:
        out.append(f"lifetime-baseline: stale entry (fixed? delete it): "
                   f"{fp}")
    for note in report.notices:
        out.append(f"notice: {note}")
    out.append(f"lifetime: {report.files_checked} files, "
               f"{report.donors} donor(s), "
               f"{len(report.findings)} finding(s), "
               f"{len(report.suppressed)} suppressed, "
               f"{len(report.baselined)} baselined")
    return "\n".join(out)


def render_json(report: LifetimeReport) -> str:
    def row(f: Finding):
        return {"rule": f.rule, "path": f.path, "line": f.line,
                "message": f.message, "context": f.context,
                "severity": RULES[f.rule].severity,
                "fingerprint": f.fingerprint()}
    facts = None
    if report.facts is not None:
        facts = [{"module": k[0], "fn": k[1], **v}
                 for k, v in sorted(report.facts.items())]
    return json.dumps({
        "findings": [row(f) for f in report.findings],
        "suppressed": [row(f) for f in report.suppressed],
        "baselined": [row(f) for f in report.baselined],
        "stale_baseline": report.stale_baseline,
        "notices": report.notices,
        "files_checked": report.files_checked,
        "donors": report.donors,
        "lowering_facts": facts,
    }, indent=2)
