"""CLI: python -m tools.analysis <targets> [--json out] [--baseline b.json]
     python -m tools.analysis --trace [--trace-baseline b.json]
                              [--update-trace-baseline] [--json out]
     python -m tools.analysis --ranges [--ranges-baseline b.json]
                              [--update-ranges-baseline] [--json out]
     python -m tools.analysis --lifetime [--lifetime-baseline b.json]
                              [--update-lifetime-baseline] [--no-lower]
                              [--json out]
     python -m tools.analysis --memory [--memory-baseline b.json]
                              [--update-memory-baseline]
                              [--memory-filter SUBSTR] [--json out]

Exit status: 0 when every finding is inline-suppressed or baselined,
1 when actionable findings remain, 2 on usage errors. Stale baseline
entries (nothing matches them any more) are reported but do not fail the
run — they are the ratchet's cue to shrink the file.

Tiers compose: any combination of targets (the AST tier), --trace,
--ranges, --lifetime and --memory runs every selected tier in order.
With ONE
tier selected, --json keeps that tier's historical report shape; with
several, the artifact is one merged document `{"tiers": {name:
report}}` and the exit status is the WORST tier's (max), so a green
multi-tier run still means "zero actionable findings anywhere".

`--trace` selects the trace tier (tools/analysis/trace/): instead of
AST passes over source targets it traces/lowers the real jitted
programs named by the kernels' TRACE_CONTRACTS and ratchets measured
op budgets against the committed tools/analysis/trace_baseline.json.
It pins XLA:CPU with 8 virtual devices before jax initializes, so
`make contracts` runs in seconds anywhere.

`--ranges` selects the value-range tier (tools/analysis/ranges/): it
traces the programs named by the kernels' RANGE_CONTRACTS (ceiling
shapes via ShapeDtypeStruct — nothing executes) and runs the interval
abstract interpreter over the jaxprs, proving the declared limb/column
budgets and wrap semantics and ratcheting the proven intervals against
tools/analysis/ranges_baseline.json.

`--lifetime` selects the buffer-lifetime tier (tools/analysis/
lifetime/): an interprocedural abstract interpreter of device-buffer
ownership (LIVE / DONATED / MAYBE-DONATED) over the call-graph IR,
cross-checked against the donation annotations that survive the REAL
lowerings (`tf.aliasing_output`) unless --no-lower skips that jax-
touching step. Accepted findings ratchet against
tools/analysis/lifetime_baseline.json.

`--memory` selects the memory tier (tools/analysis/memory/): it traces
the programs named by the kernels' MEM_CONTRACTS at their ceiling
shapes (ShapeDtypeStruct — nothing allocates) and walks the jaxprs
with the peak-liveness interpreter, proving the declared HBM/VMEM byte
budgets, the per-shard sharding bound and the scaling orders, cross-
checking the model against compiled.memory_analysis(), and ratcheting
the modeled bytes against tools/analysis/memory_baseline.json.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional, Tuple

from . import analyze_paths, load_baseline
from .core import RULES, render_human, render_json, write_baseline


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="JAX/TPU trace-safety & spec-conformance analyzer")
    parser.add_argument("targets", nargs="*",
                        help="files or directories to analyze")
    parser.add_argument("--json", metavar="PATH",
                        help="also write a JSON report (merged across "
                             "tiers when several are selected)")
    parser.add_argument("--baseline", metavar="PATH",
                        help="baseline file of accepted findings")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite --baseline from current findings")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--reference-root", metavar="DIR",
                        help="reference pyspec tree for the CSA8xx "
                             "spec-drift pass (default: "
                             "$CSTPU_REFERENCE_ROOT or /root/reference; "
                             "the pass skips with a notice when absent)")
    parser.add_argument("--trace", action="store_true",
                        help="run the trace tier (kernel TRACE_CONTRACTS "
                             "over real jaxprs/StableHLO)")
    parser.add_argument("--trace-baseline", metavar="PATH",
                        help="trace-tier metric snapshot (default: "
                             "tools/analysis/trace_baseline.json)")
    parser.add_argument("--update-trace-baseline", action="store_true",
                        help="rewrite --trace-baseline from the measured "
                             "snapshot (implies --trace)")
    parser.add_argument("--ranges", action="store_true",
                        help="run the value-range tier (kernel "
                             "RANGE_CONTRACTS through the interval "
                             "abstract interpreter)")
    parser.add_argument("--ranges-baseline", metavar="PATH",
                        help="range-tier proven-interval snapshot "
                             "(default: tools/analysis/"
                             "ranges_baseline.json)")
    parser.add_argument("--update-ranges-baseline", action="store_true",
                        help="rewrite --ranges-baseline from the proven "
                             "snapshot (implies --ranges)")
    parser.add_argument("--lifetime", action="store_true",
                        help="run the buffer-lifetime tier (the "
                             "interprocedural donation/aliasing prover, "
                             "CSA15xx)")
    parser.add_argument("--lifetime-baseline", metavar="PATH",
                        help="lifetime-tier accepted findings (default: "
                             "tools/analysis/lifetime_baseline.json)")
    parser.add_argument("--update-lifetime-baseline", action="store_true",
                        help="rewrite --lifetime-baseline from current "
                             "findings (implies --lifetime)")
    parser.add_argument("--memory", action="store_true",
                        help="run the memory tier (kernel MEM_CONTRACTS "
                             "through the peak-liveness interpreter, "
                             "CSA16xx)")
    parser.add_argument("--memory-baseline", metavar="PATH",
                        help="memory-tier modeled-bytes snapshot "
                             "(default: tools/analysis/"
                             "memory_baseline.json)")
    parser.add_argument("--update-memory-baseline", action="store_true",
                        help="rewrite --memory-baseline from the modeled "
                             "snapshot (implies --memory)")
    parser.add_argument("--memory-filter", metavar="SUBSTR",
                        help="memory tier: only run contracts whose name "
                             "contains SUBSTR (iteration aid — the "
                             "pairing traces cost ~1 min each; stale-"
                             "baseline pruning is disabled on a "
                             "filtered run)")
    parser.add_argument("--no-lower", action="store_true",
                        help="lifetime tier: skip the jax lowering "
                             "cross-check (declared donations trusted)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES.values(), key=lambda r: r.id):
            print(f"{rule.id}  {rule.severity:7s} {rule.summary}")
        return 0

    # every selected tier runs; exit = worst tier, --json merges
    runs = []   # (tier name, exit code, json text | None)
    if args.trace or args.update_trace_baseline:
        runs.append(("trace",) + _run_trace(args))
    if args.ranges or args.update_ranges_baseline:
        runs.append(("ranges",) + _run_ranges(args))
    if args.lifetime or args.update_lifetime_baseline:
        runs.append(("lifetime",) + _run_lifetime(args))
    if args.memory or args.update_memory_baseline:
        runs.append(("memory",) + _run_memory(args))
    if args.targets:
        runs.append(("ast",) + _run_ast(args))

    if not runs:
        parser.print_usage(sys.stderr)
        return 2

    if args.json:
        path = Path(args.json)
        path.parent.mkdir(parents=True, exist_ok=True)
        if len(runs) == 1:
            text = runs[0][2]
            if text is not None:
                path.write_text(text + "\n")
        else:
            merged = {"tiers": {name: (json.loads(text)
                                       if text is not None else None)
                                for name, _, text in runs}}
            path.write_text(json.dumps(merged, indent=2) + "\n")
    return max(code for _, code, _ in runs)


def _run_ast(args) -> Tuple[int, Optional[str]]:
    options = {}
    if args.reference_root:
        options["reference_root"] = args.reference_root
    baseline = load_baseline(args.baseline)
    report = analyze_paths(args.targets, baseline, options)

    if args.update_baseline:
        if not args.baseline:
            print("--update-baseline requires --baseline", file=sys.stderr)
            return 2, None
        # keep still-live baselined findings (and their reasons) alongside
        # the new ones; only entries nothing matches any more drop out
        keep = report.findings + report.baselined
        write_baseline(args.baseline, keep, prior=baseline)
        print(f"baseline: wrote {len(keep)} entr(y|ies) to {args.baseline}")
        return 0, render_json(report)

    print(render_human(report))
    return (1 if report.findings else 0), render_json(report)


def _run_trace(args) -> Tuple[int, Optional[str]]:
    from .trace import engine
    engine.ensure_cpu_devices(8)
    baseline_path = args.trace_baseline or engine.DEFAULT_BASELINE
    report = engine.run_contracts(baseline_path=baseline_path)

    if args.update_trace_baseline:
        # keep entries for contracts this machine could not run (skipped
        # mesh contracts on an under-provisioned box keep their snapshot)
        prior = engine.load_trace_baseline(baseline_path)
        snapshot = dict(prior)
        snapshot.update(report.snapshot)
        for name in report.stale_baseline:
            snapshot.pop(name, None)
        engine.write_trace_baseline(baseline_path, snapshot)
        print(f"trace-baseline: wrote {len(snapshot)} contract(s) to "
              f"{baseline_path}")
        # a baseline refresh clears only the ratchet family (CSA1102/03/
        # 04); budget violations and hygiene findings survive it — report
        # them NOW instead of deferring the failure to the next CI run
        remaining = [f for f in report.findings
                     if f.rule not in ("CSA1102", "CSA1103", "CSA1104")]
        if remaining:
            print("trace-baseline: the refresh does NOT clear these "
                  "(fix the kernel or change its contract):")
            for f in remaining:
                print(f"{f.path}:{f.line}: [{f.rule}] "
                      f"{RULES[f.rule].severity}: {f.context}: {f.message}")
        # the refresh just cleared the ratchet family: drop it from the
        # reported findings so the JSON artifact and exit code agree
        # with the baseline that now exists on disk
        report.findings = remaining
    else:
        print(engine.render_human(report))
    return (1 if report.findings else 0), engine.render_json(report)


def _run_ranges(args) -> Tuple[int, Optional[str]]:
    from .ranges import engine
    from .trace.engine import ensure_cpu_devices
    ensure_cpu_devices(8)
    baseline_path = args.ranges_baseline or engine.DEFAULT_BASELINE
    report = engine.run_contracts(baseline_path=baseline_path)

    if args.update_ranges_baseline:
        prior = engine.load_ranges_baseline(baseline_path)
        snapshot = dict(prior)
        snapshot.update(report.snapshot)
        for name in report.stale_baseline:
            snapshot.pop(name, None)
        engine.write_ranges_baseline(baseline_path, snapshot)
        print(f"ranges-baseline: wrote {len(snapshot)} contract(s) to "
              f"{baseline_path}")
        # the refresh clears only the snapshot-drift family (CSA1404);
        # proved overflows, unprovable ops and missing invariants
        # survive it — report them NOW, not on the next CI run
        remaining = [f for f in report.findings if f.rule != "CSA1404"]
        if remaining:
            print("ranges-baseline: the refresh does NOT clear these "
                  "(fix the kernel or change its contract):")
            for f in remaining:
                print(f"{f.path}:{f.line}: [{f.rule}] "
                      f"{RULES[f.rule].severity}: {f.context}: {f.message}")
        report.findings = remaining
    else:
        print(engine.render_human(report))
    return (1 if report.findings else 0), engine.render_json(report)


def _run_memory(args) -> Tuple[int, Optional[str]]:
    from .memory import engine
    from .trace.engine import ensure_cpu_devices
    ensure_cpu_devices(8)
    baseline_path = args.memory_baseline or engine.DEFAULT_BASELINE
    contracts = None
    if args.memory_filter:
        contracts = [c for c in engine.discover()
                     if args.memory_filter in c["name"]]
        if not contracts:
            print(f"memory: no contract name contains "
                  f"{args.memory_filter!r}", file=sys.stderr)
            return 2, None
    report = engine.run_contracts(contracts=contracts,
                                  baseline_path=baseline_path)
    if args.memory_filter:
        # baseline entries outside the filter are unmatched by
        # construction, not stale — never prune or report them
        report.stale_baseline = []

    if args.update_memory_baseline:
        prior = engine.load_memory_baseline(baseline_path)
        snapshot = dict(prior)
        snapshot.update(report.snapshot)
        for name in report.stale_baseline:
            snapshot.pop(name, None)
        engine.write_memory_baseline(baseline_path, snapshot)
        print(f"memory-baseline: wrote {len(snapshot)} contract(s) to "
              f"{baseline_path}")
        # the refresh clears only the bytes-ratchet family (CSA1602);
        # budget/shard/compiled violations, scaling escapes and VMEM
        # overflows survive it — report them NOW, not on the next CI run
        remaining = [f for f in report.findings if f.rule != "CSA1602"]
        if remaining:
            print("memory-baseline: the refresh does NOT clear these "
                  "(fix the kernel or change its contract):")
            for f in remaining:
                print(f"{f.path}:{f.line}: [{f.rule}] "
                      f"{RULES[f.rule].severity}: {f.context}: {f.message}")
        report.findings = remaining
    else:
        print(engine.render_human(report))
    return (1 if report.findings else 0), engine.render_json(report)


def _run_lifetime(args) -> Tuple[int, Optional[str]]:
    from .lifetime import engine
    baseline_path = str(args.lifetime_baseline or engine.DEFAULT_BASELINE)
    baseline = load_baseline(baseline_path)
    report = engine.run_lifetime(baseline=baseline,
                                 baseline_path=baseline_path,
                                 lower=not args.no_lower)

    if args.update_lifetime_baseline:
        keep = report.findings + report.baselined
        write_baseline(baseline_path, keep, prior=baseline)
        print(f"lifetime-baseline: wrote {len(keep)} entr(y|ies) to "
              f"{baseline_path}")
        return 0, engine.render_json(report)

    print(engine.render_human(report))
    return (1 if report.findings else 0), engine.render_json(report)


if __name__ == "__main__":
    sys.exit(main())
