"""CLI: python -m tools.analysis <targets> [--json out] [--baseline b.json]

Exit status: 0 when every finding is inline-suppressed or baselined,
1 when actionable findings remain, 2 on usage errors. Stale baseline
entries (nothing matches them any more) are reported but do not fail the
run — they are the ratchet's cue to shrink the file.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path

from . import analyze_paths, load_baseline
from .core import RULES, render_human, render_json, write_baseline


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="JAX/TPU trace-safety & spec-conformance analyzer")
    parser.add_argument("targets", nargs="*",
                        help="files or directories to analyze")
    parser.add_argument("--json", metavar="PATH",
                        help="also write a JSON report")
    parser.add_argument("--baseline", metavar="PATH",
                        help="baseline file of accepted findings")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite --baseline from current findings")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--reference-root", metavar="DIR",
                        help="reference pyspec tree for the CSA8xx "
                             "spec-drift pass (default: "
                             "$CSTPU_REFERENCE_ROOT or /root/reference; "
                             "the pass skips with a notice when absent)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(RULES.values(), key=lambda r: r.id):
            print(f"{rule.id}  {rule.severity:7s} {rule.summary}")
        return 0
    if not args.targets:
        parser.print_usage(sys.stderr)
        return 2

    options = {}
    if args.reference_root:
        options["reference_root"] = args.reference_root
    baseline = load_baseline(args.baseline)
    report = analyze_paths(args.targets, baseline, options)

    if args.update_baseline:
        if not args.baseline:
            print("--update-baseline requires --baseline", file=sys.stderr)
            return 2
        # keep still-live baselined findings (and their reasons) alongside
        # the new ones; only entries nothing matches any more drop out
        keep = report.findings + report.baselined
        write_baseline(args.baseline, keep, prior=baseline)
        print(f"baseline: wrote {len(keep)} entr(y|ies) to {args.baseline}")
        return 0

    print(render_human(report))
    if args.json:
        Path(args.json).parent.mkdir(parents=True, exist_ok=True)
        Path(args.json).write_text(render_json(report) + "\n")
    return 1 if report.findings else 0


if __name__ == "__main__":
    sys.exit(main())
