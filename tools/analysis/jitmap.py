"""Shared jit-context discovery for the trace-sensitive passes.

Identifies, per module, which function definitions execute under a JAX
trace, and which of their parameters are traced (vs static). Three ways a
function enters jit context, all used in this codebase:

  decorator      @jax.jit / @jit / @partial(jax.jit, static_argnums=(0,))
  wrapper assign _f_jit = jax.jit(f)           (ops/bls_jax.py:394)
                 _g = partial(jax.jit, ...)(g) (models/phase0/epoch_soa.py:367)
  transitive     a plain def called (by name, same module) from any
                 jit-context function — the "scan callees" requirement,
                 e.g. _total_balance / _stage_a_traced in epoch_soa.py

Static parameters come from static_argnums / static_argnames on the jit
call. For transitive callees no static info exists; a parameter there is
treated as traced unless its annotation names a clearly-host type (int,
bool, bytes, str, *Config, ...) — the repo consistently annotates traced
params `jnp.ndarray`, so this keeps config plumbing out of the taint set.
Nested defs inherit jit context from their enclosing function (fori_loop /
cond / scan bodies) with all parameters traced.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# Annotations that mean "host-side value, not a tracer" on callee params.
_HOST_ANNOTATIONS = {"int", "bool", "float", "str", "bytes", "bytearray",
                     "list", "tuple", "dict", "set", "List", "Tuple",
                     "Dict", "Set", "Sequence", "Optional", "Callable"}


@dataclass
class JitFunc:
    node: ast.AST                  # FunctionDef (or Lambda) in jit context
    qualname: str
    direct: bool                   # decorated/wrapped vs transitive callee
    traced_params: Set[str] = field(default_factory=set)
    static_params: Set[str] = field(default_factory=set)
    # the jit(...) call node that created it, for static_argnums checks
    jit_call: Optional[ast.Call] = None


@dataclass
class JitMap:
    funcs: Dict[ast.AST, JitFunc] = field(default_factory=dict)
    # module-level names that resolve to a jitted callable (for call-site
    # passes): name -> the wrapped FunctionDef (or None if unresolvable)
    jitted_names: Dict[str, Optional[ast.FunctionDef]] = field(
        default_factory=dict)


def _dotted(node: ast.AST) -> str:
    """'jax.jit' for Attribute chains, 'jit' for Name, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_jit_name(dotted: str) -> bool:
    return dotted in ("jit", "jax.jit", "pjit", "jax.pjit") or \
        dotted.endswith(".jit") or dotted.endswith(".pjit")


def _jit_call_of(node: ast.AST) -> Optional[ast.Call]:
    """The Call node carrying static_argnums if `node` is a jit
    application: jax.jit, jit, partial(jax.jit, ...)."""
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func)
        if _is_jit_name(dotted):
            return node
        # partial(jax.jit, static_argnums=...) — the partial call holds
        # the kwargs; report it as the carrier
        if dotted in ("partial", "functools.partial") and node.args:
            if _is_jit_name(_dotted(node.args[0])):
                return node
    elif isinstance(node, (ast.Attribute, ast.Name)):
        if _is_jit_name(_dotted(node)):
            # bare @jax.jit decorator: no kwargs to carry
            return ast.Call(func=node, args=[], keywords=[])
    return None


def static_info(jit_call: Optional[ast.Call],
                fn: ast.FunctionDef) -> Tuple[Set[str], Set[str]]:
    """(static param names, traced param names) for a DIRECTLY jitted fn."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    static: Set[str] = set()
    if jit_call is not None:
        for kw in jit_call.keywords:
            if kw.arg == "static_argnums":
                for idx in _const_ints(kw.value):
                    if 0 <= idx < len(params):
                        static.add(params[idx])
            elif kw.arg == "static_argnames":
                static.update(_const_strs(kw.value))
    traced = {p for p in params if p not in static and p != "self"}
    return static, traced


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            out.extend(_const_ints(elt))
        return out
    return []


def _const_strs(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            out.extend(_const_strs(elt))
        return out
    return []


def _annotation_is_host(ann: Optional[ast.AST]) -> bool:
    if ann is None:
        return False
    if isinstance(ann, ast.Subscript):   # List[int], Optional[bytes], ...
        ann = ann.value
    name = _dotted(ann)
    parts = name.split(".")
    if parts[0] in ("np", "numpy"):
        # np.ndarray params are host-side trace-time constants in this
        # codebase (jnp.ndarray is the traced annotation) — e.g. the
        # static int matrices fq_tower unrolls at trace time
        return True
    base = parts[-1]
    return base in _HOST_ANNOTATIONS or base.endswith("Config")


def _callee_params(fn: ast.FunctionDef) -> Tuple[Set[str], Set[str]]:
    """(static, traced) for a transitive callee: annotation-driven."""
    static: Set[str] = set()
    traced: Set[str] = set()
    for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
        if a.arg == "self" or _annotation_is_host(a.annotation):
            static.add(a.arg)
        else:
            traced.add(a.arg)
    return static, traced


def build(tree: ast.Module) -> JitMap:
    jmap = JitMap()
    # module-level defs by name (for wrapper-assign + call-graph edges)
    defs: Dict[str, ast.FunctionDef] = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node

    # 1. decorator form
    for fn in defs.values():
        for deco in fn.decorator_list:
            jit_call = _jit_call_of(deco)
            if jit_call is not None:
                static, traced = static_info(jit_call, fn)
                jmap.funcs[fn] = JitFunc(fn, fn.name, direct=True,
                                         traced_params=traced,
                                         static_params=static,
                                         jit_call=jit_call)
                jmap.jitted_names[fn.name] = fn
                break

    # 2. wrapper-assignment form: name = jax.jit(f) / partial(jax.jit,..)(f)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        call = node.value
        wrapped: Optional[ast.AST] = None
        jit_call: Optional[ast.Call] = None
        if _is_jit_name(_dotted(call.func)) and call.args:
            wrapped, jit_call = call.args[0], call
        elif isinstance(call.func, ast.Call):
            inner = _jit_call_of(call.func)
            if inner is not None and call.args:
                wrapped, jit_call = call.args[0], inner
        if wrapped is None:
            continue
        target_names = [t.id for t in node.targets
                        if isinstance(t, ast.Name)]
        fn = defs.get(_dotted(wrapped))
        for name in target_names:
            jmap.jitted_names[name] = fn
        if fn is not None and fn not in jmap.funcs:
            static, traced = static_info(jit_call, fn)
            jmap.funcs[fn] = JitFunc(fn, fn.name, direct=True,
                                     traced_params=traced,
                                     static_params=static,
                                     jit_call=jit_call)

    # 2b. jit-factory form: a def (module-level OR nested) passed BY NAME
    # into any call whose callee mentions "jit" (utils/ssz/bulk.py's
    # memoizing `_get_root_jit(name, fn)` over a nested `both`). No static
    # info exists at that distance: annotation-driven params.
    all_defs: Dict[str, ast.FunctionDef] = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        callee_name = _dotted(node.func).split(".")[-1].lower()
        if "jit" not in callee_name:
            continue
        for arg in node.args:
            fn = all_defs.get(_dotted(arg))
            if fn is not None and fn not in jmap.funcs:
                static, traced = _callee_params(fn)
                jmap.funcs[fn] = JitFunc(fn, fn.name, direct=False,
                                         traced_params=traced,
                                         static_params=static)

    # 3. transitive callees: names called from jit-context bodies
    worklist = [jf.node for jf in jmap.funcs.values()]
    seen = set(id(n) for n in worklist)
    while worklist:
        fn = worklist.pop()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                callee = defs.get(node.func.id)
                if callee is not None and callee not in jmap.funcs:
                    static, traced = _callee_params(callee)
                    jmap.funcs[callee] = JitFunc(
                        callee, callee.name, direct=False,
                        traced_params=traced, static_params=static)
                    if id(callee) not in seen:
                        seen.add(id(callee))
                        worklist.append(callee)
    return jmap


# -- taint ------------------------------------------------------------------

# Calls whose RESULT is host-side even when arguments are traced: shape
# inspection is static during tracing.
_UNTAINT_CALLS = {"len", "range", "isinstance", "type", "id", "enumerate",
                  "zip"}
_UNTAINT_ATTRS = {"shape", "dtype", "ndim", "size", "_fields"}
# Roots whose calls produce traced values.
_TRACED_ROOTS = {"jnp", "lax"}


def _expr_names(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class Taint:
    """Flow-insensitive taint over one function body: which local names
    (can) hold traced values. Seeds from traced params; propagates through
    assignment until fixpoint. `jnp.*` / `jax.lax.*` / `jax.numpy.*` call
    results are traced; `.shape`/`.dtype`/len() are not."""

    def __init__(self, fn: ast.AST, traced_params: Set[str]):
        self.tainted: Set[str] = set(traced_params)
        body = fn.body if isinstance(
            fn, (ast.FunctionDef, ast.AsyncFunctionDef)) else [fn]
        changed = True
        while changed:
            changed = False
            for stmt in body:
                for node in ast.walk(stmt):
                    targets: List[ast.AST] = []
                    value: Optional[ast.AST] = None
                    if isinstance(node, ast.Assign):
                        targets, value = node.targets, node.value
                    elif isinstance(node, ast.AugAssign):
                        targets, value = [node.target], node.value
                    elif isinstance(node, ast.AnnAssign) and node.value:
                        targets, value = [node.target], node.value
                    elif isinstance(node, ast.NamedExpr):
                        # walrus: `(s := jnp.sum(x))` binds like an Assign
                        targets, value = [node.target], node.value
                    elif isinstance(node, (ast.For, ast.comprehension)):
                        # iterating a traced value taints the loop var
                        it = node.iter
                        tgt = node.target
                        if self.expr_tainted(it):
                            targets, value = [tgt], it
                    if value is None or not self.expr_tainted(value):
                        continue
                    for t in targets:
                        for name in _assigned_names(t):
                            if name not in self.tainted:
                                self.tainted.add(name)
                                changed = True

    def expr_tainted(self, node: ast.AST) -> bool:
        return self._tainted(node)

    def _tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _UNTAINT_ATTRS:
                return False
            return self._tainted(node.value)
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            root = fname.split(".")[0]
            if fname in _UNTAINT_CALLS or root in ("np", "numpy", "math"):
                return False
            if root in _TRACED_ROOTS or fname.startswith("jax."):
                return True
            if isinstance(node.func, ast.Attribute):
                # method call: traced iff the receiver is (covers .at[..]
                # .set/.add, .astype, .reshape, ...)
                return self._tainted(node.func.value)
            # plain-name call (helper fn): traced iff any argument is —
            # conservative for same-module numeric helpers
            return any(self._tainted(a) for a in node.args) or \
                any(self._tainted(k.value) for k in node.keywords)
        if isinstance(node, ast.Compare) and \
                all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
            # `x is None` is an object-identity check: a host bool even
            # when x holds a tracer (never calls the tracer's __bool__)
            return False
        if isinstance(node, (ast.BinOp, ast.BoolOp, ast.Compare,
                             ast.UnaryOp, ast.Subscript, ast.IfExp,
                             ast.Tuple, ast.List, ast.Starred,
                             ast.NamedExpr)):
            return any(self._tainted(c) for c in ast.iter_child_nodes(node)
                       if not isinstance(c, (ast.cmpop, ast.operator,
                                             ast.boolop, ast.unaryop,
                                             ast.expr_context)))
        return False


def _assigned_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for elt in target.elts:
            out.extend(_assigned_names(elt))
        return out
    return []   # attribute/subscript targets: not a simple name binding


def own_nodes(fn: ast.AST):
    """ast.walk over a function's OWN body, stopping at nested function
    boundaries (nested defs are yielded separately by iter_jit_functions,
    with their own Taint — descending here would double-report)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def iter_jit_functions(jmap: JitMap):
    """Yield (JitFunc, Taint) for every jit-context function, including
    nested defs (which inherit context, all params traced)."""
    for jf in jmap.funcs.values():
        taint = Taint(jf.node, jf.traced_params)
        yield jf, taint
        for node in ast.walk(jf.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not jf.node:
                params = {a.arg for a in node.args.posonlyargs
                          + node.args.args + node.args.kwonlyargs}
                nested = JitFunc(node, f"{jf.qualname}.{node.name}",
                                 direct=False, traced_params=params)
                yield nested, Taint(node, params | taint.tainted)
