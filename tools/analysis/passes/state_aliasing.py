"""CSA401 — `state` parameters a function body never consults.

The spec's method surface threads `state` through every helper; an
override or helper that ACCEPTS a state but answers from captured context
silently returns wrong data the moment a caller passes a different state
— exactly the resident-mirror bug class (models/phase0/resident.py
`_install` pre-guard: fork choice hands the JUSTIFIED state to
spec.get_active_validator_indices, and the override answered from the
head state's device mirrors). A body that never mentions `state` cannot
be distinguishing states, so it is either dead API surface or an
aliasing bug; both deserve a look.

Not flagged: stubs (docstring + pass/.../raise only) — abstract interface
conformance is the one legitimate shape.
"""
from __future__ import annotations

import ast

from ..core import Finding, register_pass, register_rule

register_rule(
    "CSA401",
    "function accepts a `state` parameter but never reads it",
    "error",
    "answer from the passed state (or delegate when `state is not` the "
    "one your captured context describes); if the parameter is pure "
    "interface conformance, suppress with a justification",
)

_PARAM = "state"


def _is_stub(fn: ast.FunctionDef) -> bool:
    body = fn.body
    if body and isinstance(body[0], ast.Expr) and \
            isinstance(body[0].value, ast.Constant) and \
            isinstance(body[0].value.value, str):
        body = body[1:]
    return all(isinstance(s, (ast.Pass, ast.Raise)) or
               (isinstance(s, ast.Expr) and
                isinstance(s.value, ast.Constant) and
                s.value.value is Ellipsis)
               for s in body)


@register_pass
def run(mod):
    findings = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params = {a.arg for a in node.args.posonlyargs + node.args.args
                  + node.args.kwonlyargs}
        if _PARAM not in params or _is_stub(node):
            continue
        used = any(isinstance(n, ast.Name) and n.id == _PARAM
                   for body_stmt in node.body
                   for n in ast.walk(body_stmt))
        if not used:
            findings.append(Finding(
                "CSA401", mod.path, node.lineno,
                f"`{node.name}` takes `state` but never reads it — "
                f"aliasing hazard if it answers from captured context",
                context=mod.qualname(node)))
    return findings
