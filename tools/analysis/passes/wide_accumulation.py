"""CSA901 — raw wide-column accumulation without an interposed carry round.

The double-width lazy-Montgomery pipeline (ops/fq.py) keeps tower
products as 2L int64 columns and reduces once per output coefficient.
Raw `fq_mul_wide` columns reach 14*2^58 < 2^62, so summing MORE THAN TWO
of them can exceed int64 (3 * 14 * 2^58 > 2^63) and wrap silently —
corrupting every pairing built on top while still producing plausible
limb arrays. The laziness contract therefore requires a value-preserving
wide carry round (`fq_wide_norm` / `_carry_rounds`) between the
schoolbook and any >2-term accumulation — including `_apply_int_matrix`
gamma combinations, whose fan-in reaches 36.

Simple per-function AST dataflow: a name assigned from
`fq_mul_wide(...)` is tainted "raw wide" (weight 1); weights add through
+/- chains and rebinding; any other call (fq_wide_norm, fq_redc, ...)
yields a fresh weight-0 value, which is how the interposed carry round
clears the taint. Flagged: an Add/Sub accumulation whose total raw-wide
weight exceeds 2, or a raw-wide value handed to an
`_apply_int_matrix`-shaped callee.

Since the value-range tier landed (tools/analysis/ranges/, CSA1401—
`make ranges`), this pass is the fast syntactic PRE-CHECK, not the
authority: the interval interpreter proves the same budget on the real
traced values. A function that a module's RANGE_CONTRACTS section
references is therefore skipped here — the proving tier owns it and
double-reporting the same accumulation in out/analysis.json would be
noise; everywhere else the notice survives as the cheap early warning
(it runs on the no-jax lint lane where the prover cannot).
"""
from __future__ import annotations

import ast

from ..core import Finding, register_pass, register_rule

register_rule(
    "CSA901",
    "fq_mul_wide columns accumulated >2 deep with no wide carry round "
    "(syntactic pre-check of the CSA1401 range proof)",
    "notice",
    "raw wide columns reach 14*2^58; interpose fq_wide_norm (a value-"
    "preserving wide carry round) before summing more than two or before "
    "any _apply_int_matrix combination — or cover the site with a "
    "RANGE_CONTRACTS entry and let `make ranges` (CSA1401) prove the "
    "budget on the real traced values",
)

_WIDE_SOURCES = ("fq_mul_wide",)
_MATRIX_CALLEES = ("_apply_int_matrix", "apply_int_matrix")


def _callee(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


class _FnScanner:
    """Statement-ordered taint walk of one function body (branch joins are
    approximated by last-write-wins — fine for a notice-level heuristic)."""

    def __init__(self, mod, fn):
        self.mod = mod
        self.fn = fn
        self.weights = {}   # name -> raw-wide term count
        self.findings = []

    def weight(self, node) -> int:
        """Raw-wide terms the expression contributes to an accumulation."""
        if isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.Add, ast.Sub)):
            return self.weight(node.left) + self.weight(node.right)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
            # scalar * wide keeps the wide side's term count
            return self.weight(node.left) + self.weight(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.weight(node.operand)
        if isinstance(node, ast.Call):
            return 1 if _callee(node) in _WIDE_SOURCES else 0
        if isinstance(node, ast.Name):
            return self.weights.get(node.id, 0)
        return 0

    def _flag_sum(self, w, lineno):
        self.findings.append(Finding(
            "CSA901", self.mod.path, lineno,
            f"accumulation of {w} raw fq_mul_wide terms with no interposed "
            f"wide carry round (int64 columns overflow beyond 2 terms); "
            f"the proving check is the CSA1401 range contract "
            f"(`make ranges`)",
            context=self.mod.qualname(self.fn)))

    def check_expr(self, node, lineno):
        w = self.weight(node)
        if w > 2:
            self._flag_sum(w, lineno)
            return
        for call in ast.walk(node):
            if isinstance(call, ast.Call) and _callee(call) in _MATRIX_CALLEES:
                if any(self.weight(arg) >= 1 for arg in call.args):
                    self.findings.append(Finding(
                        "CSA901", self.mod.path, call.lineno,
                        "_apply_int_matrix over raw fq_mul_wide columns — "
                        "interpose fq_wide_norm before the matrix "
                        "combination", context=self.mod.qualname(self.fn)))

    def run_stmts(self, body):
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue   # nested defs get their own scan
            if isinstance(stmt, ast.Assign):
                self.check_expr(stmt.value, stmt.lineno)
                # clamp the recorded weight so one over-budget site is
                # flagged once, not again at every downstream use
                w = min(self.weight(stmt.value), 2)
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        self.weights[target.id] = w
            elif isinstance(stmt, ast.AugAssign):
                self.check_expr(stmt.value, stmt.lineno)
                if isinstance(stmt.target, ast.Name) and isinstance(
                        stmt.op, (ast.Add, ast.Sub)):
                    w = (self.weights.get(stmt.target.id, 0)
                         + self.weight(stmt.value))
                    if w > 2:
                        self._flag_sum(w, stmt.lineno)
                    self.weights[stmt.target.id] = min(w, 2)
            elif isinstance(stmt, (ast.Expr, ast.Return)):
                if stmt.value is not None:
                    self.check_expr(stmt.value, stmt.lineno)
            elif isinstance(stmt, (ast.For, ast.While, ast.If)):
                self.run_stmts(stmt.body)
                self.run_stmts(stmt.orelse)
            elif isinstance(stmt, ast.With):
                self.run_stmts(stmt.body)


def _range_covered_names(mod) -> set:
    """Function names the module's RANGE_CONTRACTS registry references,
    transitively through its builder helpers — those accumulations are
    owned by the proving tier (CSA1401), so the syntactic pre-check
    stays quiet there (no double-reporting in out/analysis.json). AST
    scope, not textual: a docstring mentioning the word must not exempt
    the whole module."""
    fns = {n.name: n for n in mod.tree.body
           if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    seeds = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "RANGE_CONTRACTS"
                for t in node.targets):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name):
                    seeds.add(sub.id)
                elif isinstance(sub, ast.Attribute):
                    seeds.add(sub.attr)
    covered: set = set()
    work = [s for s in seeds if s in fns]
    while work:
        name = work.pop()
        if name in covered:
            continue
        covered.add(name)
        for sub in ast.walk(fns[name]):
            ref = sub.id if isinstance(sub, ast.Name) else (
                sub.attr if isinstance(sub, ast.Attribute) else None)
            if ref in fns and ref not in covered:
                work.append(ref)
    return covered


@register_pass
def run(mod):
    findings = []
    covered = _range_covered_names(mod)
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in covered:
            continue
        scanner = _FnScanner(mod, node)
        scanner.run_stmts(node.body)
        findings.extend(scanner.findings)
    return findings
