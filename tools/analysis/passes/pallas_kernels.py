"""CSA7xx — Pallas kernel call constraints.

A `pl.pallas_call` is a contract in three parts: the grid, the
BlockSpecs (block shape + index map), and the kernel's Ref parameters.
Nothing checks the parts against each other until Mosaic lowering on a
real TPU — and the CPU test path runs `interpret=True`, which validates
much less. These checks are pure arithmetic over the AST:

  CSA701  BlockSpec index-map arity must equal the grid rank, and the
          index tuple it returns must match the block shape's rank
  CSA702  `grid` / `block_shape` entries must be static (a traced value
          there fails at trace time on the first real-TPU run)
  CSA703  a module with pallas_call sites but no `interpret=` escape
          hatch anywhere cannot run its kernels on CPU at all — the
          fixture/test path silently loses coverage
  CSA704  a constant Ref index outside the declared block shape (or a
          subscript of higher rank than the block) reads/writes out of
          the tile the BlockSpec actually maps in
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Finding, register_pass, register_rule
from .. import jitmap
from ..callgraph import enclosing_qualnames

register_rule(
    "CSA701",
    "BlockSpec index-map arity or index rank disagrees with grid/block "
    "shape",
    "error",
    "the index map takes one argument per grid dimension and returns "
    "one block index per block_shape dimension",
)
register_rule(
    "CSA702",
    "traced value in pallas_call grid or BlockSpec block_shape",
    "error",
    "grid and block shapes are compile-time constants; derive them from "
    "`.shape` (static under trace) or pass them as static_argnums",
)
register_rule(
    "CSA703",
    "pallas_call sites with no interpret= escape hatch in the module",
    "warning",
    "Mosaic lowering is TPU-only; thread an `interpret=` flag through "
    "at least one call path so the kernel runs (and is tested) on CPU",
)
register_rule(
    "CSA704",
    "Ref indexed outside the BlockSpec's declared block",
    "error",
    "each grid step owns exactly the block_shape tile its index map "
    "selects; constant indices must stay inside it",
)


def _const_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _const_int(node.operand)
        return -inner if inner is not None else None
    return None


def _tuple_elts(node: Optional[ast.AST]) -> Optional[List[ast.AST]]:
    """Elements of a literal tuple/list; a bare expr is a 1-tuple."""
    if node is None:
        return None
    if isinstance(node, (ast.Tuple, ast.List)):
        return list(node.elts)
    return [node]


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class _BlockSpec:
    """Statically-known facts about one BlockSpec expression."""

    def __init__(self, call: ast.Call):
        self.call = call
        shape_expr = call.args[0] if call.args else _kwarg(call,
                                                           "block_shape")
        self.shape_elts = _tuple_elts(shape_expr)
        self.dims: Optional[List[Optional[int]]] = None
        if self.shape_elts is not None:
            self.dims = [_const_int(e) for e in self.shape_elts]
        index_map = call.args[1] if len(call.args) > 1 else \
            _kwarg(call, "index_map")
        self.index_map = index_map if isinstance(index_map,
                                                 ast.Lambda) else None


def _resolve_blockspec(node: ast.AST,
                       assigns: Dict[str, ast.AST]) -> Optional[_BlockSpec]:
    if isinstance(node, ast.Name):
        node = assigns.get(node.id, node)
    if isinstance(node, ast.Call) and \
            jitmap._dotted(node.func).split(".")[-1] == "BlockSpec":
        return _BlockSpec(node)
    return None


def _spec_list(node: Optional[ast.AST],
               assigns: Dict[str, ast.AST]) -> List[Optional[_BlockSpec]]:
    if node is None:
        return []
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    return [_resolve_blockspec(e, assigns) for e in elts]


@register_pass
def run(mod) -> List[Finding]:
    findings: List[Finding] = []
    tree = mod.tree

    # name -> assigned value, SCOPED: module-level assigns overlaid with
    # the enclosing function's own assigns (two functions reusing the
    # name `spec` for different BlockSpecs must not see each other's)
    def _scope_assigns(nodes) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        for node in nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                out[node.targets[0].id] = node.value
        return out

    module_assigns = _scope_assigns(
        n for stmt in tree.body for n in ast.walk(stmt)
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)))
    enclosing = enclosing_qualnames(mod)
    _fn_assigns: Dict[int, Dict[str, ast.AST]] = {}

    def assigns_for(node: ast.AST) -> Dict[str, ast.AST]:
        scope = enclosing.get(id(node))
        while scope is not None and not isinstance(
                scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scope = enclosing.get(id(scope))
        if scope is None:
            return module_assigns
        if id(scope) not in _fn_assigns:
            local = _scope_assigns(jitmap.own_nodes(scope))
            _fn_assigns[id(scope)] = {**module_assigns, **local}
        return _fn_assigns[id(scope)]

    all_defs: Dict[str, ast.FunctionDef] = {
        n.name: n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}

    calls: List[Tuple[ast.Call, str]] = []   # (pallas_call node, context)
    # jit-context taint for CSA702 (pallas_call usually sits inside a
    # jitted wrapper; traced grid/block entries are what we hunt)
    taint_of: Dict[int, object] = {}
    ctx_of: Dict[int, str] = {}
    for jf, taint in jitmap.iter_jit_functions(mod.jit_map):
        for node in jitmap.own_nodes(jf.node):
            if isinstance(node, ast.Call) and \
                    jitmap._dotted(node.func).split(".")[-1] == "pallas_call":
                taint_of[id(node)] = taint
                ctx_of[id(node)] = jf.qualname

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                jitmap._dotted(node.func).split(".")[-1] == "pallas_call":
            calls.append((node, ctx_of.get(id(node), "")))

    has_interpret = any(_kwarg(c, "interpret") is not None
                        for c, _ in calls)
    if calls and not has_interpret:
        first = min(c.lineno for c, _ in calls)
        findings.append(Finding(
            "CSA703", mod.path, first,
            f"{len(calls)} pallas_call site(s) and none takes an "
            f"`interpret=` flag — kernels cannot run off-TPU",
            context="module"))

    for call, ctx in calls:
        assigns = assigns_for(call)
        grid_expr = _kwarg(call, "grid")
        if isinstance(grid_expr, ast.Name):     # grid = (...) then grid=grid
            grid_expr = assigns.get(grid_expr.id)
        grid_elts = _tuple_elts(grid_expr) if isinstance(
            grid_expr, (ast.Tuple, ast.List, ast.Constant,
                        ast.BinOp)) else None
        grid_rank = len(grid_elts) if grid_elts is not None else None

        in_specs = _spec_list(_kwarg(call, "in_specs"), assigns)
        out_specs = _spec_list(_kwarg(call, "out_specs"), assigns)
        specs = in_specs + out_specs

        taint = taint_of.get(id(call))
        if taint is not None and grid_elts:
            for e in grid_elts:
                if taint.expr_tainted(e):
                    findings.append(Finding(
                        "CSA702", mod.path, call.lineno,
                        f"traced value `{ast.unparse(e)}` in pallas_call "
                        f"grid",
                        context=ctx))

        for spec in specs:
            if spec is None:
                continue
            if taint is not None and spec.shape_elts:
                for e in spec.shape_elts:
                    if taint.expr_tainted(e):
                        findings.append(Finding(
                            "CSA702", mod.path, spec.call.lineno,
                            f"traced value `{ast.unparse(e)}` in "
                            f"BlockSpec block_shape",
                            context=ctx))
            if spec.index_map is not None:
                arity = len(spec.index_map.args.args)
                if grid_rank is not None and arity != grid_rank:
                    findings.append(Finding(
                        "CSA701", mod.path, spec.call.lineno,
                        f"BlockSpec index map takes {arity} arg(s) but "
                        f"the grid has rank {grid_rank}",
                        context=ctx))
                ret = _tuple_elts(spec.index_map.body)
                if ret is not None and spec.dims is not None and \
                        len(ret) != len(spec.dims):
                    findings.append(Finding(
                        "CSA701", mod.path, spec.call.lineno,
                        f"BlockSpec index map returns {len(ret)} "
                        f"index(es) for a rank-{len(spec.dims)} block",
                        context=ctx))

        # CSA704: map kernel ref params to block shapes
        kernel = call.args[0] if call.args else None
        fndef = all_defs.get(jitmap._dotted(kernel)) \
            if kernel is not None else None
        if fndef is None:
            continue
        params = [a.arg for a in fndef.args.posonlyargs + fndef.args.args]
        if len(params) != len(specs) or not specs:
            continue   # scalar-prefetch / scratch shapes: out of scope
        dims_of = {p: s.dims for p, s in zip(params, specs)
                   if s is not None and s.dims is not None}
        for sub in ast.walk(fndef):
            if not isinstance(sub, ast.Subscript) or \
                    not isinstance(sub.value, ast.Name):
                continue
            dims = dims_of.get(sub.value.id)
            if dims is None:
                continue
            idx_elts = sub.slice.elts if isinstance(
                sub.slice, ast.Tuple) else [sub.slice]
            if len(idx_elts) > len(dims):
                findings.append(Finding(
                    "CSA704", mod.path, sub.lineno,
                    f"`{sub.value.id}` indexed with {len(idx_elts)} "
                    f"dims but its block is rank {len(dims)}",
                    context=mod.qualname(fndef)))
                continue
            for i, e in enumerate(idx_elts):
                iv = _const_int(e)
                if iv is None or dims[i] is None:
                    continue
                if not (-dims[i] <= iv < dims[i]):
                    findings.append(Finding(
                        "CSA704", mod.path, sub.lineno,
                        f"`{sub.value.id}` index {iv} is outside its "
                        f"declared block dim of size {dims[i]}",
                        context=mod.qualname(fndef)))
    return findings
