"""CSA2xx — uint64 Gwei/slot math through 32-bit-defaulting constructs.

Balances are uint64 Gwei and epochs/slots are uint64 (reference SSZ
types); JAX's default integer dtype is 32-bit unless jax_enable_x64 is
set (ops/intmath.py sets it on import, but only for programs that import
it). An array constructor without an explicit dtype, or a bare Python
int literal wider than 31 bits mixed into traced arithmetic, silently
truncates on any path that misses the x64 import — the house style is
`u64(...)` / `dtype=jnp.uint64` everywhere (epoch_soa.py).
"""
from __future__ import annotations

import ast

from ..core import Finding, register_pass, register_rule
from .. import jitmap

register_rule(
    "CSA201",
    "jnp array constructor without an explicit dtype in a jitted function",
    "warning",
    "pass dtype=jnp.uint64 (Gwei/epoch math) or the intended narrow "
    "dtype explicitly; the 32-bit default truncates without x64",
)
register_rule(
    "CSA202",
    "Python int literal wider than 31 bits in traced arithmetic",
    "error",
    "wrap the literal: u64(...) / jnp.uint64(...) — bare wide literals "
    "overflow the default 32-bit lane",
)

# dtype-defaulting constructors; array/asarray only flagged for integer
# payloads (copying an existing array preserves its dtype).
_SHAPE_CTORS = {"zeros", "ones", "full", "empty", "arange"}
_COPY_CTORS = {"array", "asarray"}
_WIDE = 2 ** 31


def _int_payload(node: ast.AST) -> bool:
    """Expression is an int literal or a list/tuple of them."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, int) and not isinstance(node.value, bool)
    if isinstance(node, (ast.List, ast.Tuple)):
        return bool(node.elts) and all(_int_payload(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _int_payload(node.operand)
    return False


def _wide_literal(node: ast.AST):
    """The int value if node is a bare wide literal (incl. 2**40 style)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool) and abs(node.value) >= _WIDE:
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Pow) and \
            isinstance(node.left, ast.Constant) and \
            isinstance(node.right, ast.Constant):
        try:
            value = node.left.value ** node.right.value
        except Exception:
            return None
        if isinstance(value, int) and abs(value) >= _WIDE:
            return value
    return None


@register_pass
def run(mod):
    findings = []
    for jf, taint in jitmap.iter_jit_functions(mod.jit_map):
        for node in jitmap.own_nodes(jf.node):
            if isinstance(node, ast.Call):
                fname = jitmap._dotted(node.func)
                root, _, ctor = fname.rpartition(".")
                if root in ("jnp", "jax.numpy"):
                    has_dtype = any(k.arg == "dtype" for k in node.keywords)
                    payload_ok = (ctor in _SHAPE_CTORS
                                  or (ctor in _COPY_CTORS and node.args
                                      and _int_payload(node.args[0])))
                    if payload_ok and not has_dtype:
                        findings.append(Finding(
                            "CSA201", mod.path, node.lineno,
                            f"`jnp.{ctor}(...)` without dtype in jitted "
                            f"`{jf.qualname}`",
                            context=jf.qualname))
            elif isinstance(node, ast.BinOp) and \
                    not isinstance(node.op, ast.Pow):
                for lit_node, other in ((node.left, node.right),
                                        (node.right, node.left)):
                    value = _wide_literal(lit_node)
                    if value is not None and taint.expr_tainted(other):
                        findings.append(Finding(
                            "CSA202", mod.path, node.lineno,
                            f"bare int literal {value} in traced "
                            f"arithmetic in jitted `{jf.qualname}`",
                            context=jf.qualname))
                        break
    return findings
