"""CSA10xx — honest timing around async dispatch.

CSA1001: a `time.perf_counter()` delta measured around a call to a
known-jitted callable with no device fence between the dispatch and the
second clock read. JAX dispatch is asynchronous: the call returns as soon
as the program is enqueued, so the delta records launch overhead (often
well under 1% of the real cost) while looking exactly like a wall-clock
measurement. Every committed bench number in this repo fences by
materializing output bytes (`np.asarray(out.ravel()[0:1])` — the repo's
`_sync` idiom; `jax.block_until_ready` alone is accepted as a fence too,
though the tunneled TPU relay has been observed returning early from it),
or routes through `telemetry.span(...).fence(out)`, which fences at span
exit.

Detection (per statement block, nested bodies of the timed region
included):

    t0 = time.perf_counter()          # opens a timed region for `t0`
    y = f_jit(x)                      # jitted dispatch (plain name, or an
                                      #   attribute call `m.f_jit(x)` of a
                                      #   module whose jit map names it)
    dt = time.perf_counter() - t0     # closes the region -> FINDING if no
                                      #   fence call appeared in between

A region also closes at the next `t1 = time.perf_counter()` assignment
(the t0/t1/t2 chained-bucket style): the elapsed segment is checked, then
a new region opens. Fences recognized anywhere in the region:
`block_until_ready`, `device_get`, `np.asarray`/`np.array`/`onp.asarray`,
`.tolist()`, `.item()`, and calls to a local `_sync`/`sync` helper.

Dispatch resolution is a program pass over the call-graph IR: plain-name
calls resolve through the module's own jit map (imported jitted names
included — callgraph's fixpoint already folds `from m import f_jit` in),
and attribute calls `mod.f_jit(...)` resolve the base through the
program's import graph to the defining module's jitted names — the
dispatch form bench.py and the resident loop actually use, which PR 1's
per-module pass documented as out of scope. Cross-block `t0` captures
remain out of scope (the goal is catching the pattern the repo itself
used to hand-roll, at zero false positives on the shipped tree).
"""
from __future__ import annotations

import ast

from ..core import Finding, register_program_pass, register_rule
from .. import callgraph, jitmap

register_rule(
    "CSA1001",
    "perf_counter delta spans a jitted dispatch with no device fence",
    "warning",
    "materialize output bytes (np.asarray(out.ravel()[0:1]) — the _sync "
    "idiom) or jax.block_until_ready(out) before the closing "
    "perf_counter() read, or wrap the region in telemetry.span(...) and "
    "register the output with .fence(out)",
)

# call-name suffixes that complete device work before returning
_FENCE_SUFFIXES = ("block_until_ready", "device_get", "asarray", "array",
                   "tolist", "item")
# local helper names treated as fences (the repo's honest-fence wrappers)
_FENCE_NAMES = {"_sync", "sync"}


def _is_perf_counter_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and jitmap._dotted(node.func).split(".")[-1] == "perf_counter")


def _perf_assign_target(stmt: ast.stmt):
    """`t0 = time.perf_counter()` -> "t0" (single Name target only)."""
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
            and isinstance(stmt.targets[0], ast.Name) \
            and _is_perf_counter_call(stmt.value):
        return stmt.targets[0].id
    return None


def _closing_vars(stmt: ast.stmt, open_vars) -> set:
    """Timer vars whose delta this statement reads: a BinOp subtraction
    pairing a perf_counter() call with an open timer Name (either side)."""
    closed = set()
    for node in ast.walk(stmt):
        if not isinstance(node, ast.BinOp) or \
                not isinstance(node.op, ast.Sub):
            continue
        sides = (node.left, node.right)
        for a, b in (sides, sides[::-1]):
            if _is_perf_counter_call(a) and isinstance(b, ast.Name) \
                    and b.id in open_vars:
                closed.add(b.id)
    return closed


def _region_calls(stmts):
    """Every Call node in a statement span, nested bodies included."""
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                yield node


def _has_fence(calls) -> bool:
    for call in calls:
        dotted = jitmap._dotted(call.func)
        last = dotted.split(".")[-1]
        if last in _FENCE_NAMES or last in _FENCE_SUFFIXES:
            return True
    return False


def _make_dispatch_resolver(node, program):
    """A predicate `is_jitted_dispatch(call)` for one module: plain-name
    calls against the module's own jitted names (imported names included
    — the callgraph fixpoint folded those in), attribute calls against
    the jitted names of the module their base resolves to through the
    program's import graph."""
    own_jitted = set(node.info.jit_map.jitted_names)

    def is_jitted_dispatch(call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Name):
            return func.id in own_jitted
        if isinstance(func, ast.Attribute):
            base = jitmap._dotted(func.value)
            target = callgraph.resolve_module(node, base, program)
            if target is not None and target is not node:
                return func.attr in target.info.jit_map.jitted_names
        return False

    return is_jitted_dispatch


def _scan_block(stmts, mod, is_dispatch, context, findings) -> None:
    open_vars = {}          # timer var -> index of its perf_counter assign
    for i, stmt in enumerate(stmts):
        # close first: `t1 = perf_counter()` both closes open regions
        # (chained-bucket style) and opens its own
        closers = set(_closing_vars(stmt, open_vars))
        new_var = _perf_assign_target(stmt)
        if new_var is not None:
            closers |= set(open_vars)            # every open region ends here
        for var in closers:
            start = open_vars[var]
            region = list(_region_calls(stmts[start + 1:i]))
            if any(is_dispatch(c) for c in region) \
                    and not _has_fence(region):
                findings.append(Finding(
                    "CSA1001", mod.path, stmt.lineno,
                    f"perf_counter delta over `{var}` times a jitted "
                    f"dispatch with no fence before the second read",
                    context=context))
            if new_var is None:
                # a `dt = pc() - t0` read leaves the region open (bench
                # re-reads the same t0 after more work) but advances its
                # start: the checked segment never double-reports
                open_vars[var] = i
        if new_var is not None:
            open_vars = {new_var: i}
        # recurse into nested statement blocks (loops/with/try/if) for
        # regions fully inside them; function and class bodies are scanned
        # separately by run() with their own qualname context
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for attr in ("body", "orelse", "finalbody"):
            inner = getattr(stmt, attr, None)
            if inner:
                _scan_block(inner, mod, is_dispatch, context, findings)
        for handler in getattr(stmt, "handlers", ()) or ():
            _scan_block(handler.body, mod, is_dispatch, context, findings)


@register_program_pass
def run(program):
    findings = []
    for node in program.modules.values():
        mod = node.info
        if "perf_counter" not in mod.source:
            continue
        is_dispatch = _make_dispatch_resolver(node, program)
        _scan_block(mod.tree.body, mod, is_dispatch, "<module>", findings)
        for fn in ast.walk(mod.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_block(fn.body, mod, is_dispatch, mod.qualname(fn),
                            findings)
    return findings
