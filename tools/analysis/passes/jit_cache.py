"""CSA5xx — jit compilation-cache hygiene.

CSA501: a jitted callable invoked with a bare Python scalar (or a fresh
`int()` / `float()` / `len()` result) in a traced positional slot. Weak-
typed scalars commit to a different dtype than the arrays the tests
traced with, so the first production call recompiles — and a scalar that
VARIES (slot counters, validator counts) whose parameter later feeds a
shape recompiles per value: the retrace-storm class.

CSA502: static_argnums/static_argnames naming a parameter whose
annotation or default is unhashable (list/dict/set/ndarray). jit hashes
static arguments for the program cache; this raises TypeError on the
first call with a non-trivial value — but only on the code path that
passes one, which tests that always use the default never exercise.
"""
from __future__ import annotations

import ast

from ..core import Finding, register_pass, register_rule
from .. import jitmap

register_rule(
    "CSA501",
    "Python scalar passed positionally into a jitted callable's traced slot",
    "warning",
    "pass jnp.asarray(x, dtype=...) to pin the dtype, or declare the "
    "parameter static if it is genuinely shape-like",
)
register_rule(
    "CSA502",
    "static_argnums/static_argnames names an unhashable parameter",
    "error",
    "static arguments are dict keys of the compilation cache; pass "
    "arrays as traced args, or convert to tuple before the call",
)

_UNHASHABLE_ANNOTATIONS = {"list", "dict", "set", "List", "Dict", "Set",
                           "ndarray", "Array", "DeviceArray"}
_SCALAR_MAKERS = {"int", "float", "len"}


def _is_scalar_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) and \
            not isinstance(node.value, bool)
    if isinstance(node, ast.Call):
        return jitmap._dotted(node.func) in _SCALAR_MAKERS
    return False


def _annotation_unhashable(ann) -> bool:
    if ann is None:
        return False
    if isinstance(ann, ast.Subscript):
        ann = ann.value
    name = jitmap._dotted(ann)
    return name.split(".")[-1] in _UNHASHABLE_ANNOTATIONS


@register_pass
def run(mod):
    findings = []
    jmap = mod.jit_map

    # CSA502 — inspect each directly-jitted function's static params
    for jf in jmap.funcs.values():
        if not jf.direct or jf.jit_call is None:
            continue
        fn = jf.node
        args = fn.args.posonlyargs + fn.args.args
        defaults = dict(zip([a.arg for a in args[len(args)
                                                 - len(fn.args.defaults):]],
                            fn.args.defaults))
        for a in args:
            if a.arg not in jf.static_params:
                continue
            bad = _annotation_unhashable(a.annotation)
            default = defaults.get(a.arg)
            if default is not None and isinstance(
                    default, (ast.List, ast.Dict, ast.Set)):
                bad = True
            if bad:
                findings.append(Finding(
                    "CSA502", mod.path, fn.lineno,
                    f"static param `{a.arg}` of jitted `{fn.name}` is "
                    f"unhashable by annotation/default",
                    context=fn.name))

    # CSA501 — call sites of known-jitted names, module-wide. Plain Name
    # calls only: an attribute call (store.update(...)) whose final
    # segment happens to match a jitted name is some other object's method
    jitted = {name: fn for name, fn in jmap.jitted_names.items()}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Name):
            continue
        base = node.func.id
        if base not in jitted:
            continue
        fn = jitted[base]
        static = set()
        params = []
        if fn is not None:
            for jf in jmap.funcs.values():
                if jf.node is fn:
                    static = jf.static_params
                    break
            params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
        for i, arg in enumerate(node.args):
            pname = params[i] if i < len(params) else None
            if pname is not None and pname in static:
                continue
            if _is_scalar_expr(arg):
                findings.append(Finding(
                    "CSA501", mod.path, node.lineno,
                    f"scalar positional arg {i} to jitted `{base}` "
                    f"(traced slot `{pname or i}`)",
                    context=base))
    return findings
