"""CSA1xx — Python control flow / host casts on traced values.

Inside a jit-context function every jnp-derived value is a tracer; `if`,
`while`, `bool()`, `int()`, `float()` and `.item()` on one either raises
TracerBoolConversionError at trace time or — worse, on paths the tests
never trace — silently bakes one branch into the compiled program. The
spec lift rewrites these as jnp.where / lax.cond / lax.fori_loop
(models/phase0/epoch_soa.py is the house style).
"""
from __future__ import annotations

import ast

from ..core import Finding, register_pass, register_rule
from .. import jitmap

register_rule(
    "CSA101",
    "Python control flow on a traced value inside a jitted function",
    "error",
    "branch with jnp.where / jax.lax.cond, loop with jax.lax.fori_loop "
    "or jax.lax.while_loop",
)
register_rule(
    "CSA102",
    "host cast (bool/int/float/.item) of a traced value inside a jitted "
    "function",
    "error",
    "keep the value on device; cast only after jax.device_get outside "
    "the traced program",
)

_CASTS = {"bool", "int", "float"}


def _test_of(node: ast.AST):
    if isinstance(node, (ast.If, ast.While)):
        return node.test
    return None


@register_pass
def run(mod):
    findings = []
    for jf, taint in jitmap.iter_jit_functions(mod.jit_map):
        for node in jitmap.own_nodes(jf.node):
            test = _test_of(node)
            if test is not None and taint.expr_tainted(test):
                kind = "if" if isinstance(node, ast.If) else "while"
                names = sorted(n for n in jitmap._expr_names(test)
                               if n in taint.tainted)
                findings.append(Finding(
                    "CSA101", mod.path, node.lineno,
                    f"`{kind}` on traced value(s) {', '.join(names)} "
                    f"in jitted `{jf.qualname}`",
                    context=jf.qualname))
            elif isinstance(node, ast.Call):
                fname = jitmap._dotted(node.func)
                if fname in _CASTS and node.args and \
                        taint.expr_tainted(node.args[0]):
                    findings.append(Finding(
                        "CSA102", mod.path, node.lineno,
                        f"`{fname}()` applied to a traced value in "
                        f"jitted `{jf.qualname}`",
                        context=jf.qualname))
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item" and not node.args and \
                        taint.expr_tainted(node.func.value):
                    findings.append(Finding(
                        "CSA102", mod.path, node.lineno,
                        f"`.item()` on a traced value in jitted "
                        f"`{jf.qualname}`",
                        context=jf.qualname))
    return findings
