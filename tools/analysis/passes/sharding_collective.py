"""CSA6xx — sharding / collective consistency (whole-program pass).

The distributed-correctness analogue of the trace-safety family: axis
names are stringly-typed, so a collective over an axis no mesh declares,
a PartitionSpec naming a misspelled mesh axis, or a constraint that
needs an ambient mesh none provides, all pass every single-device test
and fail (or silently mis-place data) only on real multi-chip hardware.
This is the same contract SNIPPETS.md §[1] documents for staged pjit —
one stage's out specs must be the next stage's in specs — checked
statically at the call-graph level: mesh axis declarations anywhere in
the program (`Mesh(..., axis_names=...)`, `jax.make_mesh`, `pmap
(axis_name=...)`) form the program's axis vocabulary, and every
collective / PartitionSpec / constraint is checked against it.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, register_program_pass, register_rule
from .. import jitmap
from ..callgraph import Program, context_of, enclosing_qualnames

register_rule(
    "CSA601",
    "collective over an axis name no mesh/pmap in the program declares",
    "error",
    "bind the axis first: Mesh(..., axis_names=(...)), shard_map over "
    "that mesh, or pmap(axis_name=...) — collectives over unbound names "
    "raise NameError-like failures only at lowering time on real devices",
)
register_rule(
    "CSA602",
    "PartitionSpec names an axis no mesh in the program declares",
    "error",
    "PartitionSpec entries must name axes of the mesh the sharding is "
    "applied under; a misspelled axis places every shard on device 0",
)
register_rule(
    "CSA603",
    "with_sharding_constraint with a bare PartitionSpec outside any "
    "visible mesh scope",
    "warning",
    "a bare PartitionSpec needs an ambient mesh (`with mesh:`); pass "
    "NamedSharding(mesh, spec) instead, or move the call under the mesh "
    "context manager",
)
register_rule(
    "CSA604",
    "value resharded to a different PartitionSpec than its producer",
    "warning",
    "a sharded producer feeding a differently-specced consumer inserts "
    "a silent all-to-all reshard; make the producer's out spec the "
    "consumer's in spec (or constrain once at the boundary)",
)
register_rule(
    "CSA605",
    "jitted producer's out_shardings differ from the jitted consumer's "
    "in_shardings",
    "warning",
    "chained jit programs (the serving loop's slot/epoch steps) re-lay "
    "data out between every pair of calls whose shardings disagree; make "
    "the producer's out_shardings the consumer's in_shardings "
    "(SNIPPETS.md [1]: matched out/in axis resources in chained pjit)",
)

_COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather",
                "all_to_all", "ppermute", "pshuffle", "psum_scatter",
                "axis_index"}
# collectives whose axis name is the FIRST positional argument
_AXIS_ARG0 = {"axis_index"}
_MESH_CTORS = {"Mesh", "AbstractMesh", "make_mesh"}


def _dotted(node: ast.AST) -> str:
    return jitmap._dotted(node)


def _is_collective(mnode, call: ast.Call) -> Optional[str]:
    """The collective's name when `call` is a jax.lax collective."""
    dotted = _dotted(call.func)
    if not dotted:
        return None
    parts = dotted.split(".")
    last = parts[-1]
    if last not in _COLLECTIVES:
        return None
    if len(parts) > 1:
        return last if "lax" in parts[:-1] else None
    src = mnode.from_imports.get(last)
    if src is not None and src[0].endswith("lax"):
        return last
    return None


def _axis_arg(name: str, call: ast.Call) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "axis_name":
            return kw.value
    idx = 0 if name in _AXIS_ARG0 else 1
    if len(call.args) > idx:
        return call.args[idx]
    return None


def _partition_spec_locals(mnode) -> Set[str]:
    """Local names bound to jax.sharding.PartitionSpec by from-import."""
    return {local for local, (src, remote) in mnode.from_imports.items()
            if remote == "PartitionSpec"}


def _is_pspec_call(mnode, p_locals: Set[str], node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dotted = _dotted(node.func)
    return dotted.split(".")[-1] == "PartitionSpec" or dotted in p_locals


def _declared_axes(program: Program) -> Set[str]:
    axes: Set[str] = set()
    for mnode in program.modules.values():
        for node in ast.walk(mnode.info.tree):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            last = dotted.split(".")[-1]
            if last in _MESH_CTORS:
                target = None
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        target = kw.value
                if target is None and len(node.args) > 1:
                    target = node.args[1]
                if target is not None:
                    axes.update(jitmap._const_strs(target))
                    if isinstance(target, ast.Constant) and \
                            isinstance(target.value, str):
                        axes.add(target.value)
            elif last in ("pmap", "shard_map", "smap"):
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis_names"):
                        axes.update(jitmap._const_strs(kw.value))
    return axes


def _spec_key(node: ast.AST) -> str:
    """Canonical text of a sharding expression for CSA604 comparison."""
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on 3.9+
        return ast.dump(node)


def _inner_pspec(mnode, p_locals: Set[str], node: ast.AST
                 ) -> Optional[ast.Call]:
    """The PartitionSpec(...) call inside a sharding expression, if it
    appears literally (NamedSharding(mesh, P(...)) or bare P(...))."""
    for sub in ast.walk(node):
        if _is_pspec_call(mnode, p_locals, sub):
            return sub
    return None


@register_program_pass
def run(program: Program) -> List[Finding]:
    findings: List[Finding] = []
    axes = _declared_axes(program)
    for mnode in program.modules.values():
        info = mnode.info
        p_locals = _partition_spec_locals(mnode)
        enclosing = enclosing_qualnames(info)
        parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(info.tree):
            for child in ast.iter_child_nodes(parent):
                parents[id(child)] = parent

        def in_mesh_scope(node: ast.AST) -> bool:
            cur = node
            while id(cur) in parents:
                cur = parents[id(cur)]
                if isinstance(cur, ast.With):
                    for item in cur.items:
                        if "mesh" in _spec_key(item.context_expr).lower():
                            return True
            return False

        for node in ast.walk(info.tree):
            if not isinstance(node, ast.Call):
                continue
            ctx = context_of(info, enclosing, node)

            coll = _is_collective(mnode, node)
            if coll is not None:
                axis_expr = _axis_arg(coll, node)
                for name in (jitmap._const_strs(axis_expr)
                             if axis_expr is not None else []):
                    if name not in axes:
                        findings.append(Finding(
                            "CSA601", info.path, node.lineno,
                            f"collective `{coll}` over axis '{name}' "
                            f"which no Mesh/pmap in the program declares",
                            context=ctx))

            if _is_pspec_call(mnode, p_locals, node):
                for name in jitmap._const_strs(ast.Tuple(
                        elts=list(node.args), ctx=ast.Load())):
                    if name not in axes:
                        findings.append(Finding(
                            "CSA602", info.path, node.lineno,
                            f"PartitionSpec axis '{name}' is not an axis "
                            f"of any declared mesh",
                            context=ctx))

            dotted = _dotted(node.func)
            if dotted.split(".")[-1] == "with_sharding_constraint" and \
                    len(node.args) > 1:
                if _is_pspec_call(mnode, p_locals, node.args[1]) and \
                        not in_mesh_scope(node):
                    findings.append(Finding(
                        "CSA603", info.path, node.lineno,
                        "with_sharding_constraint with a bare "
                        "PartitionSpec outside any `with mesh:` scope",
                        context=ctx))

        # CSA604: per-function producer/consumer spec tracking. Named
        # shardings resolve through single-target assigns (module level,
        # then function-local) so `SPEC = NamedSharding(mesh, P('v'))`
        # compares equal to the same spec written inline.
        module_assigns: Dict[str, ast.AST] = {}
        for stmt in info.tree.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name):
                module_assigns[stmt.targets[0].id] = stmt.value
        for fn in ast.walk(info.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            spec_of: Dict[str, str] = {}
            nodes = [n for n in jitmap.own_nodes(fn)
                     if isinstance(n, ast.Assign)]
            nodes.sort(key=lambda n: n.lineno)
            local_assigns = dict(module_assigns)
            for node in nodes:
                if len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    local_assigns[node.targets[0].id] = node.value
            for node in nodes:
                if not isinstance(node.value, ast.Call):
                    continue
                call = node.value
                last = _dotted(call.func).split(".")[-1]
                if last not in ("device_put", "with_sharding_constraint"):
                    continue
                if len(call.args) < 2:
                    continue
                src, spec_expr = call.args[0], call.args[1]
                if isinstance(spec_expr, ast.Name):
                    spec_expr = local_assigns.get(spec_expr.id, spec_expr)
                pspec = _inner_pspec(mnode, p_locals, spec_expr)
                key = _spec_key(pspec if pspec is not None else spec_expr)
                if isinstance(src, ast.Name) and \
                        spec_of.get(src.id, key) != key:
                    findings.append(Finding(
                        "CSA604", info.path, node.lineno,
                        f"`{src.id}` produced with spec "
                        f"{spec_of[src.id]} is re-specced to {key} "
                        f"(implicit reshard)",
                        context=info.qualname(fn)))
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        spec_of[tgt.id] = key

            # CSA605: chained jitted programs — a value produced by a jit
            # with declared out_shardings feeding a jit whose in_shardings
            # (at that argument position) disagree re-lays the data out
            # between every pair of calls. Producer/consumer resolve the
            # same single-target assigns as CSA604, so shardings named by
            # constants compare equal to the same spec written inline.
            def _jit_shardings(expr):
                if not isinstance(expr, ast.Call) or \
                        _dotted(expr.func).split(".")[-1] != "jit":
                    return None
                ins = outs = None
                for kw in expr.keywords:
                    if kw.arg == "in_shardings":
                        ins = kw.value
                    elif kw.arg == "out_shardings":
                        outs = kw.value
                return (ins, outs) if ins is not None or outs is not None \
                    else None

            jit_specs: Dict[str, tuple] = {}
            for nm, expr in local_assigns.items():
                got = _jit_shardings(expr)
                if got is not None:
                    jit_specs[nm] = got
            if not jit_specs:
                continue

            def _resolve(e):
                if isinstance(e, ast.Name):
                    return local_assigns.get(e.id, e)
                return e

            def _in_elem(ins, i):
                ins = _resolve(ins)
                if isinstance(ins, ast.Tuple) and i < len(ins.elts):
                    return _spec_key(_resolve(ins.elts[i]))
                return _spec_key(ins)

            # any rebinding of a name between producer and consumer (an
            # explicit device_put re-layout, `y = y + 1`, ...) invalidates
            # the recorded out-sharding — only a DIRECT producer->consumer
            # chain is checked
            rebinds: Dict[str, List[int]] = {}
            for a in jitmap.own_nodes(fn):
                if isinstance(a, ast.Assign):
                    targets = list(a.targets)
                elif isinstance(a, (ast.AugAssign, ast.AnnAssign, ast.For,
                                    ast.AsyncFor, ast.NamedExpr)):
                    targets = [a.target]
                elif isinstance(a, (ast.With, ast.AsyncWith)):
                    targets = [i.optional_vars for i in a.items
                               if i.optional_vars is not None]
                else:
                    continue
                for t in targets:
                    elts = t.elts if isinstance(t, ast.Tuple) else [t]
                    for e in elts:
                        if isinstance(e, ast.Name):
                            rebinds.setdefault(e.id, []).append(a.lineno)

            def _stale(name: str, born: int, used: int) -> bool:
                return any(born < ln < used for ln in rebinds.get(name, ()))

            produced: Dict[str, tuple] = {}   # name -> (spec text, lineno)
            calls = [c for c in jitmap.own_nodes(fn)
                     if isinstance(c, ast.Call)
                     and isinstance(c.func, ast.Name)
                     and c.func.id in jit_specs]
            for call in sorted(calls, key=lambda c: c.lineno):
                ins, outs = jit_specs[call.func.id]
                if ins is not None:
                    for i, arg in enumerate(call.args):
                        if isinstance(arg, ast.Name) and arg.id in produced:
                            got, born = produced[arg.id]
                            if _stale(arg.id, born, call.lineno):
                                del produced[arg.id]
                                continue
                            want = _in_elem(ins, i)
                            if want != got:
                                findings.append(Finding(
                                    "CSA605", info.path, call.lineno,
                                    f"`{arg.id}` produced with "
                                    f"out_shardings {got} feeds "
                                    f"`{call.func.id}` whose in_shardings "
                                    f"expect {want} (implicit per-call "
                                    f"re-layout)",
                                    context=info.qualname(fn)))
                if outs is None:
                    continue
                par = parents.get(id(call))
                if isinstance(par, ast.Assign) and len(par.targets) == 1:
                    tgt = par.targets[0]
                    outs_r = _resolve(outs)
                    if isinstance(tgt, ast.Name):
                        produced[tgt.id] = (_spec_key(outs_r), par.lineno)
                    elif isinstance(tgt, ast.Tuple) and \
                            isinstance(outs_r, ast.Tuple) and \
                            len(tgt.elts) == len(outs_r.elts):
                        for t, o in zip(tgt.elts, outs_r.elts):
                            if isinstance(t, ast.Name):
                                produced[t.id] = (_spec_key(_resolve(o)),
                                                  par.lineno)
    return findings
