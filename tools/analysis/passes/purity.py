"""CSA3xx — host side effects inside traced programs.

A jitted function body runs ONCE, at trace time; `time.time()` /
`random.random()` / `np.random.*` results are baked into the compiled
program as constants, and mutation of globals or argument attributes
happens at trace time only — every later cached-program call skips it.
Both are silent wrong-answer classes, not crashes.
"""
from __future__ import annotations

import ast

from ..core import Finding, register_pass, register_rule
from .. import jitmap

register_rule(
    "CSA301",
    "impure host call (time/random) inside a jitted function",
    "error",
    "thread entropy in as a jax.random key argument; take timestamps "
    "outside the traced program",
)
register_rule(
    "CSA302",
    "`global` declaration inside a jitted function",
    "error",
    "trace-time global writes run once, not per call; return the value "
    "instead",
)
register_rule(
    "CSA303",
    "mutation of a parameter/global object inside a jitted function",
    "error",
    "tracer-backed containers cannot be mutated in place; use the "
    "functional .at[...].set(...) form and return the result",
)

_IMPURE_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.",
                    "jax.random.PRNGKey")


@register_pass
def run(mod):
    findings = []
    for jf, taint in jitmap.iter_jit_functions(mod.jit_map):
        params = jf.traced_params | jf.static_params
        for node in jitmap.own_nodes(jf.node):
            if isinstance(node, ast.Call):
                fname = jitmap._dotted(node.func)
                if any(fname.startswith(p) or fname == p.rstrip(".")
                       for p in _IMPURE_PREFIXES):
                    findings.append(Finding(
                        "CSA301", mod.path, node.lineno,
                        f"impure call `{fname}(...)` in jitted "
                        f"`{jf.qualname}` — result is frozen at trace time",
                        context=jf.qualname))
            elif isinstance(node, ast.Global):
                findings.append(Finding(
                    "CSA302", mod.path, node.lineno,
                    f"`global {', '.join(node.names)}` in jitted "
                    f"`{jf.qualname}`",
                    context=jf.qualname))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    root = tgt
                    while isinstance(root, (ast.Attribute, ast.Subscript)):
                        root = root.value
                    if root is tgt or not isinstance(root, ast.Name):
                        continue   # plain name rebinding is fine
                    if root.id in params or taint.expr_tainted(root):
                        findings.append(Finding(
                            "CSA303", mod.path, node.lineno,
                            f"in-place mutation of `{root.id}` in jitted "
                            f"`{jf.qualname}`",
                            context=jf.qualname))
    return findings
