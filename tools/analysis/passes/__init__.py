"""Importing this package registers every analysis pass with core.PASSES
(per-module) or core.PROGRAM_PASSES (whole-program, over the call-graph
IR in tools/analysis/callgraph.py)."""
from . import trace_safety  # noqa: F401
from . import dtype_width   # noqa: F401
from . import purity        # noqa: F401
from . import state_aliasing  # noqa: F401
from . import jit_cache     # noqa: F401
from . import sharding_collective  # noqa: F401
from . import pallas_kernels  # noqa: F401
from . import spec_drift    # noqa: F401
from . import wide_accumulation  # noqa: F401
from . import honest_timing  # noqa: F401
