"""Importing this package registers every analysis pass with core.PASSES."""
from . import trace_safety  # noqa: F401
from . import dtype_width   # noqa: F401
from . import purity        # noqa: F401
from . import state_aliasing  # noqa: F401
from . import jit_cache     # noqa: F401
