"""CSA8xx — differential spec drift vs the reference pyspec.

The TPU port must track the reference pyspec's surface exactly: the
constants in `configs/*.yaml` (loaded by `utils/config.py`) and the
spec functions `models/phase0/spec.py` binds as methods. Nothing in the
test suite diffs them — a renamed helper or a drifted constant simply
becomes "our" behavior. This pass parses the reference tree under
`--reference-root` (default `$CSTPU_REFERENCE_ROOT` or
`/root/reference`) with zero imports of either side:

  constants  reference `configs/constant_presets/<name>.yaml` vs the
             port's `configs/<name>.yaml`, flat key: value comparison
             (a tiny stdlib parser — the CI lint job has no pyyaml)
  functions  `def` signatures from the reference pyspec `.py` files vs
             the port's phase-0 spec surface (module-level defs whose
             first parameter is `spec` — the bound-method convention),
             compared by name and parameter order after dropping the
             port's leading `spec`

When the reference tree is absent the pass emits an explicit notice and
reports nothing: CI machines do not carry the reference checkout.
"""
from __future__ import annotations

import ast
import os
import re
from pathlib import Path
from typing import Dict, List, Tuple

from ..core import Finding, register_program_pass, register_rule
from ..callgraph import Program

register_rule(
    "CSA801",
    "constant value drift between a reference preset and the port's",
    "error",
    "the port's configs/*.yaml must carry the reference values verbatim; "
    "fix the port (or record a deliberate divergence in the baseline)",
)
register_rule(
    "CSA802",
    "constant present in the reference preset but missing from the port",
    "warning",
    "add the constant to the port preset even if unused yet — spec "
    "functions index presets by name at runtime",
)
register_rule(
    "CSA803",
    "reference spec function missing from the port's phase-0 surface",
    "warning",
    "port the function (taking `spec` first, per the bound-method "
    "convention) or baseline the entry with the reason it is not needed",
)
register_rule(
    "CSA804",
    "parameter names/order drift from the reference spec function",
    "error",
    "keep the reference parameter order after the leading `spec`; "
    "callers ported later pass positionally",
)

_UPPER_CONST = re.compile(r"^[A-Z][A-Z0-9_]*$")


def parse_flat_yaml(path: Path) -> Dict[str, str]:
    """`KEY: value` pairs of a flat preset file, values as normalized
    strings (quotes stripped, ints canonicalized) — enough for the
    constant presets, with no pyyaml dependency in the lint lane."""
    out: Dict[str, str] = {}
    for line in path.read_text().splitlines():
        line = line.split("#", 1)[0].strip()
        if not line or ":" not in line:
            continue
        key, _, value = line.partition(":")
        key, value = key.strip(), value.strip().strip("'\"")
        if not _UPPER_CONST.match(key):
            continue
        try:
            value = str(int(value, 0))
        except ValueError:
            pass
        out[key] = value
    return out


def _ref_presets(ref_root: Path) -> Dict[str, Path]:
    """preset name -> reference yaml path."""
    candidates = list(ref_root.glob("configs/constant_presets/*.yaml"))
    if not candidates:
        candidates = list(ref_root.glob("**/constant_presets/*.yaml"))
    return {p.stem: p for p in candidates}


def _ref_functions(ref_root: Path) -> Dict[str, Tuple[List[str], str]]:
    """function name -> (param names, defining file) from the reference
    pyspec python sources (the eth2spec/pyspec subtree when present,
    else every .py under the root)."""
    roots = [d for d in (ref_root / "test_libs" / "pyspec",
                         ref_root / "pyspec") if d.is_dir()] or [ref_root]
    out: Dict[str, Tuple[List[str], str]] = {}
    for root in roots:
        for path in sorted(root.rglob("*.py")):
            try:
                tree = ast.parse(path.read_text())
            except (SyntaxError, UnicodeDecodeError):
                continue
            for node in tree.body:
                if not isinstance(node, ast.FunctionDef) or \
                        node.name.startswith("_"):
                    continue
                params = [a.arg for a in node.args.posonlyargs
                          + node.args.args]
                out.setdefault(node.name, (params, str(path)))
    return out


def _port_functions(program: Program, prefix: str
                    ) -> Dict[str, Tuple[List[str], str, int]]:
    """name -> (params-after-spec, path, lineno) for module-level defs
    in `prefix` modules whose first parameter is `spec` (the surface
    spec.py binds as methods)."""
    out: Dict[str, Tuple[List[str], str, int]] = {}
    for name, mnode in sorted(program.modules.items()):
        if prefix not in name:
            continue
        for fname, fn in mnode.defs.items():
            if fname.startswith("_"):
                continue
            params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
            if not params or params[0] != "spec":
                continue
            out.setdefault(fname, (params[1:], mnode.info.path, fn.lineno))
    return out


def _rel(path: Path) -> str:
    """Anchor findings with a cwd-relative path when possible: the
    fingerprint embeds the path, and an absolute one would never match
    the same finding from another checkout location."""
    try:
        return str(path.resolve().relative_to(Path.cwd()))
    except ValueError:
        return str(path)


def _line_of(path: Path, key: str) -> int:
    try:
        for i, line in enumerate(path.read_text().splitlines(), 1):
            if line.split(":", 1)[0].strip() == key:
                return i
    except OSError:
        pass
    return 1


@register_program_pass
def run(program: Program) -> List[Finding]:
    opts = program.options
    ref_root = Path(opts.get("reference_root")
                    or os.environ.get("CSTPU_REFERENCE_ROOT")
                    or "/root/reference")
    if not ref_root.is_dir():
        program.notices.append(
            f"CSA8xx spec-drift: reference tree absent at {ref_root}; "
            f"pass skipped (set --reference-root to enable)")
        program.skipped_rules.update(
            ("CSA801", "CSA802", "CSA803", "CSA804"))
        return []

    findings: List[Finding] = []
    repo_root = Path(__file__).resolve().parents[3]
    port_configs = Path(opts.get("drift_port_configs")
                        or repo_root / "configs")

    # -- constants ----------------------------------------------------------
    for preset, ref_path in sorted(_ref_presets(ref_root).items()):
        port_path = port_configs / f"{preset}.yaml"
        if not port_path.exists():
            program.notices.append(
                f"CSA8xx spec-drift: no port preset for reference "
                f"'{preset}' ({port_path} missing)")
            continue
        ref_consts = parse_flat_yaml(ref_path)
        port_consts = parse_flat_yaml(port_path)
        for key, ref_value in sorted(ref_consts.items()):
            if key not in port_consts:
                findings.append(Finding(
                    "CSA802", _rel(port_path), 1,
                    f"constant {key} ({preset}) in the reference preset "
                    f"but not the port's",
                    context=f"preset:{preset}"))
            elif port_consts[key] != ref_value:
                findings.append(Finding(
                    "CSA801", _rel(port_path), _line_of(port_path, key),
                    f"constant {key} ({preset}) drifted: port has "
                    f"{port_consts[key]}, reference has {ref_value}",
                    context=f"preset:{preset}"))

    # -- function signatures ------------------------------------------------
    prefix = str(opts.get("drift_port_prefix") or "models.phase0")
    port_fns = _port_functions(program, prefix)
    if not port_fns:
        program.notices.append(
            f"CSA8xx spec-drift: no port modules matching '{prefix}'; "
            f"function diff skipped")
        return findings
    spec_mod = program.module_named(f"{prefix}.spec".lstrip("."))
    fn_anchor = spec_mod.info.path if spec_mod else \
        next(iter(port_fns.values()))[1]
    for fname, (ref_params, ref_file) in sorted(_ref_functions(
            ref_root).items()):
        port = port_fns.get(fname)
        if port is None:
            findings.append(Finding(
                "CSA803", fn_anchor, 1,
                f"reference spec function `{fname}` "
                f"({Path(ref_file).name}) has no port counterpart",
                context="spec-surface"))
            continue
        port_params, port_path, lineno = port
        if port_params != ref_params:
            findings.append(Finding(
                "CSA804", port_path, lineno,
                f"`{fname}` parameters drifted: port "
                f"({', '.join(port_params)}) vs reference "
                f"({', '.join(ref_params)})",
                context=fname))
    return findings
