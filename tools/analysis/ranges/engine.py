"""Range-contract engine: discover RANGE_CONTRACTS, run the interval
interpreter over the real jaxprs, ratchet the proven intervals against
the committed baseline.

A **range contract** is a plain dict a kernel module exports in its
`RANGE_CONTRACTS` list (plain data, the TRACE_CONTRACTS idiom — the
engine imports the kernel modules, never the reverse):

    name         unique id, e.g. "ops.fq.fq_redc"
    build        () -> {"fn": traceable (all args traced — close over
                        static config), "args": tuple of arrays or
                        jax.ShapeDtypeStruct pytrees (nothing is
                        executed: the ceiling shapes — V = 10^7
                        validators, n near the shuffle bound — cost
                        nothing to trace), "ranges": pytree congruent
                        to args whose dict leaves declare the input
                        intervals {"lo", "hi"} (+ optional "top_lo"/
                        "top_hi" overriding the LAST trailing position
                        — the narrow-limb budget is positional: body
                        limbs and the top value-spill limb have
                        different documented bounds),
                        "context": () -> contextmanager (optional)}
    output       declared bound the interpreter must PROVE: a dict
                 spec applied to every output leaf, a pytree of them
                 congruent to fn's output, or None (no pin — the proof
                 is then only the absence of undeclared wraps, plus
                 the baseline ratchet on the derived hull)
    wrap_ok      iterable of "dtype" / "dtype:kind" (kind in add/sub/
                 mul/shl/convert/div) declaring INTENTIONAL modular
                 arithmetic, e.g. ("uint32",) for SHA-256
    wrap_ok_sources  filename fragments whose staged ops may wrap
                 (ops/intmath.py's documented 128-bit machinery)
    invariants   per-loop carry invariants, consumed in loop encounter
                 order for loops beyond the unroll window: "dtype" |
                 {"lo","hi"} | [per-carry spec]
    max_unroll   abstract unroll window (default interp.DEFAULT_MAX_UNROLL)

The ratchet (ranges_baseline.json maps contract -> {metric: value},
metrics "out_lo"/"out_hi" = the proven output hull, "widened" = count
of CSA1402 degradations): a proven interval that GREW (out_hi up,
out_lo down, widened up) vs the committed snapshot is CSA1404 — as is
a contract with no snapshot. Wrap/bound/invariant failures are CSA1401,
degraded ops CSA1402 (notice), missing invariants CSA1403. Overflow
findings anchor at the *staging source line* when jax can resolve it,
so inline `# csa: ignore[CSA1401]` suppressions sit next to the
arithmetic they justify, exactly like the AST tier's.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from ..core import Finding, _parse_suppressions

REPO_ROOT = Path(__file__).resolve().parents[3]
DEFAULT_BASELINE = Path(__file__).resolve().parents[1] / \
    "ranges_baseline.json"

# ratchet direction per metric: +1 = bigger is a regression, -1 = smaller
METRIC_SIGN = {"out_hi": 1, "out_lo": -1, "widened": 1}


# ---------------------------------------------------------------------------
# Discovery (mirrors trace/engine.discover)
# ---------------------------------------------------------------------------

def discover(package_root: Optional[Path] = None) -> List[dict]:
    import importlib
    root = Path(package_root or REPO_ROOT / "consensus_specs_tpu")
    contracts: List[dict] = []
    seen = set()
    for path in sorted(root.rglob("*.py")):
        source = path.read_text()
        if "RANGE_CONTRACTS" not in source:
            continue
        rel = path.relative_to(root.parent).with_suffix("")
        module = importlib.import_module(".".join(rel.parts))
        for contract in getattr(module, "RANGE_CONTRACTS", []):
            c = dict(contract)
            name = c["name"]
            assert name not in seen, f"duplicate range contract {name}"
            seen.add(name)
            c.setdefault("path", str(path))
            c.setdefault("line", _name_line(source, name))
            contracts.append(c)
    return contracts


def _name_line(source: str, name: str) -> int:
    lines = source.splitlines()
    # quoted match first: a bare substring scan would anchor
    # "ops.fq.fq_mul" at the earlier "ops.fq.fq_mul_wide" line,
    # mis-placing findings and their inline suppressions
    for i, line in enumerate(lines, 1):
        if f'"{name}"' in line or f"'{name}'" in line:
            return i
    for i, line in enumerate(lines, 1):
        if name in line:
            return i
    for i, line in enumerate(lines, 1):
        if "RANGE_CONTRACTS" in line:
            return i
    return 1


def declared_snapshot(contracts: Optional[Iterable[dict]] = None) -> dict:
    """{contract: declared output spec} without tracing anything — the
    cheap declaration read bench.py embeds next to the trace-tier budget
    snapshot."""
    if contracts is None:
        contracts = discover()
    return {c["name"]: c.get("output") for c in contracts}


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def load_ranges_baseline(path=None) -> Dict[str, Dict[str, int]]:
    p = Path(path or DEFAULT_BASELINE)
    if not p.exists():
        return {}
    return {k: dict(v) for k, v in
            json.loads(p.read_text()).get("contracts", {}).items()}


def write_ranges_baseline(path, snapshot: Dict[str, Dict[str, int]]) -> None:
    ordered = {k: {m: snapshot[k][m] for m in sorted(snapshot[k])}
               for k in sorted(snapshot)}
    Path(path).write_text(json.dumps(
        {"version": 1,
         "comment": "Proven value-range snapshot (the CSA1404 ratchet). "
                    "out_lo/out_hi are the interval hull the interpreter "
                    "PROVED over the contract's outputs; widened counts "
                    "CSA1402 degradations. Loosening an entry is a "
                    "reviewed edit; --update-ranges-baseline refreshes "
                    "after wins.",
         "contracts": ordered}, indent=2) + "\n")


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------

@dataclass
class RangeResult:
    name: str
    path: str
    line: int
    measured: Dict[str, int] = field(default_factory=dict)
    outputs: List[dict] = field(default_factory=list)  # per-leaf proven hulls
    skipped: str = ""


@dataclass
class RangeReport:
    findings: List[Finding]
    suppressed: List[Finding]
    results: List[RangeResult]
    notices: List[str]
    stale_baseline: List[str]

    @property
    def snapshot(self) -> Dict[str, Dict[str, int]]:
        return {r.name: dict(r.measured) for r in self.results
                if not r.skipped and r.measured}


def _is_spec(x) -> bool:
    return isinstance(x, dict) and "lo" in x


def _flat_specs(spec, n_leaves, tree=None):
    """Flatten a contract range/output spec against a pytree arity."""
    import jax
    if spec is None:
        return [None] * n_leaves
    if _is_spec(spec):
        return [spec] * n_leaves
    leaves = jax.tree_util.tree_leaves(spec, is_leaf=_is_spec)
    assert len(leaves) == n_leaves, \
        f"spec arity {len(leaves)} != leaf arity {n_leaves}"
    return leaves


def _rel(path: str) -> str:
    try:
        return str(Path(path).resolve().relative_to(REPO_ROOT))
    except ValueError:
        return path


def _measure(contract: dict):
    """Trace one contract's program and run the interpreter. Returns
    (RangeResult, events, interp)."""
    from . import interp as P
    from . import interval as I
    import contextlib
    import jax

    res = RangeResult(name=contract["name"], path=contract["path"],
                      line=contract["line"])
    spec = contract["build"]()
    fn, args = spec["fn"], tuple(spec["args"])
    ctx_factory = spec.get("context")
    with contextlib.ExitStack() as stack:
        if ctx_factory:
            stack.enter_context(ctx_factory())
        # stage ops/fq's carry-round helper as a named call so the
        # interpreter's exact summary can replace it (production
        # tracing keeps it inlined — see fq.staged_helpers)
        try:
            from consensus_specs_tpu.ops import fq as _fq
            stack.enter_context(_fq.staged_helpers())
        except ImportError:
            pass
        closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)
    in_leaves = jax.tree_util.tree_leaves(args)
    range_specs = _flat_specs(spec.get("ranges"), len(in_leaves))
    assert len(closed.jaxpr.invars) == len(range_specs), \
        (len(closed.jaxpr.invars), len(range_specs))
    in_vals = [P.for_aval(v.aval, s)
               for v, s in zip(closed.jaxpr.invars, range_specs)]
    it = P.Interp(wrap_ok=tuple(contract.get("wrap_ok", ())),
                  wrap_ok_sources=tuple(contract.get("wrap_ok_sources", ())),
                  invariants=list(contract.get("invariants", ())),
                  max_unroll=int(contract.get(
                      "max_unroll", P.DEFAULT_MAX_UNROLL)))
    outs = it.run(closed, in_vals)

    out_leaves = jax.tree_util.tree_leaves(out_shape)
    out_specs = _flat_specs(contract.get("output"), len(out_leaves))
    bound_failures = []
    hull_lo, hull_hi = None, None
    for i, (val, ospec) in enumerate(zip(outs, out_specs)):
        dtype = val.dtype
        h = val.hull()
        res.outputs.append({"index": i, "dtype": dtype,
                            "lo": h.lo, "hi": h.hi,
                            "vec": [[v.lo, v.hi] for v in val.vec]
                            if val.positional else None})
        if I.is_int_dtype(dtype) or dtype == "bool":
            hull_lo = h.lo if hull_lo is None else min(hull_lo, h.lo)
            hull_hi = h.hi if hull_hi is None else max(hull_hi, h.hi)
        if ospec is None:
            continue
        body = I.Interval(ospec["lo"], ospec["hi"])
        top = I.Interval(ospec.get("top_lo", ospec["lo"]),
                         ospec.get("top_hi", ospec["hi"]))
        vec = val.vec
        if val.positional and len(vec) >= 2:
            ok = (all(v.within(body) for v in vec[:-1])
                  and vec[-1].within(top))
        else:
            # positional tracking was lost (or the trailing axis is
            # degenerate): body and top positions are indistinguishable,
            # so the SOUND check is the hull against both bounds —
            # strict rather than vacuous (a collapsing op downgrading a
            # body-bound check to the looser top bound would otherwise
            # report PROVEN)
            hl = val.hull()
            ok = hl.within(body) and hl.within(top)
        if not ok:
            worst = val.hull()
            bound_failures.append(
                f"output {i}: proven interval [{worst.lo}, {worst.hi}] "
                f"escapes the declared bound [{body.lo}, {body.hi}]"
                + (f" (top [{top.lo}, {top.hi}])" if "top_hi" in ospec
                   else ""))
    res.measured = {"out_lo": hull_lo if hull_lo is not None else 0,
                    "out_hi": hull_hi if hull_hi is not None else 0,
                    "widened": it.widened()}
    return res, it.events, bound_failures


def run_contracts(contracts: Optional[List[dict]] = None,
                  baseline: Optional[Dict[str, Dict[str, int]]] = None,
                  baseline_path=None) -> RangeReport:
    if contracts is None:
        contracts = discover()
    if baseline is None:
        baseline = load_ranges_baseline(baseline_path)
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    results: List[RangeResult] = []
    notices: List[str] = []
    matched = set()
    suppression_cache: Dict[str, Dict[int, set]] = {}

    def emit(res, rule, message, path=None, line=None):
        path = _rel(path or res.path)
        line = line or res.line
        f = Finding(rule, path, line, message, context=res.name)
        sup = suppression_cache.get(path)
        if sup is None:
            try:
                sup = _parse_suppressions(
                    (REPO_ROOT / path).read_text()
                    if not Path(path).is_absolute()
                    else Path(path).read_text())
            except OSError:
                sup = {}
            suppression_cache[path] = sup
        for ln in (line, line - 1):
            rules = sup.get(ln)
            if rules and ("*" in rules or rule in rules):
                suppressed.append(f)
                return
        findings.append(f)

    for contract in contracts:
        try:
            res, events, bound_failures = _measure(contract)
        except Exception as exc:   # a broken contract is a finding, not a crash
            res = RangeResult(name=contract["name"], path=contract["path"],
                              line=contract["line"],
                              skipped=f"{type(exc).__name__}: {exc}")
            results.append(res)
            emit(res, "CSA1401",
                 f"contract failed to trace/interpret: {res.skipped}")
            matched.add(res.name)     # unverifiable, not stale: the
            continue                  # baseline entry must survive
        results.append(res)
        for ev in events:
            emit(res, ev.rule, ev.message,
                 path=ev.path or None, line=ev.line or None)
        for msg in bound_failures:
            emit(res, "CSA1401", msg)

        base = baseline.get(res.name, {})
        if res.name in baseline:
            matched.add(res.name)
        for metric, got in res.measured.items():
            sign = METRIC_SIGN.get(metric, 1)
            prior = base.get(metric)
            if prior is None:
                emit(res, "CSA1404",
                     f"`{metric}` = {got} has no ranges-baseline entry "
                     f"(run --update-ranges-baseline and commit)")
            elif sign * (got - prior) > 0:
                emit(res, "CSA1404",
                     f"proven `{metric}` = {got} regressed vs the "
                     f"committed baseline {prior}")
            elif got != prior:
                notices.append(
                    f"ranges: {res.name} `{metric}` tightened "
                    f"{prior} -> {got}; refresh via "
                    f"--update-ranges-baseline")

    stale = sorted(set(baseline) - matched)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return RangeReport(findings=findings, suppressed=suppressed,
                       results=results, notices=notices,
                       stale_baseline=stale)


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------

def render_human(report: RangeReport) -> str:
    from ..core import RULES
    out = []
    for f in report.findings:
        out.append(f"{f.path}:{f.line}: [{f.rule}] {RULES[f.rule].severity}:"
                   f" {f.context}: {f.message}")
        if RULES[f.rule].hint:
            out.append(f"    hint: {RULES[f.rule].hint}")
    for name in report.stale_baseline:
        out.append(f"ranges-baseline: stale contract (removed? delete it): "
                   f"{name}")
    for note in report.notices:
        out.append(f"notice: {note}")
    ran = sum(1 for r in report.results if not r.skipped)
    out.append(f"ranges: {len(report.results)} contract(s), {ran} proven, "
               f"{len(report.findings)} finding(s), "
               f"{len(report.suppressed)} suppressed")
    return "\n".join(out)


def render_json(report: RangeReport) -> str:
    from ..core import RULES

    def row(f: Finding):
        return {"rule": f.rule, "path": f.path, "line": f.line,
                "contract": f.context, "message": f.message,
                "severity": RULES[f.rule].severity,
                "fingerprint": f.fingerprint()}

    return json.dumps({
        "findings": [row(f) for f in report.findings],
        "suppressed": [row(f) for f in report.suppressed],
        "contracts": [
            {"name": r.name, "path": _rel(r.path), "line": r.line,
             "skipped": r.skipped, "measured": r.measured,
             "outputs": r.outputs}
            for r in report.results],
        "notices": report.notices,
        "stale_baseline": report.stale_baseline,
    }, indent=2)
