"""Exact integer/float interval arithmetic — the abstract domain of the
value-range tier.

Intervals carry arbitrary-precision Python ints (floats only for float
dtypes), so a bound like `14 * 2^58` is exact, never a rounded double.
Every transfer function here is the true mathematical image of the
concrete op over the interval box (for the nonlinear ones, the min/max
over the corner combinations, which is exact for monotone-per-argument
ops like mul/div on fixed signs); WRAPPING is not modeled here — the
interpreter (interp.py) compares the ideal-arithmetic result against
the dtype bounds and decides whether a wrap is possible.
"""
from __future__ import annotations

import math
from dataclasses import dataclass


@dataclass(frozen=True)
class Interval:
    lo: object   # int (or float for float dtypes; may be +-inf)
    hi: object

    def __post_init__(self):
        assert self.lo <= self.hi, (self.lo, self.hi)

    @property
    def singleton(self):
        return self.lo == self.hi

    def __contains__(self, x):
        return self.lo <= x <= self.hi

    def within(self, other: "Interval") -> bool:
        return other.lo <= self.lo and self.hi <= other.hi


def iv(lo, hi=None) -> Interval:
    return Interval(lo, lo if hi is None else hi)


def _mk(lo, hi) -> Interval:
    """Order-and-sanitize constructor for arithmetic results: float NaN
    (inf * 0 and friends) degrades to the infinite interval instead of
    poisoning comparisons."""
    if isinstance(lo, float) and math.isnan(lo):
        lo = float("-inf")
    if isinstance(hi, float) and math.isnan(hi):
        hi = float("inf")
    if lo > hi:
        lo, hi = hi, lo
    return Interval(lo, hi)


def join(a: Interval, b: Interval) -> Interval:
    return _mk(min(a.lo, b.lo), max(a.hi, b.hi))


def join_all(ivs) -> Interval:
    ivs = list(ivs)
    return _mk(min(i.lo for i in ivs), max(i.hi for i in ivs))


def add(a, b):
    return _mk(a.lo + b.lo, a.hi + b.hi)


def sub(a, b):
    return _mk(a.lo - b.hi, a.hi - b.lo)


def neg(a):
    return _mk(-a.hi, -a.lo)


def mul(a, b):
    cs = [x * y for x in (a.lo, a.hi) for y in (b.lo, b.hi)]
    if any(isinstance(c, float) and math.isnan(c) for c in cs):
        return Interval(float("-inf"), float("inf"))
    return _mk(min(cs), max(cs))


def scale(a: Interval, n: int) -> Interval:
    """n summed copies of a value in `a` (reduce_sum over n elements)."""
    if n <= 0:
        return iv(0)
    return Interval(min(a.lo, n * a.lo), max(a.hi, n * a.hi))


def floordiv(a, b):
    """Floor division; caller guarantees 0 not in b. Covers both python
    floor and C trunc-toward-zero semantics (XLA integer div truncates)
    by taking the hull of the two roundings at every corner."""
    outs = []
    for x in (a.lo, a.hi):
        for d in (b.lo, b.hi):
            if isinstance(x, float) or isinstance(d, float):
                if d == 0:
                    outs.extend([float("-inf"), float("inf")])
                else:
                    outs.append(x / d)
                continue
            outs.append(x // d)                  # floor
            outs.append(-((-x) // d) if (x < 0) != (d < 0) else x // d)  # trunc
    return _mk(min(outs), max(outs))


def rem(a, b):
    """a % b with 0 < b (unsigned/remainder-of-nonneg case); the sign of
    a C-style remainder follows the dividend."""
    m = b.hi - 1
    if a.lo >= 0:
        return Interval(0, min(a.hi, m))
    return Interval(max(a.lo, -m), min(max(a.hi, 0), m))


def shl(a, s):
    cs = (a.lo << s.lo, a.lo << s.hi, a.hi << s.lo, a.hi << s.hi)
    return Interval(min(cs), max(cs))


def ashr(a, s):
    """Arithmetic right shift (python >> is arithmetic/floor)."""
    cs = (a.lo >> s.lo, a.lo >> s.hi, a.hi >> s.lo, a.hi >> s.hi)
    return Interval(min(cs), max(cs))


def and_(a, b):
    """Bitwise and. Precise only for the mask idiom (one side nonneg):
    x & m with m >= 0 lands in [0, m] regardless of x's sign (two's
    complement). Fully-signed case falls back to the caller's dtype
    widening (return None)."""
    if a.lo >= 0 and b.lo >= 0:
        return Interval(0, min(a.hi, b.hi))
    if b.lo >= 0:
        return Interval(0, b.hi)
    if a.lo >= 0:
        return Interval(0, a.hi)
    return None


def _pow2_ceil(x: int) -> int:
    return 1 << max(x, 1).bit_length()


def or_xor(a, b):
    """Bitwise or/xor share a bound: both operands nonneg -> result in
    [0, 2^ceil(log2(max+1)) - 1]. Signed case -> None (dtype range)."""
    if a.lo >= 0 and b.lo >= 0:
        return Interval(0, _pow2_ceil(max(a.hi, b.hi)) - 1)
    return None


def not_(a):
    return Interval(-1 - a.hi, -1 - a.lo)


def min_(a, b):
    return Interval(min(a.lo, b.lo), min(a.hi, b.hi))


def max_(a, b):
    return Interval(max(a.lo, b.lo), max(a.hi, b.hi))


def abs_(a):
    if a.lo >= 0:
        return a
    if a.hi <= 0:
        return Interval(-a.hi, -a.lo)
    return Interval(0, max(-a.lo, a.hi))


def sqrt(a):
    lo = math.sqrt(a.lo) if a.lo > 0 else 0.0
    hi = math.sqrt(a.hi) if a.hi > 0 else 0.0
    return Interval(lo, hi)


def isqrt(a):
    """Exact integer square root image (clamped at 0 below)."""
    return Interval(math.isqrt(max(a.lo, 0)), math.isqrt(max(a.hi, 0)))


BOOL = Interval(0, 1)
TRUE = Interval(1, 1)
FALSE = Interval(0, 0)


# ---------------------------------------------------------------------------
# Dtype ranges
# ---------------------------------------------------------------------------

_INT_RANGES = {}
for _bits in (8, 16, 32, 64):
    _INT_RANGES[f"int{_bits}"] = Interval(-(1 << (_bits - 1)),
                                          (1 << (_bits - 1)) - 1)
    _INT_RANGES[f"uint{_bits}"] = Interval(0, (1 << _bits) - 1)
_INT_RANGES["bool"] = BOOL


def dtype_range(dtype) -> Interval:
    """Representable range of a dtype; floats get the infinite interval
    (they saturate, never wrap — overflow discipline is ints-only)."""
    name = str(dtype)
    r = _INT_RANGES.get(name)
    if r is None:
        return Interval(float("-inf"), float("inf"))
    return r


def is_int_dtype(dtype) -> bool:
    return str(dtype) in _INT_RANGES and str(dtype) != "bool"
