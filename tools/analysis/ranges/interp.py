"""The interval abstract interpreter over jaxprs.

Abstraction: an array is a vector of per-element magnitude intervals
along its TRAILING axis (uniform over every leading batch axis), or a
single interval when the trailing axis is wide/untracked. The trailing
axis is where this codebase keeps its limb/column structure
(`[..., L]` narrow elements, `[..., 2L]` wide columns, `[..., 16]`
SHA-256 words), so positional tracking is what lets structural facts —
"schoolbook column 27 is identically zero", "`_Q_SHIFTS[i]` never
touches the top column" — survive into the proof; those facts are
exactly why the committed budgets hold at all.

Soundness contract: every transfer function's output interval contains
every concretely reachable value, *in ideal (unbounded) arithmetic*.
Wrapping is the checked property, not part of the domain: when an int
op's ideal interval escapes its dtype, the interpreter (a) widens the
result to the dtype range — the wrapped value really can be anywhere —
and (b) records a proved-overflow event (CSA1401) unless the contract
declared that wrap intentional (`wrap_ok` dtype / dtype:kind entries,
or a `wrap_ok_sources` file match for e.g. ops/intmath.py's documented
128-bit machinery). Widened values are TAINTED so one root cause yields
one finding, not a cascade.

Loops (`while`/`scan`, what fori_loop lowers to) unroll abstractly while
the trip decision stays definite and the count stays under
`max_unroll`; past that the contract must supply the carry invariant
and the interpreter checks the body maps invariant -> invariant
(CSA1401 if not, CSA1403 if none declared), widening on failure.

Named-jit summaries: a nested-jit call boundary survives into the jaxpr
as a `pjit` eqn carrying the callee's name; `SUMMARIES` maps the two
ops/intmath.py helpers to their exact mathematical interval images
(`math.isqrt`, exact 128-bit muldiv bounds) — those helpers are
differentially tested bit-exact against Python bigints, so the summary
is a theorem about the function, not an assumption about the code.
Everything else recurses into the sub-jaxpr.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

from . import interval as I
from .interval import Interval

TRACK_MAX = 64          # widest trailing axis tracked positionally
DEFAULT_MAX_UNROLL = 128


@dataclasses.dataclass
class AbsVal:
    """Abstract array: per-trailing-position intervals (len == shape[-1])
    or a single hull interval (len == 1), uniform over leading axes."""
    shape: Tuple[int, ...]
    dtype: str
    vec: Tuple[Interval, ...]
    tainted: bool = False

    @property
    def positional(self) -> bool:
        return len(self.shape) >= 1 and len(self.vec) == self.shape[-1]

    def hull(self) -> Interval:
        return I.join_all(self.vec)


def _uniform(shape, dtype, ivl, tainted=False) -> AbsVal:
    return AbsVal(tuple(shape), str(dtype), (ivl,), tainted)


def _vec(shape, dtype, vec, tainted=False) -> AbsVal:
    vec = tuple(vec)
    if len(shape) == 0 or len(vec) != shape[-1] or shape[-1] > TRACK_MAX:
        vec = (I.join_all(vec),)
    return AbsVal(tuple(shape), str(dtype), vec, tainted)


def from_concrete(x, aval) -> AbsVal:
    """Lift a trace-time constant (numpy array / python scalar) exactly;
    per-position mins/maxes over leading axes when tracked."""
    import numpy as np
    arr = np.asarray(x)
    shape, dtype = tuple(arr.shape), str(aval.dtype)
    if arr.size == 0:
        return _uniform(shape, dtype, I.iv(0))
    if arr.ndim >= 1 and shape[-1] <= TRACK_MAX:
        flat = arr.reshape(-1, shape[-1])
        if flat.dtype == np.bool_:
            flat = flat.astype(np.int64)
        los = flat.min(axis=0)
        his = flat.max(axis=0)
        return AbsVal(shape, dtype,
                      tuple(Interval(_py(l), _py(h))
                            for l, h in zip(los, his)))
    if arr.dtype == np.bool_:
        arr = arr.astype(np.int64)
    return _uniform(shape, dtype, Interval(_py(arr.min()), _py(arr.max())))


def _py(x):
    """numpy scalar -> exact python number."""
    import numpy as np
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, (np.bool_,)):
        return int(x)
    return int(x)


def for_aval(aval, spec: Optional[dict] = None) -> AbsVal:
    """AbsVal for an input aval from a contract range spec
    ({"lo", "hi"} with optional {"top_lo", "top_hi"} overriding the last
    trailing position); no spec -> full dtype range."""
    shape, dtype = tuple(aval.shape), str(aval.dtype)
    if spec is None:
        return _uniform(shape, dtype, I.dtype_range(dtype))
    body = Interval(spec["lo"], spec["hi"])
    n = shape[-1] if shape else 0
    if "top_lo" in spec and len(shape) >= 1 and 1 < n <= TRACK_MAX:
        top = Interval(spec["top_lo"], spec["top_hi"])
        return AbsVal(shape, dtype, (body,) * (n - 1) + (top,))
    if len(shape) >= 1 and 1 <= n <= TRACK_MAX:
        return AbsVal(shape, dtype, (body,) * n)
    return _uniform(shape, dtype, body)


# ---------------------------------------------------------------------------
# Events
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Event:
    rule: str            # CSA1401 / CSA1402 / CSA1403
    message: str
    path: str            # source site when resolvable, else ""
    line: int
    prim: str


def _eqn_site(eqn) -> Tuple[str, int]:
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return str(frame.file_name), int(frame.start_line)
    except Exception:
        pass
    return "", 0


# ---------------------------------------------------------------------------
# Interpreter
# ---------------------------------------------------------------------------

class Interp:
    def __init__(self, wrap_ok: Sequence[str] = (),
                 wrap_ok_sources: Sequence[str] = (),
                 invariants: Sequence[object] = (),
                 max_unroll: int = DEFAULT_MAX_UNROLL):
        self.wrap_ok = frozenset(wrap_ok)
        self.wrap_ok_sources = tuple(wrap_ok_sources)
        self.invariants = list(invariants)
        self.max_unroll = int(max_unroll)
        self.events: List[Event] = []
        self._event_keys = set()
        self._loop_idx = 0
        self._defs: Dict[object, object] = {}   # Var -> defining eqn

    # -- events -------------------------------------------------------------

    def _emit(self, rule, message, eqn):
        path, line = _eqn_site(eqn)
        key = (rule, path, line, eqn.primitive.name, message.split(":")[0])
        if key in self._event_keys:
            return
        self._event_keys.add(key)
        self.events.append(Event(rule, message, path, line,
                                 eqn.primitive.name))

    def widened(self) -> int:
        return sum(1 for e in self.events if e.rule == "CSA1402")

    # -- wrap discipline ----------------------------------------------------

    def _wrap_allowed(self, dtype: str, kind: str, eqn) -> bool:
        if dtype in self.wrap_ok or f"{dtype}:{kind}" in self.wrap_ok:
            return True
        path, _ = _eqn_site(eqn)
        return bool(path) and any(s in path for s in self.wrap_ok_sources)

    def _finish(self, eqn, shape, dtype, vec, kind, tainted) -> AbsVal:
        """Clamp an ideal-arithmetic result against its dtype; flag a
        possible wrap unless tainted/declared."""
        dtype = str(dtype)
        rng = I.dtype_range(dtype)
        if not I.is_int_dtype(dtype) and dtype != "bool":
            return _vec(shape, dtype, vec, tainted)          # floats saturate
        if all(v.within(rng) for v in vec):
            return _vec(shape, dtype, vec, tainted)
        out = tuple(v if v.within(rng) else rng for v in vec)
        if tainted:
            return _vec(shape, dtype, out, True)
        if kind is None:
            return _vec(shape, dtype, out, False)
        if self._wrap_allowed(dtype, kind, eqn):
            # declared-intentional wrap: the value really can be anywhere
            # in the dtype, and everything derived from it is modular
            # arithmetic by declaration — taint so downstream ops do not
            # re-flag the same declared root cause
            return _vec(shape, dtype, out, True)
        worst = I.join_all(v for v in vec if not v.within(rng))
        self._emit("CSA1401",
                   f"`{eqn.primitive.name}` on {dtype} can wrap: ideal "
                   f"interval [{worst.lo}, {worst.hi}] escapes "
                   f"[{rng.lo}, {rng.hi}]", eqn)
        return _vec(shape, dtype, out, True)

    def _widen(self, eqn, why: str) -> List[AbsVal]:
        outs = []
        for ov in eqn.outvars:
            dtype = str(ov.aval.dtype)
            if I.is_int_dtype(dtype) or dtype == "bool":
                self._emit("CSA1402",
                           f"`{eqn.primitive.name}` not modeled ({why}); "
                           f"result widened to the {dtype} range", eqn)
                outs.append(_uniform(ov.aval.shape, dtype,
                                     I.dtype_range(dtype), tainted=True))
            else:
                outs.append(_uniform(ov.aval.shape, dtype,
                                     I.dtype_range(dtype)))
        return outs

    # -- jaxpr evaluation ---------------------------------------------------

    def run(self, closed, in_vals: Sequence[AbsVal]) -> List[AbsVal]:
        consts = [from_concrete(c, v.aval)
                  for c, v in zip(closed.consts, closed.jaxpr.constvars)]
        return self.eval_jaxpr(closed.jaxpr, consts, list(in_vals))

    def eval_jaxpr(self, jaxpr, consts, args) -> List[AbsVal]:
        env: Dict[object, AbsVal] = {}
        for var, val in zip(jaxpr.constvars, consts):
            env[var] = val
        for var, val in zip(jaxpr.invars, args):
            env[var] = val

        def read(atom) -> AbsVal:
            if hasattr(atom, "val"):          # Literal
                return from_concrete(atom.val, atom.aval)
            return env[atom]

        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                self._defs[ov] = eqn
            in_vals = [read(v) for v in eqn.invars]
            handler = _HANDLERS.get(eqn.primitive.name)
            if handler is None:
                outs = self._widen(eqn, "no handler")
            else:
                outs = handler(self, eqn, in_vals)
                if isinstance(outs, AbsVal):
                    outs = [outs]
            assert len(outs) == len(eqn.outvars), eqn.primitive.name
            for var, val in zip(eqn.outvars, outs):
                env[var] = val
        return [read(v) for v in jaxpr.outvars]

    def eval_closed(self, closed, args) -> List[AbsVal]:
        consts = [from_concrete(c, v.aval)
                  for c, v in zip(closed.consts, closed.jaxpr.constvars)]
        return self.eval_jaxpr(closed.jaxpr, consts, list(args))

    # -- elementwise plumbing -----------------------------------------------

    def _aligned(self, val: AbsVal, n: int) -> Tuple[Interval, ...]:
        """Operand intervals aligned to an output trailing size n: its
        own positions when they line up, else its hull everywhere (a
        broadcast size-1 trailing axis contributes its single value)."""
        if len(val.vec) == n:
            return val.vec
        return (val.hull(),) * n

    def _ew(self, eqn, vals, fn, kind=None) -> AbsVal:
        out_aval = eqn.outvars[0].aval
        shape = tuple(out_aval.shape)
        n = shape[-1] if (shape and shape[-1] <= TRACK_MAX) else 1
        cols = [self._aligned(v, n) for v in vals]
        vec = []
        punted = False
        for pos in range(n):
            r = fn(*[c[pos] for c in cols])
            if r is None:                       # handler punts -> dtype range
                r = I.dtype_range(out_aval.dtype)
                punted = True
            vec.append(r)
        tainted = any(v.tainted for v in vals)
        if punted:
            # operands outside the modeled sub-domain (out-of-range
            # shift amount, fully-signed bitwise op): a degradation
            # like any other unmodeled op — taint + count it, so the
            # `widened` ratchet moves and downstream ops don't cascade
            self._emit("CSA1402",
                       f"`{eqn.primitive.name}` operands outside the "
                       f"modeled domain; result widened to the "
                       f"{out_aval.dtype} range", eqn)
            tainted = True
        return self._finish(eqn, shape, out_aval.dtype, vec, kind, tainted)


# ---------------------------------------------------------------------------
# Handlers
# ---------------------------------------------------------------------------

_HANDLERS = {}


def handler(*names):
    def wrap(fn):
        for n in names:
            _HANDLERS[n] = fn
        return fn
    return wrap


@handler("add", "add_any")
def _add(self, eqn, vals):
    return self._ew(eqn, vals, I.add, kind="add")


@handler("sub")
def _sub(self, eqn, vals):
    if _sub_is_nonneg(self, eqn, vals):
        # the saturating-subtraction idioms — x - min(x, y),
        # max(x, y) - y, cumsum(x) - x — are pointwise >= 0 by algebra
        # the interval box cannot see; a one-step def-use look-back
        # recovers them so the hot guards do not degrade to a
        # declared-wrap taint
        return self._ew(eqn, vals,
                        lambda a, b: _clamp_lo0(I.sub(a, b)), kind="sub")
    return self._ew(eqn, vals, I.sub, kind="sub")


def _clamp_lo0(v):
    return Interval(max(v.lo, 0), max(v.hi, 0))


class _DefProxy:
    """A sub-eqn lifted through a trivial pjit wrapper, with its invars
    rewritten into the enclosing scope's atoms."""
    __slots__ = ("primitive", "params", "invars")


def _def_of(self, atom):
    """Defining eqn of a var, looking through single-eqn pjit wrappers
    (jnp.cumsum and friends stage `pjit[name=cumsum] { cumsum }`)."""
    if hasattr(atom, "val"):              # Literal: no def, unhashable
        return None
    d = self._defs.get(atom)
    if d is None or d.primitive.name != "pjit":
        return d
    inner = d.params.get("jaxpr")
    if inner is None:
        return d
    j = inner.jaxpr
    if (len(j.eqns) != 1 or len(j.outvars) != 1 or len(d.outvars) != 1
            or j.outvars[0] is not j.eqns[0].outvars[0]):
        return d
    mapping = dict(zip(j.invars, d.invars))
    p = _DefProxy()
    p.primitive = j.eqns[0].primitive
    p.params = j.eqns[0].params
    p.invars = [mapping.get(iv, iv) if not hasattr(iv, "val") else iv
                for iv in j.eqns[0].invars]
    return p


def _same_value(self, x, y) -> bool:
    """x and y are the same var, or the same convert of the same var
    (uncse'd `v.astype(t)` appearing twice stages two convert eqns)."""
    if x is y:
        return True
    dx, dy = _def_of(self, x), _def_of(self, y)
    return (dx is not None and dy is not None
            and dx.primitive.name == dy.primitive.name
            == "convert_element_type"
            and dx.params.get("new_dtype") == dy.params.get("new_dtype")
            and dx.invars[0] is dy.invars[0])


def _sub_is_nonneg(self, eqn, vals) -> bool:
    a_atom, b_atom = eqn.invars
    b_def = _def_of(self, b_atom)
    if b_def is not None and b_def.primitive.name == "min" \
            and any(_same_value(self, iv, a_atom) for iv in b_def.invars):
        return True                       # x - min(x, y) >= 0
    a_def = _def_of(self, a_atom)
    if a_def is not None and a_def.primitive.name == "max" \
            and any(_same_value(self, iv, b_atom) for iv in a_def.invars):
        return True                       # max(x, y) - y >= 0
    if a_def is not None and a_def.primitive.name == "cumsum" \
            and not a_def.params.get("reverse") \
            and any(_same_value(self, iv, b_atom) for iv in a_def.invars) \
            and vals[1].hull().lo >= 0:
        return True                       # cumsum(x) - x >= 0 for x >= 0
    return False


@handler("mul")
def _mul(self, eqn, vals):
    return self._ew(eqn, vals, I.mul, kind="mul")


@handler("neg")
def _neg(self, eqn, vals):
    return self._ew(eqn, vals, I.neg, kind="sub")


@handler("max")
def _max(self, eqn, vals):
    return self._ew(eqn, vals, I.max_)


@handler("min")
def _min(self, eqn, vals):
    return self._ew(eqn, vals, I.min_)


@handler("abs")
def _abs(self, eqn, vals):
    return self._ew(eqn, vals, I.abs_, kind="sub")


@handler("sign")
def _sign(self, eqn, vals):
    def f(a):
        lo = -1 if a.lo < 0 else (0 if a.lo == 0 else 1)
        hi = 1 if a.hi > 0 else (0 if a.hi == 0 else -1)
        return Interval(lo, hi)
    return self._ew(eqn, vals, f)


@handler("clamp")
def _clamp(self, eqn, vals):
    return self._ew(eqn, vals,
                    lambda lo, x, hi: I.min_(I.max_(x, lo), hi))


@handler("div")
def _div(self, eqn, vals):
    a, b = vals
    if I.is_int_dtype(str(eqn.outvars[0].aval.dtype)):
        bh = b.hull()
        if bh.lo <= 0 <= bh.hi:
            return self._widen(eqn, "possible division by zero")
    return self._ew(eqn, vals, I.floordiv, kind="div")


@handler("rem")
def _rem(self, eqn, vals):
    a, b = vals
    bh = b.hull()
    if bh.lo <= 0 <= bh.hi:
        return self._widen(eqn, "possible remainder by zero")
    if bh.hi < 0:
        vals = [a, AbsVal(b.shape, b.dtype,
                          tuple(I.neg(v) for v in b.vec), b.tainted)]
    return self._ew(eqn, vals, I.rem)


@handler("pow", "integer_pow")
def _pow(self, eqn, vals):
    y = eqn.params.get("y")
    if y is None or not isinstance(y, int) or y < 0:
        return self._widen(eqn, "non-static exponent")

    def f(a):
        cs = [a.lo ** y, a.hi ** y]
        if y % 2 == 0 and a.lo < 0 < a.hi:
            cs.append(0)
        return Interval(min(cs), max(cs))
    return self._ew(eqn, vals, f, kind="mul")


@handler("shift_left")
def _shl(self, eqn, vals):
    bits = I.dtype_range(str(eqn.outvars[0].aval.dtype))
    width = (bits.hi - bits.lo + 1).bit_length() - 1

    def f(a, s):
        if s.lo < 0 or s.hi >= width:
            return None
        return I.shl(a, s)
    return self._ew(eqn, vals, f, kind="shl")


@handler("shift_right_arithmetic")
def _ashr(self, eqn, vals):
    def f(a, s):
        if s.lo < 0:
            return None
        return I.ashr(a, Interval(s.lo, min(s.hi, 1 << 10)))
    return self._ew(eqn, vals, f)


@handler("shift_right_logical")
def _lshr(self, eqn, vals):
    rng = I.dtype_range(str(eqn.outvars[0].aval.dtype))
    nbits = (rng.hi - rng.lo + 1).bit_length() - 1

    def f(a, s):
        if s.lo < 0:
            return None
        if a.lo < 0:                  # reinterpreted as unsigned bits
            return Interval(0, ((1 << nbits) - 1) >> s.lo)
        return I.ashr(a, Interval(s.lo, min(s.hi, 1 << 10)))
    return self._ew(eqn, vals, f)


@handler("and")
def _and(self, eqn, vals):
    return self._ew(eqn, vals, I.and_)


@handler("or", "xor")
def _or(self, eqn, vals):
    return self._ew(eqn, vals, I.or_xor)


@handler("not")
def _not(self, eqn, vals):
    if str(eqn.outvars[0].aval.dtype) == "bool":
        return self._ew(eqn, vals,
                        lambda a: Interval(1 - a.hi, 1 - a.lo))
    return self._ew(eqn, vals, I.not_)


@handler("population_count", "clz")
def _popcount(self, eqn, vals):
    rng = I.dtype_range(str(eqn.outvars[0].aval.dtype))
    nbits = (rng.hi - rng.lo + 1).bit_length() - 1
    return self._ew(eqn, vals, lambda a: Interval(0, nbits))


# -- comparisons / selection -------------------------------------------------

def _cmp(op):
    def f(a, b):
        if op == "lt":
            if a.hi < b.lo:
                return I.TRUE
            if a.lo >= b.hi:
                return I.FALSE
        elif op == "le":
            if a.hi <= b.lo:
                return I.TRUE
            if a.lo > b.hi:
                return I.FALSE
        elif op == "gt":
            if a.lo > b.hi:
                return I.TRUE
            if a.hi <= b.lo:
                return I.FALSE
        elif op == "ge":
            if a.lo >= b.hi:
                return I.TRUE
            if a.hi < b.lo:
                return I.FALSE
        elif op == "eq":
            if a.singleton and b.singleton and a.lo == b.lo:
                return I.TRUE
            if a.hi < b.lo or b.hi < a.lo:
                return I.FALSE
        elif op == "ne":
            if a.singleton and b.singleton and a.lo == b.lo:
                return I.FALSE
            if a.hi < b.lo or b.hi < a.lo:
                return I.TRUE
        return I.BOOL
    return f


for _name in ("lt", "le", "gt", "ge", "eq", "ne"):
    def _mk(nm):
        def h(self, eqn, vals):
            return self._ew(eqn, vals, _cmp(nm))
        return h
    _HANDLERS[_name] = _mk(_name)


@handler("select_n")
def _select_n(self, eqn, vals):
    pred, *cases = vals

    def f(p, *cs):
        if p.singleton and 0 <= p.lo < len(cs):
            return cs[p.lo]
        return I.join_all(cs)
    return self._ew(eqn, [pred] + cases, f)


@handler("is_finite")
def _is_finite(self, eqn, vals):
    return self._ew(eqn, vals, lambda a: I.BOOL)


# -- float transcendentals ---------------------------------------------------

@handler("sqrt")
def _sqrt(self, eqn, vals):
    return self._ew(eqn, vals, I.sqrt)


@handler("rsqrt", "exp", "log", "log1p", "expm1", "tanh", "erf", "logistic",
         "sin", "cos", "floor", "ceil", "round", "real", "imag")
def _float_misc(self, eqn, vals):
    dtype = str(eqn.outvars[0].aval.dtype)
    if eqn.primitive.name == "floor":
        return self._ew(eqn, vals,
                        lambda a: Interval(math.floor(a.lo), math.floor(a.hi))
                        if _finite(a) else a)
    if eqn.primitive.name == "ceil":
        return self._ew(eqn, vals,
                        lambda a: Interval(math.ceil(a.lo), math.ceil(a.hi))
                        if _finite(a) else a)
    return self._ew(eqn, vals, lambda a: I.dtype_range(dtype))


def _finite(a):
    return not (math.isinf(a.lo) or math.isinf(a.hi))


@handler("convert_element_type")
def _convert(self, eqn, vals):
    (a,) = vals
    out_dtype = str(eqn.outvars[0].aval.dtype)

    def f(v):
        lo, hi = v.lo, v.hi
        if isinstance(lo, float) or isinstance(hi, float):
            if I.is_int_dtype(out_dtype) or out_dtype == "bool":
                lo = math.floor(lo) if _finite(v) else I.dtype_range(out_dtype).lo
                hi = math.ceil(hi) if _finite(v) else I.dtype_range(out_dtype).hi
        if out_dtype == "bool":
            return Interval(1 if lo > 0 or hi < 0 else 0,
                            0 if lo == hi == 0 else 1)
        return Interval(lo, hi)
    return self._ew(eqn, vals, f, kind="convert")


@handler("bitcast_convert_type", "reduce_precision")
def _bitcast(self, eqn, vals):
    if eqn.primitive.name == "reduce_precision":
        return vals[0]
    return self._widen(eqn, "bitcast")


# -- structural ops ----------------------------------------------------------

@handler("device_put", "copy", "stop_gradient", "opt-barrier",
         "optimization_barrier")
def _identity(self, eqn, vals):
    outs = []
    for ov, v in zip(eqn.outvars, vals):
        outs.append(AbsVal(tuple(ov.aval.shape), str(ov.aval.dtype),
                           v.vec, v.tainted))
    return outs


@handler("broadcast_in_dim")
def _broadcast(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    bdims = tuple(eqn.params["broadcast_dimensions"])
    if (a.positional and bdims and bdims[-1] == len(out.shape) - 1
            and a.shape[-1] == out.shape[-1]):
        return _vec(out.shape, out.dtype, a.vec, a.tainted)
    return _uniform(out.shape, out.dtype, a.hull(), a.tainted)


@handler("reshape")
def _reshape(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    if a.positional and out.shape and out.shape[-1] == a.shape[-1]:
        return _vec(out.shape, out.dtype, a.vec, a.tainted)
    return _uniform(out.shape, out.dtype, a.hull(), a.tainted)


@handler("squeeze")
def _squeeze(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    dims = tuple(eqn.params["dimensions"])
    if a.positional and len(a.shape) - 1 not in dims:
        return _vec(out.shape, out.dtype, a.vec, a.tainted)
    return _uniform(out.shape, out.dtype, a.hull(), a.tainted)


@handler("expand_dims")
def _expand(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    if a.positional and out.shape and out.shape[-1] == a.shape[-1]:
        return _vec(out.shape, out.dtype, a.vec, a.tainted)
    return _uniform(out.shape, out.dtype, a.hull(), a.tainted)


@handler("transpose")
def _transpose(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    perm = tuple(eqn.params["permutation"])
    if a.positional and perm and perm[-1] == len(a.shape) - 1:
        return _vec(out.shape, out.dtype, a.vec, a.tainted)
    return _uniform(out.shape, out.dtype, a.hull(), a.tainted)


@handler("rev")
def _rev(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    dims = tuple(eqn.params["dimensions"])
    if a.positional and len(a.shape) - 1 in dims:
        return _vec(out.shape, out.dtype, tuple(reversed(a.vec)), a.tainted)
    return AbsVal(tuple(out.shape), str(out.dtype), a.vec, a.tainted)


@handler("iota")
def _iota(self, eqn, vals):
    out = eqn.outvars[0].aval
    dim = int(eqn.params["dimension"])
    n = out.shape[dim]
    if dim == len(out.shape) - 1 and n <= TRACK_MAX:
        return _vec(out.shape, out.dtype, tuple(I.iv(k) for k in range(n)))
    return _uniform(out.shape, out.dtype, Interval(0, max(n - 1, 0)))


@handler("concatenate")
def _concat(self, eqn, vals):
    out = eqn.outvars[0].aval
    dim = int(eqn.params["dimension"])
    tainted = any(v.tainted for v in vals)
    if dim == len(out.shape) - 1 and out.shape[-1] <= TRACK_MAX:
        vec = []
        for v in vals:
            n = v.shape[-1]
            vec.extend(v.vec if len(v.vec) == n else (v.hull(),) * n)
        return _vec(out.shape, out.dtype, vec, tainted)
    n = out.shape[-1] if out.shape else 0
    if n and n <= TRACK_MAX and all(len(v.vec) in (1, n) for v in vals):
        cols = [self._aligned(v, n) for v in vals]
        return _vec(out.shape, out.dtype,
                    [I.join_all(c[pos] for c in cols) for pos in range(n)],
                    tainted)
    return _uniform(out.shape, out.dtype,
                    I.join_all(v.hull() for v in vals), tainted)


@handler("slice")
def _slice(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    if not a.positional:
        return _uniform(out.shape, out.dtype, a.hull(), a.tainted)
    start = eqn.params["start_indices"][-1]
    limit = eqn.params["limit_indices"][-1]
    strides = eqn.params.get("strides")
    step = strides[-1] if strides else 1
    return _vec(out.shape, out.dtype, a.vec[start:limit:step], a.tainted)


@handler("pad")
def _pad(self, eqn, vals):
    a, pv = vals
    out = eqn.outvars[0].aval
    cfg = eqn.params["padding_config"]
    tainted = a.tainted or pv.tainted
    p = pv.hull()
    if not (a.positional and out.shape
            and out.shape[-1] <= TRACK_MAX):
        return _uniform(out.shape, out.dtype, I.join(a.hull(), p), tainted)
    lo, hi, inner = cfg[-1]
    vec = []
    for i, v in enumerate(a.vec):
        vec.append(v)
        if inner and i < len(a.vec) - 1:
            vec.extend([p] * inner)
    vec = [p] * max(lo, 0) + (vec[-lo:] if lo < 0 else vec)
    vec = (vec + [p] * max(hi, 0))[:None if hi >= 0 else hi]
    if any(c[0] > 0 or c[1] > 0 or c[2] > 0 for c in cfg[:-1]):
        vec = [I.join(v, p) for v in vec]
    return _vec(out.shape, out.dtype, vec, tainted)


@handler("dynamic_slice")
def _dynamic_slice(self, eqn, vals):
    a, *starts = vals
    out = eqn.outvars[0].aval
    sizes = tuple(eqn.params["slice_sizes"])
    tainted = a.tainted
    if not a.positional:
        return _uniform(out.shape, out.dtype, a.hull(), tainted)
    n, s = a.shape[-1], sizes[-1]
    if s == n:
        return _vec(out.shape, out.dtype, a.vec, tainted)
    st = starts[-1].hull()
    if st.singleton:
        c = max(0, min(int(st.lo), n - s))
        return _vec(out.shape, out.dtype, a.vec[c:c + s], tainted)
    return _uniform(out.shape, out.dtype, a.hull(), tainted)


@handler("dynamic_update_slice")
def _dus(self, eqn, vals):
    a, u, *starts = vals
    out = eqn.outvars[0].aval
    tainted = a.tainted or u.tainted
    if not a.positional:
        return _uniform(out.shape, out.dtype,
                        I.join(a.hull(), u.hull()), tainted)
    n, m = a.shape[-1], (u.shape[-1] if u.shape else 1)
    st = starts[-1].hull() if starts else I.iv(0)
    uvec = u.vec if len(u.vec) == m else (u.hull(),) * m
    vec = list(a.vec)
    if st.singleton:
        c = max(0, min(int(st.lo), n - m))
        vec[c:c + m] = uvec
    else:
        uh = u.hull()
        vec = [I.join(v, uh) for v in vec]
    return _vec(out.shape, out.dtype, vec, tainted)


@handler("gather")
def _gather(self, eqn, vals):
    a, idx = vals
    out = eqn.outvars[0].aval
    dn = eqn.params["dimension_numbers"]
    sizes = tuple(eqn.params["slice_sizes"])
    tainted = a.tainted
    fill = "FILL_OR_DROP" in str(eqn.params.get("mode", ""))
    last = len(a.shape) - 1
    if (a.positional and last not in dn.collapsed_slice_dims
            and last not in dn.start_index_map
            and sizes[last] == a.shape[-1]
            and dn.offset_dims and dn.offset_dims[-1] == len(out.shape) - 1):
        vec = a.vec
        if fill:
            vec = tuple(I.join(v, I.iv(0)) for v in vec)
        return _vec(out.shape, out.dtype, vec, tainted)
    h = a.hull()
    if fill:
        h = I.join(h, I.iv(0))
    return _uniform(out.shape, out.dtype, h, tainted)


@handler("scatter", "scatter-add")
def _scatter(self, eqn, vals):
    a, idx, u = vals
    out = eqn.outvars[0].aval
    add = eqn.primitive.name == "scatter-add"
    dn = eqn.params["dimension_numbers"]
    tainted = a.tainted or u.tainted
    last = len(a.shape) - 1
    uh = u.hull()
    # updates landing per target position: every non-window update element
    n_upd = 1
    for d, size in enumerate(u.shape):
        if d not in dn.update_window_dims:
            n_upd *= size

    def bump(v):
        if not add:
            return I.join(v, uh)
        if n_upd == 1:
            return I.add(v, uh)
        return I.add(v, Interval(min(0, n_upd * uh.lo),
                                 max(0, n_upd * uh.hi)))

    if not a.positional:
        vec = [bump(a.hull())] if add else [I.join(a.hull(), uh)]
        return self._finish(eqn, out.shape, out.dtype, vec,
                            "add" if add else None, tainted)
    vec = list(a.vec)
    trailing_window = (last not in dn.inserted_window_dims
                       and last not in dn.scatter_dims_to_operand_dims)
    if trailing_window:
        # trailing axis rides the update window: pairwise against the
        # update's own trailing positions
        un = u.shape[-1] if u.shape else 1
        uvec = u.vec if len(u.vec) == un == len(vec) else (uh,) * len(vec)
        if add and n_upd == 1 and _exact_single(dn, idx, a):
            vec = [I.add(v, uu) for v, uu in zip(vec, uvec)]
        elif add:
            vec = [I.add(v, Interval(min(0, n_upd * uu.lo),
                                     max(0, n_upd * uu.hi)))
                   for v, uu in zip(vec, uvec)]
        else:
            vec = [I.join(v, uu) for v, uu in zip(vec, uvec)]
        return self._finish(eqn, out.shape, out.dtype, vec,
                            "add" if add else None, tainted)
    ih = idx.hull()
    if (tuple(dn.scatter_dims_to_operand_dims) == (last,) and ih.singleton
            and n_upd == 1):
        k = int(ih.lo)
        if 0 <= k < len(vec):
            vec[k] = I.add(vec[k], uh) if add else uh
        return self._finish(eqn, out.shape, out.dtype, vec,
                            "add" if add else None, tainted)
    vec = [bump(v) for v in vec]
    return self._finish(eqn, out.shape, out.dtype, vec,
                        "add" if add else None, tainted)


def _exact_single(dn, idx, a):
    return False   # conservative: window updates may overlap


# -- reductions --------------------------------------------------------------

@handler("reduce_sum")
def _reduce_sum(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    axes = tuple(eqn.params["axes"])
    n_red = 1
    for ax in axes:
        n_red *= a.shape[ax]
    last = len(a.shape) - 1
    tainted = a.tainted
    if a.positional and last in axes:
        m = n_red // a.shape[-1]
        total = I.iv(0)
        for v in a.vec:
            total = I.add(total, v)
        return self._finish(eqn, out.shape, out.dtype,
                            [I.scale(total, max(m, 1))], "add", tainted)
    if a.positional and last not in axes:
        vec = [I.scale(v, n_red) for v in a.vec]
        return self._finish(eqn, out.shape, out.dtype, vec, "add", tainted)
    return self._finish(eqn, out.shape, out.dtype,
                        [I.scale(a.hull(), n_red)], "add", tainted)


@handler("reduce_max", "reduce_min", "reduce_and", "reduce_or", "cummax",
         "cummin")
def _reduce_minmax(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    axes = tuple(eqn.params.get("axes", (eqn.params.get("axis", 0),)))
    last = len(a.shape) - 1
    if a.positional and last in axes:
        return _vec(out.shape, out.dtype, (a.hull(),), a.tainted)
    return AbsVal(tuple(out.shape), str(out.dtype), a.vec, a.tainted)


@handler("reduce_prod")
def _reduce_prod(self, eqn, vals):
    return self._widen(eqn, "product reduction")


@handler("argmax", "argmin")
def _argminmax(self, eqn, vals):
    out = eqn.outvars[0].aval
    axes = tuple(eqn.params["axes"])
    n = max(vals[0].shape[ax] for ax in axes)
    return _uniform(out.shape, out.dtype, Interval(0, max(n - 1, 0)))


@handler("cumsum")
def _cumsum(self, eqn, vals):
    (a,) = vals
    out = eqn.outvars[0].aval
    axis = int(eqn.params["axis"])
    last = len(a.shape) - 1
    if a.positional and axis == last and not eqn.params.get("reverse"):
        vec, run = [], I.iv(0)
        for v in a.vec:
            run = I.add(run, v)
            vec.append(run)
        return self._finish(eqn, out.shape, out.dtype, vec, "add", a.tainted)
    n = a.shape[axis]
    if a.positional and axis != last:
        vec = [I.scale(v, n) for v in a.vec]
        return self._finish(eqn, out.shape, out.dtype, vec, "add", a.tainted)
    return self._finish(eqn, out.shape, out.dtype,
                        [I.scale(a.hull(), n)], "add", a.tainted)


@handler("sort")
def _sort(self, eqn, vals):
    out_avals = [ov.aval for ov in eqn.outvars]
    dim = int(eqn.params["dimension"])
    outs = []
    for ov, v in zip(out_avals, vals):
        if dim == len(v.shape) - 1:
            outs.append(_vec(ov.shape, ov.dtype, (v.hull(),), v.tainted))
        else:
            outs.append(AbsVal(tuple(ov.shape), str(ov.dtype), v.vec,
                               v.tainted))
    return outs


@handler("dot_general")
def _dot_general(self, eqn, vals):
    a, b = vals
    out = eqn.outvars[0].aval
    ((lc, rc), _) = eqn.params["dimension_numbers"]
    n = 1
    for d in lc:
        n *= a.shape[d]
    prod = I.mul(a.hull(), b.hull())
    return self._finish(eqn, out.shape, out.dtype,
                        [I.scale(prod, max(n, 1))], "mul",
                        a.tainted or b.tainted)


# -- control flow ------------------------------------------------------------

def _invariant_avals(self, spec, carry_avals) -> Optional[List[AbsVal]]:
    """Materialize a declared invariant for a loop's carry avals."""
    def one(entry, aval):
        if entry in (None, "dtype"):
            return for_aval(aval, None)
        return for_aval(aval, entry)
    if spec in ("dtype",):
        return [for_aval(av, None) for av in carry_avals]
    if isinstance(spec, dict):
        return [one(spec, av) for av in carry_avals]
    if isinstance(spec, (list, tuple)):
        assert len(spec) == len(carry_avals), \
            f"invariant arity {len(spec)} != carry arity {len(carry_avals)}"
        return [one(e, av) for e, av in zip(spec, carry_avals)]
    return None


def _within(val: AbsVal, inv: AbsVal) -> bool:
    if len(inv.vec) == 1:
        h = inv.vec[0]
        return all(v.within(h) for v in val.vec)
    if len(val.vec) == len(inv.vec):
        return all(v.within(w) for v, w in zip(val.vec, inv.vec))
    return val.hull().within(inv.hull())


def _loop_fallback(self, eqn, body_closed, consts, init, n_carry,
                   what) -> List[AbsVal]:
    """Invariant path for a loop the interpreter could not unroll."""
    carry_avals = [v.aval for v in body_closed.jaxpr.invars[
        len(consts):len(consts) + n_carry]]
    spec = (self.invariants[self._loop_idx]
            if self._loop_idx < len(self.invariants) else None)
    self._loop_idx += 1
    inv = _invariant_avals(self, spec, carry_avals) if spec is not None \
        else None
    if inv is None:
        self._emit("CSA1403",
                   f"{what} beyond the unroll window with no declared "
                   f"invariant; carries widened to their dtype ranges", eqn)
        inv = [dataclasses.replace(for_aval(av, None), tainted=True)
               for av in carry_avals]
        entry_ok = True
    else:
        entry_ok = all(_within(v, w) for v, w in zip(init, inv))
        if not entry_ok:
            self._emit("CSA1401",
                       f"{what} invariant does not hold at loop entry", eqn)
    return inv, spec is not None and entry_ok


@handler("while")
def _while(self, eqn, vals):
    cn = int(eqn.params["cond_nconsts"])
    bn = int(eqn.params["body_nconsts"])
    cond = eqn.params["cond_jaxpr"]
    body = eqn.params["body_jaxpr"]
    cond_consts, body_consts = vals[:cn], vals[cn:cn + bn]
    carry = list(vals[cn + bn:])
    init = list(carry)
    for _ in range(self.max_unroll):
        pred = self.eval_closed(cond, cond_consts + carry)[0].hull()
        if pred == I.FALSE:
            return carry
        if pred != I.TRUE:
            break
        carry = self.eval_closed(body, body_consts + carry)
    else:
        pred = I.BOOL
    inv, check = _loop_fallback(self, eqn, body, body_consts, init,
                                len(init), "while loop")
    if check:
        out = self.eval_closed(body, body_consts + inv)
        if not all(_within(v, w) for v, w in zip(out, inv)):
            self._emit("CSA1401",
                       "while-loop body escapes the declared invariant; "
                       "carries widened to their dtype ranges", eqn)
            inv = [dataclasses.replace(for_aval(w.aval, None), tainted=True)
                   for w in eqn.outvars]
    return inv


@handler("scan")
def _scan(self, eqn, vals):
    params = eqn.params
    nc, n_carry = int(params["num_consts"]), int(params["num_carry"])
    length = int(params["length"])
    body = params["jaxpr"]
    consts = vals[:nc]
    carry = list(vals[nc:nc + n_carry])
    xs = vals[nc + n_carry:]
    xs_slices = []
    for x in xs:
        inner_shape = tuple(x.shape[1:])
        vec = x.vec if (inner_shape and len(x.vec) == inner_shape[-1]) \
            else (x.hull(),)
        xs_slices.append(AbsVal(inner_shape, x.dtype, vec, x.tainted))
    n_ys = len(eqn.outvars) - n_carry
    ys_join: List[Optional[AbsVal]] = [None] * n_ys

    def note_ys(ys):
        for i, y in enumerate(ys):
            if ys_join[i] is None:
                ys_join[i] = y
            else:
                prev = ys_join[i]
                n = max(len(prev.vec), len(y.vec))
                pv = self._aligned(prev, n)
                yv = self._aligned(y, n)
                ys_join[i] = AbsVal(y.shape, y.dtype,
                                    tuple(I.join(p, q)
                                          for p, q in zip(pv, yv)),
                                    prev.tainted or y.tainted)

    if length <= self.max_unroll:
        for _ in range(length):
            outs = self.eval_closed(body, consts + carry + xs_slices)
            carry = outs[:n_carry]
            note_ys(outs[n_carry:])
    else:
        inv, check_idx = _scan_invariants(self, eqn, body, nc, n_carry,
                                          carry, length)
        if check_idx:
            outs = self.eval_closed(body, consts + inv + xs_slices)
            if not all(_within(outs[k], inv[k]) for k in check_idx):
                self._emit("CSA1401",
                           "scan body escapes the declared invariant; "
                           "carries widened to their dtype ranges", eqn)
                inv = [dataclasses.replace(
                    for_aval(v.aval, None), tainted=True)
                    for v in body.jaxpr.invars[nc:nc + n_carry]]
                outs = self.eval_closed(body, consts + inv + xs_slices)
        else:
            outs = self.eval_closed(body, consts + inv + xs_slices)
        carry = inv
        note_ys(outs[n_carry:])

    result = list(carry)
    for i, ov in enumerate(eqn.outvars[n_carry:]):
        y = ys_join[i]
        if y is None:
            y = for_aval(ov.aval, None)
        result.append(AbsVal(tuple(ov.aval.shape), str(ov.aval.dtype),
                             y.vec, y.tainted))
    return [AbsVal(tuple(ov.aval.shape), str(ov.aval.dtype), v.vec,
                   v.tainted)
            for ov, v in zip(eqn.outvars, result)]


def _counter_bound(body, nc, k, init, length):
    """Exact range of a scan carry that is a pure counter (`c + const`,
    what fori_loop's index lowers to) or a passthrough — those have no
    inductive interval (a counter strictly increases), but their image
    over `length` trips is closed-form."""
    j = body.jaxpr
    outv = j.outvars[k]
    carry_in = j.invars[nc + k]
    if outv is carry_in:
        return init.hull()                       # loop-invariant carry
    for e in j.eqns:
        if any(ov is outv for ov in e.outvars):
            if e.primitive.name not in ("add", "sub"):
                return None
            a, b = e.invars
            lit = None
            if a is carry_in and hasattr(b, "val"):
                lit = int(b.val)
                if e.primitive.name == "sub":
                    lit = -lit
            elif b is carry_in and hasattr(a, "val") \
                    and e.primitive.name == "add":
                lit = int(a.val)
            if lit is None:
                return None
            h = init.hull()
            # `length` full steps: the carry OUT of the final iteration
            # is init + length*lit (the hull covers every intermediate
            # value AND the loop's returned final value)
            step = lit * max(length, 0)
            return Interval(h.lo + min(0, step), h.hi + max(0, step))
    return None


def _scan_invariants(self, eqn, body, nc, n_carry, init, length):
    """Carry intervals for a scan beyond the unroll window: counters
    bound in closed form, everything else from the contract's declared
    invariant (checked inductively by the caller over `check_idx`);
    missing declarations widen to the dtype range with CSA1403."""
    spec = (self.invariants[self._loop_idx]
            if self._loop_idx < len(self.invariants) else None)
    self._loop_idx += 1
    entries = None
    if isinstance(spec, (list, tuple)):
        assert len(spec) == n_carry, (len(spec), n_carry)
        entries = list(spec)
    elif spec is not None:
        entries = [spec] * n_carry
    carry_avals = [v.aval for v in body.jaxpr.invars[nc:nc + n_carry]]
    inv, check_idx, missing = [], [], False
    for k, aval in enumerate(carry_avals):
        auto = _counter_bound(body, nc, k, init[k], length)
        if auto is not None:
            inv.append(_uniform(aval.shape, aval.dtype, auto,
                                init[k].tainted))
            continue
        entry = entries[k] if entries is not None else None
        if entry in (None, "dtype"):
            if entries is None:
                missing = True
            inv.append(dataclasses.replace(for_aval(aval, None),
                                           tainted=True))
        else:
            val = for_aval(aval, entry)
            if not _within(init[k], val):
                self._emit("CSA1401",
                           f"scan of length {length}: declared invariant "
                           f"does not hold at loop entry (carry {k})", eqn)
            inv.append(val)
            check_idx.append(k)
    if missing:
        self._emit("CSA1403",
                   f"scan of length {length} beyond the unroll window "
                   f"with no declared invariant; non-counter carries "
                   f"widened to their dtype ranges", eqn)
    return inv, check_idx


@handler("cond")
def _cond(self, eqn, vals):
    idx, *ops = vals
    branches = eqn.params["branches"]
    h = idx.hull()
    if h.singleton and 0 <= h.lo < len(branches):
        return self.eval_closed(branches[int(h.lo)], ops)
    outs = None
    for br in branches:
        res = self.eval_closed(br, ops)
        if outs is None:
            outs = res
        else:
            outs = [AbsVal(a.shape, a.dtype,
                           tuple(I.join(p, q) for p, q in zip(
                               self._aligned(a, max(len(a.vec), len(b.vec))),
                               self._aligned(b, max(len(a.vec), len(b.vec))))),
                           a.tainted or b.tainted)
                    for a, b in zip(outs, res)]
    return outs


# -- named-jit summaries (exact images of the intmath helpers) ---------------

def _sum_isqrt(self, eqn, in_vals):
    (n,) = in_vals
    out = eqn.outvars[0].aval
    h = n.hull()
    return [_uniform(out.shape, out.dtype, I.isqrt(h), n.tainted)]


def _sum_muldiv(self, eqn, in_vals):
    a, b, d = in_vals
    out = eqn.outvars[0].aval
    ah, bh, dh = a.hull(), b.hull(), d.hull()
    if dh.lo < 1 or ah.lo < 0 or bh.lo < 0:
        return None
    top = I.dtype_range(out.dtype).hi
    lo = min(ah.lo * bh.lo // dh.hi, top)
    hi = ah.hi * bh.hi // dh.lo
    # the static bound escaping the dtype means the proof leans on the
    # helper's documented caller guarantee (quotient fits 64 bits) —
    # taint so that assumption is not silently compounded downstream
    assumed = hi > top
    return [_uniform(out.shape, out.dtype, Interval(lo, min(hi, top)),
                     a.tainted or b.tainted or d.tainted or assumed)]


def _sum_mulwide(self, eqn, in_vals):
    a, b = in_vals
    ah, bh = a.hull(), b.hull()
    if ah.lo < 0 or bh.lo < 0:
        return None
    p = I.mul(ah, bh)
    tainted = a.tainted or b.tainted
    hi_aval, lo_aval = eqn.outvars[0].aval, eqn.outvars[1].aval
    hi = Interval(p.lo >> 64, p.hi >> 64)
    lo = Interval(p.lo, p.hi) if p.hi < (1 << 64) \
        else I.dtype_range(lo_aval.dtype)
    return [_uniform(hi_aval.shape, hi_aval.dtype, hi, tainted),
            _uniform(lo_aval.shape, lo_aval.dtype, lo, tainted)]


def _sum_carry_rounds(self, eqn, in_vals):
    """Exact positional transfer of ops/fq._carry_rounds (jitted so the
    boundary is visible here). Per round, per element:

        new[0]   = old[0] & MASK
        new[k]   = (old[k] & MASK) + (old[k-1] >> B)      0 < k < top
        new[top] = old[top] + (old[top-1] >> B)

    the top identity because (x & MASK) + ((x >> B) << B) == x — the
    algebraic cancellation the interval domain cannot see positionally
    (it would otherwise grow the top limb ~2^29 per round). The round
    count is read back off the staged body (one scatter-add per round)."""
    (a,) = in_vals
    if not a.positional:
        return None                      # recurse: still sound, just loose
    from consensus_specs_tpu.ops.fq import B, MASK
    inner = eqn.params.get("jaxpr")
    n = sum(1 for e in inner.jaxpr.eqns
            if e.primitive.name == "scatter-add") if inner is not None else 0
    if n == 0:
        return None
    shift = I.iv(B)
    mask = Interval(0, MASK)

    def lo_part(v):
        return v if (v.lo >= 0 and v.hi <= MASK) else mask

    vec = list(a.vec)
    for _ in range(n):
        new = [lo_part(vec[0])]
        for k in range(1, len(vec)):
            new.append(I.add(lo_part(vec[k]), I.ashr(vec[k - 1], shift)))
        new[-1] = I.add(vec[-1], I.ashr(vec[-2], shift))
        vec = new
    out = eqn.outvars[0].aval
    return [self._finish(eqn, tuple(out.shape), out.dtype, vec, "add",
                         a.tainted)]


def _sum_roll(self, eqn, in_vals):
    """jnp.roll is a permutation: its image is exactly the operand's
    interval. The summary also sidesteps jnp's negative-start
    normalization arm (`start + 2n`), whose ideal value exceeds int32
    near the 2^30 shuffle ceiling on a branch the select provably
    discards — a dead-arm wrap the interval domain would otherwise
    flag."""
    a = in_vals[0]
    out = eqn.outvars[0].aval
    return [_uniform(out.shape, out.dtype, a.hull(), a.tainted)]


SUMMARIES = {
    "isqrt_u64": _sum_isqrt,
    "muldiv_u64": _sum_muldiv,
    "mulwide_u64": _sum_mulwide,
    "_carry_rounds_impl": _sum_carry_rounds,
    "_roll_dynamic": _sum_roll,
    "_roll_static": _sum_roll,
}


@handler("pjit", "closed_call", "core_call", "xla_call", "remat",
         "remat_call", "checkpoint", "custom_jvp_call", "custom_vjp_call",
         "custom_jvp_call_jaxpr", "custom_vjp_call_jaxpr")
def _call(self, eqn, vals):
    name = eqn.params.get("name")
    summary = SUMMARIES.get(name)
    if summary is not None:
        outs = summary(self, eqn, vals)
        if outs is not None:
            return outs
    inner = None
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in eqn.params:
            inner = eqn.params[key]
            break
    if inner is None:
        return self._widen(eqn, f"opaque call {name or ''}")
    if hasattr(inner, "consts"):
        return self.eval_closed(inner, vals)
    return self.eval_jaxpr(inner, [], vals)
