"""Value-range tier: an interval abstract interpreter over the REAL
jaxprs that machine-checks the limb-overflow and wrap-semantics budgets.

The trace tier (tools/analysis/trace/) counts ops; this tier bounds
VALUES. The double-width lazy-Montgomery fast path (ops/fq.py, PR 5,
Aranha et al. EUROCRYPT 2011) is only correct while wide accumulation
columns stay inside `|col| < 2^35` and narrow limbs inside the
`[-1, 2^29]` budget — claims that used to live as docstring prose and a
syntactic notice (CSA901) that pattern-matches source, not values. Here
they are theorems: kernel modules export `RANGE_CONTRACTS` lists (the
TRACE_CONTRACTS idiom) declaring per-argument input intervals, and the
interpreter (ranges/interp.py) propagates per-element magnitude
intervals through the traced program — positionally along the trailing
(limb/column) axis, so structural facts like "schoolbook column 27 is
identically zero" survive — and proves the declared output bounds plus
the absence of undeclared integer wraparound.

`fori_loop`/`scan` are handled by exact abstract unrolling when the
trip count is small and statically evident, else inductively: the
contract supplies the loop invariant, the interpreter checks the body
maps invariant -> invariant, and otherwise widens the carries to the
dtype range and flags. Intentional modular arithmetic (SHA-256's
mod-2^32 words, the justification bitfield's shifted uint64) is
DECLARED (`wrap_ok`, or an inline `# csa: ignore[CSA1401]` at the
wrapping site), never inferred.

  CSA1401  proved-overflow violation   (a wrap the input bounds cannot
                                        exclude, a declared output bound
                                        the interpreter cannot prove, or
                                        a loop invariant the body escapes)
  CSA1402  unprovable-op notice        (an op the interpreter cannot
                                        model — result widened to the
                                        dtype range; the proof degrades,
                                        visibly)
  CSA1403  missing loop invariant      (a loop beyond the unroll window
                                        with no declared invariant)
  CSA1404  stale range contract        (proven intervals regressed vs the
                                        committed ranges_baseline.json,
                                        or a contract with no snapshot)

Entry points:

  python -m tools.analysis --ranges [--ranges-baseline b.json]
                                    [--update-ranges-baseline]
                                    [--json out/ranges.json]
  make ranges

This module registers the rule catalog only (stdlib, importable by the
no-jax lint lane for `--list-rules`); interval.py, interp.py and
engine.py are loaded lazily by the CLI's --ranges path, by tests, and
by bench.py's range-snapshot row.
"""
from ..core import register_rule

register_rule(
    "CSA1401",
    "proved overflow: a traced op can wrap, or a declared range bound "
    "fails",
    "error",
    "the interpreter derived an interval that escapes the dtype (or the "
    "contract's declared output/invariant bound) from the declared input "
    "ranges — tighten the kernel, widen the contract in the same "
    "reviewable diff, or declare the wrap intentional (wrap_ok / inline "
    "suppression at the wrapping site)",
)
register_rule(
    "CSA1402",
    "unprovable op: the interval interpreter widened a value to the "
    "dtype range",
    "notice",
    "an unmodeled primitive or a possible division-by-zero degraded the "
    "proof at this op; the widened value is tracked (not flagged again "
    "downstream) — extend ranges/interp.py or refine the input ranges",
)
register_rule(
    "CSA1403",
    "loop beyond the unroll window with no declared range invariant",
    "error",
    "declare the carry invariant in the contract (`invariants`, checked "
    "inductively: body must map invariant -> invariant) — without one "
    "the carries widen to the dtype range and the proof is vacuous",
)
register_rule(
    "CSA1404",
    "range-contract snapshot drift vs the committed ranges baseline",
    "error",
    "proven intervals only loosen by a reviewed edit: run "
    "`python -m tools.analysis --ranges --update-ranges-baseline` and "
    "commit tools/analysis/ranges_baseline.json in the diff that "
    "explains the new bound",
)

RANGE_RULE_IDS = ("CSA1401", "CSA1402", "CSA1403", "CSA1404")
