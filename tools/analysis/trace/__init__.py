"""Trace-tier contract analyzer: op-budget ratchets and lowered-program
hygiene over the REAL jaxprs/StableHLO of the hot kernels.

PRs 4/5/6 bought their wins as op-count invariants (256->72 dependent
adds, 54->12 REDC lanes, zero re-layout on chained steps). The AST tier
(tools/analysis/passes/) cannot see those: they are properties of the
*traced programs*, not the source. This tier traces and lowers the
actual jitted programs and checks them against declarative **kernel
contracts** exported by the modules that own the kernels
(`TRACE_CONTRACTS` lists in consensus_specs_tpu/ops/*.py,
parallel/sharding.py, models/phase0/epoch_soa.py,
utils/ssz/incremental.py), ratcheting measured values against the
committed `tools/analysis/trace_baseline.json`:

  CSA11xx  jaxpr op-budget ratchet   (REDC lanes, dependent jac_add
                                      chains, pair-hash lanes, graph size)
  CSA12xx  lowered-program hygiene   (f64 ops, host callbacks,
                                      device_put inside jit, dropped
                                      donation)
  CSA13xx  collective/layout drift   (collective inventory, chained
                                      out_shardings != next in_shardings)

The ratchet: tightening a budget requires touching the contract (next
to the kernel), loosening one requires touching the baseline — both
reviewable diffs.  Entry points:

  python -m tools.analysis --trace [--trace-baseline b.json]
                                   [--update-trace-baseline]
                                   [--json out/contracts.json]
  make contracts

This module registers the rule catalog only (stdlib, importable by the
no-jax lint lane for `--list-rules`); tracer.py and engine.py import
jax and are loaded lazily by the CLI's --trace path, by tests, and by
bench.py's contract-snapshot row.
"""
from ..core import register_rule

# -- CSA11xx: jaxpr op-budget ratchet ---------------------------------------

register_rule(
    "CSA1101",
    "traced op count violates the kernel contract's declared budget",
    "error",
    "the budget lives next to the kernel (TRACE_CONTRACTS); fix the "
    "kernel regression, or change the contract in the same diff that "
    "justifies the new cost",
)
register_rule(
    "CSA1102",
    "traced op count regressed vs the committed trace baseline",
    "error",
    "the committed snapshot (tools/analysis/trace_baseline.json) only "
    "loosens by a reviewed edit: update the entry (or run "
    "--update-trace-baseline) in the same diff that explains the cost",
)
register_rule(
    "CSA1103",
    "traced op count improved below the committed trace baseline",
    "notice",
    "tighten the ratchet: refresh the baseline entry "
    "(--update-trace-baseline) so the win cannot silently regress",
)
register_rule(
    "CSA1104",
    "kernel contract metric has no committed trace-baseline entry",
    "error",
    "run `python -m tools.analysis --trace --update-trace-baseline` and "
    "commit the snapshot: a new contract without a baseline has no "
    "ratchet",
)

# -- CSA12xx: lowered-program hygiene ---------------------------------------

register_rule(
    "CSA1201",
    "f64 ops in the lowered program of an f64-forbidding contract",
    "error",
    "a silent float64 upcast doubles lane width and is rejected (or "
    "software-emulated) on TPU; trace the upcast to a weak-typed float "
    "literal or a missing dtype= and pin it",
)
register_rule(
    "CSA1202",
    "host callback staged inside a hot jitted program",
    "error",
    "pure_callback/io_callback/debug round-trips the host every call — "
    "hoist the host work out of the traced program",
)
register_rule(
    "CSA1203",
    "device_put with an explicit placement staged inside a hot jitted "
    "program",
    "error",
    "a targeted device_put under jit records a mid-program transfer/"
    "re-placement in the compiled artifact; place inputs before the "
    "call (the resident/ServingMesh pattern) instead",
)
register_rule(
    "CSA1204",
    "declared donation dropped in lowering",
    "error",
    "the contract declares donate_argnums but the lowered program "
    "carries fewer tf.aliasing_output annotations than the contract's "
    "donate_min — the buffer reuse the epoch boundary depends on is "
    "silently gone",
)

# -- CSA13xx: collective/layout inventory drift -----------------------------

register_rule(
    "CSA1301",
    "collective inventory drift vs the kernel contract",
    "error",
    "the compiled program's collective kinds differ from the contract's "
    "declared inventory — a new all-to-all/all-gather on the serving "
    "path is cross-device traffic the mesh design did not budget",
)
register_rule(
    "CSA1302",
    "chained program's lowered out-shardings disagree with its "
    "in-shardings",
    "error",
    "the pjit staging contract (SNIPPETS.md [1][2], runtime twin: "
    "telemetry/watchdog.layout_check): a chained step whose lowered "
    "result sharding differs from the matching operand sharding "
    "re-lays data out on every call",
)

TRACE_RULE_IDS = tuple(
    f"CSA{n}" for n in (1101, 1102, 1103, 1104,
                        1201, 1202, 1203, 1204, 1301, 1302))
