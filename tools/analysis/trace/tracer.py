"""Shared tracer library: walk real jaxprs, lower to StableHLO text,
count tagged op classes.

This is the one home of the jaxpr-walking op models that used to be
hand-rolled in tests/test_fq_redc.py (`_iter_subjaxprs` /
`qinv_mul_lanes` / `_fresh_jaxpr`) and tests/test_scalar_mul.py (the
monkeypatched sequential-add counter): the contract engine
(tools/analysis/trace/engine.py) and the op-count tests now both assert
through these helpers, so the REDC/add op models have one source of
truth.

Unlike the rest of tools/analysis this module imports jax (it operates
on programs, not source); the AST tier never loads it.
"""
from __future__ import annotations

import contextlib
import re
from collections import Counter
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

import jax


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------

def iter_subjaxprs(params) -> Iterable[Tuple[object, list]]:
    """Yield (jaxpr, consts) for every sub-jaxpr in an eqn's params —
    fori/scan/cond/custom_* bodies, nested arbitrarily in lists/tuples."""
    for v in params.values():
        stack = [v]
        while stack:
            x = stack.pop()
            if isinstance(x, jax.core.ClosedJaxpr):
                yield x.jaxpr, x.consts
            elif isinstance(x, jax.core.Jaxpr):
                yield x, []
            elif isinstance(x, (list, tuple)):
                stack.extend(x)


def fresh_jaxpr(fn, *xs, **kwargs):
    """Trace through a FRESH wrapper so jax's trace cache (keyed on
    function identity + avals, blind to backend globals like
    CSTPU_FQ_REDC) cannot hand back another mode's jaxpr — the very
    staleness ops/bls_jax.py's mode-keyed jitted programs exist to
    prevent."""
    return jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*xs)


def walk_eqns(closed):
    """Yield (eqn, const_env) for every eqn in a closed jaxpr including
    every sub-jaxpr body (loop bodies count ONCE — these are
    traced-graph walks, not execution counts). const_env maps the
    enclosing jaxpr's constvars to their values."""
    stack = [(closed.jaxpr, closed.consts)]
    while stack:
        jaxpr, consts = stack.pop()
        env = dict(zip(jaxpr.constvars, consts))
        for eqn in jaxpr.eqns:
            stack.extend(iter_subjaxprs(eqn.params))
            yield eqn, env


def _scalar_const_of(invar, env) -> Optional[int]:
    if isinstance(invar, jax.core.Literal):
        val = invar.val
    elif invar in env:
        val = env[invar]
    else:
        return None
    if np.ndim(val) == 0:
        try:
            return int(val)
        except (TypeError, ValueError):
            return None
    return None


def qinv_mul_lanes(closed) -> int:
    """Total REDC lanes in a traced program, read off the jaxpr itself:
    each REDC instance multiplies by the Montgomery constant QINV_NEG
    exactly L times (once per interleaved-reduction step), and each such
    multiply's shape is the stacked lane batch. Nothing else multiplies
    by that 29-bit constant, so lanes = sum(prod(shape)) / L."""
    from consensus_specs_tpu.ops import fq as F
    total = scan_program(closed, tagged_const=F.QINV_NEG)["tagged_lanes"]
    assert total % F.L == 0, total
    return total // F.L


def scan_program(closed, tagged_const: Optional[int] = None) -> dict:
    """ONE traversal computing everything the contract engine reads off
    a traced graph (the big pairing programs run to ~150k eqns — walking
    them once instead of once per check keeps `make contracts` fast):

      eqns            whole-graph eqn count (sub-jaxprs included) — the
                      coarse program-size ratchet
      tagged_lanes    output lanes of `mul`-by-`tagged_const` eqns (pick
                      a constant nothing else multiplies by and the op
                      class reads straight off the graph — QINV_NEG)
      callbacks       host-callback primitive names staged (pure_ /
                      io_ / debug_callback, debug_print)
      device_puts     device_put eqns with an EXPLICIT placement target
                      (a device/sharding) — a mid-program transfer.
                      Target-less puts do not count: that is how
                      jnp.asarray stages trace-time constants (the
                      `_Q_SHIFTS` idiom — jax threads them through loop
                      bodies as ALIAS/devices=[None] puts), and a bare
                      jax.device_put(x) under jit is a no-op
      f64_ops         eqns with a float64 output aval
    """
    eqns = 0
    tagged = 0
    callbacks = set()
    device_puts = 0
    f64_ops = 0
    for eqn, env in walk_eqns(closed):
        eqns += 1
        name = eqn.primitive.name
        if any(f in name for f in _CALLBACK_FRAGMENTS):
            callbacks.add(name)
        if name == "device_put":
            targets = list(eqn.params.get("devices", ())) \
                + list(eqn.params.get("srcs", ()))
            if any(t is not None for t in targets):
                device_puts += 1
        if any(getattr(ov.aval, "dtype", None) == np.float64
               for ov in eqn.outvars):
            f64_ops += 1
        if tagged_const is not None and name == "mul":
            for iv in eqn.invars:
                if _scalar_const_of(iv, env) == tagged_const:
                    tagged += int(np.prod(eqn.outvars[0].aval.shape,
                                          dtype=np.int64))
                    break
    return {"eqns": eqns, "tagged_lanes": tagged,
            "callbacks": sorted(callbacks), "device_puts": device_puts,
            "f64_ops": f64_ops}


_CALLBACK_FRAGMENTS = ("callback", "debug_print")


# ---------------------------------------------------------------------------
# Lowering (StableHLO text) and compiled-HLO scans
# ---------------------------------------------------------------------------

def donated_count(text: str) -> int:
    """tf.aliasing_output annotations in the lowered signature — one per
    flattened donated argument that survived lowering."""
    return text.count("tf.aliasing_output")


# An HLO *instruction* whose opcode is a collective: the opcode token sits
# right before its operand list's "(" and is never "%"-prefixed (operand
# REFERENCES like `%all-reduce.1` are — counting those would measure uses,
# not ops). `-start` async halves carry the op; `-done` (whose opcode ends
# in -done, so the "(" never directly follows the base name) does not.
_COLLECTIVE_RE = re.compile(
    r"(?<!%)\b(all-reduce|all-gather|all-to-all|reduce-scatter|"
    r"collective-permute|collective-broadcast)(?:-start)?\(")


def collective_inventory(text: str) -> Dict[str, int]:
    """collective kind -> instruction count in a compiled-HLO text."""
    counts: Counter = Counter()
    for line in text.splitlines():
        if "=" not in line:
            continue
        m = _COLLECTIVE_RE.search(line.split("=", 1)[1])
        if m:
            counts[m.group(1)] += 1
    return dict(counts)


def _split_top_level(s: str) -> list:
    """Split on commas not nested in (), <>, {}, [] or quotes."""
    out, depth, start, in_str = [], 0, 0, False
    for i, ch in enumerate(s):
        if in_str:
            if ch == '"':
                in_str = False
            continue
        if ch == '"':
            in_str = True
        elif ch in "(<{[":
            depth += 1
        elif ch in ")>}]":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(s[start:i].strip())
            start = i + 1
    tail = s[start:].strip()
    if tail:
        out.append(tail)
    return out


_SHARDING_ATTR_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')


def signature_shardings(text: str):
    """(arg_shardings, result_shardings) of the @main function of a
    lowered StableHLO module: per flattened arg/result, the
    mhlo.sharding attribute string or None when unannotated."""
    anchor = text.index("func.func public @main(")
    i = text.index("(", anchor)
    depth, j = 0, i
    while True:
        if text[j] == "(":
            depth += 1
        elif text[j] == ")":
            depth -= 1
            if depth == 0:
                break
        j += 1
    args_src = text[i + 1:j]
    rest = text[j:]
    arrow = rest.index("->")
    k = rest.index("(", arrow)
    depth, m = 0, k
    while True:
        if rest[m] == "(":
            depth += 1
        elif rest[m] == ")":
            depth -= 1
            if depth == 0:
                break
        m += 1
    results_src = rest[k + 1:m]

    def shard_of(entry: str):
        m2 = _SHARDING_ATTR_RE.search(entry)
        return m2.group(1) if m2 else None

    return ([shard_of(e) for e in _split_top_level(args_src)],
            [shard_of(e) for e in _split_top_level(results_src)])


# ---------------------------------------------------------------------------
# Counted call chains (the sequential-add cost model's measurement arm)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def counted_calls(module, names: Tuple[str, ...]):
    """Wrap `module.<name>` for each name with a counting shim (callees
    resolved through the module's own globals are counted too); yields
    the live {name: count} dict and restores the originals on exit."""
    counts = {n: 0 for n in names}
    originals = {n: getattr(module, n) for n in names}

    def wrap(name, real):
        def counted(*args, **kwargs):
            counts[name] += 1
            return real(*args, **kwargs)
        return counted

    for n in names:
        setattr(module, n, wrap(n, originals[n]))
    try:
        yield counts
    finally:
        for n in names:
            setattr(module, n, originals[n])


@contextlib.contextmanager
def counted_point_ops():
    """Count the REAL jac_add / jac_double chain of an (eager, unrolled)
    scalar-mul evaluation — the windowed kernel resolves both through
    ops/scalar_mul.py's module globals, so wrapping there sees every
    dependent step. Yields {"jac_add": n, "jac_double": n}. NOTE the
    cost-model convention: every jac_add internally evaluates one
    jac_double (the branch-free P1 == P2 fallback), so the *dependent
    doubling chain* is counts["jac_double"] - counts["jac_add"]."""
    from consensus_specs_tpu.ops import scalar_mul as SM
    with counted_calls(SM, ("jac_add", "jac_double")) as counts:
        yield counts
