"""Kernel-contract engine: discover TRACE_CONTRACTS, trace/lower the
real programs, ratchet measured values against the committed baseline.

A **contract** is a plain dict a kernel module exports in its
`TRACE_CONTRACTS` list (plain data so the package never imports
tools.*; the engine imports the kernel modules, not the reverse):

    name             unique id, e.g. "ops.fq_tower.fq12_mul[coeff]"
    build            () -> {"fn": traceable, "args": tuple,
                            "jit_kwargs": dict (optional),
                            "context": () -> contextmanager (optional,
                              e.g. pinning CSTPU_FQ_REDC for tracing)}
                     (optional when the contract only has `measure`)
    budgets          {metric: int} — declared maxima. Engine-computed
                     metrics: "redc_lanes" (QINV-tagged multiply lanes
                     / L), "jaxpr_eqns" (whole-graph eqn count),
                     "seq_adds"/"seq_doubles" (with count_point_ops),
                     "collective_ops" (with collectives). Any other
                     name must come from `measure`.
    exact            metric names that must EQUAL the budget — drift in
                     either direction is a contract violation (the lane
                     counts: an improvement should edit the contract
                     consciously, not float)
    measure          () -> {metric: int} — module-provided measured
                     metrics (counted pair-hash lanes, the analytic
                     seq-adds model at the hot shapes, ...)
    count_point_ops  True: run fn(*args) EAGERLY under
                     tracer.counted_point_ops and record
                     seq_adds/seq_doubles (the dependent-chain
                     convention of ops/scalar_mul.sequential_*)
    forbid           subset of ("f64", "callback", "device_put") —
                     lowered/traced hygiene (CSA12xx)
    donate_min       minimum tf.aliasing_output annotations that must
                     survive lowering (CSA1204); 0 = unchecked
    collectives      iterable of collective kinds the COMPILED program
                     must contain exactly (CSA1301); None = unchecked
                     (compiling is the engine's only expensive step —
                     only contracts that declare collectives or budget
                     "collective_ops" pay it)
    chained_prefix   first n flattened outputs' lowered shardings must
                     equal the first n flattened args' (CSA1302) — the
                     static form of watchdog.layout_check on a
                     self-chained serving-loop step; 0 = unchecked
    requires_devices engine skips the contract (with a notice) when
                     jax.device_count() is smaller

The ratchet (trace_baseline.json maps contract -> {metric: value}):
measured > budget (or != for `exact`) is CSA1101 — fix the kernel or
change the contract; measured > baseline is CSA1102 — loosening means
editing the committed snapshot; measured < baseline is a CSA1103
notice (tighten cue; --update-trace-baseline refreshes); a metric with
no baseline entry is CSA1104 (new contracts commit their snapshot).
Inline `# csa: ignore[...]` suppressions on the contract's `"name":`
line (or the line above) work exactly like the AST tier's.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from ..core import Finding, _parse_suppressions

REPO_ROOT = Path(__file__).resolve().parents[3]
DEFAULT_BASELINE = Path(__file__).resolve().parents[1] / "trace_baseline.json"

_HYGIENE_RULES = {"f64": "CSA1201", "callback": "CSA1202",
                  "device_put": "CSA1203"}


def ensure_cpu_devices(n: int = 8) -> None:
    """Pin XLA:CPU with >= n virtual devices BEFORE jax initializes a
    backend (the __graft_entry__ idiom): the contract driver must run in
    seconds on any machine, never touch an accelerator relay, and the
    ServingMesh contracts need the 8-device virtual mesh. A no-op once a
    backend exists (pytest's conftest already pinned it)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:
        # pre-0.5 jax: XLA_FLAGS is read lazily at backend init
        flag = f" --xla_force_host_platform_device_count={n}"
        if flag.strip() not in os.environ.get("XLA_FLAGS", ""):
            os.environ["XLA_FLAGS"] = os.environ.get("XLA_FLAGS", "") + flag


# ---------------------------------------------------------------------------
# Discovery
# ---------------------------------------------------------------------------

def discover(package_root: Optional[Path] = None) -> List[dict]:
    """Collect every TRACE_CONTRACTS entry under consensus_specs_tpu.

    Cheap static pre-filter (only files whose text mentions
    TRACE_CONTRACTS are imported), then each contract is annotated with
    its defining module's `path` and the `line` of its `"name"` literal
    so findings anchor — and inline suppressions apply — exactly like
    the AST tier's."""
    import importlib
    root = Path(package_root or REPO_ROOT / "consensus_specs_tpu")
    contracts: List[dict] = []
    seen = set()
    for path in sorted(root.rglob("*.py")):
        source = path.read_text()
        if "TRACE_CONTRACTS" not in source:
            continue
        rel = path.relative_to(root.parent).with_suffix("")
        module = importlib.import_module(".".join(rel.parts))
        for contract in getattr(module, "TRACE_CONTRACTS", []):
            c = dict(contract)
            name = c["name"]
            assert name not in seen, f"duplicate trace contract {name}"
            seen.add(name)
            c.setdefault("path", str(path))
            c.setdefault("line", _name_line(source, name))
            contracts.append(c)
    return contracts


def _name_line(source: str, name: str) -> int:
    """Anchor line for a contract's findings/suppressions: the line its
    full name literal appears on, else the module's TRACE_CONTRACTS
    assignment (names built by f-string helpers anchor there)."""
    lines = source.splitlines()
    for i, line in enumerate(lines, 1):
        if name in line:
            return i
    for i, line in enumerate(lines, 1):
        if "TRACE_CONTRACTS" in line:
            return i
    return 1


def budget_snapshot(contracts: Optional[Iterable[dict]] = None) -> dict:
    """{contract: {metric: budget}} without tracing anything — the cheap
    snapshot bench.py embeds next to its telemetry registry dump so a
    bench capture and the static budgets it ran under are
    cross-checkable in one artifact."""
    return {c["name"]: dict(c.get("budgets", {}))
            for c in (contracts if contracts is not None else discover())}


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

def load_trace_baseline(path=None) -> Dict[str, Dict[str, int]]:
    p = Path(path or DEFAULT_BASELINE)
    if not p.exists():
        return {}
    return {k: dict(v) for k, v in
            json.loads(p.read_text()).get("contracts", {}).items()}


def write_trace_baseline(path, snapshot: Dict[str, Dict[str, int]]) -> None:
    ordered = {k: {m: snapshot[k][m] for m in sorted(snapshot[k])}
               for k in sorted(snapshot)}
    Path(path).write_text(json.dumps(
        {"version": 1,
         "comment": "Measured trace-tier snapshot (the CSA1102 ratchet). "
                    "Loosening an entry is a reviewed edit; "
                    "--update-trace-baseline refreshes after wins.",
         "contracts": ordered}, indent=2) + "\n")


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------

@dataclass
class ContractResult:
    name: str
    path: str
    line: int
    measured: Dict[str, int] = field(default_factory=dict)
    budgets: Dict[str, int] = field(default_factory=dict)
    hygiene: Dict[str, object] = field(default_factory=dict)
    skipped: str = ""          # non-empty reason when the contract didn't run


@dataclass
class TraceReport:
    findings: List[Finding]            # actionable
    suppressed: List[Finding]
    results: List[ContractResult]
    notices: List[str]
    stale_baseline: List[str]          # baseline contract names nothing matched

    @property
    def snapshot(self) -> Dict[str, Dict[str, int]]:
        return {r.name: dict(r.measured) for r in self.results
                if not r.skipped and r.measured}


def _measure(contract: dict) -> ContractResult:
    """Run one contract's programs and collect every measured metric and
    hygiene observation. Pure measurement — ratchet classification
    happens in run_contracts so tests can re-classify one measurement
    against many baselines."""
    from . import tracer
    import contextlib
    import jax

    res = ContractResult(name=contract["name"], path=contract["path"],
                         line=contract["line"],
                         budgets=dict(contract.get("budgets", {})))
    need = jax.device_count()
    want = int(contract.get("requires_devices", 1))
    if need < want:
        res.skipped = f"needs {want} devices, have {need}"
        return res

    measured: Dict[str, int] = {}
    hygiene: Dict[str, object] = {}
    build = contract.get("build")
    if build is not None:
        spec = build()
        fn, args = spec["fn"], tuple(spec["args"])
        jit_kwargs = dict(spec.get("jit_kwargs", {}))
        ctx_factory = spec.get("context")
        ctx = ctx_factory() if ctx_factory else contextlib.nullcontext()
        budgets = contract.get("budgets", {})
        forbid = tuple(contract.get("forbid", ()))
        with ctx:
            need_jaxpr = ("redc_lanes" in budgets or "jaxpr_eqns" in budgets
                          or "f64_ops" in budgets or forbid)
            if need_jaxpr:
                static = jit_kwargs.get("static_argnums", ())
                # normalize BEFORE truthiness: a bare `static_argnums=0`
                # (valid for jax.jit) is falsy as an int
                static = (static,) if isinstance(static, int) else \
                    tuple(static)
                if static:
                    closed = tracer.fresh_jaxpr(
                        lambda *dyn: fn(*[
                            args[i] if i in static else dyn[_dyn_index(
                                i, static)] for i in range(len(args))]),
                        *[a for i, a in enumerate(args) if i not in static])
                else:
                    closed = tracer.fresh_jaxpr(fn, *args)
                qinv = None
                if "redc_lanes" in budgets:
                    from consensus_specs_tpu.ops import fq as F
                    qinv = F.QINV_NEG
                scan = tracer.scan_program(closed, tagged_const=qinv)
                if "redc_lanes" in budgets:
                    assert scan["tagged_lanes"] % F.L == 0, scan
                    measured["redc_lanes"] = scan["tagged_lanes"] // F.L
                if "jaxpr_eqns" in budgets:
                    measured["jaxpr_eqns"] = scan["eqns"]
                if "f64_ops" in budgets:
                    # a budgeted (usually exact) f64 count: the contract
                    # declares its DELIBERATE float64 ops (e.g. the
                    # isqrt_u64 Newton seed) so any new upcast fails
                    measured["f64_ops"] = scan["f64_ops"]
                if "callback" in forbid:
                    hygiene["callbacks"] = scan["callbacks"]
                if "device_put" in forbid:
                    hygiene["device_puts"] = scan["device_puts"]
                if "f64" in forbid:
                    hygiene["f64"] = scan["f64_ops"]
            need_lowered = (contract.get("donate_min")
                            or contract.get("chained_prefix"))
            need_compiled = (contract.get("collectives") is not None
                             or "collective_ops" in budgets)
            if need_lowered or need_compiled:
                # lower ONCE; the StableHLO text and the compiled HLO
                # both read off the same Lowered object (the sharded
                # epoch program is the expensive one here)
                import jax
                lowered = jax.jit(fn, **jit_kwargs).lower(*args)
            if need_lowered:
                text = lowered.as_text()
                if contract.get("donate_min"):
                    hygiene["donated"] = tracer.donated_count(text)
                n_chain = int(contract.get("chained_prefix", 0))
                if n_chain:
                    arg_sh, out_sh = tracer.signature_shardings(text)
                    if len(arg_sh) < n_chain or len(out_sh) < n_chain:
                        # fewer flattened args/results than the declared
                        # prefix: the contract no longer matches the
                        # program — a mismatch, not a silent pass
                        hygiene["chain"] = [
                            (i,
                             arg_sh[i] if i < len(arg_sh) else "<missing>",
                             out_sh[i] if i < len(out_sh) else "<missing>")
                            for i in range(n_chain)
                            if i >= len(arg_sh) or i >= len(out_sh)]
                    elif all(arg_sh[i] is None and out_sh[i] is None
                             for i in range(n_chain)):
                        # no mhlo.sharding annotations at all (e.g. a jax
                        # upgrade moving to Shardy's sdy.sharding): the
                        # check would pass VACUOUSLY — degrade loudly
                        # instead, this is the silent-degradation mode
                        # the tier exists to prevent
                        hygiene["chain_unannotated"] = n_chain
                    else:
                        hygiene["chain"] = [
                            (i, arg_sh[i], out_sh[i])
                            for i in range(n_chain)
                            if arg_sh[i] != out_sh[i]]
            if need_compiled:
                inv = tracer.collective_inventory(
                    lowered.compile().as_text())
                hygiene["collectives"] = inv
                if "collective_ops" in budgets:
                    measured["collective_ops"] = sum(inv.values())
            if contract.get("count_point_ops"):
                with tracer.counted_point_ops() as counts:
                    fn(*args)
                measured["seq_adds"] = counts["jac_add"]
                measured["seq_doubles"] = (counts["jac_double"]
                                           - counts["jac_add"])
    if contract.get("measure") is not None:
        measured.update({k: int(v)
                         for k, v in contract["measure"]().items()})
    res.measured = measured
    res.hygiene = hygiene
    return res


def _dyn_index(i: int, static) -> int:
    return i - sum(1 for s in static if s < i)


def run_contracts(contracts: Optional[List[dict]] = None,
                  baseline: Optional[Dict[str, Dict[str, int]]] = None,
                  baseline_path=None) -> TraceReport:
    """Measure every contract and classify against budgets + baseline."""
    if contracts is None:
        contracts = discover()
    if baseline is None:
        baseline = load_trace_baseline(baseline_path)
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    results: List[ContractResult] = []
    notices: List[str] = []
    matched = set()
    suppression_cache: Dict[str, Dict[int, set]] = {}

    def emit(contract, res, rule, message):
        f = Finding(rule, res.path, res.line, message, context=res.name)
        sup = suppression_cache.get(res.path)
        if sup is None:
            try:
                sup = _parse_suppressions(Path(res.path).read_text())
            except OSError:
                sup = {}
            suppression_cache[res.path] = sup
        for line in (res.line, res.line - 1):
            rules = sup.get(line)
            if rules and ("*" in rules or rule in rules):
                suppressed.append(f)
                return
        findings.append(f)

    for contract in contracts:
        res = _measure(contract)
        results.append(res)
        if res.skipped:
            notices.append(
                f"trace: contract {res.name} skipped ({res.skipped})")
            matched.add(res.name)     # unverifiable, not stale
            continue
        base = baseline.get(res.name, {})
        if res.name in baseline:
            matched.add(res.name)
        exact = set(contract.get("exact", ()))
        hygiene = res.hygiene

        for metric, budget in res.budgets.items():
            got = res.measured.get(metric)
            if got is None:
                emit(contract, res, "CSA1101",
                     f"budgeted metric `{metric}` was never measured "
                     f"(no engine kind and no `measure` entry)")
                continue
            if metric in exact:
                if got != budget:
                    emit(contract, res, "CSA1101",
                         f"`{metric}` = {got}, contract pins exactly "
                         f"{budget}")
            elif got > budget:
                emit(contract, res, "CSA1101",
                     f"`{metric}` = {got} exceeds the declared budget "
                     f"{budget}")
        for metric, got in res.measured.items():
            if metric in exact:
                continue            # the pin already owns its drift
            prior = base.get(metric)
            if prior is None:
                emit(contract, res, "CSA1104",
                     f"`{metric}` = {got} has no trace-baseline entry "
                     f"(run --update-trace-baseline and commit)")
            elif got > prior:
                emit(contract, res, "CSA1102",
                     f"`{metric}` = {got} regressed vs the committed "
                     f"baseline {prior}")
            elif got < prior:
                notices.append(
                    f"trace: {res.name} `{metric}` improved {prior} -> "
                    f"{got}; tighten via --update-trace-baseline")

        if hygiene.get("f64"):
            emit(contract, res, "CSA1201",
                 f"traced program stages {hygiene['f64']} float64 op(s)")
        if hygiene.get("callbacks"):
            emit(contract, res, "CSA1202",
                 f"host callback primitives staged: "
                 f"{', '.join(hygiene['callbacks'])}")
        if hygiene.get("device_puts"):
            emit(contract, res, "CSA1203",
                 f"{hygiene['device_puts']} device_put op(s) staged "
                 f"inside the program")
        want_donated = int(contract.get("donate_min", 0))
        if want_donated and hygiene.get("donated", 0) < want_donated:
            emit(contract, res, "CSA1204",
                 f"only {hygiene.get('donated', 0)} donated buffers "
                 f"survive lowering; contract requires >= {want_donated}")
        if contract.get("collectives") is not None:
            want = sorted(contract["collectives"])
            got_inv = sorted(hygiene.get("collectives", {}))
            if got_inv != want:
                emit(contract, res, "CSA1301",
                     f"collective inventory {got_inv or ['<none>']} != "
                     f"declared {want or ['<none>']}")
        for (i, in_sh, out_sh) in hygiene.get("chain", []):
            emit(contract, res, "CSA1302",
                 f"chained operand {i}: out sharding {out_sh!r} != in "
                 f"sharding {in_sh!r}")
        if hygiene.get("chain_unannotated"):
            emit(contract, res, "CSA1302",
                 f"none of the {hygiene['chain_unannotated']} chained "
                 f"operands carry an mhlo.sharding annotation — the "
                 f"layout check cannot see the lowered placement "
                 f"(partitioner/dialect change?); it must not pass "
                 f"vacuously")

    stale = sorted(set(baseline) - matched)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return TraceReport(findings=findings, suppressed=suppressed,
                       results=results, notices=notices,
                       stale_baseline=stale)


# ---------------------------------------------------------------------------
# Reporting
# ---------------------------------------------------------------------------

def render_human(report: TraceReport) -> str:
    from ..core import RULES
    out = []
    for f in report.findings:
        out.append(f"{f.path}:{f.line}: [{f.rule}] {RULES[f.rule].severity}:"
                   f" {f.context}: {f.message}")
        if RULES[f.rule].hint:
            out.append(f"    hint: {RULES[f.rule].hint}")
    for name in report.stale_baseline:
        out.append(f"trace-baseline: stale contract (removed? delete it): "
                   f"{name}")
    for note in report.notices:
        out.append(f"notice: {note}")
    ran = sum(1 for r in report.results if not r.skipped)
    out.append(f"contracts: {len(report.results)} declared, {ran} run, "
               f"{len(report.findings)} finding(s), "
               f"{len(report.suppressed)} suppressed")
    return "\n".join(out)


def render_json(report: TraceReport) -> str:
    from ..core import RULES

    def row(f: Finding):
        return {"rule": f.rule, "path": f.path, "line": f.line,
                "contract": f.context, "message": f.message,
                "severity": RULES[f.rule].severity,
                "fingerprint": f.fingerprint()}

    return json.dumps({
        "findings": [row(f) for f in report.findings],
        "suppressed": [row(f) for f in report.suppressed],
        "contracts": [
            {"name": r.name, "path": r.path, "line": r.line,
             "skipped": r.skipped, "budgets": r.budgets,
             "measured": r.measured}
            for r in report.results],
        "notices": report.notices,
        "stale_baseline": report.stale_baseline,
    }, indent=2)
