"""Stdlib line-coverage for the test suite (VERDICT r4 missing #2).

The reference gates CI on line coverage of the compiled spec
(/root/reference/Makefile:49-58, pytest --cov=eth2spec.phase0.spec); this
image has neither coverage.py nor pytest-cov, so this module implements
the same capability on sys.monitoring (PEP 669, CPython 3.12+):

  * collection — `start(package_dir)` registers a LINE callback under the
    reserved COVERAGE_ID tool slot. The callback records (file, line) and
    returns sys.monitoring.DISABLE, which turns off that exact code
    location — every line traces at most once, so steady-state overhead
    on a 600-test suite is near zero (unlike sys.settrace).
  * denominator — executable lines are derived by compiling each package
    source and walking the code-object tree's co_lines() tables, the same
    ground truth the interpreter uses.
  * gating — run as a script, `--check` reads the JSON artifact a
    collection run wrote (tests/conftest.py triggers collection when
    CSTPU_COV=1) and exits 1 below `--floor`.

Usage:
  CSTPU_COV=1 python -m pytest tests/ -q     # writes out/coverage.json
  python tools/cov.py --check --floor 85     # gate (see Makefile citest-cov)
"""
import argparse
import json
import os
import sys
import types

_ARTIFACT = os.path.join("out", "coverage.json")
_executed: dict = {}     # abs filename -> set[int]
_package_dir = None


def _on_line(code, line):
    f = code.co_filename
    if f.startswith(_package_dir):
        s = _executed.get(f)
        if s is None:
            s = _executed[f] = set()
        s.add(line)
    return sys.monitoring.DISABLE


def start(package_dir: str, artifact: str = _ARTIFACT) -> None:
    """Begin collection over `package_dir`; write `artifact` at exit."""
    global _package_dir
    _package_dir = os.path.abspath(package_dir) + os.sep
    mon = sys.monitoring
    mon.use_tool_id(mon.COVERAGE_ID, "cstpu-cov")
    mon.register_callback(mon.COVERAGE_ID, mon.events.LINE, _on_line)
    mon.set_events(mon.COVERAGE_ID, mon.events.LINE)
    import atexit
    atexit.register(_dump, artifact)


def executable_lines(path: str) -> set:
    """Line numbers the compiler marks executable (co_lines ground truth)."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    lines: set = set()
    stack = [compile(src, path, "exec")]
    while stack:
        c = stack.pop()
        for _, _, ln in c.co_lines():
            if ln is not None:
                lines.add(ln)
        stack.extend(k for k in c.co_consts if isinstance(k, types.CodeType))
    # module docstrings/constant folding can report line 0/None artifacts
    lines.discard(0)
    return lines


def _dump(artifact: str) -> None:
    sys.monitoring.set_events(sys.monitoring.COVERAGE_ID, 0)
    per_file = {}
    tot_exec = tot_hit = 0
    for root, _, files in os.walk(_package_dir):
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            try:
                ex = executable_lines(path)
            except SyntaxError:
                continue
            hit = _executed.get(path, set()) & ex
            rel = os.path.relpath(path, os.path.dirname(_package_dir.rstrip(os.sep)))
            per_file[rel] = {"executable": len(ex), "hit": len(hit),
                             "pct": round(100 * len(hit) / len(ex), 1) if ex else 100.0}
            tot_exec += len(ex)
            tot_hit += len(hit)
    os.makedirs(os.path.dirname(artifact) or ".", exist_ok=True)
    pct = round(100 * tot_hit / tot_exec, 2) if tot_exec else 100.0
    with open(artifact, "w") as f:
        json.dump({"total_pct": pct, "hit": tot_hit, "executable": tot_exec,
                   "files": per_file}, f, indent=1, sort_keys=True)
    print(f"[cov] line coverage {pct}% ({tot_hit}/{tot_exec}) -> {artifact}",
          file=sys.stderr)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="gate on an existing artifact")
    ap.add_argument("--floor", type=float, default=80.0)
    ap.add_argument("--artifact", default=_ARTIFACT)
    args = ap.parse_args()
    if not args.check:
        ap.error("collection runs via CSTPU_COV=1 pytest; use --check here")
    with open(args.artifact) as f:
        data = json.load(f)
    worst = sorted(data["files"].items(), key=lambda kv: kv[1]["pct"])[:8]
    print(f"total: {data['total_pct']}% "
          f"({data['hit']}/{data['executable']} lines)")
    for rel, d in worst:
        print(f"  {d['pct']:5.1f}%  {rel}")
    if data["total_pct"] < args.floor:
        print(f"FAIL: coverage {data['total_pct']}% < floor {args.floor}%")
        return 1
    print(f"OK: coverage {data['total_pct']}% >= floor {args.floor}%")
    return 0


if __name__ == "__main__":
    sys.exit(main())
