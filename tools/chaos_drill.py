"""`make chaos`: drive the resident serving loop through a seeded fault
schedule and prove it recovers BIT-IDENTICALLY (ISSUE 13 acceptance).

Phases (all on the virtual 8-device CPU mesh, minimal preset):

    baseline   fault-free reference: warm-up epoch, then 3 epochs of
               chained sharded slot steps (24 slot steps + 3 boundaries
               >= the required 8 steps + boundary) -> reference
               checkpoint bytes + state root.
    dispatch   >=3 fault kinds, ONE per boundary so each recovery is
               retry-shaped — a transient raise, a poisoned output
               (tripwired against the committed RANGE_CONTRACTS hulls),
               a hang past the armed deadline — recovered WITHOUT any
               ladder degradation (asserted: degradations == 0) and
               bit-identical to the reference.
    ladder     the wedged-mesh scenario: EVERY sharded epoch dispatch
               raises, so recovery walks the whole degradation ladder
               (merkle pallas->xla, REDC coeff->leaf, scalar-mul
               window->double_add, sharded->single-device) and finishes
               the drive single-device — still bit-identical, because
               every rung is a committed differential oracle.
    checkpoint crash-safe failover: good generation at the warm-up
               point, a TRUNCATED generation mid-drive (written
               "successfully" — silent media corruption), a kill
               mid-write (partial temp file, no rename), then a
               simulated restart: restore falls back to the previous
               good generation, replays, and lands on the reference
               bytes. The restore also runs under a CHANGED serving-mesh
               shape (8 -> 2 devices; the payload is logical bytes).

Across the WHOLE drill the retrace/re-layout watchdogs must record ZERO
events (recoveries use fresh keys; the deliberate single-device
re-placement forgets its keys) — the "zero residual watchdog events"
acceptance bar. Artifact: out/chaos.json. Exit 0 = every phase held.

Usage: python tools/chaos_drill.py  (from the repo root)
"""
import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLOTS = {}          # phase -> slots driven (reported in the artifact)

# one fault per boundary (each recovery consumes 2 occurrences: the
# faulted attempt + the clean retry): boundary 1 -> transient raise,
# boundary 2 -> poisoned balance column (leaf 6), boundary 3 -> hang
# past the armed deadline. Every recovery is pure retry/re-dispatch —
# the phase asserts ZERO ladder degradations.
DISPATCH_SCHEDULE = ("seed=7;"
                     "dispatch:*mesh.epoch*@1=raise;"
                     "dispatch:*mesh.epoch*@3=poison:6;"
                     "dispatch:*mesh.epoch*@5=hang:4000")
LADDER_SCHEDULE = "seed=7;dispatch:*mesh.epoch*@1-99=raise"
DEADLINE_MS = "3000"


def main() -> int:
    if os.environ.get("CSTPU_TEST_TPU") != "1":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if os.environ.get("CSTPU_TEST_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8")
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "..", ".cache", "xla")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from consensus_specs_tpu import resilience, telemetry
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.models import phase0
    from consensus_specs_tpu.models.phase0.resident import ResidentCore
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    from consensus_specs_tpu.resilience import CheckpointStore, faults
    from consensus_specs_tpu.resilience.errors import SimulatedCrash
    from consensus_specs_tpu.testing import factories
    from consensus_specs_tpu.utils.ssz.impl import serialize

    telemetry.set_enabled(True)
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "out")
    os.makedirs(out_dir, exist_ok=True)

    n_dev = 1
    while n_dev * 2 <= min(8, len(jax.devices())):
        n_dev *= 2
    if n_dev < 2:
        print("chaos drill needs a multi-device mesh (have "
              f"{len(jax.devices())} device)", flush=True)
        return 1

    bls.bls_active = False
    spec = phase0.get_spec("minimal")
    spec.clear_caches()
    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    data = serialize(state, spec.BeaconState)
    spe = int(spec.SLOTS_PER_EPOCH)
    start = int(state.slot)
    warm = (start // spe + 1) * spe + 1        # one boundary in
    target = warm + 3 * spe                    # + 24 slot steps, 3 boundaries
    SLOTS["warmup"] = warm - start
    SLOTS["drive"] = target - warm

    report = {"devices": n_dev, "preset": "minimal",
              "validators": len(state.validator_registry),
              "slots": dict(SLOTS), "deadline_ms": float(DEADLINE_MS),
              "schedules": {"dispatch": DISPATCH_SCHEDULE,
                            "ladder": LADDER_SCHEDULE},
              "phases": {}}
    failures = []
    retrace0 = telemetry.counter("watchdog.retrace_events").value
    relayout0 = telemetry.counter("watchdog.relayout_events").value

    def fresh_core(mesh="default"):
        faults.set_schedule(None)
        os.environ.pop("CSTPU_DEADLINE_MS", None)
        core = ResidentCore.from_checkpoint(
            spec, data,
            mesh=ServingMesh.create(n_dev) if mesh == "default" else mesh)
        core.process_slots(core.state, warm)      # warm boundary, no faults
        return core

    def finish(core):
        final = core.checkpoint_bytes()
        root = core._state_root(core.state)
        core._uninstall()
        faults.set_schedule(None)
        os.environ.pop("CSTPU_DEADLINE_MS", None)
        return final, root

    def phase(name, fn):
        t0 = time.perf_counter()
        counters0 = {k: telemetry.counter(k, always=True).value
                     for k in ("resilience.retries",
                               "resilience.deadline_misses",
                               "resilience.corrupt_outputs",
                               "resilience.transient_errors",
                               "resilience.degradations",
                               "resilience.faults_injected")}
        try:
            row = fn()
        except Exception as exc:        # noqa: BLE001 - a failed phase
            # must still land in out/chaos.json (the CI artifact exists
            # precisely to diagnose failures) and must not keep later
            # phases from running
            import traceback
            traceback.print_exc()
            faults.set_schedule(None)
            os.environ.pop("CSTPU_DEADLINE_MS", None)
            row = {"ok": False,
                   "error": f"{type(exc).__name__}: {exc}"}
        row["seconds"] = round(time.perf_counter() - t0, 2)
        row["counters"] = {
            k.split("resilience.", 1)[-1]:
                int(telemetry.counter(k, always=True).value - v)
            for k, v in counters0.items()}
        ok = row.get("ok", True)
        report["phases"][name] = row
        status = "ok" if ok else "FAIL"
        print(f"[{name}] {status} in {row['seconds']}s: "
              f"{row['counters']}", flush=True)
        if not ok:
            failures.append(name)

    # -- baseline ---------------------------------------------------------
    ref = {}

    def run_baseline():
        core = fresh_core()
        core.process_slots(core.state, target)
        ref["bytes"], ref["root"] = finish(core)
        return {"root": ref["root"].hex(), "ok": True}

    phase("baseline", run_baseline)

    # -- dispatch faults --------------------------------------------------
    def run_dispatch():
        deg0 = telemetry.counter("resilience.degradations", always=True).value
        core = fresh_core()
        os.environ["CSTPU_DEADLINE_MS"] = DEADLINE_MS
        faults.set_schedule(DISPATCH_SCHEDULE)
        core.process_slots(core.state, target)
        final, root = finish(core)
        degraded = telemetry.counter("resilience.degradations",
                                     always=True).value - deg0
        return {"root": root.hex(),
                "bit_identical": final == ref["bytes"],
                "retry_only": degraded == 0,
                "ok": (final == ref["bytes"] and root == ref["root"]
                       and degraded == 0)}

    phase("dispatch", run_dispatch)

    # -- ladder walk ------------------------------------------------------
    def run_ladder():
        resilience.ladder().reset()
        core = fresh_core()
        faults.set_schedule(LADDER_SCHEDULE)
        core.process_slots(core.state, target)
        rung = resilience.ladder().rung_name
        single = core._mesh is None
        final, root = finish(core)
        resilience.ladder().reset()
        return {"root": root.hex(), "final_rung": rung,
                "single_device": single,
                "bit_identical": final == ref["bytes"],
                "ok": (final == ref["bytes"] and rung == "single_device"
                       and single)}

    phase("ladder", run_ladder)

    # -- checkpoint failover ---------------------------------------------
    def run_checkpoint():
        ckpt_root = os.path.join(out_dir, "chaos_ckpt")
        shutil.rmtree(ckpt_root, ignore_errors=True)
        store = CheckpointStore(ckpt_root, keep=4)
        core = fresh_core()
        gen1 = store.save(core.checkpoint_bytes())          # good, at `warm`
        core.process_slots(core.state, warm + spe)
        faults.set_schedule("ckpt.write@1=truncate:33")     # silent corruption
        gen2 = store.save(core.checkpoint_bytes())
        faults.set_schedule("ckpt.write@1=crash:0.5")       # kill mid-write
        crashed = False
        try:
            store.save(core.checkpoint_bytes())
        except SimulatedCrash:
            crashed = True
        core._uninstall()                                   # "the process died"
        faults.set_schedule(None)

        # restart: newest intact generation wins (gen2 is corrupt, the
        # crashed write never committed), under a CHANGED mesh shape
        gen, core2 = store.restore(spec, mesh=ServingMesh.create(2))
        fell_back = (gen == gen1) and (gen2 == gen1 + 1)
        core2.process_slots(core2.state, target)            # replay
        final, root = finish(core2)
        return {"root": root.hex(), "generations": store.generations(),
                "restored_generation": gen, "fell_back": fell_back,
                "kill_mid_write_survived": crashed,
                "restore_mesh_devices": 2,
                "bit_identical": final == ref["bytes"],
                "ok": (final == ref["bytes"] and fell_back and crashed)}

    phase("checkpoint", run_checkpoint)

    # -- residual watchdog gate ------------------------------------------
    retrace = telemetry.counter("watchdog.retrace_events").value - retrace0
    relayout = telemetry.counter("watchdog.relayout_events").value - relayout0
    report["watchdog"] = {"retrace_events": int(retrace),
                          "relayout_events": int(relayout)}
    if retrace or relayout:
        failures.append("watchdog")
    report["health"] = resilience.health_snapshot()
    report["ok"] = not failures

    path = os.path.join(out_dir, "chaos.json")
    with open(path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"artifact: out/chaos.json; watchdogs across the whole drill: "
          f"{retrace} retrace, {relayout} re-layout events", flush=True)
    if failures:
        print(f"CHAOS DRILL FAIL: {failures}", flush=True)
        return 1
    print("CHAOS DRILL OK — recovered bit-identically from "
          "raise/poison/hang, a full ladder walk, a corrupt checkpoint "
          "generation, and a kill mid-write", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
