"""`make telemetry`: drive the resident serving loop with telemetry on
and dump the observability artifacts:

    out/trace.json        Chrome-trace/Perfetto span timeline
    out/metrics.prom      Prometheus text exposition (the /metrics body)
    out/telemetry.jsonl   one snapshot line per epoch driven

Runs on the virtual 8-device CPU mesh (the test topology; a real
accelerator brings its own devices), asserts the retrace and re-layout
watchdogs stay at ZERO events across the steady-state drive — the
runtime pjit layout-stability contract — and exits non-zero otherwise.

Usage: python tools/telemetry_smoke.py  (from the repo root)
"""
import os
import sys
import time

# `python tools/telemetry_smoke.py` puts tools/ (not the repo root) on
# sys.path; the package lives at the root.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    # CPU pin + virtual mesh BEFORE backend init (the conftest recipe:
    # the ambient environment may point jax at a TPU relay)
    if os.environ.get("CSTPU_TEST_TPU") != "1":
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    if os.environ.get("CSTPU_TEST_TPU") != "1":
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 8)
        except AttributeError:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8")
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "..", ".cache", "xla")
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from consensus_specs_tpu import telemetry
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.models import phase0
    from consensus_specs_tpu.models.phase0.resident import ResidentCore
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    from consensus_specs_tpu.testing import factories

    telemetry.set_enabled(True)
    telemetry.watchdog.install_compile_listener()
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "..", "out")
    os.makedirs(out_dir, exist_ok=True)
    jsonl_path = os.path.join(out_dir, "telemetry.jsonl")
    if os.path.exists(jsonl_path):
        os.remove(jsonl_path)

    n_dev = 1
    while n_dev * 2 <= min(8, len(jax.devices())):
        n_dev *= 2
    mesh = ServingMesh.create(n_dev) if n_dev >= 2 else None
    print(f"devices: {len(jax.devices())} ({jax.devices()[0].platform}); "
          f"serving mesh: {n_dev if mesh else 'single-device'}", flush=True)

    bls.bls_active = False
    spec = phase0.get_spec("minimal")
    spec.clear_caches()
    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    core = ResidentCore(spec, state, mesh=mesh)
    spe = spec.SLOTS_PER_EPOCH
    epochs = int(os.environ.get("CSTPU_TELEMETRY_EPOCHS", "3"))
    try:
        target = (state.slot // spe + 1) * spe + 1
        t0 = time.perf_counter()
        core.process_slots(state, target)             # warm-up epoch
        print(f"warm-up epoch: {time.perf_counter() - t0:.2f}s", flush=True)
        retrace0 = telemetry.counter("watchdog.retrace_events").value
        relayout0 = telemetry.counter("watchdog.relayout_events").value
        for i in range(epochs):
            t0 = time.perf_counter()
            core.process_slots(state, target + (i + 1) * spe)
            tm = core.timings
            print(f"epoch {i}: {time.perf_counter() - t0:.2f}s "
                  f"(stage {tm['stage'] * 1e3:.0f} ms, device "
                  f"{tm['device'] * 1e3:.0f} ms, refresh "
                  f"{tm['refresh'] * 1e3:.0f} ms)", flush=True)
            telemetry.write_jsonl(jsonl_path, extra={"epoch": i})
        retrace = telemetry.counter("watchdog.retrace_events").value - retrace0
        relayout = (telemetry.counter("watchdog.relayout_events").value
                    - relayout0)
    finally:
        core.exit()

    telemetry.dump_chrome_trace(os.path.join(out_dir, "trace.json"))
    telemetry.dump_prometheus(os.path.join(out_dir, "metrics.prom"))
    telemetry.set_enabled(None)
    snap = telemetry.snapshot()
    print(f"artifacts: out/trace.json ({len(telemetry.ring())} spans), "
          f"out/metrics.prom ({len(snap['counters'])} counters, "
          f"{len(snap['spans'])} span names), out/telemetry.jsonl "
          f"({epochs} lines)", flush=True)
    print(f"watchdogs over {epochs} steady epochs "
          f"({epochs * spe} slot steps, {epochs} boundaries): "
          f"{retrace} retrace, {relayout} re-layout events", flush=True)
    if retrace or relayout:
        print("FAIL: the steady-state resident loop tripped a watchdog",
              flush=True)
        return 1
    print("TELEMETRY SMOKE OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
