# Build / test / vector orchestration.
# Capability parity with /root/reference Makefile:43-104 (pyspec build, tests,
# lint, YAML vector generation, deposit-contract tests) — compiled-spec steps
# don't exist here (the spec IS the package), so targets map to the runtime
# equivalents.

PYTHON ?= python
VECTOR_DIR ?= out/vectors
JUNIT ?= out/test-results.xml

.PHONY: test testall citest citest-cov citest-mainnet lint analyze contracts ranges lifetime memory vectors vectors-minimal bench bench-cpu multichip telemetry chaos firehose smoke clean

# measured 90.64% on the round-5 full suite; floor set just under so real
# regressions fail while normal drift doesn't
COV_FLOOR ?= 88

# Default lane: the suite minus the `slow`-marked modules (pairing corpus,
# state-to-state) — sub-10-minute on the virtual CPU mesh (VERDICT r4 #8).
test:
	$(PYTHON) -m pytest tests/ -q -m "not slow"

# Everything, including slow.
testall:
	$(PYTHON) -m pytest tests/ -q

# CI flavor: full suite, fail fast, machine-readable results.
citest:
	mkdir -p $(dir $(JUNIT))
	$(PYTHON) -m pytest tests/ -x -q --junitxml=$(JUNIT)

# CI coverage gate (VERDICT r4 missing #2; reference Makefile:49-58 runs
# --cov): full suite under the stdlib line tracer (tools/cov.py), then
# fail below the floor. Artifact: out/coverage.json.
citest-cov:
	mkdir -p $(dir $(JUNIT))
	CSTPU_COV=1 $(PYTHON) -m pytest tests/ -x -q --junitxml=$(JUNIT)
	$(PYTHON) tools/cov.py --check --floor $(COV_FLOOR)

# Preset-divergence gate: the corpus subset where mainnet differs most from
# minimal (committee counts 64 vs 8, 90 vs 10 shuffle rounds, 64-slot
# epochs) runs under CSTPU_PRESET=mainnet (VERDICT r3 #7).
citest-mainnet:
	CSTPU_PRESET=mainnet CSTPU_ACCEL=1 $(PYTHON) -m pytest \
		tests/test_spec_phase0.py -x -q \
		-k "attestation or crosslinks or registry_updates or sanity_slots or finality"

# Syntax + style gate (see tools/lint.py; no third-party linters in image).
lint:
	$(PYTHON) tools/lint.py consensus_specs_tpu tests bench.py __graft_entry__.py tools

# Trace-safety / spec-conformance static analysis (tools/analysis/README.md):
# ten pass families over the call-graph IR — Python control flow on
# tracers, 32-bit truncation of uint64 math, impure traced code,
# state-aliasing overrides, jit-cache hygiene, sharding/collective axis
# consistency, pallas BlockSpec/grid/Ref contracts, spec drift vs the
# reference pyspec (REFERENCE_ROOT, skips with a notice when absent),
# wide-column accumulation past the double-width laziness budget (CSA901),
# and unfenced perf_counter timing around jitted dispatch (CSA1001).
# Exit 0 = no findings beyond the committed baseline + inline
# `# csa: ignore[...]` suppressions. JSON artifact: out/analysis.json.
REFERENCE_ROOT ?= /root/reference
analyze:
	$(PYTHON) -m tools.analysis consensus_specs_tpu bench.py __graft_entry__.py \
		--baseline tools/analysis/baseline.json --json out/analysis.json \
		--reference-root $(REFERENCE_ROOT)

# Trace-tier contract analyzer (tools/analysis/trace/): traces/lowers the
# REAL jitted kernels named by the modules' TRACE_CONTRACTS and ratchets
# measured op budgets (REDC lanes, dependent add chains, pair-hash lanes,
# collective inventory, chained out/in shardings, donation survival, f64/
# callback/transfer hygiene) against the committed
# tools/analysis/trace_baseline.json. Pins XLA:CPU with 8 virtual devices
# itself, so it runs identically on CI and laptops. Exit 0 = every budget
# met. JSON artifact: out/contracts.json. Tighten a budget by editing the
# contract next to its kernel; loosen one via --update-trace-baseline.
contracts:
	mkdir -p out
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.analysis --trace \
		--trace-baseline tools/analysis/trace_baseline.json \
		--json out/contracts.json

# Value-range tier (tools/analysis/ranges/): an interval abstract
# interpreter over the REAL jaxprs of the kernels' RANGE_CONTRACTS —
# proves the limb/column magnitude budgets (|col| < 2^35 into fq_redc,
# narrow limbs back to [-16, 2^29], shuffle int32 at the 2^30 ceiling,
# uint64 Gwei math at 10M validators) and the declared wrap semantics
# (SHA-256's mod-2^32), ratcheting the proven intervals against the
# committed tools/analysis/ranges_baseline.json (CSA1401-1404). Ceiling
# shapes trace via ShapeDtypeStruct, so the whole run is ~15 s of pure
# interpretation — no arrays, no devices. Exit 0 = every budget proven.
# JSON artifact: out/ranges.json. Loosen via --update-ranges-baseline.
ranges:
	mkdir -p out
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.analysis --ranges \
		--ranges-baseline tools/analysis/ranges_baseline.json \
		--json out/ranges.json

# Buffer-lifetime tier (tools/analysis/lifetime/): the interprocedural
# donation/aliasing prover (CSA1501-1505) — abstract LIVE / DONATED /
# MAYBE-DONATED ownership states flow over the call-graph IR through
# calls, dispatch wrappers, attribute stores, destructuring and loops,
# cross-checked against the `tf.aliasing_output` annotations that
# survive the REAL lowerings of the donate_min trace contracts. Exit
# 0 = the committed tree proves clean (every donated buffer rebound,
# returned, or routed through utils/donation.platform_donated_jit).
# JSON artifact: out/lifetime.json. Accepted findings ratchet via
# tools/analysis/lifetime_baseline.json (--update-lifetime-baseline).
lifetime:
	mkdir -p out
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.analysis --lifetime \
		--lifetime-baseline tools/analysis/lifetime_baseline.json \
		--json out/lifetime.json

# Memory tier (tools/analysis/memory/): a peak-buffer-liveness abstract
# interpreter over the REAL jaxprs of the kernels' MEM_CONTRACTS at
# ceiling shapes (V=10M epoch, 1M-leaf forest, G=128 pairing, firehose
# steady state) — donation-aware per-eqn live sets prove each kernel's
# declared HBM budget (CSA1601), per-shard bytes on the 8-device mesh,
# scaling exponents from probe shapes (CSA1603), and the Pallas VMEM
# footprint vs the 16 MiB core (CSA1604), cross-checked against
# compiled.memory_analysis() where XLA reports it and ratcheted against
# the committed tools/analysis/memory_baseline.json (CSA1602). Traces
# via ShapeDtypeStruct — no ceiling-sized arrays are ever allocated.
# Exit 0 = every budget proven. JSON artifact: out/memory.json. Loosen
# via --update-memory-baseline.
memory:
	mkdir -p out
	JAX_PLATFORMS=cpu $(PYTHON) -m tools.analysis --memory \
		--memory-baseline tools/analysis/memory_baseline.json \
		--json out/memory.json

# Conformance vectors, both presets (reference: make gen_yaml_tests).
vectors:
	$(PYTHON) -m consensus_specs_tpu.generators -o $(VECTOR_DIR)

vectors-minimal:
	$(PYTHON) -m consensus_specs_tpu.generators -o $(VECTOR_DIR) -p minimal

# Headline benchmark (real TPU when present; CSTPU_BENCH_CPU=1 to smoke).
bench:
	$(PYTHON) bench.py

# Reproducible off-chip capture: the identical harness pinned to XLA:CPU.
# Committed bench_logs/bench_cpu_*.json artifacts use V=65536 (smoke scale)
# and V=1000000 (headline scale); override V to match the one to reproduce.
bench-cpu:
	CSTPU_BENCH_CPU=1 CSTPU_BENCH_V=$(or $(V),65536) \
	CSTPU_BENCH_ATT=32 $(PYTHON) bench.py

# The driver's multi-chip dry run, locally on 8 virtual devices.
multichip:
	$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

# Observability smoke: the resident serving loop with telemetry on —
# dumps out/trace.json (Chrome trace), out/metrics.prom (Prometheus
# exposition), out/telemetry.jsonl, and fails if the retrace/re-layout
# watchdogs record any event on the steady-state drive (CI artifacts).
telemetry:
	$(PYTHON) tools/telemetry_smoke.py

# Chaos drill (tools/chaos_drill.py): the resident serving loop driven
# through a seeded fault schedule — transient raises, a poisoned output
# (tripwired against the proven RANGE_CONTRACTS hulls), a hang past the
# armed deadline, a full degradation-ladder walk down to single-device,
# a corrupt checkpoint generation, and a kill mid-write — asserting the
# final state is BIT-IDENTICAL to the fault-free run with zero residual
# watchdog events. Artifact: out/chaos.json (CI uploads it).
chaos:
	$(PYTHON) tools/chaos_drill.py

# Firehose smoke (tools/firehose_smoke.py): the streaming verifier under
# sustained synthetic gossip load — waves of valid + deterministic-FALSE
# aggregates accumulated across slot ticks into full device batches,
# flushed at an armed deadline. Exits non-zero on any streamed-vs-
# synchronous verdict mismatch, watchdog event, or deadline miss.
# Artifact: out/firehose.json (CI uploads it). Bench runs the committed
# 128-group occupancy; the smoke shape defaults to 8 for speed
# (CSTPU_FIREHOSE_GROUPS overrides).
firehose:
	$(PYTHON) tools/firehose_smoke.py

# Quick health check: lint + static analysis (all five tiers) + the
# fast test modules. `make contracts`, `make ranges`, `make lifetime`
# and `make memory` ride here so an op-budget, value-range,
# buffer-lifetime or memory-budget regression fails at smoke time,
# before any bench run.
smoke:
	$(PYTHON) tools/lint.py consensus_specs_tpu tests bench.py __graft_entry__.py tools
	$(PYTHON) -m tools.analysis --list-rules >/dev/null
	$(PYTHON) -m tools.analysis consensus_specs_tpu bench.py __graft_entry__.py \
		--baseline tools/analysis/baseline.json \
		--reference-root $(REFERENCE_ROOT)
	$(MAKE) contracts
	$(MAKE) ranges
	$(MAKE) lifetime
	$(MAKE) memory
	$(MAKE) firehose
	$(PYTHON) -m pytest tests/test_config.py tests/test_ssz.py tests/test_fork_choice.py tests/test_sharding.py tests/test_incremental_merkle.py tests/test_scalar_mul.py tests/test_fq_redc.py tests/test_analysis.py tests/test_trace_contracts.py tests/test_range_contracts.py tests/test_lifetime.py tests/test_memory_contracts.py tests/test_bench_probe.py tests/test_multichip.py tests/test_resident.py tests/test_telemetry.py tests/test_resilience.py tests/test_chaos_checkpoint.py tests/test_streaming.py -q -m "not slow"

clean:
	rm -rf out .pytest_cache $(VECTOR_DIR)
	find . -name __pycache__ -type d -exec rm -rf {} +
