"""SSZ typing/serialization/Merkleization unit tests.

Known-answer vectors are computed from the 2019 SSZ rules
(/root/reference specs/simple-serialize.md): little-endian uints, 4-byte
offsets for variable-size parts, pow2-padded Merkleization, mix_in_length
for lists, truncated signing_root.
"""
import hashlib

import pytest

from consensus_specs_tpu.utils.ssz import (
    Bytes32, Bytes96, Container, List, Vector,
    uint8, uint16, uint32, uint64, uint128, uint256,
    serialize, deserialize, hash_tree_root, signing_root,
    get_zero_value, is_fixed_size,
)
from consensus_specs_tpu.utils.merkle import merkleize_chunks, next_power_of_two
from consensus_specs_tpu.utils.hash import zerohashes, ZERO_BYTES32


def h(x: bytes) -> bytes:
    return hashlib.sha256(x).digest()


class Point(Container):
    x: uint64
    y: uint64


class Signed(Container):
    value: uint64
    sig: Bytes96


class VarBox(Container):
    tag: uint8
    items: List[uint64]


# ---------------------------------------------------------------- serialization

def test_serialize_uints():
    assert serialize(uint8(5)) == b"\x05"
    assert serialize(uint16(0x0102)) == b"\x02\x01"
    assert serialize(uint32(1)) == b"\x01\x00\x00\x00"
    assert serialize(5, uint64) == (5).to_bytes(8, "little")
    assert serialize(uint256(1)) == b"\x01" + b"\x00" * 31
    assert serialize(uint128(2 ** 127)) == b"\x00" * 15 + b"\x80"


def test_uint_bounds():
    with pytest.raises(ValueError):
        uint8(256)
    with pytest.raises(ValueError):
        uint64(-1)
    with pytest.raises(ValueError):
        uint64(2 ** 64)


def test_serialize_bool():
    assert serialize(True, bool) == b"\x01"
    assert serialize(False, bool) == b"\x00"


def test_serialize_fixed_container():
    p = Point(x=1, y=2)
    assert serialize(p) == (1).to_bytes(8, "little") + (2).to_bytes(8, "little")
    assert is_fixed_size(Point)


def test_serialize_variable_container():
    b = VarBox(tag=7, items=[1, 2, 3])
    # fixed region: tag (1 byte) + offset (4 bytes) = 5; items start at 5
    expected = b"\x07" + (5).to_bytes(4, "little") + b"".join(
        i.to_bytes(8, "little") for i in (1, 2, 3))
    assert serialize(b) == expected
    assert not is_fixed_size(VarBox)


def test_serialize_vector_and_bytes():
    V = Vector[uint16, 3]
    assert serialize(V(1, 2, 3)) == b"\x01\x00\x02\x00\x03\x00"
    assert serialize(Bytes32()) == b"\x00" * 32
    assert serialize(b"\xab\xcd", bytes) == b"\xab\xcd"


def test_serialize_list_of_containers():
    LP = List[Point]
    data = serialize([Point(x=1, y=2), Point(x=3, y=4)], LP)
    # fixed-size elements inline, no offsets
    assert data == serialize(Point(x=1, y=2)) + serialize(Point(x=3, y=4))


def test_list_of_variable_elements():
    LL = List[List[uint64]]
    data = serialize([[1], [2, 3]], LL)
    # two offsets (8 bytes), then 8 bytes, then 16 bytes
    assert data[:4] == (8).to_bytes(4, "little")
    assert data[4:8] == (16).to_bytes(4, "little")
    assert deserialize(data, LL) == [[1], [2, 3]]


# ------------------------------------------------------------- deserialization

@pytest.mark.parametrize("obj,typ", [
    (uint64(12345), uint64),
    (Point(x=9, y=10), Point),
    (VarBox(tag=1, items=[5, 6, 7, 8]), VarBox),
    (Signed(value=3, sig=Bytes96(b"\x11" * 96)), Signed),
])
def test_roundtrip(obj, typ):
    data = serialize(obj, typ)
    back = deserialize(data, typ)
    assert serialize(back, typ) == data
    assert hash_tree_root(back, typ) == hash_tree_root(obj, typ)


def test_roundtrip_nested():
    class Outer(Container):
        p: Point
        boxes: List[VarBox]
        roots: Vector[Bytes32, 2]

    o = Outer(p=Point(x=1, y=2),
              boxes=[VarBox(tag=3, items=[4]), VarBox(tag=5, items=[])],
              roots=Vector[Bytes32, 2](Bytes32(b"\x01" * 32), Bytes32(b"\x02" * 32)))
    back = deserialize(serialize(o), Outer)
    assert back == o


# --------------------------------------------------------------- merkleization

def test_next_power_of_two():
    assert [next_power_of_two(i) for i in (0, 1, 2, 3, 4, 5, 8, 9)] == [1, 1, 2, 4, 4, 8, 8, 16]


def test_merkleize_single_chunk():
    c = b"\x01" * 32
    assert merkleize_chunks([c]) == c


def test_merkleize_two_chunks():
    a, b = b"\x01" * 32, b"\x02" * 32
    assert merkleize_chunks([a, b]) == h(a + b)


def test_merkleize_three_chunks_pads():
    a, b, c = b"\x01" * 32, b"\x02" * 32, b"\x03" * 32
    assert merkleize_chunks([a, b, c]) == h(h(a + b) + h(c + ZERO_BYTES32))


def test_merkleize_empty():
    assert merkleize_chunks([]) == ZERO_BYTES32


def test_zerohashes_chain():
    assert zerohashes[1] == h(ZERO_BYTES32 + ZERO_BYTES32)
    assert zerohashes[2] == h(zerohashes[1] + zerohashes[1])


def test_htr_uint():
    assert hash_tree_root(uint64(5)) == (5).to_bytes(8, "little") + b"\x00" * 24


def test_htr_container():
    p = Point(x=1, y=2)
    left = (1).to_bytes(8, "little") + b"\x00" * 24
    right = (2).to_bytes(8, "little") + b"\x00" * 24
    assert hash_tree_root(p) == h(left + right)


def test_htr_list_mixes_length():
    root = hash_tree_root([uint64(1), uint64(2)], List[uint64])
    packed = (1).to_bytes(8, "little") + (2).to_bytes(8, "little") + b"\x00" * 16
    assert root == h(packed + (2).to_bytes(32, "little"))


def test_htr_empty_list():
    assert hash_tree_root([], List[uint64]) == h(ZERO_BYTES32 + (0).to_bytes(32, "little"))


def test_htr_bytes32_identity_chunk():
    b = Bytes32(b"\x05" * 32)
    assert hash_tree_root(b) == bytes(b)


def test_htr_bytes96():
    b = Bytes96(b"\x01" * 96)
    chunks = [b"\x01" * 32] * 3
    assert hash_tree_root(b) == h(h(chunks[0] + chunks[1]) + h(chunks[2] + ZERO_BYTES32))


def test_signing_root_drops_last_field():
    s = Signed(value=3, sig=Bytes96(b"\xaa" * 96))
    assert signing_root(s) == hash_tree_root(uint64(3))
    # independent of the signature value
    s2 = Signed(value=3, sig=Bytes96(b"\xbb" * 96))
    assert signing_root(s2) == signing_root(s)


# ------------------------------------------------------------------ containers

def test_zero_value_defaults():
    b = VarBox()
    assert b.tag == 0 and b.items == []
    assert get_zero_value(Bytes32) == b"\x00" * 32
    assert get_zero_value(Vector[uint64, 3]).items == [0, 0, 0]


def test_container_copy_is_deep():
    b = VarBox(tag=1, items=[1, 2])
    c = b.copy()
    c.items.append(3)
    c.tag = 9
    assert b.items == [1, 2] and b.tag == 1


def test_container_field_inheritance():
    class Extended(Point):
        z: uint64

    assert Extended.get_field_names() == ["x", "y", "z"]
    e = Extended(x=1, y=2, z=3)
    assert serialize(e) == b"".join(i.to_bytes(8, "little") for i in (1, 2, 3))


def test_eq_by_hash_tree_root():
    assert Point(x=1, y=2) == Point(x=1, y=2)
    assert Point(x=1, y=2) != Point(x=2, y=1)
