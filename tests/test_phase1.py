"""Phase 1: custody game + shard chains on the object-model spec.

Covers /root/reference specs/core/1_custody-game.md (field-append
containers, the five operation families, epoch inserts) and
1_shard-data-chains.md (persistent committees, shard proposer, crosslink
data root, shard block validity). BLS off except where a scenario is about
signatures (mirroring the phase-0 corpus convention).
"""
from copy import deepcopy

import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0, phase1
from consensus_specs_tpu.testing import factories as f
from consensus_specs_tpu.utils.merkle import (
    calc_merkle_tree_from_leaves, get_merkle_proof)
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root, serialize


@pytest.fixture(scope="module")
def spec():
    return phase1.get_spec("minimal")


@pytest.fixture(autouse=True)
def _bls_off():
    old = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = old


@pytest.fixture()
def state(spec):
    return f.seed_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)


# ---------------------------------------------------------------------------
# Containers: field-append semantics
# ---------------------------------------------------------------------------

def test_appended_fields_preserve_phase0_prefix(spec):
    p0 = phase0.get_spec("minimal")
    for name in ("Validator", "BeaconState", "BeaconBlockBody"):
        p0_fields = [fname for fname, _ in getattr(p0, name).get_fields()]
        p1_fields = [fname for fname, _ in getattr(spec, name).get_fields()]
        assert p1_fields[:len(p0_fields)] == p0_fields, name
        assert len(p1_fields) > len(p0_fields), name


def test_phase1_validator_fields(spec):
    v = spec.Validator()
    assert v.next_custody_reveal_period == 0
    assert v.max_reveal_lateness == 0


def test_phase1_state_serializes_and_roots(spec, state):
    data = serialize(state, spec.BeaconState)
    from consensus_specs_tpu.utils.ssz.impl import deserialize
    back = deserialize(data, spec.BeaconState)
    assert hash_tree_root(back, spec.BeaconState) == \
        hash_tree_root(state, spec.BeaconState)


def test_registry_holds_extended_validators(spec):
    typ = spec.BeaconState.get_fields()
    registry_type = dict(typ)["validator_registry"]
    assert registry_type.elem_type is spec.Validator


# ---------------------------------------------------------------------------
# Custody key reveals
# ---------------------------------------------------------------------------

def _mature_custody_state(spec, state, periods=2):
    state.slot = spec.SLOTS_PER_EPOCH * spec.EPOCHS_PER_CUSTODY_PERIOD * periods
    return state


def test_custody_key_reveal_success(spec, state):
    _mature_custody_state(spec, state)
    reveal = spec.CustodyKeyReveal(revealer_index=3, reveal=b"\x11" * 96)
    before = state.validator_registry[3].next_custody_reveal_period
    spec.process_custody_key_reveal(state, reveal)
    assert state.validator_registry[3].next_custody_reveal_period == before + 1


def test_custody_key_reveal_not_yet_due(spec, state):
    # current period == next_custody_reveal_period: nothing to reveal yet
    reveal = spec.CustodyKeyReveal(revealer_index=3, reveal=b"\x11" * 96)
    with pytest.raises(AssertionError):
        spec.process_custody_key_reveal(state, reveal)


def test_custody_key_reveal_in_block(spec, state):
    """e2e: a phase-1 block carrying a custody key reveal transitions."""
    _mature_custody_state(spec, state)
    block = f.empty_block_next(spec, state)
    block.body.custody_key_reveals.append(
        spec.CustodyKeyReveal(revealer_index=5, reveal=b"\x22" * 96))
    spec.state_transition(state, block)
    assert state.validator_registry[5].next_custody_reveal_period == 1


# ---------------------------------------------------------------------------
# Early derived secret reveals
# ---------------------------------------------------------------------------

def _edsr(spec, state, epoch_ahead, revealed_index=2, masker_index=9):
    return spec.EarlyDerivedSecretReveal(
        revealed_index=revealed_index,
        epoch=spec.get_current_epoch(state) + epoch_ahead,
        reveal=b"\x33" * 96,
        masker_index=masker_index,
        mask=b"\x44" * 32,
    )


def test_early_reveal_inside_custody_window_slashes(spec, state):
    reveal = _edsr(spec, state, spec.CUSTODY_PERIOD_TO_RANDAO_PADDING)
    spec.process_early_derived_secret_reveal(state, reveal)
    assert state.validator_registry[reveal.revealed_index].slashed


def test_early_reveal_outside_window_penalizes_only(spec, state):
    reveal = _edsr(spec, state, spec.RANDAO_PENALTY_EPOCHS)
    pre_balance = state.balances[reveal.revealed_index]
    spec.process_early_derived_secret_reveal(state, reveal)
    assert not state.validator_registry[reveal.revealed_index].slashed
    assert state.balances[reveal.revealed_index] < pre_balance
    slot_index = reveal.epoch % spec.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS
    assert reveal.revealed_index in list(state.exposed_derived_secrets[slot_index])


def test_early_reveal_duplicate_rejected(spec, state):
    reveal = _edsr(spec, state, spec.RANDAO_PENALTY_EPOCHS)
    spec.process_early_derived_secret_reveal(state, reveal)
    with pytest.raises(AssertionError):
        spec.process_early_derived_secret_reveal(state, deepcopy(reveal))


def test_early_reveal_too_late_rejected(spec, state):
    reveal = _edsr(spec, state, 0)   # current epoch: not early at all
    with pytest.raises(AssertionError):
        spec.process_early_derived_secret_reveal(state, reveal)


# ---------------------------------------------------------------------------
# Chunk challenges + responses
# ---------------------------------------------------------------------------

def _challengeable_attestation(spec, state, chunk_count, data_root):
    """An includable attestation whose crosslink spans >=1 epoch and commits
    to `data_root` (challenge paths don't re-check phase-0 data_root rules)."""
    f.advance_epoch(spec, state)
    f.transition_with_empty_block(spec, state)
    att = f.new_attestation(spec, state)
    att.data.crosslink.data_root = data_root
    if chunk_count:
        att.data.crosslink.end_epoch = att.data.crosslink.start_epoch + 1
    return att


def test_chunk_challenge_and_response(spec, state):
    chunk = b"\x07" * spec.BYTES_PER_CUSTODY_CHUNK
    # crosslink spans one epoch -> real chunk tree; commit to a tree whose
    # leaf 0 is our chunk so the response's Merkle branch verifies
    att = _challengeable_attestation(spec, state, 1, spec.ZERO_HASH)
    chunk_count = spec.get_custody_chunk_count(att.data.crosslink)
    depth = spec.ceillog2(chunk_count)
    leaves = [hash_tree_root(chunk)] + [spec.ZERO_HASH] * (chunk_count - 1)
    tree = calc_merkle_tree_from_leaves(leaves, depth)
    att.data.crosslink.data_root = tree[-1][0]

    responder = spec.get_attesting_indices(
        state, att.data, att.aggregation_bitfield)[0]
    challenge = spec.CustodyChunkChallenge(
        responder_index=responder, attestation=att, chunk_index=0)
    spec.process_chunk_challenge(state, challenge)

    records = [r for r in state.custody_chunk_challenge_records
               if r != spec.CustodyChunkChallengeRecord()]
    assert len(records) == 1
    record = records[0]
    assert record.responder_index == responder
    assert record.depth == depth
    assert state.validator_registry[responder].withdrawable_epoch == spec.FAR_FUTURE_EPOCH

    # duplicate challenge on the same (data_root, chunk) must be rejected
    with pytest.raises(AssertionError):
        spec.process_chunk_challenge(state, deepcopy(challenge))

    # answer it after the minimum delay
    state.slot += spec.SLOTS_PER_EPOCH * (spec.ACTIVATION_EXIT_DELAY + 1)
    response = spec.CustodyResponse(
        challenge_index=record.challenge_index,
        chunk_index=0,
        chunk=chunk,
        data_branch=get_merkle_proof(tree, 0),
        chunk_bits_branch=[],
        chunk_bits_leaf=spec.ZERO_HASH,
    )
    spec.process_custody_response(state, response)
    assert all(r == spec.CustodyChunkChallengeRecord()
               for r in state.custody_chunk_challenge_records)


def test_chunk_challenge_wrong_responder_rejected(spec, state):
    att = _challengeable_attestation(spec, state, 0, spec.ZERO_HASH)
    outsiders = [i for i in range(len(state.validator_registry))
                 if i not in spec.get_attesting_indices(
                     state, att.data, att.aggregation_bitfield)]
    challenge = spec.CustodyChunkChallenge(
        responder_index=outsiders[0], attestation=att, chunk_index=0)
    with pytest.raises(AssertionError):
        spec.process_chunk_challenge(state, challenge)


def test_challenge_deadline_slashes_responder(spec, state):
    att = _challengeable_attestation(spec, state, 0, spec.ZERO_HASH)
    responder = spec.get_attesting_indices(
        state, att.data, att.aggregation_bitfield)[0]
    spec.process_chunk_challenge(state, spec.CustodyChunkChallenge(
        responder_index=responder, attestation=att, chunk_index=0))
    state.slot += spec.SLOTS_PER_EPOCH * (spec.CUSTODY_RESPONSE_DEADLINE + 2)
    spec.process_challenge_deadlines(state)
    assert state.validator_registry[responder].slashed
    assert all(r == spec.CustodyChunkChallengeRecord()
               for r in state.custody_chunk_challenge_records)


# ---------------------------------------------------------------------------
# Bit challenges
# ---------------------------------------------------------------------------

def test_bit_challenge_opens_record(spec, state):
    att = _challengeable_attestation(spec, state, 1, spec.ZERO_HASH)
    # a bit challenge targets an attestation from a custody period the
    # responder has already passed: age the state by two full periods
    state.slot += spec.SLOTS_PER_EPOCH * spec.EPOCHS_PER_CUSTODY_PERIOD * 2
    attesters = spec.get_attesting_indices(state, att.data, att.aggregation_bitfield)
    responder = attesters[0]
    challenger = [i for i in range(len(state.validator_registry))
                  if i not in attesters][0]
    chunk_count = spec.get_custody_chunk_count(att.data.crosslink)
    assert chunk_count > 0

    # find chunk bits whose folded-hash first bit is 1 (custody bit is 0)
    width = (chunk_count + 7) // 8
    chunk_bits = None
    for probe in range(256):
        candidate = bytes([probe]) + b"\x00" * (width - 1)
        if spec.get_bitfield_bit(spec.get_chunk_bits_root(candidate), 0) == 1:
            chunk_bits = candidate
            break
    assert chunk_bits is not None

    challenge = spec.CustodyBitChallenge(
        responder_index=responder,
        attestation=att,
        challenger_index=challenger,
        responder_key=b"\x55" * 96,
        chunk_bits=chunk_bits,
        signature=b"\x66" * 96,
    )
    spec.process_bit_challenge(state, challenge)
    records = [r for r in state.custody_bit_challenge_records
               if r != spec.CustodyBitChallengeRecord()]
    assert len(records) == 1
    assert records[0].chunk_count == chunk_count

    # one challenger, one open challenge at a time
    with pytest.raises(AssertionError):
        spec.process_bit_challenge(state, deepcopy(challenge))


# ---------------------------------------------------------------------------
# Epoch inserts
# ---------------------------------------------------------------------------

def test_reveal_deadline_slashes_laggards(spec, state):
    periods_late = spec.CUSTODY_RESPONSE_DEADLINE // spec.EPOCHS_PER_CUSTODY_PERIOD + 2
    _mature_custody_state(spec, state, periods=periods_late)
    spec.process_reveal_deadlines(state)
    assert all(v.slashed for v in state.validator_registry)


def test_final_updates_cleans_exposed_secrets_and_unfreezes(spec, state):
    reveal = _edsr(spec, state, spec.RANDAO_PENALTY_EPOCHS)
    spec.process_early_derived_secret_reveal(state, reveal)
    slot_index = reveal.epoch % spec.EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS

    # a frozen-withdrawability exited validator with no open challenge
    leaver = 7
    state.validator_registry[leaver].exit_epoch = spec.get_current_epoch(state)
    state.validator_registry[leaver].withdrawable_epoch = spec.FAR_FUTURE_EPOCH

    # roll current_epoch onto the reveal's storage slot, then clean up
    state.slot = reveal.epoch * spec.SLOTS_PER_EPOCH
    spec.after_process_final_updates(state)
    assert list(state.exposed_derived_secrets[slot_index]) == []
    assert state.validator_registry[leaver].withdrawable_epoch != spec.FAR_FUTURE_EPOCH


def test_phase1_epoch_transition_runs_inserts(spec, state):
    """Full process_slots across an epoch boundary with the phase-1 hooks
    registered must execute without error."""
    f.advance_epoch(spec, state)
    assert spec.get_current_epoch(state) == 1


# ---------------------------------------------------------------------------
# Shard chains
# ---------------------------------------------------------------------------

def test_persistent_committee_deterministic(spec, state):
    a = spec.get_persistent_committee(state, 0, state.slot)
    b = spec.get_persistent_committee(state, 0, state.slot)
    assert a == b
    assert a == sorted(a)
    assert all(0 <= i < len(state.validator_registry) for i in a)


def test_shard_proposer_is_active_member(spec, state):
    committee = spec.get_persistent_committee(state, 1, state.slot)
    proposer = spec.get_shard_proposer_index(state, 1, state.slot)
    if committee:
        assert proposer in committee
        assert spec.is_active_validator(
            state.validator_registry[proposer], spec.get_current_epoch(state))


def test_crosslink_data_root_deterministic_and_sensitive(spec, state):
    body = spec.ShardBlockBody(data=b"\x01" * spec.BYTES_PER_SHARD_BLOCK_BODY)
    blk = spec.ShardBlock(slot=0, shard=0, data=body)
    root1 = spec.compute_crosslink_data_root([blk])
    assert root1 == spec.compute_crosslink_data_root([deepcopy(blk)])
    blk2 = deepcopy(blk)
    blk2.data = spec.ShardBlockBody(data=b"\x02" * spec.BYTES_PER_SHARD_BLOCK_BODY)
    assert spec.compute_crosslink_data_root([blk2]) != root1
    assert spec.compute_crosslink_data_root([]) != root1


def test_shard_block_validity_happy_path(spec, state):
    """A fork-slot shard block anchored to a real beacon block validates."""
    beacon_block = f.empty_block(spec, state)
    beacon_blocks = [beacon_block] * (spec.SLOTS_PER_EPOCH * 2)
    candidate = spec.ShardBlock(
        slot=spec.PHASE_1_FORK_SLOT,
        shard=1,
        beacon_chain_root=spec.signing_root(beacon_block),
        parent_root=spec.ZERO_HASH,
        data=spec.ShardBlockBody(data=b"\x00" * spec.BYTES_PER_SHARD_BLOCK_BODY),
        state_root=spec.ZERO_HASH,
    )
    assert spec.is_valid_shard_block(beacon_blocks, state, [], candidate)


def test_shard_block_wrong_beacon_root_rejected(spec, state):
    beacon_block = f.empty_block(spec, state)
    beacon_blocks = [beacon_block] * spec.SLOTS_PER_EPOCH
    candidate = spec.ShardBlock(
        slot=spec.PHASE_1_FORK_SLOT,
        shard=1,
        beacon_chain_root=b"\x13" * 32,
        parent_root=spec.ZERO_HASH,
        data=spec.ShardBlockBody(data=b"\x00" * spec.BYTES_PER_SHARD_BLOCK_BODY),
        state_root=spec.ZERO_HASH,
    )
    with pytest.raises(AssertionError):
        spec.is_valid_shard_block(beacon_blocks, state, [], candidate)


# ---------------------------------------------------------------------------
# Device epoch path with insert hooks (VERDICT r3 #6)
# ---------------------------------------------------------------------------

def _diff_epoch_paths(spec, state):
    """process_epoch vs process_epoch_soa on copies; returns (ref, soa)."""
    from consensus_specs_tpu.models.phase0.epoch_soa import process_epoch_soa
    if (state.slot + 1) % spec.SLOTS_PER_EPOCH != 0:
        state.slot += (spec.SLOTS_PER_EPOCH - 1
                       - state.slot % spec.SLOTS_PER_EPOCH)
    ref, soa = deepcopy(state), deepcopy(state)
    spec.process_epoch(ref)
    out = process_epoch_soa(spec, soa)
    assert out is not None, "staged device path must run, not fall back"
    assert hash_tree_root(ref) == hash_tree_root(soa)
    return ref, soa


def test_phase1_device_epoch_matches_object_model(spec, state):
    """Attested phase-1 epoch: the staged device path (stage A -> hooks ->
    stage B) must equal Phase1Spec.process_epoch bit-for-bit."""
    from consensus_specs_tpu.testing.cases.finality import attested_epoch
    f.advance_epoch(spec, state)
    f.transition_with_empty_block(spec, state)
    _, _, state = attested_epoch(spec, state, current=True, previous=True)
    _diff_epoch_paths(spec, state)


def test_phase1_hook_slashing_lands_between_stages(spec, state):
    """An overdue custody challenge makes @process_challenge_deadlines slash
    BETWEEN the two device stages; stage B must see the new slashed flag and
    slashed-balance table exactly like the object model's sequential run."""
    att = _challengeable_attestation(spec, state, 0, spec.ZERO_HASH)
    responder = spec.get_attesting_indices(
        state, att.data, att.aggregation_bitfield)[0]
    spec.process_chunk_challenge(state, spec.CustodyChunkChallenge(
        responder_index=responder, attestation=att, chunk_index=0))
    state.previous_epoch_attestations = []
    state.current_epoch_attestations = []
    state.slot += spec.SLOTS_PER_EPOCH * (spec.CUSTODY_RESPONSE_DEADLINE + 2)
    ref, soa = _diff_epoch_paths(spec, state)
    assert soa.validator_registry[responder].slashed
