"""Differential gate for the device-resident multi-epoch pipeline.

ResidentCore keeps registry/balances on device across slots, blocks, and
epoch boundaries (models/phase0/resident.py). These tests drive the SAME
block sequence through the object-model spec and through ResidentCore and
assert byte-identical outcomes:

  1. multi-epoch drive with attestation-carrying blocks — per-transition
     full-state roots agree, and the serialized states agree after exit();
  2. a registry-mutating block (proposer slashing) takes the fallback
     (exit -> object path -> re-enter) and stays bit-equal;
  3. the resident state-root backend declines foreign states (the object
     model's differential copy must not be rooted from device columns).
"""
from copy import deepcopy
from types import SimpleNamespace

import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.models.phase0.resident import ResidentCore
from consensus_specs_tpu.testing import factories
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root, serialize


@pytest.fixture
def spec():
    s = phase0.get_spec("minimal")
    bls.bls_active = False
    s.clear_caches()
    yield s
    s.clear_caches()


def _attestation_block(spec, ref):
    """A block at ref.slot+delay carrying a fully-participated attestation
    for ref's current slot (built on the object state; both paths apply
    the identical block)."""
    att = factories.new_attestation(spec, ref)
    block = factories.empty_block_next(spec, ref)
    block.slot = ref.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY
    block.body.attestations.append(att)
    return block


def _drive(spec, ref, res, core, n_blocks, mutate=None):
    """Apply n_blocks attestation blocks to both paths, checking the full
    state root after every transition. `mutate(i, block)` can inject
    extra operations into block i."""
    for i in range(n_blocks):
        with core.suspended():
            # the reference path must run against the UNPATCHED spec —
            # otherwise mirror-derived committees/proposers/index-roots
            # would be compared against themselves
            block = _attestation_block(spec, ref)
            if mutate is not None:
                mutate(i, block)
            spec.process_slots(ref, block.slot)
            spec.process_block(ref, block)
        core.state_transition(res, block)
        assert hash_tree_root(ref) == core._state_root(res), \
            f"state root diverged after block {i} (slot {block.slot})"


def test_resident_multi_epoch_bit_equality(spec):
    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    # move off genesis so attestations target a real block history
    factories.advance_slots(spec, state, 2)
    ref, res = deepcopy(state), deepcopy(state)
    core = ResidentCore(spec, res)
    try:
        # > 3 epochs of consecutive attestation-carrying blocks
        _drive(spec, ref, res, core, 3 * spec.SLOTS_PER_EPOCH + 4)
        assert spec.get_current_epoch(ref) >= 3
    finally:
        core.exit()
    assert serialize(ref, spec.BeaconState) == serialize(res, spec.BeaconState)


def test_resident_fallback_on_registry_mutating_block(spec):
    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    ref, res = deepcopy(state), deepcopy(state)
    core = ResidentCore(spec, res)

    def mutate(i, block):
        if i == spec.SLOTS_PER_EPOCH + 1:   # mid-drive, epoch > 0
            block.body.proposer_slashings.append(
                factories.double_proposal(spec, ref))
    try:
        _drive(spec, ref, res, core, 2 * spec.SLOTS_PER_EPOCH, mutate=mutate)
        # the slashing really happened on both paths
        assert any(v.slashed for v in ref.validator_registry)
    finally:
        core.exit()
    assert serialize(ref, spec.BeaconState) == serialize(res, spec.BeaconState)


def test_fallback_is_incremental_and_grows_forest(spec):
    """A registry-mutating block must NOT throw the registry-scale trees
    away: the same incremental forests survive the fallback with
    O(dirty·log V) pair-hash lanes, and a deposit block append-grows them
    across the padded power-of-two boundary — roots bit-equal to the
    object model throughout."""
    from consensus_specs_tpu.utils.merkle import tree_depth

    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    ref, res = deepcopy(state), deepcopy(state)
    core = ResidentCore(spec, res)
    try:
        core._state_root(res)                    # build the forests
        f_reg, f_bal = core._reg_forest, core._bal_forest
        V = len(ref.validator_registry)
        assert f_reg is not None and f_reg.builds == 1 and f_reg.n == V
        assert V & (V - 1) == 0, "seed V must be a power of two for the test"

        # -- slashing: dirties a handful of validators -----------------------
        with core.suspended():
            block = factories.empty_block_next(spec, ref)
            block.body.proposer_slashings.append(
                factories.double_proposal(spec, ref))
            spec.process_slots(ref, block.slot)
            spec.process_block(ref, block)
        core.state_transition(res, block)
        assert core._reg_forest is f_reg and core._bal_forest is f_bal
        assert f_reg.builds == 1                 # updated in place, no rebuild
        # the slashing touches one validator's registry leaf (plus pow2
        # index padding); nowhere near the V-leaf rebuild
        assert 0 < sum(f_reg.last_pairs_per_level) <= 2 * 2 * f_reg.depth
        assert hash_tree_root(ref) == core._state_root(res)

        # -- deposit: grows V -> V+1 across the padded power of two ----------
        with core.suspended():
            # stage the deposit BEFORE building the block: it plants eth1
            # data into the state, and empty_block seals the parent header
            # with the state root as of build time
            deposit = factories.stage_deposit(
                spec, ref, V, spec.MAX_EFFECTIVE_BALANCE)
            # the planted eth1 data is pre-block chain context BOTH paths
            # need (snapshot before ref's transition can vote on it)
            res.latest_eth1_data = deepcopy(ref.latest_eth1_data)
            block = factories.empty_block_next(spec, ref)
            block.body.deposits.append(deposit)
            spec.process_slots(ref, block.slot)
            spec.process_block(ref, block)
        core.state_transition(res, block)
        assert core._reg_forest is f_reg and f_reg.n == V + 1
        assert f_reg.depth == tree_depth(V + 1) > tree_depth(V)
        assert len(core._pk_np) == V + 1         # identity columns grew too
        assert hash_tree_root(ref) == core._state_root(res)
    finally:
        core.exit()
    assert serialize(ref, spec.BeaconState) == serialize(res, spec.BeaconState)


def test_resident_root_backend_declines_foreign_state(spec):
    state = factories.seed_genesis_state(spec, 2 * spec.SLOTS_PER_EPOCH)
    res = deepcopy(state)
    other = deepcopy(state)
    other.slot += 123    # diverge the foreign state
    core = ResidentCore(spec, res)
    try:
        # entry parity: resident root == recursive oracle root
        assert core._state_root(res) == hash_tree_root(res)
        # the spec-level hook must route the foreign state to the oracle,
        # not to the resident device columns
        assert spec.hash_tree_root(other) == hash_tree_root(other)
    finally:
        core.exit()


def test_overrides_delegate_for_foreign_state(spec):
    """The _install overrides mirror the _state_root guard: a state other
    than the resident one (fork choice's justified state, a differential
    copy) must be answered from ITS registry via the saved object path,
    not from the resident device mirrors."""
    from consensus_specs_tpu.models.phase0.fork_choice import Store, get_head

    state = factories.seed_genesis_state(spec, 8)
    res = deepcopy(state)
    justified = deepcopy(state)
    # diverge the justified state's registry: validators 0-3 exited, and a
    # distinct effective balance on validator 4
    epoch = spec.slot_to_epoch(justified.slot)
    for i in range(4):
        justified.validator_registry[i].exit_epoch = epoch
    justified.validator_registry[4].effective_balance -= \
        spec.EFFECTIVE_BALANCE_INCREMENT

    core = ResidentCore(spec, res)
    try:
        with core.suspended():
            want_active = spec.get_active_validator_indices(justified, epoch)
            want_total = spec.get_total_balance(justified, want_active)
            want_eb = spec.effective_balance_of(justified, 4)
        # overrides installed: foreign state -> object-path answers
        assert list(spec.get_active_validator_indices(justified, epoch)) \
            == list(want_active) == [4, 5, 6, 7]
        assert spec.get_total_balance(justified, want_active) == want_total
        assert spec.effective_balance_of(justified, 4) == want_eb
        # ... while the resident state still answers from the mirrors
        assert list(spec.get_active_validator_indices(res, epoch)) \
            == list(range(8))

        # end to end through fork choice's justified-state path: votes of
        # the justified-exited validators 0-3 must not count
        store = Store()
        root_g, root_a, root_b = (bytes([9]) + bytes(31),
                                  bytes([1]) + bytes(31),
                                  bytes([2]) + bytes(31))
        store.add_block(root_g, SimpleNamespace(slot=0), None)
        store.add_block(root_a, SimpleNamespace(slot=1), root_g)
        store.add_block(root_b, SimpleNamespace(slot=1), root_g)
        store.on_attestation([0, 1, 2, 3], root_a, slot=1)   # exited
        store.on_attestation([5, 6, 7], root_b, slot=1)      # active
        assert get_head(spec, store, justified) == root_b
    finally:
        core.exit()


def test_light_core_refuses_state_transition(spec):
    """A checkpoint-resumed (light) core must fail loudly BEFORE
    process_slots mutates state: block processing needs the object
    registry the light entry deliberately never built."""
    state = factories.seed_genesis_state(spec, 2 * spec.SLOTS_PER_EPOCH)
    data = serialize(state, spec.BeaconState)
    core = ResidentCore.from_checkpoint(spec, data)
    try:
        block = SimpleNamespace(slot=int(state.slot) + 1)
        before = int(core.state.slot)
        with pytest.raises(NotImplementedError):
            core.state_transition(core.state, block)
        assert int(core.state.slot) == before   # nothing mutated
    finally:
        core._uninstall()


def test_checkpoint_resume_light_residency(spec):
    """Serialized state -> light residency (no Validator objects) -> drive
    an epoch boundary -> checkpoint_bytes == the object model's serialized
    post-state. The production resume path end to end."""
    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    data = serialize(state, spec.BeaconState)

    from consensus_specs_tpu.models.phase0.resident import light_state_from_bytes
    core = ResidentCore.from_checkpoint(spec, data)
    try:
        # entry round trip: no transition -> byte-identical checkpoint
        assert core.checkpoint_bytes() == data
        # entry root parity against the object-model recursive oracle
        assert core._state_root(core.state) == hash_tree_root(state)

        # drive both paths to the first slot of the next epoch
        ref = deepcopy(state)
        target = spec.get_epoch_start_slot(spec.get_current_epoch(ref) + 1)
        with core.suspended():
            spec.process_slots(ref, target)
        core.process_slots(core.state, target)
        assert core.checkpoint_bytes() == serialize(ref, spec.BeaconState)
        # light residency has no objects to exit into
        with pytest.raises(NotImplementedError):
            core.exit()
    finally:
        core._uninstall()

    # light_state_from_bytes really leaves the registry unmaterialized
    light = light_state_from_bytes(spec, data)
    assert len(light.validator_registry) == 0 and len(light.balances) == 0
    assert int(light.slot) == int(state.slot)


@pytest.fixture
def serving_mesh():
    import jax
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    if len(jax.devices()) < 8:
        pytest.skip(f"needs 8 devices, have {len(jax.devices())}")
    return ServingMesh.create(8)


def test_resident_sharded_serving_loop(spec, serving_mesh):
    """The whole serving loop under the validator-axis NamedSharding:
    multi-slot chained steps across epoch boundaries with the columns and
    forests never leaving the mesh layout, every per-transition root
    bit-equal to the object model (which the single-device suite above
    already gates bit-equal to the single-device core)."""
    mesh = serving_mesh
    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    ref, res = deepcopy(state), deepcopy(state)
    core = ResidentCore(spec, res, mesh=mesh)
    try:
        assert core.cols.balance.sharding.is_equivalent_to(mesh.shard_v, 1)
        _drive(spec, ref, res, core, 2 * spec.SLOTS_PER_EPOCH + 2)
        assert spec.get_current_epoch(ref) >= 2
        # chained boundaries kept the layout: columns still sharded, the
        # forests' sharded levels still on their shards, cap replicated
        assert core.cols.balance.sharding.is_equivalent_to(mesh.shard_v, 1)
        assert core._reg_forest.levels[0].sharding.is_equivalent_to(
            mesh.shard_v, 2)
        assert core._reg_forest.levels[-1].sharding.is_equivalent_to(
            mesh.replicated, 2)
    finally:
        core.exit()
    assert serialize(ref, spec.BeaconState) == serialize(res, spec.BeaconState)


def test_resident_sharded_fallback_and_deposit_growth(spec, serving_mesh):
    """Under sharding, a registry-mutating block re-enters INCREMENTALLY
    (same forests, scatter-only updates, no rebuild) and a deposit
    append-grows the padded columns and forests across a shard boundary
    (V 32 -> 33: columns 32 -> 40 rows, forest capacity 32 -> 64), all
    bit-equal to the object model."""
    from consensus_specs_tpu.utils.merkle import tree_depth

    mesh = serving_mesh
    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    ref, res = deepcopy(state), deepcopy(state)
    core = ResidentCore(spec, res, mesh=mesh)
    try:
        core._state_root(res)
        f_reg, f_bal = core._reg_forest, core._bal_forest
        V = len(ref.validator_registry)
        assert V % mesh.size == 0, "seed V must already tile the mesh"
        assert f_reg.n == V and f_reg.builds == 1

        # -- slashing: incremental re-entry, forests survive -----------------
        with core.suspended():
            block = factories.empty_block_next(spec, ref)
            block.body.proposer_slashings.append(
                factories.double_proposal(spec, ref))
            spec.process_slots(ref, block.slot)
            spec.process_block(ref, block)
        core.state_transition(res, block)
        assert core._reg_forest is f_reg and core._bal_forest is f_bal
        assert f_reg.builds == 1
        assert 0 < sum(f_reg.last_pairs_per_level) <= 2 * 2 * f_reg.depth
        assert hash_tree_root(ref) == core._state_root(res)
        assert core.cols.balance.sharding.is_equivalent_to(mesh.shard_v, 1)

        # -- deposit: V -> V+1 crosses padding AND capacity ------------------
        with core.suspended():
            deposit = factories.stage_deposit(
                spec, ref, V, spec.MAX_EFFECTIVE_BALANCE)
            res.latest_eth1_data = deepcopy(ref.latest_eth1_data)
            block = factories.empty_block_next(spec, ref)
            block.body.deposits.append(deposit)
            spec.process_slots(ref, block.slot)
            spec.process_block(ref, block)
        core.state_transition(res, block)
        assert core._v == V + 1
        # columns padded to the next mesh multiple with inert rows
        assert int(core.cols.balance.shape[0]) == mesh.pad_rows(V + 1)
        assert core.cols.balance.sharding.is_equivalent_to(mesh.shard_v, 1)
        assert core._reg_forest is f_reg and f_reg.n == V + 1
        assert f_reg.depth == tree_depth(V + 1) > tree_depth(V)
        assert f_reg.builds == 1                  # grew, did not rebuild
        assert len(core._pk_np) == V + 1
        assert hash_tree_root(ref) == core._state_root(res)

        # -- and the next epoch boundary still runs sharded ------------------
        target = spec.get_epoch_start_slot(spec.get_current_epoch(ref) + 1)
        with core.suspended():
            spec.process_slots(ref, target)
        core.process_slots(res, target)
        assert hash_tree_root(ref) == core._state_root(res)
        assert core.cols.balance.sharding.is_equivalent_to(mesh.shard_v, 1)
    finally:
        core.exit()
    assert serialize(ref, spec.BeaconState) == serialize(res, spec.BeaconState)


def test_resident_serving_mesh_env_knob(spec, serving_mesh, monkeypatch):
    """CSTPU_SERVING_MESH turns the sharded serving path on without code
    changes (the production entry); unset/0 keeps single-device."""
    state = factories.seed_genesis_state(spec, 2 * spec.SLOTS_PER_EPOCH)
    monkeypatch.setenv("CSTPU_SERVING_MESH", "8")
    core = ResidentCore(spec, deepcopy(state))
    try:
        assert core._mesh is not None and core._mesh.size == 8
        assert core.cols.balance.sharding.is_equivalent_to(
            core._mesh.shard_v, 1)
        assert core._state_root(core.state) == hash_tree_root(state)
    finally:
        core.exit()
    monkeypatch.setenv("CSTPU_SERVING_MESH", "0")
    core = ResidentCore(spec, deepcopy(state))
    try:
        assert core._mesh is None
    finally:
        core.exit()


def test_from_checkpoint_rejects_phase1_hooks(spec):
    """A phase-1 spec (epoch insert hooks) must refuse BOTH entry points —
    the staged path (process_epoch_soa_staged) owns that configuration."""
    from consensus_specs_tpu.models import phase1
    p1 = phase1.get_spec("minimal")
    state = factories.seed_genesis_state(p1, 8)
    data = serialize(state, p1.BeaconState)
    with pytest.raises(NotImplementedError):
        ResidentCore.from_checkpoint(p1, data)
    with pytest.raises(NotImplementedError):
        ResidentCore(p1, state)
