"""Signature-bearing spec scenarios under the JAX BLS backend.

The e2e gate VERDICT r2 asked for: rows from the scenario corpus that
actually exercise signatures (the @always_bls rejection rows plus the
success rows re-run with BLS ON) execute under BOTH crypto backends, and
their generator-mode artifacts — encoded pre/post states and operations —
must match byte-for-byte. This proves the device pairing path is a drop-in
for the bignum oracle inside real process_* handlers, not just in isolated
curve tests.

Backend boundary: consensus_specs_tpu/crypto/bls.py (mirrors
/root/reference test_libs/pyspec/eth2spec/utils/bls.py:24-46 + the
bls_setting test switch at eth2spec/test/context.py:79-90).
"""
import importlib

import pytest

pytestmark = pytest.mark.slow  # pairing compiles dominate suite wall-clock

from consensus_specs_tpu.crypto import bls

# (table module, case name) — kept small: every row here signs and/or
# verifies real signatures, and each runs twice (once per backend)
ROWS = [
    ("attestation", "test_success"),
    ("attestation", "test_invalid_attestation_signature"),
    ("block_header", "test_success_block_header"),
    ("block_header", "test_invalid_sig_block_header"),
    ("proposer_slashing", "test_success"),
    ("proposer_slashing", "test_invalid_sig_1"),
    ("deposit", "test_new_deposit"),
    ("deposit", "test_invalid_sig_new_deposit"),
    ("voluntary_exit", "test_success"),
    ("voluntary_exit", "test_invalid_signature"),
]


def _run_row(module_name: str, case_name: str, backend: str):
    mod = importlib.import_module(
        f"consensus_specs_tpu.testing.cases.{module_name}")
    fn = getattr(mod, case_name)
    old = bls._active_backend_name
    bls.set_backend(backend)
    try:
        return fn(generator_mode=True, phase="phase0", preset="minimal",
                  bls_active=True)
    finally:
        bls.set_backend(old)


@pytest.mark.parametrize("module_name,case_name", ROWS,
                         ids=[f"{m}:{c}" for m, c in ROWS])
def test_jax_backend_matches_python(module_name, case_name):
    via_python = _run_row(module_name, case_name, "python")
    via_jax = _run_row(module_name, case_name, "jax")
    assert via_python == via_jax


def test_backend_sign_agreement():
    """Direct cross-backend signing equality on a spec-shaped message."""
    msg, sk, dom = b"\x42" * 32, 777, 5
    bls.set_backend("python")
    ref = bls.get_backend().sign(msg, sk, dom)
    bls.set_backend("jax")
    try:
        dev = bls.get_backend().sign(msg, sk, dom)
    finally:
        bls.set_backend("python")
    assert ref == dev
