"""The bulk-Merkleizer state-root hook vs the recursive oracle.

VERDICT r3 #3: process_slot's full-state hash_tree_root (the reference's
hottest loop, 0_beacon-chain.md:1232-1245) must actually route through
utils/ssz/bulk.py when installed. These tests install the hook and drive
real transitions, requiring bit-identical states against the un-hooked
recursive path at every step.
"""
from copy import deepcopy

import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.models.phase0 import helpers
from consensus_specs_tpu.testing.cases.finality import attested_epoch
from consensus_specs_tpu.testing.factories import (
    advance_epoch,
    advance_slots,
    empty_block_next,
    new_attestation,
    seed_genesis_state,
    transition_with_empty_block,
)
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root


@pytest.fixture(scope="module")
def spec():
    return phase0.get_spec("minimal")


@pytest.fixture(autouse=True)
def _bls_off_and_hook():
    old = bls.bls_active
    bls.bls_active = False
    helpers.install_bulk_state_root()
    yield
    helpers.set_state_root_backend(None)
    bls.bls_active = old


def test_hook_returns_oracle_root(spec):
    state = seed_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)
    hooked = spec.hash_tree_root(state)
    helpers.set_state_root_backend(None)
    assert hooked == spec.hash_tree_root(state) == hash_tree_root(state)


def test_hook_is_actually_consulted(spec):
    state = seed_genesis_state(spec, 8)
    seen = []

    def probe(s):
        seen.append(s)
        return None  # decline -> fall back to oracle

    helpers.set_state_root_backend(probe)
    root = spec.hash_tree_root(state)
    assert seen == [state]
    assert root == hash_tree_root(state)


def test_transitions_identical_with_and_without_hook(spec):
    """Blocks, attestations, and epoch boundaries under the hooked root."""
    base = seed_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)
    plain = deepcopy(base)

    def script(state):
        advance_epoch(spec, state)
        transition_with_empty_block(spec, state)
        att = new_attestation(spec, state)
        advance_slots(spec, state, spec.MIN_ATTESTATION_INCLUSION_DELAY)
        block = empty_block_next(spec, state)
        block.body.attestations.append(att)
        spec.state_transition(state, block)
        _, _, state = attested_epoch(spec, state, current=True)
        return state

    state = script(base)               # hooked run
    helpers.set_state_root_backend(None)
    plain = script(plain)              # un-hooked run, same script

    assert hash_tree_root(state) == hash_tree_root(plain)


def test_hook_covers_nonempty_operations_state(spec):
    """A state dirtied by slashings/exits still roots identically."""
    state = seed_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)
    advance_epoch(spec, state)
    transition_with_empty_block(spec, state)
    current_epoch = spec.get_current_epoch(state)
    for i in (1, 5):
        v = state.validator_registry[i]
        v.slashed = True
        v.exit_epoch = current_epoch + 1
        v.withdrawable_epoch = current_epoch + spec.LATEST_SLASHED_EXIT_LENGTH
    state.validator_registry[2].exit_epoch = current_epoch + 4
    hooked = spec.hash_tree_root(state)
    helpers.set_state_root_backend(None)
    assert hooked == spec.hash_tree_root(state)
