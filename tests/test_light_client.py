"""Light-client multiproofs: generalized indices, proofs, partials.

Contract: /root/reference specs/light_client/merkle_proofs.md. Every proof
here is cross-checked two ways: the prover's node map must agree with the
recursive hash_tree_root at the root, and tampered leaves/proofs must fail
verification.
"""
from random import Random

import pytest

from consensus_specs_tpu.light_client import (
    MerklePartial, SSZMerkleTree, generalized_index_for_path,
    get_helper_indices, merkle_tree_nodes, verify_multiproof)
from consensus_specs_tpu.light_client.multiproof import LENGTH_FLAG, object_tree
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.testing import factories as f
from consensus_specs_tpu.utils.hash import sha256
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root
from consensus_specs_tpu.utils.ssz.typing import (
    Bytes32, Container, List as SSZList, Vector, uint64)

SPEC = phase0.get_spec("minimal")


def test_merkle_tree_nodes_structure():
    leaves = [bytes([i]) * 32 for i in range(4)]
    nodes = merkle_tree_nodes(leaves)
    assert nodes[4] == leaves[0] and nodes[7] == leaves[3]
    assert nodes[2] == sha256(leaves[0] + leaves[1])
    assert nodes[1] == sha256(nodes[2] + nodes[3])


def test_single_leaf_proof_roundtrip():
    leaves = [bytes([i]) * 32 for i in range(8)]
    nodes = merkle_tree_nodes(leaves)
    for gidx in (8, 11, 15):
        helpers = get_helper_indices([gidx])
        proof = [nodes[i] for i in helpers]
        assert verify_multiproof(nodes[1], [gidx], [nodes[gidx]], proof)
        assert not verify_multiproof(nodes[1], [gidx], [b"\xff" * 32], proof)


def test_multiproof_smaller_than_separate_proofs():
    leaves = [bytes([i]) * 32 for i in range(8)]
    nodes = merkle_tree_nodes(leaves)
    indices = [8, 9, 14]   # the spec's worked example (:121-130)
    helpers = get_helper_indices(indices)
    assert len(helpers) == 3   # vs 9 for three separate depth-3 proofs
    proof = [nodes[i] for i in helpers]
    assert verify_multiproof(nodes[1], indices, [nodes[i] for i in indices], proof)


@pytest.mark.parametrize("seed", range(4))
def test_random_multiproofs(seed):
    rng = Random(seed)
    n = 16
    leaves = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(n)]
    nodes = merkle_tree_nodes(leaves)
    k = rng.randrange(1, 6)
    indices = rng.sample(range(n, 2 * n), k)
    helpers = get_helper_indices(indices)
    proof = [nodes[i] for i in helpers]
    values = [nodes[i] for i in indices]
    assert verify_multiproof(nodes[1], indices, values, proof)
    # corrupt one proof node
    if proof:
        bad = list(proof)
        bad[0] = b"\x00" * 32 if bad[0] != b"\x00" * 32 else b"\x01" * 32
        assert not verify_multiproof(nodes[1], indices, values, bad)


class Inner(Container):
    w: uint64
    r: Bytes32


class Demo(Container):
    x: uint64
    y: SSZList[uint64]
    vec: Vector[Inner, 2]


def _demo():
    return Demo(x=7, y=[5, 6, 7],
                vec=Vector[Inner, 2]([Inner(w=1, r=b"\xaa" * 32),
                                      Inner(w=2, r=b"\xbb" * 32)]))


def test_object_tree_root_matches_htr():
    obj = _demo()
    nodes = object_tree(obj, Demo)
    assert nodes[1] == hash_tree_root(obj, Demo)


def test_path_indices_resolve_to_correct_nodes():
    obj = _demo()
    tree = SSZMerkleTree(obj, Demo)

    gx = generalized_index_for_path(obj, Demo, ["x"])
    assert tree.nodes[gx] == (7).to_bytes(8, "little") + b"\x00" * 24

    glen = generalized_index_for_path(obj, Demo, ["y", LENGTH_FLAG])
    assert tree.nodes[glen] == (3).to_bytes(32, "little")

    gy0 = generalized_index_for_path(obj, Demo, ["y", 0])
    chunk = tree.nodes[gy0]
    assert chunk[:8] == (5).to_bytes(8, "little")

    gw = generalized_index_for_path(obj, Demo, ["vec", 1, "w"])
    assert tree.nodes[gw] == (2).to_bytes(8, "little") + b"\x00" * 24


def test_partial_proves_paths_against_state_root():
    obj = _demo()
    tree = SSZMerkleTree(obj, Demo)
    indices = [
        generalized_index_for_path(obj, Demo, ["x"]),
        generalized_index_for_path(obj, Demo, ["y", LENGTH_FLAG]),
        generalized_index_for_path(obj, Demo, ["vec", 0, "r"]),
    ]
    partial = tree.prove(indices)
    assert partial.verify()
    assert partial.value_at(indices[2]) == b"\xaa" * 32
    # against the wrong root it must fail
    assert not MerklePartial(b"\x42" * 32, partial.indices, partial.values,
                             partial.proof).verify()


def test_beacon_state_field_proof():
    """A light client authenticates finalized_epoch against the state root."""
    from consensus_specs_tpu.crypto import bls
    bls.bls_active = False
    state = f.seed_genesis_state(SPEC, SPEC.SLOTS_PER_EPOCH * 8)
    state.finalized_epoch = 9
    tree = SSZMerkleTree(state, SPEC.BeaconState)
    gidx = generalized_index_for_path(state, SPEC.BeaconState, ["finalized_epoch"])
    partial = tree.prove([gidx])
    assert partial.verify()
    assert int.from_bytes(partial.value_at(gidx)[:8], "little") == 9
    assert tree.root == hash_tree_root(state, SPEC.BeaconState)
