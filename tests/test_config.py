from consensus_specs_tpu.utils.config import load_preset, mainnet, minimal


def test_load_presets():
    mn, ml = mainnet(), minimal()
    assert mn.SLOTS_PER_EPOCH == 64
    assert ml.SLOTS_PER_EPOCH == 8
    assert mn.SHUFFLE_ROUND_COUNT == 90
    assert ml.SHUFFLE_ROUND_COUNT == 10
    assert mn.FAR_FUTURE_EPOCH == 2 ** 64 - 1
    assert mn.GENESIS_FORK_VERSION == b"\x00" * 4


def test_preset_immutable_and_replace():
    ml = minimal()
    try:
        ml.SLOTS_PER_EPOCH = 4
        raised = False
    except AttributeError:
        raised = True
    assert raised
    custom = ml.replace(SLOTS_PER_EPOCH=4)
    assert custom.SLOTS_PER_EPOCH == 4
    assert minimal().SLOTS_PER_EPOCH == 8


def test_preset_cached():
    assert load_preset("minimal") is load_preset("minimal")


# ---------------------------------------------------------------------------
# Fork timelines (reference configs/fork_timelines/*)
# ---------------------------------------------------------------------------

def test_fork_timelines_load_and_schedule():
    from consensus_specs_tpu.utils.config import fork_at_epoch, load_fork_timeline
    for name in ("mainnet", "testing"):
        tl = load_fork_timeline(name)
        assert tl["phase0"] == 0  # == GENESIS_EPOCH (GENESIS_SLOT normalized to 0)
        assert fork_at_epoch(tl, 0) == "phase0"
        assert fork_at_epoch(tl, 10 ** 6) in tl


def test_fork_timeline_picks_latest_activated():
    from consensus_specs_tpu.utils.config import fork_at_epoch
    tl = {"phase0": 0, "phase1": 100}
    assert fork_at_epoch(tl, 99) == "phase0"
    assert fork_at_epoch(tl, 100) == "phase1"
    assert fork_at_epoch(tl, 500) == "phase1"
