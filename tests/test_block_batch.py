"""End-to-end config-3: a block of real attestations verified on device
through ONE batched pipeline (VERDICT r3 #4).

process_operations collapses the attestation family's signature checks into
JaxBackend.verify_indexed_batch (grouped G1 decompress+aggregate, batched
G2 decompress, batched hash_to_G2, one grouped pairing program). These
tests pin it to the sequential bignum oracle: same post-states, same
failures, under always-on BLS.
"""
from copy import deepcopy

import pytest

import bench
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.models.phase0 import block as block_mod
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root

N_KEYS = 8


@pytest.fixture(autouse=True)
def _bls_on():
    old_active, old_batching = bls.bls_active, block_mod._batching_enabled
    bls.bls_active = True
    yield
    bls.bls_active = old_active
    bls.set_backend("python")
    block_mod.set_attestation_batching(old_batching)


def _build(spec, v, n_atts):
    bls.set_backend("python")  # stage signatures with the bignum oracle
    return bench.build_config3_state_and_block(spec, v, n_atts, n_keys=N_KEYS)


def test_batched_block_matches_sequential_oracle():
    """jax-batched process_block == python-sequential on the same block."""
    spec = phase0.get_spec("minimal")
    state, block = _build(spec, 8 * spec.SLOTS_PER_EPOCH, 4)

    ref = deepcopy(state)
    bls.set_backend("python")  # no verify_indexed_batch -> sequential path
    spec.state_transition(ref, block)

    bls.set_backend("jax")
    spec.state_transition(state, block)
    assert hash_tree_root(state) == hash_tree_root(ref)
    assert len(state.previous_epoch_attestations) == 4


def test_batched_equals_forced_sequential_same_backend():
    spec = phase0.get_spec("minimal")
    state, block = _build(spec, 8 * spec.SLOTS_PER_EPOCH, 3)
    bls.set_backend("jax")

    seq = deepcopy(state)
    block_mod.set_attestation_batching(False)
    spec.state_transition(seq, deepcopy(block))
    block_mod.set_attestation_batching(True)
    spec.state_transition(state, block)
    assert hash_tree_root(state) == hash_tree_root(seq)


@pytest.mark.parametrize("backend", ["python", "jax"])
def test_invalid_signature_fails_block(backend):
    spec = phase0.get_spec("minimal")
    state, block = _build(spec, 8 * spec.SLOTS_PER_EPOCH, 3)
    # corrupt the middle attestation's signature (swap with another's)
    block.body.attestations[1].signature = block.body.attestations[2].signature
    bls.set_backend(backend)
    with pytest.raises(AssertionError):
        spec.state_transition(deepcopy(state), block)


def test_wrong_participants_fail_batched():
    """A bitfield naming a non-signer must fail the grouped check."""
    spec = phase0.get_spec("minimal")
    state, block = _build(spec, 8 * spec.SLOTS_PER_EPOCH, 3)
    att = block.body.attestations[0]
    bf = bytearray(att.aggregation_bitfield)
    bf[0] ^= 0x01  # drop one signer from the claimed set
    att.aggregation_bitfield = bytes(bf)
    bls.set_backend("jax")
    with pytest.raises(AssertionError):
        spec.state_transition(deepcopy(state), block)


def test_mainnet_preset_batched_block():
    """always_bls, mainnet preset, jax backend: the VERDICT r3 #4 gate."""
    spec = phase0.get_spec("mainnet")
    state, block = _build(spec, 4 * spec.SLOTS_PER_EPOCH, 4)
    bls.set_backend("jax")
    spec.state_transition(state, block)
    assert len(state.previous_epoch_attestations) == 4
