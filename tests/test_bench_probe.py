"""Regression tests for bench.py's accelerator probe (BENCH_r04/r05: a
wedged TPU relay hung the probe child in uninterruptible native code,
subprocess.run's unbounded post-kill wait never returned, and `make bench`
recorded rc=2 with no JSON instead of falling through to the CPU smoke
shape)."""
import os
import time

import pytest

import bench


def test_run_probe_child_kills_hung_child():
    """A child that sleeps past the timeout is SIGKILLed (whole process
    group) and reported as hung within a BOUNDED wait — not subprocess.run's
    indefinite post-kill reap."""
    t0 = time.monotonic()
    rc, out, err = bench._run_probe_child(
        "import time; time.sleep(600)", timeout_s=1)
    elapsed = time.monotonic() - t0
    assert rc is None
    assert elapsed < 30, f"reap not bounded: {elapsed:.1f}s"


def test_run_probe_child_passes_env_and_output():
    rc, out, err = bench._run_probe_child(
        "import os; print(os.environ.get('JAX_PLATFORMS', ''))",
        timeout_s=60, env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert rc == 0 and out.strip() == "cpu"


def test_probe_hang_falls_through_to_cpu_smoke(monkeypatch):
    """A simulated relay hang on the device probe must demote the run to
    the CPU smoke shape (not exit 2): the CPU re-probe runs with
    JAX_PLATFORMS=cpu pinned in the child ENV (a wedged relay can hang
    `import jax` itself, so an in-code pin is too late), and the scale
    knobs rebind so the artifact is still emitted."""
    calls = []

    def fake_child(code, timeout_s, env=None):
        calls.append(env)
        if env is None:                   # device probe: simulate the hang
            return None, "", ""
        assert env.get("JAX_PLATFORMS") == "cpu"
        assert env.get("CSTPU_BENCH_CPU") == "1"
        return 0, "cpu\n", ""

    monkeypatch.setattr(bench, "_run_probe_child", fake_child)
    monkeypatch.setattr(bench, "V_DEVICE", 1_000_000)
    monkeypatch.setattr(bench, "V_STATE", 1_000_000)
    monkeypatch.setattr(bench, "N_ATTESTATIONS", 128)
    monkeypatch.setattr(bench, "_CPU_FALLBACK", False)
    monkeypatch.setenv("CSTPU_BENCH_CPU", "")   # not the pinned-CPU mode
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")  # keep the parent pin (tests)
    bench._probe_backend(timeout_s=1)
    assert bench._CPU_FALLBACK is True
    assert bench.V_DEVICE <= 65536 and bench.V_STATE <= bench.V_DEVICE
    assert bench.N_ATTESTATIONS <= 32
    assert len(calls) == 2 and calls[0] is None and calls[1] is not None


def test_probe_require_accel_refuses_cpu_fallback(monkeypatch):
    """CSTPU_BENCH_REQUIRE_ACCEL=1: a dead accelerator must exit nonzero
    instead of demoting to the CPU smoke shape — the knob that makes
    BENCH_r03-r05-style silent fallbacks impossible for driver captures."""
    def fake_child(code, timeout_s, env=None):
        assert env is None, "must not even re-probe the CPU"
        return None, "", ""               # device probe hangs

    monkeypatch.setattr(bench, "_run_probe_child", fake_child)
    monkeypatch.setattr(bench, "_CPU_FALLBACK", False)
    monkeypatch.setenv("CSTPU_BENCH_CPU", "")
    monkeypatch.setenv("CSTPU_BENCH_REQUIRE_ACCEL", "1")
    with pytest.raises(SystemExit) as exc:
        bench._probe_backend(timeout_s=1)
    assert exc.value.code == 3
    assert bench._CPU_FALLBACK is False   # no silent demotion happened


def test_probe_cpu_unreachable_still_aborts(monkeypatch):
    """Only a dead CPU backend (nothing to fall back to) may exit 2."""
    def fake_child(code, timeout_s, env=None):
        return None, "", ""               # everything hangs

    monkeypatch.setattr(bench, "_run_probe_child", fake_child)
    monkeypatch.setenv("CSTPU_BENCH_CPU", "")
    with pytest.raises(SystemExit) as exc:
        bench._probe_backend(timeout_s=1)
    assert exc.value.code == 2
