"""Value-range tier (tools/analysis/ranges/): seeded-regression fixtures
proving each CSA14xx rule trips on a REAL traced program, the baseline
loosen/tighten/missing/suppressed workflow (mirroring
tests/test_trace_contracts.py), the committed registry's proofs, and
the doc-constant drift guard the ISSUE demands (fq.py's prose budget ==
the contract constants, so they cannot drift apart again).

The headline theorems themselves — |col| < 2^35 into fq_redc from the
narrow budget, narrow limbs back to [-16, 2^29], shuffle int32 at the
2^30 ceiling, uint64 Gwei math at 10M validators — are committed as
RANGE_CONTRACTS next to their kernels and run under `make ranges`; this
file owns the ENGINE's behavior: a deleted fq_wide_norm, a loop grown
past its invariant, an index upcast at V = 2^31 — each must fail
through the engine, and the documented accept paths must clear it.
"""
import json

import jax
import jax.numpy as jnp

from consensus_specs_tpu.ops import fq as F
from consensus_specs_tpu.ops import fq_tower as T
from tools.analysis.ranges import engine
from tools.analysis.ranges import interp as P
from tools.analysis.ranges import interval as I


def _contract(tmp_path, name="fixture.contract", **kw):
    """A synthetic contract anchored in a real tmp file (so inline
    suppressions work exactly like a kernel module's)."""
    path = tmp_path / "kernel_fixture.py"
    if not path.exists():
        path.write_text(f'RANGE_CONTRACTS = [{{"name": "{name}"}}]\n')
    c = dict(name=name, path=str(path),
             line=engine._name_line(path.read_text(), name))
    c.update(kw)
    return c


def _rules(report):
    return sorted(f.rule for f in report.findings)


NARROW = {"lo": -F.NARROW_INPUT_BOUND, "hi": F.NARROW_INPUT_BOUND,
          "top_lo": -F.NARROW_TOP_SPILL, "top_hi": F.NARROW_TOP_SPILL}


def _z12():
    return jnp.zeros((2, 12, F.L), jnp.int64)


# ---------------------------------------------------------------------------
# CSA1401: proved overflow / failed output bound
# ---------------------------------------------------------------------------

def test_deleted_wide_norm_trips_overflow(tmp_path):
    """THE seeded regression the tier exists for: drop the interposed
    fq_wide_norm from the gamma recombination and the raw schoolbook
    columns (14*2^58 each) provably wrap int64 in the >2-term sum —
    CSA1401, caught before any silent pairing corruption."""
    def no_norm(av, bv):
        A = T._apply_int_matrix(T._ALPHA, av)
        Bv = T._apply_int_matrix(T._BETA, bv)
        Pw = F.fq_mul_wide(A, Bv)          # raw columns: no fq_wide_norm
        return T._apply_int_matrix(T._GAMMA, Pw)

    c = _contract(
        tmp_path,
        build=lambda: dict(fn=no_norm, args=(_z12(), _z12()),
                           ranges=(NARROW, NARROW)))
    report = engine.run_contracts([c], baseline={})
    assert "CSA1401" in _rules(report)
    assert any("int64" in f.message and "wrap" in f.message
               for f in report.findings if f.rule == "CSA1401")


def test_declared_output_bound_failure_trips(tmp_path):
    """A bound the interpreter cannot prove (fq_mul_wide columns pinned
    to the REDC budget 2^35 instead of the raw 14*2^58) is CSA1401 with
    the proven interval in the message."""
    c = _contract(
        tmp_path,
        build=lambda: dict(fn=F.fq_mul_wide,
                           args=(jnp.zeros((2, F.L), jnp.int64),) * 2,
                           ranges=(NARROW, NARROW)),
        output={"lo": -F.WIDE_COL_BUDGET, "hi": F.WIDE_COL_BUDGET})
    report = engine.run_contracts([c], baseline={})
    assert "CSA1401" in _rules(report)
    assert any("escapes the declared bound" in f.message
               for f in report.findings)


def test_index_upcast_at_2_31_trips(tmp_path):
    """Upcasting a validator index to int32 at V = 2^31 provably wraps
    the convert — the dtype-pinning regression for the shuffle/epoch
    index columns."""
    def narrows(idx):
        return idx.astype(jnp.int32)

    c = _contract(
        tmp_path,
        build=lambda: dict(
            fn=narrows,
            args=(jax.ShapeDtypeStruct(((1 << 31),), jnp.int64),),
            ranges=({"lo": 0, "hi": (1 << 31)},)))
    report = engine.run_contracts([c], baseline={})
    assert "CSA1401" in _rules(report)
    # at V = 2^31 - 1 the same cast is fine: the ceiling is sharp
    c2 = _contract(
        tmp_path, name="fixture.fits",
        build=lambda: dict(
            fn=narrows,
            args=(jax.ShapeDtypeStruct(((1 << 16),), jnp.int64),),
            ranges=({"lo": 0, "hi": (1 << 31) - 1},)))
    report2 = engine.run_contracts([c2], baseline={})
    assert "CSA1401" not in _rules(report2)


def test_intentional_wrap_declaration_is_not_flagged(tmp_path):
    """The sha256 posture: uint32 modular arithmetic declared wrap_ok
    passes; the identical program without the declaration fails."""
    def mod32(x):
        return x + jnp.uint32(0xFFFFFFFF)

    build = lambda: dict(fn=mod32, args=(jnp.zeros(4, jnp.uint32),),
                         ranges=({"lo": 0, "hi": (1 << 32) - 1},))
    flagged = engine.run_contracts(
        [_contract(tmp_path, build=build)], baseline={})
    assert "CSA1401" in _rules(flagged)
    declared = engine.run_contracts(
        [_contract(tmp_path, name="fixture.mod32", build=build,
                   wrap_ok=("uint32",))], baseline={})
    assert "CSA1401" not in _rules(declared)


# ---------------------------------------------------------------------------
# CSA1402/1403: unprovable ops and loop invariants
# ---------------------------------------------------------------------------

def test_unmodeled_op_widens_with_notice(tmp_path):
    """An op the interpreter has no handler for degrades the proof
    visibly (CSA1402 notice), never silently."""
    def odd(x):
        return jnp.prod(x)         # reduce_prod: deliberately unmodeled

    c = _contract(tmp_path,
                  build=lambda: dict(fn=odd, args=(jnp.ones(4, jnp.int64),),
                                     ranges=({"lo": 0, "hi": 7},)))
    report = engine.run_contracts([c], baseline={})
    assert "CSA1402" in _rules(report)


def test_long_loop_without_invariant_trips_missing(tmp_path):
    """A fori_loop past the unroll window whose carry is not a
    closed-form counter and has no declared invariant is CSA1403 — the
    carries widen to the dtype range instead of passing vacuously."""
    def long_loop(x):
        return jax.lax.fori_loop(0, 4096, lambda i, a: a + a, x)

    c = _contract(tmp_path,
                  build=lambda: dict(fn=long_loop,
                                     args=(jnp.int64(1),),
                                     ranges=({"lo": 0, "hi": 1},)))
    report = engine.run_contracts([c], baseline={})
    assert "CSA1403" in _rules(report)


def test_counter_accumulator_proves_in_closed_form(tmp_path):
    """A pure `carry + const` accumulator (what fori indices lower to)
    needs no invariant at any trip count: its image is closed-form."""
    def accumulating(n):
        return jax.lax.fori_loop(
            0, n, lambda i, a: a + jnp.int64(1 << 29), jnp.int64(0))

    big = _contract(
        tmp_path,
        build=lambda: dict(fn=lambda x: accumulating(100_000) + x,
                           args=(jnp.int64(0),),
                           ranges=({"lo": 0, "hi": 0},)),
        output={"lo": 0, "hi": 100_000 << 29})
    report = engine.run_contracts([big], baseline={})
    assert "CSA1401" not in _rules(report)
    assert "CSA1403" not in _rules(report)


def test_counter_final_value_covered(tmp_path):
    """Soundness pin (review finding): the closed-form counter bound
    must cover the carry OUT of the final iteration (init + length*step),
    not just the body-input values — an output pinned one step short
    must FAIL, the true bound must prove."""
    def count(x):
        return jax.lax.fori_loop(200, 400, lambda i, a: a + 1, x) \
            + jax.lax.fori_loop(0, 400, lambda i, a: a - 1, x)

    tight = _contract(
        tmp_path,
        build=lambda: dict(fn=count, args=(jnp.int64(0),),
                           ranges=({"lo": 0, "hi": 0},)),
        output={"lo": -400, "hi": 199})         # one step short
    assert any("escapes the declared bound" in f.message
               for f in engine.run_contracts([tight], baseline={}).findings)
    true = _contract(
        tmp_path, name="fixture.true",
        build=lambda: dict(fn=count, args=(jnp.int64(0),),
                           ranges=({"lo": 0, "hi": 0},)),
        output={"lo": -400, "hi": 200})
    report = engine.run_contracts([true], baseline={})
    assert not any("escapes" in f.message for f in report.findings)


def test_collapsed_output_checks_body_bound(tmp_path):
    """Soundness pin (review finding): an output that lost positional
    tracking (sort on the trailing axis) must still be held to the
    declared BODY bound — strictly, never vacuously against the looser
    top bound."""
    c = _contract(
        tmp_path,
        build=lambda: dict(fn=lambda a: jnp.sort(a, axis=-1),
                           args=(jnp.zeros((2, F.L), jnp.int64),),
                           ranges=({"lo": 0, "hi": 1 << 38},)),
        output={"lo": -16, "hi": 1 << 29,
                "top_lo": -(1 << 39), "top_hi": 1 << 39})
    report = engine.run_contracts([c], baseline={})
    assert any("escapes the declared bound" in f.message
               for f in report.findings)


def test_contract_names_anchor_exactly():
    """Review finding: "ops.fq.fq_mul" must anchor at its own contract
    line, not the earlier "ops.fq.fq_mul_wide" substring match."""
    src = F.__file__
    lines = open(src).read().splitlines()
    line = engine._name_line(open(src).read(), "ops.fq.fq_mul")
    assert '"ops.fq.fq_mul"' in lines[line - 1]


def test_trip_count_past_invariant_trips_proved_overflow(tmp_path):
    """The ISSUE's seeded regression: a loop that proves by exact
    unrolling at a short trip count fails by induction when the trip
    count grows past what its declared invariant covers — the
    doubling body escapes the invariant (CSA1401)."""
    def doubling(n):
        return jax.lax.fori_loop(0, n, lambda i, a: a + a, jnp.int64(1))

    short = _contract(
        tmp_path,
        build=lambda: dict(fn=lambda x: doubling(8) + x,
                           args=(jnp.int64(0),),
                           ranges=({"lo": 0, "hi": 0},)),
        output={"lo": 0, "hi": 1 << 8})
    ok = engine.run_contracts([short], baseline={})
    assert "CSA1401" not in _rules(ok) and "CSA1403" not in _rules(ok)

    widened = _contract(
        tmp_path, name="fixture.widened",
        build=lambda: dict(fn=lambda x: doubling(100_000) + x,
                           args=(jnp.int64(0),),
                           ranges=({"lo": 0, "hi": 0},)),
        invariants=[[None, {"lo": 0, "hi": 1 << 8}]],
        output={"lo": 0, "hi": 1 << 8})
    bad = engine.run_contracts([widened], baseline={})
    assert "CSA1401" in _rules(bad)
    assert any("invariant" in f.message for f in bad.findings)


def test_inductive_invariant_proves_long_loop(tmp_path):
    """The accept path for big loops: a genuinely inductive invariant
    (a clamped carry) closes the proof at any trip count."""
    def clamped(x):
        def body(i, a):
            return jnp.minimum(a + a + 1, jnp.int64(100))
        return jax.lax.fori_loop(0, 1_000_000, body, x)

    c = _contract(
        tmp_path,
        build=lambda: dict(fn=clamped, args=(jnp.int64(0),),
                           ranges=({"lo": 0, "hi": 0},)),
        invariants=[[None, {"lo": 0, "hi": 100}]],
        output={"lo": 0, "hi": 100})
    report = engine.run_contracts([c], baseline={})
    assert "CSA1401" not in _rules(report)
    assert "CSA1403" not in _rules(report)


# ---------------------------------------------------------------------------
# CSA1404: the baseline ratchet (loosen/tighten/missing/suppressed)
# ---------------------------------------------------------------------------

def _simple(tmp_path, name="fixture.contract", hi=100):
    return _contract(
        tmp_path, name=name,
        build=lambda: dict(fn=lambda x: x * 2,
                           args=(jnp.zeros(4, jnp.int64),),
                           ranges=({"lo": 0, "hi": hi},)))


def test_missing_baseline_entry_trips(tmp_path):
    report = engine.run_contracts([_simple(tmp_path)], baseline={})
    assert _rules(report) == ["CSA1404"] * 3      # out_lo / out_hi / widened


def test_regression_vs_baseline_trips_and_loosening_clears(tmp_path):
    base = {"fixture.contract": {"out_lo": 0, "out_hi": 100,
                                 "widened": 0}}
    dirty = engine.run_contracts([_simple(tmp_path, hi=200)], baseline=base)
    assert _rules(dirty) == ["CSA1404"]
    assert "regressed" in dirty.findings[0].message
    # the accept path: a reviewed baseline edit to the proven value
    loosened = engine.run_contracts(
        [_simple(tmp_path, hi=200)],
        baseline={"fixture.contract": {"out_lo": 0, "out_hi": 400,
                                       "widened": 0}})
    assert loosened.findings == []
    # improvement below the committed snapshot: a tighten notice
    slack = engine.run_contracts(
        [_simple(tmp_path, hi=200)],
        baseline={"fixture.contract": {"out_lo": 0, "out_hi": 800,
                                       "widened": 0}})
    assert slack.findings == []
    assert any("tightened" in n for n in slack.notices)


def test_suppression_on_contract_line(tmp_path):
    path = tmp_path / "kernel_fixture.py"
    path.write_text(
        'RANGE_CONTRACTS = [\n'
        '    # csa: ignore[CSA1404] -- fixture: snapshot intentionally absent\n'
        '    {"name": "fixture.contract"},\n'
        ']\n')
    report = engine.run_contracts([_simple(tmp_path)], baseline={})
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["CSA1404"] * 3


def test_stale_baseline_contract_reported(tmp_path):
    base = {"fixture.contract": {"out_lo": 0, "out_hi": 8, "widened": 0},
            "deleted.contract": {"out_hi": 1}}
    report = engine.run_contracts([_simple(tmp_path, hi=4)], baseline=base)
    assert report.stale_baseline == ["deleted.contract"]


def test_baseline_roundtrip_and_json(tmp_path):
    report = engine.run_contracts([_simple(tmp_path)], baseline={})
    path = tmp_path / "ranges_baseline.json"
    engine.write_ranges_baseline(path, report.snapshot)
    loaded = engine.load_ranges_baseline(path)
    assert loaded == report.snapshot
    again = engine.run_contracts([_simple(tmp_path)], baseline=loaded)
    assert again.findings == []
    data = json.loads(engine.render_json(report))
    assert data["contracts"][0]["name"] == "fixture.contract"
    assert data["contracts"][0]["measured"]["out_hi"] == 200


def test_broken_contract_is_a_finding_not_a_crash(tmp_path):
    c = _contract(tmp_path,
                  build=lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    report = engine.run_contracts([c], baseline={})
    assert "CSA1401" in _rules(report)
    assert report.results[0].skipped


# ---------------------------------------------------------------------------
# The committed registry and its theorems
# ---------------------------------------------------------------------------

def test_committed_registry_proves_clean():
    """`make ranges` in miniature: every committed RANGE_CONTRACT proves
    against the committed baseline with zero actionable findings — the
    acceptance bar (>= 10 contracts over fq / fq_tower / scalar_mul /
    sha256 / shuffle / epoch_soa, wide budget proven not asserted)."""
    contracts = engine.discover()
    assert len(contracts) >= 10
    names = [c["name"] for c in contracts]
    for needle in ("ops.fq.", "ops.fq_tower.", "ops.scalar_mul.",
                   "ops.sha256.", "ops.shuffle.",
                   "models.phase0.epoch_soa."):
        assert any(n.startswith(needle) for n in names), needle
    report = engine.run_contracts(contracts)
    assert report.findings == [], [
        f"{f.path}:{f.line} {f.rule} {f.message}" for f in report.findings]
    assert report.stale_baseline == []
    # the FAR-sentinel add is the one declared (inline-suppressed) wrap
    assert [f.rule for f in report.suppressed] == ["CSA1401"]


def test_wide_budget_is_proven_not_asserted():
    """The acceptance criterion verbatim: the |col| < 2^35 REDC input
    budget is derived by the interpreter from the narrow input budget —
    check the gamma contract's proven hull actually sits under the
    declared 2^35, with real slack (i.e. a nontrivial proof, not a pin
    at the boundary)."""
    contracts = [c for c in engine.discover()
                 if c["name"] == "ops.fq_tower.fq12_mul.redc_cols[coeff]"]
    assert len(contracts) == 1
    res, events, failures = engine._measure(contracts[0])
    assert failures == [] and not [e for e in events
                                   if e.rule == "CSA1401"]
    body_cols = [iv for out in res.outputs
                 for iv in (out["vec"][:-1] if out["vec"] else [])]
    assert body_cols, "gamma output lost positional tracking"
    worst = max(abs(lo) if abs(lo) > hi else hi for lo, hi in body_cols)
    assert worst < F.WIDE_COL_BUDGET
    assert worst > F.WIDE_COL_BUDGET // 8      # nontrivial: real content


def test_doc_constants_match_contract_constants():
    """The fq.py docstring's budget numbers are the exported constants
    the contracts declare — asserted so prose and prover cannot drift
    (the pre-PR state: hand-derived 2^35 / [-1, 2^29] prose nothing
    checked)."""
    doc = F.__doc__
    assert F.WIDE_COL_BUDGET == F.WIDE_ACCUM_FANIN << F.B == 1 << 35
    assert F.WIDE_COL_RAW == F.L << (2 * F.B) == 14 << 58
    assert F.NARROW_LIMB_HI == 1 << 29
    assert F.CANONICAL_TOP == F.Q >> (F.B * (F.L - 1))
    for token in ("NARROW_INPUT_BOUND = 2^32", "NARROW_TOP_SPILL = 2^16",
                  "WIDE_COL_RAW = 14*2^58", "[-16, 2^29]",
                  "WIDE_ACCUM_FANIN * 2^29 = 2^35",
                  "WIDE_TOP_SPILL = 2^38"):
        assert token in doc, f"fq.py docstring lost budget token {token!r}"
    # the tower's fan-in ceiling is the same constant, not a re-derived 64
    import inspect
    assert "F.WIDE_ACCUM_FANIN" in inspect.getsource(T._check_budget)
    # and the redc docstring still carries the proving pointer
    assert "2^35" in F.fq_redc.__doc__


def test_narrow_norm_proof_matches_docstring_interval():
    """The machine-proven post-norm body interval IS the documented
    [NARROW_LIMB_LO, NARROW_LIMB_HI]: prove fq_mul's committed contract
    and compare the body hull directly."""
    contracts = [c for c in engine.discover() if c["name"] == "ops.fq.fq_mul"]
    res, events, failures = engine._measure(contracts[0])
    assert failures == []
    (out,) = res.outputs
    body = out["vec"][:-1]
    lo = min(l for l, _ in body)
    hi = max(h for _, h in body)
    assert F.NARROW_LIMB_LO <= lo and hi <= F.NARROW_LIMB_HI
    assert hi == F.NARROW_LIMB_HI          # the 2^29 ceiling is tight


def test_rules_registered_without_jax_tier():
    """--list-rules must span all three tiers on the no-jax lint lane."""
    from tools.analysis.core import RULES
    from tools.analysis.ranges import RANGE_RULE_IDS
    assert set(RANGE_RULE_IDS) <= set(RULES)
    assert RULES["CSA1402"].severity == "notice"
    for rule_id in ("CSA1401", "CSA1403", "CSA1404"):
        assert RULES[rule_id].severity == "error"


def test_csa901_defers_to_range_contracts(tmp_path):
    """The demoted pre-check: an accumulation inside a function the
    module's RANGE_CONTRACTS section references is NOT double-reported
    by CSA901 (the proving tier owns it); the same code without a
    contract still gets the syntactic notice."""
    from tools.analysis.core import analyze_paths
    body = (
        "def hot(a, b):\n"
        "    w = fq_mul_wide(a, b)\n"
        "    return w + w + w\n")
    bare = tmp_path / "bare.py"
    bare.write_text(body)
    covered = tmp_path / "covered.py"
    covered.write_text(body + "\nRANGE_CONTRACTS = [dict(name='x.hot', "
                       "build=lambda: dict(fn=hot))]\n")
    assert [f.rule for f in analyze_paths([str(bare)]).findings] == ["CSA901"]
    assert analyze_paths([str(covered)]).findings == []


# ---------------------------------------------------------------------------
# Interpreter internals worth pinning
# ---------------------------------------------------------------------------

def test_carry_rounds_summary_matches_concrete():
    """The jitted _carry_rounds summary is the exact positional
    transfer: drive random in-budget arrays through the CONCRETE kernel
    and check every limb lands inside the summary's proven interval."""
    import numpy as np
    rng = np.random.default_rng(7)
    arr = rng.integers(-(1 << 32), 1 << 32, size=(64, F.L))
    arr[:, -1] = rng.integers(-(1 << 16), 1 << 16, size=64)
    out = np.asarray(F._carry_rounds(jnp.asarray(arr), 3))

    with F.staged_helpers():
        closed = jax.make_jaxpr(lambda t: F._carry_rounds(t, 3))(
            jnp.zeros((2, F.L), jnp.int64))
    vals = [P.for_aval(closed.jaxpr.invars[0].aval,
                       {"lo": -(1 << 32), "hi": 1 << 32,
                        "top_lo": -(1 << 16), "top_hi": 1 << 16})]
    it = P.Interp()
    (res,) = it.run(closed, vals)
    assert it.events == []
    for pos in range(F.L):
        lo, hi = res.vec[pos].lo, res.vec[pos].hi
        assert lo <= int(out[:, pos].min()) and int(out[:, pos].max()) <= hi


def test_interval_arithmetic_exactness():
    a = I.Interval(-3, 5)
    b = I.Interval(2, 4)
    assert I.mul(a, b) == I.Interval(-12, 20)
    assert I.floordiv(I.Interval(-7, 7), I.Interval(2, 2)).lo == -4
    assert I.ashr(I.Interval(-8, 8), I.iv(1)) == I.Interval(-4, 4)
    assert I.and_(I.Interval(-100, 100), I.Interval(0, 15)) == \
        I.Interval(0, 15)
    assert I.isqrt(I.Interval(0, 17)) == I.Interval(0, 4)
    assert I.scale(I.Interval(1, 3), 10) == I.Interval(1, 30)
