"""Honest-validator duties: assignments, proposals, attesting, protection.

Contract: /root/reference specs/validator/0_beacon-chain-validator.md
(:133-158 assignments, :182-276 proposal construction, :278-361
attestation construction, :363-389 slashing protection).
"""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.models.phase0.validator import SlashingProtection
from consensus_specs_tpu.testing import factories as f
from consensus_specs_tpu.testing.keys import privkeys
from consensus_specs_tpu.utils.ssz.impl import signing_root


@pytest.fixture(scope="module")
def spec():
    return phase0.get_spec("minimal")


@pytest.fixture(autouse=True)
def _bls_off():
    old = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = old


@pytest.fixture()
def state(spec):
    return f.seed_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)


def test_every_active_validator_has_an_assignment(spec, state):
    epoch = spec.get_current_epoch(state)
    seen_slots = set()
    for index in spec.get_active_validator_indices(state, epoch):
        assignment = spec.get_committee_assignment(state, epoch, index)
        assert assignment is not None
        committee, shard, slot = assignment
        assert index in committee
        assert spec.get_epoch_start_slot(epoch) <= slot \
            < spec.get_epoch_start_slot(epoch) + spec.SLOTS_PER_EPOCH
        assert committee == spec.get_crosslink_committee(state, epoch, shard)
        seen_slots.add(slot)
    assert len(seen_slots) >= 1


def test_next_epoch_assignment_allowed_future_rejected(spec, state):
    epoch = spec.get_current_epoch(state)
    assert spec.get_committee_assignment(state, epoch + 1, 0) is not None
    with pytest.raises(AssertionError):
        spec.get_committee_assignment(state, epoch + 2, 0)


def test_exactly_one_proposer_per_slot(spec, state):
    f.advance_slots(spec, state)
    epoch = spec.get_current_epoch(state)
    active = spec.get_active_validator_indices(state, epoch)
    proposers = [i for i in active if spec.is_proposer(state, i)]
    assert len(proposers) == 1


def test_build_proposal_transitions_cleanly(spec, state):
    f.advance_slots(spec, state)
    proposer = spec.get_beacon_proposer_index(state)
    parent_root = signing_root(state.latest_block_header) \
        if state.latest_block_header.state_root != spec.ZERO_HASH \
        else f.empty_block(spec, state).parent_root
    block = spec.build_proposal(state, state.slot, parent_root,
                                privkeys[proposer])
    spec.state_transition(state, block)
    assert state.slot == block.slot


def test_attestation_duty_is_processable(spec, state):
    state.slot = spec.SLOTS_PER_EPOCH  # off the genesis boundary
    epoch = spec.get_current_epoch(state)
    index = spec.get_active_validator_indices(state, epoch)[0]
    committee, shard, slot = spec.get_committee_assignment(state, epoch, index)

    spec.process_slots(state, slot) if slot > state.slot else None
    head_root = f.empty_block_next(spec, state).parent_root
    att = spec.build_attestation_duty(
        state, head_root, committee, shard, index, privkeys[index])

    # single-bit participation, as the guide requires
    attesters = spec.get_attesting_indices(state, att.data, att.aggregation_bitfield)
    assert attesters == [index]

    # and the produced attestation passes process_attestation
    state.slot = max(state.slot, slot) + spec.MIN_ATTESTATION_INCLUSION_DELAY
    spec.process_attestation(state, att)


def test_eth1_vote_majority(spec, state):
    a = spec.Eth1Data(deposit_root=b"\x01" * 32, deposit_count=1, block_hash=b"\x02" * 32)
    b = spec.Eth1Data(deposit_root=b"\x03" * 32, deposit_count=2, block_hash=b"\x04" * 32)
    state.eth1_data_votes = [a, b, b]
    assert spec.get_eth1_vote(state) == b
    state.eth1_data_votes = []
    assert spec.get_eth1_vote(state) == state.latest_eth1_data
    assert spec.get_eth1_vote(state, known_eth1_data=a) == a


def test_slashing_protection_blocks_double_proposal():
    db = SlashingProtection()
    db.record_proposal(5, 100)
    assert not db.may_propose(5, 100)
    assert db.may_propose(5, 101)
    assert db.may_propose(6, 100)


def test_slashing_protection_blocks_double_and_surround_votes():
    db = SlashingProtection()
    db.record_attestation(1, source_epoch=2, target_epoch=4)
    assert not db.may_attest(1, 3, 4)     # double vote at target 4
    assert not db.may_attest(1, 1, 5)     # would surround (1,5) around (2,4)
    assert not db.may_attest(1, 3, 3.5)   # hypothetical inner: surrounded
    assert db.may_attest(1, 4, 5)         # clean successive vote
    assert db.may_attest(2, 2, 4)         # other validator unaffected
