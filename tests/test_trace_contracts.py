"""Trace-tier contract engine (tools/analysis/trace/): seeded-regression
fixtures proving each rule family trips on a REAL traced/lowered
program, plus the ratchet workflow (baseline loosening/tightening,
suppression, staleness, skip) and the committed registry's hygiene.

The op-count assertions for the committed kernel contracts live with
their kernels' tests (tests/test_fq_redc.py asserts the fq_tower/
bls_jax lane pins through the engine, tests/test_scalar_mul.py the
windowed chain); this file owns the ENGINE's behavior: a kernel variant
with one extra REDC lane, a program that silently upcasts to f64, a
chained pair whose lowered shardings disagree — each must fail the
ratchet, and the documented accept paths must clear it.
"""
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensus_specs_tpu.ops import fq as F
from consensus_specs_tpu.ops import fq_tower as T
from tools.analysis.trace import engine


def _contract(tmp_path, name="fixture.contract", **kw):
    """A synthetic contract anchored in a real tmp file (so inline
    suppressions work exactly like a kernel module's)."""
    path = tmp_path / "kernel_fixture.py"
    if not path.exists():
        path.write_text(f'TRACE_CONTRACTS = [{{"name": "{name}"}}]\n')
    c = dict(name=name, path=str(path),
             line=engine._name_line(path.read_text(), name))
    c.update(kw)
    return c


def _rules(report):
    return sorted(f.rule for f in report.findings)


def _z2():
    return jnp.zeros((2, F.L), jnp.int64)


def _fq2_mul_plus_one_redc(a, b):
    """The seeded regression: fq2_mul (2 REDC lanes under coeff) plus ONE
    gratuitous extra reduction."""
    out = T.fq2_mul(a, b)
    return out + F.fq_mul(a[..., 0, :], b[..., 0, :])[..., None, :]


def _coeff_ctx():
    return F.pinned_fq_redc_backend("coeff")


# ---------------------------------------------------------------------------
# CSA11xx: op-budget ratchet
# ---------------------------------------------------------------------------

def test_extra_redc_lane_trips_budget(tmp_path):
    """+1 REDC lane over an exact pin fails CSA1101 — and the message
    names the measured/declared values."""
    c = _contract(
        tmp_path,
        build=lambda: dict(fn=_fq2_mul_plus_one_redc, args=(_z2(), _z2()),
                           context=_coeff_ctx),
        budgets={"redc_lanes": 2}, exact=("redc_lanes",))
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1101"]
    assert "3" in report.findings[0].message
    assert report.results[0].measured["redc_lanes"] == 3


def test_regression_vs_baseline_trips_even_within_budget(tmp_path):
    """A non-exact metric inside its budget but above the committed
    snapshot is CSA1102: loosening requires touching the baseline."""
    c = _contract(
        tmp_path,
        build=lambda: dict(fn=_fq2_mul_plus_one_redc, args=(_z2(), _z2()),
                           context=_coeff_ctx),
        budgets={"redc_lanes": 10})
    dirty = engine.run_contracts(
        [c], baseline={"fixture.contract": {"redc_lanes": 2}})
    assert _rules(dirty) == ["CSA1102"]
    # the accept path: a reviewed baseline edit to the measured value
    loosened = engine.run_contracts(
        [c], baseline={"fixture.contract": {"redc_lanes": 3}})
    assert loosened.findings == []
    # improvement below baseline: a tighten notice, never a failure
    slack = engine.run_contracts(
        [c], baseline={"fixture.contract": {"redc_lanes": 7}})
    assert slack.findings == []
    assert any("improved 7 -> 3" in n for n in slack.notices)


def test_missing_baseline_entry_trips(tmp_path):
    c = _contract(
        tmp_path,
        build=lambda: dict(fn=T.fq2_mul, args=(_z2(), _z2()),
                           context=_coeff_ctx),
        budgets={"redc_lanes": 10})
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1104"]


def test_suppression_on_contract_line(tmp_path):
    """# csa: ignore[...] on the contract's "name": line downgrades the
    finding to suppressed, exactly like the AST tier."""
    path = tmp_path / "kernel_fixture.py"
    path.write_text(
        'TRACE_CONTRACTS = [\n'
        '    # csa: ignore[CSA1101] -- seeded fixture, lane cost accepted\n'
        '    {"name": "fixture.contract"},\n'
        ']\n')
    c = _contract(
        tmp_path,
        build=lambda: dict(fn=_fq2_mul_plus_one_redc, args=(_z2(), _z2()),
                           context=_coeff_ctx),
        budgets={"redc_lanes": 2}, exact=("redc_lanes",))
    report = engine.run_contracts([c], baseline={})
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["CSA1101"]


def test_unmeasured_budget_metric_is_a_finding(tmp_path):
    c = _contract(tmp_path, build=lambda: dict(fn=lambda x: x + 1,
                                               args=(jnp.zeros(3),)),
                  budgets={"bogus_metric": 1})
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1101"]
    assert "never measured" in report.findings[0].message


# ---------------------------------------------------------------------------
# CSA12xx: lowered-program hygiene
# ---------------------------------------------------------------------------

def test_silent_f64_upcast_trips(tmp_path):
    def upcasts(x):
        # the classic: a float literal promotes the math through f64
        return (x.astype(jnp.float64) * 1.5).astype(jnp.int64)

    c = _contract(tmp_path,
                  build=lambda: dict(fn=upcasts, args=(jnp.zeros(
                      4, jnp.int64),)),
                  forbid=("f64",))
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1201"]


def test_host_callback_trips(tmp_path):
    def chatty(x):
        jax.debug.callback(lambda v: None, x)
        return x * 2

    c = _contract(tmp_path,
                  build=lambda: dict(fn=chatty, args=(jnp.zeros(3),)),
                  forbid=("callback",))
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1202"]


def test_targeted_device_put_trips_and_constant_staging_does_not(tmp_path):
    def forces_placement(x):
        return jax.device_put(x * 2, jax.devices()[0])

    c = _contract(tmp_path,
                  build=lambda: dict(fn=forces_placement,
                                     args=(jnp.zeros(3),)),
                  forbid=("device_put",))
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1203"]

    def stages_constants(x):
        # jnp.asarray over host tables is the legitimate constant path
        return x + jnp.asarray(np.arange(3, dtype=np.float32))

    c2 = _contract(tmp_path, name="fixture.clean",
                   build=lambda: dict(fn=stages_constants,
                                      args=(jnp.zeros(3),)),
                   forbid=("device_put",))
    assert engine.run_contracts([c2], baseline={}).findings == []


def test_dropped_donation_trips(tmp_path):
    def f(a, b):
        return a + b

    args = (jnp.zeros(8), jnp.zeros(8))
    c = _contract(tmp_path,
                  build=lambda: dict(fn=f, args=args, jit_kwargs={}),
                  donate_min=1)
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1204"]
    # with the donation actually declared, the annotation survives
    c2 = _contract(tmp_path, name="fixture.donated",
                   build=lambda: dict(
                       fn=f, args=args,
                       jit_kwargs=dict(donate_argnums=(0,))),
                   donate_min=1)
    assert engine.run_contracts([c2], baseline={}).findings == []


# ---------------------------------------------------------------------------
# CSA13xx: collective / chained-layout drift (8-device virtual mesh)
# ---------------------------------------------------------------------------

N_DEV = 8


def _mesh_or_skip():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices, have {len(jax.devices())}")
    from jax.sharding import Mesh
    return Mesh(np.asarray(jax.devices()[:N_DEV]), ("v",))


def test_chained_sharding_mismatch_trips(tmp_path):
    """A self-chained step whose out sharding differs from its in
    sharding re-lays data out every call — CSA1302, the static form of
    the re-layout watchdog."""
    mesh = _mesh_or_skip()
    from jax.sharding import NamedSharding, PartitionSpec as P
    shard, repl = NamedSharding(mesh, P("v")), NamedSharding(mesh, P())

    def step(x):
        return x * 2

    mismatched = _contract(
        tmp_path,
        build=lambda: dict(fn=step, args=(jnp.zeros(16),),
                           jit_kwargs=dict(in_shardings=(repl,),
                                           out_shardings=shard)),
        chained_prefix=1)
    report = engine.run_contracts([mismatched], baseline={})
    assert _rules(report) == ["CSA1302"]

    matched = _contract(
        tmp_path, name="fixture.stable",
        build=lambda: dict(fn=step, args=(jnp.zeros(16),),
                           jit_kwargs=dict(in_shardings=(shard,),
                                           out_shardings=shard)),
        chained_prefix=1)
    assert engine.run_contracts([matched], baseline={}).findings == []


def test_collective_inventory_drift_trips(tmp_path):
    mesh = _mesh_or_skip()
    from jax.sharding import NamedSharding, PartitionSpec as P
    shard, repl = NamedSharding(mesh, P("v")), NamedSharding(mesh, P())

    def reduces(x):
        return jnp.sum(x)

    c = _contract(
        tmp_path,
        build=lambda: dict(fn=reduces, args=(jnp.zeros(16),),
                           jit_kwargs=dict(in_shardings=(shard,),
                                           out_shardings=repl)),
        collectives=("all-gather",))     # declared wrong: it all-reduces
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1301"]
    assert "all-reduce" in report.findings[0].message

    c2 = _contract(
        tmp_path, name="fixture.reduce",
        build=lambda: dict(fn=reduces, args=(jnp.zeros(16),),
                           jit_kwargs=dict(in_shardings=(shard,),
                                           out_shardings=repl)),
        collectives=("all-reduce",))
    assert engine.run_contracts([c2], baseline={}).findings == []


def test_unannotated_chain_degrades_loudly_not_vacuously(tmp_path):
    """A chained_prefix check over a program whose lowered signature
    carries NO sharding annotations (partitioner/dialect change) must
    fail, not pass vacuously — the silent-degradation mode the tier
    exists to prevent."""
    c = _contract(
        tmp_path,
        build=lambda: dict(fn=lambda x: x * 2, args=(jnp.zeros(16),),
                           jit_kwargs={}),    # no shardings at all
        chained_prefix=1)
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1302"]
    assert "vacuously" in report.findings[0].message


def test_bare_int_static_argnums_normalized(tmp_path):
    """`static_argnums=0` (a falsy bare int, valid for jax.jit) must be
    honored when building the measurement jaxpr."""
    def f(n, x):
        return x + n   # n is a static python int under jit

    c = _contract(tmp_path,
                  build=lambda: dict(fn=f, args=(3, jnp.zeros(4)),
                                     jit_kwargs=dict(static_argnums=0)),
                  budgets={"jaxpr_eqns": 10})
    report = engine.run_contracts(
        [c], baseline={"fixture.contract": {"jaxpr_eqns": 10}})
    assert report.findings == [], [f.message for f in report.findings]
    assert report.results[0].measured["jaxpr_eqns"] >= 1


# ---------------------------------------------------------------------------
# Engine plumbing: skip, staleness, baseline IO, snapshot
# ---------------------------------------------------------------------------

def test_underprovisioned_contract_skips_with_notice(tmp_path):
    c = _contract(tmp_path, requires_devices=4096,
                  build=lambda: dict(fn=lambda x: x, args=(jnp.zeros(2),)),
                  budgets={"jaxpr_eqns": 10})
    report = engine.run_contracts(
        [c], baseline={"fixture.contract": {"jaxpr_eqns": 3}})
    assert report.findings == []
    assert any("skipped" in n for n in report.notices)
    # the skipped contract's baseline entry is unverifiable, NOT stale
    assert report.stale_baseline == []


def test_stale_baseline_contract_reported(tmp_path):
    c = _contract(tmp_path,
                  build=lambda: dict(fn=lambda x: x + 1,
                                     args=(jnp.zeros(2),)),
                  budgets={"jaxpr_eqns": 10})
    report = engine.run_contracts(
        [c], baseline={"fixture.contract": {"jaxpr_eqns": 5},
                       "deleted.contract": {"redc_lanes": 1}})
    assert report.stale_baseline == ["deleted.contract"]


def test_baseline_roundtrip_and_snapshot(tmp_path):
    c = _contract(tmp_path,
                  build=lambda: dict(fn=lambda x: x + 1,
                                     args=(jnp.zeros(2),)),
                  budgets={"jaxpr_eqns": 10})
    report = engine.run_contracts([c], baseline={})
    assert _rules(report) == ["CSA1104"]          # unsnapshotted
    path = tmp_path / "trace_baseline.json"
    engine.write_trace_baseline(path, report.snapshot)
    loaded = engine.load_trace_baseline(path)
    assert loaded == report.snapshot
    again = engine.run_contracts([c], baseline=loaded)
    assert again.findings == []
    # the artifact row shape bench.py embeds
    data = json.loads(engine.render_json(report))
    assert data["contracts"][0]["name"] == "fixture.contract"
    assert data["contracts"][0]["measured"]["jaxpr_eqns"] >= 1


# ---------------------------------------------------------------------------
# The committed registry
# ---------------------------------------------------------------------------

def test_committed_registry_shape():
    """Every committed contract is well-formed and every committed
    baseline entry maps to a declared contract + metric. (The full
    measured run is `make contracts`; the cheap structural guarantee
    keeps the suite fast.)"""
    contracts = engine.discover()
    assert len(contracts) >= 20
    names = [c["name"] for c in contracts]
    assert len(names) == len(set(names))
    by_name = {c["name"]: c for c in contracts}
    for c in contracts:
        assert ("build" in c) or ("measure" in c), c["name"]
        assert isinstance(c.get("budgets", {}), dict)
        for m in c.get("exact", ()):
            assert m in c["budgets"], (c["name"], m)
        for v in c.get("budgets", {}).values():
            assert isinstance(v, int), c["name"]
    # the hot programs the tentpole names are all covered
    for needle in ("miller_loop_grouped", "grouped_verdict",
                   "windowed_chain", "cofactor_clear",
                   "pair_hash_level", "epoch_transition",
                   "mesh_epoch_chain", "forest_build",
                   "forest_pair_lanes"):
        assert any(needle in n for n in names), needle
    baseline = engine.load_trace_baseline()
    assert baseline, "trace_baseline.json missing or empty"
    for name, metrics in baseline.items():
        assert name in by_name, f"stale baseline contract {name}"
        declared = by_name[name]
        known_engine_metrics = {"redc_lanes", "jaxpr_eqns", "f64_ops",
                                "collective_ops", "seq_adds",
                                "seq_doubles"}
        for metric in metrics:
            assert metric in declared.get("budgets", {}) \
                or metric not in known_engine_metrics \
                or declared.get("measure") is not None, (name, metric)
    # budget_snapshot (the bench.py row) never traces: pure declaration
    snap = engine.budget_snapshot(contracts)
    assert snap["ops.fq_tower.fq12_mul[coeff]"] == {"redc_lanes": 12}


def test_trace_rules_registered_without_jax_tier():
    """The trace-tier rule catalog registers through the stdlib-only
    import path (`--list-rules` must show CSA11xx-13xx on the no-jax CI
    lint lane; tracing itself stays lazily imported)."""
    from tools.analysis.core import RULES
    from tools.analysis.trace import TRACE_RULE_IDS
    assert set(TRACE_RULE_IDS) <= set(RULES)
    for rule_id in TRACE_RULE_IDS:
        assert RULES[rule_id].severity in ("error", "notice")


def test_incremental_forest_contract_measures_live():
    """The cheap measured contract (no tracing): the forest pair-lane
    pins, through the engine against the committed baseline."""
    contracts = [c for c in engine.discover()
                 if c["name"] == "utils.ssz.incremental.forest_pair_lanes"]
    assert len(contracts) == 1
    report = engine.run_contracts(contracts)
    assert report.findings == [], [f.message for f in report.findings]
    (res,) = report.results
    assert res.measured == {"build_pair_lanes": 63, "update_pair_lanes": 11}
