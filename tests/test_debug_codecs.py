"""debug/encode <-> debug/decode round trip (the coverage gate caught
decode.py at 0% — the generator suites only exercise the encode side).

Mirrors the reference pair test_libs/pyspec/eth2spec/debug/{encode,decode}.py:
any value encode() renders into YAML/JSON-friendly form must decode() back
to an SSZ-equal value (compared by serialization, the strongest equality
the type system offers).
"""
import pytest

from consensus_specs_tpu.debug.decode import decode
from consensus_specs_tpu.debug.encode import encode
from consensus_specs_tpu.debug.random_value import RandomizationMode, get_random_ssz_object
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.utils.ssz.impl import serialize
from consensus_specs_tpu.utils.ssz.typing import (
    Bytes32, Bytes96, Container, List, Vector, uint8, uint64)


class Inner(Container):
    a: uint64
    b: Bytes32


class Outer(Container):
    x: uint8
    items: List[uint64]
    fixed: Vector[uint64, 3]
    inner: Inner
    sig: Bytes96
    raw: List[uint8]


@pytest.mark.parametrize("mode", [RandomizationMode.RANDOM,
                                  RandomizationMode.ZERO,
                                  RandomizationMode.MAX])
@pytest.mark.parametrize("seed", [0, 7])
def test_encode_decode_round_trip_synthetic(mode, seed):
    import random
    rng = random.Random(seed)
    obj = get_random_ssz_object(rng, Outer, mode=mode, max_list_length=5)
    back = decode(encode(obj, Outer), Outer)
    assert serialize(back, Outer) == serialize(obj, Outer)


def test_encode_decode_round_trip_spec_containers():
    import random
    spec = phase0.get_spec("minimal")
    rng = random.Random(42)
    for name in ("Validator", "AttestationData", "BeaconBlockHeader",
                 "Crosslink", "Deposit", "Checkpoint" ):
        typ = getattr(spec, name, None)
        if typ is None:
            continue
        obj = get_random_ssz_object(rng, typ, max_list_length=4)
        back = decode(encode(obj, typ), typ)
        assert serialize(back, typ) == serialize(obj, typ), name
