"""tools/analysis — fixture snippets per rule (positive, negative,
suppressed), the baseline ratchet, the CLI contract, and the repo-wide
green guarantee `make analyze` enforces.

Runs in the default (not slow) lane: pure AST work, no jax imports by the
analyzer itself.
"""
import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.analysis import analyze_paths, load_baseline
from tools.analysis.core import RULES, write_baseline

REPO = Path(__file__).resolve().parent.parent


def findings_for(tmp_path, source, name="snippet.py"):
    path = tmp_path / name
    path.write_text(source)
    return analyze_paths([str(path)]).findings


def rule_ids(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# CSA1xx trace-safety
# ---------------------------------------------------------------------------

def test_trace_safety_flags_control_flow_and_casts(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        x = x + 1\n"
        "    while x < 3:\n"
        "        x = x * 2\n"
        "    y = jnp.sum(x)\n"
        "    return int(y)\n"
    )
    got = rule_ids(findings_for(tmp_path, src))
    assert got == ["CSA101", "CSA101", "CSA102"]


def test_trace_safety_scans_transitive_callees(tmp_path):
    # the jitted fn is clean; the plain helper it calls is not
    src = (
        "import jax\n"
        "def helper(y):\n"
        "    return bool(y)\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return helper(x)\n"
    )
    found = findings_for(tmp_path, src)
    assert rule_ids(found) == ["CSA102"]
    assert found[0].context == "helper"


def test_trace_safety_negative_static_and_shape(tmp_path):
    # static args, shape reads, and host-annotated callee params are not
    # tracers; partial(jax.jit, static_argnums) form must be understood
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from functools import partial\n"
        "def pick(n: int):\n"
        "    if n > 2:\n"
        "        return 1\n"
        "    return 0\n"
        "@partial(jax.jit, static_argnums=(0,))\n"
        "def f(cfg, x):\n"
        "    if cfg.wide:\n"
        "        x = x + 1\n"
        "    n = x.shape[0]\n"
        "    if n > 2:\n"
        "        x = x * 2\n"
        "    return x + pick(int(n))\n"
    )
    assert findings_for(tmp_path, src) == []


def test_trace_safety_jit_factory_form(tmp_path):
    # a def passed by name into a jit-memoizing factory (the
    # utils/ssz/bulk.py `_get_root_jit(name, fn)` shape) is jit context
    src = (
        "import jax\n"
        "_memo = {}\n"
        "def get_jit(name, fn):\n"
        "    if name not in _memo:\n"
        "        _memo[name] = jax.jit(fn)\n"
        "    return _memo[name]\n"
        "def root(x):\n"
        "    return int(x)\n"
        "def driver(x):\n"
        "    return get_jit('root', root)(x)\n"
    )
    found = findings_for(tmp_path, src)
    assert rule_ids(found) == ["CSA102"]
    assert found[0].context == "root"


def test_trace_safety_wrapper_assignment_form(tmp_path):
    # name = jax.jit(fn): fn is jit context even without a decorator
    src = (
        "import jax\n"
        "def g(x):\n"
        "    return x.item()\n"
        "g_jit = jax.jit(g)\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA102"]


# ---------------------------------------------------------------------------
# CSA2xx dtype-width
# ---------------------------------------------------------------------------

def test_dtype_width_flags_defaulting_ctor_and_wide_literal(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(v):\n"
        "    z = jnp.zeros(4)\n"
        "    return z + v * 2 ** 40\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA201", "CSA202"]


def test_dtype_width_negative_explicit_dtype(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(v):\n"
        "    z = jnp.zeros(4, dtype=jnp.uint64)\n"
        "    w = jnp.asarray(v)\n"          # copy ctor keeps dtype: fine
        "    return z + w * jnp.uint64(2 ** 40)\n"
    )
    assert findings_for(tmp_path, src) == []


# ---------------------------------------------------------------------------
# CSA3xx purity
# ---------------------------------------------------------------------------

def test_purity_flags_time_random_global_and_mutation(tmp_path):
    src = (
        "import jax, time, random\n"
        "import numpy as np\n"
        "COUNTER = 0\n"
        "@jax.jit\n"
        "def f(x, out):\n"
        "    global COUNTER\n"
        "    t = time.time()\n"
        "    r = random.random()\n"
        "    s = np.random.rand()\n"
        "    out[0] = t + r + s\n"
        "    return x\n"
    )
    got = rule_ids(findings_for(tmp_path, src))
    assert got == ["CSA301", "CSA301", "CSA301", "CSA302", "CSA303"]


def test_purity_negative_host_code_untouched(tmp_path):
    # the same calls OUTSIDE jit context are host code, perfectly legal
    src = (
        "import time\n"
        "def bench():\n"
        "    t0 = time.perf_counter()\n"
        "    return time.perf_counter() - t0\n"
    )
    assert findings_for(tmp_path, src) == []


# ---------------------------------------------------------------------------
# CSA401 state-aliasing
# ---------------------------------------------------------------------------

PRE_FIX_RESIDENT_SNIPPET = (
    # the exact shape of the pre-fix resident.py _install overrides: a
    # `state`-accepting closure answering from captured mirrors
    "import numpy as np\n"
    "class ResidentCore:\n"
    "    def _install(self):\n"
    "        mirrors = self.mirrors\n"
    "        def get_total_balance(state, indices):\n"
    "            idx = np.fromiter(indices, dtype=np.int64)\n"
    "            return max(int(mirrors['effective_balance'][idx].sum()), 1)\n"
    "        def effective_balance_of(state, index):\n"
    "            return int(mirrors['effective_balance'][index])\n"
    "        return get_total_balance, effective_balance_of\n"
)


def test_state_aliasing_flags_pre_fix_resident_pattern(tmp_path):
    found = findings_for(tmp_path, PRE_FIX_RESIDENT_SNIPPET)
    assert rule_ids(found) == ["CSA401", "CSA401"]
    # context is scope-qualified so same-named closures elsewhere in the
    # file can't share a fingerprint
    assert {f.context for f in found} == \
        {"ResidentCore._install.get_total_balance",
         "ResidentCore._install.effective_balance_of"}


def test_state_aliasing_same_named_closures_get_distinct_fingerprints(
        tmp_path):
    src = (
        "class A:\n"
        "    def make(self):\n"
        "        def handler(state, x):\n"
        "            return x\n"
        "        return handler\n"
        "class B:\n"
        "    def make(self):\n"
        "        def handler(state, x):\n"
        "            return x + 1\n"
        "        return handler\n"
    )
    found = findings_for(tmp_path, src)
    assert rule_ids(found) == ["CSA401", "CSA401"]
    fps = {f.fingerprint() for f in found}
    assert len(fps) == 2   # baselining one must not hide the other


def test_state_aliasing_negative_guarded_override(tmp_path):
    # the post-fix shape: delegating on `state is not self.state` reads
    # the parameter, so the aliasing hazard is structurally gone
    src = (
        "class Core:\n"
        "    def _install(self, saved):\n"
        "        def effective_balance_of(state, index):\n"
        "            if state is not self.state:\n"
        "                return saved(state, index)\n"
        "            return int(self.mirrors['effective_balance'][index])\n"
        "        return effective_balance_of\n"
    )
    assert findings_for(tmp_path, src) == []


def test_state_aliasing_skips_stubs_and_honors_suppression(tmp_path):
    src = (
        "def abstract_handler(state, msg):\n"
        "    raise NotImplementedError\n"
        "# csa: ignore[CSA401]\n"
        "def interface_conformance(state, x):\n"
        "    return x\n"
    )
    path = tmp_path / "s.py"
    path.write_text(src)
    report = analyze_paths([str(path)])
    assert report.findings == []
    assert len(report.suppressed) == 1
    assert report.suppressed[0].rule == "CSA401"


# ---------------------------------------------------------------------------
# CSA5xx jit-cache hygiene
# ---------------------------------------------------------------------------

def test_jit_cache_flags_scalar_call_and_unhashable_static(tmp_path):
    src = (
        "import jax\n"
        "from functools import partial\n"
        "def f(n, x):\n"
        "    return x\n"
        "f_jit = jax.jit(f)\n"
        "@partial(jax.jit, static_argnums=(0,))\n"
        "def g(table: list, x):\n"
        "    return x\n"
        "def driver(x):\n"
        "    return f_jit(3, x)\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA501", "CSA502"]


def test_jit_cache_ignores_same_named_attribute_calls(tmp_path):
    # store.update(...) is some other object's method, not the module's
    # jitted `update` — no CSA501
    src = (
        "import jax\n"
        "def _update(n, x):\n"
        "    return x\n"
        "update = jax.jit(_update)\n"
        "def driver(store, x):\n"
        "    store.update(3, x)\n"
        "    return update(x, x)\n"
    )
    assert findings_for(tmp_path, src) == []


def test_trace_safety_walrus_taint(tmp_path):
    # NamedExpr binds like an Assign: both the `if` test containing the
    # walrus and later host casts of its target are traced hazards
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if (s := jnp.sum(x)) > 0:\n"
        "        return int(s)\n"
        "    return s\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA101", "CSA102"]


def test_jit_cache_negative_static_scalar_ok(tmp_path):
    # a scalar into a STATIC slot is the intended use; arrays into traced
    # slots are fine too
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=(0,))\n"
        "def f(n: int, x):\n"
        "    return x * n\n"
        "def driver(x):\n"
        "    return f(3, jnp.asarray(x))\n"
    )
    assert findings_for(tmp_path, src) == []


# ---------------------------------------------------------------------------
# framework: baseline ratchet + CLI + repo green
# ---------------------------------------------------------------------------

def test_baseline_roundtrip_and_stale_detection(tmp_path):
    path = tmp_path / "s.py"
    path.write_text(PRE_FIX_RESIDENT_SNIPPET)
    report = analyze_paths([str(path)])
    assert len(report.findings) == 2

    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), report.findings)
    baseline = load_baseline(str(bl_path))
    ratcheted = analyze_paths([str(path)], baseline)
    assert ratcheted.findings == []
    assert len(ratcheted.baselined) == 2
    assert ratcheted.stale_baseline == []

    # fix one of the two: its baseline entry goes stale, run stays green
    path.write_text(PRE_FIX_RESIDENT_SNIPPET.replace(
        "return int(mirrors['effective_balance'][index])",
        "return int(state.validator_registry[index].effective_balance)"))
    after_fix = analyze_paths([str(path)], baseline)
    assert after_fix.findings == []
    assert len(after_fix.stale_baseline) == 1


def test_update_baseline_preserves_live_entries_and_reasons(tmp_path):
    """Refreshing the baseline must keep still-live entries (with their
    hand-written reasons), not reset the file to just-new findings."""
    path = tmp_path / "s.py"
    path.write_text(PRE_FIX_RESIDENT_SNIPPET)
    first = analyze_paths([str(path)])
    live_fp = first.findings[0].fingerprint()

    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), [first.findings[0]])
    # hand-edit the reason, as the README instructs
    data = json.loads(bl_path.read_text())
    data["entries"][0]["reason"] = "deliberate: documented at the site"
    bl_path.write_text(json.dumps(data))

    baseline = load_baseline(str(bl_path))
    report = analyze_paths([str(path)], baseline)
    assert len(report.findings) == 1 and len(report.baselined) == 1
    # the --update-baseline merge: actionable + still-baselined, reasons
    # carried over for entries that were already in the file
    write_baseline(str(bl_path), report.findings + report.baselined,
                   prior=baseline)
    merged = json.loads(bl_path.read_text())["entries"]
    assert len(merged) == 2
    by_fp = {e["fingerprint"]: e["reason"] for e in merged}
    assert by_fp[live_fp] == "deliberate: documented at the site"
    refreshed = analyze_paths([str(path)], load_baseline(str(bl_path)))
    assert refreshed.findings == [] and refreshed.stale_baseline == []


def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.analysis", *args],
        cwd=cwd, capture_output=True, text=True)


def test_cli_exit_codes_and_json(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text(PRE_FIX_RESIDENT_SNIPPET)
    clean = tmp_path / "clean.py"
    clean.write_text("def f(state):\n    return state.slot\n")
    out_json = tmp_path / "analysis.json"

    proc = _run_cli([str(dirty), "--json", str(out_json)])
    assert proc.returncode == 1
    assert "CSA401" in proc.stdout
    data = json.loads(out_json.read_text())
    assert [f["rule"] for f in data["findings"]] == ["CSA401", "CSA401"]

    assert _run_cli([str(clean)]).returncode == 0
    assert _run_cli(["--list-rules"]).returncode == 0


@pytest.mark.parametrize("rule_class,snippet", [
    ("CSA101", "import jax\n@jax.jit\ndef f(x):\n    if x > 0:\n"
               "        return x\n    return -x\n"),
    ("CSA201", "import jax\nimport jax.numpy as jnp\n@jax.jit\n"
               "def f(x):\n    return x + jnp.zeros(3)\n"),
    ("CSA301", "import jax, time\n@jax.jit\ndef f(x):\n"
               "    return x + time.time()\n"),
    ("CSA401", "def f(state):\n    return 1\n"),
    ("CSA501", "import jax\ndef f(x):\n    return x\n"
               "f_jit = jax.jit(f)\ny = f_jit(3)\n"),
])
def test_cli_nonzero_per_rule_class(tmp_path, rule_class, snippet):
    """Acceptance: injected fixtures for each of the 5 rule classes exit
    non-zero through the real CLI."""
    path = tmp_path / "inject.py"
    path.write_text(snippet)
    proc = _run_cli([str(path)])
    assert proc.returncode == 1
    assert rule_class in proc.stdout


def test_repo_is_analysis_clean():
    """The `make analyze` guarantee, asserted in-process: the shipped tree
    has no actionable findings over the committed baseline."""
    baseline = load_baseline(str(REPO / "tools" / "analysis" / "baseline.json"))
    report = analyze_paths(
        [str(REPO / "consensus_specs_tpu"), str(REPO / "bench.py"),
         str(REPO / "__graft_entry__.py")], baseline)
    assert report.findings == []
    assert report.stale_baseline == []


def test_rule_catalog_documented():
    """Every registered rule appears in tools/analysis/README.md."""
    readme = (REPO / "tools" / "analysis" / "README.md").read_text()
    for rule_id in RULES:
        assert rule_id in readme, f"{rule_id} missing from README"
