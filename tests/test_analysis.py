"""tools/analysis — fixture snippets per rule (positive, negative,
suppressed), the baseline ratchet, the CLI contract, and the repo-wide
green guarantee `make analyze` enforces.

Runs in the default (not slow) lane: pure AST work, no jax imports by the
analyzer itself.
"""
import json
import subprocess
import sys
from pathlib import Path

import pytest

from tools.analysis import analyze_paths, load_baseline
from tools.analysis.core import RULES, write_baseline

REPO = Path(__file__).resolve().parent.parent


def findings_for(tmp_path, source, name="snippet.py"):
    path = tmp_path / name
    path.write_text(source)
    return analyze_paths([str(path)]).findings


def rule_ids(findings):
    return sorted(f.rule for f in findings)


# ---------------------------------------------------------------------------
# CSA1xx trace-safety
# ---------------------------------------------------------------------------

def test_trace_safety_flags_control_flow_and_casts(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        x = x + 1\n"
        "    while x < 3:\n"
        "        x = x * 2\n"
        "    y = jnp.sum(x)\n"
        "    return int(y)\n"
    )
    got = rule_ids(findings_for(tmp_path, src))
    assert got == ["CSA101", "CSA101", "CSA102"]


def test_trace_safety_scans_transitive_callees(tmp_path):
    # the jitted fn is clean; the plain helper it calls is not
    src = (
        "import jax\n"
        "def helper(y):\n"
        "    return bool(y)\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return helper(x)\n"
    )
    found = findings_for(tmp_path, src)
    assert rule_ids(found) == ["CSA102"]
    assert found[0].context == "helper"


def test_trace_safety_negative_static_and_shape(tmp_path):
    # static args, shape reads, and host-annotated callee params are not
    # tracers; partial(jax.jit, static_argnums) form must be understood
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from functools import partial\n"
        "def pick(n: int):\n"
        "    if n > 2:\n"
        "        return 1\n"
        "    return 0\n"
        "@partial(jax.jit, static_argnums=(0,))\n"
        "def f(cfg, x):\n"
        "    if cfg.wide:\n"
        "        x = x + 1\n"
        "    n = x.shape[0]\n"
        "    if n > 2:\n"
        "        x = x * 2\n"
        "    return x + pick(int(n))\n"
    )
    assert findings_for(tmp_path, src) == []


def test_trace_safety_jit_factory_form(tmp_path):
    # a def passed by name into a jit-memoizing factory (the
    # utils/ssz/bulk.py `_get_root_jit(name, fn)` shape) is jit context
    src = (
        "import jax\n"
        "_memo = {}\n"
        "def get_jit(name, fn):\n"
        "    if name not in _memo:\n"
        "        _memo[name] = jax.jit(fn)\n"
        "    return _memo[name]\n"
        "def root(x):\n"
        "    return int(x)\n"
        "def driver(x):\n"
        "    return get_jit('root', root)(x)\n"
    )
    found = findings_for(tmp_path, src)
    assert rule_ids(found) == ["CSA102"]
    assert found[0].context == "root"


def test_trace_safety_wrapper_assignment_form(tmp_path):
    # name = jax.jit(fn): fn is jit context even without a decorator
    src = (
        "import jax\n"
        "def g(x):\n"
        "    return x.item()\n"
        "g_jit = jax.jit(g)\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA102"]


# ---------------------------------------------------------------------------
# CSA2xx dtype-width
# ---------------------------------------------------------------------------

def test_dtype_width_flags_defaulting_ctor_and_wide_literal(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(v):\n"
        "    z = jnp.zeros(4)\n"
        "    return z + v * 2 ** 40\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA201", "CSA202"]


def test_dtype_width_negative_explicit_dtype(tmp_path):
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(v):\n"
        "    z = jnp.zeros(4, dtype=jnp.uint64)\n"
        "    w = jnp.asarray(v)\n"          # copy ctor keeps dtype: fine
        "    return z + w * jnp.uint64(2 ** 40)\n"
    )
    assert findings_for(tmp_path, src) == []


# ---------------------------------------------------------------------------
# CSA3xx purity
# ---------------------------------------------------------------------------

def test_purity_flags_time_random_global_and_mutation(tmp_path):
    src = (
        "import jax, time, random\n"
        "import numpy as np\n"
        "COUNTER = 0\n"
        "@jax.jit\n"
        "def f(x, out):\n"
        "    global COUNTER\n"
        "    t = time.time()\n"
        "    r = random.random()\n"
        "    s = np.random.rand()\n"
        "    out[0] = t + r + s\n"
        "    return x\n"
    )
    got = rule_ids(findings_for(tmp_path, src))
    assert got == ["CSA301", "CSA301", "CSA301", "CSA302", "CSA303"]


def test_purity_negative_host_code_untouched(tmp_path):
    # the same calls OUTSIDE jit context are host code, perfectly legal
    src = (
        "import time\n"
        "def bench():\n"
        "    t0 = time.perf_counter()\n"
        "    return time.perf_counter() - t0\n"
    )
    assert findings_for(tmp_path, src) == []


# ---------------------------------------------------------------------------
# CSA401 state-aliasing
# ---------------------------------------------------------------------------

PRE_FIX_RESIDENT_SNIPPET = (
    # the exact shape of the pre-fix resident.py _install overrides: a
    # `state`-accepting closure answering from captured mirrors
    "import numpy as np\n"
    "class ResidentCore:\n"
    "    def _install(self):\n"
    "        mirrors = self.mirrors\n"
    "        def get_total_balance(state, indices):\n"
    "            idx = np.fromiter(indices, dtype=np.int64)\n"
    "            return max(int(mirrors['effective_balance'][idx].sum()), 1)\n"
    "        def effective_balance_of(state, index):\n"
    "            return int(mirrors['effective_balance'][index])\n"
    "        return get_total_balance, effective_balance_of\n"
)


def test_state_aliasing_flags_pre_fix_resident_pattern(tmp_path):
    found = findings_for(tmp_path, PRE_FIX_RESIDENT_SNIPPET)
    assert rule_ids(found) == ["CSA401", "CSA401"]
    # context is scope-qualified so same-named closures elsewhere in the
    # file can't share a fingerprint
    assert {f.context for f in found} == \
        {"ResidentCore._install.get_total_balance",
         "ResidentCore._install.effective_balance_of"}


def test_state_aliasing_same_named_closures_get_distinct_fingerprints(
        tmp_path):
    src = (
        "class A:\n"
        "    def make(self):\n"
        "        def handler(state, x):\n"
        "            return x\n"
        "        return handler\n"
        "class B:\n"
        "    def make(self):\n"
        "        def handler(state, x):\n"
        "            return x + 1\n"
        "        return handler\n"
    )
    found = findings_for(tmp_path, src)
    assert rule_ids(found) == ["CSA401", "CSA401"]
    fps = {f.fingerprint() for f in found}
    assert len(fps) == 2   # baselining one must not hide the other


def test_state_aliasing_negative_guarded_override(tmp_path):
    # the post-fix shape: delegating on `state is not self.state` reads
    # the parameter, so the aliasing hazard is structurally gone
    src = (
        "class Core:\n"
        "    def _install(self, saved):\n"
        "        def effective_balance_of(state, index):\n"
        "            if state is not self.state:\n"
        "                return saved(state, index)\n"
        "            return int(self.mirrors['effective_balance'][index])\n"
        "        return effective_balance_of\n"
    )
    assert findings_for(tmp_path, src) == []


def test_state_aliasing_skips_stubs_and_honors_suppression(tmp_path):
    src = (
        "def abstract_handler(state, msg):\n"
        "    raise NotImplementedError\n"
        "# csa: ignore[CSA401]\n"
        "def interface_conformance(state, x):\n"
        "    return x\n"
    )
    path = tmp_path / "s.py"
    path.write_text(src)
    report = analyze_paths([str(path)])
    assert report.findings == []
    assert len(report.suppressed) == 1
    assert report.suppressed[0].rule == "CSA401"


# ---------------------------------------------------------------------------
# CSA5xx jit-cache hygiene
# ---------------------------------------------------------------------------

def test_jit_cache_flags_scalar_call_and_unhashable_static(tmp_path):
    src = (
        "import jax\n"
        "from functools import partial\n"
        "def f(n, x):\n"
        "    return x\n"
        "f_jit = jax.jit(f)\n"
        "@partial(jax.jit, static_argnums=(0,))\n"
        "def g(table: list, x):\n"
        "    return x\n"
        "def driver(x):\n"
        "    return f_jit(3, x)\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA501", "CSA502"]


def test_jit_cache_ignores_same_named_attribute_calls(tmp_path):
    # store.update(...) is some other object's method, not the module's
    # jitted `update` — no CSA501
    src = (
        "import jax\n"
        "def _update(n, x):\n"
        "    return x\n"
        "update = jax.jit(_update)\n"
        "def driver(store, x):\n"
        "    store.update(3, x)\n"
        "    return update(x, x)\n"
    )
    assert findings_for(tmp_path, src) == []


def test_trace_safety_walrus_taint(tmp_path):
    # NamedExpr binds like an Assign: both the `if` test containing the
    # walrus and later host casts of its target are traced hazards
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if (s := jnp.sum(x)) > 0:\n"
        "        return int(s)\n"
        "    return s\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA101", "CSA102"]


def test_jit_cache_negative_static_scalar_ok(tmp_path):
    # a scalar into a STATIC slot is the intended use; arrays into traced
    # slots are fine too
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=(0,))\n"
        "def f(n: int, x):\n"
        "    return x * n\n"
        "def driver(x):\n"
        "    return f(3, jnp.asarray(x))\n"
    )
    assert findings_for(tmp_path, src) == []


# ---------------------------------------------------------------------------
# call-graph IR: cross-module jit context (tools/analysis/callgraph.py)
# ---------------------------------------------------------------------------

def _write_pkg(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        (pkg / name).write_text(src)
    return tmp_path


def test_callgraph_taint_crosses_from_import(tmp_path):
    # PR 1 stopped at the file edge: the helper was analyzed as host code
    root = _write_pkg(tmp_path, {
        "helpers.py": "def helper(y):\n    return int(y)\n",
        "main.py": ("import jax\nfrom .helpers import helper\n"
                    "@jax.jit\ndef f(x):\n    return helper(x)\n"),
    })
    found = findings_for_dir(root)
    assert rule_ids(found) == ["CSA102"]
    assert found[0].path.endswith("helpers.py")
    assert found[0].context == "helper"


def test_callgraph_taint_crosses_module_attribute_calls(tmp_path):
    root = _write_pkg(tmp_path, {
        "helpers.py": "def helper(y):\n    return bool(y)\n",
        "main.py": ("import jax\nfrom . import helpers\n"
                    "@jax.jit\ndef f(x):\n    return helpers.helper(x)\n"),
    })
    found = findings_for_dir(root)
    assert rule_ids(found) == ["CSA102"]
    assert found[0].path.endswith("helpers.py")


def test_callgraph_imported_jitted_name_feeds_csa501(tmp_path):
    # `from .kern import f_jit` call sites are CSA5xx-visible now
    root = _write_pkg(tmp_path, {
        "kern.py": ("import jax\ndef _f(x):\n    return x\n"
                    "f_jit = jax.jit(_f)\n"),
        "drv.py": ("from .kern import f_jit\n"
                   "def run():\n    return f_jit(3)\n"),
    })
    found = findings_for_dir(root)
    assert rule_ids(found) == ["CSA501"]
    assert found[0].path.endswith("drv.py")


def test_callgraph_host_annotations_stay_host_cross_module(tmp_path):
    # np.ndarray params are trace-time constants (the fq_tower static
    # int-matrix idiom); `x is None` is an identity check, never a
    # tracer bool — neither may fire CSA101/102 through the call graph
    root = _write_pkg(tmp_path, {
        "helpers.py": ("import numpy as np\n"
                       "def unroll(mat: np.ndarray, x, acc=None):\n"
                       "    for r in range(mat.shape[0]):\n"
                       "        v = int(mat[r, 0])\n"
                       "        if v != 0:\n"
                       "            acc = x if acc is None else acc + x\n"
                       "    return acc\n"),
        "main.py": ("import jax\nfrom .helpers import unroll\n"
                    "@jax.jit\ndef f(mat, x):\n"
                    "    return unroll(mat, x)\n"),
    })
    assert findings_for_dir(root) == []


def findings_for_dir(root, options=None):
    return analyze_paths([str(root)], options=options).findings


# ---------------------------------------------------------------------------
# CSA6xx sharding / collective consistency
# ---------------------------------------------------------------------------

def test_sharding_flags_unbound_collective_axis(tmp_path):
    src = (
        "import jax\n"
        "from jax.sharding import Mesh\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'w')\n"    # typo: no mesh binds 'w'
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA601"]


def test_sharding_negative_bound_axes_and_suppression(tmp_path):
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('host', 'v'))\n"
        "spec = P(('host', 'v'))\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, ('host', 'v'))\n"
        "def g(x):\n"
        "    return jax.lax.psum(x, 'q')  # csa: ignore[CSA601] -- doc\n"
    )
    path = tmp_path / "s.py"
    path.write_text(src)
    report = analyze_paths([str(path)])
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["CSA601"]


def test_sharding_flags_unknown_partition_spec_axis(tmp_path):
    src = (
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "spec = P('validators')\n"             # not a mesh axis
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA602"]


def test_sharding_negative_partition_spec_none_entries(tmp_path):
    src = (
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "spec = P(None, 'v')\n"
    )
    assert findings_for(tmp_path, src) == []


def test_sharding_flags_bare_constraint_outside_mesh(tmp_path):
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "def f(x):\n"
        "    return jax.lax.with_sharding_constraint(x, P('v'))\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA603"]


def test_sharding_negative_constraint_under_mesh_scope(tmp_path):
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "def f(x):\n"
        "    with mesh:\n"
        "        return jax.lax.with_sharding_constraint(x, P('v'))\n"
    )
    assert findings_for(tmp_path, src) == []


def test_sharding_flags_producer_consumer_spec_mismatch(tmp_path):
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "def f(x):\n"
        "    y = jax.device_put(x, NamedSharding(mesh, P('v')))\n"
        "    z = jax.device_put(y, NamedSharding(mesh, P(None, 'v')))\n"
        "    return z\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA604"]


def test_sharding_negative_named_spec_matches_inline(tmp_path):
    # a spec bound to a named constant is the SAME spec, not a reshard
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "SPEC = NamedSharding(mesh, P('v'))\n"
        "def f(x):\n"
        "    y = jax.device_put(x, NamedSharding(mesh, P('v')))\n"
        "    z = jax.device_put(y, SPEC)\n"
        "    return z\n"
    )
    assert findings_for(tmp_path, src) == []


def test_callgraph_jitted_name_reexport_chain(tmp_path):
    # a -> re-exported by b -> called in c: CSA501 must fire regardless
    # of module iteration order (names chosen to sort c before b)
    root = _write_pkg(tmp_path, {
        "z_src.py": ("import jax\ndef _f(x):\n    return x\n"
                     "f_jit = jax.jit(_f)\n"),
        "m_mid.py": "from .z_src import f_jit\n",
        "a_use.py": ("from .m_mid import f_jit\n"
                     "def run():\n    return f_jit(3)\n"),
    })
    found = findings_for_dir(root)
    assert rule_ids(found) == ["CSA501"]
    assert found[0].path.endswith("a_use.py")


def test_sharding_negative_consistent_producer_consumer(tmp_path):
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "def f(x):\n"
        "    y = jax.device_put(x, NamedSharding(mesh, P('v')))\n"
        "    z = jax.device_put(y, NamedSharding(mesh, P('v')))\n"
        "    return z\n"
    )
    assert findings_for(tmp_path, src) == []


def test_sharding_flags_chained_jit_sharding_mismatch(tmp_path):
    """CSA605: a jitted producer's out_shardings feeding a jitted consumer
    whose in_shardings disagree at that argument position — the serving-
    loop contract (SNIPPETS.md [1]) checked statically."""
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "def serve(x):\n"
        "    step = jax.jit(lambda a: a,\n"
        "                   in_shardings=NamedSharding(mesh, P('v')),\n"
        "                   out_shardings=NamedSharding(mesh, P('v')))\n"
        "    gather = jax.jit(lambda a: a,\n"
        "                     in_shardings=NamedSharding(mesh, P()),\n"
        "                     out_shardings=NamedSharding(mesh, P()))\n"
        "    y = step(x)\n"
        "    return gather(y)\n"        # P('v') output into P() input
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA605"]


def test_sharding_negative_chained_jit_matched_shardings(tmp_path):
    """Matched out/in shardings — including specs named by a constant and
    tuple outputs unpacked into the next call — produce no finding."""
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "SH = NamedSharding(mesh, P('v'))\n"
        "def serve(x, s):\n"
        "    step = jax.jit(lambda a, b: (a, b),\n"
        "                   in_shardings=(SH, NamedSharding(mesh, P())),\n"
        "                   out_shardings=(NamedSharding(mesh, P('v')),\n"
        "                                  NamedSharding(mesh, P())))\n"
        "    cols, scal = step(x, s)\n"
        "    cols, scal = step(cols, scal)\n"   # chained, matched per-arg
        "    return cols\n"
    )
    assert findings_for(tmp_path, src) == []


def test_sharding_negative_chained_jit_rebound_value(tmp_path):
    """An explicit re-layout (or any rebinding) between producer and
    consumer invalidates the recorded out-sharding — deliberate gathers
    must not be flagged as implicit reshards."""
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "def serve(x):\n"
        "    step = jax.jit(lambda a: a,\n"
        "                   in_shardings=NamedSharding(mesh, P('v')),\n"
        "                   out_shardings=NamedSharding(mesh, P('v')))\n"
        "    gather = jax.jit(lambda a: a,\n"
        "                     in_shardings=NamedSharding(mesh, P()))\n"
        "    y = step(x)\n"
        "    y = jax.device_put(y, NamedSharding(mesh, P()))\n"
        "    return gather(y)\n"       # explicit re-layout: no finding
    )
    assert findings_for(tmp_path, src) == []
    # non-Assign rebindings (AugAssign here) invalidate the same way
    src_aug = src.replace(
        "    y = jax.device_put(y, NamedSharding(mesh, P()))\n",
        "    y += 1\n")
    assert findings_for(tmp_path, src_aug) == []


def test_sharding_chained_jit_mismatch_suppressible(tmp_path):
    src = (
        "import jax\n"
        "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
        "mesh = Mesh(None, axis_names=('v',))\n"
        "def serve(x):\n"
        "    step = jax.jit(lambda a: a,\n"
        "                   in_shardings=NamedSharding(mesh, P('v')),\n"
        "                   out_shardings=NamedSharding(mesh, P('v')))\n"
        "    gather = jax.jit(lambda a: a,\n"
        "                     in_shardings=NamedSharding(mesh, P()))\n"
        "    y = step(x)\n"
        "    return gather(y)  # csa: ignore[CSA605] -- one-shot download\n"
    )
    path = tmp_path / "s.py"
    path.write_text(src)
    report = analyze_paths([str(path)])
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["CSA605"]


# ---------------------------------------------------------------------------
# CSA7xx pallas kernel constraints
# ---------------------------------------------------------------------------

_PALLAS_HEADER = (
    "import jax\n"
    "from jax.experimental import pallas as pl\n"
    "def k(x_ref, o_ref):\n"
    "    o_ref[0, :] = x_ref[0, :]\n"
)


def test_pallas_flags_index_map_arity_and_rank(tmp_path):
    src = _PALLAS_HEADER + (
        "def run(x):\n"
        "    return pl.pallas_call(k, grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((8, 128), lambda i, j: (0, i))],\n"
        "        out_specs=pl.BlockSpec((8, 128), lambda i: (i,)),\n"
        "        interpret=True)(x)\n"
    )
    # in spec: 2 lambda args vs rank-1 grid; out spec: 1 index for a
    # rank-2 block
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA701", "CSA701"]


def test_pallas_flags_traced_grid(tmp_path):
    src = _PALLAS_HEADER + (
        "@jax.jit\n"
        "def run(x, n):\n"
        "    return pl.pallas_call(k, grid=(n,),\n"
        "        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, i))],\n"
        "        out_specs=pl.BlockSpec((8, 128), lambda i: (0, i)),\n"
        "        interpret=True)(x)\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA702"]


def test_pallas_flags_missing_interpret_escape_hatch(tmp_path):
    src = _PALLAS_HEADER + (
        "def run(x):\n"
        "    return pl.pallas_call(k, grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, i))],\n"
        "        out_specs=pl.BlockSpec((8, 128), lambda i: (0, i)))(x)\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA703"]


def test_pallas_flags_out_of_block_ref_access(tmp_path):
    src = (
        "import jax\n"
        "from jax.experimental import pallas as pl\n"
        "def k(x_ref, o_ref):\n"
        "    o_ref[9, :] = x_ref[0, :, 0]\n"   # 9 >= 8; rank 3 > rank 2
        "def run(x):\n"
        "    return pl.pallas_call(k, grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, i))],\n"
        "        out_specs=pl.BlockSpec((8, 128), lambda i: (0, i)),\n"
        "        interpret=True)(x)\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA704", "CSA704"]


def test_pallas_negative_consistent_call(tmp_path):
    # the sha256_pallas shape: named specs, static shapes from .shape,
    # loop-variable indices, paired compiled/interpret call sites
    src = (
        "import jax\n"
        "from jax.experimental import pallas as pl\n"
        "def k(x_ref, o_ref):\n"
        "    for i in range(8):\n"
        "        o_ref[i, :] = x_ref[i, :]\n"
        "def run(x, interpret=False):\n"
        "    n = x.shape[1]\n"
        "    spec = pl.BlockSpec((8, 128), lambda i: (0, i))\n"
        "    grid = (n // 128,)\n"
        "    return pl.pallas_call(k, grid=grid,\n"
        "        in_specs=[spec], out_specs=spec,\n"
        "        interpret=interpret)(x)\n"
    )
    assert findings_for(tmp_path, src) == []
    report = analyze_paths(
        [str(REPO / "consensus_specs_tpu" / "ops" / "sha256_pallas.py")])
    assert report.findings == []


def test_pallas_suppression(tmp_path):
    src = _PALLAS_HEADER + (
        "def run(x):\n"
        "    # csa: ignore[CSA703] -- TPU-only by design\n"
        "    return pl.pallas_call(k, grid=(4,),\n"
        "        in_specs=[pl.BlockSpec((8, 128), lambda i: (0, i))],\n"
        "        out_specs=pl.BlockSpec((8, 128), lambda i: (0, i)))(x)\n"
    )
    path = tmp_path / "s.py"
    path.write_text(src)
    report = analyze_paths([str(path)])
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["CSA703"]


# ---------------------------------------------------------------------------
# CSA901 wide-column accumulation (the double-width lazy-Montgomery budget)
# ---------------------------------------------------------------------------

def test_wide_accumulation_flags_three_term_sum(tmp_path):
    src = (
        "from consensus_specs_tpu.ops import fq as F\n"
        "def f(a, b, c):\n"
        "    t0 = F.fq_mul_wide(a, b)\n"
        "    t1 = F.fq_mul_wide(a, c)\n"
        "    t2 = F.fq_mul_wide(b, c)\n"
        "    return t0 + t1 - t2\n"
    )
    found = findings_for(tmp_path, src)
    assert rule_ids(found) == ["CSA901"]
    assert found[0].severity == "notice"


def test_wide_accumulation_flags_augassign_loop(tmp_path):
    # taint accumulates through rebinding and +=
    src = (
        "from consensus_specs_tpu.ops import fq as F\n"
        "def f(a, bs):\n"
        "    acc = F.fq_mul_wide(a, bs[0])\n"
        "    acc += F.fq_mul_wide(a, bs[1])\n"
        "    acc += F.fq_mul_wide(a, bs[2])\n"
        "    return acc\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA901"]


def test_wide_accumulation_flags_matrix_over_raw_columns(tmp_path):
    src = (
        "from consensus_specs_tpu.ops import fq as F\n"
        "from consensus_specs_tpu.ops.fq_tower import _apply_int_matrix\n"
        "def f(gamma, a, b):\n"
        "    P = F.fq_mul_wide(a, b)\n"
        "    return _apply_int_matrix(gamma, P)\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA901"]


def test_wide_accumulation_negative_normed_and_shallow(tmp_path):
    # the shipped pipeline shape: fq_wide_norm clears the taint, and a
    # 2-term raw sum is inside the int64 headroom
    src = (
        "from consensus_specs_tpu.ops import fq as F\n"
        "from consensus_specs_tpu.ops.fq_tower import _apply_int_matrix\n"
        "def f(gamma, a, b, c):\n"
        "    P = F.fq_wide_norm(F.fq_mul_wide(a, b))\n"
        "    t = F.fq_mul_wide(a, c)\n"
        "    u = F.fq_mul_wide(b, c)\n"
        "    shallow = t - u\n"
        "    deep = P + P + P + P\n"
        "    return _apply_int_matrix(gamma, P) + shallow + deep\n"
    )
    assert findings_for(tmp_path, src) == []


def test_wide_accumulation_suppression(tmp_path):
    src = (
        "from consensus_specs_tpu.ops import fq as F\n"
        "def f(a, b, c):\n"
        "    # csa: ignore[CSA901] -- operands are half-width here\n"
        "    return F.fq_mul_wide(a, b) + F.fq_mul_wide(a, c) + "
        "F.fq_mul_wide(b, c)\n"
    )
    path = tmp_path / "s.py"
    path.write_text(src)
    report = analyze_paths([str(path)])
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["CSA901"]


# ---------------------------------------------------------------------------
# CSA1001 honest timing (perf_counter around async dispatch with no fence)
# ---------------------------------------------------------------------------

_JIT_PREAMBLE = (
    "import jax, time\n"
    "import numpy as np\n"
    "def f(x):\n"
    "    return x\n"
    "f_jit = jax.jit(f)\n"
)


def test_honest_timing_flags_unfenced_delta(tmp_path):
    src = _JIT_PREAMBLE + (
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = f_jit(x)\n"
        "    dt = time.perf_counter() - t0\n"
        "    return y, dt\n"
    )
    found = findings_for(tmp_path, src)
    assert rule_ids(found) == ["CSA1001"]
    assert found[0].context == "bench"


def test_honest_timing_flags_chained_bucket_style(tmp_path):
    # the t0/t1/t2 style epoch_soa used to hand-roll: the next
    # perf_counter assignment closes the open region
    src = _JIT_PREAMBLE + (
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = f_jit(x)\n"
        "    t1 = time.perf_counter()\n"
        "    return y, t1 - t0\n"
    )
    assert rule_ids(findings_for(tmp_path, src)) == ["CSA1001"]


def test_honest_timing_negative_fenced(tmp_path):
    # every repo fence idiom clears the region, including inside the
    # timed loop body
    for fence in ("jax.block_until_ready(y)",
                  "np.asarray(y.ravel()[0:1])",
                  "y = y.tolist()"):
        src = _JIT_PREAMBLE + (
            "def bench(x):\n"
            "    t0 = time.perf_counter()\n"
            "    y = f_jit(x)\n"
            f"    {fence}\n"
            "    dt = time.perf_counter() - t0\n"
            "    return dt\n"
        )
        assert findings_for(tmp_path, src) == [], fence
    src = _JIT_PREAMBLE + (
        "def _sync(o):\n"
        "    return np.asarray(o)\n"
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    for _ in range(3):\n"
        "        _sync(f_jit(x))\n"
        "    return time.perf_counter() - t0\n"
    )
    assert findings_for(tmp_path, src) == []


def test_honest_timing_negative_no_dispatch(tmp_path):
    # a plain host computation between the reads is not a finding
    src = _JIT_PREAMBLE + (
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = x + 1\n"
        "    return time.perf_counter() - t0\n"
    )
    assert findings_for(tmp_path, src) == []


def test_honest_timing_flags_attribute_call_dispatch(tmp_path):
    """The documented CSA1001 gap, closed: an unfenced delta around a
    module-ATTRIBUTE dispatch (`kern.f_jit(x)`) of a jitted name
    resolved through the call-graph IR."""
    root = _write_pkg(tmp_path, {
        "kern.py": ("import jax\ndef _f(x):\n    return x\n"
                    "f_jit = jax.jit(_f)\n"),
        "drv.py": ("import time\nfrom . import kern\n"
                   "def bench(x):\n"
                   "    t0 = time.perf_counter()\n"
                   "    y = kern.f_jit(x)\n"
                   "    dt = time.perf_counter() - t0\n"
                   "    return y, dt\n"),
    })
    found = [f for f in findings_for_dir(root) if f.rule == "CSA1001"]
    assert len(found) == 1
    assert found[0].path.endswith("drv.py")
    assert found[0].context == "bench"


def test_honest_timing_attribute_call_negative_fenced_and_unjitted(
        tmp_path):
    # a fenced attribute dispatch is clean, and an attribute call whose
    # target module has no such jitted name never fires
    root = _write_pkg(tmp_path, {
        "kern.py": ("import jax\ndef _f(x):\n    return x\n"
                    "f_jit = jax.jit(_f)\n"
                    "def host_helper(x):\n    return x\n"),
        "drv.py": ("import time\nimport numpy as np\nfrom . import kern\n"
                   "def bench(x):\n"
                   "    t0 = time.perf_counter()\n"
                   "    y = kern.f_jit(x)\n"
                   "    np.asarray(y)\n"
                   "    dt = time.perf_counter() - t0\n"
                   "    t1 = time.perf_counter()\n"
                   "    z = kern.host_helper(x)\n"
                   "    return y, z, dt, time.perf_counter() - t1\n"),
    })
    assert [f for f in findings_for_dir(root) if f.rule == "CSA1001"] == []


def test_honest_timing_attribute_call_suppressible(tmp_path):
    root = _write_pkg(tmp_path, {
        "kern.py": ("import jax\ndef _f(x):\n    return x\n"
                    "f_jit = jax.jit(_f)\n"),
        "drv.py": ("import time\nfrom . import kern\n"
                   "def bench(x):\n"
                   "    t0 = time.perf_counter()\n"
                   "    y = kern.f_jit(x)\n"
                   "    # csa: ignore[CSA1001] -- launch-overhead probe\n"
                   "    dt = time.perf_counter() - t0\n"
                   "    return y, dt\n"),
    })
    report = analyze_paths([str(root)])
    assert [f for f in report.findings if f.rule == "CSA1001"] == []
    assert [f.rule for f in report.suppressed] == ["CSA1001"]


def test_honest_timing_suppression(tmp_path):
    src = _JIT_PREAMBLE + (
        "def bench(x):\n"
        "    t0 = time.perf_counter()\n"
        "    y = f_jit(x)\n"
        "    # csa: ignore[CSA1001] -- dispatch-only timing on purpose\n"
        "    dt = time.perf_counter() - t0\n"
        "    return y, dt\n"
    )
    path = tmp_path / "s.py"
    path.write_text(src)
    report = analyze_paths([str(path)])
    assert report.findings == []
    assert [f.rule for f in report.suppressed] == ["CSA1001"]


# ---------------------------------------------------------------------------
# CSA8xx spec drift (differential vs a reference tree)
# ---------------------------------------------------------------------------

def _mini_reference(tmp_path):
    ref = tmp_path / "reference"
    presets = ref / "configs" / "constant_presets"
    presets.mkdir(parents=True)
    (presets / "minimal.yaml").write_text(
        "# comment\n"
        "SHUFFLE_ROUND_COUNT: 10\n"
        "MAX_EFFECTIVE_BALANCE: 32000000000\n"
        "NEW_CONST: 7\n"
        "GENESIS_FORK_VERSION: '0x00000000'\n"
    )
    pyspec = ref / "test_libs" / "pyspec" / "eth2spec"
    pyspec.mkdir(parents=True)
    (pyspec / "spec.py").write_text(
        "def get_current_epoch(state):\n    return state.slot\n"
        "def integer_squareroot(n):\n    return n\n"
        "def slot_to_epoch(slot):\n    return slot\n"
        "def _private_helper(x):\n    return x\n"
    )
    return ref


def _mini_port(tmp_path, helpers_src):
    port = tmp_path / "port"
    tree = port / "models" / "phase0"
    tree.mkdir(parents=True)
    for d in (port, port / "models", tree):
        (d / "__init__.py").write_text("")
    (tree / "spec.py").write_text("")
    (tree / "helpers.py").write_text(helpers_src)
    cfg = tmp_path / "portcfg"
    cfg.mkdir()
    (cfg / "minimal.yaml").write_text(
        "SHUFFLE_ROUND_COUNT: 90\n"                # drifted value
        "MAX_EFFECTIVE_BALANCE: 32000000000\n"
        "GENESIS_FORK_VERSION: '0x00000000'\n"     # quoting-insensitive
    )
    return port, cfg


def test_spec_drift_reports_constant_function_and_signature_drift(tmp_path):
    ref = _mini_reference(tmp_path)
    port, cfg = _mini_port(tmp_path, (
        "def get_current_epoch(spec, state):\n    return state.slot\n"
        "def integer_squareroot(spec, value):\n    return value\n"
    ))
    report = analyze_paths([str(port)], options={
        "reference_root": str(ref), "drift_port_configs": str(cfg)})
    got = rule_ids(report.findings)
    # SHUFFLE_ROUND_COUNT drifted, NEW_CONST missing, slot_to_epoch
    # missing, integer_squareroot renamed its parameter
    assert got == ["CSA801", "CSA802", "CSA803", "CSA804"]
    by_rule = {f.rule: f for f in report.findings}
    assert "SHUFFLE_ROUND_COUNT" in by_rule["CSA801"].message
    assert "NEW_CONST" in by_rule["CSA802"].message
    assert "slot_to_epoch" in by_rule["CSA803"].message
    assert "integer_squareroot" in by_rule["CSA804"].message


def test_spec_drift_negative_conforming_port(tmp_path):
    ref = _mini_reference(tmp_path)
    port, cfg = _mini_port(tmp_path, (
        "def get_current_epoch(spec, state):\n    return state.slot\n"
        "def integer_squareroot(spec, n):\n    return n\n"
        "def slot_to_epoch(spec, slot):\n    return slot\n"
        "def extra_port_only_fn(spec, x):\n    return x\n"
    ))
    (cfg / "minimal.yaml").write_text(
        "SHUFFLE_ROUND_COUNT: 10\n"
        "MAX_EFFECTIVE_BALANCE: 32000000000\n"
        "NEW_CONST: 7\n"
        "GENESIS_FORK_VERSION: 0x00000000\n"
    )
    report = analyze_paths([str(port)], options={
        "reference_root": str(ref), "drift_port_configs": str(cfg)})
    assert report.findings == []


def test_spec_drift_skips_with_notice_when_reference_absent(tmp_path):
    port, cfg = _mini_port(tmp_path, "def f(spec, x):\n    return x\n")
    missing = tmp_path / "no-such-reference"
    report = analyze_paths([str(port)], options={
        "reference_root": str(missing), "drift_port_configs": str(cfg)})
    assert report.findings == []
    assert any("spec-drift" in n and "skipped" in n for n in report.notices)


def test_spec_drift_baseline_entries_not_stale_when_pass_skipped(tmp_path):
    """A deliberate-divergence CSA8xx baseline entry recorded where the
    reference exists must not read as stale on machines without it —
    the skipped pass makes the entry unverifiable, not fixed."""
    ref = _mini_reference(tmp_path)
    port, cfg = _mini_port(tmp_path, (
        "def get_current_epoch(spec, state):\n    return state.slot\n"
        "def integer_squareroot(spec, n):\n    return n\n"
        "def slot_to_epoch(spec, slot):\n    return slot\n"))
    opts = {"reference_root": str(ref), "drift_port_configs": str(cfg)}
    with_ref = analyze_paths([str(port)], options=opts)
    assert "CSA801" in rule_ids(with_ref.findings)
    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), with_ref.findings)
    baseline = load_baseline(str(bl_path))
    # with the reference: baselined, nothing stale
    again = analyze_paths([str(port)], baseline, options=opts)
    assert again.findings == [] and again.stale_baseline == []
    # without it: the pass skips, the entries stay exempt (CI machines)
    without = analyze_paths([str(port)], baseline, options={
        "reference_root": str(tmp_path / "gone"),
        "drift_port_configs": str(cfg)})
    assert without.findings == [] and without.stale_baseline == []


def test_callgraph_ambiguous_module_names_both_scanned(tmp_path):
    """Two targets mapping to one dotted name must both be analyzed,
    in either order (a silent drop was order-dependent)."""
    a = tmp_path / "a"
    b = tmp_path / "b"
    a.mkdir()
    b.mkdir()
    (a / "util.py").write_text(
        "from jax.sharding import Mesh\n"
        "mesh = Mesh(None, axis_names=('v',))\n")
    (b / "util.py").write_text(
        "import jax\ndef f(x):\n    return jax.lax.psum(x, 'v')\n")
    for targets in ([str(a / "util.py"), str(b / "util.py")],
                    [str(b / "util.py"), str(a / "util.py")]):
        report = analyze_paths(targets)
        assert report.findings == []       # a's mesh axes always visible
        assert any("ambiguous" in n for n in report.notices)


def test_pallas_blockspec_names_resolve_per_function(tmp_path):
    # two functions reusing the name `spec` for different-rank BlockSpecs
    # must each be checked against their OWN spec
    src = (
        "from jax.experimental import pallas as pl\n"
        "def k2(x_ref, o_ref):\n"
        "    o_ref[0, :] = x_ref[0, :]\n"
        "def k1(x_ref, o_ref):\n"
        "    o_ref[0] = x_ref[0]\n"
        "def f(x):\n"
        "    spec = pl.BlockSpec((8, 128), lambda i: (0, i))\n"
        "    return pl.pallas_call(k2, grid=(4,), in_specs=[spec],\n"
        "        out_specs=spec, interpret=True)(x)\n"
        "def g(x):\n"
        "    spec = pl.BlockSpec((128,), lambda i: (i,))\n"
        "    return pl.pallas_call(k1, grid=(4,), in_specs=[spec],\n"
        "        out_specs=spec, interpret=True)(x)\n"
    )
    assert findings_for(tmp_path, src) == []


# ---------------------------------------------------------------------------
# framework: baseline ratchet + CLI + repo green
# ---------------------------------------------------------------------------

def test_baseline_roundtrip_and_stale_detection(tmp_path):
    path = tmp_path / "s.py"
    path.write_text(PRE_FIX_RESIDENT_SNIPPET)
    report = analyze_paths([str(path)])
    assert len(report.findings) == 2

    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), report.findings)
    baseline = load_baseline(str(bl_path))
    ratcheted = analyze_paths([str(path)], baseline)
    assert ratcheted.findings == []
    assert len(ratcheted.baselined) == 2
    assert ratcheted.stale_baseline == []

    # fix one of the two: its baseline entry goes stale, run stays green
    path.write_text(PRE_FIX_RESIDENT_SNIPPET.replace(
        "return int(mirrors['effective_balance'][index])",
        "return int(state.validator_registry[index].effective_balance)"))
    after_fix = analyze_paths([str(path)], baseline)
    assert after_fix.findings == []
    assert len(after_fix.stale_baseline) == 1


def test_update_baseline_preserves_live_entries_and_reasons(tmp_path):
    """Refreshing the baseline must keep still-live entries (with their
    hand-written reasons), not reset the file to just-new findings."""
    path = tmp_path / "s.py"
    path.write_text(PRE_FIX_RESIDENT_SNIPPET)
    first = analyze_paths([str(path)])
    live_fp = first.findings[0].fingerprint()

    bl_path = tmp_path / "baseline.json"
    write_baseline(str(bl_path), [first.findings[0]])
    # hand-edit the reason, as the README instructs
    data = json.loads(bl_path.read_text())
    data["entries"][0]["reason"] = "deliberate: documented at the site"
    bl_path.write_text(json.dumps(data))

    baseline = load_baseline(str(bl_path))
    report = analyze_paths([str(path)], baseline)
    assert len(report.findings) == 1 and len(report.baselined) == 1
    # the --update-baseline merge: actionable + still-baselined, reasons
    # carried over for entries that were already in the file
    write_baseline(str(bl_path), report.findings + report.baselined,
                   prior=baseline)
    merged = json.loads(bl_path.read_text())["entries"]
    assert len(merged) == 2
    by_fp = {e["fingerprint"]: e["reason"] for e in merged}
    assert by_fp[live_fp] == "deliberate: documented at the site"
    refreshed = analyze_paths([str(path)], load_baseline(str(bl_path)))
    assert refreshed.findings == [] and refreshed.stale_baseline == []


def _run_cli(args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "tools.analysis", *args],
        cwd=cwd, capture_output=True, text=True)


def test_cli_exit_codes_and_json(tmp_path):
    dirty = tmp_path / "dirty.py"
    dirty.write_text(PRE_FIX_RESIDENT_SNIPPET)
    clean = tmp_path / "clean.py"
    clean.write_text("def f(state):\n    return state.slot\n")
    out_json = tmp_path / "analysis.json"

    proc = _run_cli([str(dirty), "--json", str(out_json)])
    assert proc.returncode == 1
    assert "CSA401" in proc.stdout
    data = json.loads(out_json.read_text())
    assert [f["rule"] for f in data["findings"]] == ["CSA401", "CSA401"]

    assert _run_cli([str(clean)]).returncode == 0
    assert _run_cli(["--list-rules"]).returncode == 0


@pytest.mark.parametrize("rule_class,snippet", [
    ("CSA101", "import jax\n@jax.jit\ndef f(x):\n    if x > 0:\n"
               "        return x\n    return -x\n"),
    ("CSA201", "import jax\nimport jax.numpy as jnp\n@jax.jit\n"
               "def f(x):\n    return x + jnp.zeros(3)\n"),
    ("CSA301", "import jax, time\n@jax.jit\ndef f(x):\n"
               "    return x + time.time()\n"),
    ("CSA401", "def f(state):\n    return 1\n"),
    ("CSA501", "import jax\ndef f(x):\n    return x\n"
               "f_jit = jax.jit(f)\ny = f_jit(3)\n"),
    ("CSA601", "import jax\ndef f(x):\n"
               "    return jax.lax.psum(x, 'ghost')\n"),
    ("CSA701", "from jax.experimental import pallas as pl\n"
               "def k(x_ref):\n    x_ref[0] = 0\n"
               "def run(x):\n"
               "    return pl.pallas_call(k, grid=(2, 2),\n"
               "        out_specs=pl.BlockSpec((8,), lambda i: (i,)),\n"
               "        interpret=True)(x)\n"),
    ("CSA901", "def f(a, b, c):\n"
               "    return (fq_mul_wide(a, b) + fq_mul_wide(a, c)\n"
               "            + fq_mul_wide(b, c))\n"),
    ("CSA1001", "import jax, time\ndef f(x):\n    return x\n"
                "f_jit = jax.jit(f)\n"
                "def bench(x):\n"
                "    t0 = time.perf_counter()\n"
                "    y = f_jit(x)\n"
                "    return time.perf_counter() - t0\n"),
])
def test_cli_nonzero_per_rule_class(tmp_path, rule_class, snippet):
    """Acceptance: injected fixtures for each per-module rule class exit
    non-zero through the real CLI (CSA8xx is differential — covered by
    the spec-drift fixtures above)."""
    path = tmp_path / "inject.py"
    path.write_text(snippet)
    proc = _run_cli([str(path)])
    assert proc.returncode == 1
    assert rule_class in proc.stdout


def test_repo_is_analysis_clean():
    """The `make analyze` guarantee, asserted in-process: the shipped tree
    has no actionable findings over the committed baseline, the baseline
    carries no stale entries (any rule family, including CSA6xx-8xx —
    the ratchet only shrinks), and every baseline entry names a rule the
    analyzer still registers."""
    baseline = load_baseline(str(REPO / "tools" / "analysis" / "baseline.json"))
    report = analyze_paths(
        [str(REPO / "consensus_specs_tpu"), str(REPO / "bench.py"),
         str(REPO / "__graft_entry__.py")], baseline)
    assert report.findings == []
    assert report.stale_baseline == []
    for fingerprint in baseline:
        rule = fingerprint.split("::")[1]
        assert rule in RULES, f"baseline entry for unknown rule {rule}"
    # the reference tree is not shipped with the repo: the differential
    # pass must announce it skipped rather than silently pass
    if not (Path("/root/reference").is_dir()
            or "CSTPU_REFERENCE_ROOT" in __import__("os").environ):
        assert any("spec-drift" in n for n in report.notices)


def test_rule_catalog_documented():
    """Every registered rule appears in tools/analysis/README.md."""
    readme = (REPO / "tools" / "analysis" / "README.md").read_text()
    for rule_id in RULES:
        assert rule_id in readme, f"{rule_id} missing from README"
