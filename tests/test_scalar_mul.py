"""Windowed signed-digit scalar mul (ops/scalar_mul.py) vs the
double-and-add reference vs the host bignum oracle.

Three layers: host recoding algebra (exact int arithmetic), device
bit-exactness across backends/widths/batch shapes (including the pow2 pad
and point-at-infinity inputs), and the sequential-add cost model — counted
op-by-op on an unrolled eager evaluation, the way
tests/test_incremental_merkle.py asserts pair-lane counts."""
import random

import numpy as np
import pytest

import jax.numpy as jnp

from consensus_specs_tpu.crypto import bls12_381 as gt
from consensus_specs_tpu.ops import bls_jax as BJ
from consensus_specs_tpu.ops import fq as F
from consensus_specs_tpu.ops import fq_tower as T
from consensus_specs_tpu.ops import scalar_mul as SM

rng = random.Random(0x5CA1A)

SCALARS = [0, 1, 2, gt.r - 1, rng.randrange(1 << 255, 1 << 256)]


def g1_val(x, y, inf_flag, i=()):
    if bool(np.asarray(inf_flag)[i] if i != () else np.asarray(inf_flag)):
        return None
    return (F.from_mont(np.asarray(x)[i]), F.from_mont(np.asarray(y)[i]))


def g2_val(x, y, inf_flag, i=()):
    if bool(np.asarray(inf_flag)[i] if i != () else np.asarray(inf_flag)):
        return None
    return (T.fq2_from_limbs(np.asarray(x)[i]),
            T.fq2_from_limbs(np.asarray(y)[i]))


# ---------------------------------------------------------------------------
# Host recoding
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("w", [2, 3, 4, 5])
def test_recode_digit_properties(w):
    """Digits odd, in-range, fixed count, top digit +1; the value identity
    itself is asserted inside recode_signed_windows in exact arithmetic."""
    for k in SCALARS + [rng.randrange(0, 1 << 256) for _ in range(8)]:
        rec = SM.recode_signed_windows(k, 256, w)
        m = SM.n_windows(256, w)
        assert rec.idx.shape == rec.sign.shape == (m,)
        assert rec.correction == (k % 2 == 0)
        assert rec.idx.min() >= 0 and rec.idx.max() < 2 ** (w - 1)
        assert set(np.unique(rec.sign)) <= {-1, 1}
        assert rec.idx[0] == 0 and rec.sign[0] == 1   # fixed-length tail
        digits = (2 * rec.idx.astype(int) + 1) * rec.sign
        value = 0
        for d in digits:
            value = (value << w) + int(d)
        assert value - (1 if rec.correction else 0) == k


def test_recode_memoized_and_readonly():
    a = SM.recode_signed_windows(12345, 256, 4)
    b = SM.recode_signed_windows(12345, 256, 4)
    assert a is b
    with pytest.raises(ValueError):
        a.idx[0] = 3
    bits = SM.scalar_bits(12345, 256)
    assert SM.scalar_bits(12345, 256) is bits
    with pytest.raises(ValueError):
        bits[0] = 1
    assert np.array_equal(
        bits, [(12345 >> (255 - i)) & 1 for i in range(256)])


# ---------------------------------------------------------------------------
# Device bit-exactness: windowed vs double-and-add vs host bignum
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("w", [2, 4, 5])
def test_windowed_g1_matches_oracle(w):
    """All SCALARS at one batch shape per width (one compile per w; the
    width sweep 2–5 splits across G1 here and G2 below, every width
    differential-tested against the double-and-add path and the bignum
    oracle)."""
    pts = [gt.ec_mul(gt.G1_GEN, 3 * i + 2) for i in range(2)]
    arr = np.stack([BJ.g1_to_limbs(p) for p in pts])
    x, y = jnp.asarray(arr[:, 0]), jnp.asarray(arr[:, 1])
    for k in SCALARS:
        rec = SM.recode_signed_windows(k, 256, w)
        gx, gy, ginf = BJ._g1_scalar_mul_win(
            x, y, jnp.asarray(rec.idx), jnp.asarray(rec.sign),
            jnp.asarray(np.bool_(rec.correction)), w=w)
        da_x, da_y, da_inf = BJ._g1_scalar_mul(
            x, y, jnp.asarray(SM.scalar_bits(k, 256)))
        for i, p in enumerate(pts):
            want = gt.ec_mul(p, k)
            assert g1_val(gx, gy, ginf, i) == want, (k, w, i)
            assert g1_val(da_x, da_y, da_inf, i) == want, (k, i)


@pytest.mark.parametrize("w", [3])
def test_windowed_g2_matches_oracle(w):
    pts = [gt.ec_mul(gt.G2_GEN, 5 * i + 7) for i in range(2)]
    arr = np.stack([BJ.g2_to_limbs(p) for p in pts])
    x, y = jnp.asarray(arr[:, 0]), jnp.asarray(arr[:, 1])
    for k in SCALARS:
        rec = SM.recode_signed_windows(k, 256, w)
        gx, gy, ginf = BJ._g2_scalar_mul_win(
            x, y, jnp.asarray(rec.idx), jnp.asarray(rec.sign),
            jnp.asarray(np.bool_(rec.correction)), w=w)
        for i, p in enumerate(pts):
            assert g2_val(gx, gy, ginf, i) == gt.ec_mul(p, k), (k, w, i)


def test_windowed_cofactor_fixed_scalar():
    """The ~509-bit fixed-scalar path: module-load digits, G2 batch (8
    points — the same program shape hash_to_g2_batch's pow2 pad hits, so
    the compile is shared with those tests)."""
    nbits = gt.G2_COFACTOR.bit_length()
    pts = [gt.hash_to_g2_candidate(bytes([m]) * 32, 1) for m in range(1, 9)]
    arr = np.stack([BJ.g2_to_limbs(p) for p in pts])
    x, y, inf = BJ.g2_scalar_mul(jnp.asarray(arr[:, 0]),
                                 jnp.asarray(arr[:, 1]),
                                 gt.G2_COFACTOR, nbits=nbits)
    for i, p in enumerate(pts):
        assert g2_val(x, y, inf, i) == gt.ec_mul(p, gt.G2_COFACTOR), i


def test_point_at_infinity_inputs():
    """Batch mixing finite points with flagged infinity inputs: infinity
    propagates through table build + loop on BOTH backends; finite lanes
    are unaffected. 24-bit scalar: the windowed side runs eagerly
    unrolled, the double-and-add side compiles one small program."""
    nbits, w = 24, 3
    k = rng.randrange(1, 1 << nbits)
    p = gt.ec_mul(gt.G1_GEN, 5)
    arr = np.stack([BJ.g1_to_limbs(p), BJ.g1_to_limbs(p)])
    x, y = jnp.asarray(arr[:, 0]), jnp.asarray(arr[:, 1])
    inf = jnp.asarray(np.array([False, True]))
    rec = SM.recode_signed_windows(k, nbits, w)
    win = SM.windowed_scalar_mul(
        BJ.G1_OPS, (x, y), rec.idx, rec.sign, rec.correction, w=w,
        inf=inf, unroll=True)
    da = SM.jac_scalar_mul(BJ.G1_OPS, (x, y),
                           jnp.asarray(SM.scalar_bits(k, nbits)), inf=inf)
    for pt in (win, da):
        ax, ay, ainf = BJ.jac_to_affine(BJ.G1_OPS, pt)
        assert g1_val(ax, ay, ainf, 0) == gt.ec_mul(p, k)
        assert g1_val(ax, ay, ainf, 1) is None   # O stays O


def test_batch_crossing_pow2_pad():
    """hash_to_g2_batch pads 5 -> 8: every unpadded lane must still equal
    the host oracle, on both backends."""
    reqs = [(bytes([m]) * 32, 3) for m in range(5)]
    want = [gt.hash_to_g2(mh, d) for mh, d in reqs]
    for backend in ("window", "double_add"):
        SM.set_scalar_mul_backend(backend)
        try:
            assert BJ.hash_to_g2_batch(reqs) == want, backend
        finally:
            SM.set_scalar_mul_backend(None)


def test_backend_knob():
    """Env knob + override semantics mirror CSTPU_MERKLE_BACKEND."""
    assert SM.scalar_mul_backend_name() == "window"   # default
    SM.set_scalar_mul_backend("double_add")
    try:
        assert SM.scalar_mul_backend_name() == "double_add"
    finally:
        SM.set_scalar_mul_backend(None)
    with pytest.raises(AssertionError):
        SM.set_scalar_mul_backend("bogus")


def test_backend_env_validation(monkeypatch):
    monkeypatch.setenv("CSTPU_SCALAR_MUL", "nope")
    with pytest.raises(ValueError):
        SM.scalar_mul_backend_name()
    monkeypatch.setenv("CSTPU_SCALAR_MUL", "double_add")
    assert SM.scalar_mul_backend_name() == "double_add"
    monkeypatch.setenv("CSTPU_SCALAR_WINDOW", "0")
    with pytest.raises(ValueError):
        SM.scalar_mul_window()
    monkeypatch.setenv("CSTPU_SCALAR_WINDOW", "5")
    assert SM.scalar_mul_window() == 5


def test_sign_privtopub_parity_both_backends():
    """The spec-facing surface stays byte-identical to the bignum oracle
    under either scalar-mul backend."""
    py, jx = gt.PythonBackend(), BJ.JaxBackend()
    msg = b"\x5a" * 32
    for backend in ("window", "double_add"):
        SM.set_scalar_mul_backend(backend)
        try:
            assert jx.privtopub(0xBEEF) == gt.privtopub(0xBEEF), backend
            assert jx.sign(msg, 77, 2) == py.sign(msg, 77, 2), backend
        finally:
            SM.set_scalar_mul_backend(None)


# ---------------------------------------------------------------------------
# Sequential-add cost model (the acceptance bound)
# ---------------------------------------------------------------------------
# The jac_add/jac_double counter this section hand-rolled through PR 8
# now lives in the shared tracer library (tools/analysis/trace/tracer.py
# `counted_point_ops`) and the count itself is a committed kernel
# contract (ops.scalar_mul.windowed_chain) — the test asserts the chain
# THROUGH the contract engine, so the op model the ratchet enforces and
# the one the tests pin are the same object.


def test_sequential_add_count_measured_through_contract():
    """The windowed_chain contract: an unrolled eager windowed evaluation
    counted op-by-op (every call one dependent step at batch ()), pinned
    exactly to the analytic model bench.py reports — measured by the
    contract engine, value-checked against the host oracle here."""
    from tools.analysis.trace import engine as trace_engine
    contracts = [c for c in trace_engine.discover()
                 if c["name"] == "ops.scalar_mul.windowed_chain"]
    assert len(contracts) == 1
    report = trace_engine.run_contracts(contracts)
    assert report.findings == [], [f.message for f in report.findings]
    (res,) = report.results
    nbits, w = 24, 3
    assert res.measured["seq_adds"] == SM.sequential_adds("window", nbits, w)
    assert res.measured["seq_doubles"] == SM.sequential_doubles(
        "window", nbits, w)
    # the shared counter itself, exercised directly at a tiny shape and
    # value-checked against the bignum oracle (the big-shape eager run
    # already happened once, inside the engine)
    from tools.analysis.trace import tracer
    nbits, w = 8, 2
    k = 0b10110100   # even: exercises the fixup add
    rec = SM.recode_signed_windows(k, nbits, w)
    arr = BJ.g1_to_limbs(gt.ec_mul(gt.G1_GEN, 9))
    with tracer.counted_point_ops() as counts:
        pt = SM.windowed_scalar_mul(
            BJ.G1_OPS, (jnp.asarray(arr[0]), jnp.asarray(arr[1])),
            rec.idx, rec.sign, rec.correction, w=w, unroll=True)
    assert counts["jac_add"] == SM.sequential_adds("window", nbits, w)
    # every jac_add internally evaluates one jac_double (the branch-free
    # P1 == P2 fallback), so the raw double count carries one extra per add
    assert (counts["jac_double"] - counts["jac_add"]
            == SM.sequential_doubles("window", nbits, w))
    x, y, inf = BJ.jac_to_affine(BJ.G1_OPS, pt)
    assert g1_val(x, y, inf) == gt.ec_mul(gt.ec_mul(gt.G1_GEN, 9), k)


def test_sequential_add_bound():
    """The acceptance criterion: ≥2.5x fewer dependent adds than
    double-and-add on BOTH hot shapes at the default width."""
    w = SM.scalar_mul_window()
    for nbits in (256, gt.G2_COFACTOR.bit_length()):
        da = SM.sequential_adds("double_add", nbits)
        win = SM.sequential_adds("window", nbits, w)
        assert da >= 2.5 * win, (nbits, da, win)
        # doublings must not regress past the window-rounding slack
        assert SM.sequential_doubles("window", nbits, w) <= nbits + w
