"""tools/analysis/lifetime — the buffer-lifetime tier (CSA1501-1505).

Fixture snippets per rule (positive, negative, suppressed), the
interprocedural paths (from-imports, call summaries, factories,
dispatch wrappers), the PR 3 cols-reuse regression and the firehose
double-in-flight shape, the baseline loosen/tighten/missing/stale
workflow, and the multi-tier CLI contract (merged --json, max exit).

The prover itself is pure AST interpretation (lower=False throughout);
only the platform_donated_jit runtime checks import jax.
"""
import json
import subprocess
import sys
from pathlib import Path

from tools.analysis.core import load_baseline, write_baseline
from tools.analysis.lifetime.engine import run_lifetime

REPO = Path(__file__).resolve().parent.parent

DONOR = (
    "import jax\n"
    "from functools import partial\n"
    "@partial(jax.jit, donate_argnums=(0,))\n"
    "def consume(x, y):\n"
    "    return x + y\n"
)


def report_for(tmp_path, source, name="snippet.py"):
    path = tmp_path / name
    path.write_text(source)
    return run_lifetime(targets=[str(path)], baseline={}, lower=False)


def rules_of(report):
    return sorted(f.rule for f in report.findings)


def only(report, rule):
    return [f for f in report.findings if f.rule == rule]


# ---------------------------------------------------------------------------
# CSA1501 use-after-donate
# ---------------------------------------------------------------------------

def test_csa1501_use_after_donate_trips(tmp_path):
    src = DONOR + (
        "def step(cols, y):\n"
        "    out = consume(cols, y)\n"
        "    return cols + out\n"
    )
    hits = only(report_for(tmp_path, src), "CSA1501")
    assert len(hits) == 1 and "`cols`" in hits[0].message


def test_csa1501_rebind_chaining_is_clean(tmp_path):
    src = DONOR + (
        "def step(cols, y):\n"
        "    cols = consume(cols, y)\n"
        "    return cols + 1\n"
    )
    assert not only(report_for(tmp_path, src), "CSA1501")


def test_csa1501_metadata_reads_stay_legal(tmp_path):
    # jax keeps the aval on a deleted array: .shape/.dtype reads are fine
    src = DONOR + (
        "def step(cols, y):\n"
        "    out = consume(cols, y)\n"
        "    n = cols.shape[0] + cols.dtype.itemsize\n"
        "    return out, n\n"
    )
    assert not only(report_for(tmp_path, src), "CSA1501")


def test_csa1501_field_read_through_donated_root_trips(tmp_path):
    # donating `cols` kills `cols.balance` too (prefix coverage)
    src = DONOR + (
        "def step(cols, y):\n"
        "    out = consume(cols, y)\n"
        "    z = cols.balance + out\n"
        "    return z\n"
    )
    assert len(only(report_for(tmp_path, src), "CSA1501")) == 1


def test_csa1501_crosses_from_import(tmp_path):
    (tmp_path / "kern.py").write_text(DONOR)
    (tmp_path / "caller.py").write_text(
        "from kern import consume\n"
        "def step(cols, y):\n"
        "    out = consume(cols, y)\n"
        "    return cols + out\n"
    )
    report = run_lifetime(targets=[str(tmp_path)], baseline={},
                          lower=False)
    hits = only(report, "CSA1501")
    assert len(hits) == 1 and hits[0].path.endswith("caller.py")


def test_csa1501_call_summary_propagates(tmp_path):
    # a plain helper that forwards into the donor carries its donation
    src = DONOR + (
        "def forward(buf, y):\n"
        "    return consume(buf, y)\n"
        "def step(cols, y):\n"
        "    out = forward(cols, y)\n"
        "    return cols + out\n"
    )
    assert len(only(report_for(tmp_path, src), "CSA1501")) == 1


def test_csa1501_factory_return_summary(tmp_path):
    # `fn = make(); fn(cols, y)` resolves through the return summary
    src = DONOR + (
        "def make():\n"
        "    return consume\n"
        "def step(cols, y):\n"
        "    fn = make()\n"
        "    out = fn(cols, y)\n"
        "    return cols + out\n"
    )
    assert len(only(report_for(tmp_path, src), "CSA1501")) == 1


def test_csa1501_suppression_honored(tmp_path):
    src = DONOR + (
        "def step(cols, y):\n"
        "    out = consume(cols, y)\n"
        "    return cols + out  # csa: ignore[CSA1501] proven host copy\n"
    )
    report = report_for(tmp_path, src)
    assert not only(report, "CSA1501")
    assert any(f.rule == "CSA1501" for f in report.suppressed)


# ---------------------------------------------------------------------------
# CSA1502 donated-value escape
# ---------------------------------------------------------------------------

def test_csa1502_attribute_escape_trips(tmp_path):
    src = DONOR + (
        "class Holder:\n"
        "    def step(self, y):\n"
        "        consume(self._ring, y)\n"
        "        return y\n"
    )
    hits = only(report_for(tmp_path, src), "CSA1502")
    assert len(hits) == 1 and "self._ring" in hits[0].message


def test_csa1502_same_statement_rebind_is_clean(tmp_path):
    # the firehose idiom: the attribute takes the call's output
    src = DONOR + (
        "class Holder:\n"
        "    def step(self, y):\n"
        "        self._ring = consume(self._ring, y)\n"
        "        return y\n"
    )
    assert not only(report_for(tmp_path, src), "CSA1502")


def test_csa1502_return_of_donated_trips(tmp_path):
    src = DONOR + (
        "def step(cols, y):\n"
        "    out = consume(cols, y)\n"
        "    return cols\n"
    )
    hits = only(report_for(tmp_path, src), "CSA1502")
    assert len(hits) == 1 and "escapes" in hits[0].message


def test_csa1502_return_dispatch_handoff_is_clean(tmp_path):
    # `return dispatch(..., self.cols, ...)` hands ownership up — the
    # documented chaining convention, not an escape
    src = DONOR + (
        "class Holder:\n"
        "    def step(self, y):\n"
        "        return dispatch('k', consume, self.cols, y)\n"
    )
    assert not report_for(tmp_path, src).findings or \
        not only(report_for(tmp_path, src), "CSA1502")


def test_csa1502_local_subscript_donation_is_not_an_escape(tmp_path):
    # donating `single[0]` as its final use: the tuple is frame-local,
    # the stale handle dies here (the test_multichip shape)
    src = DONOR + (
        "def step(cols, y):\n"
        "    single = (consume(cols, y), y)\n"
        "    out = consume(single[0], single[1])\n"
        "    return out\n"
    )
    assert not only(report_for(tmp_path, src), "CSA1502")


# ---------------------------------------------------------------------------
# CSA1503 double-in-flight
# ---------------------------------------------------------------------------

def test_csa1503_double_in_flight_trips(tmp_path):
    src = DONOR + (
        "def overlap(buf, y):\n"
        "    a = dispatch('k1', consume, buf, y)\n"
        "    b = dispatch('k2', consume, buf, y)\n"
        "    return a, b\n"
    )
    hits = only(report_for(tmp_path, src), "CSA1503")
    assert len(hits) == 1 and "in flight" in hits[0].message


def test_csa1503_materialization_fence_clears(tmp_path):
    src = DONOR + (
        "def fenced(buf, y):\n"
        "    a = dispatch('k1', consume, buf, y)\n"
        "    a.block_until_ready()\n"
        "    b = dispatch('k2', consume, buf, y)\n"
        "    return a, b\n"
    )
    report = report_for(tmp_path, src)
    assert not only(report, "CSA1503")


def test_csa1503_double_buffer_rotation_is_clean(tmp_path):
    # each launch owns its own buffer — the firehose rotation
    src = DONOR + (
        "def rotate(front, back, y):\n"
        "    a = dispatch('k1', consume, front, y)\n"
        "    b = dispatch('k2', consume, back, y)\n"
        "    return a, b\n"
    )
    assert not only(report_for(tmp_path, src), "CSA1503")


def test_csa1503_firehose_ring_shape(tmp_path):
    # the PR 15 hazard: one ring reaching two wrapper dispatches before
    # any materialization point, attribute-rooted
    src = DONOR + (
        "class Firehose:\n"
        "    def flush_twice(self, y):\n"
        "        self._ring = dispatch('a', consume, self._ring, y)\n"
        "        bad = dispatch('b', consume, self._ring, y)\n"
        "        return bad\n"
    )
    # the rebound ring is LIVE again after the first statement, so the
    # clean rotation passes; re-donating the SAME pre-rebind handle trips
    assert not only(report_for(tmp_path, src), "CSA1503")
    src_bad = DONOR + (
        "class Firehose:\n"
        "    def flush_twice(self, y):\n"
        "        a = dispatch('a', consume, self._ring, y)\n"
        "        b = dispatch('b', consume, self._ring, y)\n"
        "        return a, b\n"
    )
    assert len(only(report_for(tmp_path, src_bad), "CSA1503")) == 1


# ---------------------------------------------------------------------------
# CSA1504 missing platform guard
# ---------------------------------------------------------------------------

def test_csa1504_unguarded_jit_warns(tmp_path):
    hits = only(report_for(tmp_path, DONOR), "CSA1504")
    assert len(hits) == 1 and "platform guard" in hits[0].message


def test_csa1504_platform_helper_is_blessed(tmp_path):
    src = (
        "from consensus_specs_tpu.utils.donation import "
        "platform_donated_jit\n"
        "def _k(x, y):\n"
        "    return x + y\n"
        "_k_pd = platform_donated_jit(_k, donate_argnums=(0,))\n"
    )
    assert not only(report_for(tmp_path, src), "CSA1504")


def test_csa1504_conditional_donate_is_guarded(tmp_path):
    src = (
        "import jax\n"
        "def _k(x, y):\n"
        "    return x + y\n"
        "_kj = jax.jit(_k, donate_argnums=(0,) "
        "if jax.default_backend() != 'cpu' else ())\n"
    )
    assert not only(report_for(tmp_path, src), "CSA1504")


# ---------------------------------------------------------------------------
# CSA1505 redundant copy
# ---------------------------------------------------------------------------

def test_csa1505_copy_into_undonated_position_notices(tmp_path):
    src = DONOR + (
        "def step(cols, y):\n"
        "    out = consume(cols, y.copy())\n"
        "    return out\n"
    )
    hits = only(report_for(tmp_path, src), "CSA1505")
    assert len(hits) == 1 and "pure overhead" in hits[0].message


def test_csa1505_copy_into_donated_position_is_justified(tmp_path):
    src = DONOR + (
        "def step(cols, y):\n"
        "    out = consume(cols.copy(), y)\n"
        "    return out, cols\n"
    )
    assert not only(report_for(tmp_path, src), "CSA1505")


# ---------------------------------------------------------------------------
# regressions: the PR 3 epoch shape, the resident recovery loop
# ---------------------------------------------------------------------------

def test_pr3_cols_reuse_regression(tmp_path):
    # the original PR 3 bug shape: a factory hands back the donated
    # epoch program, guarded_dispatch launches it, and the caller then
    # touches the pre-donation cols
    src = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))\n"
        "def _epoch(cfg, cols, scal):\n"
        "    return cols, scal\n"
        "def _epoch_jit():\n"
        "    return _epoch\n"
        "def boundary(cfg, cols, scal):\n"
        "    out = guarded_dispatch(('k',), _epoch_jit(), cfg, cols, "
        "scal)\n"
        "    root = cols.balance\n"
        "    return out, root\n"
    )
    hits = only(report_for(tmp_path, src), "CSA1501")
    assert len(hits) == 1 and "cols.balance" in hits[0].message


def test_pr3_chained_rebind_is_clean(tmp_path):
    src = (
        "import jax\n"
        "from functools import partial\n"
        "@partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))\n"
        "def _epoch(cfg, cols, scal):\n"
        "    return cols, scal\n"
        "def boundary(cfg, cols, scal):\n"
        "    cols, scal = _epoch(cfg, cols, scal)\n"
        "    return cols.balance\n"
    )
    assert not only(report_for(tmp_path, src), "CSA1501")


def test_resident_recovery_loop_platform_guard_absolves(tmp_path):
    # the resident retry shape: a conditional donor dispatched inside
    # try/while; the except arm raises OUT of the donating world before
    # the loop retries, so the CPU-world retry reads are legal
    src = (
        "import jax\n"
        "from consensus_specs_tpu.utils.donation import "
        "platform_donated_jit\n"
        "def _k(cols, y):\n"
        "    return cols + y\n"
        "_pd = platform_donated_jit(_k, donate_argnums=(0,))\n"
        "class Loop:\n"
        "    def run(self, y):\n"
        "        while True:\n"
        "            try:\n"
        "                return dispatch('k', _pd.resolve(), "
        "self.cols, y)\n"
        "            except RuntimeError as exc:\n"
        "                if jax.default_backend() != 'cpu':\n"
        "                    raise\n"
    )
    report = report_for(tmp_path, src)
    assert not report.findings, rules_of(report)


# ---------------------------------------------------------------------------
# baseline workflow: loosen / tighten / missing / stale
# ---------------------------------------------------------------------------

def test_baseline_loosen_tighten_missing_stale(tmp_path):
    src = DONOR + (
        "def step(cols, y):\n"
        "    out = consume(cols, y)\n"
        "    return cols + out\n"
    )
    path = tmp_path / "snippet.py"
    path.write_text(src)
    # missing baseline: every finding actionable
    r1 = run_lifetime(targets=[str(path)], baseline={}, lower=False)
    assert r1.findings
    # loosen: write the baseline, findings become baselined
    bpath = tmp_path / "b.json"
    write_baseline(str(bpath), r1.findings)
    accepted = load_baseline(str(bpath))
    r2 = run_lifetime(targets=[str(path)], baseline=accepted,
                      lower=False)
    assert not r2.findings
    assert sorted(f.rule for f in r2.baselined) == rules_of(r1)
    # tighten: fix the code, the entries go stale (the ratchet's cue)
    path.write_text(DONOR + (
        "def step(cols, y):\n"
        "    cols = consume(cols, y)\n"
        "    return cols\n"
    ))
    r3 = run_lifetime(targets=[str(path)], baseline=accepted,
                      lower=False)
    assert not r3.findings
    assert len(r3.stale_baseline) >= 1


# ---------------------------------------------------------------------------
# the committed tree proves clean
# ---------------------------------------------------------------------------

def test_committed_repo_proves_clean():
    report = run_lifetime(lower=False)
    assert not report.findings, [
        (f.path, f.line, f.rule, f.message) for f in report.findings]
    # the retrofitted platform_donated_jit sites are visible as donors
    assert report.donors >= 4
    assert report.files_checked > 50


def test_default_baseline_is_committed_and_empty():
    bpath = REPO / "tools" / "analysis" / "lifetime_baseline.json"
    data = json.loads(bpath.read_text())
    assert data["version"] == 1
    assert data["entries"] == []


# ---------------------------------------------------------------------------
# CLI: four-tier --list-rules, merged multi-tier --json, max exit
# ---------------------------------------------------------------------------

def test_list_rules_spans_four_tiers():
    out = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, check=True).stdout
    for probe in ("CSA101", "CSA1101", "CSA1401", "CSA1501", "CSA1505"):
        assert probe in out, probe


def test_cli_single_tier_json_shape(tmp_path):
    out = tmp_path / "lifetime.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--lifetime",
         "--no-lower", "--json", str(out)],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(out.read_text())
    assert "tiers" not in data           # historical single-tier shape
    assert data["findings"] == []
    assert data["donors"] >= 4


def test_cli_merged_tiers_json_and_max_exit(tmp_path):
    # an AST-tier finding (host cast under jit) + a clean lifetime run:
    # the merged artifact carries both tiers, the exit is the WORST
    snippet = tmp_path / "bad_ast.py"
    snippet.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return int(x)\n"
    )
    out = tmp_path / "merged.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", str(snippet),
         "--lifetime", "--no-lower", "--json", str(out)],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 1          # max(ast=1, lifetime=0)
    data = json.loads(out.read_text())
    assert sorted(data["tiers"]) == ["ast", "lifetime"]
    assert data["tiers"]["lifetime"]["findings"] == []
    assert any(f["rule"] == "CSA102"
               for f in data["tiers"]["ast"]["findings"])


def test_cli_update_lifetime_baseline_roundtrip(tmp_path):
    # the committed tree is clean, so a refresh writes an EMPTY ratchet
    bpath = tmp_path / "lb.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis",
         "--update-lifetime-baseline", "--no-lower",
         "--lifetime-baseline", str(bpath)],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(bpath.read_text())["entries"] == []


# ---------------------------------------------------------------------------
# the blessed helper itself (runtime, XLA:CPU)
# ---------------------------------------------------------------------------

def test_platform_donated_jit_runtime_contract():
    import jax
    import jax.numpy as jnp
    from consensus_specs_tpu.utils.donation import platform_donated_jit

    calls = []

    def kern(x, y):
        calls.append(1)
        return x + y

    pd = platform_donated_jit(kern, donate_argnums=(0,))
    # on XLA:CPU the resolved twin is the undonated one (the PR 3
    # deserialized-donated-aliasing caveat)
    assert jax.default_backend() == "cpu"
    assert pd.donate_now() is False
    assert pd.resolve() is pd.undonated
    assert pd.resolve() is not pd.donated
    x = jnp.arange(4, dtype=jnp.int32)
    out = pd(x, jnp.int32(1))
    assert out.tolist() == [1, 2, 3, 4]
    # the undonated twin leaves the input alive even after dispatch
    assert x.tolist() == [0, 1, 2, 3]
    # twins are cached jax.jit objects (the retrace watchdog inspects
    # their compile cache), constructed lazily and exactly once
    assert pd.undonated is pd.undonated
    assert pd.donated is pd.donated


def test_platform_donated_jit_rejects_missing_donation_args():
    import pytest
    from consensus_specs_tpu.utils.donation import platform_donated_jit

    def kern(x, y):
        return x

    with pytest.raises(AssertionError):
        platform_donated_jit(kern, donate_argnums=(5,))
