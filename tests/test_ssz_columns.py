"""Checkpoint fast path: SSZ BeaconState bytes -> SoA columns, diffed
against the object-model walk (epoch_soa.columns_np_from_state)."""
import numpy as np
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0, phase1
from consensus_specs_tpu.models.phase0.epoch_soa import columns_np_from_state
from consensus_specs_tpu.testing.factories import seed_genesis_state
from consensus_specs_tpu.utils.ssz.columns import (
    container_field_spans, fixed_field_layout, state_columns_from_bytes)
from consensus_specs_tpu.utils.ssz.impl import serialize
from consensus_specs_tpu.utils.ssz.typing import List as SSZList, uint64


def _spec(phase):
    return (phase0 if phase == 0 else phase1).get_spec("minimal")


@pytest.mark.parametrize("phase", [0, 1])
def test_state_columns_match_object_walk(phase):
    """Both phases: phase 1 appends custody fields to Validator, so the
    record stride differs while the phase-0 offsets must not move."""
    bls.bls_active = False
    spec = _spec(phase)
    state = seed_genesis_state(spec, 37)
    # make the columns non-trivial
    state.validator_registry[3].slashed = True
    state.validator_registry[5].exit_epoch = 7
    state.balances[11] = 12345
    data = serialize(state, spec.BeaconState)
    cols = state_columns_from_bytes(data, spec)
    want = columns_np_from_state(state)
    for key, w in want.items():
        assert (np.asarray(cols[key]) == np.asarray(w)).all(), key
    pubs = np.stack([np.frombuffer(bytes(v.pubkey), np.uint8)
                     for v in state.validator_registry])
    assert (cols["pubkey"] == pubs).all()


def test_phase1_stride_grows_offsets_stable():
    l0, s0 = fixed_field_layout(_spec(0).Validator)
    l1, s1 = fixed_field_layout(_spec(1).Validator)
    assert s1 > s0, "phase-1 Validator must append fields"
    for name, span in l0.items():
        assert l1[name] == span, f"phase-0 offset moved: {name}"


def test_corrupt_bool_byte_rejected():
    """A non-0/1 slashed byte must fail loudly (deserialize_basic parity),
    not resume as slashed=True."""
    bls.bls_active = False
    spec = _spec(0)
    state = seed_genesis_state(spec, 4)
    data = bytearray(serialize(state, spec.BeaconState))
    spans = container_field_spans(bytes(data), spec.BeaconState)
    layout, stride = fixed_field_layout(spec.Validator)
    off, _ = layout["slashed"]
    lo, _ = spans["validator_registry"]
    data[lo + 2 * stride + off] = 0x02
    with pytest.raises(ValueError, match="bool"):
        state_columns_from_bytes(bytes(data), spec)


def test_field_spans_match_serialization():
    """Variable-field spans slice back to payloads the deserializer agrees
    with (registry payload length == V * stride)."""
    bls.bls_active = False
    spec = _spec(0)
    state = seed_genesis_state(spec, 9)
    data = serialize(state, spec.BeaconState)
    spans = container_field_spans(data, spec.BeaconState)
    _, stride = fixed_field_layout(spec.Validator)
    lo, hi = spans["validator_registry"]
    assert (hi - lo) == 9 * stride
    lo, hi = spans["balances"]
    assert data[lo:hi] == serialize(list(state.balances), SSZList[uint64])
