"""Vector emission: suites render, write, and reload as valid YAML in the
reference's layout (runner/handler nesting, suite header fields).

Format contract: /root/reference specs/test_formats/README.md:104-188.
BLS-bearing suites are exercised under the minimal preset with real
signatures only where cheap (shuffling/ssz_static are crypto-free; the
operations replay runs with bls off here — the CLI default emits with BLS
on, which the corpus itself covers in test_bls_corpus).
"""
import os

import yaml

from consensus_specs_tpu.generators import suites
from consensus_specs_tpu.generators.base import run_generator, write_suite
from consensus_specs_tpu.generators.from_tables import cases_from_table, table


def test_operations_suite_replays_table(tmp_path):
    cases = cases_from_table(table("block_header"), "minimal", bls_default=False)
    assert len(cases) == 5
    ok = [c for c in cases if c.get("post") is not None]
    bad = [c for c in cases if c.get("post") is None]
    assert len(ok) >= 1 and len(bad) >= 3
    for c in cases:
        assert "pre" in c and "description" in c


def test_sanity_slots_suite(tmp_path):
    cases = cases_from_table(table("sanity_slots"), "minimal", bls_default=False)
    assert len(cases) == 5
    for c in cases:
        assert isinstance(c["slots"], int)
        assert c["post"] is not None


def test_shuffling_suite_layout(tmp_path):
    suite = suites.shuffling_suite("minimal")
    path = write_suite(str(tmp_path), suite)
    assert path.endswith(os.path.join("tests", "shuffling", "core", "core_minimal.yaml"))
    doc = yaml.safe_load(open(path))
    for key in ("title", "summary", "forks_timeline", "forks", "config",
                "runner", "handler", "test_cases"):
        assert key in doc
    assert doc["runner"] == "shuffling"
    sizes = [c["count"] for c in doc["test_cases"]]
    assert sizes == sorted(sizes)
    # permutation property
    for c in doc["test_cases"]:
        assert sorted(c["shuffled"]) == list(range(c["count"]))


def test_ssz_static_suite_roundtrips(tmp_path):
    suite = suites.ssz_static_suite("minimal")
    assert suite.test_cases, "must emit cases for every container"
    names = {c["type_name"] for c in suite.test_cases}
    assert "BeaconState" in names and "Validator" in names
    for c in suite.test_cases[:20]:
        assert c["serialized"].startswith("0x")
        assert len(c["root"]) == 66


def test_run_generator_cli(tmp_path):
    out = run_generator(
        "shuffling", [suites.shuffling_suite],
        argv=["-o", str(tmp_path), "-p", "minimal"])
    assert len(out) == 1
    assert os.path.exists(out[0])


def test_epoch_processing_suite(tmp_path):
    cases = cases_from_table(table("registry_updates"), "minimal", bls_default=False)
    assert len(cases) == 4
    for c in cases:
        assert c["post"] is not None


def test_dry_run_writes_nothing(tmp_path, capsys):
    run_generator("shuffling", [suites.shuffling_suite],
                  argv=["-o", str(tmp_path), "-p", "minimal", "--dry"])
    assert not os.path.exists(os.path.join(str(tmp_path), "tests"))


def test_ssz_generic_uint_suite_diffs_against_main_stack():
    """Every valid uint case must decode+re-encode identically through the
    MAIN SSZ stack (utils/ssz), not just the sedes codec that emitted it —
    the differential purpose of ssz_generic vectors."""
    from consensus_specs_tpu.utils.ssz import impl, typing as st

    suite = suites.ssz_generic_suite("mainnet")
    assert suite is not None and suites.ssz_generic_suite("minimal") is None
    widths = {c["type"] for c in suite.test_cases}
    assert widths == {f"uint{b}" for b in (8, 16, 32, 64, 128, 256)}
    uint_by_bits = {8: st.uint8, 16: st.uint16, 32: st.uint32,
                    64: st.uint64, 128: st.uint128, 256: st.uint256}
    n_valid = n_invalid = 0
    for c in suite.test_cases:
        bits = int(c["type"][4:])
        typ = uint_by_bits[bits]
        if c["valid"]:
            n_valid += 1
            raw = bytes.fromhex(c["ssz"][2:])
            assert len(raw) == bits // 8
            value = int(c["value"])
            assert impl.serialize(value, typ) == raw
            assert impl.deserialize(raw, typ) == value
        else:
            n_invalid += 1
            if "ssz" in c:
                raw = bytes.fromhex(c["ssz"][2:])
                assert len(raw) != bits // 8
            else:
                v = int(c["value"])
                assert v < 0 or v >= 2 ** bits
    assert n_valid >= 60 and n_invalid >= 36


def test_ssz_static_phase1_covers_extended_containers():
    suite = suites.ssz_static_phase1_suite("minimal")
    names = {c["type_name"] for c in suite.test_cases}
    # field-appended phase-0 types AND the new phase-1 families
    for required in ("BeaconState", "Validator", "ShardBlock",
                     "CustodyBitChallenge", "CustodyKeyReveal"):
        assert required in names, required
    assert suite.handler == "core_phase1" and suite.forks == ["phase1"]
    for c in suite.test_cases[:10]:
        assert c["serialized"].startswith("0x") and len(c["root"]) == 66


def test_cli_module_main(tmp_path):
    """The `python -m consensus_specs_tpu.generators` entry point (family
    selection + arg passthrough) — the piece `make vectors` runs."""
    from consensus_specs_tpu.generators.__main__ import main
    out = tmp_path / "v"
    main(["-o", str(out), "-p", "minimal", "--family", "shuffling"])
    files = list(out.rglob("*.yaml"))
    assert files, "shuffling family must emit at least one suite file"
