"""Networking model: envelope codec, RPC protocol, gossip router, identity.

Contracts: /root/reference specs/networking/{messaging,rpc-interface,
libp2p-standardization,node-identification}.md. The reference ships no
networking code, only these documents — the tests here pin our executable
model to their MUSTs (ignore malformed, verify ENR signatures, id
matching, response codes, topic hashing, 512KB cap).
"""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.networking import (
    GossipRouter, Hello, MessageEnvelopeError, NodeRecord, RpcError, RpcNode,
    decode_message, encode_message, loopback_pair, multiaddr, peer_id,
    shard_attestation_topic, topic_hash)
from consensus_specs_tpu.networking import messaging, rpc
from consensus_specs_tpu.testing.keys import privkeys, pubkeys
from consensus_specs_tpu.utils.hash import sha256


# ---------------------------------------------------------------------------
# Envelope (messaging.md:21-45)
# ---------------------------------------------------------------------------

def test_envelope_roundtrip():
    body = b"\x01\x02\x03" * 100
    wire = encode_message(body)
    comp, enc, out = decode_message(wire)
    assert (comp, enc) == (messaging.COMPRESSION_NONE, messaging.ENCODING_SSZ)
    assert out == body


@pytest.mark.parametrize("mutate", [
    lambda w: w[:5],                                   # short header
    lambda w: bytes([0x12]) + w[1:],                   # unknown compression
    lambda w: bytes([0x02]) + w[1:],                   # unknown encoding
    lambda w: w[:-1],                                  # truncated body
    lambda w: w + b"\x00",                             # trailing junk
])
def test_malformed_envelopes_are_ignorable(mutate):
    wire = encode_message(b"payload")
    with pytest.raises(MessageEnvelopeError):
        decode_message(mutate(wire))


def test_tcp_prefix():
    framed = messaging.frame_tcp(encode_message(b"x"))
    assert framed.startswith(b"ETH") and framed[:3] == bytes.fromhex("455448")
    assert messaging.unframe_tcp(framed) == encode_message(b"x")
    with pytest.raises(MessageEnvelopeError):
        messaging.unframe_tcp(b"BTC" + b"rest")


# ---------------------------------------------------------------------------
# RPC (rpc-interface.md)
# ---------------------------------------------------------------------------

def _hello(net=1, slot=64):
    return Hello(network_id=net, chain_id=1,
                 latest_finalized_root=b"\x0a" * 32,
                 latest_finalized_epoch=2,
                 best_root=b"\x0b" * 32, best_slot=slot)


def test_hello_exchange_and_id_matching():
    a, b = loopback_pair()
    b.register(rpc.HELLO, lambda h: _hello(net=1, slot=128))
    first = a.call(rpc.HELLO, _hello())
    second = a.call(rpc.HELLO, _hello())
    assert int(first.best_slot) == 128 and int(second.best_slot) == 128
    assert a._next_id == 2   # monotonic per-connection ids


def test_goodbye_records_reason_and_returns_empty():
    a, b = loopback_pair()
    assert a.call(rpc.GOODBYE, rpc.Goodbye(reason=2)) is None
    assert b.said_goodbye == 2


def test_method_not_found_code():
    a, _ = loopback_pair()
    with pytest.raises(RpcError) as err:
        a.call(rpc.BEACON_BLOCK_ROOTS,
               rpc.BlockRootsRequest(start_slot=0, count=10))
    assert err.value.code == rpc.METHOD_NOT_FOUND


def test_block_roots_request_response():
    a, b = loopback_pair()

    def serve(req):
        assert int(req.count) <= rpc.MAX_BLOCK_ROOTS_COUNT
        return rpc.BlockRootsResponse(roots=[
            rpc.BlockRootSlot(block_root=bytes([s]) * 32, slot=s)
            for s in range(int(req.start_slot), int(req.start_slot) + 3)
        ])

    b.register(rpc.BEACON_BLOCK_ROOTS, serve)
    resp = a.call(rpc.BEACON_BLOCK_ROOTS,
                  rpc.BlockRootsRequest(start_slot=5, count=3))
    slots = [int(r.slot) for r in resp.roots]
    assert slots == sorted(slots) == [5, 6, 7]


def test_server_error_maps_to_code():
    a, b = loopback_pair()
    b.register(rpc.GET_STATUS, lambda s: 1 / 0)
    with pytest.raises(RpcError) as err:
        a.call(rpc.GET_STATUS, rpc.Status(sha=b"\x00" * 32,
                                          user_agent=b"t", timestamp=0))
    assert err.value.code == rpc.SERVER_ERROR


def test_parse_error_on_garbage_wire():
    node = RpcNode()
    resp_wire = node.handle_wire(b"\xff" * 40)
    _, _, payload = decode_message(resp_wire)
    from consensus_specs_tpu.utils.ssz.impl import deserialize
    resp = deserialize(payload, rpc.Response)
    assert int(resp.response_code) == rpc.PARSE_ERROR


def test_handshake_disconnect_policy():
    mine, theirs = _hello(net=1), _hello(net=2)
    assert rpc.should_disconnect(mine, theirs, lambda e: None)
    same_net = _hello(net=1)
    # peer's finalized root not on our chain at that epoch -> disconnect
    assert rpc.should_disconnect(mine, same_net, lambda e: b"\xff" * 32)
    # matching root (or unknown epoch) -> stay
    assert not rpc.should_disconnect(mine, same_net, lambda e: b"\x0a" * 32)
    assert not rpc.should_disconnect(mine, same_net, lambda e: None)


# ---------------------------------------------------------------------------
# Gossip (libp2p-standardization.md:72-158)
# ---------------------------------------------------------------------------

def test_topic_hash_and_shard_subnets():
    assert topic_hash("beacon_block") == sha256(b"beacon_block")
    assert shard_attestation_topic(shard=1029, shard_subnet_count=16) == \
        "shard5_attestation"


def test_gossip_delivery_and_dedup():
    router = GossipRouter()
    seen = {"a": [], "b": [], "c": []}
    for node in seen:
        router.subscribe(node, "beacon_block",
                         lambda t, p, node=node: seen[node].append(p))
    reached = router.publish("a", "beacon_block", b"block-bytes")
    assert reached == 2                       # everyone but the publisher
    assert seen["a"] == [] and seen["b"] == [b"block-bytes"]
    # a forwarding node re-publishing is a no-op (seen-cache)
    assert router.publish("b", "beacon_block", b"block-bytes") == 0


def test_gossip_message_size_cap():
    router = GossipRouter()
    router.subscribe("b", "beacon_block", lambda t, p: None)
    assert router.publish("a", "beacon_block",
                          b"\x00" * (512 * 1024 + 1)) == 0
    assert router.dropped_oversize == 1


# ---------------------------------------------------------------------------
# Identity (node-identification.md:11-27)
# ---------------------------------------------------------------------------

def test_node_record_sign_verify_and_multiaddr():
    old = bls.bls_active
    bls.bls_active = True
    try:
        record = NodeRecord(ip="10.0.0.1", pubkey=pubkeys[0]).sign(privkeys[0])
        assert record.tcp_port == 9000
        assert record.verify()
        # MUST disconnect on bad signatures: any content change invalidates
        record.seq += 1
        assert not record.verify()
    finally:
        bls.bls_active = old
    pid = peer_id(pubkeys[0])
    assert pid[:2] == bytes([0x12, 0x20]) and len(pid) == 34
    addr = multiaddr(NodeRecord(ip="10.0.0.1", pubkey=pubkeys[0]))
    assert addr.startswith("/ip4/10.0.0.1/tcp/9000/p2p/1220")


def test_untyped_method_registration_round_trips():
    """Reserved/custom method ids (e.g. BEACON_CHAIN_STATE=13) work once a
    node registers types — or raw bytes handlers on both ends."""
    a, b = loopback_pair()
    b.register(rpc.BEACON_CHAIN_STATE, lambda raw: raw[::-1])  # raw-bytes echo
    with pytest.raises(RpcError) as err:
        a.call(rpc.BEACON_CHAIN_STATE, b"\x01\x02")  # a has no types for 13
    assert err.value.code == rpc.METHOD_NOT_FOUND
    a.register(rpc.BEACON_CHAIN_STATE, lambda raw: raw)  # untyped on a too
    assert a.call(rpc.BEACON_CHAIN_STATE, b"\x01\x02") == b"\x02\x01"


def test_gossip_handler_failure_isolated():
    router = GossipRouter()
    got = []
    router.subscribe("bad", "beacon_block",
                     lambda t, p: (_ for _ in ()).throw(RuntimeError("boom")))
    router.subscribe("good", "beacon_block", lambda t, p: got.append(p))
    assert router.publish("src", "beacon_block", b"payload") == 1
    assert got == [b"payload"] and router.handler_failures == 1
