"""parallel/sharding.py unit coverage on the virtual 8-device CPU mesh.

Direct tests for the placement helpers that previously only ran inside
the multichip dry-run: mesh construction, leading-axis round trips
(values must be bitwise-unchanged by placement), hierarchical mesh
shapes, and the unequal-tree detector the dry-run relies on for its
bitwise verdicts.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from consensus_specs_tpu.parallel.sharding import (
    hierarchical_mesh, shard_hierarchical, shard_leading_axis,
    trees_bitwise_equal, validator_mesh)


def _tree():
    return {
        "cols": jnp.arange(64, dtype=jnp.uint64).reshape(8, 8),
        "flat": jnp.arange(16, dtype=jnp.uint32),
        "scalar": jnp.uint64(7),
    }


def test_validator_mesh_uses_all_devices():
    mesh = validator_mesh()
    assert mesh.axis_names == ("v",)
    assert mesh.devices.shape == (len(jax.devices()),)


def test_validator_mesh_subset_and_overask():
    assert validator_mesh(n=4).devices.shape == (4,)
    with pytest.raises(AssertionError):
        validator_mesh(n=len(jax.devices()) + 1)


def test_shard_leading_axis_roundtrip_bitwise():
    mesh = validator_mesh()
    tree = _tree()
    sharded = shard_leading_axis(mesh, tree)
    # placement must not change a single bit
    assert trees_bitwise_equal(tree, sharded)
    # array leaves shard their leading axis over "v"
    assert sharded["cols"].sharding == NamedSharding(mesh, P("v"))
    assert sharded["flat"].sharding == NamedSharding(mesh, P("v"))
    # 0-d leaves replicate
    assert sharded["scalar"].sharding == NamedSharding(mesh, P())
    # every device owns a distinct shard of the leading axis
    devs = {s.device for s in sharded["cols"].addressable_shards}
    assert len(devs) == len(jax.devices())


def test_hierarchical_mesh_shapes():
    assert hierarchical_mesh(hosts=2).devices.shape == (2, 4)
    assert hierarchical_mesh(hosts=4).devices.shape == (4, 2)
    assert hierarchical_mesh(hosts=2).axis_names == ("host", "v")
    with pytest.raises(AssertionError):
        hierarchical_mesh(hosts=3)   # 8 devices don't tile 3 hosts


def test_shard_hierarchical_roundtrip_bitwise():
    mesh = hierarchical_mesh(hosts=2)
    tree = _tree()
    sharded = shard_hierarchical(mesh, tree)
    assert trees_bitwise_equal(tree, sharded)
    # flattened (host, v) product: all 8 devices own leading-axis shards
    assert sharded["cols"].sharding == NamedSharding(mesh, P(("host", "v")))
    devs = {s.device for s in sharded["cols"].addressable_shards}
    assert len(devs) == len(jax.devices())


def test_trees_bitwise_equal_detects_value_drift():
    a = _tree()
    b = _tree()
    assert trees_bitwise_equal(a, b)
    b["flat"] = b["flat"].at[3].set(99)
    assert not trees_bitwise_equal(a, b)


def test_trees_bitwise_equal_detects_dtype_shape_and_arity():
    a = _tree()
    narrower = dict(a, cols=a["cols"].astype(jnp.uint32))
    assert not trees_bitwise_equal(a, narrower)
    reshaped = dict(a, cols=a["cols"].reshape(4, 16))
    assert not trees_bitwise_equal(a, reshaped)
    pruned = {k: v for k, v in a.items() if k != "scalar"}
    assert not trees_bitwise_equal(a, pruned)


def test_trees_bitwise_equal_mixed_host_device_leaves():
    # host compare: numpy and device arrays with identical bits are equal
    a = {"x": np.arange(8, dtype=np.uint64)}
    b = {"x": jnp.arange(8, dtype=jnp.uint64)}
    assert trees_bitwise_equal(a, b)
