"""parallel/sharding.py unit coverage on the virtual 8-device CPU mesh.

Direct tests for the placement helpers that previously only ran inside
the multichip dry-run: mesh construction, leading-axis round trips
(values must be bitwise-unchanged by placement), hierarchical mesh
shapes, and the unequal-tree detector the dry-run relies on for its
bitwise verdicts.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from consensus_specs_tpu.parallel.sharding import (
    hierarchical_mesh, shard_hierarchical, shard_leading_axis,
    trees_bitwise_equal, validator_mesh)


def _tree():
    return {
        "cols": jnp.arange(64, dtype=jnp.uint64).reshape(8, 8),
        "flat": jnp.arange(16, dtype=jnp.uint32),
        "scalar": jnp.uint64(7),
    }


def test_validator_mesh_uses_all_devices():
    mesh = validator_mesh()
    assert mesh.axis_names == ("v",)
    assert mesh.devices.shape == (len(jax.devices()),)


def test_validator_mesh_subset_and_overask():
    assert validator_mesh(n=4).devices.shape == (4,)
    with pytest.raises(AssertionError):
        validator_mesh(n=len(jax.devices()) + 1)


def test_shard_leading_axis_roundtrip_bitwise():
    mesh = validator_mesh()
    tree = _tree()
    sharded = shard_leading_axis(mesh, tree)
    # placement must not change a single bit
    assert trees_bitwise_equal(tree, sharded)
    # array leaves shard their leading axis over "v"
    assert sharded["cols"].sharding == NamedSharding(mesh, P("v"))
    assert sharded["flat"].sharding == NamedSharding(mesh, P("v"))
    # 0-d leaves replicate
    assert sharded["scalar"].sharding == NamedSharding(mesh, P())
    # every device owns a distinct shard of the leading axis
    devs = {s.device for s in sharded["cols"].addressable_shards}
    assert len(devs) == len(jax.devices())


def test_hierarchical_mesh_shapes():
    assert hierarchical_mesh(hosts=2).devices.shape == (2, 4)
    assert hierarchical_mesh(hosts=4).devices.shape == (4, 2)
    assert hierarchical_mesh(hosts=2).axis_names == ("host", "v")
    with pytest.raises(AssertionError):
        hierarchical_mesh(hosts=3)   # 8 devices don't tile 3 hosts


def test_shard_hierarchical_roundtrip_bitwise():
    mesh = hierarchical_mesh(hosts=2)
    tree = _tree()
    sharded = shard_hierarchical(mesh, tree)
    assert trees_bitwise_equal(tree, sharded)
    # flattened (host, v) product: all 8 devices own leading-axis shards
    assert sharded["cols"].sharding == NamedSharding(mesh, P(("host", "v")))
    devs = {s.device for s in sharded["cols"].addressable_shards}
    assert len(devs) == len(jax.devices())


def test_trees_bitwise_equal_detects_value_drift():
    a = _tree()
    b = _tree()
    assert trees_bitwise_equal(a, b)
    b["flat"] = b["flat"].at[3].set(99)
    assert not trees_bitwise_equal(a, b)


def test_trees_bitwise_equal_detects_dtype_shape_and_arity():
    a = _tree()
    narrower = dict(a, cols=a["cols"].astype(jnp.uint32))
    assert not trees_bitwise_equal(a, narrower)
    reshaped = dict(a, cols=a["cols"].reshape(4, 16))
    assert not trees_bitwise_equal(a, reshaped)
    pruned = {k: v for k, v in a.items() if k != "scalar"}
    assert not trees_bitwise_equal(a, pruned)


def test_trees_bitwise_equal_mixed_host_device_leaves():
    # host compare: numpy and device arrays with identical bits are equal
    a = {"x": np.arange(8, dtype=np.uint64)}
    b = {"x": jnp.arange(8, dtype=jnp.uint64)}
    assert trees_bitwise_equal(a, b)


def test_shard_leading_axis_rejects_non_divisible_axis():
    """A leading axis that does not tile the mesh must raise up front —
    naming the axis size, the mesh size, and the pow2-pad helper — instead
    of letting pjit pad (or reject) unpredictably per jax version."""
    mesh = validator_mesh()
    bad = {"cols": jnp.arange(33, dtype=jnp.uint32)}
    with pytest.raises(ValueError) as exc:
        shard_leading_axis(mesh, bad)
    msg = str(exc.value)
    assert "33" in msg and "8-device" in msg
    assert "pad_leading_pow2" in msg and "64" in msg


def test_pad_leading_pow2_makes_axis_shardable():
    from consensus_specs_tpu.parallel.sharding import pad_leading_pow2
    mesh = validator_mesh()
    x = jnp.arange(33, dtype=jnp.uint32)
    padded = pad_leading_pow2(x, mesh)
    assert padded.shape == (64,)
    assert (np.asarray(padded)[:33] == np.arange(33)).all()
    assert not np.asarray(padded)[33:].any()
    sharded = shard_leading_axis(mesh, padded)   # now accepted
    assert sharded.sharding == NamedSharding(mesh, P("v"))
    # already-divisible axes pass through untouched
    y = jnp.arange(16, dtype=jnp.uint32)
    assert pad_leading_pow2(y, mesh) is y


def test_serving_mesh_from_env(monkeypatch):
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    for off in ("", "0", "off"):
        monkeypatch.setenv("CSTPU_SERVING_MESH", off)
        assert ServingMesh.from_env() is None
    monkeypatch.setenv("CSTPU_SERVING_MESH", "1")
    assert ServingMesh.from_env() is None        # nothing to shard
    monkeypatch.setenv("CSTPU_SERVING_MESH", "4")
    m = ServingMesh.from_env()
    assert m is not None and m.size == 4
    monkeypatch.setenv("CSTPU_SERVING_MESH", "all")
    # "all" rounds DOWN to a power of two (8 virtual devices here)
    assert ServingMesh.from_env().size == 8
    # explicit asks are refused with a clear message, never rounded
    monkeypatch.setenv("CSTPU_SERVING_MESH", "6")
    with pytest.raises(ValueError, match="power of two"):
        ServingMesh.from_env()
    monkeypatch.setenv("CSTPU_SERVING_MESH", "six")
    with pytest.raises(ValueError, match="CSTPU_SERVING_MESH"):
        ServingMesh.from_env()


def test_serving_mesh_padding_and_row_sharding():
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    mesh = ServingMesh.create(8)
    assert mesh.pad_rows(0) == 0
    assert mesh.pad_rows(1) == 8
    assert mesh.pad_rows(32) == 32
    assert mesh.pad_rows(33) == 40
    # forest levels shard while their rows tile the mesh; the cap replicates
    assert mesh.row_sharding(64) == mesh.shard_v
    assert mesh.row_sharding(8) == mesh.shard_v
    assert mesh.row_sharding(4) == mesh.replicated
    assert mesh.row_sharding(1) == mesh.replicated
    with pytest.raises(AssertionError):
        ServingMesh.create(3)                    # mesh size must be pow2
