"""Pallas pair-hash kernel == XLA kernel == hashlib (interpret mode on CPU;
the on-chip Mosaic compile is exercised by tools/tpu_followup.py)."""
import hashlib

import numpy as np
import pytest

from consensus_specs_tpu.ops import sha256 as S
from consensus_specs_tpu.ops.sha256_pallas import sha256_pairs_pallas


@pytest.mark.parametrize("n", [1, 5, 128, 300])
def test_pallas_pairs_match_xla(n):
    """Ragged sizes cross the lane-padding boundaries (128, 512)."""
    rng = np.random.default_rng(n)
    words = rng.integers(0, 2 ** 32, (n, 16), dtype=np.uint32)
    got = np.asarray(sha256_pairs_pallas(words))
    want = np.asarray(S.sha256_pairs(words))
    assert (got == want).all()


def test_pallas_pairs_multi_tile_grid():
    """n=300 at block_lanes=128 runs a 3-step grid: a broken BlockSpec
    index map (e.g. every step reading tile 0) cannot pass this."""
    rng = np.random.default_rng(99)
    words = rng.integers(0, 2 ** 32, (300, 16), dtype=np.uint32)
    got = np.asarray(sha256_pairs_pallas(words, block_lanes=128))
    want = np.asarray(S.sha256_pairs(words))
    assert (got == want).all()


def test_pallas_pairs_match_hashlib():
    msgs = [bytes(range(64)), b"\x00" * 64, b"\xff" * 64]
    words = np.stack([
        S.bytes_to_words(np.frombuffer(m, dtype=np.uint8)) for m in msgs])
    got = np.asarray(sha256_pairs_pallas(words))
    for k, m in enumerate(msgs):
        assert S.words_to_bytes(got[k]).tobytes() == hashlib.sha256(m).digest()
