"""Fork choice: vectorized LMD-GHOST vs the reference-shaped object walk.

Covers the contract of /root/reference specs/core/0_fork-choice.md:59-105:
ancestor lookup, effective-balance-weighted vote counting, head selection,
tie-breaking by lexicographically higher root, and the genesis aliasing of
ZERO_HASH attestation targets (:105-109).
"""
import random
from types import SimpleNamespace

import numpy as np
import pytest

from consensus_specs_tpu.models.phase0.fork_choice import (
    Store, lmd_ghost, lmd_ghost_reference, subtree_weights)


def _blk(slot):
    return SimpleNamespace(slot=slot)


def _root(i):
    return bytes([i]) + bytes(31)


def build_random_store(rng, n_blocks=40):
    """Random tree with strictly increasing slots along every branch."""
    store = Store()
    store.add_block(_root(0), _blk(0), None)
    for i in range(1, n_blocks):
        parent = rng.randrange(i)
        slot = store.slots[parent] + rng.randrange(1, 4)
        store.add_block(_root(i), _blk(slot), store.roots[parent])
    return store


@pytest.mark.parametrize("seed", range(8))
def test_vectorized_matches_reference_walk(seed):
    rng = random.Random(seed)
    store = build_random_store(rng)
    V = 50
    balances = [32_000_000_000 + rng.randrange(10 ** 9) for _ in range(V)]
    for v in range(V):
        tgt = rng.randrange(len(store.roots))
        store.on_attestation([v], store.roots[tgt], slot=store.slots[tgt])
    active = list(range(V))
    got = lmd_ghost(store, balances, active, store.roots[0])
    want = lmd_ghost_reference(store, balances, active, store.roots[0])
    assert got == want


def test_tie_broken_by_higher_root():
    store = Store()
    store.add_block(_root(0), _blk(0), None)
    store.add_block(_root(1), _blk(1), _root(0))   # child A
    store.add_block(_root(2), _blk(1), _root(0))   # child B: higher root
    balances = [1, 1]
    store.on_attestation([0], _root(1), slot=1)
    store.on_attestation([1], _root(2), slot=1)
    head = lmd_ghost(store, balances, [0, 1], _root(0))
    assert head == _root(2)
    assert head == lmd_ghost_reference(store, balances, [0, 1], _root(0))


def test_heavier_subtree_wins_over_longer_chain():
    # chain A: 1 -> 3 (one voter), chain B: 2 (two heavy voters)
    store = Store()
    store.add_block(_root(0), _blk(0), None)
    store.add_block(_root(1), _blk(1), _root(0))
    store.add_block(_root(3), _blk(2), _root(1))
    store.add_block(_root(2), _blk(1), _root(0))
    balances = [32, 32, 32]
    store.on_attestation([0], _root(3), slot=2)
    store.on_attestation([1], _root(2), slot=1)
    store.on_attestation([2], _root(2), slot=1)
    assert lmd_ghost(store, balances, [0, 1, 2], _root(0)) == _root(2)


def test_latest_message_highest_slot_wins():
    store = Store()
    store.add_block(_root(0), _blk(0), None)
    store.add_block(_root(1), _blk(1), _root(0))
    store.add_block(_root(2), _blk(1), _root(0))
    store.on_attestation([0], _root(1), slot=5)
    store.on_attestation([0], _root(2), slot=3)   # older: ignored
    assert store.latest_messages[0].beacon_block_root == _root(1)
    store.on_attestation([0], _root(2), slot=7)   # newer: replaces
    assert store.latest_messages[0].beacon_block_root == _root(2)


def test_zero_hash_target_aliases_genesis():
    store = Store()
    store.add_block(_root(0), _blk(0), None)
    store.on_attestation([0], b"\x00" * 32, slot=1)
    assert store.latest_messages[0].beacon_block_root == _root(0)


def test_subtree_weights_direct_and_accumulated():
    store = Store()
    store.add_block(_root(0), _blk(0), None)
    store.add_block(_root(1), _blk(1), _root(0))
    store.add_block(_root(2), _blk(2), _root(1))
    balances = np.asarray([10, 20, 0], dtype=np.uint64)
    store.on_attestation([0], _root(1), slot=1)
    store.on_attestation([1], _root(2), slot=2)
    w = subtree_weights(store, balances, [0, 1, 2])
    assert list(w) == [30, 30, 20]


def test_unknown_attestation_target_ignored():
    store = Store()
    store.add_block(_root(0), _blk(0), None)
    store.on_attestation([0], _root(9), slot=1)
    assert 0 not in store.latest_messages


def test_get_ancestor():
    store = Store()
    store.add_block(_root(0), _blk(0), None)
    store.add_block(_root(1), _blk(2), _root(0))
    store.add_block(_root(2), _blk(5), _root(1))
    assert store.get_ancestor(2, 5) == 2
    assert store.get_ancestor(2, 2) == 1
    assert store.get_ancestor(2, 0) == 0
    assert store.get_ancestor(2, 3) is None   # skipped slot
    assert store.get_ancestor(0, 4) is None   # above the block
