"""Telemetry subsystem gate (consensus_specs_tpu/telemetry/):

  - span nesting, exit-only fencing, decorator form, ring buffer;
  - metrics registry (counters/gauges/pow2-bucket histograms), the
    `always=True` trace-time accounting path (fq REDC shims);
  - Prometheus text exposition validity and Chrome-trace JSON schema;
  - the retrace watchdog fires on a deliberately shape-polymorphic loop
    and stays SILENT (zero events, zero re-layouts) across chained
    resident slot steps + an epoch boundary on the 8-device mesh — the
    runtime pjit layout-stability contract (ISSUE 8 acceptance);
  - no-op mode (CSTPU_TELEMETRY=0) overhead bound.
"""
import json
import re
import time
from copy import deepcopy

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensus_specs_tpu import telemetry as T
from consensus_specs_tpu.telemetry import watchdog as W
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.testing import factories


@pytest.fixture(autouse=True)
def tele():
    """Pinned-on telemetry with a clean registry per test; restores env
    control (and fencing) afterwards. Watchdog warm-up state is NOT
    cleared globally — tests use fresh keys or explicit W.reset()."""
    T.set_enabled(True)
    T.reset()
    yield
    T.set_enabled(None)
    T.set_fencing(None)


@pytest.fixture
def spec():
    s = phase0.get_spec("minimal")
    bls.bls_active = False
    s.clear_caches()
    yield s
    s.clear_caches()


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

def test_span_nesting_ring_and_aggregates():
    with T.span("outer") as outer:
        with T.span("inner", tag="x") as inner:
            time.sleep(0.003)
    assert outer.duration >= inner.duration > 0
    records = T.ring()
    assert [r["name"] for r in records] == ["inner", "outer"]  # close order
    assert records[0]["parent"] == "outer" and records[0]["depth"] == 1
    assert records[1]["parent"] == "" and records[1]["depth"] == 0
    assert records[0]["args"] == {"tag": "x"}
    snap = T.snapshot()["spans"]
    assert snap["outer"]["count"] == 1
    assert snap["inner"]["last_ms"] == snap["inner"]["total_ms"] > 0
    assert T.span_seconds("inner") == inner.duration


def test_instrument_decorator_respects_runtime_toggle():
    @T.instrument("deco.fn")
    def double(a):
        return a * 2

    assert double(3) == 6
    assert T.snapshot()["spans"]["deco.fn"]["count"] == 1
    T.set_enabled(False)
    assert double(4) == 8          # still runs, nothing recorded
    T.set_enabled(True)
    assert T.snapshot()["spans"]["deco.fn"]["count"] == 1


class _FakeLeaf:
    """Duck-typed device array: records when its bytes were fetched."""

    def __init__(self):
        self.fetched_at = []

    def ravel(self):
        self.fetched_at.append(time.perf_counter())
        return np.zeros(4)


def test_span_fences_at_exit_only():
    leaf = _FakeLeaf()
    with T.span("fenced") as sp:
        sp.fence((leaf,))          # nested pytree form
        body_done = time.perf_counter()
    assert len(leaf.fetched_at) == 1
    assert leaf.fetched_at[0] >= body_done     # after the body, at exit
    assert sp.duration >= leaf.fetched_at[0] - sp.t0  # fence inside the span

    T.set_fencing(False)           # CSTPU_TELEMETRY_FENCE=0 equivalent
    silent = _FakeLeaf()
    with T.span("unfenced") as sp2:
        sp2.fence(silent)
    assert silent.fetched_at == []


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_registry_identity_and_noop_gating():
    c = T.counter("t.ctr")
    assert c is T.counter("t.ctr")
    c.inc()
    c.inc(4)
    assert c.value == 5
    T.gauge("t.g").set(2.5)
    assert T.snapshot()["gauges"]["t.g"] == 2.5

    T.set_enabled(False)
    c.inc(100)
    T.gauge("t.g").set(9.0)
    assert c.value == 5 and T.gauge("t.g").value == 2.5
    always = T.counter("t.always", always=True)
    always.inc(2)
    assert always.value == 2       # trace-time accounting ignores the switch


def test_histogram_pow2_buckets():
    h = T.histogram("t.h")
    for v in (0.25, 0.3, 1.0, 1.5, 2.0, 5.0, 0.0, -3):
        h.observe(v)
    snap = T.snapshot()["histograms"]["t.h"]
    assert snap["count"] == 8
    assert snap["buckets"] == {"0": 2, "0.25": 1, "0.5": 1, "1": 1,
                               "2": 2, "8": 1}
    assert snap["sum"] == pytest.approx(0.25 + 0.3 + 1.0 + 1.5 + 2.0 + 5.0
                                        + 0.0 - 3)


def test_redc_shims_ride_the_registry_even_when_off():
    from consensus_specs_tpu.ops import fq as F
    T.set_enabled(False)           # lane assertions must survive opt-out
    F.reset_redc_trace_stats()
    jax.make_jaxpr(lambda a, b: F.fq_mul(a, b))(
        jnp.zeros((2, F.L), jnp.int64), jnp.zeros((2, F.L), jnp.int64))
    stats = F.redc_trace_stats()
    assert stats["instances"] == 1 and stats["lanes"] == 2
    assert T.counter("fq.redc.lanes").value == 2


def test_forest_pair_lane_counters():
    from consensus_specs_tpu.utils.ssz.incremental import IncrementalMerkleTree
    rng = np.random.default_rng(0)
    leaves = rng.integers(0, 2 ** 32, (16, 8), dtype=np.uint32)
    base = T.counter("merkle.forest.pair_lanes").value
    tree = IncrementalMerkleTree(leaves)
    lanes = T.counter("merkle.forest.pair_lanes").value - base
    assert lanes == sum(tree.last_pairs_per_level) == 8 + 4 + 2 + 1
    assert T.counter("merkle.forest.builds").value >= 1


# ---------------------------------------------------------------------------
# export surfaces
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9.eE+-]+$|"
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*\{le=\"\+Inf\"\} [0-9]+$")


def test_prometheus_exposition_is_valid():
    T.counter("p.ctr").inc(7)
    T.gauge("p.g").set(1.25)
    h = T.histogram("p.h")
    for v in (0.3, 1.0, 9.0):
        h.observe(v)
    with T.span("p.span"):
        pass
    text = T.prometheus_text()
    lines = text.strip().splitlines()
    families = set()
    for line in lines:
        if line.startswith("# TYPE "):
            _, _, family, kind = line.split(" ")
            assert kind in ("counter", "gauge", "histogram")
            families.add(family)
        else:
            assert _SAMPLE_RE.match(line), line
            name = line.split("{")[0].split(" ")[0]
            base = re.sub(r"_(total|bucket|sum|count)$", "", name)
            assert name in families or base in families, line
    # counters follow the _total convention
    assert "cstpu_p_ctr_total 7" in lines
    # histogram buckets are cumulative with the mandatory +Inf == count
    buckets = [line for line in lines if line.startswith("cstpu_p_h_bucket")]
    counts = [int(line.rsplit(" ", 1)[1]) for line in buckets]
    assert counts == sorted(counts) and buckets[-1].endswith("} 3")
    assert "cstpu_p_h_count 3" in lines
    # span aggregates exposed as labeled counters
    assert any(line.startswith('cstpu_span_total{span="p.span"}')
               for line in lines)


def test_beacon_api_serves_metrics(spec):
    from consensus_specs_tpu.api.beacon_node import BeaconNodeAPI
    state = factories.seed_genesis_state(spec, 8)
    api = BeaconNodeAPI(spec, state)
    T.counter("api.test").inc()
    text = api.get_metrics()
    assert "cstpu_api_test_total 1" in text
    # served even while syncing: the operational surface stays up
    api.syncing.is_syncing = True
    assert "cstpu_api_test_total 1" in api.get_metrics()
    assert "traceEvents" in api.get_trace()


def test_chrome_trace_schema_and_dump(tmp_path):
    with T.span("trace.a"):
        with T.span("trace.b", idx=3):
            pass
    doc = T.chrome_trace()
    events = doc["traceEvents"]
    assert len(events) == 2
    for event in events:
        assert event["ph"] == "X"
        assert set(event) >= {"name", "ph", "ts", "dur", "pid", "tid"}
        assert event["ts"] >= 0 and event["dur"] >= 0
    assert [e["ts"] for e in events] == sorted(e["ts"] for e in events)
    child = next(e for e in events if e["name"] == "trace.b")
    assert child["args"]["parent"] == "trace.a" and child["args"]["idx"] == 3
    path = tmp_path / "trace.json"
    T.dump_chrome_trace(str(path))
    assert json.loads(path.read_text())["traceEvents"]


def test_jsonl_sink(tmp_path):
    path = tmp_path / "telemetry.jsonl"
    T.counter("sink.n").inc()
    T.write_jsonl(str(path), extra={"stage": "one"})
    T.counter("sink.n").inc()
    T.write_jsonl(str(path))
    rows = [json.loads(line) for line in path.read_text().splitlines()]
    assert len(rows) == 2
    assert rows[0]["stage"] == "one"
    assert rows[0]["counters"]["sink.n"] == 1
    assert rows[1]["counters"]["sink.n"] == 2


# ---------------------------------------------------------------------------
# watchdogs
# ---------------------------------------------------------------------------

def test_retrace_watchdog_fires_on_shape_polymorphic_loop():
    f = jax.jit(lambda x: x * 2 + 1)
    base = T.counter("watchdog.retrace_events").value
    with pytest.warns(T.TelemetryWarning, match="retracing"):
        for n in range(1, 6):
            W.dispatch("test.poly", f, jnp.ones(n))
    stats = W.stats("test.poly")
    assert stats["calls"] == 5 and stats["compiles"] == 5
    assert stats["events"] == 4      # first compile is warm-up, rest are not
    assert T.counter("watchdog.retrace_events").value - base == 4


def test_retrace_watchdog_silent_on_cache_hits():
    f = jax.jit(lambda x: x - 1)
    for _ in range(5):
        W.dispatch("test.stable", f, jnp.ones(7))
    assert W.stats("test.stable")["events"] == 0


def test_retrace_watchdog_noop_when_disabled():
    T.set_enabled(False)
    f = jax.jit(lambda x: x + 3)
    for n in range(1, 5):
        W.dispatch("test.off", f, jnp.ones(n))
    assert W.stats("test.off") == {"calls": 0, "compiles": 0, "events": 0}


def _serving_mesh(min_devices=2):
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    n = 1
    while n * 2 <= min(8, len(jax.devices())):
        n *= 2
    if n < min_devices:
        pytest.skip(f"needs >= {min_devices} devices, have {len(jax.devices())}")
    return ServingMesh.create(n)


def test_relayout_watchdog_fires_on_placement_change():
    mesh = _serving_mesh()
    x = jnp.zeros((16, 8), jnp.uint32)
    W.layout_check("test.layout", jax.device_put(x, mesh.shard_v))
    base = T.counter("watchdog.relayout_events").value
    with pytest.warns(T.TelemetryWarning, match="re-laying-out"):
        W.layout_check("test.layout", jax.device_put(x, mesh.replicated))
    assert T.counter("watchdog.relayout_events").value - base == 1
    # and settles once the new placement is steady
    W.layout_check("test.layout", jax.device_put(x, mesh.replicated))
    assert T.counter("watchdog.relayout_events").value - base == 1


def test_watchdogs_silent_on_layout_stable_resident_loop(spec):
    """ISSUE 8 acceptance, test-scale: >= 4 chained resident slot steps
    plus one epoch boundary under the validator-axis mesh report ZERO
    retrace and ZERO re-layout events — the runtime form of the pjit
    staging contract the serving loop was built around (PR 6)."""
    from consensus_specs_tpu.models.phase0.resident import ResidentCore
    mesh = _serving_mesh()
    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    core = ResidentCore(spec, state, mesh=mesh)
    try:
        # one full warm-up epoch (first compiles are free for the
        # watchdog; the measured window below is the steady state)
        spe = spec.SLOTS_PER_EPOCH
        target = (state.slot // spe + 1) * spe + 1
        core.process_slots(state, target)
        retrace0 = T.counter("watchdog.retrace_events").value
        relayout0 = T.counter("watchdog.relayout_events").value
        core.process_slots(state, target + spe)   # >= 4 slots + 1 boundary
        assert T.counter("watchdog.retrace_events").value == retrace0
        assert T.counter("watchdog.relayout_events").value == relayout0
        # the boundary ran, span-derived timings carry the historic keys
        assert set(core.timings) == {"stage", "device", "refresh"}
        assert all(v > 0 for v in core.timings.values())
        spans = T.snapshot()["spans"]
        assert spans["resident.device"]["count"] >= 2
        assert spans["resident.slot_root"]["count"] >= spe + 4
    finally:
        core.exit()


def test_process_epoch_soa_span_derived_timings(spec):
    from consensus_specs_tpu.models.phase0.epoch_soa import process_epoch_soa
    state = factories.seed_genesis_state(spec, 2 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    timings = {}
    process_epoch_soa(spec, deepcopy(state), timings=timings)
    assert set(timings) == {"distill", "perm", "device", "writeback"}
    assert timings["device"] > 0 and timings["distill"] > 0
    spans = T.snapshot()["spans"]
    assert spans["epoch.device"]["count"] == 1
    assert spans["epoch.distill"]["count"] == 2   # cols + inputs segments


# ---------------------------------------------------------------------------
# no-op mode overhead
# ---------------------------------------------------------------------------

def test_noop_mode_overhead_bound():
    """CSTPU_TELEMETRY=0 must make the layer disappear: the disabled span
    is a shared singleton and a span+counter round trip stays under a
    generous per-op bound (typical is well under 1 us)."""
    T.set_enabled(False)
    assert T.span("a") is T.span("b")
    n = 20_000
    ctr = T.counter("off.ctr")
    t0 = time.perf_counter()
    for _ in range(n):
        with T.span("off.span") as sp:
            sp.fence(None)
        ctr.inc()
    per_op = (time.perf_counter() - t0) / n
    assert per_op < 20e-6, f"no-op overhead {per_op * 1e6:.2f} us/op"
    assert ctr.value == 0
    T.set_enabled(True)
    assert "off.span" not in T.snapshot()["spans"]
