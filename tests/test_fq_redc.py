"""Double-width lazy Montgomery (CSTPU_FQ_REDC=coeff): fq_mul_wide /
fq_wide_norm / fq_redc against exact Python bignums, the coeff-vs-leaf
tower bit-exactness, and the traced REDC lane counts.

Three layers, mirroring tests/test_scalar_mul.py's structure: the host
oracle algebra on the wide-column representation (exact ints, including
worst-case-magnitude limbs at the documented laziness budget), device
bit-exactness of every tower op across both backends, and the op-count
model — REDC instances/lanes counted in the actual traced jaxprs (each
REDC contributes exactly L multiplies by the Montgomery constant
QINV_NEG, a 29-bit value nothing else in the program multiplies by).
"""
import random

import numpy as np
import pytest

import jax.numpy as jnp

from consensus_specs_tpu.crypto import bls12_381 as gt
from consensus_specs_tpu.ops import fq as F
from consensus_specs_tpu.ops import fq_tower as T

rng = random.Random(0x2EDC)

Q = gt.q
R = F.R_MONT
QR = Q * (1 << (F.B * F.L))
RINV = pow(R, -1, Q)


def rand_fq():
    return rng.randrange(Q)


def fq_batch(values):
    return np.stack([F.to_mont(v) for v in values])


def wide_to_int(cols) -> int:
    """Exact (un-reduced) value of a [2L] wide-column array."""
    cols = np.asarray(cols)
    return sum(int(cols[..., i]) << (F.B * i) for i in range(2 * F.L))


def redc_oracle(cols) -> int:
    """What fq_redc must compute: value * R^-1 mod q."""
    return wide_to_int(cols) * RINV % Q


# ---------------------------------------------------------------------------
# Backend knob
# ---------------------------------------------------------------------------

def test_backend_knob_and_env(monkeypatch):
    """Mirrors CSTPU_SCALAR_MUL's override/env semantics."""
    assert F.fq_redc_backend_name() == "coeff"   # default
    F.set_fq_redc_backend("leaf")
    try:
        assert F.fq_redc_backend_name() == "leaf"
    finally:
        F.set_fq_redc_backend(None)
    assert F.fq_redc_backend_name() == "coeff"
    with pytest.raises(AssertionError):
        F.set_fq_redc_backend("bogus")
    monkeypatch.setenv("CSTPU_FQ_REDC", "nope")
    with pytest.raises(ValueError):
        F.fq_redc_backend_name()
    monkeypatch.setenv("CSTPU_FQ_REDC", "leaf")
    assert F.fq_redc_backend_name() == "leaf"
    with F.pinned_fq_redc_backend("coeff"):
        assert F.fq_redc_backend_name() == "coeff"
    assert F.fq_redc_backend_name() == "leaf"


# ---------------------------------------------------------------------------
# fq_mul_wide / fq_wide_norm / fq_redc vs exact host bignums
# ---------------------------------------------------------------------------

def test_mul_wide_then_redc_is_fq_mul():
    """fq_redc(fq_mul_wide(a, b)) is bit-identical to fq_mul(a, b) (the
    refactor is a pure split) and equals a*b under the bignum oracle."""
    a_vals = [0, 1, Q - 1] + [rand_fq() for _ in range(8)]
    b_vals = [Q - 1, 1, 0] + [rand_fq() for _ in range(8)]
    a, b = fq_batch(a_vals), fq_batch(b_vals)
    wide = F.fq_mul_wide(a, b)
    assert wide.shape == a.shape[:-1] + (2 * F.L,)
    out = np.asarray(F.fq_redc(wide))
    assert np.array_equal(out, np.asarray(F.fq_mul(a, b)))
    for i, (x, y) in enumerate(zip(a_vals, b_vals)):
        # wide columns hold the exact double-width product of the
        # (carry-normalized) Montgomery representatives
        assert wide_to_int(np.asarray(wide)[i]) % Q == (
            (x * R % Q) * (y * R % Q)) % Q
        assert F.from_mont(out[i]) == x * y % Q


def test_wide_norm_value_preserving_and_crushing():
    """fq_wide_norm preserves the exact column value and crushes non-top
    limb magnitudes from the raw-product scale (~2^61) into [-1, 2^29].
    The TOP column keeps the value spill in place (value-preserving by
    design — its weight has nowhere to carry to), bounded by the
    neighbor's carry: < 2^61 >> 29 + 2^30 here, and ~8 per accumulated
    term for in-budget pipeline values (< q*R)."""
    nprng = np.random.default_rng(0xA11CE)
    cols = nprng.integers(-(1 << 61), 1 << 61, (6, 2 * F.L), dtype=np.int64)
    out = np.asarray(F.fq_wide_norm(jnp.asarray(cols)))
    for i in range(cols.shape[0]):
        assert wide_to_int(out[i]) == wide_to_int(cols[i])
        body = out[i][:-1]
        assert body.min() >= -1 and body.max() <= (1 << F.B)
        # the top column keeps its own input magnitude plus the spill
        assert abs(int(out[i][-1])) < (1 << 61) + (1 << 33)

    # in-budget shape: the top column of a real (raw-product) wide array
    # is carry-only, so the stable spill is small
    a = fq_batch([rand_fq() for _ in range(4)])
    b = fq_batch([rand_fq() for _ in range(4)])
    prod = np.asarray(F.fq_wide_norm(F.fq_mul_wide(a, b)))
    assert prod.min() >= -1 and prod.max() <= (1 << (F.B + 1))


def test_redc_adversarial_budget_inputs():
    """fq_redc at the documented laziness budget: limbs at the full
    +/-(2^35 - 1) magnitude (the gamma fan-in ceiling 64 x 2^29) on every
    column the value bound |v| < q*R permits, checked against the exact
    host bignum, with the output contract (value in (-2q, 2q), limbs in
    [-1, 2^29]) asserted too."""
    lim = (1 << 35) - 1
    cases = []
    top = np.zeros(2 * F.L, np.int64)
    top[:26] = lim                      # all-max positive
    cases.append(top)
    cases.append(-top)                  # all-max negative
    nprng = np.random.default_rng(0xB16)
    for _ in range(8):
        c = nprng.integers(-lim, lim + 1, 2 * F.L).astype(np.int64)
        c[26:] = 0                      # keep |value| < q*R
        cases.append(c)
    cols = np.stack(cases)
    for c in cases:
        assert abs(wide_to_int(c)) < QR
    out = np.asarray(F.fq_redc(jnp.asarray(cols)))
    for i, c in enumerate(cases):
        assert F.limbs_to_int(out[i]) == redc_oracle(c)
        assert out[i].min() >= -1 and out[i].max() <= (1 << F.B)
        val = sum(int(out[i][k]) << (F.B * k) for k in range(F.L))
        assert -2 * Q < val < 2 * Q


def test_redc_gamma_shaped_accumulation():
    """The coeff pipeline's exact shape: 36 wide products (the fq12_mul
    gamma fan-in ceiling), wide-normalized, accumulated with coefficients
    in {-2..2}, one REDC — vs the same accumulation in exact bignums."""
    n = 36
    a_vals = [rand_fq() for _ in range(n)]
    b_vals = [rand_fq() for _ in range(n)]
    coeffs = [rng.choice([-2, -1, 1, 2]) for _ in range(n)]
    wide = F.fq_wide_norm(F.fq_mul_wide(fq_batch(a_vals), fq_batch(b_vals)))
    acc = sum(int(c) * wide[i] for i, c in enumerate(coeffs))
    out = np.asarray(F.fq_redc(acc[None]))[0]
    # out value = sum( c * xR * yR ) * R^-1 = mont(sum c*x*y), so
    # from_mont strips the remaining R factor
    want = sum(c * x * y for c, x, y in zip(coeffs, a_vals, b_vals)) % Q
    assert F.from_mont(out) == want


def test_wide_from_mont_contributes_identity_through_redc():
    """fq_wide_from_mont lifts a Montgomery element into the wide domain
    with an extra R factor, so it passes through fq_redc unchanged — the
    cyclo-squaring passthrough path."""
    vals = [0, 1, Q - 1] + [rand_fq() for _ in range(5)]
    a = fq_batch(vals)
    lifted = F.fq_wide_from_mont(a)
    out = np.asarray(F.fq_redc(lifted))
    for i, v in enumerate(vals):
        assert F.from_mont(out[i]) == v
    # and it composes additively with real products
    prod = F.fq_wide_norm(F.fq_mul_wide(a, a))
    out2 = np.asarray(F.fq_redc(prod + 2 * lifted))
    for i, v in enumerate(vals):
        assert F.from_mont(out2[i]) == (v * v + 2 * v) % Q


# ---------------------------------------------------------------------------
# Tower ops: coeff vs leaf vs the bignum oracle
# ---------------------------------------------------------------------------

def rand_fq2():
    return gt.Fq2(rand_fq(), rand_fq())


def rand_fq12():
    return gt.Fq12(gt.Fq6(rand_fq2(), rand_fq2(), rand_fq2()),
                   gt.Fq6(rand_fq2(), rand_fq2(), rand_fq2()))


def fq2_batch(vals):
    return np.stack([T.fq2_to_limbs(v) for v in vals])


def fq12_batch(vals):
    return np.stack([T.fq12_to_limbs(v) for v in vals])


def fq12_out(arr):
    arr = np.asarray(arr)
    return [T.fq12_from_limbs(arr[i]) for i in range(arr.shape[0])]


def _both_backends(fn):
    out = {}
    for mode in ("leaf", "coeff"):
        F.set_fq_redc_backend(mode)
        try:
            out[mode] = fn()
        finally:
            F.set_fq_redc_backend(None)
    return out


def test_fq2_mul_backends_match_oracle():
    a_vals = [gt.FQ2_ZERO, gt.FQ2_ONE, gt.XI] + [rand_fq2() for _ in range(5)]
    b_vals = [rand_fq2() for _ in range(len(a_vals))]
    a, b = fq2_batch(a_vals), fq2_batch(b_vals)
    # lazy rep: +q on every limb of one operand must not change values
    lazy = a + np.asarray(F.int_to_limbs(Q))
    want = [x * y for x, y in zip(a_vals, b_vals)]
    res = _both_backends(lambda: (np.asarray(T.fq2_mul(a, b)),
                                  np.asarray(T.fq2_mul(lazy, b))))
    for mode, (r, rl) in res.items():
        got = [T.fq2_from_limbs(r[i]) for i in range(r.shape[0])]
        gotl = [T.fq2_from_limbs(rl[i]) for i in range(rl.shape[0])]
        assert got == want, mode
        assert gotl == want, mode


@pytest.mark.parametrize("op,n_ops", [
    ("mul", 2), ("sqr", 1), ("line", 4), ("cyclo", 1)])
def test_fq12_ops_backends_match_oracle(op, n_ops):
    if op == "cyclo":
        # cyclotomic-subgroup elements (the _pow_abs precondition)
        a_vals = []
        for _ in range(2):
            f = rand_fq12()
            easy = f.conj() * f.inv()
            a_vals.append((easy ** (gt.q ** 2)) * easy)
    else:
        a_vals = [gt.FQ12_ONE, rand_fq12(), rand_fq12()]
    a = fq12_batch(a_vals)
    if op == "mul":
        b_vals = [rand_fq12() for _ in a_vals]
        b = fq12_batch(b_vals)
        run = lambda: np.asarray(T.fq12_mul(a, b))
        want = [x * y for x, y in zip(a_vals, b_vals)]
    elif op == "sqr":
        run = lambda: np.asarray(T.fq12_sqr(a))
        want = [x.square() for x in a_vals]
    elif op == "line":
        zero2 = gt.Fq2(0, 0)
        c_a = [rand_fq2() for _ in a_vals]
        c_v = [rand_fq2() for _ in a_vals]
        c_vw = [rand_fq2() for _ in a_vals]
        run = lambda: np.asarray(T.fq12_mul_line(
            a, fq2_batch(c_a), fq2_batch(c_v), fq2_batch(c_vw)))
        want = [f * gt.Fq12(gt.Fq6(x, v, zero2), gt.Fq6(zero2, vw, zero2))
                for f, x, v, vw in zip(a_vals, c_a, c_v, c_vw)]
    else:
        run = lambda: np.asarray(T.fq12_cyclo_sqr(a))
        want = [g * g for g in a_vals]
    res = _both_backends(run)
    assert fq12_out(res["leaf"]) == want
    assert fq12_out(res["coeff"]) == want


def test_cyclo_sqr_chained_50_coeff():
    """The value-growth regression under the coeff backend: every chained
    squaring's passthrough now rides the output REDC (no explicit
    multiply-by-one normalization), so 50 chained squarings — longer than
    the BLS parameter's 47-zero run — must stay exact."""
    f = rand_fq12()
    easy = f.conj() * f.inv()
    g = (easy ** (gt.q ** 2)) * easy
    F.set_fq_redc_backend("coeff")
    try:
        chained = fq12_batch([g])
        for _ in range(50):
            chained = T.fq12_cyclo_sqr(chained)
        assert fq12_out(chained) == [g ** (2 ** 50)]
    finally:
        F.set_fq_redc_backend(None)


# ---------------------------------------------------------------------------
# Traced REDC lane counts (the acceptance bound)
# ---------------------------------------------------------------------------
# The jaxpr walkers (`fresh_jaxpr` / `qinv_mul_lanes`) this section
# hand-rolled through PR 8 now live in the shared tracer library the
# contract engine uses (tools/analysis/trace/tracer.py) — one source of
# truth for the REDC op model; these tests assert the same numbers the
# trace tier ratchets (`make contracts`).

from tools.analysis.trace import engine as trace_engine  # noqa: E402
from tools.analysis.trace import tracer  # noqa: E402

_fresh_jaxpr = tracer.fresh_jaxpr
qinv_mul_lanes = tracer.qinv_mul_lanes


@pytest.mark.parametrize("name,leaf_lanes,coeff_lanes", [
    ("fq2_mul", 3, 2),
    ("fq12_mul", 54, 12),
    ("fq12_sqr", 36, 12),
    ("fq12_mul_line", 39, 12),
    ("fq12_cyclo_sqr", 30, 12),
])
def test_redc_lane_counts_in_traced_programs(name, leaf_lanes, coeff_lanes):
    """The headline claim, asserted on the real jaxprs: 54→12 / 39→12 /
    36→12 / 30→12 REDC lanes per tower op (and 3→2 for fq2_mul), cross-
    checked against fq.py's trace-time lane counters."""
    z2 = jnp.zeros((2, F.L), jnp.int64)
    z12 = jnp.zeros((2, 3, 2, F.L), jnp.int64)
    progs = {
        "fq2_mul": (lambda: _fresh_jaxpr(T.fq2_mul, z2, z2)),
        "fq12_mul": (lambda: _fresh_jaxpr(T.fq12_mul, z12, z12)),
        "fq12_sqr": (lambda: _fresh_jaxpr(T.fq12_sqr, z12)),
        "fq12_mul_line": (lambda: _fresh_jaxpr(
            lambda f, c: T.fq12_mul_line(f, c, c, c), z12, z2)),
        "fq12_cyclo_sqr": (lambda: _fresh_jaxpr(T.fq12_cyclo_sqr, z12)),
    }
    for mode, want in (("leaf", leaf_lanes), ("coeff", coeff_lanes)):
        F.set_fq_redc_backend(mode)
        try:
            F.reset_redc_trace_stats()
            closed = progs[name]()
            stats = F.redc_trace_stats()
        finally:
            F.set_fq_redc_backend(None)
        assert qinv_mul_lanes(closed) == want, (name, mode)
        assert stats["lanes"] == want, (name, mode)
    ratio = leaf_lanes / coeff_lanes
    if name.startswith("fq12"):
        assert ratio >= 2.5, (name, ratio)


def test_grouped_pairing_traced_lane_cut():
    """The whole-path bound bench.py's pairing_redc_ab row asserts: the
    grouped Miller + final-exponentiation traced programs carry >=2.5x
    fewer REDC lanes under coeff than leaf."""
    from consensus_specs_tpu.ops import bls_jax as BJ
    g1 = jnp.zeros((1, 2, 2, F.L), jnp.int64)
    g2 = jnp.zeros((1, 2, 2, 2, F.L), jnp.int64)
    f12 = jnp.zeros((1, 2, 3, 2, F.L), jnp.int64)
    lanes = {}
    for mode in ("leaf", "coeff"):
        with F.pinned_fq_redc_backend(mode):
            F.reset_redc_trace_stats()
            _fresh_jaxpr(BJ.miller_loop_grouped, g1, g2)
            _fresh_jaxpr(BJ.final_exponentiation_3x, f12)
            lanes[mode] = F.redc_trace_stats()["lanes"]
    assert lanes["leaf"] >= 2.5 * lanes["coeff"], lanes


def test_fq_tower_contracts_clean_and_pinned():
    """The tower's lane counts asserted THROUGH the contract engine: the
    committed TRACE_CONTRACTS run clean against the committed
    trace_baseline.json, every budget is an exact pin the engine
    re-measured, and the pins match this file's expectation table — so
    the test suite and `make contracts` cannot drift apart."""
    want = {
        "fq2_mul": (3, 2), "fq12_mul": (54, 12), "fq12_sqr": (36, 12),
        "fq12_mul_line": (39, 12), "fq12_cyclo_sqr": (30, 12)}
    contracts = [c for c in trace_engine.discover()
                 if c["name"].startswith("ops.fq_tower.")]
    assert len(contracts) == 2 * len(want)
    report = trace_engine.run_contracts(contracts)
    assert report.findings == [], [f.message for f in report.findings]
    measured = {r.name: r.measured for r in report.results}
    for op, (leaf, coeff) in want.items():
        assert measured[f"ops.fq_tower.{op}[leaf]"]["redc_lanes"] == leaf
        assert measured[f"ops.fq_tower.{op}[coeff]"]["redc_lanes"] == coeff
    # the pairing-path contracts' exact pins carry the >=2.5x whole-path
    # lane cut (miller + verdict, leaf vs coeff) as committed budgets
    budgets = {c["name"]: c["budgets"] for c in trace_engine.discover()
               if c["name"].startswith("ops.bls_jax.")}
    leaf_total = (budgets["ops.bls_jax.miller_loop_grouped[leaf]"]["redc_lanes"]
                  + budgets["ops.bls_jax.grouped_verdict[leaf]"]["redc_lanes"])
    coeff_total = (
        budgets["ops.bls_jax.miller_loop_grouped[coeff]"]["redc_lanes"]
        + budgets["ops.bls_jax.grouped_verdict[coeff]"]["redc_lanes"])
    assert leaf_total >= 2.5 * coeff_total, (leaf_total, coeff_total)


# ---------------------------------------------------------------------------
# Windowed static exponentiation (fq_inv / fq_sqrt_candidate)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("w", [1, 2, 4])
def test_pow_static_windowed_matches_per_bit_and_host(w):
    vals = [1, Q - 1] + [rand_fq() for _ in range(3)]
    a = fq_batch(vals)
    exps = [3, 0b10110111, rng.randrange(1, 1 << 64)]
    for e in exps:
        bits = F._exp_bits(e)
        win = np.asarray(F._fq_pow_static(a, bits, w=w))
        ref = np.asarray(F._fq_pow_static_per_bit(a, bits))
        for i, v in enumerate(vals):
            want = pow(v, e, Q)
            assert F.from_mont(win[i]) == want, (e, w, i)
            assert F.from_mont(ref[i]) == want, (e, i)


def test_inv_and_sqrt_use_windowed_path():
    """fq_inv / fq_sqrt_candidate ride the windowed walk by default and
    still match the host oracle (table muls included)."""
    vals = [1, Q - 1] + [rand_fq() for _ in range(3)]
    a = fq_batch(vals)
    inv = np.asarray(F.fq_inv(a))
    for i, v in enumerate(vals):
        assert F.from_mont(inv[i]) == pow(v, -1, Q)
    sq = [pow(rand_fq(), 2, Q) for _ in range(3)]
    cands = np.asarray(F.fq_sqrt_candidate(fq_batch(sq)))
    for v, c in zip(sq, cands):
        r = F.from_mont(c)
        assert r * r % Q == v
    # the windowed walk multiplies ~nbits/w + 2^w times instead of ~nbits
    per_bit = int(F._INV_EXP_BITS.shape[0])
    windowed = F.pow_static_muls(per_bit, F._POW_WINDOW)
    assert per_bit >= 2.5 * windowed, (per_bit, windowed)


# ---------------------------------------------------------------------------
# Full-path verdict parity (slow: two extra pairing compiles)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_grouped_pairing_verdict_parity_across_modes():
    """grouped_pairing_check verdicts are bit-identical between the leaf
    and coeff backends — one genuinely-cancelling group (e(P,Q)*e(-P,Q))
    and one non-identity group (e(P,Q)^2)."""
    from consensus_specs_tpu.ops import bls_jax as BJ
    P = gt.G1_GEN
    Qp = gt.G2_GEN
    negP = gt.ec_neg(P)
    g1 = np.stack([
        np.stack([BJ.g1_to_limbs(P), BJ.g1_to_limbs(negP)]),
        np.stack([BJ.g1_to_limbs(P), BJ.g1_to_limbs(P)]),
    ])
    g2 = np.stack([
        np.stack([BJ.g2_to_limbs(Qp), BJ.g2_to_limbs(Qp)]),
        np.stack([BJ.g2_to_limbs(Qp), BJ.g2_to_limbs(Qp)]),
    ])
    res = _both_backends(lambda: np.asarray(
        BJ.grouped_pairing_check(jnp.asarray(g1), jnp.asarray(g2))))
    assert res["leaf"].tolist() == [True, False]
    assert res["coeff"].tolist() == [True, False]
