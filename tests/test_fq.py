"""Differential tests: JAX limb-array field tower vs the bignum ground truth.

Every op in ops/fq.py and ops/fq_tower.py is checked bit-for-bit against
crypto/bls12_381.py on random values and the edge cases 0, 1, q-1. These are
the building blocks of the TPU pairing (ops/bls_jax.py); a subtle Montgomery
or Frobenius bug here corrupts every signature check above, so the tower gets
its own oracle suite (the gap VERDICT/ADVICE round 1 flagged).
"""
import random

import numpy as np
import pytest

from consensus_specs_tpu.crypto import bls12_381 as gt
from consensus_specs_tpu.ops import fq as F
from consensus_specs_tpu.ops import fq_tower as T

rng = random.Random(0xB15)

EDGE = [0, 1, gt.q - 1]


def rand_fq():
    return rng.randrange(gt.q)


def fq_batch(values):
    """ints -> [N, L] Montgomery device array."""
    return np.stack([F.to_mont(v) for v in values])


def fq_out(arr):
    return [F.from_mont(np.asarray(arr)[i]) for i in range(np.asarray(arr).shape[0])]


# ---------------------------------------------------------------------------
# Fq
# ---------------------------------------------------------------------------

def test_fq_roundtrip():
    vals = EDGE + [rand_fq() for _ in range(5)]
    assert fq_out(fq_batch(vals)) == vals


def test_fq_add_sub_neg():
    a_vals = EDGE + [rand_fq() for _ in range(8)]
    b_vals = [rand_fq() for _ in range(len(a_vals) - 1)] + [gt.q - 1]
    a, b = fq_batch(a_vals), fq_batch(b_vals)
    assert fq_out(F.fq_add(a, b)) == [(x + y) % gt.q for x, y in zip(a_vals, b_vals)]
    assert fq_out(F.fq_sub(a, b)) == [(x - y) % gt.q for x, y in zip(a_vals, b_vals)]
    assert fq_out(F.fq_neg(a)) == [(-x) % gt.q for x in a_vals]


def test_fq_mul():
    a_vals = EDGE + [rand_fq() for _ in range(8)]
    b_vals = [gt.q - 1, 1, 0] + [rand_fq() for _ in range(8)]
    out = fq_out(F.fq_mul(fq_batch(a_vals), fq_batch(b_vals)))
    assert out == [x * y % gt.q for x, y in zip(a_vals, b_vals)]


def test_fq_inv():
    vals = [1, gt.q - 1] + [rand_fq() for _ in range(4)]
    out = fq_out(F.fq_inv(fq_batch(vals)))
    assert out == [pow(v, -1, gt.q) for v in vals]


def test_fq_sqrt_candidate():
    # squares -> candidate recovers a root; non-residues -> candidate fails check
    sq = [pow(rand_fq(), 2, gt.q) for _ in range(4)]
    cands = fq_out(F.fq_sqrt_candidate(fq_batch(sq)))
    for v, c in zip(sq, cands):
        assert c * c % gt.q == v
    # find a non-residue (Euler criterion) and confirm the candidate is garbage
    while True:
        nr = rand_fq()
        if pow(nr, (gt.q - 1) // 2, gt.q) == gt.q - 1:
            break
    c = fq_out(F.fq_sqrt_candidate(fq_batch([nr])))[0]
    assert c * c % gt.q != nr


# ---------------------------------------------------------------------------
# Fq2 / Fq6 / Fq12
# ---------------------------------------------------------------------------

def rand_fq2():
    return gt.Fq2(rand_fq(), rand_fq())


def rand_fq6():
    return gt.Fq6(rand_fq2(), rand_fq2(), rand_fq2())


def rand_fq12():
    return gt.Fq12(rand_fq6(), rand_fq6())


def fq2_batch(vals):
    return np.stack([T.fq2_to_limbs(v) for v in vals])


def fq2_out(arr):
    arr = np.asarray(arr)
    return [T.fq2_from_limbs(arr[i]) for i in range(arr.shape[0])]


def test_fq2_ops():
    a_vals = [gt.FQ2_ZERO, gt.FQ2_ONE, gt.XI] + [rand_fq2() for _ in range(5)]
    b_vals = [rand_fq2() for _ in range(len(a_vals))]
    a, b = fq2_batch(a_vals), fq2_batch(b_vals)
    assert fq2_out(T.fq2_mul(a, b)) == [x * y for x, y in zip(a_vals, b_vals)]
    assert fq2_out(T.fq2_sqr(a)) == [x.square() for x in a_vals]
    assert fq2_out(T.fq2_add(a, b)) == [x + y for x, y in zip(a_vals, b_vals)]
    assert fq2_out(T.fq2_sub(a, b)) == [x - y for x, y in zip(a_vals, b_vals)]
    assert fq2_out(T.fq2_conj(a)) == [x.conj() for x in a_vals]
    assert fq2_out(T.fq2_mul_xi(a)) == [x * gt.XI for x in a_vals]


def test_fq2_inv():
    vals = [gt.FQ2_ONE, gt.Fq2(0, 1)] + [rand_fq2() for _ in range(3)]
    assert fq2_out(T.fq2_inv(fq2_batch(vals))) == [v.inv() for v in vals]


def fq6_batch(vals):
    return np.stack([T.fq6_to_limbs(v) for v in vals])


def fq6_out(arr):
    arr = np.asarray(arr)
    return [T.fq6_from_limbs(arr[i]) for i in range(arr.shape[0])]


def test_fq6_ops():
    a_vals = [gt.FQ6_ONE] + [rand_fq6() for _ in range(3)]
    b_vals = [rand_fq6() for _ in range(len(a_vals))]
    a, b = fq6_batch(a_vals), fq6_batch(b_vals)
    assert fq6_out(T.fq6_mul(a, b)) == [x * y for x, y in zip(a_vals, b_vals)]
    assert fq6_out(T.fq6_mul_by_v(a)) == [x.mul_by_v() for x in a_vals]
    assert fq6_out(T.fq6_inv(fq6_batch(b_vals))) == [v.inv() for v in b_vals]


def fq12_batch(vals):
    return np.stack([T.fq12_to_limbs(v) for v in vals])


def fq12_out(arr):
    arr = np.asarray(arr)
    return [T.fq12_from_limbs(arr[i]) for i in range(arr.shape[0])]


def test_fq12_ops():
    a_vals = [gt.FQ12_ONE, gt.FQ12_W] + [rand_fq12() for _ in range(3)]
    b_vals = [rand_fq12() for _ in range(len(a_vals))]
    a, b = fq12_batch(a_vals), fq12_batch(b_vals)
    assert fq12_out(T.fq12_mul(a, b)) == [x * y for x, y in zip(a_vals, b_vals)]
    assert fq12_out(T.fq12_conj(a)) == [x.conj() for x in a_vals]
    assert fq12_out(T.fq12_inv(fq12_batch(b_vals))) == [v.inv() for v in b_vals]


@pytest.mark.parametrize("k", [1, 2, 3])
def test_fq12_frobenius(k):
    """fq12_frobenius(x, k) == x^(q^k) — the bug ADVICE r1 found trips here."""
    vals = [gt.FQ12_W, rand_fq12()]
    out = fq12_out(T.fq12_frobenius(fq12_batch(vals), k))
    assert out == [v ** (gt.q ** k) for v in vals]
