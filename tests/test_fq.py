"""Differential tests: JAX limb-array field tower vs the bignum ground truth.

Every op in ops/fq.py and ops/fq_tower.py is checked bit-for-bit against
crypto/bls12_381.py on random values and the edge cases 0, 1, q-1. These are
the building blocks of the TPU pairing (ops/bls_jax.py); a subtle Montgomery
or Frobenius bug here corrupts every signature check above, so the tower gets
its own oracle suite (the gap VERDICT/ADVICE round 1 flagged).
"""
import random

import numpy as np
import pytest

from consensus_specs_tpu.crypto import bls12_381 as gt
from consensus_specs_tpu.ops import fq as F
from consensus_specs_tpu.ops import fq_tower as T

rng = random.Random(0xB15)

EDGE = [0, 1, gt.q - 1]


def rand_fq():
    return rng.randrange(gt.q)


def fq_batch(values):
    """ints -> [N, L] Montgomery device array."""
    return np.stack([F.to_mont(v) for v in values])


def fq_out(arr):
    return [F.from_mont(np.asarray(arr)[i]) for i in range(np.asarray(arr).shape[0])]


# ---------------------------------------------------------------------------
# Fq
# ---------------------------------------------------------------------------

def test_fq_roundtrip():
    vals = EDGE + [rand_fq() for _ in range(5)]
    assert fq_out(fq_batch(vals)) == vals


def test_fq_add_sub_neg():
    a_vals = EDGE + [rand_fq() for _ in range(8)]
    b_vals = [rand_fq() for _ in range(len(a_vals) - 1)] + [gt.q - 1]
    a, b = fq_batch(a_vals), fq_batch(b_vals)
    assert fq_out(F.fq_add(a, b)) == [(x + y) % gt.q for x, y in zip(a_vals, b_vals)]
    assert fq_out(F.fq_sub(a, b)) == [(x - y) % gt.q for x, y in zip(a_vals, b_vals)]
    assert fq_out(F.fq_neg(a)) == [(-x) % gt.q for x in a_vals]


def test_fq_mul():
    a_vals = EDGE + [rand_fq() for _ in range(8)]
    b_vals = [gt.q - 1, 1, 0] + [rand_fq() for _ in range(8)]
    out = fq_out(F.fq_mul(fq_batch(a_vals), fq_batch(b_vals)))
    assert out == [x * y % gt.q for x, y in zip(a_vals, b_vals)]


def test_fq_inv():
    vals = [1, gt.q - 1] + [rand_fq() for _ in range(4)]
    out = fq_out(F.fq_inv(fq_batch(vals)))
    assert out == [pow(v, -1, gt.q) for v in vals]


def test_fq_sqrt_candidate():
    # squares -> candidate recovers a root; non-residues -> candidate fails check
    sq = [pow(rand_fq(), 2, gt.q) for _ in range(4)]
    cands = fq_out(F.fq_sqrt_candidate(fq_batch(sq)))
    for v, c in zip(sq, cands):
        assert c * c % gt.q == v
    # find a non-residue (Euler criterion) and confirm the candidate is garbage
    while True:
        nr = rand_fq()
        if pow(nr, (gt.q - 1) // 2, gt.q) == gt.q - 1:
            break
    c = fq_out(F.fq_sqrt_candidate(fq_batch([nr])))[0]
    assert c * c % gt.q != nr


# ---------------------------------------------------------------------------
# Fq2 / Fq6 / Fq12
# ---------------------------------------------------------------------------

def rand_fq2():
    return gt.Fq2(rand_fq(), rand_fq())


def rand_fq6():
    return gt.Fq6(rand_fq2(), rand_fq2(), rand_fq2())


def rand_fq12():
    return gt.Fq12(rand_fq6(), rand_fq6())


def fq2_batch(vals):
    return np.stack([T.fq2_to_limbs(v) for v in vals])


def fq2_out(arr):
    arr = np.asarray(arr)
    return [T.fq2_from_limbs(arr[i]) for i in range(arr.shape[0])]


def test_fq2_ops():
    a_vals = [gt.FQ2_ZERO, gt.FQ2_ONE, gt.XI] + [rand_fq2() for _ in range(5)]
    b_vals = [rand_fq2() for _ in range(len(a_vals))]
    a, b = fq2_batch(a_vals), fq2_batch(b_vals)
    assert fq2_out(T.fq2_mul(a, b)) == [x * y for x, y in zip(a_vals, b_vals)]
    assert fq2_out(T.fq2_sqr(a)) == [x.square() for x in a_vals]
    assert fq2_out(T.fq2_add(a, b)) == [x + y for x, y in zip(a_vals, b_vals)]
    assert fq2_out(T.fq2_sub(a, b)) == [x - y for x, y in zip(a_vals, b_vals)]
    assert fq2_out(T.fq2_conj(a)) == [x.conj() for x in a_vals]
    assert fq2_out(T.fq2_mul_xi(a)) == [x * gt.XI for x in a_vals]


def test_fq2_inv():
    vals = [gt.FQ2_ONE, gt.Fq2(0, 1)] + [rand_fq2() for _ in range(3)]
    assert fq2_out(T.fq2_inv(fq2_batch(vals))) == [v.inv() for v in vals]


def fq6_batch(vals):
    return np.stack([T.fq6_to_limbs(v) for v in vals])


def fq6_out(arr):
    arr = np.asarray(arr)
    return [T.fq6_from_limbs(arr[i]) for i in range(arr.shape[0])]


def test_fq6_ops():
    a_vals = [gt.FQ6_ONE] + [rand_fq6() for _ in range(3)]
    b_vals = [rand_fq6() for _ in range(len(a_vals))]
    a, b = fq6_batch(a_vals), fq6_batch(b_vals)
    assert fq6_out(T.fq6_mul(a, b)) == [x * y for x, y in zip(a_vals, b_vals)]
    assert fq6_out(T.fq6_mul_by_v(a)) == [x.mul_by_v() for x in a_vals]
    assert fq6_out(T.fq6_inv(fq6_batch(b_vals))) == [v.inv() for v in b_vals]


def fq12_batch(vals):
    return np.stack([T.fq12_to_limbs(v) for v in vals])


def fq12_out(arr):
    arr = np.asarray(arr)
    return [T.fq12_from_limbs(arr[i]) for i in range(arr.shape[0])]


def test_fq12_ops():
    a_vals = [gt.FQ12_ONE, gt.FQ12_W] + [rand_fq12() for _ in range(3)]
    b_vals = [rand_fq12() for _ in range(len(a_vals))]
    a, b = fq12_batch(a_vals), fq12_batch(b_vals)
    assert fq12_out(T.fq12_mul(a, b)) == [x * y for x, y in zip(a_vals, b_vals)]
    assert fq12_out(T.fq12_conj(a)) == [x.conj() for x in a_vals]
    assert fq12_out(T.fq12_inv(fq12_batch(b_vals))) == [v.inv() for v in b_vals]


@pytest.mark.parametrize("k", [1, 2, 3])
def test_fq12_frobenius(k):
    """fq12_frobenius(x, k) == x^(q^k) — the bug ADVICE r1 found trips here."""
    vals = [gt.FQ12_W, rand_fq12()]
    out = fq12_out(T.fq12_frobenius(fq12_batch(vals), k))
    assert out == [v ** (gt.q ** k) for v in vals]


# ---------------------------------------------------------------------------
# Boundary ops on adversarial lazy representations
# ---------------------------------------------------------------------------

def test_fq_canon_and_eq_adversarial():
    """fq_canon/fq_is_zero/fq_eq on cascade-forcing lazy reps.

    Patterns: all-MASK limbs (+1 value), exact multiples of q as lazy sums,
    negative values, and Montgomery outputs."""
    import numpy as np
    one = F.fq_ones()
    # value 2^406-1 as limbs (all MASK), canonized
    allmask = np.full((1, F.L), F.MASK, dtype=np.int64)
    expect = ((1 << (F.B * F.L)) - 1) % gt.q
    assert F.limbs_to_int(np.asarray(F.fq_canon(allmask))[0]) == expect

    # k*q lazy sums must be exactly zero for k in {-3..3}
    qlimbs = np.asarray(F.int_to_limbs(gt.q))
    for k in range(-3, 4):
        lazy = (qlimbs * k)[None, :]
        assert bool(np.asarray(F.fq_is_zero(lazy))[0]), f"k={k}"
        assert F.limbs_to_int(np.asarray(F.fq_canon(lazy))[0]) == 0

    # x vs x + q vs x - 2q: all fq_eq, canon identical, nonzero
    x = rand_fq()
    reps = np.stack([
        np.asarray(F.int_to_limbs(x)),
        np.asarray(F.int_to_limbs(x)) + qlimbs,
        np.asarray(F.int_to_limbs(x)) - 2 * qlimbs,
    ])
    canon = np.asarray(F.fq_canon(reps))
    for i in range(3):
        assert F.limbs_to_int(canon[i]) == x
        assert not bool(np.asarray(F.fq_is_zero(reps[i:i+1]))[0])
    assert bool(np.asarray(F.fq_eq(reps[0:1], reps[1:2]))[0])
    assert bool(np.asarray(F.fq_eq(reps[1:2], reps[2:3]))[0])
    assert not bool(np.asarray(F.fq_eq(reps[0:1], one[None, :] * 0 + np.asarray(F.to_mont(1))))[0]) or x == 1


def test_fq_sqr_scale_and_tower_sqr():
    vals = [rand_fq() for _ in range(4)]
    out = fq_out(F.fq_sqr(fq_batch(vals)))
    assert out == [v * v % gt.q for v in vals]

    a2 = [rand_fq2() for _ in range(3)]
    s = [rand_fq() for _ in range(3)]
    scaled = T.fq2_scale(fq2_batch(a2), fq_batch(s))
    assert fq2_out(scaled) == [x * sv for x, sv in zip(a2, s)]
    assert fq2_out(T.fq2_sqr(fq2_batch(a2))) == [x.square() for x in a2]

    a6 = [rand_fq6() for _ in range(2)]
    assert fq6_out(T.fq6_sqr(fq6_batch(a6))) == [x.square() for x in a6]
    a12 = [rand_fq12() for _ in range(2)]
    assert fq12_out(T.fq12_sqr(fq12_batch(a12))) == [x.square() for x in a12]


def test_fq12_mul_line():
    """Sparse line multiply == full product with the assembled line element
    l = c_a + c_v*v + c_vw*(v*w) (the Miller-loop shape, bls_jax)."""
    zero2 = gt.Fq2(0, 0)
    f_vals = [rand_fq12() for _ in range(3)]
    c_a = [rand_fq2() for _ in range(3)]
    c_v = [rand_fq2() for _ in range(3)]
    c_vw = [rand_fq2() for _ in range(3)]
    want = [
        f * gt.Fq12(gt.Fq6(a, v, zero2), gt.Fq6(zero2, vw, zero2))
        for f, a, v, vw in zip(f_vals, c_a, c_v, c_vw)
    ]
    out = T.fq12_mul_line(fq12_batch(f_vals), fq2_batch(c_a),
                          fq2_batch(c_v), fq2_batch(c_vw))
    assert fq12_out(out) == want


def test_fq12_cyclo_sqr():
    """Granger–Scott squaring == generic squaring on cyclotomic-subgroup
    elements (staged via the easy part f^((q^6-1)(q^2+1)) on the oracle) —
    the final-exponentiation _pow_abs precondition in bls_jax.

    The 50-step chain is the regression for the value-growth bug: the
    ±2·conj passthrough must Montgomery-reduce its inputs or chained
    squarings (the BLS parameter has zero-runs up to 47) overflow the
    fq_mul value budget."""
    gs = []
    for _ in range(2):
        f = rand_fq12()
        easy = f.conj() * f.inv()
        gs.append((easy ** (gt.q ** 2)) * easy)
    assert fq12_out(T.fq12_cyclo_sqr(fq12_batch(gs))) == [g * g for g in gs]

    chained = fq12_batch(gs[:1])
    for _ in range(50):
        chained = T.fq12_cyclo_sqr(chained)
    assert fq12_out(chained) == [gs[0] ** (2 ** 50)]


def test_tower_eq_on_lazy_reps():
    """fq2/fq12 equality must see through non-canonical representations —
    this is the final pairing verdict path (bls_jax.pairing_product_is_one)."""
    import numpy as np
    qlimbs = np.asarray(F.int_to_limbs(gt.q))
    a = rand_fq12()
    x = T.fq12_to_limbs(a)
    y = x + qlimbs          # every component shifted by +q: same field value
    assert bool(np.asarray(T.fq12_eq(x[None], y[None]))[0])
    z = np.array(y)
    z[0, 0, 0] = z[0, 0, 0] + 1  # genuinely different value
    assert not bool(np.asarray(T.fq12_eq(x[None], z[None]))[0])

    b = rand_fq2()
    bx = T.fq2_to_limbs(b)
    assert bool(np.asarray(T.fq2_eq(bx[None], (bx - 3 * qlimbs)[None]))[0])
    assert bool(np.asarray(T.fq2_is_zero((qlimbs * np.int64(2))[None, None, :].repeat(2, 1)))[0])
