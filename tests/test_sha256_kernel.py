"""Batched SHA-256 kernel vs hashlib ground truth."""
import hashlib

import numpy as np
import pytest

from consensus_specs_tpu.ops import sha256 as k
from consensus_specs_tpu.utils.merkle import merkleize_chunks
from consensus_specs_tpu.utils.hash import zerohashes


def test_pair_hash_matches_hashlib():
    rng = np.random.default_rng(0)
    blocks = [rng.integers(0, 256, 64, dtype=np.uint8).tobytes() for _ in range(300)]
    got = k.jax_pair_hasher(blocks)
    want = [hashlib.sha256(b).digest() for b in blocks]
    assert got == want


def test_sha256_many_various_lengths():
    rng = np.random.default_rng(1)
    for length in (1, 33, 37, 55, 56, 64, 65, 100, 128, 200):
        msgs = rng.integers(0, 256, (5, length), dtype=np.uint8)
        got = k.sha256_many(msgs)
        for i in range(5):
            assert got[i].tobytes() == hashlib.sha256(msgs[i].tobytes()).digest(), length


def test_single_block_padding():
    rng = np.random.default_rng(2)
    for length in (1, 33, 37, 55):
        msgs = rng.integers(0, 256, (4, length), dtype=np.uint8)
        words = k.pad_to_single_block(msgs, length)
        digests = k.words_to_bytes(np.asarray(k.sha256_single_block(words)))
        for i in range(4):
            assert digests[i].tobytes() == hashlib.sha256(msgs[i].tobytes()).digest()


def test_device_merkle_root_matches_host():
    rng = np.random.default_rng(3)
    for n, pad_to in ((1, 1), (3, 4), (8, 8), (5, 16), (100, 128)):
        leaves = [rng.integers(0, 256, 32, dtype=np.uint8).tobytes() for _ in range(n)]
        got = k.merkle_root_from_leaves_device(leaves, pad_to)
        padded = leaves + [b"\x00" * 32] * (pad_to - n)
        assert got == merkleize_chunks(padded)


def test_device_merkle_empty():
    assert k.merkle_root_from_leaves_device([], 8) == zerohashes[3]


def test_words_roundtrip():
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, (7, 64), dtype=np.uint8)
    assert np.array_equal(k.words_to_bytes(k.bytes_to_words(data)), data)


def test_unrolled_equals_fori_rounds():
    """The two round structures must agree bit-for-bit. XLA:CPU cannot
    compile the unrolled form (simplifier loop — see ops/sha256._unroll_for),
    so this runs only against a real accelerator (CSTPU_TEST_TPU=1)."""
    import jax
    if jax.default_backend() == "cpu":
        pytest.skip("unrolled form is TPU-only (XLA:CPU simplifier loop)")
    import jax.numpy as jnp
    rng = np.random.default_rng(5)
    words = jnp.asarray(rng.integers(0, 2 ** 32, (8192, 16), dtype=np.uint32))
    a = np.asarray(k.sha256_pairs(words, unroll=True))
    b = np.asarray(k.sha256_pairs(words, unroll=False))
    assert (a == b).all()
