"""Deposit contract model vs the consensus-side SSZ Merkleizer.

The reference cross-validates its EVM contract against pyspec's
hash_tree_root(DepositData) on an in-process chain
(/root/reference deposit_contract/tests/contracts/test_deposit.py);
here the same differential runs between the contract state machine and
the framework's generic SSZ machinery + DepositTree test factory.
"""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.deposit_contract import DepositContract
from consensus_specs_tpu.deposit_contract.contract import (
    CHAIN_START_FULL_DEPOSIT_THRESHOLD, FULL_DEPOSIT_GWEI, MIN_DEPOSIT_GWEI,
    deposit_data_root)
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.testing import factories as f
from consensus_specs_tpu.utils.merkle import get_merkle_root
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root

SPEC = phase0.get_spec("minimal")


def _args(i, amount=FULL_DEPOSIT_GWEI):
    return dict(
        pubkey=bytes([i]) * 48,
        withdrawal_credentials=bytes([i + 1]) * 32,
        signature=bytes([i + 2]) * 96,
        value_gwei=amount,
    )


def test_leaf_matches_ssz_hash_tree_root():
    """The contract's hand-rolled DepositData root == generic SSZ."""
    for i in range(5):
        a = _args(i, amount=MIN_DEPOSIT_GWEI + i)
        data = SPEC.DepositData(
            pubkey=a["pubkey"],
            withdrawal_credentials=a["withdrawal_credentials"],
            amount=a["value_gwei"],
            signature=a["signature"],
        )
        assert deposit_data_root(a["pubkey"], a["withdrawal_credentials"],
                                 a["value_gwei"], a["signature"]) \
            == hash_tree_root(data, SPEC.DepositData)


@pytest.mark.parametrize("count", [1, 2, 3, 7, 10])
def test_incremental_root_matches_full_tree(count):
    """O(log n) branch accumulation == recomputing the whole padded tree."""
    contract = DepositContract()
    leaves = []
    for i in range(count):
        a = _args(i)
        contract.deposit(**a)
        leaves.append(deposit_data_root(
            a["pubkey"], a["withdrawal_credentials"], a["value_gwei"],
            a["signature"]))
        assert contract.get_deposit_root() == \
            get_merkle_root(leaves, pad_to=2 ** 32)
    assert contract.get_deposit_count() == count.to_bytes(8, "little")


def test_contract_deposits_process_on_chain():
    """e2e: a deposit made through the contract model is accepted by
    process_deposit against the contract's own root."""
    bls.bls_active = False
    state = f.seed_genesis_state(SPEC, SPEC.SLOTS_PER_EPOCH * 8)
    contract = DepositContract()

    # replay the registry's existing deposits as contract zero-leaves is
    # not possible (the mock genesis has none); start a fresh eth1 view
    state.deposit_index = 0
    newcomer = len(state.validator_registry)
    data = f.deposit_payload(SPEC, newcomer, FULL_DEPOSIT_GWEI)
    contract.deposit(
        pubkey=bytes(data.pubkey),
        withdrawal_credentials=bytes(data.withdrawal_credentials),
        signature=bytes(data.signature),
        value_gwei=int(data.amount),
    )
    state.latest_eth1_data.deposit_root = contract.get_deposit_root()
    state.latest_eth1_data.deposit_count = contract.deposit_count

    tree = f.DepositTree(SPEC, [])
    deposit = SPEC.Deposit(
        proof=list(tree.proof_of(tree.append(data))),
        data=data,
    )
    SPEC.process_deposit(state, deposit)
    assert len(state.validator_registry) == newcomer + 1
    assert state.validator_registry[newcomer].pubkey == data.pubkey


def test_rejects_malformed_deposits():
    contract = DepositContract()
    good = _args(0)
    with pytest.raises(AssertionError):
        contract.deposit(**{**good, "pubkey": b"\x00" * 47})
    with pytest.raises(AssertionError):
        contract.deposit(**{**good, "withdrawal_credentials": b"\x00" * 31})
    with pytest.raises(AssertionError):
        contract.deposit(**{**good, "signature": b"\x00" * 95})
    with pytest.raises(AssertionError):
        contract.deposit(**{**good, "value_gwei": MIN_DEPOSIT_GWEI - 1})
    assert contract.deposit_count == 0


def test_eth2genesis_fires_at_threshold(monkeypatch):
    import consensus_specs_tpu.deposit_contract.contract as c
    monkeypatch.setattr(c, "CHAIN_START_FULL_DEPOSIT_THRESHOLD", 3)
    contract = DepositContract()
    events = []
    for i in range(3):
        events.append(contract.deposit(**_args(i), timestamp=1_700_000_123))
    assert events[:2] == [None, None]
    genesis = events[2]
    assert contract.chain_started
    assert genesis.deposit_root == contract.get_deposit_root()
    assert genesis.deposit_count == (3).to_bytes(8, "little")
    t = int.from_bytes(genesis.time, "little")
    assert t % 86400 == 0 and t > 1_700_000_123


def test_partial_deposits_do_not_count_toward_genesis(monkeypatch):
    import consensus_specs_tpu.deposit_contract.contract as c
    monkeypatch.setattr(c, "CHAIN_START_FULL_DEPOSIT_THRESHOLD", 2)
    contract = DepositContract()
    assert contract.deposit(**_args(0, amount=MIN_DEPOSIT_GWEI)) is None
    assert contract.deposit(**_args(1, amount=MIN_DEPOSIT_GWEI)) is None
    assert not contract.chain_started
    assert contract.deposit(**_args(2)) is None   # first FULL deposit
    assert contract.deposit(**_args(3)) is not None
    assert contract.chain_started


def test_deposit_events_logged():
    contract = DepositContract()
    contract.deposit(**_args(5))
    (event,) = contract.logs
    assert event.pubkey == bytes([5]) * 48
    assert event.merkle_tree_index == (0).to_bytes(8, "little")
    assert event.amount == FULL_DEPOSIT_GWEI.to_bytes(8, "little")


# ---------------------------------------------------------------------------
# Native (C++) accumulator: the python <-> native differential, mirroring
# the reference's python <-> EVM cross-check
# (/root/reference deposit_contract/tests/contracts/test_deposit.py)
# ---------------------------------------------------------------------------

native = pytest.importorskip("consensus_specs_tpu.deposit_contract.native")


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
def test_native_tree_matches_python_model():
    from random import Random
    rng = Random(77)
    py = DepositContract()
    cc = native.NativeDepositTree()
    assert cc.get_deposit_root() == py.get_deposit_root()
    for i in range(33):   # crosses several subtree-completion boundaries
        pk = bytes(rng.randrange(256) for _ in range(48))
        wc = bytes(rng.randrange(256) for _ in range(32))
        sig = bytes(rng.randrange(256) for _ in range(96))
        amount = rng.choice([1_000_000_000, 32_000_000_000, 5_555_555_555])
        py.deposit(pk, wc, sig, amount)
        cc.deposit(pk, wc, sig, amount)
        assert cc.deposit_count == py.deposit_count == i + 1
        assert cc.get_deposit_root() == py.get_deposit_root(), i


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
def test_native_batch_matches_sequential():
    import numpy as np
    rng = np.random.default_rng(9)
    n = 20
    pks = rng.integers(0, 256, (n, 48), dtype=np.uint8)
    wcs = rng.integers(0, 256, (n, 32), dtype=np.uint8)
    sigs = rng.integers(0, 256, (n, 96), dtype=np.uint8)
    vals = np.full(n, 32_000_000_000, np.uint64)
    a, b = native.NativeDepositTree(), native.NativeDepositTree()
    a.deposit_batch(pks, wcs, sigs, vals)
    for i in range(n):
        b.deposit(pks[i].tobytes(), wcs[i].tobytes(), sigs[i].tobytes(),
                  int(vals[i]))
    assert a.get_deposit_root() == b.get_deposit_root()
    assert a.deposit_count == n


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
def test_native_rejects_below_minimum():
    cc = native.NativeDepositTree()
    with pytest.raises(AssertionError):
        cc.deposit(b"\x01" * 48, b"\x02" * 32, b"\x03" * 96, 999)
