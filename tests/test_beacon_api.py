"""Beacon-node API facade: the validator-client contract.

Contract: /root/reference specs/validator/beacon_node_oapi.yaml (+ intro
0_beacon-node-validator-api.md). Drives the full duty cycle a validator
client performs against a node: discover duties, produce a block, sign,
publish, produce an attestation, publish — plus every documented error
path (404 unknown pubkey, 400 invalid, 503 syncing).
"""
import pytest

from consensus_specs_tpu.api import ApiError, BeaconNodeAPI, SyncingStatus
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.testing import factories as f
from consensus_specs_tpu.testing.keys import pubkeys

SPEC = phase0.get_spec("minimal")


@pytest.fixture(autouse=True)
def _bls_off():
    old = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = old


@pytest.fixture()
def api():
    state = f.seed_genesis_state(SPEC, SPEC.SLOTS_PER_EPOCH * 8)
    f.advance_slots(SPEC, state, 3)
    return BeaconNodeAPI(SPEC, state)


def test_node_endpoints(api):
    assert "consensus-specs-tpu" in api.get_version()
    assert api.get_genesis_time() == int(api.state.genesis_time)
    assert api.get_syncing().is_syncing is False
    fork, chain_id = api.get_fork()
    assert bytes(fork.current_version) == b"\x00" * 4 and chain_id == 0


def test_duties_for_known_pubkeys(api):
    keys = [pubkeys[i] for i in range(4)]
    duties = api.get_validator_duties(keys)
    assert [d.validator_pubkey for d in duties] == [bytes(k) for k in keys]
    for d in duties:
        assert d.validator_index in d.committee
        assert 0 <= d.attestation_shard < SPEC.SHARD_COUNT
        epoch = SPEC.slot_to_epoch(d.attestation_slot)
        assert epoch == SPEC.get_current_epoch(api.state)


def test_duties_unknown_pubkey_404(api):
    with pytest.raises(ApiError) as err:
        api.get_validator_duties([b"\xfe" * 48])
    assert err.value.status == 404


def test_duties_far_epoch_406(api):
    with pytest.raises(ApiError) as err:
        api.get_validator_duties([pubkeys[0]], epoch=99)
    assert err.value.status == 406


def test_produce_sign_publish_block(api):
    slot = int(api.state.slot) + 1
    proposer = f.proposer_of(SPEC, api.state, slot)
    block = api.produce_block(slot, randao_reveal=b"\x00" * 96)
    assert int(block.slot) == slot
    assert bytes(block.state_root) != b"\x00" * 32
    f.sign_proposal(SPEC, api.state, block, proposer)
    pre_slot = int(api.state.slot)
    api.publish_block(block)
    assert int(api.state.slot) == slot > pre_slot
    assert api.published_blocks == [block]


def test_publish_invalid_block_400(api):
    block = api.produce_block(int(api.state.slot) + 1, randao_reveal=b"\x00" * 96)
    block.state_root = b"\x13" * 32     # corrupt: transition must reject
    with pytest.raises(ApiError) as err:
        api.publish_block(block)
    assert err.value.status == 400
    assert api.published_blocks == []


def test_produce_block_into_past_400(api):
    with pytest.raises(ApiError) as err:
        api.produce_block(0, randao_reveal=b"\x00" * 96)
    assert err.value.status == 400


def test_attestation_cycle(api):
    state = api.state
    # find a validator whose duty slot is already reachable
    for i in range(16):
        duty = api.get_validator_duties([pubkeys[i]])[0]
        if duty.attestation_slot <= int(state.slot):
            break
    else:
        pytest.skip("no past-duty validator in window")
    att = api.produce_attestation(
        pubkeys[i], duty.attestation_slot, duty.attestation_shard)
    assert bytes(att.signature) == b"\x00" * 96        # unsigned: client signs
    assert int(att.data.crosslink.shard) == duty.attestation_shard
    api.publish_attestation(att)
    assert api.published_attestations == [att]


def test_attestation_wrong_shard_400(api):
    duty = api.get_validator_duties([pubkeys[0]])[0]
    wrong = (duty.attestation_shard + 1) % SPEC.SHARD_COUNT
    with pytest.raises(ApiError) as err:
        api.produce_attestation(pubkeys[0], duty.attestation_slot, wrong)
    assert err.value.status == 400


def test_syncing_node_returns_503():
    state = f.seed_genesis_state(SPEC, SPEC.SLOTS_PER_EPOCH * 8)
    api = BeaconNodeAPI(SPEC, state,
                        syncing=SyncingStatus(is_syncing=True, highest_slot=99))
    for call in (lambda: api.get_validator_duties([pubkeys[0]]),
                 lambda: api.produce_block(1, b"\x00" * 96),
                 lambda: api.publish_attestation(None)):
        with pytest.raises(ApiError) as err:
            call()
        assert err.value.status == 503
    # /node/* stays available while syncing
    assert api.get_syncing().is_syncing is True
    assert api.get_version()
    # /healthz and /metrics too: the operational surface must answer
    # exactly when the node is limping (ISSUE 13 satellite)
    assert "status" in api.get_healthz()
    assert api.get_metrics() is not None


def test_healthz_reflects_degradation(api):
    from consensus_specs_tpu import resilience
    snap = api.get_healthz()
    assert snap["status"] in ("ok", "degraded")
    assert snap["rung"]["name"] in resilience.DegradationLadder.RUNGS
    assert set(snap["counters"]) >= {"retries", "deadline_misses",
                                     "faults_injected", "degradations"}
    resilience.ladder().degrade("test")
    try:
        degraded = api.get_healthz()
        assert degraded["status"] == "degraded"
        assert degraded["rung"]["index"] == 1
    finally:
        resilience.ladder().reset()
    assert api.get_healthz()["rung"]["index"] == 0


def test_healthz_firehose_section(api):
    """/healthz carries the firehose view: queue backlog, in-flight
    batches, last-flush age (ISSUE 15 satellite) — zeroed when no
    streaming verifier is active, live when one is."""
    from consensus_specs_tpu import streaming
    snap = api.get_healthz()
    assert snap["firehose"]["backlog"] == 0
    assert snap["firehose"]["last_flush_age_s"] is None
    v = streaming.StreamingVerifier(target_groups=8, register=True)
    try:
        live = api.get_healthz()["firehose"]
        assert live["target_groups"] == 8
        assert live["in_flight_batches"] == 0
        assert set(live["counters"]) >= {"ingested", "duplicates",
                                         "cache_hits", "deadline_miss",
                                         "partial_flushes"}
    finally:
        streaming.activate(None)
    assert v.queue.depth == 0


def test_metrics_expose_firehose_instruments(api):
    """The firehose gauges/counters ride /metrics (queue depth gauge,
    batch-occupancy histogram name space, deadline-miss counter)."""
    from consensus_specs_tpu import streaming
    v = streaming.StreamingVerifier(target_groups=8, register=True)
    try:
        api.get_healthz()            # touches the always-on counters
        text = api.get_metrics()
        assert "cstpu_firehose_queue_depth" in text
        assert "cstpu_firehose_deadline_miss_total" in text
        assert "cstpu_firehose_ingested_total" in text
    finally:
        streaming.activate(None)
    assert v.pipeline.in_flight == 0


def test_duty_proposal_slot_covers_future_slots(api):
    """Every slot in the rest of the epoch must be claimable by exactly one
    duty: scanning all validators' duties, the proposal slots seen must
    cover the state's remaining epoch slots."""
    duties = api.get_validator_duties(
        [pubkeys[i] for i in range(len(api.state.validator_registry))])
    slots = sorted(d.block_proposal_slot for d in duties
                   if d.block_proposal_slot is not None)
    last = SPEC.get_epoch_start_slot(SPEC.get_current_epoch(api.state)) \
        + SPEC.SLOTS_PER_EPOCH - 1
    assert slots, "someone must propose"
    assert all(int(api.state.slot) <= s <= last for s in slots)
    assert len(set(slots)) == len(slots)   # one proposer per slot
    assert int(api.state.slot) in slots    # head slot's proposer visible


def test_publish_malformed_block_maps_to_400(api):
    block = api.produce_block(int(api.state.slot) + 1, b"\x00" * 96)
    block.slot = None   # wrong-typed field: must be 400, not TypeError
    with pytest.raises(ApiError) as err:
        api.publish_block(block)
    assert err.value.status == 400


def test_attestation_poc_bit_sets_custody_bit(api):
    state = api.state
    for i in range(16):
        duty = api.get_validator_duties([pubkeys[i]])[0]
        if duty.attestation_slot <= int(state.slot):
            break
    else:
        pytest.skip("no past-duty validator in window")
    att = api.produce_attestation(
        pubkeys[i], duty.attestation_slot, duty.attestation_shard, poc_bit=1)
    position = duty.committee.index(duty.validator_index)
    assert att.custody_bitfield[position // 8] & (1 << (position % 8))
    att0 = api.produce_attestation(
        pubkeys[i], duty.attestation_slot, duty.attestation_shard, poc_bit=0)
    assert att0.custody_bitfield == bytes(len(att0.custody_bitfield))
