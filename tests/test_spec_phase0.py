"""Pytest collection shim for the dual-use spec test corpus.

The corpus lives inside the package (consensus_specs_tpu/testing/spec_tests)
so the vector generators can import the same functions; this module re-exports
every test_* function for pytest discovery under tests/, suffixed with its
module name to avoid cross-module shadowing (several modules define
test_success etc.).
"""
import importlib

_CORPUS_MODULES = [
    "consensus_specs_tpu.testing.spec_tests.block_processing.test_process_attestation",
    "consensus_specs_tpu.testing.spec_tests.block_processing.test_process_attester_slashing",
    "consensus_specs_tpu.testing.spec_tests.block_processing.test_process_block_header",
    "consensus_specs_tpu.testing.spec_tests.block_processing.test_process_deposit",
    "consensus_specs_tpu.testing.spec_tests.block_processing.test_process_proposer_slashing",
    "consensus_specs_tpu.testing.spec_tests.block_processing.test_process_transfer",
    "consensus_specs_tpu.testing.spec_tests.block_processing.test_process_voluntary_exit",
    "consensus_specs_tpu.testing.spec_tests.epoch_processing.test_process_crosslinks",
    "consensus_specs_tpu.testing.spec_tests.epoch_processing.test_process_registry_updates",
    "consensus_specs_tpu.testing.spec_tests.sanity.test_blocks",
    "consensus_specs_tpu.testing.spec_tests.sanity.test_slots",
    "consensus_specs_tpu.testing.spec_tests.test_finality",
]

for _mod_name in _CORPUS_MODULES:
    _mod = importlib.import_module(_mod_name)
    _suffix = _mod_name.rsplit(".", 1)[-1].removeprefix("test_")
    for _name, _fn in list(vars(_mod).items()):
        if _name.startswith("test_") and callable(_fn):
            globals()[f"{_name}__{_suffix}"] = _fn
