"""Pytest collection shim for the dual-use spec-test corpus.

The corpus lives inside the package (consensus_specs_tpu/testing/cases) as
table-driven scenario modules, so the vector generators can run the same
rows; this module re-exports every synthesized test_* entry for pytest
discovery under tests/, suffixed with the table name to avoid cross-module
shadowing (several tables define `success` etc.).
"""
import importlib

_CASE_TABLES = [
    "consensus_specs_tpu.testing.cases.attestation",
    "consensus_specs_tpu.testing.cases.attester_slashing",
    "consensus_specs_tpu.testing.cases.block_header",
    "consensus_specs_tpu.testing.cases.deposit",
    "consensus_specs_tpu.testing.cases.proposer_slashing",
    "consensus_specs_tpu.testing.cases.transfer",
    "consensus_specs_tpu.testing.cases.voluntary_exit",
    "consensus_specs_tpu.testing.cases.crosslinks",
    "consensus_specs_tpu.testing.cases.registry_updates",
    "consensus_specs_tpu.testing.cases.sanity_blocks",
    "consensus_specs_tpu.testing.cases.sanity_slots",
    "consensus_specs_tpu.testing.cases.finality",
]

for _mod_name in _CASE_TABLES:
    _mod = importlib.import_module(_mod_name)
    _suffix = _mod_name.rsplit(".", 1)[-1]
    for _name, _fn in list(vars(_mod).items()):
        if _name.startswith("test_") and callable(_fn):
            globals()[f"{_name}__{_suffix}"] = _fn
