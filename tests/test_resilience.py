"""Resilience subsystem tests (ISSUE 13): fault-schedule grammar,
guarded dispatch (fake-clock retry/backoff, deadline, taxonomy,
tripwires), the degradation ladder over the committed oracle knobs, and
the CSTPU_FAULTS-off no-op bound.

No test here sleeps for real: the clock and sleeper of guarded_dispatch
are injectable, so the retry/backoff assertions run in microseconds.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_specs_tpu import resilience, telemetry
from consensus_specs_tpu.resilience import dispatch as rdispatch
from consensus_specs_tpu.resilience import faults, integrity
from consensus_specs_tpu.resilience.errors import (
    DeadlineExceeded, FatalDispatchError, InjectedFault,
    TransientDispatchError)
from consensus_specs_tpu.telemetry import watchdog as wd


@pytest.fixture(autouse=True)
def _clean():
    """Every test starts fault-free, full-speed, with zeroed metrics."""
    faults.set_schedule(None)
    resilience.ladder().reset()
    telemetry.reset()
    wd.reset()
    yield
    faults.set_schedule(None)
    resilience.ladder().reset()
    telemetry.reset()
    wd.reset()


def _ctr(name):
    return telemetry.counter(name, always=True).value


# ---------------------------------------------------------------------------
# Schedule grammar
# ---------------------------------------------------------------------------

def test_schedule_grammar_round_trip():
    s = faults.parse_schedule(
        "seed=42;dispatch:*epoch*@2=raise;dispatch:*@5-7=hang:150;"
        "ckpt.write@1=truncate:33;ckpt.read@2=bitflip:4;mesh@1=lose:2")
    assert s.seed == 42 and len(s.entries) == 5
    e = s.entries[1]
    assert (e.site, e.lo, e.hi, e.action, e.param) == \
        ("dispatch", 5, 7, "hang", "150")


@pytest.mark.parametrize("bad", [
    "dispatch@0=raise",              # occurrences count from 1
    "dispatch@3-2=raise",            # inverted range
    "nosite@1=raise",                # unknown site
    "ckpt.write@1=poison",           # action/site mismatch
    "mesh:glob@1=lose:1",            # only dispatch takes a glob
    "dispatch@x=raise",              # non-integer occurrence
    "dispatch=raise",                # missing @occurrence
    "dispatch@1",                    # missing =action
])
def test_schedule_grammar_rejects(bad):
    with pytest.raises(ValueError, match="CSTPU_FAULTS|occurrence|site"):
        faults.parse_schedule(bad)


def test_env_rearm_resets_occurrence_counters(monkeypatch):
    """Disarm + re-arm of the IDENTICAL env text must parse fresh: spent
    occurrence counters from the first arming cannot make the second
    drill silently fault-free."""
    monkeypatch.setenv("CSTPU_FAULTS", "dispatch@1=raise")
    faults.set_schedule(None)
    assert faults.on_dispatch("k").action == "raise"    # occurrence spent
    assert faults.on_dispatch("k") is None
    monkeypatch.delenv("CSTPU_FAULTS")
    assert not faults.active()                          # disarm drops cache
    monkeypatch.setenv("CSTPU_FAULTS", "dispatch@1=raise")
    assert faults.on_dispatch("k").action == "raise"    # fresh counters


def test_occurrence_counting_and_glob():
    faults.set_schedule("dispatch:*epoch*@2=raise")
    assert faults.on_dispatch(("mesh.other",)) is None      # glob miss
    assert faults.on_dispatch(("mesh.epoch", 8)) is None    # occurrence 1
    fault = faults.on_dispatch(("mesh.epoch", 8))           # occurrence 2
    assert fault is not None and fault.action == "raise"
    assert faults.on_dispatch(("mesh.epoch", 8)) is None    # spent


def test_faults_inactive_when_unset(monkeypatch):
    monkeypatch.delenv("CSTPU_FAULTS", raising=False)
    faults.set_schedule(None)
    assert not faults.active()
    assert faults.on_dispatch("k") is None
    assert faults.filter_devices([1, 2, 3]) == [1, 2, 3]
    data, crash = faults.on_checkpoint_write(b"x")
    assert data == b"x" and not crash


def test_faults_env_driven(monkeypatch):
    monkeypatch.setenv("CSTPU_FAULTS", "dispatch@1=raise")
    faults.set_schedule(None)
    assert faults.active()
    assert faults.on_dispatch("anything").action == "raise"


# ---------------------------------------------------------------------------
# Guarded dispatch: retry / backoff / deadline / taxonomy (fake clock)
# ---------------------------------------------------------------------------

def test_transient_retries_with_backoff_fake_clock():
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("UNAVAILABLE: relay flaked")
        return 7

    out = rdispatch.guarded_dispatch(
        ("t", 1), flaky, retries=3, backoff_ms=25.0, sleep=sleeps.append)
    assert out == 7 and len(calls) == 3
    # exponential: 25 ms, then 50 ms — and NO real time passed
    assert sleeps == [0.025, 0.05]
    assert _ctr("resilience.retries") == 2
    assert _ctr("resilience.transient_errors") == 2


def test_transient_exhaustion_raises_typed():
    def always_down():
        raise RuntimeError("RESOURCE_EXHAUSTED: out of HBM")

    with pytest.raises(TransientDispatchError) as ei:
        rdispatch.guarded_dispatch(("t", 2), always_down, retries=2,
                                   sleep=lambda s: None)
    assert ei.value.attempts == 3


def test_predispatch_transient_retries_despite_retries_zero():
    """A donated call site pins retries=0 for post-consume safety, but a
    failure raised BEFORE the dispatch (injected raise, pre-flight
    error) leaves the argument buffers intact — the guard must honor the
    standard budget for those instead of walking the ladder on a
    one-off blip."""
    faults.set_schedule("dispatch:*donated*@1=raise")
    out = rdispatch.guarded_dispatch(
        ("donated",), lambda: 42, retries=0, sleep=lambda s: None)
    assert out == 42
    assert _ctr("resilience.retries") == 1

    # post-dispatch failures (here: a tripwire rejection) must NOT gain
    # that allowance: retries=0 means the first corrupt output raises
    with pytest.raises(rdispatch.CorruptOutput):
        rdispatch.guarded_dispatch(
            ("donated2",), lambda: 7, retries=0,
            check=lambda o: False, sleep=lambda s: None)


def test_fatal_never_retries():
    calls = []

    def buggy():
        calls.append(1)
        raise TypeError("shapes do not match")

    with pytest.raises(FatalDispatchError):
        rdispatch.guarded_dispatch(("t", 3), buggy, retries=5,
                                   sleep=lambda s: None)
    assert len(calls) == 1
    assert _ctr("resilience.fatal_errors") == 1
    assert _ctr("resilience.retries") == 0


def test_deadline_miss_fake_clock_then_recovery():
    # attempt 1 "takes" 400 ms on the fake clock, attempt 2 is instant
    times = iter([0.0, 0.4, 1.0, 1.001])
    fn = jax.jit(lambda x: x + 1)
    _ = fn(jnp.arange(4))                       # warm compile
    out = rdispatch.guarded_dispatch(
        ("t", 4), fn, jnp.arange(4), deadline_ms=100.0,
        clock=lambda: next(times), sleep=lambda s: None)
    assert np.array_equal(np.asarray(out), [1, 2, 3, 4])
    assert _ctr("resilience.deadline_misses") == 1


def test_deadline_exhaustion_raises_typed():
    t = [0.0]

    def clock():
        t[0] += 10.0
        return t[0]

    fn = jax.jit(lambda x: x + 1)
    with pytest.raises(DeadlineExceeded) as ei:
        rdispatch.guarded_dispatch(("t", 5), fn, jnp.arange(4),
                                   deadline_ms=50.0, retries=1,
                                   clock=clock, sleep=lambda s: None)
    assert ei.value.deadline_ms == 50.0 and ei.value.elapsed_ms > 50.0


def test_deadline_salvage_on_zero_retry_sites():
    """A donated call site (retries=0) gets its valid-but-late output
    BACK instead of an exception: the consumed buffers make re-dispatch
    impossible, so raising would turn lateness into unavailability (and
    on the resident path, a restore loop). The miss is still counted."""
    t = [0.0]

    def clock():
        t[0] += 10.0
        return t[0]

    fn = jax.jit(lambda x: x + 1)
    _ = fn(jnp.arange(4))
    out = rdispatch.guarded_dispatch(
        ("salv",), fn, jnp.arange(4), deadline_ms=50.0, retries=0,
        clock=clock, sleep=lambda s: None)
    assert np.array_equal(np.asarray(out), [1, 2, 3, 4])
    assert _ctr("resilience.deadline_misses") == 1
    assert _ctr("resilience.deadline_salvaged") == 1
    # ...but a late output that ALSO fails its tripwire is never salvaged
    with pytest.raises(rdispatch.DeadlineExceeded):
        rdispatch.guarded_dispatch(
            ("salv2",), fn, jnp.arange(4), deadline_ms=50.0, retries=0,
            check=lambda o: False, clock=clock, sleep=lambda s: None)


def test_injected_hang_burns_the_injected_clock():
    """A `hang` fault wedges the dispatch via the injectable sleeper —
    the deadline sees it, the suite never really sleeps."""
    faults.set_schedule("dispatch:*t6*@1=hang:400")
    t = [0.0]
    slept = []

    def sleep(s):
        slept.append(s)
        t[0] += s

    fn = jax.jit(lambda x: x * 2)
    _ = fn(jnp.arange(3))
    out = rdispatch.guarded_dispatch(
        ("t6",), fn, jnp.arange(3), deadline_ms=100.0,
        clock=lambda: t[0], sleep=sleep)
    assert np.array_equal(np.asarray(out), [0, 2, 4])
    assert 0.4 in slept                      # the injected wedge
    assert _ctr("resilience.deadline_misses") == 1
    assert _ctr("resilience.faults_injected") == 1


def test_poison_tripwire_redispatch():
    faults.set_schedule("dispatch:*t7*@1=poison:0")
    fn = jax.jit(lambda x: x + 1)

    out = rdispatch.guarded_dispatch(
        ("t7",), fn, jnp.arange(8, dtype=jnp.uint32),
        check=lambda o: bool(jnp.all(o < 1000)), sleep=lambda s: None)
    assert np.array_equal(np.asarray(out), np.arange(8, dtype=np.uint32) + 1)
    assert _ctr("resilience.corrupt_outputs") == 1
    assert _ctr("resilience.retries") == 1


def test_injected_fault_classifies_like_real_weather():
    faults.set_schedule("dispatch:*t8*@1=raise;dispatch:*t8f*@1=fatal")
    assert rdispatch.guarded_dispatch(
        ("t8",), lambda: 3, sleep=lambda s: None) == 3
    with pytest.raises(FatalDispatchError):
        rdispatch.guarded_dispatch(("t8f",), lambda: 3,
                                   sleep=lambda s: None)
    with pytest.raises(InjectedFault):
        faults.raise_injected("k", faults.Fault("raise", None, "e"))


# ---------------------------------------------------------------------------
# Degradation ladder
# ---------------------------------------------------------------------------

def test_ladder_walks_the_oracle_knobs():
    from consensus_specs_tpu.ops import fq, scalar_mul, sha256
    lad = rdispatch.DegradationLadder()
    assert lad.rung_name == "full"
    assert lad.degrade("test") == "merkle_xla"
    assert sha256.merkle_pair_backend_name() == "xla"
    assert lad.degrade("test") == "redc_leaf"
    assert fq.fq_redc_backend_name() == "leaf"
    assert lad.degrade("test") == "scalar_double_add"
    assert scalar_mul.scalar_mul_backend_name() == "double_add"
    hits = []
    lad.register_single_device(lambda: hits.append(1))
    assert lad.degrade("test") == "single_device"
    assert hits == [1]
    assert lad.exhausted and lad.degrade("test") is None
    assert _ctr("resilience.degradations") == 4
    assert telemetry.gauge("resilience.rung", always=True).value == 4
    lad.reset()
    assert lad.rung_name == "full"
    assert telemetry.gauge("resilience.rung", always=True).value == 0
    # reset returns the knobs to env control
    assert sha256._pair_backend_override is None
    assert fq.fq_redc_backend_name() in ("coeff", "leaf")
    # ...but the IRREVERSIBLE rung's history survives reset on /healthz:
    # a core that went single-device only re-shards via restore
    snap = resilience.health_snapshot()
    assert snap["counters"]["degradations.single_device"] == 1
    assert snap["status"] == "ok"      # rung gauge reset — counter remains


def test_ladder_counters_survive_telemetry_off():
    telemetry.set_enabled(False)
    try:
        lad = rdispatch.DegradationLadder()
        lad.degrade("weather")
        assert _ctr("resilience.degradations") == 1
        snap = resilience.health_snapshot()
        assert snap["counters"]["degradations"] == 1
        lad.reset()
    finally:
        telemetry.set_enabled(None)


def test_run_with_recovery_degrades_then_succeeds():
    lad = rdispatch.DegradationLadder()
    state = {"fail": True}

    def make():
        def fn():
            if state["fail"]:
                raise RuntimeError("INTERNAL: wedged")
            return 11
        return fn, ()

    # heal the moment the ladder first degrades
    lad.register_single_device(lambda: None)
    orig = lad._apply

    def apply_and_heal(name):
        state["fail"] = False
        return orig(name)

    lad._apply = apply_and_heal
    out = rdispatch.run_with_recovery(
        ("r", 1), make, ladder=lad, retries=1, sleep=lambda s: None)
    assert out == 11 and lad.rung_name == "merkle_xla"
    lad.reset()


def test_run_with_recovery_exhausted_is_fatal():
    lad = rdispatch.DegradationLadder()

    def make():
        def fn():
            raise RuntimeError("UNAVAILABLE: forever")
        return fn, ()

    with pytest.raises(FatalDispatchError):
        rdispatch.run_with_recovery(("r", 2), make, ladder=lad,
                                    retries=0, sleep=lambda s: None)
    assert lad.exhausted
    lad.reset()


# ---------------------------------------------------------------------------
# Integrity tripwires
# ---------------------------------------------------------------------------

def test_epoch_tripwire_hulls_match_range_contracts():
    hulls = integrity.declared_epoch_hulls()
    # spot-pin the committed declarations the tripwire derives from
    assert hulls["balance"] == (0, 1 << 45)
    assert hulls["effective_balance"][1] == 32 * 10 ** 9
    from consensus_specs_tpu.models.phase0.epoch_soa import ValidatorColumns
    assert set(hulls) == set(ValidatorColumns._fields)


def test_epoch_tripwire_trips_on_poison():
    from consensus_specs_tpu.models.phase0.epoch_soa import ValidatorColumns
    V = 16
    u = jnp.zeros(V, jnp.uint64)
    cols = ValidatorColumns(u, u, u, u, jnp.zeros(V, bool), u, u)
    out = (cols, None, None)
    assert integrity.epoch_output_check(out)
    bad = cols._replace(balance=u.at[3].set(jnp.uint64(1) << 60))
    assert not integrity.epoch_output_check((bad, None, None))
    # poison_tree's int corruption is exactly what the hull rejects
    poisoned = faults.poison_tree(
        out, str(list(ValidatorColumns._fields).index("balance")))
    assert not integrity.epoch_output_check(poisoned)


def test_epoch_tripwire_covers_scalar_hulls():
    """The poison surface includes the EpochScalars leaves (flattened
    indices past the 7 columns): every finitely-declared scalar hull is
    checked, so a poisoned slot/epoch/slashed-balance leaf trips the
    wire instead of chaining into justification state."""
    from consensus_specs_tpu.models.phase0.epoch_soa import (EpochScalars,
                                                             ValidatorColumns)
    V = 16
    u = jnp.zeros(V, jnp.uint64)
    cols = ValidatorColumns(u, u, u, u, jnp.zeros(V, bool), u, u)
    scal = EpochScalars(*([jnp.zeros((), jnp.uint64)] * 6),
                        latest_slashed_balances=jnp.zeros(8, jnp.uint64))
    out = (cols, scal, None)
    assert integrity.epoch_output_check(out)
    hulls = integrity.declared_epoch_scalar_hulls()
    assert hulls["slot"][1] < (1 << 64) - 1          # declared finite
    bad = scal._replace(slot=jnp.asarray(1 << 40, jnp.uint64))
    assert not integrity.epoch_output_check((cols, bad, None))
    # poison leaf 7 = the first EpochScalars leaf (slot -> uint64 max)
    assert not integrity.epoch_output_check(faults.poison_tree(out, "7"))
    # the bitfield leaf legitimately spans uint64: excluded from the
    # finite item set — the documented blind spot of a range tripwire
    assert hulls["justification_bitfield"][1] == (1 << 64) - 1
    assert "justification_bitfield" not in dict(
        integrity._finite_items(hulls))


def test_finite_check_and_float_poison():
    tree = {"a": jnp.ones((4,), jnp.float32), "b": jnp.arange(3)}
    assert integrity.finite_check(tree)
    assert not integrity.finite_check(faults.poison_tree(tree, "0"))


def test_tripwires_env_knob(monkeypatch):
    monkeypatch.delenv("CSTPU_TRIPWIRES", raising=False)
    assert integrity.tripwires_enabled()
    monkeypatch.setenv("CSTPU_TRIPWIRES", "0")
    assert not integrity.tripwires_enabled()


# ---------------------------------------------------------------------------
# Steady-state hygiene: zero overhead off, zero watchdog events guarded
# ---------------------------------------------------------------------------

def test_noop_bound_faults_off(monkeypatch):
    """CSTPU_FAULTS unset + no deadline + no check => guarded_dispatch is
    the plain watchdog call: under the same generous <20 us/op bound the
    telemetry no-op test uses (mirrors test_telemetry's)."""
    monkeypatch.delenv("CSTPU_FAULTS", raising=False)
    monkeypatch.delenv("CSTPU_DEADLINE_MS", raising=False)
    faults.set_schedule(None)
    telemetry.set_enabled(False)
    try:
        def fn():
            return None
        n = 20_000
        t0 = time.perf_counter()
        for _ in range(n):
            rdispatch.guarded_dispatch(("noop",), fn)
        per_op = (time.perf_counter() - t0) / n
        assert per_op < 20e-6, f"guarded no-op {per_op * 1e6:.2f} us/op"
    finally:
        telemetry.set_enabled(None)


def test_guarded_chain_zero_watchdog_events():
    """Chained guarded dispatches of one jitted program: the retrace
    watchdog under the guard sees one warm-up compile and NOTHING else —
    the runtime half of the guarded_epoch_chain trace contract."""
    telemetry.set_enabled(True)
    try:
        fn = jax.jit(lambda x: x * 2 + 1)
        x = jnp.arange(16)
        for _ in range(6):
            x = rdispatch.guarded_dispatch(("chain",), fn, x)
        stats = wd.stats(("chain",))
        assert stats["calls"] == 6 and stats["events"] == 0
        assert telemetry.counter("watchdog.retrace_events").value == 0
    finally:
        telemetry.set_enabled(None)


def test_trace_contract_registry_shape():
    """The committed resilience contracts: the guarded chain pins the
    SAME chained prefix as the serving-mesh contract (a ValidatorColumns
    or EpochScalars field addition must update both), and the tripwire
    contract stays collective-lean."""
    from consensus_specs_tpu.models.phase0.epoch_soa import (
        EpochScalars, ValidatorColumns)
    from consensus_specs_tpu.parallel import sharding

    [c_chain] = rdispatch.TRACE_CONTRACTS
    assert c_chain["chained_prefix"] == \
        len(ValidatorColumns._fields) + len(EpochScalars._fields)
    assert c_chain["chained_prefix"] == \
        sharding.TRACE_CONTRACTS[0]["chained_prefix"]
    [c_trip] = integrity.TRACE_CONTRACTS
    assert c_trip["collectives"] == ("all-reduce",)
    assert "device_put" in c_trip["forbid"]


def test_health_snapshot_shape():
    snap = resilience.health_snapshot()
    assert snap["status"] == "ok"
    assert snap["rung"]["name"] == "full"
    assert set(snap["counters"]) >= {"retries", "deadline_misses",
                                     "degradations", "faults_injected",
                                     "corrupt_outputs"}
    assert "last_good_generation" in snap["checkpoint"]
