"""Differential tests: device BLS backend (ops/bls_jax.py) vs the bignum
oracle (crypto/bls12_381.py).

Layers, bottom up: Jacobian point ops -> scalar mul -> Miller loop + final
exponentiation (compared to the oracle's pairing value CUBED — the device
computes f^(3e), see ops/bls_jax.py docstring) -> the five spec-facing
backend functions, which must be byte-identical to PythonBackend
(/root/reference test_libs/pyspec/eth2spec/utils/bls.py:24-46 contract).
"""
import random

import numpy as np
import pytest

from consensus_specs_tpu.crypto import bls12_381 as gt
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.ops import bls_jax as BJ
from consensus_specs_tpu.ops import fq as F
from consensus_specs_tpu.ops import fq_tower as T

rng = random.Random(0x515)


def rand_g1():
    return gt.ec_mul(gt.G1_GEN, rng.randrange(1, gt.r))


def rand_g2():
    return gt.ec_mul(gt.G2_GEN, rng.randrange(1, gt.r))


def g1_from_dev(x, y, inf):
    if bool(np.asarray(inf)):
        return None
    return (F.from_mont(np.asarray(x)), F.from_mont(np.asarray(y)))


def g2_from_dev(x, y, inf):
    if bool(np.asarray(inf)):
        return None
    return (T.fq2_from_limbs(np.asarray(x)), T.fq2_from_limbs(np.asarray(y)))


# ---------------------------------------------------------------------------
# Point arithmetic
# ---------------------------------------------------------------------------

def _dev_g1_add(p1, p2):
    """Host helper: affine oracle points -> device jac add -> affine."""
    import jax
    def lift(p):
        if p is None:
            return BJ.jac_infinity(BJ.G1_OPS)
        arr = BJ.g1_to_limbs(p)
        return (arr[0], arr[1], np.asarray(F.to_mont(1)))
    out = BJ.jac_add(BJ.G1_OPS, lift(p1), lift(p2))
    return g1_from_dev(*BJ.jac_to_affine(BJ.G1_OPS, out))


def _dev_g2_add(p1, p2):
    def lift(p):
        if p is None:
            return BJ.jac_infinity(BJ.G2_OPS)
        arr = BJ.g2_to_limbs(p)
        return (arr[0], arr[1], np.asarray(T.fq2_to_limbs(gt.FQ2_ONE)))
    out = BJ.jac_add(BJ.G2_OPS, lift(p1), lift(p2))
    return g2_from_dev(*BJ.jac_to_affine(BJ.G2_OPS, out))


def test_g1_add_cases():
    a, b = rand_g1(), rand_g1()
    assert _dev_g1_add(a, b) == gt.ec_add(a, b)          # generic
    assert _dev_g1_add(a, a) == gt.ec_double(a)          # P + P
    assert _dev_g1_add(a, gt.ec_neg(a)) is None          # P + (-P)
    assert _dev_g1_add(None, b) == b                     # O + Q
    assert _dev_g1_add(a, None) == a                     # P + O
    assert _dev_g1_add(None, None) is None               # O + O


def test_g2_add_cases():
    a, b = rand_g2(), rand_g2()
    assert _dev_g2_add(a, b) == gt.ec_add(a, b)
    assert _dev_g2_add(a, a) == gt.ec_double(a)
    assert _dev_g2_add(a, gt.ec_neg(a)) is None
    assert _dev_g2_add(None, b) == b


@pytest.mark.parametrize("k", [1, 2, 3, 0xD201000000010000, None])
def test_g2_scalar_mul(k):
    if k is None:
        k = rng.randrange(1, gt.r)
    h = rand_g2()
    arr = BJ.g2_to_limbs(h)
    out = BJ._g2_scalar_mul(arr[0], arr[1], BJ._scalar_bits(k))
    assert g2_from_dev(*out) == gt.ec_mul(h, k)


def test_g1_scalar_mul():
    k = rng.randrange(1, gt.r)
    arr = BJ.g1_to_limbs(gt.G1_GEN)
    out = BJ._g1_scalar_mul(arr[0], arr[1], BJ._scalar_bits(k))
    assert g1_from_dev(*out) == gt.ec_mul(gt.G1_GEN, k)


# ---------------------------------------------------------------------------
# Pairing
# ---------------------------------------------------------------------------

def test_pairing_value_vs_oracle_cubed():
    import jax
    P, Q = rand_g1(), rand_g2()
    fn = jax.jit(lambda x, y: BJ.final_exponentiation_3x(BJ.miller_loop_batch(x, y)))
    res = fn(np.stack([BJ.g1_to_limbs(P)]), np.stack([BJ.g2_to_limbs(Q)]))
    assert T.fq12_from_limbs(np.asarray(res)[0]) == gt.pairing(P, Q) ** 3


def test_pairing_product_check():
    P, Q = rand_g1(), rand_g2()
    g2b = np.stack([BJ.g2_to_limbs(Q), BJ.g2_to_limbs(Q)])
    good = np.stack([BJ.g1_to_limbs(P), BJ.g1_to_limbs(gt.ec_neg(P))])
    bad = np.stack([BJ.g1_to_limbs(P), BJ.g1_to_limbs(P)])
    assert bool(np.asarray(BJ.pairing_product_is_one(good, g2b)))
    assert not bool(np.asarray(BJ.pairing_product_is_one(bad, g2b)))


def test_pairing_bilinearity():
    """e([2]P, Q) * e(-P, [2]Q) == 1 — exercises distinct points per slot."""
    P, Q = rand_g1(), rand_g2()
    g1b = np.stack([BJ.g1_to_limbs(gt.ec_mul(P, 2)),
                    BJ.g1_to_limbs(gt.ec_neg(P))])
    g2b = np.stack([BJ.g2_to_limbs(Q), BJ.g2_to_limbs(gt.ec_mul(Q, 2))])
    assert bool(np.asarray(BJ.pairing_product_is_one(g1b, g2b)))


# ---------------------------------------------------------------------------
# Backend surface: byte parity with PythonBackend
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def backends():
    return gt.PythonBackend(), BJ.JaxBackend()


PRIVKEYS = [1, 2, 3, 0xDEADBEEF]
DOMAIN = 5


def test_sign_parity(backends):
    py, jx = backends
    msg = b"\x42" * 32
    for k in PRIVKEYS[:2]:
        assert jx.sign(msg, k, DOMAIN) == py.sign(msg, k, DOMAIN)


def test_aggregate_parity(backends):
    py, jx = backends
    pubs = [gt.privtopub(k) for k in PRIVKEYS]
    assert jx.aggregate_pubkeys(pubs) == py.aggregate_pubkeys(pubs)
    msg = b"\x33" * 32
    sigs = [py.sign(msg, k, DOMAIN) for k in PRIVKEYS]
    assert jx.aggregate_signatures(sigs) == py.aggregate_signatures(sigs)
    # non-power-of-two and single-element inputs
    assert jx.aggregate_pubkeys(pubs[:3]) == py.aggregate_pubkeys(pubs[:3])
    assert jx.aggregate_pubkeys(pubs[:1]) == py.aggregate_pubkeys(pubs[:1])


def test_verify_roundtrip(backends):
    _, jx = backends
    msg = b"\x77" * 32
    k = 123
    sig = jx.sign(msg, k, DOMAIN)
    pub = gt.privtopub(k)
    assert jx.verify(pub, msg, sig, DOMAIN)
    assert not jx.verify(pub, b"\x78" * 32, sig, DOMAIN)      # wrong message
    assert not jx.verify(pub, msg, sig, DOMAIN + 1)           # wrong domain
    other = gt.privtopub(k + 1)
    assert not jx.verify(other, msg, sig, DOMAIN)             # wrong key
    assert not jx.verify(pub, msg, b"\x00" * 96, DOMAIN)      # garbage sig


def test_verify_aggregate(backends):
    py, jx = backends
    msg = b"\x55" * 32
    keys = PRIVKEYS[:3]
    sigs = [py.sign(msg, k, DOMAIN) for k in keys]
    agg_sig = py.aggregate_signatures(sigs)
    agg_pub = py.aggregate_pubkeys([gt.privtopub(k) for k in keys])
    assert jx.verify(agg_pub, msg, agg_sig, DOMAIN)
    assert py.verify(agg_pub, msg, agg_sig, DOMAIN)  # oracle agrees


def test_verify_multiple(backends):
    py, jx = backends
    msgs = [b"\x01" * 32, b"\x02" * 32]
    keys = [7, 8]
    sigs = [py.sign(m, k, DOMAIN) for m, k in zip(msgs, keys)]
    agg = py.aggregate_signatures(sigs)
    pubs = [gt.privtopub(k) for k in keys]
    assert jx.verify_multiple(pubs, msgs, agg, DOMAIN)
    assert not jx.verify_multiple(pubs, msgs[::-1], agg, DOMAIN)
    assert not jx.verify_multiple(pubs, msgs, agg, DOMAIN + 1)
    # length mismatch -> False (oracle behavior)
    assert not jx.verify_multiple(pubs, msgs[:1], agg, DOMAIN)


def test_registered_backend_switch():
    """crypto.bls.set_backend('jax') works end to end and is restorable."""
    msg = b"\x99" * 32
    bls.set_backend("jax")
    try:
        sig = bls.bls_sign(msg, 42, DOMAIN)
        pub = gt.privtopub(42)
        assert bls.bls_verify(pub, msg, sig, DOMAIN)
    finally:
        bls.set_backend("python")
    assert bls.bls_verify(pub, msg, sig, DOMAIN)  # python agrees on same bytes


def test_verify_multiple_batch(backends):
    """Grouped device check == per-item oracle verdicts, mixed valid/invalid."""
    py, jx = backends
    items = []
    expected = []
    for i, (k0, k1) in enumerate([(3, 4), (5, 6), (9, 10)]):
        msgs = [bytes([i + 1]) * 32, bytes([i + 7]) * 32]
        agg = py.aggregate_signatures(
            [py.sign(m, k, DOMAIN) for m, k in zip(msgs, (k0, k1))])
        pubs = [gt.privtopub(k0), gt.privtopub(k1)]
        if i == 1:  # corrupt the middle item's message pairing
            msgs = msgs[::-1]
        items.append((pubs, msgs, agg, DOMAIN))
        expected.append(py.verify_multiple(pubs, msgs, agg, DOMAIN))
    got = jx.verify_multiple_batch(items)
    assert got == expected == [True, False, True]


def test_verify_multiple_batch_bad_encoding(backends):
    """A stage-failing item yields False without poisoning the batch."""
    py, jx = backends
    msg = b"\x21" * 32
    agg = py.aggregate_signatures([py.sign(msg, 11, DOMAIN)])
    pubs = [gt.privtopub(11)]
    good = (pubs, [msg], agg, DOMAIN)
    bad = (pubs, [msg], b"\xff" * 96, DOMAIN)   # undecodable signature
    got = jx.verify_multiple_batch([good, bad, good])
    assert got == [True, False, True]


def test_verify_multiple_batch_ragged_and_infinity(backends):
    """Mixed pair counts in one batch, plus the oracle's infinity semantics:
    an all-infinity item is an empty product (True), exactly like
    verify_multiple."""
    py, jx = backends
    msg = b"\x31" * 32
    one = (
        [gt.privtopub(13)], [msg],
        py.aggregate_signatures([py.sign(msg, 13, DOMAIN)]), DOMAIN)
    two_msgs = [b"\x32" * 32, b"\x33" * 32]
    two = (
        [gt.privtopub(14), gt.privtopub(15)], two_msgs,
        py.aggregate_signatures(
            [py.sign(m, k, DOMAIN) for m, k in zip(two_msgs, (14, 15))]),
        DOMAIN)
    empty = ([], [], gt.compress_g2(None), DOMAIN)   # infinity signature
    assert py.verify_multiple(*empty)                # oracle: empty product
    got = jx.verify_multiple_batch([one, empty, two])
    assert got == [True, True, True]


def test_aggregate_pubkeys_rejects_malformed_like_oracle(backends):
    """The fused device decompress+aggregate must reject exactly what the
    bignum oracle rejects (bad flags / off-curve), and treat the infinity
    pubkey as the identity, byte-for-byte."""
    py, jx = backends
    good = [gt.privtopub(k) for k in PRIVKEYS[:3]]
    inf = gt.compress_g1(None)
    assert jx.aggregate_pubkeys(good + [inf]) == \
        py.aggregate_pubkeys(good + [inf]) == jx.aggregate_pubkeys(good)
    # an x whose x^3+4 is a quadratic non-residue: genuinely off-curve
    x_off = next(x for x in range(2, 50)
                 if pow(x ** 3 + 4, (gt.q - 1) // 2, gt.q) != 1)
    off_curve = bytearray(x_off.to_bytes(48, "big"))
    off_curve[0] |= 0x80
    for bad in (bytes(off_curve),                       # not on curve
                bytes([good[0][0] & 0x7F]) + good[0][1:],   # c_flag unset
                bytes([0xE0]) + b"\x00" * 47):          # infinity with a_flag
        for backend in (py, jx):
            with pytest.raises(AssertionError):
                backend.aggregate_pubkeys(good + [bad])


def test_hash_to_g2_batch_matches_oracle(backends):
    """The batched device cofactor-multiply path must equal gt.hash_to_g2
    per (message, domain) pair — mixed domains in one batch."""
    from consensus_specs_tpu.ops.bls_jax import hash_to_g2_batch
    reqs = [(bytes([m]) * 32, d) for m in (1, 2, 3) for d in (0, 7)]
    got = hash_to_g2_batch(reqs)
    want = [gt.hash_to_g2(mh, d) for mh, d in reqs]
    assert got == want
    assert hash_to_g2_batch([]) == []


def test_hash_batch_threshold_parity(backends):
    """_HASH_BATCH_MIN switches per-message host bignum hashing to the
    batched device cofactor multiply once the batch's DISTINCT (message,
    domain) count reaches it; verdicts must agree with the oracle on both
    sides of the threshold (the shortcut had no direct test). Items keep
    the spec's 3-pair shape so only message count crosses the line."""
    py, jx = backends
    from consensus_specs_tpu.ops.bls_jax import _HASH_BATCH_MIN
    assert _HASH_BATCH_MIN % 2 == 0   # 2 distinct messages per item
    items = []
    expected = []
    for i in range(_HASH_BATCH_MIN // 2):
        k0, k1 = 31 + 2 * i, 32 + 2 * i
        msgs = [bytes([60 + 2 * i]) * 32, bytes([61 + 2 * i]) * 32]
        agg = py.aggregate_signatures(
            [py.sign(m, k, DOMAIN) for m, k in zip(msgs, (k0, k1))])
        if i == 1:
            msgs = msgs[::-1]   # one failing item for verdict variety
        item = ([gt.privtopub(k0), gt.privtopub(k1)], msgs, agg, DOMAIN)
        items.append(item)
        expected.append(py.verify_multiple(*item))
    assert expected[0] and not expected[1]
    # the 4 staged items serve both sides: the 3-item prefix has 6 distinct
    # (message, domain) keys -> host hashing; all 4 reach the threshold ->
    # batched device cofactor multiply
    for n_items in (len(items) - 1, len(items)):
        assert jx.verify_multiple_batch(items[:n_items]) \
            == expected[:n_items], n_items


def test_grouped_miller_matches_pairwise_product():
    """The shared-squaring multi-pairing (miller_loop_grouped) must agree
    with the differential oracle: pairwise Miller loops multiplied
    group-wise, through the same final exponentiation — on a batch with
    DISTINCT points per slot and a failing group."""
    import jax.numpy as jnp
    Ps = [rand_g1() for _ in range(4)]
    Qs = [rand_g2() for _ in range(4)]
    # group 0: e(2P0,Q0)*e(-P0,2Q0)=1 times a stray e(P1,Q1) [fails];
    # group 1: three slots that do NOT cancel [fails];
    # group 2: e(P0,Q0)*e(P0,Q0)*e(-P0,2Q0) = e(P0,Q0)^2 * e(P0,Q0)^-2
    #          — the slots genuinely cancel [passes]
    g1 = np.stack([
        np.stack([BJ.g1_to_limbs(gt.ec_mul(Ps[0], 2)),
                  BJ.g1_to_limbs(gt.ec_neg(Ps[0])),
                  BJ.g1_to_limbs(Ps[1])]),
        np.stack([BJ.g1_to_limbs(Ps[2]), BJ.g1_to_limbs(Ps[3]),
                  BJ.g1_to_limbs(gt.ec_mul(Ps[2], 5))]),
        np.stack([BJ.g1_to_limbs(Ps[0]), BJ.g1_to_limbs(Ps[0]),
                  BJ.g1_to_limbs(gt.ec_neg(Ps[0]))]),
    ])
    g2 = np.stack([
        np.stack([BJ.g2_to_limbs(Qs[0]),
                  BJ.g2_to_limbs(gt.ec_mul(Qs[0], 2)),
                  BJ.g2_to_limbs(Qs[1])]),
        np.stack([BJ.g2_to_limbs(Qs[2]), BJ.g2_to_limbs(Qs[3]),
                  BJ.g2_to_limbs(gt.ec_mul(Qs[2], 7))]),
        np.stack([BJ.g2_to_limbs(Qs[0]), BJ.g2_to_limbs(Qs[0]),
                  BJ.g2_to_limbs(gt.ec_mul(Qs[0], 2))]),
    ])

    G, P = g1.shape[0], g1.shape[1]
    f_grouped = np.asarray(BJ._miller_loop_grouped_jit(jnp.asarray(g1),
                                                       jnp.asarray(g2)))
    fs_pair = np.asarray(BJ._miller_loop_batch_jit(
        jnp.asarray(g1.reshape((G * P,) + g1.shape[2:])),
        jnp.asarray(g2.reshape((G * P,) + g2.shape[2:]))))
    verdict_grouped = np.asarray(BJ._grouped_verdict_jit(jnp.asarray(f_grouped)))
    verdict_pair = np.asarray(BJ._group_product_is_one_jit(
        jnp.asarray(fs_pair.reshape((G, P) + fs_pair.shape[1:]))))
    assert np.array_equal(verdict_grouped, verdict_pair)
    assert not bool(verdict_grouped[0])      # stray e(P1,Q1) spoils group 0
    assert not bool(verdict_grouped[1])      # the failing group fails
    assert bool(verdict_grouped[2])          # the canceling group passes
    # value-level agreement (not just verdicts): group products equal
    for g in range(G):
        prod = T.fq12_from_limbs(fs_pair[g * P])
        for p in range(1, P):
            prod = prod * T.fq12_from_limbs(fs_pair[g * P + p])
        assert T.fq12_from_limbs(f_grouped[g]) == prod, g
