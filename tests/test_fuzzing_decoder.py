"""Differential SSZ: the independent sedes codec vs utils/ssz/impl.

The reference round-trips a random BeaconState through external pyssz and
back (/root/reference test_libs/pyspec/eth2spec/fuzzing/test_decoder.py);
here random instances of every container go through both in-repo codecs
in both directions, and malformed inputs must be rejected by the sedes
decoder rather than mis-parsed.
"""
import zlib
from random import Random

import pytest

from consensus_specs_tpu.debug.random_value import (
    RandomizationMode, get_random_ssz_object)
from consensus_specs_tpu.fuzzing import translate_type, translate_value
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root, serialize

SPEC = phase0.get_spec("minimal")


@pytest.mark.parametrize("name", sorted(SPEC.container_types.keys()))
def test_cross_decode_every_container(name):
    typ = getattr(SPEC, name)
    sedes = translate_type(typ)
    rng = Random(zlib.crc32(name.encode()))
    for mode in (RandomizationMode.RANDOM, RandomizationMode.NIL,
                 RandomizationMode.LENGTHY):
        obj = get_random_ssz_object(rng, typ, mode, max_list_length=4)
        wire = serialize(obj, typ)
        # independent decode, translate back, re-serialize: must be identical
        decoded = sedes.decode(wire)
        back = translate_value(decoded, typ)
        assert serialize(back, typ) == wire
        assert hash_tree_root(back, typ) == hash_tree_root(obj, typ)
        # and the independent ENCODER must agree with the spec serializer
        assert sedes.encode(decoded) == wire


def test_random_beacon_state_roundtrip():
    typ = SPEC.BeaconState
    sedes = translate_type(typ)
    rng = Random(99)
    obj = get_random_ssz_object(rng, typ, RandomizationMode.RANDOM,
                                max_list_length=3)
    wire = serialize(obj, typ)
    back = translate_value(sedes.decode(wire), typ)
    assert hash_tree_root(back, typ) == hash_tree_root(obj, typ)


@pytest.mark.parametrize("mutilate", [
    lambda b: b[:-1],                            # truncated tail
    lambda b: b[: len(b) // 2],                  # half the message
    # absurd body offset (BeaconBlock's only variable field, at byte 72
    # after slot/parent_root/state_root)
    lambda b: b[:72] + b"\xff\xff\xff\xff" + b[76:],
])
def test_malformed_wire_rejected(mutilate):
    typ = SPEC.BeaconBlock
    sedes = translate_type(typ)
    rng = Random(3)
    obj = get_random_ssz_object(rng, typ, RandomizationMode.RANDOM,
                                max_list_length=2)
    wire = mutilate(serialize(obj, typ))
    with pytest.raises(ValueError):
        sedes.decode(wire)


def test_uint_bounds_and_bool_strictness():
    from consensus_specs_tpu.fuzzing.sedes import Boolean, UInt
    assert UInt(8).decode(b"\xff" * 8) == 2 ** 64 - 1
    with pytest.raises(ValueError):
        UInt(8).decode(b"\x00" * 7)
    with pytest.raises(ValueError):
        Boolean().decode(b"\x02")


def test_hostile_first_offset_rejected_cheaply():
    """A 4-byte input whose offset implies ~2^30 elements must fail the
    bounds check before any count-sized allocation."""
    from consensus_specs_tpu.fuzzing.sedes import HomogeneousList, UInt
    lst = HomogeneousList(UInt(8))
    with pytest.raises(ValueError):
        lst.decode(b"\xfc\xff\xff\xff")
