"""Light-client sync protocol: offline committee reconstruction + block
validity proofs.

Contract: /root/reference specs/light_client/sync_protocol.md. The load-
bearing property is that a client holding only two PeriodData objects
rebuilds the SAME persistent committee the full node computes from the
registry (get_persistent_committee, 1_shard-data-chains.md:150-177) — the
equality is asserted bit-for-bit here. Proof verification runs with real
BLS (it is a signature check by definition).
"""
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.light_client import sync_protocol as sp
from consensus_specs_tpu.models import phase1
from consensus_specs_tpu.testing import factories as f
from consensus_specs_tpu.testing.keys import privkeys


@pytest.fixture(scope="module")
def spec():
    return phase1.get_spec("minimal")


@pytest.fixture()
def state(spec):
    return f.seed_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)


def _header_at(spec, state, slot):
    return spec.BeaconBlockHeader(slot=slot, parent_root=b"\x01" * 32,
                                  state_root=b"\x02" * 32,
                                  body_root=b"\x03" * 32)


def test_reconstructed_committee_matches_full_node(spec, state):
    for shard in range(spec.SHARD_COUNT):
        for slot in (0, 1, 5, spec.SLOTS_PER_EPOCH + 3):
            header = _header_at(spec, state, slot)
            memory = sp.build_validator_memory(spec, state, slot, shard, header)
            got = sp.compute_committee(spec, header, memory)
            want = spec.get_persistent_committee(state, shard, slot)
            assert got == want, (shard, slot)
            assert got, "minimal-preset committees must be non-empty"


def test_cross_period_handover_matches_full_node(spec, state, monkeypatch):
    """The genesis-clamped regime degenerates (earlier == later period), so
    force a real two-period handover: shrink the period to 2 epochs and
    advance the state past epoch 4 — earlier/later seeds and shuffles then
    genuinely differ and the switchover union is exercised."""
    monkeypatch.setattr(spec, "PERSISTENT_COMMITTEE_PERIOD", 2)
    state.slot = 5 * spec.SLOTS_PER_EPOCH + 1
    probed_union = False
    for shard in range(spec.SHARD_COUNT):
        for slot in (state.slot - 3, state.slot):
            header = _header_at(spec, state, slot)
            memory = sp.build_validator_memory(spec, state, slot, shard, header)
            earlier, later = memory.earlier_period_data, memory.later_period_data
            assert earlier.seed != later.seed      # genuinely distinct periods
            got = sp.compute_committee(spec, header, memory)
            want = spec.get_persistent_committee(state, shard, slot)
            assert got == want, (shard, slot)
            if earlier.committee != later.committee:
                probed_union = True
    assert probed_union, "periods must shuffle differently somewhere"


def test_period_data_is_registry_free(spec, state):
    """PeriodData carries only the shard's span — O(V/SHARD_COUNT) records,
    not the registry (the ~38 bytes/epoch budget, sync_protocol.md:112)."""
    pd = sp.get_period_data(spec, state, 0, 2, later=True)
    assert pd.validator_count == len(state.validator_registry)
    assert len(pd.committee) == len(state.validator_registry) // spec.SHARD_COUNT
    assert set(pd.validators) == set(pd.committee)


def _build_proof(spec, state, shard, slot):
    header = _header_at(spec, state, slot)
    memory = sp.build_validator_memory(spec, state, slot, shard, header)
    committee = sp.compute_committee(spec, header, memory)
    parent = spec.ShardBlock(
        slot=slot, shard=shard,
        beacon_chain_root=spec.signing_root(header),
        parent_root=spec.ZERO_HASH,
        data=spec.ShardBlockBody(data=b"\x00" * spec.BYTES_PER_SHARD_BLOCK_BODY),
        state_root=spec.ZERO_HASH,
    )
    message = spec.signing_root(parent)
    domain = spec.bls_domain(spec.DOMAIN_SHARD_ATTESTER, b"\x00\x00\x00\x00")
    sigs = [bls.bls_sign(message, privkeys[i], domain) for i in committee]
    nbytes = (len(committee) + 7) // 8
    bitfield = bytes([0xFF] * nbytes)
    # mask tail bits beyond committee size (verify_bitfield requirement)
    tail = len(committee) % 8
    if tail:
        bitfield = bitfield[:-1] + bytes([(1 << tail) - 1])
    proof = sp.BlockValidityProof(
        header=header,
        shard_aggregate_signature=bls.bls_aggregate_signatures(sigs),
        shard_bitfield=bitfield,
        shard_parent_block=parent,
    )
    return proof, memory


def test_block_validity_proof_verifies(spec, state):
    old = bls.bls_active
    bls.bls_active = True
    try:
        proof, memory = _build_proof(spec, state, shard=1, slot=0)
        assert sp.verify_block_validity_proof(spec, proof, memory)
    finally:
        bls.bls_active = old


def test_block_validity_proof_rejects_tampering(spec, state):
    old = bls.bls_active
    bls.bls_active = True
    try:
        proof, memory = _build_proof(spec, state, shard=1, slot=0)
        # wrong anchor: parent block does not commit to this header
        bad = sp.BlockValidityProof(
            header=_header_at(spec, state, 1),
            shard_aggregate_signature=proof.shard_aggregate_signature,
            shard_bitfield=proof.shard_bitfield,
            shard_parent_block=proof.shard_parent_block)
        assert not sp.verify_block_validity_proof(spec, bad, memory)
        # empty support: no balance -> <= 50%
        empty = sp.BlockValidityProof(
            header=proof.header,
            shard_aggregate_signature=proof.shard_aggregate_signature,
            shard_bitfield=bytes(len(proof.shard_bitfield)),
            shard_parent_block=proof.shard_parent_block)
        assert not sp.verify_block_validity_proof(spec, empty, memory)
        # corrupted signature
        sig = bytearray(proof.shard_aggregate_signature)
        sig[5] ^= 0x01
        bad_sig = sp.BlockValidityProof(
            header=proof.header,
            shard_aggregate_signature=bytes(sig),
            shard_bitfield=proof.shard_bitfield,
            shard_parent_block=proof.shard_parent_block)
        assert not sp.verify_block_validity_proof(spec, bad_sig, memory)
    finally:
        bls.bls_active = old


def test_period_data_merkle_partial_roundtrip(spec, state):
    """The committee-update proof (sync_protocol.md:108-117): PeriodData
    ships with a multiproof a client verifies against the finalized state
    root alone — record hashes and the seed's inputs included."""
    from consensus_specs_tpu.utils.ssz.impl import hash_tree_root

    # make every randao-mix entry distinct, and every active-index-root
    # entry EXCEPT the true period-start position garbage, so proving the
    # WRONG leaf cannot accidentally verify (genesis fills them all with
    # identical values, which once masked an off-by-delay bug here). The
    # correct position must hold the real commitment: verify_period_data
    # hashes the shipped expansion against that exact leaf.
    from consensus_specs_tpu.utils.ssz.typing import List as SSZList, uint64
    for j in range(spec.LATEST_RANDAO_MIXES_LENGTH):
        state.latest_randao_mixes[j] = bytes([j]) * 32
    for j in range(spec.LATEST_ACTIVE_INDEX_ROOTS_LENGTH):
        state.latest_active_index_roots[j] = bytes([0x40 | j]) * 32
    period_start = sp.get_later_start_epoch(spec, 0)
    active = [int(i) for i in spec.get_active_validator_indices(state, period_start)]
    state.latest_active_index_roots[
        period_start % spec.LATEST_ACTIVE_INDEX_ROOTS_LENGTH] = \
        hash_tree_root(active, SSZList[uint64])

    root = hash_tree_root(state, spec.BeaconState)
    pd, proof = sp.prove_period_data(spec, state, slot=0, shard_id=2,
                                     later=True)
    ok = sp.verify_period_data(spec, root, pd, proof, slot=0, shard_id=2,
                               later=True)
    assert ok

    # tampered state root
    assert not sp.verify_period_data(spec, b"\xee" * 32, pd, proof,
                                     slot=0, shard_id=2, later=True)
    # tampered record (server lies about a member's balance)
    import copy
    pd_bad = copy.deepcopy(pd)
    victim = sorted(pd_bad.validators)[0]
    pd_bad.validators[victim].effective_balance += 1
    assert not sp.verify_period_data(spec, root, pd_bad, proof,
                                     slot=0, shard_id=2, later=True)
    # tampered seed
    pd_bad2 = copy.deepcopy(pd)
    pd_bad2.seed = b"\x55" * 32
    assert not sp.verify_period_data(spec, root, pd_bad2, proof,
                                     slot=0, shard_id=2, later=True)
    # forged committee span riding the honest proof (records/seed intact):
    # an unconditional tamper so the rejection path always runs
    pd_bad3 = copy.deepcopy(pd)
    if len(pd_bad3.committee) > 1:
        pd_bad3.committee = ([pd_bad3.committee[1], pd_bad3.committee[0]]
                             + list(pd_bad3.committee[2:]))
    else:
        pd_bad3.committee = list(pd_bad3.committee) + [0]
    assert pd_bad3.committee != list(pd.committee)
    assert not sp.verify_period_data(spec, root, pd_bad3, proof,
                                     slot=0, shard_id=2, later=True)
    # forged active-index expansion (wrong count)
    proof_bad = copy.deepcopy(proof)
    proof_bad.active_indices = proof.active_indices[:-1]
    assert not sp.verify_period_data(spec, root, pd, proof_bad,
                                     slot=0, shard_id=2, later=True)
    # tampered proof leaf
    proof.partial.values[0] = b"\x99" * 32
    assert not sp.verify_period_data(spec, root, pd, proof,
                                     slot=0, shard_id=2, later=True)


def test_period_data_proof_forgeries_rejected(spec, state):
    """The two executable forgeries from review: (a) proving a DIFFERENT
    validator's registry leaf under a claimed member, (b) proving arbitrary
    tree nodes as the seed inputs and deriving the seed from them. Both
    verify as multiproofs against the honest root; both must fail
    verify_period_data's index recomputation."""
    import copy

    from consensus_specs_tpu.light_client.multiproof import (
        LENGTH_FLAG, SSZMerkleTree, generalized_index_for_path)
    from consensus_specs_tpu.utils.ssz.impl import hash_tree_root

    root = hash_tree_root(state, spec.BeaconState)
    pd, _ = sp.prove_period_data(spec, state, slot=0, shard_id=2, later=True)
    members = sorted(pd.validators)
    outsider = next(i for i in range(len(state.validator_registry))
                    if i not in pd.validators)
    tree = SSZMerkleTree(state, spec.BeaconState)

    # (a) record substitution: claim member V holds the outsider's record,
    # prove the outsider's leaf in V's position
    victim = members[0]
    pd_forged = copy.deepcopy(pd)
    pd_forged.validators[victim] = state.validator_registry[outsider]
    paths = [["validator_registry", LENGTH_FLAG]]
    paths += [["validator_registry", outsider if i == victim else i]
              for i in members]
    period_start = sp.get_later_start_epoch(spec, 0)
    paths += sp._seed_input_paths(spec, period_start)
    forged = tree.prove([generalized_index_for_path(state, spec.BeaconState, p)
                         for p in paths])
    assert forged.verify()   # it IS a valid multiproof of the honest root
    active = [int(i) for i in spec.get_active_validator_indices(state, period_start)]
    assert not sp.verify_period_data(
        spec, root, pd_forged, sp.PeriodDataProof(forged, active),
        slot=0, shard_id=2, later=True)

    # (b) seed forgery: prove two registry leaves in the seed-input slots
    # and derive the claimed seed from them
    paths = [["validator_registry", LENGTH_FLAG]]
    paths += [["validator_registry", i] for i in members]
    paths += [["validator_registry", outsider],
              ["validator_registry", (outsider + 1) % len(state.validator_registry)]]
    idxs = [generalized_index_for_path(state, spec.BeaconState, p) for p in paths]
    forged2 = tree.prove(idxs)
    assert forged2.verify()
    pd_forged2 = copy.deepcopy(pd)
    pd_forged2.seed = spec.hash(forged2.value_at(idxs[-2])
                                + forged2.value_at(idxs[-1])
                                + spec.int_to_bytes(period_start, length=32))
    assert not sp.verify_period_data(
        spec, root, pd_forged2, sp.PeriodDataProof(forged2, active),
        slot=0, shard_id=2, later=True)


def test_typed_path_indices_agree_with_value_paths(spec, state):
    from consensus_specs_tpu.light_client.multiproof import (
        LENGTH_FLAG, generalized_index_for_path, generalized_index_for_typed_path)
    lengths = {("validator_registry",): len(state.validator_registry)}
    paths = ([["validator_registry", LENGTH_FLAG],
              ["validator_registry", 0],
              ["validator_registry", 7],
              ["latest_randao_mixes", 3],
              ["latest_active_index_roots", 1],
              ["fork"], ["slot"]])
    for p in paths:
        assert generalized_index_for_typed_path(spec.BeaconState, p, lengths) \
            == generalized_index_for_path(state, spec.BeaconState, p), p
