"""Crash-safe checkpointing tests (ISSUE 13): CRC framing, atomic-rename
generations, corruption fallback, kill-mid-write, the typed
`CheckpointCorrupt` from `ResidentCore.from_checkpoint`, and restore
across a changed serving-mesh shape / simulated device loss.
"""
import os

import pytest

from consensus_specs_tpu import resilience, telemetry
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.models.phase0.resident import ResidentCore
from consensus_specs_tpu.resilience import checkpoint as ckpt
from consensus_specs_tpu.resilience import faults
from consensus_specs_tpu.resilience.errors import (CheckpointCorrupt,
                                                   SimulatedCrash)
from consensus_specs_tpu.testing import factories
from consensus_specs_tpu.utils.ssz.impl import serialize


@pytest.fixture(autouse=True)
def _clean():
    faults.set_schedule(None)
    telemetry.reset()
    yield
    faults.set_schedule(None)
    telemetry.reset()


@pytest.fixture(scope="module")
def spec():
    bls.bls_active = False
    s = phase0.get_spec("minimal")
    s.clear_caches()
    return s


@pytest.fixture(scope="module")
def state_bytes(spec):
    state = factories.seed_genesis_state(spec, 4 * spec.SLOTS_PER_EPOCH)
    factories.advance_slots(spec, state, 2)
    return serialize(state, spec.BeaconState)


# ---------------------------------------------------------------------------
# Frame + store mechanics
# ---------------------------------------------------------------------------

def test_frame_round_trip_and_validation():
    payload = b"state-bytes" * 99
    data = ckpt.frame(payload, 7)
    gen, back = ckpt.unframe(data)
    assert (gen, back) == (7, payload)
    with pytest.raises(CheckpointCorrupt):
        ckpt.unframe(data[:10])                       # header truncated
    with pytest.raises(CheckpointCorrupt):
        ckpt.unframe(data[:-3])                       # payload truncated
    with pytest.raises(CheckpointCorrupt):
        ckpt.unframe(b"JUNK" + data[4:])              # bad magic
    flipped = bytearray(data)
    flipped[40] ^= 0x10                               # payload bit rot
    with pytest.raises(CheckpointCorrupt):
        ckpt.unframe(bytes(flipped))
    # header gen field (bytes 8..15) is outside the payload CRC: the
    # filename cross-check is its integrity cover
    gen_rot = bytearray(data)
    gen_rot[9] ^= 0x01
    with pytest.raises(CheckpointCorrupt):
        ckpt.unframe(bytes(gen_rot), generation=7)
    # raw unframe without a filename context still returns the value
    assert ckpt.unframe(bytes(gen_rot))[1] == payload


def test_store_generations_save_load_prune(tmp_path):
    st = ckpt.CheckpointStore(tmp_path, keep=3)
    for i in range(5):
        assert st.save(b"gen%d" % i) == i + 1
    assert st.generations() == [3, 4, 5]              # pruned to keep
    gen, payload = st.load()
    assert (gen, payload) == (5, b"gen4")
    gen, payload = st.load(generation=4)
    assert (gen, payload) == (4, b"gen3")
    # an explicit load of an OLDER generation (inspection) must not
    # regress what /healthz advertises as the newest restorable one
    assert ckpt.last_good_generation() == 5


def test_store_falls_back_over_corrupt_generations(tmp_path):
    st = ckpt.CheckpointStore(tmp_path, keep=4)
    st.save(b"good-one")
    faults.set_schedule("ckpt.write@1=truncate:9;ckpt.write@2=bitflip:40")
    st.save(b"truncated-on-disk")
    st.save(b"bitflipped-on-disk")
    faults.set_schedule(None)
    assert st.generations() == [1, 2, 3]              # all committed...
    gen, payload = st.load()                          # ...two corrupt
    assert (gen, payload) == (1, b"good-one")
    assert telemetry.counter("resilience.checkpoint.corrupt_generations",
                             always=True).value == 2


def test_prune_never_evicts_the_last_good_generation(tmp_path):
    """Persistent silent write corruption (every save after the first is
    truncated on disk) must not let the count-based prune walk the one
    good generation out of the store."""
    st = ckpt.CheckpointStore(tmp_path, keep=2)
    st.save(b"the-only-good-one")
    faults.set_schedule("ckpt.write@1-99=truncate:15")
    for i in range(5):
        st.save(b"corrupt-%d" % i)
    faults.set_schedule(None)
    assert 1 in st.generations()          # survived five prune rounds
    gen, payload = st.load()
    assert (gen, payload) == (1, b"the-only-good-one")
    # with a good NEWEST generation the prune is purely count-based again
    st.save(b"fresh-good")
    st.save(b"fresher-good")
    assert 1 not in st.generations()


def test_silently_corrupt_save_does_not_advance_last_good(tmp_path):
    """last_good_generation is a read-back claim: a save whose bytes a
    write fault corrupted on disk (the 'successful' silent media error)
    must not advertise itself to /healthz as restorable."""
    st = ckpt.CheckpointStore(tmp_path)
    st.save(b"good")
    assert ckpt.last_good_generation() == 1
    faults.set_schedule("ckpt.write@1=truncate:9")
    st.save(b"corrupt-on-disk")
    faults.set_schedule(None)
    assert ckpt.last_good_generation() == 1       # gen 2 never validates
    assert st.load() == (1, b"good")


def test_store_empty_and_all_corrupt_raise(tmp_path):
    st = ckpt.CheckpointStore(tmp_path)
    with pytest.raises(CheckpointCorrupt):
        st.load()
    faults.set_schedule("ckpt.write@1=truncate:999999")
    st.save(b"doomed")
    faults.set_schedule(None)
    with pytest.raises(CheckpointCorrupt):
        st.load()


def test_kill_mid_write_preserves_committed_generations(tmp_path):
    st = ckpt.CheckpointStore(tmp_path)
    st.save(b"alpha")
    st.save(b"beta")
    faults.set_schedule("ckpt.write@1=crash:0.4")
    with pytest.raises(SimulatedCrash):
        st.save(b"never-lands")
    faults.set_schedule(None)
    # the partial temp file is not a generation and never loads
    assert st.generations() == [1, 2]
    assert st.load() == (2, b"beta")
    leftovers = [n for n in os.listdir(st.root) if n.startswith(".tmp-")]
    assert leftovers, "the crash must leave the torn temp file behind"
    # the next save overwrites/renames past the debris
    assert st.save(b"gamma") == 3
    assert st.load() == (3, b"gamma")


def test_read_side_fault_hook(tmp_path):
    st = ckpt.CheckpointStore(tmp_path)
    st.save(b"pristine")
    st.save(b"latest")
    faults.set_schedule("ckpt.read@1=bitflip:35")
    gen, payload = st.load()              # newest read corrupt -> fallback
    faults.set_schedule(None)
    assert (gen, payload) == (1, b"pristine")


# ---------------------------------------------------------------------------
# from_checkpoint: typed corruption errors (the ISSUE satellite)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mutate", [
    lambda d: d[:40],                     # under the fixed-part floor
    lambda d: d[:len(d) // 2],            # mid-payload truncation
    lambda d: d[:-7],                     # tail truncation
    lambda d: b"\xff" * 600,              # garbage of plausible size
    lambda d: d[:100] + d[120:],          # 20 bytes torn out of the middle
], ids=["floor", "half", "tail", "garbage", "torn"])
def test_from_checkpoint_typed_corruption(spec, state_bytes, mutate):
    with pytest.raises(CheckpointCorrupt):
        ResidentCore.from_checkpoint(spec, mutate(state_bytes))


def test_from_checkpoint_rejects_non_bytes(spec):
    with pytest.raises(CheckpointCorrupt):
        ResidentCore.from_checkpoint(spec, None)


def test_from_checkpoint_bitflip_in_offset_table(spec, state_bytes):
    """Flip bytes in the variable-field offset table until one produces
    inconsistent framing: the error must be the TYPED class, whatever
    depth the walkers notice at."""
    saw_typed = False
    for pos in range(0, 200, 4):
        bad = bytearray(state_bytes)
        bad[pos] ^= 0x80
        try:
            core = ResidentCore.from_checkpoint(spec, bytes(bad))
            core._uninstall()              # parsed fine: flip was benign
        except CheckpointCorrupt:
            saw_typed = True
        # any OTHER exception type fails the test by propagating
    assert saw_typed, "no offset flip tripped validation (test is vacuous)"


# ---------------------------------------------------------------------------
# Store -> ResidentCore restore (mesh-shape change, device loss)
# ---------------------------------------------------------------------------

def _roots(core):
    try:
        return core.checkpoint_bytes(), core._state_root(core.state)
    finally:
        core._uninstall()


def test_restore_across_mesh_shapes(tmp_path, spec, state_bytes):
    """A checkpoint written under the 8-device serving mesh restores
    under 2 devices AND single-device, bit-identically — the payload is
    logical bytes, placement is reconstructed (ROADMAP item 4)."""
    import jax
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    if len(jax.devices()) < 8:
        pytest.skip(f"needs 8 devices, have {len(jax.devices())}")
    st = ckpt.CheckpointStore(tmp_path)
    core8 = ResidentCore.from_checkpoint(
        spec, state_bytes, mesh=ServingMesh.create(8))
    st.save(core8.checkpoint_bytes())
    ref_bytes, ref_root = _roots(core8)
    assert ref_bytes == state_bytes                   # no transition ran
    for mesh in (ServingMesh.create(2), None):
        gen, core = st.restore(spec, mesh=mesh)
        assert gen == 1
        got_bytes, got_root = _roots(core)
        assert got_bytes == ref_bytes and got_root == ref_root


def test_restore_drive_after_corrupt_newest(tmp_path, spec, state_bytes):
    """The production failover story end to end: good gen, corrupt gen,
    restart -> fallback to the good generation, REPLAY the lost slots,
    land on the reference state bit-for-bit."""
    import jax
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    if len(jax.devices()) < 8:
        pytest.skip(f"needs 8 devices, have {len(jax.devices())}")
    spe = int(spec.SLOTS_PER_EPOCH)
    ref = ResidentCore.from_checkpoint(
        spec, state_bytes, mesh=ServingMesh.create(8))
    start = int(ref.state.slot)
    mid = (start // spe + 1) * spe + 1
    end = mid + spe
    ref.process_slots(ref.state, mid)
    mid_bytes = ref.checkpoint_bytes()
    ref.process_slots(ref.state, end)
    ref_bytes, ref_root = _roots(ref)

    st = ckpt.CheckpointStore(tmp_path)
    st.save(mid_bytes)                                 # good
    faults.set_schedule("ckpt.write@1=truncate:21")
    st.save(b"whatever-came-later")                    # corrupt on disk
    faults.set_schedule(None)
    gen, core = st.restore(spec, mesh=ServingMesh.create(8))
    assert gen == 1
    core.process_slots(core.state, end)                # replay
    got_bytes, got_root = _roots(core)
    assert got_bytes == ref_bytes and got_root == ref_root


def test_mesh_device_loss_rounds_down(spec):
    """`mesh=lose:k` drops devices at construction; ServingMesh.available
    re-plans to the largest surviving power of two — the
    restore-after-hardware-loss entry."""
    import jax
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    if len(jax.devices()) < 8:
        pytest.skip(f"needs 8 devices, have {len(jax.devices())}")
    faults.set_schedule("mesh@1=lose:1")
    mesh = ServingMesh.available()
    faults.set_schedule(None)
    assert mesh is not None and mesh.size == 4        # 7 survivors -> 4
    assert telemetry.counter("resilience.faults.lose",
                             always=True).value == 1
    assert ServingMesh.available().size == 8          # loss was one-shot


def test_healthz_reports_rung_and_checkpoints(tmp_path, spec, state_bytes):
    """/healthz through the API layer: rung + counters + last good
    generation, served while syncing AND degraded."""
    from consensus_specs_tpu.api.beacon_node import (BeaconNodeAPI,
                                                     SyncingStatus)
    from consensus_specs_tpu.utils.ssz.impl import deserialize
    state = deserialize(state_bytes, spec.BeaconState)
    api = BeaconNodeAPI(spec, state,
                        syncing=SyncingStatus(is_syncing=True))
    st = ckpt.CheckpointStore(tmp_path)
    st.save(b"x" * 64)
    resilience.ladder().degrade("test")
    try:
        snap = api.get_healthz()                      # no 503 while syncing
    finally:
        resilience.ladder().reset()
    assert snap["status"] == "degraded"
    assert snap["rung"]["name"] == "merkle_xla"
    assert snap["checkpoint"]["last_good_generation"] == 1
    assert snap["checkpoint"]["saves"] == 1
