"""Device G1 decompression vs the bignum oracle.

Contract: the compressed-point grammar of bls_signature.md:36-64 as
implemented by crypto/bls12_381.decompress_g1 (:368-386) — same accepted
set, same rejected set, same (x, y) for every valid encoding.
"""
import numpy as np

from consensus_specs_tpu.crypto import bls12_381 as gt
from consensus_specs_tpu.ops import decompress as D
from consensus_specs_tpu.ops import fq as F


def _oracle(data: bytes):
    try:
        return gt.decompress_g1(data)   # None = infinity
    except AssertionError:
        return "invalid"


def _batch(encodings):
    data = np.stack([np.frombuffer(e, np.uint8) for e in encodings])
    x, y, valid, inf = D.g1_decompress_batch(data)
    out = []
    for i in range(len(encodings)):
        if not valid[i]:
            out.append("invalid")
        elif inf[i]:
            out.append(None)
        else:
            out.append((F.from_mont(np.asarray(x)[i]),
                        F.from_mont(np.asarray(y)[i])))
    return out


def test_valid_points_match_oracle():
    encodings = [gt.compress_g1(gt.ec_mul(gt.G1_GEN, k)) for k in range(1, 9)]
    got = _batch(encodings)
    want = [_oracle(e) for e in encodings]
    assert got == want
    assert all(isinstance(p, tuple) for p in got)


def test_infinity_encoding():
    inf = gt.compress_g1(None)
    assert _batch([inf]) == [None] == [_oracle(inf)]


def test_malformed_encodings_rejected():
    base = bytearray(gt.compress_g1(gt.ec_mul(gt.G1_GEN, 3)))
    cases = []
    no_c = bytes([base[0] & 0x7F]) + bytes(base[1:])          # c_flag unset
    cases.append(no_c)
    bad_inf = bytes([0xC0 | 0x20]) + b"\x00" * 47             # b with a set
    cases.append(bad_inf)
    bad_inf2 = bytes([0xC0]) + b"\x00" * 46 + b"\x01"         # b with x != 0
    cases.append(bad_inf2)
    over_q = bytearray((F.Q + 1).to_bytes(48, "big"))
    over_q[0] |= 0x80                                          # x >= q
    cases.append(bytes(over_q))
    off_curve = bytearray(base)
    off_curve[-1] ^= 0x01                                      # x not on curve (w.h.p.)
    cases.append(bytes(off_curve))
    got = _batch(cases)
    want = [_oracle(bytes(c)) for c in cases]
    assert got == want
    assert all(v == "invalid" for v in want[:4])


def test_both_sign_flags_roundtrip():
    pt = gt.ec_mul(gt.G1_GEN, 7)
    x, y = pt
    enc_pos = gt.compress_g1((x, y))
    enc_neg = gt.compress_g1((x, gt.q - y))
    got = _batch([enc_pos, enc_neg])
    assert got[0] == (x, y)
    assert got[1] == (x, gt.q - y)
    assert got[0] != got[1]


def test_large_batch_matches():
    encodings = [gt.compress_g1(gt.ec_mul(gt.G1_GEN, k)) for k in range(1, 33)]
    rng = np.random.default_rng(0)
    corrupt = rng.integers(0, 256, (4, 48), dtype=np.uint8).tobytes()
    encodings += [corrupt[i * 48:(i + 1) * 48] for i in range(4)]
    got = _batch(encodings)
    want = [_oracle(bytes(e)) for e in encodings]
    assert got == want


# ---------------------------------------------------------------------------
# G2 (decompress_g2 oracle: crypto/bls12_381.py:398-419, Fq2 sqrt :430-441)
# ---------------------------------------------------------------------------

def _g2_oracle(data: bytes):
    try:
        return gt.decompress_g2(data)
    except AssertionError:
        return "invalid"


def _g2_batch(encodings):
    from consensus_specs_tpu.ops import fq_tower as T
    data = np.stack([np.frombuffer(e, np.uint8) for e in encodings])
    x, y, valid, inf = D.g2_decompress_batch(data)
    out = []
    for i in range(len(encodings)):
        if not valid[i]:
            out.append("invalid")
        elif inf[i]:
            out.append(None)
        else:
            out.append((T.fq2_from_limbs(np.asarray(x)[i]),
                        T.fq2_from_limbs(np.asarray(y)[i])))
    return out


def test_g2_valid_points_match_oracle():
    encodings = [gt.compress_g2(gt.ec_mul(gt.G2_GEN, k)) for k in range(1, 7)]
    assert _g2_batch(encodings) == [_g2_oracle(e) for e in encodings]


def test_g2_infinity_and_malformed():
    good = gt.compress_g2(gt.ec_mul(gt.G2_GEN, 5))
    inf = gt.compress_g2(None)
    cases = [
        inf,
        bytes([good[0] & 0x7F]) + good[1:],           # c_flag unset
        bytes([0xE0]) + b"\x00" * 95,                 # infinity with a_flag
        bytes([0xC0]) + b"\x00" * 46 + b"\x01" + b"\x00" * 48,  # inf, x1 != 0
        bytes([0xC0]) + b"\x00" * 47 + b"\x01" + b"\x00" * 47,  # inf, x2 != 0
        good[:48] + bytes([0x80]) + good[49:],        # z2 flag bits set
    ]
    got = _g2_batch(cases)
    want = [_g2_oracle(c) for c in cases]
    assert got == want
    assert want[0] is None and all(v == "invalid" for v in want[1:])


def test_g2_both_signs_and_offcurve():
    x, y = gt.ec_mul(gt.G2_GEN, 9)
    enc_pos = gt.compress_g2((x, y))
    enc_neg = gt.compress_g2((x, -y))
    # an x2 whose y2 is a non-square: probe small reals with zero imaginary
    bad = None
    for c0 in range(2, 60):
        probe = bytearray(96)
        probe[0] = 0x80
        probe[48:] = c0.to_bytes(48, "big")
        if _g2_oracle(bytes(probe)) == "invalid":
            bad = bytes(probe)
            break
    assert bad is not None
    got = _g2_batch([enc_pos, enc_neg, bad])
    want = [_g2_oracle(enc_pos), _g2_oracle(enc_neg), "invalid"]
    assert got == want
    assert got[0] != got[1]


def test_g2_real_y_sign_branch():
    """Adversarial encodings whose y has ZERO imaginary part: the a_flag is
    insensitive there (both roots have c1 == 0), so the sign comes from the
    oracle's max-(c1, c0) rule composed with the flag flip — the
    flag-insensitive branch of ops/decompress._fq2_sign_flip. Constructed
    algebraically: choose x = a + bi with (x^3 + B).c1 == 0, i.e.
    3a^2 b - b^3 + 4 == 0 -> a^2 = (b^3 - 4) / (3b)."""
    q = gt.q
    found = []
    for b in range(1, 80):
        a2 = (b ** 3 - 4) * pow(3 * b, q - 2, q) % q
        if pow(a2, (q - 1) // 2, q) != 1:
            continue                      # a not in Fq
        a = pow(a2, (q + 1) // 4, q)
        x = gt.Fq2(a, b)
        y2 = x * x * x + gt.G2_B
        assert y2.c1 == 0
        y = gt.modular_squareroot(y2)
        if y is None:
            continue                      # not a square at all
        if y.c1 != 0:
            continue                      # root came out purely imaginary
        assert y.c0 != 0
        found.append(x)
        if len(found) == 2:
            break
    assert found, "construction must yield real-y points"
    cases = []
    for x in found:
        for flag in (0, 1):
            z1 = (x.c1 | (1 << 383) | (flag << 381)).to_bytes(48, "big")
            cases.append(z1 + x.c0.to_bytes(48, "big"))
    got = _g2_batch(cases)
    want = [_g2_oracle(c) for c in cases]
    assert got == want
    for k in range(0, len(cases), 2):     # the two flags give distinct roots
        assert got[k] != got[k + 1]
        assert got[k][1].c1 == 0 == got[k + 1][1].c1
