"""Device G1 decompression vs the bignum oracle.

Contract: the compressed-point grammar of bls_signature.md:36-64 as
implemented by crypto/bls12_381.decompress_g1 (:368-386) — same accepted
set, same rejected set, same (x, y) for every valid encoding.
"""
import numpy as np

from consensus_specs_tpu.crypto import bls12_381 as gt
from consensus_specs_tpu.ops import decompress as D
from consensus_specs_tpu.ops import fq as F


def _oracle(data: bytes):
    try:
        return gt.decompress_g1(data)   # None = infinity
    except AssertionError:
        return "invalid"


def _batch(encodings):
    data = np.stack([np.frombuffer(e, np.uint8) for e in encodings])
    x, y, valid, inf = D.g1_decompress_batch(data)
    out = []
    for i in range(len(encodings)):
        if not valid[i]:
            out.append("invalid")
        elif inf[i]:
            out.append(None)
        else:
            out.append((F.from_mont(np.asarray(x)[i]),
                        F.from_mont(np.asarray(y)[i])))
    return out


def test_valid_points_match_oracle():
    encodings = [gt.compress_g1(gt.ec_mul(gt.G1_GEN, k)) for k in range(1, 9)]
    got = _batch(encodings)
    want = [_oracle(e) for e in encodings]
    assert got == want
    assert all(isinstance(p, tuple) for p in got)


def test_infinity_encoding():
    inf = gt.compress_g1(None)
    assert _batch([inf]) == [None] == [_oracle(inf)]


def test_malformed_encodings_rejected():
    base = bytearray(gt.compress_g1(gt.ec_mul(gt.G1_GEN, 3)))
    cases = []
    no_c = bytes([base[0] & 0x7F]) + bytes(base[1:])          # c_flag unset
    cases.append(no_c)
    bad_inf = bytes([0xC0 | 0x20]) + b"\x00" * 47             # b with a set
    cases.append(bad_inf)
    bad_inf2 = bytes([0xC0]) + b"\x00" * 46 + b"\x01"         # b with x != 0
    cases.append(bad_inf2)
    over_q = bytearray((F.Q + 1).to_bytes(48, "big"))
    over_q[0] |= 0x80                                          # x >= q
    cases.append(bytes(over_q))
    off_curve = bytearray(base)
    off_curve[-1] ^= 0x01                                      # x not on curve (w.h.p.)
    cases.append(bytes(off_curve))
    got = _batch(cases)
    want = [_oracle(bytes(c)) for c in cases]
    assert got == want
    assert all(v == "invalid" for v in want[:4])


def test_both_sign_flags_roundtrip():
    pt = gt.ec_mul(gt.G1_GEN, 7)
    x, y = pt
    enc_pos = gt.compress_g1((x, y))
    enc_neg = gt.compress_g1((x, gt.q - y))
    got = _batch([enc_pos, enc_neg])
    assert got[0] == (x, y)
    assert got[1] == (x, gt.q - y)
    assert got[0] != got[1]


def test_large_batch_matches():
    encodings = [gt.compress_g1(gt.ec_mul(gt.G1_GEN, k)) for k in range(1, 33)]
    rng = np.random.default_rng(0)
    corrupt = rng.integers(0, 256, (4, 48), dtype=np.uint8).tobytes()
    encodings += [corrupt[i * 48:(i + 1) * 48] for i in range(4)]
    got = _batch(encodings)
    want = [_oracle(bytes(e)) for e in encodings]
    assert got == want
