"""Differential tests: SoA device epoch transition vs. the object-model spec.

Every scenario runs `spec.process_epoch` (reference-semantics Python) and
`process_epoch_soa` (jitted [V]-array program) on deep copies of the same
state and requires identical post-state hash_tree_root — the strongest
whole-state equality the reference itself uses (ssz_typing __eq__ by root).
"""
import random
from copy import deepcopy

import numpy as np
import pytest

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.models.phase0.epoch_soa import process_epoch_soa
from consensus_specs_tpu.testing.cases.finality import attested_epoch
from consensus_specs_tpu.testing.factories import (
    advance_epoch as next_epoch,
    seed_genesis_state as create_genesis_state,
    transition_with_empty_block as apply_empty_block,
)
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root


@pytest.fixture(scope="module")
def spec():
    return phase0.get_spec("minimal")


@pytest.fixture(autouse=True)
def _bls_off():
    old = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = old


def assert_same_epoch_transition(spec, state):
    """Run both epoch paths at the end-of-epoch boundary and diff the states."""
    # process_epoch fires inside process_slot when (slot+1) % SLOTS_PER_EPOCH == 0;
    # align to the boundary, then call the sub-transition directly on copies.
    if (state.slot + 1) % spec.SLOTS_PER_EPOCH != 0:
        spec.process_slots(
            state, state.slot + spec.SLOTS_PER_EPOCH - 1 - state.slot % spec.SLOTS_PER_EPOCH)
    ref, soa = deepcopy(state), deepcopy(state)
    spec.process_epoch(ref)
    process_epoch_soa(spec, soa)
    assert hash_tree_root(ref) == hash_tree_root(soa)
    return ref


def test_genesis_epoch_transition(spec):
    state = create_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)
    assert_same_epoch_transition(spec, state)


def test_empty_epochs(spec):
    state = create_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)
    for _ in range(3):
        next_epoch(spec, state)
        apply_empty_block(spec, state)
    assert_same_epoch_transition(spec, state)


def test_epochs_with_attestations(spec):
    state = create_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)
    next_epoch(spec, state)
    apply_empty_block(spec, state)
    for fill_cur, fill_prev in ((True, False), (True, True), (False, True)):
        _, _, state = attested_epoch(spec, state, current=fill_cur, previous=fill_prev)
        assert_same_epoch_transition(spec, deepcopy(state))


def test_justification_and_finalization_parity(spec):
    """Drive enough attested epochs that justification + finalization fire."""
    state = create_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)
    next_epoch(spec, state)
    apply_empty_block(spec, state)
    for _ in range(4):
        _, _, state = attested_epoch(spec, state, current=True)
        assert_same_epoch_transition(spec, deepcopy(state))
    assert state.finalized_epoch > 0  # the scenario actually exercises finality


def test_slashed_and_ejected_validators(spec):
    state = create_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)
    next_epoch(spec, state)
    apply_empty_block(spec, state)
    _, _, state = attested_epoch(spec, state, current=True, previous=True)

    rng = random.Random(1234)
    current_epoch = spec.get_current_epoch(state)
    # Slash a few validators the way slash_validator would leave them
    for i in rng.sample(range(len(state.validator_registry)), 4):
        v = state.validator_registry[i]
        v.slashed = True
        v.exit_epoch = current_epoch + 1
        v.withdrawable_epoch = current_epoch + spec.LATEST_SLASHED_EXIT_LENGTH
        state.latest_slashed_balances[current_epoch % spec.LATEST_SLASHED_EXIT_LENGTH] += \
            v.effective_balance
    # One validator mid-way to the slashing-penalty epoch
    v = state.validator_registry[7]
    v.slashed = True
    v.exit_epoch = current_epoch
    v.withdrawable_epoch = current_epoch + spec.LATEST_SLASHED_EXIT_LENGTH // 2
    # Drop some balances below ejection
    for i in rng.sample(range(len(state.validator_registry)), 5):
        if not state.validator_registry[i].slashed:
            state.validator_registry[i].effective_balance = spec.EJECTION_BALANCE
            state.balances[i] = spec.EJECTION_BALANCE
    # Fresh validators waiting on the activation queue
    from consensus_specs_tpu.testing.factories import seed_validator
    for k in range(6):
        nv = seed_validator(spec, len(state.validator_registry), spec.MAX_EFFECTIVE_BALANCE)
        nv.activation_eligibility_epoch = spec.FAR_FUTURE_EPOCH if k % 3 == 0 else current_epoch - k % 2
        state.validator_registry.append(nv)
        state.balances.append(spec.MAX_EFFECTIVE_BALANCE)
    # Scatter balances so hysteresis has work to do
    for i in range(0, len(state.validator_registry), 3):
        state.balances[i] = max(0, state.balances[i] - rng.randrange(0, 3 * 10 ** 9))

    assert_same_epoch_transition(spec, state)


def test_epoch_transition_donates_column_buffers(spec):
    """The donate_argnums on the epoch program must actually stick: every
    input column buffer is consumed (the 1M-validator epoch program updates
    in place instead of holding input+output copies in HBM) and XLA emits
    no "donated buffer unused" warning. Asserted against the donated jit
    directly — the accelerator production path; the public wrapper pins
    XLA:CPU to the undonated form (persistent-cache-deserialized CPU
    executables intermittently violate donated aliasing)."""
    import warnings

    import jax

    from consensus_specs_tpu.models.phase0.epoch_soa import (
        EpochConfig, _epoch_transition_donated, epoch_transition_device,
        synthetic_epoch_state)

    cfg = EpochConfig.from_spec(spec)
    cols, scal, inp = synthetic_epoch_state(cfg, 256, np.random.default_rng(5))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = jax.block_until_ready(
            _epoch_transition_donated(cfg, cols, scal, inp))
    donation_warnings = [str(w.message) for w in caught
                         if "donated" in str(w.message).lower()]
    assert not donation_warnings, donation_warnings
    # the donation really happened: every input column buffer was consumed
    assert all(getattr(cols, f).is_deleted() for f in cols._fields)
    new_cols = out[0]
    assert not new_cols.balance.is_deleted()
    # undonated args survive
    assert not inp.prev_src.is_deleted() and not scal.slot.is_deleted()

    # the public wrapper keeps CPU on the undonated form: inputs survive
    cols2, scal2, inp2 = synthetic_epoch_state(
        cfg, 256, np.random.default_rng(5))
    jax.block_until_ready(epoch_transition_device(cfg, cols2, scal2, inp2))
    import jax as _jax
    if _jax.default_backend() == "cpu":
        assert not cols2.balance.is_deleted()


def test_wide_math_helpers_exact():
    """muldiv_u64 / isqrt_u64 vs Python bigints on adversarial values."""
    import jax.numpy as jnp
    from consensus_specs_tpu.ops.intmath import isqrt_u64, muldiv_u64

    rng = random.Random(99)
    cases = []
    for _ in range(300):
        a = rng.randrange(0, 1 << 64)
        d = rng.randrange(1, 1 << 63)
        # keep quotient within 64 bits: b <= d * 2^64 / max(a,1) bound via b <= d
        b = rng.randrange(0, d + 1)
        if (a * b) // d < (1 << 64):
            cases.append((a, b, d))
    cases += [(32 * 10 ** 9, 3 * 10 ** 16, 3 * 10 ** 16 + 1), (0, 0, 1), (1 << 63, 2, 1 << 63)]
    a, b, d = (jnp.array([c[i] for c in cases], dtype=jnp.uint64) for i in range(3))
    got = np.asarray(muldiv_u64(a, b, d))
    want = np.array([(x * y) // z for x, y, z in cases], dtype=np.uint64)
    np.testing.assert_array_equal(got, want)

    ns = [rng.randrange(0, 1 << 62) for _ in range(300)]
    ns += [0, 1, 2, 3, 4, (1 << 31) ** 2, (1 << 31) ** 2 - 1, 3 * 10 ** 16]
    ns += [k * k for k in (rng.randrange(1, 1 << 31) for _ in range(50))]
    ns += [k * k - 1 for k in (rng.randrange(2, 1 << 31) for _ in range(50))]
    got = np.asarray(isqrt_u64(jnp.array(ns, dtype=jnp.uint64)))
    import math
    want = np.array([math.isqrt(n) for n in ns], dtype=np.uint64)
    np.testing.assert_array_equal(got, want)


def test_muldiv_hardened_vs_materializing_form():
    """The memory tier's liveness walk flagged two full-width temps in
    muldiv_u64: a broadcast_to that pinned scalar divisors at [V] width
    across the whole 64-step division scan, and jnp's guarded `%` whose
    where(d == 0) select chain is dead under the documented d >= 1
    precondition. This pins the hardened body bit-identical to the old
    materializing formulation — scalar AND vector divisors — and pins
    the prover win itself: a scalar divisor must never re-enter the
    division loop as a full-width constant."""
    import jax
    import jax.numpy as jnp
    from consensus_specs_tpu.ops.intmath import muldiv_u64, mulwide_u64

    def muldiv_materializing(a, b, d):
        # the pre-hardening body, verbatim modulo names
        hi, lo = mulwide_u64(a, b)
        d = jnp.broadcast_to(jnp.asarray(d, dtype=jnp.uint64), hi.shape)

        def step(i, carry):
            rem, quot = carry
            shift = jnp.uint64(63) - jnp.asarray(i, dtype=jnp.uint64)
            bit = (lo >> shift) & jnp.uint64(1)
            top = rem >> jnp.uint64(63)
            rem2 = (rem << jnp.uint64(1)) | bit
            ge = (top == jnp.uint64(1)) | (rem2 >= d)
            rem3 = jnp.where(ge, rem2 - d, rem2)
            quot2 = (quot << jnp.uint64(1)) | ge.astype(jnp.uint64)
            return rem3, quot2

        rem0 = hi % d
        quot0 = jnp.zeros_like(hi)
        _, quot = jax.lax.fori_loop(0, 64, step, (rem0, quot0))
        return quot

    rng = random.Random(1601)
    n = 512
    a = np.array([rng.randrange(0, 1 << 64) for _ in range(n)], np.uint64)
    dv = np.array([rng.randrange(1, 1 << 63) for _ in range(n)], np.uint64)
    b = np.array([rng.randrange(0, int(x) + 1) for x in dv], np.uint64)
    ja, jb, jd = (jnp.asarray(x) for x in (a, b, dv))
    # vector divisor (the crosslink-delta shape)
    np.testing.assert_array_equal(np.asarray(muldiv_u64(ja, jb, jd)),
                                  np.asarray(muldiv_materializing(ja, jb, jd)))
    # scalar divisor (the micro-incentive / slashing shape), d = 1 edge too
    for d_scalar in (jnp.uint64(3 * 10 ** 16 + 1), jnp.uint64(1)):
        bs = jnp.minimum(jb, d_scalar)
        np.testing.assert_array_equal(
            np.asarray(muldiv_u64(ja, bs, d_scalar)),
            np.asarray(muldiv_materializing(ja, bs, d_scalar)))

    # the prover's claim, pinned structurally: in the scalar-divisor
    # jaxpr the division loop's carried/constant operands contain ONE
    # full-width uint64 stream (lo) beyond the two carries — the old
    # body carried the broadcast divisor as a second full-width const
    closed = jax.make_jaxpr(
        lambda x, y: muldiv_u64(x, y, jnp.uint64(7)))(ja, jb)

    def loop_consts(jaxpr):
        found = []
        for eqn in jaxpr.eqns:
            if eqn.primitive.name in ("while", "scan"):
                found.append([tuple(v.aval.shape) for v in eqn.invars
                              if getattr(v, "aval", None) is not None])
            for val in eqn.params.values():
                for item in (val if isinstance(val, (tuple, list)) else (val,)):
                    if hasattr(item, "jaxpr"):
                        found.extend(loop_consts(
                            getattr(item.jaxpr, "jaxpr", item.jaxpr)))
        return found

    loops = loop_consts(closed.jaxpr)
    assert loops, "division loop vanished from muldiv_u64's jaxpr"
    full_width = max(sum(1 for shp in ops if shp == (n,)) for ops in loops)
    assert full_width <= 3, (
        f"scalar-divisor muldiv carries {full_width} full-width loop "
        f"operands (expected lo + rem + quot): the divisor is being "
        f"materialized again")
