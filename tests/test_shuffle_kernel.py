"""Device swap-or-not kernel vs the one-point spec oracle and the numpy path."""
import hashlib

import numpy as np
import pytest

from consensus_specs_tpu.models.phase0 import helpers
from consensus_specs_tpu.models.phase0.spec import get_spec
from consensus_specs_tpu.ops.shuffle import shuffle_permutation_device


@pytest.mark.parametrize("n", [1, 2, 7, 100, 256, 257, 1000])
@pytest.mark.parametrize("seed_byte", [0, 0xAA])
def test_device_matches_point_oracle(n, seed_byte):
    spec = get_spec("minimal")  # 10 rounds
    seed = bytes([seed_byte]) * 32
    perm = shuffle_permutation_device(seed, n, spec.SHUFFLE_ROUND_COUNT)
    assert sorted(perm.tolist()) == list(range(n))
    for i in range(n):
        assert perm[i] == spec.get_shuffled_index(i, n, seed)


def test_device_matches_numpy_mainnet_rounds():
    spec = get_spec("mainnet")  # 90 rounds
    seed = hashlib.sha256(b"shuffle kernel").digest()
    n = 2048
    device = shuffle_permutation_device(seed, n, spec.SHUFFLE_ROUND_COUNT)
    spec.clear_caches()
    host = spec.get_shuffle_permutation(n, seed)
    assert np.array_equal(device, np.asarray(host))


def test_backend_hook_used_and_cached():
    spec = get_spec("minimal")
    spec.clear_caches()
    calls = []

    def backend(seed, n, rounds):
        if n < 50:
            return None
        calls.append((seed, n, rounds))
        return shuffle_permutation_device(seed, n, rounds)

    helpers.set_shuffle_backend(backend)
    try:
        seed = b"\x01" * 32
        p1 = spec.get_shuffle_permutation(100, seed)
        p2 = spec.get_shuffle_permutation(100, seed)  # cache hit
        assert len(calls) == 1 and p1 is p2
        spec.clear_caches()
        small = spec.get_shuffle_permutation(10, seed)  # backend declined -> host
        assert sorted(np.asarray(small).tolist()) == list(range(10))
        assert len(calls) == 1
    finally:
        helpers.set_shuffle_backend(None)
        spec.clear_caches()


@pytest.mark.parametrize("n", [1, 7, 256, 1000, 2048])
def test_stacked_variant_bit_equal(n):
    """The [2, n] stacked-movement A/B variant == the reference kernel
    (tools/tpu_followup.py picks between them on chip by timing)."""
    import jax.numpy as jnp

    from consensus_specs_tpu.ops.shuffle import (
        _shuffle_rounds_stacked, host_pivots, shuffle_permutation_on_device)
    from consensus_specs_tpu.ops.sha256 import bytes_to_words

    seed = hashlib.sha256(b"stacked shuffle").digest()
    rounds = 90
    base = np.asarray(shuffle_permutation_on_device(seed, n, rounds))
    seed_words = jnp.asarray(bytes_to_words(np.frombuffer(seed, dtype=np.uint8)))
    stacked = np.asarray(_shuffle_rounds_stacked(
        seed_words, jnp.asarray(host_pivots(seed, n, rounds)), n, rounds))
    assert np.array_equal(base, stacked)
