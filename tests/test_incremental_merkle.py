"""Incremental Merkle forest == full recompute, bit for bit, under
adversarial dirty patterns — and in O(dirty·log V) pair-hash lanes.

The forest (utils/ssz/incremental.py) keeps every tree level resident and
re-hashes only dirty root paths; every root here is checked against the
full-recompute oracle bulk.merkleize_chunk_array (itself pinned to the
recursive object-model Merkleizer in tests/test_bulk_htr.py). Patterns:
single leaf, dense stripes, repeated updates to the same leaf, append-grow
crossing a power-of-two boundary, and the all-dirty epoch-boundary shape —
on both pair-hash backends (CSTPU_MERKLE_BACKEND=xla|pallas; the Pallas
form runs the eager interpreter on CPU, so its scenario is compact).

The work bound is asserted by counting hashed pairs per level, not by
wall-clock: a ≤k-leaf update on an n-leaf tree must dispatch at most
2·k·depth lanes (the pow2 index padding at worst doubles), far below the
~2n lanes of a full rebuild.
"""
import numpy as np
import pytest

from consensus_specs_tpu.ops import sha256 as S
from consensus_specs_tpu.ops.sha256 import bytes_to_words
from consensus_specs_tpu.utils.merkle import tree_depth
from consensus_specs_tpu.utils.ssz import bulk
from consensus_specs_tpu.utils.ssz.incremental import (
    IncrementalMerkleTree, tree_from_chunks)


@pytest.fixture(params=["xla", "pallas"])
def backend(request):
    S.set_merkle_pair_backend(request.param)
    yield request.param
    S.set_merkle_pair_backend(None)


def _rand_chunks(rng, n):
    return rng.integers(0, 256, (n, 32), dtype=np.uint8)


def _check(tree, chunks, context=""):
    assert tree.root() == bulk.merkleize_chunk_array(chunks), context


# ---------------------------------------------------------------------------
# Full battery (XLA backend — the default production kernel)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [0, 1, 2, 3, 5, 8, 9, 31, 32, 33, 100, 257])
def test_build_matches_full_recompute(n):
    chunks = _rand_chunks(np.random.default_rng(n), n)
    _check(tree_from_chunks(chunks), chunks, n)


def test_single_leaf_updates():
    rng = np.random.default_rng(1)
    chunks = _rand_chunks(rng, 97)
    tree = tree_from_chunks(chunks)
    for leaf in (0, 1, 50, 95, 96):          # both edges incl. the odd tail
        row = _rand_chunks(rng, 1)
        chunks[leaf] = row
        tree.update([leaf], bytes_to_words(row))
        _check(tree, chunks, leaf)


def test_dense_stripes():
    rng = np.random.default_rng(2)
    chunks = _rand_chunks(rng, 300)
    tree = tree_from_chunks(chunks)
    for start, width in ((0, 64), (100, 37), (250, 50), (0, 300)):
        idx = np.arange(start, start + width)
        rows = _rand_chunks(rng, width)
        chunks[idx] = rows
        tree.update(idx, bytes_to_words(rows))
        _check(tree, chunks, (start, width))


def test_repeated_updates_to_same_leaf():
    rng = np.random.default_rng(3)
    chunks = _rand_chunks(rng, 64)
    tree = tree_from_chunks(chunks)
    for _ in range(10):
        row = _rand_chunks(rng, 1)
        chunks[17] = row
        tree.update([17], bytes_to_words(row))
        _check(tree, chunks)
    # ... and restoring the original content reproduces the original root
    original = tree_from_chunks(chunks).root()
    assert tree.root() == original


def test_append_grow_crossing_power_of_two():
    rng = np.random.default_rng(4)
    chunks = _rand_chunks(rng, 5)
    tree = tree_from_chunks(chunks)
    for k in (2, 1, 4, 9, 50, 200):          # crosses 8, 16, 64, 256
        rows = _rand_chunks(rng, k)
        chunks = np.concatenate([chunks, rows])
        tree.append(bytes_to_words(rows))
        _check(tree, chunks, k)
        assert tree.depth == tree_depth(chunks.shape[0])
    # interleave: update old leaves after several growth steps
    idx = np.array([0, 6, 7, 8, 100, chunks.shape[0] - 1])
    rows = _rand_chunks(rng, idx.shape[0])
    chunks[idx] = rows
    tree.update(idx, bytes_to_words(rows))
    _check(tree, chunks)


def test_append_from_empty():
    rng = np.random.default_rng(5)
    tree = tree_from_chunks(np.zeros((0, 32), np.uint8))
    assert tree.root() == bulk.merkleize_chunk_array(np.zeros((0, 32), np.uint8))
    chunks = _rand_chunks(rng, 3)
    tree.append(bytes_to_words(chunks))
    _check(tree, chunks)


def test_all_dirty_epoch_boundary_shape():
    rng = np.random.default_rng(6)
    chunks = _rand_chunks(rng, 130)
    tree = tree_from_chunks(chunks)
    rows = _rand_chunks(rng, 130)
    tree.update(np.arange(130), bytes_to_words(rows))
    _check(tree, rows)


def test_randomized_mixed_patterns():
    rng = np.random.default_rng(7)
    chunks = _rand_chunks(rng, 41)
    tree = tree_from_chunks(chunks)
    for trial in range(30):
        if rng.random() < 0.25:              # grow
            k = int(rng.integers(1, 8))
            rows = _rand_chunks(rng, k)
            chunks = np.concatenate([chunks, rows])
            tree.append(bytes_to_words(rows))
        else:                                # scattered dirty set
            k = int(rng.integers(1, min(16, chunks.shape[0]) + 1))
            idx = rng.choice(chunks.shape[0], k, replace=False)
            rows = _rand_chunks(rng, k)
            chunks[idx] = rows
            tree.update(idx, bytes_to_words(rows))
        _check(tree, chunks, trial)


def test_update_rejects_bad_indices():
    rng = np.random.default_rng(8)
    chunks = _rand_chunks(rng, 16)
    tree = tree_from_chunks(chunks)
    with pytest.raises(AssertionError):
        tree.update([16], bytes_to_words(_rand_chunks(rng, 1)))  # out of range
    with pytest.raises(AssertionError):
        tree.update([3, 3], bytes_to_words(_rand_chunks(rng, 2)))  # duplicate


# ---------------------------------------------------------------------------
# Work bound: O(dirty·log V) pair-hash lanes, counted — not wall-clocked
# ---------------------------------------------------------------------------

def test_update_work_is_dirty_log_v():
    rng = np.random.default_rng(9)
    n = 4096
    tree = IncrementalMerkleTree(
        rng.integers(0, 2 ** 32, (n, 8), dtype=np.uint32))
    full_lanes = sum(tree.last_pairs_per_level)
    assert full_lanes >= n - 1                   # the build really is O(n)
    for k in (1, 64, 16):
        idx = rng.choice(n, k, replace=False)
        tree.update(idx, rng.integers(0, 2 ** 32, (k, 8), dtype=np.uint32))
        lanes = tree.last_pairs_per_level
        assert len(lanes) == tree.depth          # one batched launch per level
        # pow2 padding at worst doubles the dirty set at each level
        assert sum(lanes) <= 2 * k * tree.depth, (k, lanes)
        assert all(lane <= 2 * k for lane in lanes), (k, lanes)
    # 16 dirty leaves of 4096: an order of magnitude under the full rebuild
    # even at this small scale (at 1k dirty of 1M the gap is ~50x — measured
    # by bench.py's `incremental state-root ms` row)
    assert sum(tree.last_pairs_per_level) * 10 < full_lanes


# ---------------------------------------------------------------------------
# Both backends (the Pallas form interprets eagerly off-TPU: keep it compact)
# ---------------------------------------------------------------------------

def test_backend_scenario_bit_exact(backend):
    """One build + scattered update + same-leaf rewrite + pow2-crossing
    append per backend, each against the full-recompute oracle (the oracle
    itself hashes through the selected backend only above its device
    threshold, so this also cross-checks pallas against hashlib)."""
    rng = np.random.default_rng(10)
    chunks = _rand_chunks(rng, 6)
    tree = tree_from_chunks(chunks)
    _check(tree, chunks, backend)
    idx = np.array([0, 3, 5])
    rows = _rand_chunks(rng, 3)
    chunks[idx] = rows
    tree.update(idx, bytes_to_words(rows))
    _check(tree, chunks, backend)
    row = _rand_chunks(rng, 1)                  # repeated same-leaf rewrite
    chunks[3] = row
    tree.update([3], bytes_to_words(row))
    _check(tree, chunks, backend)
    rows = _rand_chunks(rng, 4)                 # 6 -> 10 crosses 8
    chunks = np.concatenate([chunks, rows])
    tree.append(bytes_to_words(rows))
    _check(tree, chunks, backend)


def test_backend_selection_plumbing(monkeypatch):
    monkeypatch.setenv("CSTPU_MERKLE_BACKEND", "pallas")
    assert S.merkle_pair_backend_name() == "pallas"
    S.set_merkle_pair_backend("xla")             # explicit pin beats the env
    try:
        assert S.merkle_pair_backend_name() == "xla"
    finally:
        S.set_merkle_pair_backend(None)
    monkeypatch.setenv("CSTPU_MERKLE_BACKEND", "mosaic")
    with pytest.raises(ValueError):
        S.merkle_pair_backend_name()


# ---------------------------------------------------------------------------
# Tree-handle API (bulk.py): memo coherence with forest invalidation
# ---------------------------------------------------------------------------

def test_chunk_tree_handle_matches_oracle():
    rng = np.random.default_rng(11)
    chunks = _rand_chunks(rng, 200)
    handle = bulk.build_chunk_tree(chunks)
    assert handle.root() == bulk.merkleize_chunk_array(chunks)
    idx = [7, 100, 199]
    rows = _rand_chunks(rng, 3)
    handle.update(idx, rows)
    chunks[idx] = rows
    assert handle.root() == bulk.merkleize_chunk_array(chunks)
    rows = _rand_chunks(rng, 70)                 # 200 -> 270 crosses 256
    handle.append(rows)
    chunks = np.concatenate([chunks, rows])
    assert handle.root() == bulk.merkleize_chunk_array(chunks)


def test_handle_owns_its_chunks():
    """The handle copies the chunk matrix at build: scribbling on the
    caller's array must not desynchronize the forest from its memo key."""
    rng = np.random.default_rng(12)
    chunks = _rand_chunks(rng, 128)
    handle = bulk.build_chunk_tree(chunks)
    want = handle.root()
    chunks[:] = 0
    assert handle.root() == want


def test_forest_invalidation_evicts_memo_entries():
    """Forest invalidation and the byte memo move together: the entry a
    handle's root() inserted comes OUT when the handle updates, so the memo
    never carries entries for content the forest has superseded."""
    rng = np.random.default_rng(13)
    chunks = _rand_chunks(rng, 256)
    handle = bulk.build_chunk_tree(chunks)
    r0 = handle.root()
    key = ("mca", chunks.tobytes())
    assert bulk._memo.get(key) == r0             # root() memoized its content
    bytes_before = bulk._memo_bytes
    row = _rand_chunks(rng, 1)
    handle.update([11], row)
    assert key not in bulk._memo                 # evicted, not lingering
    assert bulk._memo_bytes < bytes_before       # accounting followed
    # the old content still roots correctly through the normal path ...
    assert bulk.merkleize_chunk_array(chunks) == r0
    # ... and the new content is served fresh, not from a stale entry
    chunks[11] = row
    assert handle.root() == bulk.merkleize_chunk_array(chunks) != r0
