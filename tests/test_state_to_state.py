"""Bit-equality gate for the bench's state-to-state config-5 path.

bench.py's bench_state_to_state() times: vectorized distillation ->
one-program device epoch -> device registry/balances roots from the
still-resident output columns. This test runs the SAME path (same state
builder, same calls) at reduced V on the mainnet preset and asserts:
  1. post-state hash_tree_root == the object-model spec.process_epoch
  2. the device roots from post-transition columns == the recursive oracle
     roots of the written-back registry/balances
"""
from copy import deepcopy

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # pairing compiles dominate suite wall-clock

import bench
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.models.phase0.epoch_soa import process_epoch_soa
from consensus_specs_tpu.utils.ssz import bulk
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root
from consensus_specs_tpu.utils.ssz.typing import List as SSZList, uint64

V = 256


@pytest.fixture(autouse=True)
def _bls_off():
    old = bls.bls_active
    bls.bls_active = False
    yield
    bls.bls_active = old


def test_bench_state_to_state_path_matches_object_model():
    spec = phase0.get_spec("mainnet")
    spec.clear_caches()
    state = bench.build_baseline_state(spec, V)
    ref = deepcopy(state)

    tm = {}
    dev_cols, _ = process_epoch_soa(spec, state, timings=tm)
    spec.process_epoch(ref)
    assert hash_tree_root(state) == hash_tree_root(ref)
    assert set(tm) == {"distill", "perm", "device", "writeback"}

    # Device roots from the post-transition columns == recursive oracle
    pk = np.zeros((V, 48), np.uint8)
    pk[:, :8] = np.arange(V, dtype=np.uint64).astype(
        "<u8").view(np.uint8).reshape(V, 8)
    wc = np.zeros((V, 32), np.uint8)
    reg_root, bal_root = bulk.registry_and_balances_roots_device(
        pk, wc, dev_cols.activation_eligibility_epoch,
        dev_cols.activation_epoch, dev_cols.exit_epoch,
        dev_cols.withdrawable_epoch, dev_cols.slashed,
        dev_cols.effective_balance, dev_cols.balance)
    assert reg_root == hash_tree_root(
        state.validator_registry, SSZList[spec.Validator])
    assert bal_root == hash_tree_root(state.balances, SSZList[uint64])
