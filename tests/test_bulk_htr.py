"""Bulk/device hash_tree_root == recursive object-model root, bit for bit.

The bulk Merkleizer (utils/ssz/bulk.py) must agree with the recursive
oracle (utils/ssz/impl.py) on every shape it fast-paths: basic lists,
Bytes32 vectors, container lists (the validator registry), whole
BeaconStates, and the SoA direct path. Merkleization contract:
/root/reference specs/simple-serialize.md:139-158.
"""
from random import Random

import numpy as np
import pytest

from consensus_specs_tpu.debug.random_value import (
    RandomizationMode, get_random_ssz_object)
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.utils.ssz import bulk
from consensus_specs_tpu.utils.ssz.impl import hash_tree_root
from consensus_specs_tpu.utils.ssz.typing import (
    Bytes32, Bytes48, List as SSZList, Vector, uint64)

SPEC = phase0.get_spec("minimal")


def test_uint64_list_matches():
    rng = Random(1)
    values = [rng.randrange(2 ** 64) for _ in range(1000)]
    assert bulk.hash_tree_root_bulk(values, SSZList[uint64]) == \
        hash_tree_root(values, SSZList[uint64])


def test_uint64_list_odd_sizes():
    for n in (0, 1, 3, 4, 5, 31, 32, 33, 257):
        values = list(range(n))
        assert bulk.hash_tree_root_bulk(values, SSZList[uint64]) == \
            hash_tree_root(values, SSZList[uint64]), n


def test_bytes32_vector_matches():
    rng = Random(2)
    n = 64
    vals = [bytes(rng.randrange(256) for _ in range(32)) for _ in range(n)]
    typ = Vector[Bytes32, n]
    assert bulk.hash_tree_root_bulk(typ(vals), typ) == hash_tree_root(typ(vals), typ)


def test_bytes48_list_matches():
    rng = Random(3)
    vals = [bytes(rng.randrange(256) for _ in range(48)) for _ in range(33)]
    typ = SSZList[Bytes48]
    assert bulk.hash_tree_root_bulk(vals, typ) == hash_tree_root(vals, typ)


@pytest.mark.parametrize("count", [1, 2, 7, 8, 100, 1024])
def test_validator_registry_matches(count):
    rng = Random(count)
    typ = SSZList[SPEC.Validator]
    validators = [
        get_random_ssz_object(rng, SPEC.Validator, RandomizationMode.RANDOM)
        for _ in range(count)
    ]
    assert bulk.hash_tree_root_bulk(validators, typ) == \
        hash_tree_root(validators, typ)


def test_full_beacon_state_matches():
    rng = Random(7)
    state = get_random_ssz_object(rng, SPEC.BeaconState, RandomizationMode.RANDOM,
                                  max_list_length=5)
    state.validator_registry = [
        get_random_ssz_object(rng, SPEC.Validator, RandomizationMode.RANDOM)
        for _ in range(50)
    ]
    state.balances = [rng.randrange(2 ** 64) for _ in range(50)]
    assert bulk.state_root_bulk(state) == hash_tree_root(state, SPEC.BeaconState)


def test_soa_registry_root_matches_objects():
    rng = Random(11)
    V = 300
    validators = [
        get_random_ssz_object(rng, SPEC.Validator, RandomizationMode.RANDOM)
        for _ in range(V)
    ]
    got = bulk.validator_registry_root_from_columns(
        pubkeys=np.stack([np.frombuffer(v.pubkey, np.uint8) for v in validators]),
        withdrawal_credentials=np.stack(
            [np.frombuffer(v.withdrawal_credentials, np.uint8) for v in validators]),
        activation_eligibility_epoch=np.asarray(
            [v.activation_eligibility_epoch for v in validators], np.uint64),
        activation_epoch=np.asarray([v.activation_epoch for v in validators], np.uint64),
        exit_epoch=np.asarray([v.exit_epoch for v in validators], np.uint64),
        withdrawable_epoch=np.asarray([v.withdrawable_epoch for v in validators], np.uint64),
        slashed=np.asarray([v.slashed for v in validators], bool),
        effective_balance=np.asarray([v.effective_balance for v in validators], np.uint64),
    )
    assert got == hash_tree_root(validators, SSZList[SPEC.Validator])


def test_soa_balances_root_matches_objects():
    rng = Random(13)
    vals = [rng.randrange(2 ** 64) for _ in range(999)]
    got = bulk.uint64_list_root_from_column(np.asarray(vals, np.uint64))
    assert got == hash_tree_root(vals, SSZList[uint64])


def test_device_path_small_threshold(monkeypatch):
    # force the device hasher for a small tree: exercises the pow2 padding
    monkeypatch.setattr(bulk, "_DEVICE_MIN_PAIRS", 1)
    rng = Random(23)
    vals = [rng.randrange(2 ** 64) for _ in range(100)]
    assert bulk.hash_tree_root_bulk(vals, SSZList[uint64]) == \
        hash_tree_root(vals, SSZList[uint64])


def test_pending_attestations_fall_back_correctly():
    # variable-size elements (bitfields) can't column-ize; the dispatcher
    # must still produce the oracle root via its fallback
    rng = Random(17)
    typ = SSZList[SPEC.PendingAttestation]
    atts = [
        get_random_ssz_object(rng, SPEC.PendingAttestation, RandomizationMode.RANDOM)
        for _ in range(5)
    ]
    assert bulk.hash_tree_root_bulk(atts, typ) == hash_tree_root(atts, typ)


@pytest.mark.parametrize("V", [1, 5, 64, 257, 1000])
def test_device_resident_roots_match_numpy_path(V):
    """The one-program device path (leaf build + all Merkle levels traced
    together) is bit-identical to the per-level numpy path — and therefore
    to the recursive object oracle — including non-pow2 odd-level
    padding."""
    rng = np.random.default_rng(V)
    pk = rng.integers(0, 256, (V, 48), dtype=np.uint8)
    wc = rng.integers(0, 256, (V, 32), dtype=np.uint8)
    e1 = rng.integers(0, 2 ** 63, V).astype(np.uint64)
    e2 = rng.integers(0, 2 ** 63, V).astype(np.uint64)
    e3 = np.full(V, 2 ** 64 - 1, np.uint64)   # FAR_FUTURE_EPOCH
    e4 = rng.integers(0, 2 ** 63, V).astype(np.uint64)
    sl = rng.integers(0, 2, V).astype(bool)
    eb = rng.integers(0, 2 ** 35, V).astype(np.uint64)
    bal = rng.integers(0, 2 ** 35, V).astype(np.uint64)
    r1_dev, r2_dev = bulk.registry_and_balances_roots_device(
        pk, wc, e1, e2, e3, e4, sl, eb, bal)
    assert r1_dev == bulk.validator_registry_root_from_columns(
        pk, wc, e1, e2, e3, e4, sl, eb)
    assert r2_dev == bulk.uint64_list_root_from_column(bal)


def test_device_resident_roots_empty_columns():
    r1, r2 = bulk.registry_and_balances_roots_device(
        np.zeros((0, 48), np.uint8), np.zeros((0, 32), np.uint8),
        np.zeros(0, np.uint64), np.zeros(0, np.uint64),
        np.zeros(0, np.uint64), np.zeros(0, np.uint64),
        np.zeros(0, bool), np.zeros(0, np.uint64), np.zeros(0, np.uint64))
    assert r1 == hash_tree_root([], SSZList[SPEC.Validator])
    assert r2 == hash_tree_root([], SSZList[uint64])


# ---------------------------------------------------------------------------
# Content-keyed merkleization memo
# ---------------------------------------------------------------------------

def test_merkleize_memo_differential_across_mutations():
    """Memo hits must track content, not identity: mutate one chunk, re-root,
    restore, re-root — every answer equals the oracle's, and the restored
    matrix reproduces the original root from the cache."""
    from consensus_specs_tpu.utils.merkle import merkleize_chunks
    rng = np.random.default_rng(7)
    chunks = rng.integers(0, 256, (256, 32), dtype=np.uint8)

    def oracle(c):
        return merkleize_chunks([c[i].tobytes() for i in range(c.shape[0])])

    r0 = bulk.merkleize_chunk_array(chunks)
    assert r0 == oracle(chunks)
    assert bulk.merkleize_chunk_array(chunks) == r0       # cache hit
    orig = chunks[11].copy()
    chunks[11] ^= 0xFF
    r1 = bulk.merkleize_chunk_array(chunks)
    assert r1 != r0 and r1 == oracle(chunks)              # miss on new content
    chunks[11] = orig
    assert bulk.merkleize_chunk_array(chunks) == r0       # hit on old content


def test_subtree_roots_memo_hit_is_writable_copy():
    """A cached subtree_roots_batch result must come back as a fresh
    writable array — a caller scribbling on it must not poison the cache."""
    rng = np.random.default_rng(8)
    leaves = rng.integers(0, 256, (64, 4, 32), dtype=np.uint8)
    first = bulk.subtree_roots_batch(leaves).copy()
    hit = bulk.subtree_roots_batch(leaves)
    hit[:] = 0
    again = bulk.subtree_roots_batch(leaves)
    np.testing.assert_array_equal(again, first)


def test_memo_put_cap_overflow_clears_wholesale(monkeypatch):
    """_MEMO_MAX_BYTES exceeded -> the next insert clears the memo wholesale
    and repopulates from the live set; evicted content recomputes correctly
    (never a stale or missing root)."""
    saved_memo = dict(bulk._memo)
    saved_bytes = bulk._memo_bytes
    try:
        bulk._memo.clear()
        bulk._memo_bytes = 0
        # one 64-chunk entry keys at 2048B (+64 overhead) — the cap admits
        # exactly one, so every later insert lands on the overflow path
        monkeypatch.setattr(bulk, "_MEMO_MAX_BYTES", 1000)
        rng = np.random.default_rng(21)
        mats = [rng.integers(0, 256, (64, 32), dtype=np.uint8)
                for _ in range(3)]
        roots = [bulk.merkleize_chunk_array(m) for m in mats]
        assert len(bulk._memo) == 1               # overflow evicted the rest
        assert bulk._memo_bytes <= 2048 + 32 + 64
        assert ("mca", mats[-1].tobytes()) in bulk._memo
        from consensus_specs_tpu.utils.merkle import merkleize_chunks
        for m, r in zip(mats, roots):             # recompute, bit-identical
            assert bulk.merkleize_chunk_array(m) == r == merkleize_chunks(
                [m[i].tobytes() for i in range(64)])
    finally:
        bulk._memo.clear()
        bulk._memo.update(saved_memo)
        bulk._memo_bytes = saved_bytes


def test_memo_size_gate_routes_large_inputs_around_cache():
    """Matrices above the per-entry key cap bypass the memo (no insertion,
    no thrash) and stay deterministic across calls."""
    n = (bulk._MEMO_MAX_KEY // 32) + 1
    chunks = np.zeros((n, 32), dtype=np.uint8)
    chunks[0, 0] = 1
    before = len(bulk._memo)
    root = bulk.merkleize_chunk_array(chunks)
    assert len(bulk._memo) == before          # nothing inserted
    assert bulk.merkleize_chunk_array(chunks) == root
