"""Multi-device sharding correctness: sharded == single-device, bit for bit.

The protocol's data-parallel axis is the validator registry (SURVEY.md §2c);
these tests jit the SAME epoch program once per placement — all inputs on
one device vs `[V]` columns sharded over an explicit 8-device Mesh — and
require bit-identical outputs. XLA inserts the cross-shard collectives
(balance-sum reductions, proposer scatter-add, activation-queue sort);
equality proves the sharded program is semantically the single-chip one.

Runs on the virtual 8-device CPU mesh the conftest pins; the driver's
dryrun_multichip does the same check at entry level.
"""
import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.parallel import (
    shard_epoch_state, trees_bitwise_equal, validator_mesh)
from consensus_specs_tpu.models.phase0.epoch_soa import (
    EpochConfig, epoch_transition_device, synthetic_epoch_state)

N_DEV = 8


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices, have {len(jax.devices())}")
    return validator_mesh(n=N_DEV)


@pytest.mark.parametrize("seed", [0, 3])
def test_epoch_transition_sharded_equals_single(mesh, seed):
    spec = phase0.get_spec("minimal")
    cfg = EpochConfig.from_spec(spec)
    V = 64 * N_DEV
    cols, scal, inp = synthetic_epoch_state(
        cfg, V, np.random.default_rng(seed), random_eligibility=True,
        random_slashed_balances=True)

    # shard (device_put copies) BEFORE the single-device run: the direct
    # epoch_transition_device call donates `cols`
    cols_s, scal_s, inp_s = shard_epoch_state(mesh, cols, scal, inp)
    single = epoch_transition_device(cfg, cols, scal, inp)
    jax.block_until_ready(single)

    sharded = jax.jit(
        lambda c, s, i: epoch_transition_device(cfg, c, s, i)
    )(cols_s, scal_s, inp_s)
    jax.block_until_ready(sharded)

    assert trees_bitwise_equal(single, sharded)


def test_grouped_pairing_sharded_equals_single(mesh):
    """The attestation axis (SURVEY §2c axis #1): a batch of aggregate-
    verify pair groups sharded over the mesh must give the single-device
    verdicts bit-for-bit. Groups are independent pair products, so the
    sharded program is embarrassingly parallel until the verdict gather."""
    import jax.numpy as jnp
    from consensus_specs_tpu.ops.bls_jax import (
        grouped_pairing_check, stage_example_groups)
    from consensus_specs_tpu.parallel import shard_leading_axis

    g1, g2 = stage_example_groups(N_DEV)
    single = np.asarray(grouped_pairing_check(jnp.asarray(g1),
                                                   jnp.asarray(g2)))
    assert single.all(), "staged groups must verify"
    g1_s, g2_s = shard_leading_axis(mesh, (jnp.asarray(g1), jnp.asarray(g2)))
    sharded = np.asarray(grouped_pairing_check(g1_s, g2_s))
    np.testing.assert_array_equal(single, sharded)

    # and a failing group must fail identically under sharding
    g1_bad = g1.copy()
    g1_bad[3, 1] = g1_bad[3, 2]   # swap in the wrong pubkey
    single = np.asarray(grouped_pairing_check(jnp.asarray(g1_bad),
                                                   jnp.asarray(g2)))
    g1_s, g2_s = shard_leading_axis(mesh, (jnp.asarray(g1_bad),
                                           jnp.asarray(g2)))
    sharded = np.asarray(grouped_pairing_check(g1_s, g2_s))
    assert not single[3] and not sharded[3]
    np.testing.assert_array_equal(single, sharded)


def test_bulk_merkleizer_sharded_equals_single(mesh):
    """The Merkle leaf axis (SURVEY §2c axis #4): registry + balances roots
    from columns sharded over the mesh == single-device == byte-identical
    roots (the tree reduction crosses shards as the levels shrink)."""
    import jax.numpy as jnp
    from consensus_specs_tpu.parallel import shard_leading_axis
    from consensus_specs_tpu.utils.ssz import bulk

    rng = np.random.default_rng(11)
    V = 256 * N_DEV
    cols = (
        rng.integers(0, 256, (V, 48), dtype=np.uint8),           # pubkeys
        rng.integers(0, 256, (V, 32), dtype=np.uint8),           # wc
        np.zeros(V, np.uint64), np.zeros(V, np.uint64),
        np.zeros(V, np.uint64), np.zeros(V, np.uint64),
        rng.random(V) < 0.01,                                    # slashed
        np.full(V, 32_000_000_000, np.uint64),
        rng.integers(31_000_000_000, 33_000_000_000, V).astype(np.uint64),
    )
    single = bulk.registry_and_balances_roots_device(*cols)
    sharded_cols = shard_leading_axis(mesh, tuple(jnp.asarray(c) for c in cols))
    sharded = bulk.registry_and_balances_roots_device(*sharded_cols)
    assert single == sharded


def test_sharded_output_stays_sharded(mesh):
    """With output shardings left to propagation, the result's [V] columns
    must come back sharded over the mesh — i.e. the partitioner kept the
    program SPMD instead of gathering to one device."""
    spec = phase0.get_spec("minimal")
    cfg = EpochConfig.from_spec(spec)
    cols, scal, inp = synthetic_epoch_state(
        cfg, 64 * N_DEV, np.random.default_rng(1), random_eligibility=True)
    cols_s, scal_s, inp_s = shard_epoch_state(mesh, cols, scal, inp)
    out_cols, _, _ = jax.jit(
        lambda c, s, i: epoch_transition_device(cfg, c, s, i)
    )(cols_s, scal_s, inp_s)
    jax.block_until_ready(out_cols)
    shard_v = NamedSharding(mesh, P("v"))
    assert out_cols.balance.sharding.is_equivalent_to(shard_v, out_cols.balance.ndim)


@pytest.fixture(scope="module")
def serving_mesh():
    from consensus_specs_tpu.parallel.sharding import ServingMesh
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices, have {len(jax.devices())}")
    return ServingMesh.create(N_DEV)


def test_sharded_forest_matches_single(serving_mesh):
    """The incremental forest under the ServingMesh: per-shard subtree
    levels sharded over "v", replicated cap tree, and every root — build,
    scattered update, append-grow crossing both the padded power of two
    AND a shard boundary — bit-identical to the single-device tree, at
    the same O(dirty·log V) pair-lane bound."""
    import jax.numpy as jnp
    from consensus_specs_tpu.utils.ssz.incremental import (
        IncrementalMerkleTree, ShardedIncrementalMerkleTree)

    mesh = serving_mesh
    rng = np.random.default_rng(21)
    V = 100                         # deliberately not pow2, not 8-divisible
    leaves = rng.integers(0, 2 ** 32, (V, 8), dtype=np.uint32)
    single = IncrementalMerkleTree(leaves.copy())
    shard = ShardedIncrementalMerkleTree(jnp.asarray(leaves), mesh)
    assert shard.root() == single.root()
    assert shard.n == single.n == V
    assert shard.depth == single.depth
    # materialized pow2 level 0 shards over "v"; the cap levels replicate
    assert shard.levels[0].shape == (128, 8)
    assert shard.levels[0].sharding.is_equivalent_to(mesh.shard_v, 2)
    assert shard.levels[-1].sharding.is_equivalent_to(mesh.replicated, 2)

    # scattered update: same dirty set, same roots, layout preserved
    idx = np.array([0, 5, 63, 99], np.int32)
    rows = rng.integers(0, 2 ** 32, (4, 8), dtype=np.uint32)
    single.update(idx, rows.copy())
    shard.update(idx, rows)
    assert shard.root() == single.root()
    assert shard.last_pairs_per_level == single.last_pairs_per_level
    assert sum(shard.last_pairs_per_level) <= 2 * 4 * shard.depth
    assert shard.levels[0].sharding.is_equivalent_to(mesh.shard_v, 2)

    # append-grow: 100 -> 140 crosses the 128 pow2 (and, at 8 devices,
    # the per-shard row boundary); the new capacity 256 rounds to a mesh
    # multiple by construction
    rows2 = rng.integers(0, 2 ** 32, (40, 8), dtype=np.uint32)
    single.append(rows2.copy())
    shard.append(rows2)
    assert shard.root() == single.root()
    assert shard.n == single.n == 140
    assert shard.levels[0].shape == (256, 8)
    assert shard.levels[0].sharding.is_equivalent_to(mesh.shard_v, 2)
    assert shard.builds == single.builds == 1   # never a full rebuild


def test_serving_mesh_epoch_padded_equals_single(serving_mesh):
    """The serving layout's inert validator padding is bit-neutral: the
    epoch program over [Vp]-padded sharded columns (V NOT divisible by the
    mesh — the deposit-grown shape) returns the single-device outputs on
    the [V] prefix, replicated scalars equal, and the padding rows stay
    inert for the NEXT boundary too (chained call, zero re-layout)."""
    import jax.numpy as jnp
    from consensus_specs_tpu.models.phase0.epoch_soa import (
        pad_epoch_inputs, pad_validator_columns)
    from consensus_specs_tpu.parallel import trees_bitwise_equal

    mesh = serving_mesh
    spec = phase0.get_spec("minimal")
    cfg = EpochConfig.from_spec(spec)
    V = 64 * N_DEV + 3              # padding must cover 5 inert rows
    cols, scal, inp = synthetic_epoch_state(
        cfg, V, np.random.default_rng(17), random_eligibility=True,
        random_slashed_balances=True)
    vp = mesh.pad_rows(V)
    cols_p = pad_validator_columns(cols, vp, cfg.FAR_FUTURE_EPOCH)
    inp_p = pad_epoch_inputs(inp, vp)

    single = epoch_transition_device(cfg, cols, scal, inp)
    jax.block_until_ready(single)
    sh_cols, sh_scal, sh_rep = mesh.epoch_transition(cfg, cols_p, scal, inp_p)
    jax.block_until_ready(sh_cols)
    trim = type(sh_cols)(*[x[:V] for x in sh_cols])
    assert trees_bitwise_equal(single[0], trim)
    assert trees_bitwise_equal(single[1], sh_scal)
    assert trees_bitwise_equal(single[2], sh_rep)
    # out_shardings matched in_shardings: outputs come back sharded and
    # chain straight into the next boundary without re-layout
    assert sh_cols.balance.sharding.is_equivalent_to(mesh.shard_v, 1)
    next_scal = sh_scal._replace(
        slot=sh_scal.slot + jnp.uint64(cfg.SLOTS_PER_EPOCH))
    sh2_cols, _, _ = mesh.epoch_transition(cfg, sh_cols, next_scal, inp_p)
    single2 = epoch_transition_device(
        cfg, single[0], single[1]._replace(
            slot=single[1].slot + jnp.uint64(cfg.SLOTS_PER_EPOCH)), inp)
    assert trees_bitwise_equal(
        single2[0], type(sh2_cols)(*[x[:V] for x in sh2_cols]))
    assert sh2_cols.balance.sharding.is_equivalent_to(mesh.shard_v, 1)


def test_serving_mesh_forest_leaf_builders_match_oracle(serving_mesh):
    """registry_forest_leaves / balances_forest_chunks: inert padding rows
    mask to the SSZ virtual-zero rows, real rows equal the single-device
    builders, output placed per row_sharding — and the traced v_count
    means a registry grown INSIDE the same padding reuses the program."""
    import jax.numpy as jnp
    from consensus_specs_tpu.utils.ssz import bulk

    mesh = serving_mesh
    rng = np.random.default_rng(29)
    V, vp = 100, mesh.pad_rows(100)
    pk = rng.integers(0, 256, (vp, 48), dtype=np.uint8)
    wc = rng.integers(0, 256, (vp, 32), dtype=np.uint8)
    epochs = [rng.integers(0, 50, vp).astype(np.uint64) for _ in range(4)]
    slashed = rng.random(vp) < 0.1
    eff = rng.integers(1, 2 ** 35, vp).astype(np.uint64)
    bal = np.where(np.arange(vp) < V,
                   rng.integers(1, 2 ** 35, vp), 0).astype(np.uint64)
    args = [jax.device_put(jnp.asarray(a), mesh.shard_v)
            for a in (pk, wc, *epochs, slashed, eff)]
    leaves = mesh.registry_forest_leaves(*args, v_count=V)
    assert leaves.shape == (128, 8)     # pow2 of the LOGICAL count
    assert leaves.sharding.is_equivalent_to(mesh.shard_v, 2)
    want = np.asarray(bulk.registry_leaf_words_device(
        pk[:V], wc[:V], *[e[:V] for e in epochs], slashed[:V], eff[:V]))
    got = np.asarray(leaves)
    np.testing.assert_array_equal(got[:V], want)
    assert not got[V:].any()            # virtual-zero padding rows

    chunks = mesh.balances_forest_chunks(
        jax.device_put(jnp.asarray(bal), mesh.shard_v), V)
    want_c = np.asarray(bulk.balances_chunk_words_device(bal[:V]))
    assert chunks.shape[0] == 32        # pow2 of ceil(100/4)
    np.testing.assert_array_equal(np.asarray(chunks)[:want_c.shape[0]], want_c)
    assert not np.asarray(chunks)[want_c.shape[0]:].any()


def test_hierarchical_mesh_epoch_equals_single():
    """Multi-host shape: 8 virtual devices arranged as 2 hosts x 4 ICI
    devices (the DCN-outer/ICI-inner mesh of parallel/sharding.py). The
    epoch program over the flattened ("host", "v") sharding must stay
    bit-equal to single-device — the multi-host counterpart of the
    NCCL/MPI backend, expressed as placement."""
    import jax.numpy as jnp

    from consensus_specs_tpu.parallel.sharding import (
        hierarchical_mesh, shard_hierarchical)
    if len(jax.devices()) < N_DEV:
        pytest.skip(f"needs {N_DEV} devices")
    hmesh = hierarchical_mesh(jax.devices()[:N_DEV], hosts=2)
    assert hmesh.devices.shape == (2, 4)

    spec = phase0.get_spec("minimal")
    cfg = EpochConfig.from_spec(spec)
    cols, scal, inp = synthetic_epoch_state(
        cfg, 64 * N_DEV, np.random.default_rng(9), random_eligibility=True)
    # shard first: the direct single-device call donates `cols`
    cols_s = shard_hierarchical(hmesh, cols)
    scal_s = shard_hierarchical(hmesh, scal)  # 0-d scalars replicate
    single = jax.device_get(epoch_transition_device(cfg, cols, scal, inp))
    # per-shard tables replicate; [V] facts shard with the columns
    import jax as _jax
    from jax.sharding import NamedSharding, PartitionSpec
    repl = NamedSharding(hmesh, PartitionSpec())
    inp_s = inp._replace(
        shard_att_balance=_jax.device_put(inp.shard_att_balance, repl),
        shard_comm_balance=_jax.device_put(inp.shard_comm_balance, repl))
    inp_s = inp_s._replace(**{
        f: _jax.device_put(getattr(inp, f),
                           NamedSharding(hmesh, PartitionSpec(("host", "v"))))
        for f in inp._fields[:-2]})
    sharded = jax.device_get(epoch_transition_device(cfg, cols_s, scal_s, inp_s))
    assert trees_bitwise_equal(single, sharded)
