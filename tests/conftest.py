"""Test-wide setup: run JAX on a virtual 8-device CPU mesh.

Must run before any jax import, so it lives at the top of conftest.
Bench/production paths use the real TPU; tests validate sharding logic on
virtual devices per the multi-chip test strategy.
"""
import os

# Force CPU: the ambient environment points JAX_PLATFORMS at the TPU relay,
# but the test suite is defined to run on a virtual 8-device CPU mesh
# (bench.py is the TPU consumer). setdefault is not enough — override.
os.environ["JAX_PLATFORMS"] = "cpu"

# jax >= 0.9: the old XLA_FLAGS --xla_force_host_platform_device_count is a
# no-op; the supported way to get virtual CPU devices is the config flag,
# set before the backend initializes (i.e. before any test imports jax).
import jax  # noqa: E402

jax.config.update("jax_num_cpu_devices", 8)

# Persistent compilation cache: the BLS pairing programs take ~1 min each to
# compile on the CPU backend; caching them across pytest processes turns
# repeat runs into millisecond cache hits.
os.makedirs("/tmp/cstpu-xla-cache", exist_ok=True)
jax.config.update("jax_compilation_cache_dir", "/tmp/cstpu-xla-cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--preset", action="store", default="minimal",
        help="constant preset to run spec tests under (minimal/mainnet)",
    )


@pytest.fixture(scope="session")
def preset_name(request):
    return request.config.getoption("--preset")
