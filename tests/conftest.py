"""Test-wide setup: run JAX on a virtual 8-device CPU mesh.

Must run before any jax import, so it lives at the top of conftest.
Bench/production paths use the real TPU; tests validate sharding logic on
virtual devices per the multi-chip test strategy.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (xla_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--preset", action="store", default="minimal",
        help="constant preset to run spec tests under (minimal/mainnet)",
    )


@pytest.fixture(scope="session")
def preset_name(request):
    return request.config.getoption("--preset")
