"""Test-wide setup: run JAX on a virtual 8-device CPU mesh.

Must run before any jax import, so it lives at the top of conftest.
Bench/production paths use the real TPU; tests validate sharding logic on
virtual devices per the multi-chip test strategy.
"""
import os

# Force CPU: the ambient environment points JAX at the TPU relay, and the
# site hook pre-imports jax — so mutating os.environ["JAX_PLATFORMS"] here
# is too late (jax read the env var at import). The robust pin is the
# config API, which works any time before backend initialization. The test
# suite is defined to run on a virtual 8-device CPU mesh (bench.py and the
# opt-in CSTPU_TEST_TPU=1 mode are the real-TPU consumers).
if os.environ.get("CSTPU_TEST_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"  # belt: covers a not-yet-imported jax

import jax  # noqa: E402

if os.environ.get("CSTPU_TEST_TPU") != "1":
    jax.config.update("jax_platforms", "cpu")  # suspenders: post-import pin
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # pre-0.5 jax has no such option; XLA reads XLA_FLAGS lazily at
        # backend init, so setting it here (pre-init) still yields 8 devices
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")

# Persistent compilation cache: the BLS pairing programs take ~1 min each to
# compile on the CPU backend; caching them across pytest processes turns
# repeat runs into millisecond cache hits.
_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "..", ".cache", "xla")
os.makedirs(_CACHE_DIR, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _CACHE_DIR)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import pytest  # noqa: E402

# Accelerated-backend mode: route the spec's permutation and full-state-root
# hooks through the batched/bulk kernels for the WHOLE corpus run. Used by
# the mainnet CI job (make citest-mainnet), where 64-slot epochs of
# recursive per-slot Merkleization are otherwise minutes per scenario —
# and doubling as continuous differential coverage of the hooks (both are
# bit-equality-tested against the recursive oracles in their own suites).
if os.environ.get("CSTPU_ACCEL") == "1":
    from consensus_specs_tpu.models.phase0.helpers import install_bulk_state_root
    from consensus_specs_tpu.ops.shuffle import install_device_shuffler
    install_bulk_state_root()
    install_device_shuffler()


# Line-coverage collection (tools/cov.py, stdlib sys.monitoring): opt-in
# because the artifact write belongs to the CI lane (make citest-cov), not
# every local run. Near-zero steady overhead (per-location DISABLE).
import sys as _sys

if os.environ.get("CSTPU_COV") == "1" and hasattr(_sys, "monitoring"):
    import importlib.util
    _root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _cspec = importlib.util.spec_from_file_location(
        "cstpu_cov", os.path.join(_root, "tools", "cov.py"))
    _cov = importlib.util.module_from_spec(_cspec)
    _cspec.loader.exec_module(_cov)
    _cov.start(os.path.join(_root, "consensus_specs_tpu"))


def pytest_addoption(parser):
    parser.addoption(
        "--preset", action="store", default="minimal",
        help="constant preset to run spec tests under (minimal/mainnet)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running (pairing corpus / state-to-state) — excluded "
        "from the default `make test` lane, included in `make citest`")


@pytest.fixture(scope="session")
def preset_name(request):
    return request.config.getoption("--preset")
