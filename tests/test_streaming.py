"""Attestation-firehose streaming verifier (ISSUE 15).

The acceptance contract: verdicts BIT-IDENTICAL to the synchronous
per-block path (`JaxBackend.verify_indexed_batch` /
`_grouped_pairing_dispatch`) for random mixes of valid + invalid +
duplicate aggregates accumulated across slot boundaries; partial
batches flush at the deadline (salvaged, counted) instead of stalling;
and >= 4 steady-state batch launches record ZERO retrace / re-layout
watchdog events.
"""
import numpy as np
import pytest

from consensus_specs_tpu import streaming, telemetry
from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.crypto import bls12_381 as gt
from consensus_specs_tpu.ops import bls_jax as BJ

P = 3   # spec aggregate-verify pair count of the staged example groups


@pytest.fixture(autouse=True)
def _no_global_verifier():
    prev = streaming.activate(None)
    yield
    streaming.activate(prev)


def _counter(name):
    return telemetry.counter(name, always=True).value


_STAGED = {}


def _staged_groups(n=2):
    """n distinct spec-shaped (P=3) verifying groups, staged once per
    session (host signing is the slow part, device work is shared)."""
    if n not in _STAGED:
        _STAGED[n] = BJ.stage_example_groups(n, n_distinct=n)
    return _STAGED[n]


def _group_pairs(g1, g2, i):
    return [(g1[i, p], g2[i, p]) for p in range(P)]


def _mismatched_pairs(g1, g2):
    """A deterministic FALSE group: group 0's G1 points against group
    1's G2 points — a well-formed pairing product that is not one."""
    return [(g1[0, p], g2[1, p]) for p in range(P)]


def _verifier(**kw):
    kw.setdefault("register", False)
    return streaming.StreamingVerifier(**kw)


def _fake_clock(step_s):
    t = [0.0]

    def clock():
        t[0] += step_s
        return t[0]

    return clock


# ---------------------------------------------------------------------------
# Differential: streamed verdicts == synchronous dispatch
# ---------------------------------------------------------------------------

def test_staged_stream_matches_sync_dispatch():
    """Valid + invalid + duplicate staged groups through the queue ==
    the synchronous _grouped_pairing_dispatch verdict map."""
    g1, g2 = _staged_groups()
    groups = [("ok0", _group_pairs(g1, g2, 0)),
              ("ok1", _group_pairs(g1, g2, 1)),
              ("bad", _mismatched_pairs(g1, g2)),
              ("ok0b", _group_pairs(g1, g2, 0))]   # same content, new key
    v = _verifier(target_groups=2)
    for key, pairs in groups:
        v.submit_staged(key, pairs)
    v.pump()
    got = dict(v.flush())
    sync = BJ._grouped_pairing_dispatch(groups)
    assert got == sync
    assert sync["bad"] is False and sync["ok0"] is True
    # duplicate KEY submission is dropped, not re-verified
    before = _counter("firehose.duplicates")
    v.submit_staged("ok0", _group_pairs(g1, g2, 0))
    assert _counter("firehose.duplicates") == before + 1
    assert v.queue.depth == 0


def test_item_stream_matches_verify_indexed_batch():
    """Random mix of valid / wrong-signer / malformed / empty items in
    the verify_indexed shape: streamed verdicts == the synchronous
    verify_indexed_batch, item by item."""
    py = gt.PythonBackend()
    dom = 1
    rng = np.random.RandomState(7)

    def item(msg, keys, sig_keys=None, custody=False):
        sig_keys = keys if sig_keys is None else sig_keys
        sig = py.aggregate_signatures([py.sign(msg, k, dom)
                                       for k in sig_keys])
        sets = [[gt.privtopub(k) for k in keys], []]
        mhs = [msg, bytes(32)]
        if custody:
            sets = sets[::-1]
            mhs = mhs[::-1]
        return (sets, mhs, sig, dom)

    msgs = [bytes([m]) * 32 for m in range(3)]
    items = [
        item(msgs[0], [11, 12]),                      # valid
        item(msgs[1], [13]),                          # valid
        item(msgs[0], [11, 12], sig_keys=[13, 14]),   # wrong signers
        item(msgs[2], [15, 16]),                      # valid
        ([[b"\x00" * 47]], [msgs[0]], b"\x11" * 96, dom),   # malformed pk
        ([[], []], [msgs[0], msgs[1]],
         gt.compress_g2(None), dom),                  # empty product
        item(msgs[1], [13]),                          # duplicate of #1
    ]
    order = rng.permutation(len(items))
    items = [items[i] for i in order]

    backend = BJ.JaxBackend()
    expect = backend.verify_indexed_batch(items)

    v = _verifier(backend=backend, target_groups=2)
    got = v.verdicts_for(items)
    assert got == expect
    assert got.count(False) >= 2 and got.count(True) >= 3
    # the duplicate collapsed onto one digest
    assert _counter("firehose.duplicates") >= 1


def test_grouped_dispatch_multi_bucket_verdict_map():
    """Overlap-fix regression: _grouped_pairing_dispatch now launches
    every bucket's program before materializing any verdict — the
    verdict map over MIXED pair counts (two buckets in one call) must
    be identical to per-group pairing_product_is_one."""
    g1, g2 = _staged_groups()
    groups = [
        ("p3_ok", _group_pairs(g1, g2, 0)),
        ("p3_bad", _mismatched_pairs(g1, g2)),
        ("p2_ok", _group_pairs(g1, g2, 1)[:2] + []),
    ]
    # a 2-pair group is NOT a verifying triple: compute its true verdict
    # from the single-group device oracle, like each 3-pair group's
    import jax.numpy as jnp
    expect = {}
    for key, pairs in groups:
        ok = np.asarray(BJ.pairing_product_is_one(
            jnp.asarray(np.stack([a for a, _ in pairs])),
            jnp.asarray(np.stack([b for _, b in pairs]))))
        expect[key] = bool(ok[0])
    launches0 = _counter("bls.grouped.launches")
    got = BJ._grouped_pairing_dispatch(groups)
    assert got == expect
    assert _counter("bls.grouped.launches") == launches0 + 2  # two buckets


# ---------------------------------------------------------------------------
# Cross-slot accumulation + deadline flush
# ---------------------------------------------------------------------------

def test_cross_slot_accumulation_single_launch():
    """Groups accumulate across slot ticks until the target occupancy;
    one launch carries work from BOTH slots."""
    g1, g2 = _staged_groups()
    v = _verifier(target_groups=4)
    launches0 = v.pipeline.launches
    for k in range(2):                       # slot N: 2 aggregates
        v.submit_staged(("s1", k), _group_pairs(g1, g2, k % 2))
    v.pump()
    assert v.pipeline.launches == launches0 and v.queue.depth == 2
    for k in range(2):                       # slot N+1: 2 more
        v.submit_staged(("s2", k), _group_pairs(g1, g2, k % 2))
    v.pump()                                 # bucket hits target: launch
    assert v.pipeline.launches == launches0 + 1
    assert v.pipeline.occupancies[-1] == 4 and v.queue.depth == 0
    got = v.flush()
    assert len(got) == 4 and all(got.values())
    assert telemetry.gauge("firehose.queue_depth", always=True).value == 0


def test_deadline_flush_partial_batch_salvaged():
    """A partial batch (occupancy < target) flushes AT the deadline; a
    budget blown by the materialization is salvaged — verdicts land,
    the miss is counted on /healthz — instead of stalling fork choice."""
    g1, g2 = _staged_groups()
    # fake clock: every read advances 100 ms, so any armed window "takes"
    # >= 100 ms against a 5 ms budget — a guaranteed, sleep-free miss
    v = _verifier(target_groups=8, clock=_fake_clock(0.1),
                  sleep=lambda s: None)
    v.submit_staged("late", _group_pairs(g1, g2, 0))
    misses0 = _counter("firehose.deadline_miss")
    salvaged0 = _counter("resilience.deadline_salvaged")
    partial0 = _counter("firehose.partial_flushes")
    got = v.flush(deadline_ms=5.0)
    assert got == {"late": True}             # late but landed
    assert v.verdict("late") is True
    assert _counter("firehose.deadline_miss") == misses0 + 1
    assert _counter("resilience.deadline_salvaged") == salvaged0 + 1
    assert _counter("firehose.partial_flushes") == partial0 + 1
    assert v.pipeline.occupancies[-1] == 1   # the partial batch


def test_flush_within_budget_counts_no_miss():
    g1, g2 = _staged_groups()
    v = _verifier(target_groups=2)
    v.submit_staged("a", _group_pairs(g1, g2, 0))
    v.submit_staged("b", _group_pairs(g1, g2, 1))
    misses0 = _counter("firehose.deadline_miss")
    got = v.flush(deadline_ms=120_000.0)     # generous real-clock budget
    assert got == {"a": True, "b": True}
    assert _counter("firehose.deadline_miss") == misses0


# ---------------------------------------------------------------------------
# Steady state: zero retrace / zero re-layout
# ---------------------------------------------------------------------------

def test_steady_state_zero_watchdog_events():
    """>= 4 steady-state batch launches at one shape: the pairing
    programs, the ring scatter, and the chained ring placement must
    record ZERO watchdog events (first compiles are warm-up, never
    events)."""
    g1, g2 = _staged_groups()
    v = _verifier(target_groups=2)
    retrace0 = _counter("watchdog.retrace_events")
    relayout0 = _counter("watchdog.relayout_events")
    for wave in range(5):
        for k in range(2):
            v.submit_staged((wave, k), _group_pairs(g1, g2, k))
        v.pump()
        if wave % 2:
            got = v.flush()
            assert all(got.values())
    v.flush()
    assert v.pipeline.launches >= 5
    assert _counter("watchdog.retrace_events") == retrace0
    assert _counter("watchdog.relayout_events") == relayout0


def test_ring_wrap_drains_early():
    """A flush window larger than the verdict ring drains early
    (counted) and still returns every verdict."""
    g1, g2 = _staged_groups()
    v = _verifier(target_groups=2, ring_capacity=4)
    wraps0 = _counter("firehose.ring_wraps")
    for k in range(6):                       # 3 batches of G=2 vs R=4
        v.submit_staged(("w", k), _group_pairs(g1, g2, k % 2))
    v.pump()
    got = v.flush()
    assert len(got) == 6 and all(got.values())
    assert _counter("firehose.ring_wraps") == wraps0 + 1


# ---------------------------------------------------------------------------
# Gossip ingest -> block path consumes queued verdicts
# ---------------------------------------------------------------------------

def test_gossip_preverification_feeds_block_path():
    """Attestations arriving over gossip are pre-verified by the
    firehose; when a block including them executes, the batched
    attestation family serves every signature verdict from the queue's
    cache (zero new pairing launches) and the post-state is
    bit-identical to the synchronous path."""
    from copy import deepcopy

    import bench
    from consensus_specs_tpu.models import phase0
    from consensus_specs_tpu.networking.gossip import (GossipRouter,
                                                       TOPIC_BEACON_ATTESTATION)
    from consensus_specs_tpu.utils.ssz.impl import hash_tree_root, serialize

    spec = phase0.get_spec("minimal")
    old_active = bls.bls_active
    bls.bls_active = True
    bls.set_backend("python")   # stage signatures with the bignum oracle
    try:
        state, block = bench.build_config3_state_and_block(
            spec, 8 * spec.SLOTS_PER_EPOCH, 3, n_keys=8)
        bls.set_backend("jax")

        # synchronous reference run
        ref = deepcopy(state)
        spec.state_transition(ref, deepcopy(block))

        # gossip ingest on the pre-state via the router decode path
        v = _verifier(target_groups=2)
        router = GossipRouter()
        router.subscribe("verifier", TOPIC_BEACON_ATTESTATION,
                         lambda _topic, payload:
                         v.ingest_gossip(spec, state, payload))
        for att in block.body.attestations:
            reached = router.publish(
                "peer", TOPIC_BEACON_ATTESTATION,
                serialize(att, spec.Attestation))
            assert reached == 1
            # a duplicate gossip publish dedups in the router seen-cache
            assert router.publish("peer2", TOPIC_BEACON_ATTESTATION,
                                  serialize(att, spec.Attestation)) == 0
        v.pump()
        v.flush()

        # block path: every sink verdict must come from the cache
        hits0 = _counter("firehose.cache_hits")
        launches0 = v.pipeline.launches
        spec._streaming_verifier = v
        try:
            spec.state_transition(state, block)
        finally:
            spec._streaming_verifier = None
        assert hash_tree_root(state) == hash_tree_root(ref)
        assert _counter("firehose.cache_hits") - hits0 == 3
        assert v.pipeline.launches == launches0   # no new device batches
    finally:
        bls.bls_active = old_active
        bls.set_backend("python")
        spec._streaming_verifier = None


def test_gossip_undecodable_payload_is_counted_not_fatal():
    from consensus_specs_tpu.models import phase0
    spec = phase0.get_spec("minimal")
    from consensus_specs_tpu.testing import factories as f
    state = f.seed_genesis_state(spec, spec.SLOTS_PER_EPOCH * 8)
    v = _verifier(target_groups=2)
    bad0 = _counter("firehose.undecodable")
    assert v.ingest_gossip(spec, state, b"\x00\x01garbage") is None
    assert _counter("firehose.undecodable") == bad0 + 1
    assert v.queue.depth == 0 and not v._pending


# ---------------------------------------------------------------------------
# Health surface
# ---------------------------------------------------------------------------

def test_firehose_health_reflects_backlog_and_flush_age():
    g1, g2 = _staged_groups()
    v = streaming.StreamingVerifier(target_groups=8, register=True)
    try:
        assert streaming.active() is v
        v.submit_staged("h0", _group_pairs(g1, g2, 0))
        health = streaming.firehose_health()
        assert health["backlog"] == 1
        assert health["last_flush_age_s"] is None   # never flushed
        assert health["counters"]["ingested"] >= 1
        v.flush()
        health = streaming.firehose_health()
        assert health["backlog"] == 0
        assert health["last_flush_age_s"] is not None
        assert health["last_flush_age_s"] < 60.0
    finally:
        streaming.activate(None)


def test_verdict_retention_is_bounded():
    """A sustained firehose must not grow host state per aggregate:
    resolved digests (and their dedup entries) evict FIFO past the
    retention bound; an evicted digest can re-verify."""
    v = _verifier(target_groups=2, retain=4096)
    assert v.retain == 4096
    for i in range(v.retain + 10):
        v._seen.add(i)
        v._remember(i, True)
    assert len(v._verdicts) == v.retain
    assert len(v._seen) == v.retain
    assert v.verdict(0) is None          # evicted (oldest)
    assert v.verdict(v.retain + 9) is True


def test_ring_capacity_misconfig_raises_clearly():
    """ring_capacity smaller than the padded target batch must fail at
    construction, not as a trace-time XLA shape error."""
    with pytest.raises(AssertionError):
        _verifier(target_groups=128, ring_capacity=64)


def test_health_without_active_verifier_is_zeroed():
    health = streaming.firehose_health()
    assert health["backlog"] == 0
    assert health["in_flight_batches"] == 0
    assert health["target_groups"] is None
    assert set(health["counters"]) >= {"ingested", "deadline_miss",
                                       "cache_hits"}
