"""Random SSZ object factory: every mode round-trips through the serializer.

Mirrors the role of the reference's random_value + fuzzing round-trip
(/root/reference test_libs/pyspec/eth2spec/debug/random_value.py,
eth2spec/fuzzing/test_decoder.py): randomized instances of every phase-0
container must serialize, deserialize back to an equal object, and produce
stable hash_tree_roots.
"""
import zlib
from random import Random

import pytest

from consensus_specs_tpu.debug.random_value import (
    RandomizationMode, get_mode_by_name, get_random_ssz_object)
from consensus_specs_tpu.models import phase0
from consensus_specs_tpu.utils.ssz.impl import (
    deserialize, hash_tree_root, serialize)
from consensus_specs_tpu.utils.ssz.typing import (
    Bytes32, List as SSZList, Vector, uint8, uint16, uint64, uint256)

SPEC = phase0.get_spec("minimal")
CONTAINER_NAMES = sorted(SPEC.container_types.keys())


@pytest.mark.parametrize("mode", list(RandomizationMode))
@pytest.mark.parametrize("name", CONTAINER_NAMES)
def test_container_roundtrip(name, mode):
    typ = getattr(SPEC, name)
    rng = Random(zlib.crc32(name.encode()) ^ mode.value)
    obj = get_random_ssz_object(rng, typ, mode)
    data = serialize(obj, typ)
    back = deserialize(data, typ)
    assert serialize(back, typ) == data
    assert hash_tree_root(back, typ) == hash_tree_root(obj, typ)


@pytest.mark.parametrize("typ", [
    uint8, uint16, uint64, uint256, bool, Bytes32,
    SSZList[uint64], Vector[uint64, 4], Vector[Bytes32, 3],
])
@pytest.mark.parametrize("mode_name", ["random", "zero", "max", "nil", "one", "lengthy"])
def test_primitive_roundtrip(typ, mode_name):
    mode = get_mode_by_name(mode_name)
    rng = Random(42)
    obj = get_random_ssz_object(rng, typ, mode)
    data = serialize(obj, typ)
    back = deserialize(data, typ)
    assert serialize(back, typ) == data


def test_modes_shape_lists():
    rng = Random(7)
    assert get_random_ssz_object(rng, SSZList[uint64], RandomizationMode.NIL) == []
    one = get_random_ssz_object(rng, SSZList[uint64], RandomizationMode.ONE)
    assert len(one) == 1
    lengthy = get_random_ssz_object(rng, SSZList[uint64], RandomizationMode.LENGTHY)
    assert 50 <= len(lengthy) <= 100


def test_zero_mode_is_zero_value():
    rng = Random(1)
    obj = get_random_ssz_object(rng, SPEC.Validator, RandomizationMode.ZERO)
    assert obj == SPEC.Validator()


def test_max_mode_uints_saturate():
    rng = Random(1)
    assert get_random_ssz_object(rng, uint16, RandomizationMode.MAX) == 0xFFFF


def test_chaos_still_roundtrips():
    rng = Random(99)
    for _ in range(5):
        obj = get_random_ssz_object(rng, SPEC.BeaconBlock, RandomizationMode.RANDOM,
                                    chaos=True)
        data = serialize(obj, SPEC.BeaconBlock)
        assert serialize(deserialize(data, SPEC.BeaconBlock), SPEC.BeaconBlock) == data
