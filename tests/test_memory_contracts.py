"""Memory tier (tools/analysis/memory/): seeded-regression fixtures
proving each CSA16xx rule trips on a REAL traced program, the liveness
model's load-bearing semantics (donated aliases counted once, sub-jaxpr
transients, host-round-trip widening), the baseline loosen/tighten/
stale/suppressed workflow (mirroring tests/test_range_contracts.py),
the committed registry's proofs, and the merged five-tier CLI.

The headline budgets themselves — the V=10M epoch HBM ceiling, the
per-shard bound on the 8-device mesh, the forest-update O(dirty·log V)
fit, the pairing and firehose working sets, the Pallas VMEM footprint —
are committed as MEM_CONTRACTS next to their kernels and run under
`make memory`; this file owns the ENGINE's behavior: a grown buffer, a
superlinear temp, an over-wide BlockSpec — each must fail through the
engine, and the documented accept paths must clear it.
"""
import json
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from consensus_specs_tpu.ops import intmath as _intmath  # noqa: F401 -- x64
from tools.analysis.memory import engine
from tools.analysis.memory import liveness as L

REPO = Path(__file__).resolve().parents[1]


def _contract(tmp_path, name="fixture.contract", **kw):
    """A synthetic contract anchored in a real tmp file (so inline
    suppressions work exactly like a kernel module's)."""
    path = tmp_path / "kernel_fixture.py"
    if not path.exists():
        path.write_text(f'MEM_CONTRACTS = [{{"name": "{name}"}}]\n')
    c = dict(name=name, path=str(path),
             line=engine._name_line(path.read_text(), name))
    c.update(kw)
    return c


def _rules(report):
    return sorted(f.rule for f in report.findings)


def _vec(n=1 << 16):
    return jax.ShapeDtypeStruct((n,), jnp.uint64)


# ---------------------------------------------------------------------------
# The liveness model's load-bearing semantics
# ---------------------------------------------------------------------------

def test_donated_alias_counted_once():
    """THE accounting rule the epoch budget rests on: a donated input
    congruent with an output shares ONE buffer. The same program with
    and without donation must differ by exactly the aliased bytes."""
    def f(x, y):
        return x + y, jnp.sum(y)

    closed = jax.make_jaxpr(f)(_vec(), _vec())
    plain = L.analyze(closed)
    donated = L.analyze(closed, donated={0})
    bytes_x = (1 << 16) * 8
    assert donated.alias_bytes == bytes_x
    assert plain.alias_bytes == 0
    assert plain.peak_bytes - donated.peak_bytes == bytes_x
    # the unmatched donated invar (no congruent output) frees instead
    def g(x, y):
        return (x + y).astype(jnp.uint32), jnp.sum(y)
    closed2 = jax.make_jaxpr(g)(_vec(), _vec())
    d2 = L.analyze(closed2, donated={0})
    assert d2.alias_bytes == 0          # uint32 out: nothing congruent


def test_scan_body_transient_contributes_atop_carry():
    """A scan's body peak beyond its own I/O rides atop the live set
    carried across the eqn — a big in-body temp must show up in the
    modeled peak even though it never escapes the scan."""
    def body(c, _):
        big = jnp.zeros((1 << 16,), jnp.uint64) + c
        return jnp.sum(big), None

    def f(x):
        out, _ = jax.lax.scan(body, jnp.sum(x), None, length=4)
        return out

    small = jax.make_jaxpr(lambda x: jnp.sum(x))(_vec(256))
    scan = jax.make_jaxpr(f)(_vec(256))
    assert L.analyze(scan).peak_bytes >= \
        L.analyze(small).peak_bytes + (1 << 16) * 8


def test_host_roundtrip_event_recorded():
    """A pure_callback staged between device eqns while buffers span it
    is a HostEvent carrying the spanning bytes (CSA1605's raw signal)."""
    def f(x):
        y = x * jnp.uint64(2)
        s = jax.pure_callback(
            lambda v: np.uint64(v[0]),
            jax.ShapeDtypeStruct((), jnp.uint64), y)
        return y + s                    # y spans the callback

    model = L.analyze(jax.make_jaxpr(f)(_vec()))
    assert model.host_events
    assert model.host_events[0].spanning_bytes >= (1 << 16) * 8


def test_traffic_bounds_bracket_program():
    lo, hi = L.traffic_bounds(jax.make_jaxpr(lambda x: x + x)(_vec()))
    assert lo == 2 * (1 << 16) * 8      # one read + one write
    assert hi >= lo


def test_fit_order_recovers_slope():
    assert abs(L.fit_order([10, 100, 1000],
                           [10, 100, 1000]) - 1.0) < 1e-9
    assert L.fit_order([10, 100], [7, 7]) == 0.0


# ---------------------------------------------------------------------------
# CSA1601: declared-budget violation (peak, shard bound, compiled)
# ---------------------------------------------------------------------------

def test_budget_violation_trips_and_honest_budget_clears(tmp_path):
    build = lambda: dict(fn=lambda x: x + x, args=(_vec(),))
    over = _contract(tmp_path, build=build, budget_bytes=1 << 10)
    report = engine.run_contracts([over], baseline={})
    assert "CSA1601" in _rules(report)
    honest = _contract(tmp_path, name="fixture.fits", build=build,
                       budget_bytes=1 << 30)
    report2 = engine.run_contracts([honest], baseline={})
    assert "CSA1601" not in _rules(report2)


def test_shard_bound_proves_and_replicated_overrun_trips(tmp_path):
    """A [V] elementwise program shards cleanly under single/N + cap; a
    program whose working set REPLICATES (small leaves) escapes the
    bound and trips."""
    shards = _contract(
        tmp_path,
        build=lambda: dict(fn=lambda x: x * jnp.uint64(3), args=(_vec(),)),
        sharded=dict(devices=8, min_elems=1 << 10,
                     replicated_cap_bytes=1 << 10))
    assert "CSA1601" not in _rules(engine.run_contracts([shards],
                                                        baseline={}))
    replicates = _contract(
        tmp_path, name="fixture.replicates",
        build=lambda: dict(fn=lambda x: x * jnp.uint64(3), args=(_vec(),)),
        sharded=dict(devices=8, min_elems=1 << 30,   # nothing shards
                     replicated_cap_bytes=1 << 10))
    report = engine.run_contracts([replicates], baseline={})
    assert "CSA1601" in _rules(report)
    assert any("replicated cap" in f.message for f in report.findings)


def test_compiled_crosscheck_divergence_trips(tmp_path):
    """Force divergence by lying to the checker: a probe whose args the
    model never saw (the contract's fn ignores its big arg, XLA drops
    it from argument_size) with zero slack must fail the arg check."""
    build = lambda: dict(fn=lambda x: jnp.zeros((4,), jnp.uint64),
                         args=(_vec(1 << 20),))
    c = _contract(tmp_path, build=build,
                  compiled=dict(tol=1.01, slack_bytes=0))
    report = engine.run_contracts([c], baseline={})
    # XLA:CPU prunes the unused [2^20] arg; the model charges it
    assert any(f.rule == "CSA1601" and "diverges" in f.message
               for f in report.findings)


def test_compiled_crosscheck_agreement_clears(tmp_path):
    c = _contract(tmp_path,
                  build=lambda: dict(fn=lambda x: x + jnp.uint64(1),
                                     args=(_vec(1 << 12),)),
                  compiled=True)
    report = engine.run_contracts([c], baseline={})
    assert "CSA1601" not in _rules(report)
    (res,) = report.results
    assert res.detail["compiled"]["argument_bytes"][2] is True


# ---------------------------------------------------------------------------
# CSA1602: the bytes ratchet (the ISSUE's seeded +1-buffer regression)
# ---------------------------------------------------------------------------

def _ratchet(tmp_path, extra_buffer=False, name="fixture.contract"):
    def lean(x):
        return x * jnp.uint64(2) + jnp.uint64(1)

    def bloated(x):
        # the seeded regression: one avoidable full-width materialization
        spill = jnp.cumsum(x * jnp.uint64(2))
        return x * jnp.uint64(2) + jnp.uint64(1) + (spill[-1] - spill[-1])

    return _contract(
        tmp_path, name=name,
        build=lambda: dict(fn=bloated if extra_buffer else lean,
                           args=(_vec(),)))


def test_seeded_extra_buffer_trips_ratchet_and_loosening_clears(tmp_path):
    clean = engine.run_contracts([_ratchet(tmp_path)], baseline={})
    snap = clean.snapshot
    # the committed posture: clean vs its own snapshot
    assert engine.run_contracts([_ratchet(tmp_path)],
                                baseline=snap).findings == []
    # grow the live set by one [V] buffer -> CSA1602 against the old pin
    dirty = engine.run_contracts([_ratchet(tmp_path, extra_buffer=True)],
                                 baseline=snap)
    assert "CSA1602" in _rules(dirty)
    assert any("regressed" in f.message for f in dirty.findings)
    # the accept path: a reviewed refresh to the new modeled bytes
    grown = engine.run_contracts(
        [_ratchet(tmp_path, extra_buffer=True)],
        baseline=engine.run_contracts(
            [_ratchet(tmp_path, extra_buffer=True)], baseline={}).snapshot)
    assert grown.findings == []


def test_missing_baseline_entry_trips(tmp_path):
    report = engine.run_contracts([_ratchet(tmp_path)], baseline={})
    assert set(_rules(report)) == {"CSA1602"}
    assert all("no memory-baseline entry" in f.message
               for f in report.findings)


def test_shrink_is_a_tighten_notice_not_a_finding(tmp_path):
    snap = engine.run_contracts(
        [_ratchet(tmp_path, extra_buffer=True)], baseline={}).snapshot
    slim = engine.run_contracts([_ratchet(tmp_path)], baseline=snap)
    assert slim.findings == []
    assert any("shrank" in n for n in slim.notices)


def test_stale_baseline_contract_reported(tmp_path):
    snap = engine.run_contracts([_ratchet(tmp_path)], baseline={}).snapshot
    snap["deleted.contract"] = {"peak_bytes": 1}
    report = engine.run_contracts([_ratchet(tmp_path)], baseline=snap)
    assert report.stale_baseline == ["deleted.contract"]
    assert report.findings == []        # stale is reported, not failed


def test_suppression_on_contract_line(tmp_path):
    path = tmp_path / "kernel_fixture.py"
    path.write_text(
        'MEM_CONTRACTS = [\n'
        '    # csa: ignore[CSA1602] -- fixture: snapshot intentionally absent\n'
        '    {"name": "fixture.contract"},\n'
        ']\n')
    report = engine.run_contracts([_ratchet(tmp_path)], baseline={})
    assert report.findings == []
    assert {f.rule for f in report.suppressed} == {"CSA1602"}


def test_baseline_roundtrip_and_json(tmp_path):
    report = engine.run_contracts([_ratchet(tmp_path)], baseline={})
    path = tmp_path / "memory_baseline.json"
    engine.write_memory_baseline(path, report.snapshot)
    loaded = engine.load_memory_baseline(path)
    assert loaded == report.snapshot
    assert engine.run_contracts([_ratchet(tmp_path)],
                                baseline=loaded).findings == []
    data = json.loads(engine.render_json(report))
    assert data["contracts"][0]["name"] == "fixture.contract"
    assert data["contracts"][0]["measured"]["peak_bytes"] > 0


def test_broken_contract_is_a_finding_not_a_crash(tmp_path):
    c = _contract(tmp_path,
                  build=lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    report = engine.run_contracts(
        [c], baseline={"fixture.contract": {"peak_bytes": 1}})
    assert "CSA1601" in _rules(report)
    assert report.results[0].skipped
    assert report.stale_baseline == []  # unverifiable != stale


# ---------------------------------------------------------------------------
# CSA1603: superlinear scaling
# ---------------------------------------------------------------------------

def test_superlinear_probe_trips_and_linear_clears(tmp_path):
    def quadratic(n):
        # [n, n] outer product: peak scales as n^2 against a declared O(n)
        return dict(fn=lambda x: jnp.outer(x, x).sum(axis=0),
                    args=(jax.ShapeDtypeStruct((n,), jnp.uint64),))

    c = _contract(tmp_path,
                  scaling=dict(ns=[64, 256, 1024], build=quadratic,
                               metric="peak_bytes", max_order=1.0))
    report = engine.run_contracts([c], baseline={})
    assert "CSA1603" in _rules(report)
    assert any("n^" in f.message for f in report.findings)

    def linear(n):
        return dict(fn=lambda x: x * jnp.uint64(2) + jnp.uint64(1),
                    args=(jax.ShapeDtypeStruct((n,), jnp.uint64),))

    ok = _contract(tmp_path, name="fixture.linear",
                   scaling=dict(ns=[64, 256, 1024], build=linear,
                                metric="peak_bytes", max_order=1.0))
    assert "CSA1603" not in _rules(engine.run_contracts([ok], baseline={}))


# ---------------------------------------------------------------------------
# CSA1604: VMEM overflow
# ---------------------------------------------------------------------------

def test_vmem_overflow_trips_and_real_blocks_fit(tmp_path):
    over = _contract(
        tmp_path,
        vmem=dict(blocks=[((16, 1 << 18), "uint32")], buffering=2))
    report = engine.run_contracts([over], baseline={})
    assert "CSA1604" in _rules(report)
    assert any("VMEM" in f.message for f in report.findings)
    # the committed kernel's real BlockSpecs, via its own model hook
    from consensus_specs_tpu.ops.sha256_pallas import vmem_block_model
    fits = _contract(tmp_path, name="fixture.fits",
                     vmem=dict(blocks=vmem_block_model, buffering=2))
    clean = engine.run_contracts([fits], baseline={})
    assert "CSA1604" not in _rules(clean)
    assert clean.results[0].measured["vmem_bytes"] == \
        ((16 + 8) * 512 * 4 + 2 * 64 * 4) * 2


# ---------------------------------------------------------------------------
# CSA1605: host round-trip notice through the engine
# ---------------------------------------------------------------------------

def test_host_roundtrip_notice_through_engine(tmp_path):
    def f(x):
        y = x * jnp.uint64(2)
        s = jax.pure_callback(lambda v: np.uint64(v[0]),
                              jax.ShapeDtypeStruct((), jnp.uint64), y)
        return y + s

    c = _contract(tmp_path, build=lambda: dict(fn=f, args=(_vec(),)))
    report = engine.run_contracts([c], baseline={})
    assert "CSA1605" in _rules(report)
    assert any("host round-trip" in f.message for f in report.findings)


# ---------------------------------------------------------------------------
# The committed registry and its theorems
# ---------------------------------------------------------------------------

def test_committed_registry_shape():
    """Discovery finds the six kernel-side contract homes the tier
    documents, with the headline budgets declared."""
    contracts = engine.discover()
    names = {c["name"]: c for c in contracts}
    for needle in ("models.phase0.epoch_soa.", "parallel.sharding.",
                   "streaming.pipeline.", "utils.ssz.incremental.",
                   "ops.bls_jax.", "ops.sha256_pallas."):
        assert any(n.startswith(needle) for n in names), needle
    epoch = names["models.phase0.epoch_soa.epoch_hbm_ceiling"]
    assert epoch["budget_bytes"] == 4 << 30
    assert epoch["scaling"]["ns"][-1] == 10_000_000
    assert names["parallel.sharding.epoch_shard_hbm"]["sharded"][
        "devices"] == 8


def test_committed_fast_contracts_prove_clean():
    """`make memory` in miniature over the sub-minute contracts (the
    epoch ceiling + shard bound + forest pair + VMEM); the pairing
    traces (~40 s each) run under the full `make memory` gate."""
    from tools.analysis.trace.engine import ensure_cpu_devices
    ensure_cpu_devices(8)
    fast = [c for c in engine.discover()
            if "bls_jax" not in c["name"] and "pipeline" not in c["name"]]
    assert len(fast) >= 4
    baseline = {k: v for k, v in engine.load_memory_baseline().items()
                if any(c["name"] == k for c in fast)}
    report = engine.run_contracts(fast, baseline=baseline)
    assert report.findings == [], [
        f"{f.path}:{f.line} {f.rule} {f.message}" for f in report.findings]
    assert report.stale_baseline == []


def test_epoch_contract_donates_and_aliases():
    """The epoch contract's accounting rests on donation: the modeled
    alias bytes must cover every donated [V] state column counted once
    (six uint64 columns + the bool slashed flags = 49 B/validator), and
    the aliased savings must land in the resident footprint
    (args + outs - alias). The mid-program PEAK sits at the crosslink
    muldiv pjit, which precedes the aliased output columns' definitions
    — so donation moves the end-of-program residency, not that site,
    and the peak must never be WORSE with donation on."""
    from consensus_specs_tpu.models.phase0 import epoch_soa as E

    spec = E._epoch_mem_build(100_000)
    closed, donated = engine._trace(spec)
    with_d = L.analyze(closed, donated=donated)
    without = L.analyze(closed)
    assert with_d.alias_bytes == 100_000 * (6 * 8 + 1)
    assert without.alias_bytes == 0
    assert with_d.peak_bytes <= without.peak_bytes
    # the accounting identity both walks must satisfy: peak splits into
    # the resident footprint plus the transient the site report blames
    for r in (with_d, without):
        assert r.peak_bytes == (r.arg_bytes + r.out_bytes - r.alias_bytes
                                + r.const_bytes + r.temp_bytes)


# ---------------------------------------------------------------------------
# CLI: five-tier --list-rules, merged --json, max exit
# ---------------------------------------------------------------------------

def test_list_rules_spans_five_tiers():
    out = subprocess.run(
        [sys.executable, "-m", "tools.analysis", "--list-rules"],
        cwd=REPO, capture_output=True, text=True, check=True).stdout
    for probe in ("CSA101", "CSA1101", "CSA1401", "CSA1501",
                  "CSA1601", "CSA1602", "CSA1603", "CSA1604", "CSA1605"):
        assert probe in out, probe


def test_rules_registered_without_jax_tier():
    from tools.analysis.core import RULES
    from tools.analysis.memory import MEMORY_RULE_IDS
    assert set(MEMORY_RULE_IDS) <= set(RULES)
    assert RULES["CSA1605"].severity == "notice"
    for rule_id in ("CSA1601", "CSA1602", "CSA1603", "CSA1604"):
        assert RULES[rule_id].severity == "error"


def _cli_env():
    import os
    return {**os.environ, "JAX_PLATFORMS": "cpu"}


def test_cli_merged_tiers_json_and_max_exit(tmp_path):
    """An AST-tier finding (host cast under jit) + a clean memory run
    (the shard contract vs the committed baseline, via --memory-filter
    so the CLI lane skips the ~1-minute pairing traces — `make memory`
    runs them): the merged artifact carries both tiers, exit is the
    worst."""
    snippet = tmp_path / "bad_ast.py"
    snippet.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return int(x)\n")
    out = tmp_path / "merged.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.analysis", str(snippet),
         "--memory", "--memory-filter", "epoch_shard",
         "--json", str(out)],
        cwd=REPO, capture_output=True, text=True, env=_cli_env(),
        timeout=600)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    data = json.loads(out.read_text())
    assert sorted(data["tiers"]) == ["ast", "memory"]
    assert data["tiers"]["memory"]["findings"] == []
    assert data["tiers"]["memory"]["stale_baseline"] == []
    assert any(f["rule"] == "CSA102"
               for f in data["tiers"]["ast"]["findings"])


def test_cli_update_memory_baseline_roundtrip(tmp_path):
    """--update-memory-baseline writes a loadable snapshot whose rerun
    exits clean — real CLI, filtered to the shard contract so the lane
    stays fast."""
    bpath = tmp_path / "mb.json"
    common = [sys.executable, "-m", "tools.analysis",
              "--memory-filter", "epoch_shard",
              "--memory-baseline", str(bpath)]
    proc = subprocess.run(
        common + ["--update-memory-baseline"],
        cwd=REPO, capture_output=True, text=True, env=_cli_env(),
        timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    written = engine.load_memory_baseline(bpath)
    assert "parallel.sharding.epoch_shard_hbm" in written
    out = tmp_path / "m.json"
    proc2 = subprocess.run(
        common + ["--memory", "--json", str(out)],
        cwd=REPO, capture_output=True, text=True, env=_cli_env(),
        timeout=600)
    assert proc2.returncode == 0, proc2.stdout + proc2.stderr
    assert json.loads(out.read_text())["findings"] == []
